"""Checkpoint/resume: WAL+snapshot persistence and restart recovery.

The reference's durability model is "etcd is the checkpoint" — every
component rebuilds state from the API server on restart (SURVEY §5; chip
occupancy from pod annotations, /root/reference/pkg/flexgpu/gpu_node.go:67-120).
These tests cover both halves: the journal restores the API server's state
across process death, and a restarted scheduler rebuilds chip occupancy from
the recovered pods' annotations without double-assigning chips."""
from __future__ import annotations

import json
import os

import pytest

from tpusched.api.core import Node, Pod, Toleration
from tpusched.api.meta import ObjectMeta
from tpusched.api.resources import TPU, make_resources
from tpusched.api.scheduling import PodGroup, PodGroupSpec
from tpusched.api.topology import TpuTopology, TpuTopologySpec
from tpusched.apiserver import persistence
from tpusched.apiserver import server as srv
from tpusched.plugins.tpuslice.chip_node import CHIP_INDEX_ANNOTATION
from tpusched.testing import TestCluster, make_pod, make_tpu_node


# -- codec --------------------------------------------------------------------

def test_codec_roundtrip_pod():
    p = make_pod("w", limits={TPU: 2}, requests=make_resources(cpu=1, memory="2Gi"))
    p.spec.tolerations.append(Toleration(key="tpu", operator="Exists"))
    p.meta.annotations["a"] = "b"
    p.status.nominated_node_name = "n9"
    back = persistence.decode_object(Pod, persistence.encode_object(p))
    assert back == p


def test_codec_roundtrip_topology_tuples():
    topo = TpuTopology(
        meta=ObjectMeta(name="pool-a"),
        spec=TpuTopologySpec(pool="pool-a", accelerator="tpu-v5p",
                             dims=(8, 8, 4), wrap=(True, True, False),
                             hosts={"n0": (0, 0, 0), "n1": (0, 0, 4)}))
    back = persistence.decode_object(TpuTopology, persistence.encode_object(topo))
    assert back == topo
    assert isinstance(back.spec.dims, tuple)
    assert isinstance(back.spec.hosts["n1"], tuple)


def test_codec_roundtrip_podgroup():
    pg = PodGroup(meta=ObjectMeta(name="g"),
                  spec=PodGroupSpec(min_member=4, tpu_slice_shape="2x2x1",
                                    min_resources=make_resources(cpu=8)))
    back = persistence.decode_object(PodGroup, persistence.encode_object(pg))
    assert back == pg


# -- journal + recovery -------------------------------------------------------

def test_wal_replay_restores_state(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    api.create(srv.NODES, make_tpu_node("n1", chips=4))
    api.create(srv.PODS, make_pod("a", limits={TPU: 1}))
    api.create(srv.PODS, make_pod("b"))
    api.patch(srv.PODS, "default/a",
              lambda p: p.meta.annotations.update({CHIP_INDEX_ANNOTATION: "0"}))
    api.delete(srv.PODS, "default/b")
    rv_before = api.get(srv.PODS, "default/a").meta.resource_version
    journal.close()  # process death: queue drained, WAL on disk

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    assert api2.try_get(srv.PODS, "default/b") is None
    a = api2.get(srv.PODS, "default/a")
    assert a.meta.annotations[CHIP_INDEX_ANNOTATION] == "0"
    assert a.meta.resource_version == rv_before
    assert api2.get(srv.NODES, "/n1").status.allocatable[TPU] == 4
    # recovered rv is monotonic: new writes must not reuse old versions
    c = api2.create(srv.PODS, make_pod("c"))
    assert c.meta.resource_version > rv_before


def test_recovery_bumps_uid_counter(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    created = api.create(srv.PODS, make_pod("a"))
    journal.close()

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    fresh = api2.create(srv.PODS, make_pod("z"))
    assert fresh.meta.uid != created.meta.uid


def test_compaction_truncates_wal_and_preserves_state(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d, compact_every=5)
    for i in range(12):  # crosses two compaction thresholds
        api.create(srv.PODS, make_pod(f"p{i}"))
    assert journal.flush()
    wal_lines = [l for l in open(os.path.join(d, persistence.WAL_FILE))
                 if l.strip()]
    assert len(wal_lines) < 12
    snap = json.load(open(os.path.join(d, persistence.SNAPSHOT_FILE)))
    assert snap["kinds"][srv.PODS]
    journal.close()

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    assert len(api2.list(srv.PODS)) == 12


def test_torn_wal_tail_is_ignored(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    api.create(srv.PODS, make_pod("a"))
    api.create(srv.PODS, make_pod("b"))
    journal.close()
    with open(os.path.join(d, persistence.WAL_FILE), "a") as f:
        f.write('{"op": "put", "kind": "pods", "obj": {"meta": {"na')  # crash mid-append

    api2 = srv.APIServer()
    restored = persistence.load_into(api2, d)
    # the snapshot from attach() already holds a+b; the torn record is dropped
    assert restored == 2


def test_rv_monotonic_across_delete_and_restart(tmp_path):
    """rv bumps consumed by objects deleted before the crash must still
    advance the recovered rv floor."""
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    api.create(srv.PODS, make_pod("a"))
    rv_b = api.create(srv.PODS, make_pod("b")).meta.resource_version
    api.delete(srv.PODS, "default/b")
    journal.close()

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    c = api2.create(srv.PODS, make_pod("c"))
    assert c.meta.resource_version > rv_b


def test_flush_reports_write_failure(tmp_path, monkeypatch):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)

    def boom(batch):
        raise OSError("disk full")
    real_write = journal._write_batch
    monkeypatch.setattr(journal, "_write_batch", boom)
    api.create(srv.PODS, make_pod("a"))
    assert journal.flush(timeout=5) is False

    # a successful compaction snapshots the full live store — the lost
    # record is durable again and flush() recovers
    monkeypatch.setattr(journal, "_write_batch", real_write)
    journal.compact()
    assert journal.flush(timeout=5) is True
    api2 = srv.APIServer()
    persistence.load_into(api2, d)
    assert api2.try_get(srv.PODS, "default/a") is not None


# -- scheduler restart over recovered state -----------------------------------

def test_scheduler_restart_rebuilds_chip_occupancy(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    with TestCluster(api=api) as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        c.create_pods([make_pod("a", limits={TPU: 2})])
        assert c.wait_for_pods_scheduled(["default/a"])
        chips_a = c.pod("default/a").meta.annotations[CHIP_INDEX_ANNOTATION]
    journal.close()

    # "process death": a brand-new API server recovers from disk, a
    # brand-new scheduler rebuilds occupancy from pod annotations
    api2 = srv.APIServer()
    persistence.attach(api2, d)
    with TestCluster(api=api2) as c2:
        c2.create_pods([make_pod("b", limits={TPU: 2})])
        assert c2.wait_for_pods_scheduled(["default/b"])
        b = c2.pod("default/b")
        chips_b = b.meta.annotations[CHIP_INDEX_ANNOTATION]
        assert b.spec.node_name == "n1"
        # restart safety: the recovered pod's chips are not re-assigned
        assert set(chips_a.split(",")).isdisjoint(chips_b.split(","))
        # and a third pod must not fit (4 chips total, all used)
        c2.create_pods([make_pod("overflow", limits={TPU: 1})])
        assert c2.wait_for_pods_unscheduled(["default/overflow"])
