"""Checkpoint/resume: WAL+snapshot persistence and restart recovery.

The reference's durability model is "etcd is the checkpoint" — every
component rebuilds state from the API server on restart (SURVEY §5; chip
occupancy from pod annotations, /root/reference/pkg/flexgpu/gpu_node.go:67-120).
These tests cover both halves: the journal restores the API server's state
across process death, and a restarted scheduler rebuilds chip occupancy from
the recovered pods' annotations without double-assigning chips."""
from __future__ import annotations

import json
import os

import pytest

from tpusched.api.core import Node, Pod, Toleration
from tpusched.api.meta import ObjectMeta
from tpusched.api.resources import TPU, make_resources
from tpusched.api.scheduling import PodGroup, PodGroupSpec
from tpusched.api.topology import TpuTopology, TpuTopologySpec
from tpusched.apiserver import persistence
from tpusched.apiserver import server as srv
from tpusched.plugins.tpuslice.chip_node import CHIP_INDEX_ANNOTATION
from tpusched.testing import TestCluster, make_pod, make_tpu_node, make_pod_group


# -- codec --------------------------------------------------------------------

def test_codec_roundtrip_pod():
    p = make_pod("w", limits={TPU: 2}, requests=make_resources(cpu=1, memory="2Gi"))
    p.spec.tolerations.append(Toleration(key="tpu", operator="Exists"))
    p.meta.annotations["a"] = "b"
    p.status.nominated_node_name = "n9"
    back = persistence.decode_object(Pod, persistence.encode_object(p))
    assert back == p


def test_codec_roundtrip_topology_tuples():
    topo = TpuTopology(
        meta=ObjectMeta(name="pool-a"),
        spec=TpuTopologySpec(pool="pool-a", accelerator="tpu-v5p",
                             dims=(8, 8, 4), wrap=(True, True, False),
                             hosts={"n0": (0, 0, 0), "n1": (0, 0, 4)}))
    back = persistence.decode_object(TpuTopology, persistence.encode_object(topo))
    assert back == topo
    assert isinstance(back.spec.dims, tuple)
    assert isinstance(back.spec.hosts["n1"], tuple)


def test_codec_roundtrip_podgroup():
    pg = PodGroup(meta=ObjectMeta(name="g"),
                  spec=PodGroupSpec(min_member=4, tpu_slice_shape="2x2x1",
                                    min_resources=make_resources(cpu=8)))
    back = persistence.decode_object(PodGroup, persistence.encode_object(pg))
    assert back == pg


# -- journal + recovery -------------------------------------------------------

def test_wal_replay_restores_state(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    api.create(srv.NODES, make_tpu_node("n1", chips=4))
    api.create(srv.PODS, make_pod("a", limits={TPU: 1}))
    api.create(srv.PODS, make_pod("b"))
    api.patch(srv.PODS, "default/a",
              lambda p: p.meta.annotations.update({CHIP_INDEX_ANNOTATION: "0"}))
    api.delete(srv.PODS, "default/b")
    rv_before = api.get(srv.PODS, "default/a").meta.resource_version
    journal.close()  # process death: queue drained, WAL on disk

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    assert api2.try_get(srv.PODS, "default/b") is None
    a = api2.get(srv.PODS, "default/a")
    assert a.meta.annotations[CHIP_INDEX_ANNOTATION] == "0"
    assert a.meta.resource_version == rv_before
    assert api2.get(srv.NODES, "/n1").status.allocatable[TPU] == 4
    # recovered rv is monotonic: new writes must not reuse old versions
    c = api2.create(srv.PODS, make_pod("c"))
    assert c.meta.resource_version > rv_before


def test_recovery_bumps_uid_counter(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    created = api.create(srv.PODS, make_pod("a"))
    journal.close()

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    fresh = api2.create(srv.PODS, make_pod("z"))
    assert fresh.meta.uid != created.meta.uid


def test_compaction_truncates_wal_and_preserves_state(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d, compact_every=5)
    for i in range(12):  # crosses two compaction thresholds
        api.create(srv.PODS, make_pod(f"p{i}"))
    assert journal.flush()
    wal_lines = [l for l in open(os.path.join(d, persistence.WAL_FILE))
                 if l.strip()]
    assert len(wal_lines) < 12
    snap = json.load(open(os.path.join(d, persistence.SNAPSHOT_FILE)))
    assert snap["kinds"][srv.PODS]
    journal.close()

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    assert len(api2.list(srv.PODS)) == 12


def test_torn_wal_tail_is_ignored(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    api.create(srv.PODS, make_pod("a"))
    api.create(srv.PODS, make_pod("b"))
    journal.close()
    with open(os.path.join(d, persistence.WAL_FILE), "a") as f:
        f.write('{"op": "put", "kind": "pods", "obj": {"meta": {"na')  # crash mid-append

    api2 = srv.APIServer()
    restored = persistence.load_into(api2, d)
    # the snapshot from attach() already holds a+b; the torn record is dropped
    assert restored == 2


def test_rv_monotonic_across_delete_and_restart(tmp_path):
    """rv bumps consumed by objects deleted before the crash must still
    advance the recovered rv floor."""
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    api.create(srv.PODS, make_pod("a"))
    rv_b = api.create(srv.PODS, make_pod("b")).meta.resource_version
    api.delete(srv.PODS, "default/b")
    journal.close()

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    c = api2.create(srv.PODS, make_pod("c"))
    assert c.meta.resource_version > rv_b


def test_flush_reports_write_failure(tmp_path, monkeypatch):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)

    def boom(batch):
        raise OSError("disk full")
    real_write = journal._write_batch
    monkeypatch.setattr(journal, "_write_batch", boom)
    api.create(srv.PODS, make_pod("a"))
    assert journal.flush(timeout=5) is False

    # a successful compaction snapshots the full live store — the lost
    # record is durable again and flush() recovers
    monkeypatch.setattr(journal, "_write_batch", real_write)
    journal.compact()
    assert journal.flush(timeout=5) is True
    api2 = srv.APIServer()
    persistence.load_into(api2, d)
    assert api2.try_get(srv.PODS, "default/a") is not None


# -- scheduler restart over recovered state -----------------------------------

def test_scheduler_restart_rebuilds_chip_occupancy(tmp_path):
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    with TestCluster(api=api) as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        c.create_pods([make_pod("a", limits={TPU: 2})])
        assert c.wait_for_pods_scheduled(["default/a"])
        chips_a = c.pod("default/a").meta.annotations[CHIP_INDEX_ANNOTATION]
    journal.close()

    # "process death": a brand-new API server recovers from disk, a
    # brand-new scheduler rebuilds occupancy from pod annotations
    api2 = srv.APIServer()
    persistence.attach(api2, d)
    with TestCluster(api=api2) as c2:
        c2.create_pods([make_pod("b", limits={TPU: 2})])
        assert c2.wait_for_pods_scheduled(["default/b"])
        b = c2.pod("default/b")
        chips_b = b.meta.annotations[CHIP_INDEX_ANNOTATION]
        assert b.spec.node_name == "n1"
        # restart safety: the recovered pod's chips are not re-assigned
        assert set(chips_a.split(",")).isdisjoint(chips_b.split(","))
        # and a third pod must not fit (4 chips total, all used)
        c2.create_pods([make_pod("overflow", limits={TPU: 1})])
        assert c2.wait_for_pods_unscheduled(["default/overflow"])


def test_wal_fuzz_random_mutations_with_torn_tails(tmp_path):
    """Randomized crash consistency: hundreds of random create/patch/delete
    mutations across kinds, flushed to the WAL, then the file is truncated
    at arbitrary byte offsets (torn tail). Replay must reconstruct exactly
    the state as of the last INTACT record — never crash, never resurrect a
    deleted object, never invent one."""
    import json
    import random

    rng = random.Random(42)
    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    # snapshots[i] = full dump of (pods, podgroups) after record i applied
    live_pods, live_pgs = {}, {}
    history = []

    def snap():
        history.append((dict(live_pods), dict(live_pgs)))

    for i in range(200):
        op = rng.random()
        if op < 0.5 or not live_pods:
            name = f"p{i}"
            pod = make_pod(name, limits={TPU: rng.randint(1, 4)})
            api.create(srv.PODS, pod)
            live_pods[f"default/{name}"] = name
        elif op < 0.75:
            key = rng.choice(list(live_pods))
            ann = str(rng.randint(0, 3))
            api.patch(srv.PODS, key,
                      lambda p, a=ann: p.meta.annotations.update({"fuzz": a}))
            live_pods[key] = live_pods[key]  # unchanged membership
        elif op < 0.9:
            key = rng.choice(list(live_pods))
            api.delete(srv.PODS, key)
            del live_pods[key]
        else:
            name = f"g{i}"
            api.create(srv.POD_GROUPS, make_pod_group(name, min_member=2))
            live_pgs[f"default/{name}"] = name
        snap()
    assert journal.flush()
    journal.close()

    wal = tmp_path / "state" / "wal.jsonl"
    raw = wal.read_bytes()

    # full replay matches the final snapshot
    api_full = srv.APIServer()
    persistence.load_into(api_full, d)
    assert {p.meta.key for p in api_full.list(srv.PODS)} == set(live_pods)
    assert {g.meta.key for g in api_full.list(srv.POD_GROUPS)} == set(live_pgs)

    # torn tails at random offsets: replay equals the prefix state
    for _ in range(12):
        cut = rng.randint(1, len(raw) - 1)
        # "intact" must mirror replay's own rule (stop at the first
        # undecodable line): a cut that strips ONLY the trailing newline
        # leaves a complete JSON record, which replay rightly applies —
        # counting by newline positions alone called that record torn and
        # flaked whenever a cut landed on end-of-record-minus-one (record
        # lengths vary run to run with timestamp digits)
        intact = 0
        for ln in raw[:cut].split(b"\n"):
            if not ln.strip():
                continue
            try:
                json.loads(ln)
            except ValueError:
                break
            intact += 1
        torn_dir = tmp_path / f"torn-{cut}"
        torn_dir.mkdir()
        # copy the snapshot file too if compaction produced one
        src_dir = tmp_path / "state"
        for f in src_dir.iterdir():
            if f.name != "wal.jsonl":
                (torn_dir / f.name).write_bytes(f.read_bytes())
        (torn_dir / "wal.jsonl").write_bytes(raw[:cut])

        api_torn = srv.APIServer()
        persistence.load_into(api_torn, str(torn_dir))
        # reconstruct expected state: how many of the 200 mutations are
        # covered by `intact` records? Each mutation = exactly one record
        # (no snapshot compaction was triggered in this run)
        if intact == 0:
            expect_pods, expect_pgs = set(), set()
        else:
            ep, eg = history[min(intact, len(history)) - 1]
            expect_pods, expect_pgs = set(ep), set(eg)
        got_pods = {p.meta.key for p in api_torn.list(srv.PODS)}
        got_pgs = {g.meta.key for g in api_torn.list(srv.POD_GROUPS)}
        assert got_pods == expect_pods, f"cut={cut} intact={intact}"
        assert got_pgs == expect_pgs, f"cut={cut} intact={intact}"


def test_slice_gang_recovery_through_wal(tmp_path):
    """Full control-plane durability for the slice path: topology CR, gang
    PodGroup, and bound members all ride the WAL; the recovered scheduler
    sees the torus as occupied (a second slice stays Pending) and defrag
    works after the recovered gang is deleted."""
    from tpusched.config.profiles import tpu_gang_profile
    from tpusched.testing import make_pod_group, make_tpu_pool

    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)

    def slice_gang(c, name):
        c.api.create(srv.POD_GROUPS, make_pod_group(
            name, min_member=16, tpu_slice_shape="4x4x4",
            tpu_accelerator="tpu-v5p"))
        ps = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
              for i in range(16)]
        c.create_pods(ps)
        return ps

    prof = tpu_gang_profile(permit_wait_s=5, denied_s=1)
    with TestCluster(profile=prof, api=api) as c:
        topo, nodes = make_tpu_pool("pool", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        first = slice_gang(c, "resident")
        assert c.wait_for_pods_scheduled([p.key for p in first], timeout=30)
    journal.close()

    api2 = srv.APIServer()
    persistence.attach(api2, d)
    prof2 = tpu_gang_profile(permit_wait_s=2, denied_s=1)
    with TestCluster(profile=prof2, api=api2) as c2:
        # recovered occupancy: the pool is full, a second slice pends
        second = slice_gang(c2, "newcomer")
        assert c2.wait_for_pods_unscheduled([p.key for p in second], hold=1.5)
        # defrag: delete the recovered gang; the newcomer takes the window
        for i in range(16):
            api2.delete(srv.PODS, f"default/resident-{i}")
        assert c2.wait_for_pods_scheduled([p.key for p in second], timeout=20)
        hosts = {c2.pod(p.key).spec.node_name for p in second}
        assert len(hosts) == 16


def test_replay_tolerates_schema_drift(tmp_path):
    """Cross-version replay contract (the codec's forward/backward
    tolerance, relied on for rolling upgrades of --state-dir):
    - a record field the current schema does not define is IGNORED (a
      newer writer added it),
    - a field the record lacks takes the dataclass default (an older
      writer predates it),
    - a whole record kind the current binary does not know is SKIPPED,
    and replay of the surrounding records is unaffected."""
    import json
    import os

    d = str(tmp_path / "state")
    api = srv.APIServer()
    journal = persistence.attach(api, d)
    api.create(srv.NODES, make_tpu_node("n1", chips=4))
    api.create(srv.PODS, make_pod("a", limits={TPU: 1}))
    journal.close()

    wal = os.path.join(d, persistence.WAL_FILE)
    with open(wal, encoding="utf-8") as f:
        recs = [json.loads(line) for line in f if line.strip()]
    # newer-writer drift: unknown object field + unknown record kind
    pod_rec = next(r for r in recs if r["kind"] == srv.PODS)
    pod_rec["obj"]["spec"]["future_field"] = {"x": 1}
    pod_rec["obj"]["meta"]["another_new"] = "y"
    recs.append({"op": "put", "kind": "futurekinds",
                 "obj": {"meta": {"name": "f", "namespace": "default"}}})
    # older-writer drift: drop an optional field entirely
    node_rec = next(r for r in recs if r["kind"] == srv.NODES)
    node_rec["obj"]["meta"].pop("annotations", None)
    with open(wal, "w", encoding="utf-8") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")

    api2 = srv.APIServer()
    n = persistence.load_into(api2, d)
    assert n == 2                                  # unknown kind skipped
    a = api2.get(srv.PODS, "default/a")
    assert not hasattr(a.spec, "future_field")     # drift dropped, not kept
    assert a.spec.containers[0].limits[TPU] == 1   # surrounding data intact
    assert api2.get(srv.NODES, "/n1").meta.annotations == {}  # default
