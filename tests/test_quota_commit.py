"""ISSUE 14: the quota-aware optimistic commit protocol + the O(Δ) cycle
core's persistent pooled snapshots.

Four layers:

1. unit semantics of the cache quota ledger + the quota-epoch
   compare-and-reserve (``Cache.assume_pod_guarded(quota_guard=...)``);
2. a hypothesis property: under fuzzed cache operations (assume, confirm,
   forget, delete, node churn, bounds churn, termination), the ledger's
   reserved usage equals the usage recomputed from the cache's own pod
   table — "reserved usage == bound usage" at every step;
3. persistent pooled snapshots: structural sub-map sharing across
   epochs, shared_snapshot()'s no-bookkeeping contract, candidate-list
   caching;
4. e2e: a SHARDED scheduler over ElasticQuota namespaces binds quota'd
   pods on SHARD lanes (the pre-14 core serialized them wholesale
   through the global lane), and an over-min borrower escalates.
"""
from __future__ import annotations

import pytest

from tpusched.api.resources import TPU, make_resources
from tpusched.api.topology import LABEL_POOL
from tpusched.apiserver import server as srv
from tpusched.config.profiles import full_stack_profile
from tpusched.fwk.nodeinfo import PooledSnapshot
from tpusched.sched.cache import Cache, QUOTA_CONFLICT, QuotaReserve
from tpusched.testing import (TestCluster, make_elastic_quota, make_node,
                              make_pod, make_tpu_pool)
from tpusched.util.podutil import pod_effective_request


def _pool_node(name: str, pool: str, chips: int = 8):
    node = make_node(name)
    node.meta.labels[LABEL_POOL] = pool
    node.status.allocatable[TPU] = chips
    return node


def _pod(name: str, ns: str = "team-a", chips: int = 2):
    return make_pod(name, namespace=ns, limits={TPU: chips})


def _quota_cache() -> Cache:
    c = Cache()
    c.add_node(_pool_node("a1", "pool-a"))
    c.add_node(_pool_node("b1", "pool-b"))
    c.sync_quota_bounds({"team-a": ({TPU: 4}, {TPU: 8}),
                         "team-b": ({TPU: 4}, {TPU: 8})})
    return c


# -- 1. ledger + compare-and-reserve unit semantics ---------------------------


def test_quota_ledger_tracks_assume_confirm_forget():
    c = _quota_cache()
    p = _pod("w0")
    c.assume_pod(p, "a1")
    assert c.quota_used_snapshot()["team-a"].get(TPU) == 2
    # watch confirm replaces the assumed entry without double-count
    confirmed = _pod("w0")
    confirmed.spec.node_name = "a1"
    c.add_pod(confirmed)
    assert c.quota_used_snapshot()["team-a"].get(TPU) == 2
    c.remove_pod(confirmed)
    assert c.quota_used_snapshot()["team-a"].get(TPU, 0) == 0


def test_quota_ledger_releases_on_forget_even_without_node():
    """A pod whose node vanished still releases its quota at forget —
    the ledger follows the pod table, not node attachment."""
    c = _quota_cache()
    p = _pod("w1")
    c.assume_pod(p, "a1")
    c.remove_node(_pool_node("a1", "pool-a"))
    c.forget_pod(p)
    assert c.quota_used_snapshot()["team-a"].get(TPU, 0) == 0


def test_quota_reserve_refuses_when_room_genuinely_consumed():
    """The semantic compare-and-reserve: a commit is refused exactly when
    concurrent quota'd traffic consumed the room its admission assumed —
    own-namespace max here."""
    c = _quota_cache()
    cursor = c.snapshot_view(["pool-a"]).pool_cursors["pool-a"]
    # a foreign commit fills team-a's max (8) to the brim...
    c.assume_pod(_pod("foreign", ns="team-a", chips=7), "b1")
    # ...so a 2-chip commit judged against empty usage is refused with
    # the QUOTA sentinel (pool-a's cursor untouched: not a pool conflict)
    guard = QuotaReserve("team-a", {TPU: 2}, {TPU: 2})
    assert c.assume_pod_guarded(_pod("mine"), "a1", cursor,
                                quota_guard=guard) is QUOTA_CONFLICT
    # a commit that still fits lands — even though the ledger CHANGED
    # since admission (semantic guard: no false conflicts on mere churn)
    small = QuotaReserve("team-a", {TPU: 1}, {TPU: 1})
    assert c.assume_pod_guarded(_pod("mine", chips=1), "a1",
                                c.pool_cursor("pool-a"),
                                quota_guard=small) is not None


def test_quota_reserve_enforces_aggregate_borrow_gate():
    """Σused + total vs Σmin is checked against the LIVE fleet sums:
    an intra-min reserve in team-b invalidates a concurrently-judged
    borrow in team-a (the cross-namespace race a per-namespace guard
    cannot see)."""
    c = _quota_cache()   # mins 4+4 = 8, maxes 8
    cursor = c.snapshot_view(["pool-a"]).pool_cursors["pool-a"]
    # borrow admission judged on an empty fleet: 8 ≤ Σmin 8, OK...
    guard = QuotaReserve("team-a", {TPU: 8}, {TPU: 8})
    # ...but a foreign intra-min reserve lands first
    c.assume_pod(_pod("foreign", ns="team-b", chips=4), "b1")
    assert c.assume_pod_guarded(_pod("borrower", chips=8), "a1", cursor,
                                quota_guard=guard) is QUOTA_CONFLICT
    # releases LOOSEN the bounds: after the foreign pod goes away the
    # same stale guard commits (teardown churn never refuses)
    c.remove_pod(_pod("foreign", ns="team-b", chips=4))
    assert c.assume_pod_guarded(_pod("borrower", chips=8), "a1",
                                c.pool_cursor("pool-a"),
                                quota_guard=guard) is not None


def test_non_quota_traffic_never_moves_the_epoch():
    c = _quota_cache()
    _, epoch = c.quota_view()
    c.assume_pod(make_pod("plain", namespace="no-quota",
                          limits={TPU: 2}), "a1")
    _, epoch2 = c.quota_view()
    assert epoch2 == epoch, "an unregistered namespace bumped the epoch"


def test_bounds_change_moves_the_epoch():
    c = _quota_cache()
    _, epoch = c.quota_view()
    c.sync_quota_bounds({"team-a": ({TPU: 4}, {TPU: 16}),
                         "team-b": ({TPU: 4}, {TPU: 8})})
    _, epoch2 = c.quota_view()
    assert epoch2 > epoch, "a max change must invalidate in-flight verdicts"


def test_quota_seed_counts_preexisting_pods():
    c = Cache()
    c.add_node(_pool_node("a1", "pool-a"))
    c.assume_pod(_pod("early"), "a1")
    c.sync_quota_bounds({"team-a": ({TPU: 4}, {TPU: 8})})
    assert c.quota_used_snapshot()["team-a"].get(TPU) == 2


# -- 2. hypothesis: reserved usage == recomputed usage under fuzzed ops -------


def _ledger_oracle(cache: Cache):
    """Recompute per-namespace usage from the cache's own pod table —
    what the ledger must equal at every step."""
    from tpusched.util.podutil import is_pod_terminated
    want = {}
    for ns in cache._quota_bounds:
        total = {}
        for pod in cache._pods.values():
            if pod.meta.namespace != ns or is_pod_terminated(pod):
                continue
            for k, v in pod_effective_request(pod).items():
                total[k] = total.get(k, 0) + v
        want[ns] = {k: v for k, v in total.items() if v}
    return want


_OPS = ("assume", "confirm", "forget", "delete", "terminate",
        "node-del", "node-add", "bounds", "unbound")


def _run_ledger_script(script) -> None:
    """Apply one op script to a fresh cache, asserting after EVERY op that
    the ledger equals the oracle and the epoch is monotone."""
    from tpusched.api.core import POD_SUCCEEDED
    c = Cache()
    c.add_node(_pool_node("a1", "pool-a"))
    c.add_node(_pool_node("b1", "pool-b"))
    c.sync_quota_bounds({"team-a": ({TPU: 4}, {TPU: 64}),
                         "team-b": ({TPU: 2}, {TPU: 64})})
    epochs = [c.quota_epoch()]
    for op, pid, ns, chips in script:
        pod = make_pod(f"p{pid}", namespace=ns, limits={TPU: chips})
        if op == "assume":
            c.assume_pod(pod, "a1")
        elif op == "confirm":
            pod.spec.node_name = "b1"
            c.add_pod(pod)
        elif op == "forget":
            c.forget_pod(pod)
        elif op == "delete":
            c.remove_pod(pod)
        elif op == "terminate":
            pod.spec.node_name = "a1"
            pod.status.phase = POD_SUCCEEDED
            c.update_pod(pod)
        elif op == "node-del":
            c.remove_node(_pool_node("b1", "pool-b"))
        elif op == "node-add":
            c.add_node(_pool_node("b1", "pool-b"))
        elif op == "bounds":
            c.sync_quota_bounds(
                {"team-a": ({TPU: 4}, {TPU: 64 + chips}),
                 "team-b": ({TPU: 2}, {TPU: 64})})
        elif op == "unbound":
            c.sync_quota_bounds({"team-a": ({TPU: 4}, {TPU: 64})})
            c.sync_quota_bounds({"team-a": ({TPU: 4}, {TPU: 64}),
                                 "team-b": ({TPU: 2}, {TPU: 64})})
        got = {ns2: {k: v for k, v in used.items() if v}
               for ns2, used in c.quota_used_snapshot().items()}
        oracle = _ledger_oracle(c)
        assert got == oracle, (op, pid, ns, chips)
        # the fleet aggregate (the borrow gate's live operand) must equal
        # the sum of the per-namespace ledgers at every step
        want_sum = {}
        for used in oracle.values():
            for k, v in used.items():
                want_sum[k] = want_sum.get(k, 0) + v
        got_sum = {k: v for k, v in c._quota_used_sum.items() if v}
        assert got_sum == want_sum, (op, pid, ns, chips)
        epochs.append(c.quota_epoch())
    assert epochs == sorted(epochs), "quota epoch went backwards"


def test_quota_ledger_property_hypothesis():
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    ops = st.lists(
        st.tuples(st.sampled_from(_OPS),
                  st.integers(min_value=0, max_value=5),   # pod id
                  st.sampled_from(["team-a", "team-b", "free"]),
                  st.integers(min_value=1, max_value=4)),  # chips
        min_size=1, max_size=40)

    @settings(max_examples=120, deadline=None)
    @given(ops)
    def run(script):
        _run_ledger_script(script)

    run()


def test_quota_ledger_property_seeded_fuzz():
    """The same property on deterministic seeds — the arm that always
    runs on boxes without hypothesis (test_window_index precedent)."""
    import random
    for seed in (1, 7, 20260804):
        rng = random.Random(seed)
        script = [(rng.choice(_OPS), rng.randrange(6),
                   rng.choice(["team-a", "team-b", "free"]),
                   rng.randrange(1, 5))
                  for _ in range(200)]
        _run_ledger_script(script)


# -- 3. persistent pooled snapshots -------------------------------------------


def test_pooled_snapshot_shares_untouched_pool_submaps():
    c = Cache()
    for i in range(4):
        c.add_node(_pool_node(f"a{i}", "pool-a"))
        c.add_node(_pool_node(f"b{i}", "pool-b"))
    s1 = c.snapshot()
    assert isinstance(s1, PooledSnapshot)
    # quiet cache: the SAME snapshot object is served
    assert c.snapshot() is s1
    # mutate pool-b only: pool-a's sub-map (and its NodeInfo clones) are
    # shared by reference between the epochs, pool-b's is rebuilt
    c.assume_pod(make_pod("x", limits={TPU: 1}), "b0")
    s2 = c.snapshot()
    assert s2 is not s1
    assert s2._pools["pool-a"] is s1._pools["pool-a"]
    assert s2._pools["pool-b"] is not s1._pools["pool-b"]
    assert s2.get("a0") is s1.get("a0")
    assert s2.get("b0") is not s1.get("b0")
    # cursor dict moved only for the mutated pool
    assert s2.pool_cursors["pool-a"] == s1.pool_cursors["pool-a"]
    assert s2.pool_cursors["pool-b"] > s1.pool_cursors["pool-b"]


def test_pooled_snapshot_candidate_list_cached_per_epoch():
    c = Cache()
    for i in range(3):
        c.add_node(_pool_node(f"n{i}", "pool-a"))
    snap = c.snapshot()
    flat = snap.list()
    assert snap.list() is flat, "per-epoch candidate list must be cached"
    assert {i.node.name for i in flat} == {"n0", "n1", "n2"}
    assert snap.num_nodes() == 3
    assert sorted(snap.node_names()) == ["n0", "n1", "n2"]


def test_shared_snapshot_never_advances_loop_bookkeeping():
    c = Cache()
    c.add_node(_pool_node("n0", "pool-a"))
    c.snapshot()
    before = c.snapshot_cursor()
    c.assume_pod(make_pod("y", limits={TPU: 1}), "n0")
    shared = c.shared_snapshot()
    # fresh content...
    assert shared.get("n0") is not None
    assert len(shared.get("n0").pods) == 1
    # ...but the loop's snapshot cursor is untouched (the equivalence
    # arming guard's input — a foreign advance would launder mutations)
    assert c.snapshot_cursor() == before
    assert c.peek_snapshot() is not shared


def test_pooled_snapshot_partition_view_is_cached_and_scoped():
    c = Cache()
    c.add_node(_pool_node("a1", "pool-a"))
    c.add_node(_pool_node("b1", "pool-b"))
    v1 = c.snapshot_view(["pool-a"])
    assert v1.snapshot.num_nodes() == 1
    assert v1.snapshot.get("b1") is None
    v2 = c.snapshot_view(["pool-a"])
    assert v2.snapshot is v1.snapshot
    # foreign-pool mutation leaves the partition view untouched
    c.assume_pod(make_pod("z", limits={TPU: 1}), "b1")
    v3 = c.snapshot_view(["pool-a"])
    assert v3.snapshot is v1.snapshot
    # the cursor tuple is memoized per epoch
    assert v3.cursor_tuple() is v1.cursor_tuple()


def test_pooled_snapshot_live_quorum_index():
    c = Cache()
    c.add_node(_pool_node("a1", "pool-a"))
    snap = c.snapshot()
    assert snap.live_pg_assigned
    assert snap.assigned_count("g", "default") == 0
    member = make_pod("m0", pod_group="g", limits={TPU: 1})
    c.assume_pod(member, "a1")
    # live-is-fresher: the SAME snapshot object sees the assume
    assert snap.assigned_count("g", "default") == 1


# -- 4. e2e: quota'd fleets dispatch on shard lanes ---------------------------


def _quota_fleet_profile(shards: int):
    prof = full_stack_profile(permit_wait_s=10, denied_s=1)
    prof.dispatch_shards = shards
    return prof


def test_sharded_quota_fleet_binds_on_shard_lanes():
    """The headline behavior: ElasticQuotas in the fleet no longer route
    every pod through the global lane — intra-min quota'd pods dispatch
    (and bind) on their shard lanes under the epoch-guarded commit."""
    with TestCluster(profile=_quota_fleet_profile(4)) as c:
        for i in range(4):
            topo, nodes = make_tpu_pool(f"pool-{i}", dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        for ns in ("team-a", "team-b"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{ns}-quota", ns, min={TPU: 512}, max={TPU: 1024}))
        pods = [make_pod(f"w{i}", namespace="team-a" if i % 2 else "team-b",
                         limits={TPU: 4},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(12)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=60)
        stats = c.scheduler._shard_stats.snapshot()
        shard_binds = sum(row["binds"] for lane, row in
                          stats["lanes"].items() if lane != "global")
        assert shard_binds > 0, (
            f"every quota'd bind went through the global lane — the "
            f"quota-aware commit protocol is not routing shard lanes: "
            f"{stats}")
        health = c.scheduler.cache.quota_health()
        assert health["namespaces"] == 2
        assert health["epoch"] > 0


def test_sharded_quota_borrower_escalates_to_global_lane():
    """An over-min borrower on a shard lane is rejected by
    CapacityScheduling's partition-scope rule and escalates; the global
    lane admits it fleet-wide (it still binds)."""
    with TestCluster(profile=_quota_fleet_profile(4)) as c:
        for i in range(2):
            topo, nodes = make_tpu_pool(f"pool-{i}", dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        # team-a: tiny min, generous max — any real pod borrows
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "a-quota", "team-a", min={TPU: 1}, max={TPU: 1024}))
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "b-quota", "team-b", min={TPU: 2048}, max={TPU: 4096}))
        pod = make_pod("borrower", namespace="team-a", limits={TPU: 4},
                       requests=make_resources(cpu=1, memory="1Gi"))
        c.create_pods([pod])
        assert c.wait_for_pods_scheduled([pod.key], timeout=60)
        router = c.scheduler.shard_router()
        assert "team-a/borrower" in router.escalated_units(), (
            router.escalated_units())


def test_sharded_quota_burst_never_overshoots_max():
    """The equivalence cache stays WARM under quotas in sharded mode
    (ISSUE 14: bounds-only fingerprint) — so this pins the safety side:
    a burst of identical quota'd pods (one equivalence class, hit-path
    commits carrying the memoized QuotaReserve) must bind at most the
    quota max; the commit's semantic re-check is the only thing standing
    between a stale memoized admission and overshoot."""
    import time as _time
    prof = _quota_fleet_profile(4)
    with TestCluster(profile=prof) as c:
        topo, nodes = make_tpu_pool("pool-0", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)          # 16 hosts × 4 chips = 64 chips
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "a-quota", "team-a", min={TPU: 12}, max={TPU: 12}))
        pods = [make_pod(f"b{i}", namespace="team-a", limits={TPU: 4},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(8)]               # 32 chips asked, 12 allowed
        c.create_pods(pods)
        deadline = _time.monotonic() + 20
        while _time.monotonic() < deadline:
            bound = [p for p in pods
                     if c.pod(p.key) and c.pod(p.key).spec.node_name]
            if len(bound) >= 3:
                break
            _time.sleep(0.05)
        _time.sleep(1.0)             # let any overshooting stragglers bind
        bound = [p for p in pods
                 if c.pod(p.key) and c.pod(p.key).spec.node_name]
        assert len(bound) == 3, (
            f"{len(bound)} × 4-chip pods bound under a 12-chip max — "
            f"{'overshoot' if len(bound) > 3 else 'under-admission'}")
        assert c.scheduler.cache.quota_used_snapshot()["team-a"].get(
            TPU, 0) <= 12


def test_quota_serialize_legacy_arm_routes_global():
    """The pre-14 wholesale serialization survives as the opt-in
    quota_serialize_dispatch knob (the bench baseline arm)."""
    prof = _quota_fleet_profile(4)
    prof.quota_serialize_dispatch = True
    with TestCluster(profile=prof) as c:
        topo, nodes = make_tpu_pool("pool-0", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "a-quota", "team-a", min={TPU: 512}, max={TPU: 1024}))
        pods = [make_pod(f"s{i}", namespace="team-a", limits={TPU: 4},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(4)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=60)
        stats = c.scheduler._shard_stats.snapshot()
        shard_binds = sum(row["binds"] for lane, row in
                          stats["lanes"].items() if lane != "global")
        assert shard_binds == 0, (
            f"legacy serialize arm bound on shard lanes: {stats}")
