"""Tests for NodeResourcesAllocatable, PodState, QOSSort,
PreemptionToleration, and CrossNodePreemption."""
import time

from tpusched.api.core import PriorityClass
from tpusched.api.meta import ObjectMeta
from tpusched.api.resources import CPU, TPU, make_resources
from tpusched.apiserver import server as srv
from tpusched.config.types import NodeResourcesAllocatableArgs
from tpusched.fwk import CycleState, PluginProfile
from tpusched.plugins.preemptiontoleration import (
    ANNOTATION_MIN_PREEMPTABLE, ANNOTATION_TOLERATION_SECONDS,
    exempted_from_preemption, parse_policy)
from tpusched.sched.queue import QueuedPodInfo
from tpusched.testing import (TestCluster, make_node, make_pod, make_tpu_node,
                              new_test_framework)


# -- NodeResourcesAllocatable -------------------------------------------------

def test_allocatable_least_mode_prefers_small_nodes():
    small = make_node("small", capacity=make_resources(cpu=8, memory="32Gi"))
    big = make_node("big", capacity=make_resources(cpu=64, memory="256Gi"))
    profile = PluginProfile(score=[("NodeResourcesAllocatable", 1)],
                            bind=["DefaultBinder"])
    fw, handle, _ = new_test_framework(profile, nodes=[small, big])
    totals, s = fw.run_score_plugins(CycleState(), make_pod("p"), [small, big])
    assert s.is_success()
    assert totals["small"] == 100 and totals["big"] == 0


def test_allocatable_most_mode_prefers_big_nodes():
    small = make_node("small", capacity=make_resources(cpu=8, memory="32Gi"))
    big = make_node("big", capacity=make_resources(cpu=64, memory="256Gi"))
    profile = PluginProfile(score=[("NodeResourcesAllocatable", 1)],
                            bind=["DefaultBinder"])
    profile.plugin_args["NodeResourcesAllocatable"] = \
        NodeResourcesAllocatableArgs(mode="Most")
    fw, handle, _ = new_test_framework(profile, nodes=[small, big])
    totals, s = fw.run_score_plugins(CycleState(), make_pod("p"), [small, big])
    assert totals["big"] == 100 and totals["small"] == 0


# -- PodState -----------------------------------------------------------------

def test_podstate_prefers_terminating_capacity():
    n1, n2 = make_node("n1"), make_node("n2")
    terminating = make_pod("t", node_name="n1")
    terminating.meta.deletion_timestamp = time.time()
    profile = PluginProfile(score=[("PodState", 1)], bind=["DefaultBinder"])
    fw, handle, _ = new_test_framework(profile, nodes=[n1, n2],
                                       pods=[terminating])
    totals, s = fw.run_score_plugins(CycleState(), make_pod("p"), [n1, n2])
    assert s.is_success()
    assert totals["n1"] > totals["n2"]


# -- QOSSort ------------------------------------------------------------------

def test_qossort_order():
    from tpusched.plugins.qossort import QOSSort
    sort = QOSSort()
    guaranteed = QueuedPodInfo(make_pod("g", requests={CPU: 100, "memory": 100},
                                        limits={CPU: 100, "memory": 100}))
    burstable = QueuedPodInfo(make_pod("b", requests={CPU: 100}))
    best_effort = QueuedPodInfo(make_pod("e"))
    assert sort.less(guaranteed, burstable)
    assert sort.less(burstable, best_effort)
    assert not sort.less(best_effort, guaranteed)
    high_priority_be = QueuedPodInfo(make_pod("hp", priority=10))
    assert sort.less(high_priority_be, guaranteed)  # priority first


# -- PreemptionToleration -----------------------------------------------------

def make_pc(name, value, minimum=None, toleration=None):
    ann = {}
    if minimum is not None:
        ann[ANNOTATION_MIN_PREEMPTABLE] = str(minimum)
    if toleration is not None:
        ann[ANNOTATION_TOLERATION_SECONDS] = str(toleration)
    return PriorityClass(meta=ObjectMeta(name=name, namespace="",
                                         annotations=ann), value=value)


def test_parse_policy_defaults():
    pc = make_pc("low", 100)
    policy = parse_policy(pc)
    assert policy.minimum_preemptable_priority == 101
    assert policy.toleration_seconds == 0
    assert parse_policy(make_pc("bad", 1, minimum="oops")) is None


def test_exempted_from_preemption_window():
    pc = make_pc("tolerant", 100, minimum=10000, toleration=3600)
    getter = lambda name: pc
    victim = make_pod("v", priority=100, priority_class_name="tolerant")
    from tpusched.api.core import PodCondition
    victim.status.conditions.append(PodCondition(
        type="PodScheduled", status="True", last_transition_time=1000.0))
    preemptor = make_pod("p", priority=500)
    # within the toleration window → exempt
    assert exempted_from_preemption(victim, preemptor, getter, now=2000.0)
    # window expired → preemptable
    assert not exempted_from_preemption(victim, preemptor, getter, now=1000.0 + 3601)
    # preemptor above minimum-preemptable → never exempt
    big = make_pod("big", priority=20000)
    assert not exempted_from_preemption(victim, big, getter, now=2000.0)
    # negative toleration → exempt forever
    pc2 = make_pc("forever", 100, minimum=10000, toleration=-1)
    assert exempted_from_preemption(victim, preemptor, lambda n: pc2, now=10**9)


def pt_profile():
    return PluginProfile(
        queue_sort="PrioritySort",
        filter=["NodeUnschedulable", "NodeResourcesFit", "TpuSlice"],
        post_filter=["PreemptionToleration"],
        score=[("TpuSlice", 1)],
        reserve=["TpuSlice"],
        bind=["TpuSlice"],
    )


def test_preemption_toleration_integration():
    """Exempt victims survive; the non-exempt one is evicted."""
    with TestCluster(profile=pt_profile()) as c:
        c.api.create(srv.PRIORITY_CLASSES,
                     make_pc("tolerant", 100, minimum=10000, toleration=-1))
        c.add_nodes([make_tpu_node("h0", chips=4)])
        protected = make_pod("protected", limits={TPU: 2}, priority=100,
                             priority_class_name="tolerant")
        plain = make_pod("plain", limits={TPU: 2}, priority=100)
        c.create_pods([protected, plain])
        assert c.wait_for_pods_scheduled([protected.key, plain.key])
        preemptor = make_pod("preemptor", limits={TPU: 2}, priority=500)
        c.create_pods([preemptor])
        assert c.wait_for_pods_scheduled([preemptor.key], timeout=15)
        assert c.pod(protected.key) is not None   # exempt → survived
        assert c.pod(plain.key) is None           # evicted


def test_parse_policy_edge_cases():
    # malformed toleration string invalidates the whole policy
    assert parse_policy(make_pc("bad-tol", 1, minimum=10, toleration="soon")) is None
    # explicit minimum respected verbatim, even below pc.value
    p = parse_policy(make_pc("low-min", 1000, minimum=5))
    assert p.minimum_preemptable_priority == 5
    # toleration alone keeps the minimum default of value+1
    p2 = parse_policy(make_pc("tol-only", 7, toleration=60))
    assert (p2.minimum_preemptable_priority, p2.toleration_seconds) == (8, 60)


def test_exempted_without_priority_class_or_schedule_condition():
    pc = make_pc("tolerant", 100, minimum=10000, toleration=3600)
    preemptor = make_pod("p", priority=500)
    # no priority class on the victim → never exempt
    bare = make_pod("bare", priority=100)
    assert not exempted_from_preemption(bare, preemptor, lambda n: pc)
    # priority class that the getter can't resolve → not exempt
    ghost = make_pod("ghost", priority=100, priority_class_name="gone")
    assert not exempted_from_preemption(ghost, preemptor, lambda n: None)
    # victim not yet scheduled (no PodScheduled condition) → tolerate
    pending = make_pod("pending", priority=100, priority_class_name="tolerant")
    assert exempted_from_preemption(pending, preemptor, lambda n: pc,
                                    now=10**9)


def select_pt_victims(priority_classes, running, preemptor, chips=4):
    """Drive PreemptionToleration._Interface.select_victims_on_node directly
    (preemption_toleration.go:182-283 table style)."""
    from tpusched.fwk.status import UNSCHEDULABLE_AND_UNRESOLVABLE  # noqa: F401
    from tpusched.plugins.preemptiontoleration import _Interface
    from tpusched.apiserver import APIServer
    api = APIServer()
    for pc in priority_classes:
        api.create(srv.PRIORITY_CLASSES, pc)
    for p in running:
        p.spec.node_name = "h0"
    node = make_tpu_node("h0", chips=chips)
    fw, handle, _ = new_test_framework(pt_profile(), nodes=[node],
                                       pods=running, api=api)
    iface = _Interface(handle, lambda name: handle.informer_factory
                       .priorityclasses().get("/" + name))
    ni = handle.snapshot_shared_lister().get("h0").clone()
    return iface.select_victims_on_node(CycleState(), preemptor, ni, [])


def test_pt_select_victims_exemption_filter():
    """The exemption filter removes tolerated pods from candidacy entirely;
    remaining lower-priority pods are selected and reprieved minimally."""
    pcs = [make_pc("tolerant", 100, minimum=10000, toleration=-1)]
    running = [
        make_pod("protected", limits={TPU: 2}, priority=100,
                 priority_class_name="tolerant"),
        make_pod("plain-lo", limits={TPU: 1}, priority=1),
        make_pod("plain-mid", limits={TPU: 1}, priority=50),
    ]
    preemptor = make_pod("pree", limits={TPU: 1}, priority=500)
    victims, n_pdb, status = select_pt_victims(pcs, running, preemptor)
    assert status.is_success()
    # one chip suffices: reprieve keeps plain-mid, evicts only plain-lo
    assert [v.name for v in victims] == ["plain-lo"]
    assert n_pdb == 0


def test_pt_select_victims_all_exempt_unresolvable():
    pcs = [make_pc("tolerant", 100, minimum=10000, toleration=-1)]
    running = [make_pod(f"prot-{i}", limits={TPU: 2}, priority=100,
                        priority_class_name="tolerant") for i in range(2)]
    preemptor = make_pod("pree", limits={TPU: 2}, priority=500)
    victims, _, status = select_pt_victims(pcs, running, preemptor)
    assert victims == []
    from tpusched.fwk.status import UNSCHEDULABLE_AND_UNRESOLVABLE
    assert status.code == UNSCHEDULABLE_AND_UNRESOLVABLE


def test_pt_select_victims_expired_window_preemptable():
    """Once the toleration window lapses, the same pod becomes a victim."""
    from tpusched.api.core import PodCondition
    pcs = [make_pc("brief", 100, minimum=10000, toleration=1)]
    victim = make_pod("was-protected", limits={TPU: 2}, priority=100,
                      priority_class_name="brief")
    victim.status.conditions.append(PodCondition(
        type="PodScheduled", status="True",
        last_transition_time=time.time() - 3600))
    preemptor = make_pod("pree", limits={TPU: 4}, priority=500)
    victims, _, status = select_pt_victims(pcs, [victim], preemptor)
    assert status.is_success()
    assert [v.name for v in victims] == ["was-protected"]


def test_pt_select_victims_preemptor_above_minimum_ignores_exemption():
    pcs = [make_pc("tolerant", 100, minimum=400, toleration=-1)]
    running = [make_pod("protected", limits={TPU: 4}, priority=100,
                        priority_class_name="tolerant")]
    preemptor = make_pod("pree", limits={TPU: 4}, priority=500)  # ≥ minimum
    victims, _, status = select_pt_victims(pcs, running, preemptor)
    assert status.is_success()
    assert [v.name for v in victims] == ["protected"]


# -- CrossNodePreemption ------------------------------------------------------

def cnp_profile():
    return PluginProfile(
        queue_sort="PrioritySort",
        filter=["NodeUnschedulable", "NodeResourcesFit", "TpuSlice"],
        post_filter=["CrossNodePreemption"],
        score=[("TpuSlice", 1)],
        reserve=["TpuSlice"],
        bind=["TpuSlice"],
    )


def test_cross_node_preemption_frees_whole_node():
    with TestCluster(profile=cnp_profile()) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        lows = [make_pod(f"low-{i}", limits={TPU: 1}, priority=1)
                for i in range(4)]
        c.create_pods(lows)
        assert c.wait_for_pods_scheduled([p.key for p in lows])
        high = make_pod("high", limits={TPU: 4}, priority=100)
        c.create_pods([high])
        assert c.wait_for_pods_scheduled([high.key], timeout=15)
        assert all(c.pod(p.key) is None for p in lows)


def test_cross_node_dry_run_has_no_prefilter_side_effects():
    """The what-if dry-run must never re-run full PreFilter plugins — a
    stateful gate (e.g. Coscheduling's denied-PG TTL cache) would be poisoned
    by a hypothetical pass (upstream dryRunOnePass runs only RemovePod
    extensions + Filter)."""
    from tpusched.fwk import CycleState
    from tpusched.plugins.crossnodepreemption import CrossNodePreemption

    nodes = [make_tpu_node("h0", chips=4)]
    victims = [make_pod(f"low-{i}", limits={TPU: 1}, priority=1,
                        node_name="h0") for i in range(4)]
    fw, handle, api = new_test_framework(cnp_profile(), nodes=nodes,
                                         pods=victims)
    calls = []
    orig = fw.run_pre_filter_plugins
    fw.run_pre_filter_plugins = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    plugin = CrossNodePreemption.new(None, handle)
    high = make_pod("high", limits={TPU: 4}, priority=100)
    node = plugin._dry_run(CycleState(), high, tuple(victims))
    assert node == "h0"
    assert calls == []


# -- NodeResourceLimits (KEP-217 analog) --------------------------------------

def test_node_resource_limits_spreads_away_from_oversubscribed():
    """The node whose resident LIMITS are oversubscribed scores lower even
    though its requests look idle (the KEP-217 use case)."""
    hot = make_node("hot", capacity=make_resources(cpu=8000, memory="32Gi"))
    cold = make_node("cold", capacity=make_resources(cpu=8000, memory="32Gi"))
    # resident burstable pod: request 1 cpu, limit 16 (2x allocatable)
    resident = make_pod("burst", node_name="hot",
                        requests=make_resources(cpu=1000),
                        limits=make_resources(cpu=16000))
    profile = PluginProfile(score=[("NodeResourceLimits", 1)],
                            bind=["DefaultBinder"])
    fw, handle, _ = new_test_framework(profile, nodes=[hot, cold],
                                       pods=[resident])
    pod = make_pod("p", limits=make_resources(cpu=2000))
    totals, s = fw.run_score_plugins(CycleState(), pod, [hot, cold])
    assert s.is_success()
    assert totals["cold"] > totals["hot"]
    assert totals["hot"] == 0            # >= 2x oversubscribed floors at 0


def test_node_resource_limits_counts_hbm():
    """tpu-memory limits join the ratio: a host whose HBM is limit-packed by
    serving pods scores below an empty one."""
    from tpusched.api.resources import TPU_MEMORY
    a = make_tpu_node("hbm-full", chips=4)
    b = make_tpu_node("hbm-free", chips=4)
    hbm = a.status.allocatable[TPU_MEMORY]
    resident = make_pod("serve", node_name="hbm-full",
                        limits={TPU_MEMORY: hbm})
    profile = PluginProfile(score=[("NodeResourceLimits", 1)],
                            bind=["DefaultBinder"])
    fw, handle, _ = new_test_framework(profile, nodes=[a, b], pods=[resident])
    pod = make_pod("p", limits={TPU_MEMORY: hbm // 4})
    totals, s = fw.run_score_plugins(CycleState(), pod, [a, b])
    assert s.is_success()
    assert totals["hbm-free"] > totals["hbm-full"]


def test_node_resource_limits_neutral_for_limitless_pods():
    """BestEffort pods on empty nodes: every node scores MAX (no limit
    pressure anywhere)."""
    n1 = make_node("n1")
    n2 = make_node("n2")
    profile = PluginProfile(score=[("NodeResourceLimits", 1)],
                            bind=["DefaultBinder"])
    fw, handle, _ = new_test_framework(profile, nodes=[n1, n2])
    totals, s = fw.run_score_plugins(CycleState(), make_pod("p"), [n1, n2])
    assert s.is_success()
    assert totals["n1"] == totals["n2"] == 100


def test_preemption_toleration_window_expires_live():
    """Timed toleration e2e (preemption_toleration.go:125-175): the victim is
    exempt while its toleration window runs, and the SAME pending preemptor
    succeeds once the window expires — no operator action in between."""
    import time as _time
    from tpusched.testing import wait_until
    with TestCluster(profile=pt_profile()) as c:
        c.api.create(srv.PRIORITY_CLASSES,
                     make_pc("short-fuse", 100, minimum=10000, toleration=2))
        node = make_tpu_node("h0", chips=4)
        c.add_nodes([node])
        victim = make_pod("victim", limits={TPU: 4}, priority=100,
                          priority_class_name="short-fuse")
        c.create_pods([victim])
        assert c.wait_for_pods_scheduled([victim.key])
        bound_at = _time.time()
        preemptor = make_pod("preemptor", limits={TPU: 4}, priority=500)
        c.create_pods([preemptor])
        # inside the window: the preemptor must NOT displace the victim
        assert c.wait_for_pods_unscheduled([preemptor.key], hold=1.0)
        assert c.pod(victim.key) is not None
        # after expiry, a cluster event requeues the pending preemptor (the
        # unschedulable-queue periodic flush is 30s; real clusters see a
        # constant event stream — emulate one poke)
        while _time.time() < bound_at + 2.2:
            _time.sleep(0.05)
        c.api.patch(srv.NODES, node.meta.key, lambda n: None)  # update event
        assert c.wait_for_pods_scheduled([preemptor.key], timeout=15)
        assert wait_until(lambda: c.pod(victim.key) is None, timeout=5)
