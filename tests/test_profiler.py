"""tpusched/obs/profiler.py + throughput telemetry — ISSUE 7 acceptance.

Covers: the profiler's bounded aggregation under a 10k-cycle soak with
concurrent scrapes (entry + byte budgets hold), the e2e attribution
contract (/debug/profile's collapsed stacks name a synthetic hot plugin as
the top plugin-attributed cost, asserted over HTTP against a live
scheduler), the /debug/flightrecorder health ride-along, and the
throughput counters/gauges (binds, cycles, arrival rate, bind-pool
backlog) including their shadow-isolation (publish=False is inert).
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from tpusched import obs
from tpusched.obs.profiler import HotPathProfiler
from tpusched.util import tracectx


@pytest.fixture(autouse=True)
def _fresh_profiler():
    prev = obs.set_profiling_enabled(True)
    prof = obs.install_profiler(HotPathProfiler(interval_s=0.002))
    yield prof
    obs.set_profiling_enabled(prev)
    obs.install_profiler(HotPathProfiler())


# -- bounded aggregation -------------------------------------------------------


def test_profiler_bounds_hold_under_soak_with_concurrent_scrapes():
    """10k work cycles of deliberately diverse stacks across several
    sampled threads, with a scraper hammering every read surface the whole
    time: the hot-path table must stay inside its entry+byte budgets
    (overflow is counted, never stored) and no read may error."""
    prof = HotPathProfiler(interval_s=0.001, max_stacks=8,
                           max_bytes=4_096)
    prof.ensure_started()
    stop = threading.Event()

    def vary(depth: int) -> None:
        if depth <= 0:
            time.sleep(0)          # yield so samples land at varied depth
            return
        vary(depth - 1)

    def worker(wid: int) -> None:
        for i in range(10_000):
            vary(i % 23)
            if stop.is_set():
                return

    workers = [threading.Thread(target=worker, args=(i,),
                                name=f"tpusched-soakwork-{i}", daemon=True)
               for i in range(3)]
    for t in workers:
        t.start()
    errors: list = []

    def scraper() -> None:
        try:
            while any(t.is_alive() for t in workers):
                prof.collapsed()
                prof.top_attribution(5)
                prof.stats()
                prof.health()
                time.sleep(0.002)   # scrape-rate, not busy-spin: a reader
                # pegging the profiler lock would starve the 2-core box
        except Exception as e:  # noqa: BLE001 — the assertion is "no read
            errors.append(e)    # ever raises"; the error itself is the fact
    s = threading.Thread(target=scraper, name="tpusched-test-scraper",
                         daemon=True)
    s.start()
    for t in workers:
        t.join(timeout=60)
        assert not t.is_alive()
    stop.set()
    s.join(timeout=10)
    prof.stop()
    assert errors == []
    st = prof.stats()
    assert st["samples"] > 0, "sampler never sampled the workers"
    assert st["stacks"] <= 8
    assert st["approx_bytes"] <= 4_096
    # diverse recursion depths overflow a 64-entry table: the budget held
    # BECAUSE overflow was dropped-and-counted, and that must be visible
    assert st["dropped_stacks"] > 0
    # collapsed output is well-formed flamegraph-collapsed text
    for line in prof.collapsed().splitlines():
        stack, _, n = line.rpartition(" ")
        assert stack and n.isdigit(), line


def test_capture_window_is_fresh_and_bounded():
    prof = HotPathProfiler(interval_s=0.001, max_stacks=32,
                           max_bytes=8_192)
    prof.ensure_started()
    stop = threading.Event()

    def spin() -> None:
        while not stop.is_set():
            time.sleep(0.0005)
    t = threading.Thread(target=spin, name="tpusched-capturework",
                         daemon=True)
    t.start()
    try:
        time.sleep(0.05)                     # rolling aggregate fills
        agg = prof.capture(0.2)
        assert agg.samples > 0
        assert agg.stats()["window_s"] < 1.0     # fresh window, not the
        assert agg.stats()["stacks"] <= 32       # rolling one
    finally:
        stop.set()
        t.join(timeout=5)
        prof.stop()


def test_kill_switch_parks_sampler():
    prof = HotPathProfiler(interval_s=0.001)
    assert prof.ensure_started()
    time.sleep(0.03)
    obs.set_profiling_enabled(False)
    time.sleep(0.02)
    before = prof.stats()["samples"]
    time.sleep(0.05)
    assert prof.stats()["samples"] == before   # parked, thread alive
    obs.set_profiling_enabled(True)
    prof.stop()
    assert not prof.running


# -- attribution context -------------------------------------------------------


def test_attribution_readable_cross_thread():
    seen = {}
    ready = threading.Event()
    release = threading.Event()

    def work() -> None:
        tracectx.set_point("Filter")
        tracectx.set_plugin("FakePlugin")
        seen["ident"] = threading.get_ident()
        ready.set()
        release.wait(5)
        tracectx.set_plugin("")
        tracectx.set_point("")
    t = threading.Thread(target=work, name="tpusched-attr", daemon=True)
    t.start()
    assert ready.wait(5)
    assert tracectx.attribution(seen["ident"]) == ("Filter", "FakePlugin",
                                                   "")
    release.set()
    t.join(timeout=5)
    assert tracectx.attribution(seen["ident"]) == ("", "", "")
    tracectx.prune_attributions(set())
    assert tracectx.attribution(seen["ident"]) == ("", "", "")


def test_prune_race_reregisters_live_thread():
    """The prune races threads that started after the sampler's frames
    snapshot: a pruned-but-LIVE thread must re-register at its next
    attribution write, or its samples stay unattributed forever."""
    me = threading.get_ident()
    tracectx.set_point("Score")
    tracectx.prune_attributions(set())       # sweep saw no threads
    assert tracectx.attribution(me) == ("", "", "")
    tracectx.set_plugin("Late")              # next write re-registers
    assert tracectx.attribution(me) == ("Score", "Late", "")
    tracectx.set_plugin("")
    tracectx.set_point("")


def test_capture_over_cap_is_explicit_not_silent():
    """Past the concurrent-capture cap, capture() must refuse (None) —
    silently substituting the since-start rolling aggregate would look
    exactly like a fresh window. Attribution-row overflow is likewise
    counted, like stack overflow."""
    from tpusched.obs import profiler as prof_mod

    prof = HotPathProfiler(interval_s=0.005)
    with prof._mu:
        prof._captures = [object()] * prof_mod._MAX_CAPTURES
    assert prof.capture(0.01) is None

    agg = prof_mod._Aggregate(max_stacks=4, max_bytes=1 << 16)
    for i in range(prof_mod._MAX_ATTR_ROWS + 5):
        agg.feed("t", (f"P{i}", "", ""), ("f",))
    assert agg.stats()["dropped_attr_rows"] == 5


def test_sampler_survives_sweep_errors():
    """An always-on sampler must outlive one bad sweep — losing the
    thread would silently end profiling for the life of the process."""
    prof = HotPathProfiler(interval_s=0.002)
    prof.ensure_started()
    spin = threading.Event()

    def work():
        while not spin.is_set():
            time.sleep(0.001)
    t = threading.Thread(target=work, name="tpusched-survivor",
                         daemon=True)
    t.start()
    try:
        with prof._mu:
            prof._captures.append(object())   # .feed will raise in-sweep
        time.sleep(0.05)
        with prof._mu:
            prof._captures.clear()
        assert prof.stats()["sweep_errors"] > 0
        before = prof.stats()["samples"]
        time.sleep(0.05)
        assert prof.running
        assert prof.stats()["samples"] > before   # sampling resumed
    finally:
        spin.set()
        t.join(timeout=5)
        prof.stop()


# -- e2e: a synthetic hot plugin is attributed at /debug/profile --------------


# Longer than sys.getswitchinterval() (5 ms default) ON PURPOSE: a Python
# sampler can only preempt a pure-Python burst via the forced GIL handoff,
# which needs the burst to outlive the switch interval — shorter bursts are
# only sampled at voluntary release points (the profiler docstring
# documents this bias). 20 ms guarantees mid-burst samples.
SPIN_S = 0.02


def _hot_cluster():
    """A live cluster whose PreFilter burns a deterministic ~20 ms per
    cycle in a synthetic plugin — the hot spot /debug/profile must name."""
    from tpusched.api.resources import make_resources
    from tpusched.fwk import PluginProfile, Status
    from tpusched.fwk.interfaces import PreFilterPlugin
    from tpusched.plugins import default_registry
    from tpusched.testing import TestCluster, make_node

    class HotSpin(PreFilterPlugin):
        NAME = "HotSpinPlugin"

        def __init__(self, args, handle):
            pass

        @classmethod
        def new(cls, args, handle):
            return cls(args, handle)

        def pre_filter(self, state, pod):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < SPIN_S:
                pass
            return Status.success()

    registry = default_registry()
    registry.register(HotSpin.NAME, HotSpin.new)
    profile = PluginProfile(
        queue_sort="PrioritySort",
        pre_filter=[HotSpin.NAME],
        filter=["NodeUnschedulable", "NodeName", "NodeSelector",
                "TaintToleration", "NodeResourcesFit"],
        bind=["DefaultBinder"],
        # identical singleton pods share one equivalence class — with the
        # cache on, PreFilter (the synthetic hot spot) runs only on cache
        # misses and the workload goes quiet; this test is about
        # attribution, so keep the plugin body on every cycle
        equiv_cache=False)
    c = TestCluster(profile=profile, registry=registry)
    c.add_nodes([make_node(f"n{i}", capacity=make_resources(
        cpu=256, memory="1024Gi")) for i in range(4)])
    return c


def _get(url: str) -> tuple:
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, resp.read().decode()


def test_debug_profile_attributes_hot_plugin_e2e():
    from tpusched.api.resources import make_resources
    from tpusched.testing import make_pod
    from tpusched.util.httpserve import MetricsServer

    srv = MetricsServer(port=0).start()
    try:
        with _hot_cluster() as c:
            stop = threading.Event()

            def feeder() -> None:
                i = 0
                while not stop.is_set() and i < 600:
                    c.create_pods([make_pod(
                        f"hot-{i:04d}",
                        requests=make_resources(cpu=1, memory="1Gi"))])
                    i += 1
                    time.sleep(0.002)
            f = threading.Thread(target=feeder, name="tpusched-feeder",
                                 daemon=True)
            f.start()
            try:
                code, body = _get(f"http://127.0.0.1:{srv.port}"
                                  "/debug/profile?seconds=1.2")
            finally:
                stop.set()
                f.join(timeout=10)
            assert code == 200
            lines = body.splitlines()
            assert lines, "empty capture despite a busy scheduler"
            # collapsed-stack well-formedness
            by_plugin: dict = {}
            for line in lines:
                stack, _, n = line.rpartition(" ")
                assert stack and n.isdigit(), line
                segs = stack.split(";")
                for s in segs:
                    if s.startswith("plugin:"):
                        by_plugin[s[7:]] = by_plugin.get(s[7:], 0) + int(n)
            # the synthetic hot spot is THE top plugin-attributed cost
            assert by_plugin, f"no plugin-attributed samples in:\n{body}"
            top = max(by_plugin, key=by_plugin.get)
            assert top == "HotSpinPlugin", by_plugin
            # and its hottest stacks carry the extension point + the
            # plugin's own frame
            hot = [l for l in lines if "plugin:HotSpinPlugin" in l]
            assert any("point:PreFilter" in l for l in hot)
            assert any("pre_filter" in l for l in hot)

            # JSON form: the top attribution table names it too
            code, body = _get(f"http://127.0.0.1:{srv.port}"
                              "/debug/profile?format=json")
            assert code == 200
            doc = json.loads(body)
            assert {"collapsed", "top", "stats"} <= set(doc)
            assert any(r["plugin"] == "HotSpinPlugin" for r in doc["top"])

            # /debug/flightrecorder rides the top-N table along in health
            code, body = _get(f"http://127.0.0.1:{srv.port}"
                              "/debug/flightrecorder")
            assert code == 200
            health = json.loads(body)["health"]
            assert "profiler" in health
            assert health["profiler"]["samples"] > 0
            assert isinstance(health["profiler"]["top"], list)
    finally:
        srv.stop()


# -- throughput telemetry ------------------------------------------------------


def test_throughput_counters_and_gauges_feed_from_live_scheduler():
    from tpusched.api.resources import make_resources
    from tpusched.testing import make_pod
    from tpusched.util.metrics import (REGISTRY, binds_total,
                                       scheduling_cycles_total)

    binds0 = binds_total.value()
    cycles0 = scheduling_cycles_total.value()
    with _hot_cluster() as c:
        pods = [make_pod(f"tp-{i}", requests=make_resources(
            cpu=1, memory="1Gi")) for i in range(8)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
        assert binds_total.value() - binds0 >= 8
        assert scheduling_cycles_total.value() - cycles0 >= 8
        assert c.scheduler._throughput.arrival_rate() > 0
        text = REGISTRY.expose()
        assert "tpusched_pod_arrivals_per_second" in text
        assert "tpusched_bind_pool_backlog" in text
        assert "tpusched_binds_total" in text


def test_throughput_shadow_shell_is_inert():
    from tpusched.obs.throughput import ThroughputTelemetry
    from tpusched.util.metrics import binds_total, scheduling_cycles_total

    binds0 = binds_total.value()
    cycles0 = scheduling_cycles_total.value()
    tp = ThroughputTelemetry("shadow-prof", publish=False)
    for _ in range(50):
        tp.on_arrival()
        tp.on_cycle()
        tp.on_bind()
    tp.register_bind_backlog(lambda: 5)
    assert binds_total.value() == binds0
    assert scheduling_cycles_total.value() == cycles0
    assert tp.arrival_rate() == 0.0
    from tpusched.util.metrics import REGISTRY
    assert 'scheduler="shadow-prof"' not in REGISTRY.expose()


def test_arrival_rate_window_math():
    from tpusched.obs.throughput import ThroughputTelemetry

    now = [100.0]
    tp = ThroughputTelemetry("rate-math", publish=True,
                             clock=lambda: now[0], window_s=10.0)
    for i in range(20):
        now[0] = 100.0 + i * 0.1      # 20 arrivals over 1.9s ≈ 10.5/s
        tp.on_arrival()
    now[0] = 102.0
    assert 9.0 < tp.arrival_rate() < 12.0
    now[0] = 200.0                    # window empty again
    assert tp.arrival_rate() == 0.0
