"""Diagnosis-engine memory bounds: the 10k-cycle soak.

Mirror of the PR 2 flight-recorder ring soak: an always-on control plane
must hold its memory ceiling through ANY workload — thousands of distinct
pods churning through failure/resolution, per-pod reason-row growth, gang
index growth.  Asserts the entry and byte budgets hold at every step (not
just at the end), that resolved pods are evicted immediately, and that
the LRU keeps the MOST RECENT pods when over budget.
"""
from __future__ import annotations

import threading

from tpusched.obs import DiagnosisEngine
from tpusched.obs.diagnosis import MAX_ROWS_PER_POD


def test_diagnosis_engine_10k_cycle_soak_stays_bounded():
    eng = DiagnosisEngine(max_pods=256, max_bytes=128 * 1024)
    peak_pods = peak_bytes = 0
    for i in range(10_000):
        pod = f"default/p-{i % 3000:04d}"
        gang = f"default/g-{i % 211:03d}" if i % 3 else None
        eng.on_attempt(
            pod, gang, "unschedulable", "TpuSlice",
            f"0/{64 + i % 5} nodes are available: insufficient resource "
            f"google.com/tpu",
            [{"plugin": "TpuSlice",
              "reason": "insufficient resource google.com/tpu",
              "nodes": 1 + i % 64},
             {"plugin": "NodeResourcesFit",
              "reason": f"Insufficient cpu ({i % 9} tried)",
              "nodes": i % 8}],
            attempt=i % 7)
        if i % 5 == 0:
            eng.on_resolved(f"default/p-{(i * 7) % 3000:04d}")
        if i % 97 == 0:
            s = eng.stats()
            peak_pods = max(peak_pods, s["pods"])
            peak_bytes = max(peak_bytes, s["approx_bytes"])
            assert s["pods"] <= 256, i
            assert s["approx_bytes"] <= 128 * 1024, i
    s = eng.stats()
    assert s["pods"] <= 256 and s["approx_bytes"] <= 128 * 1024
    assert s["fed_total"] == 10_000
    assert s["evicted_total"] > 0              # the soak DID hit the cap
    # the table actually filled toward its budgets (the byte cap is the
    # binding constraint for this workload's row sizes)
    assert peak_pods >= 200 and peak_bytes >= 100 * 1024
    # internal consistency after the churn: blocker counts sum to pods
    assert sum(b["pods"] for b in eng.top_blockers(100)) == s["pods"]
    # LRU: the very last pod fed (i=9999 → p-0999) must have survived
    assert eng.explain_pod("default/p-0999") is not None


def test_resolved_pods_evict_immediately_and_gang_index_follows():
    eng = DiagnosisEngine()
    for i in range(4):
        eng.on_attempt(f"default/m-{i}", "default/g", "unschedulable",
                       "Coscheduling", "not enough siblings", None)
    assert eng.explain_gang("default/g")["members_pending"] == 4
    for i in range(4):
        eng.on_resolved(f"default/m-{i}")
    assert eng.explain_pod("default/m-0") is None
    assert eng.explain_gang("default/g") is None      # index cleaned up
    assert eng.stats()["gangs"] == 0
    assert eng.top_blockers() == []                   # rollup decremented


def test_per_pod_reason_rows_bounded():
    eng = DiagnosisEngine()
    for i in range(100):
        eng.on_attempt("default/noisy", None, "unschedulable",
                       f"Plugin{i}", f"distinct reason {i} with text", None)
    out = eng.explain_pod("default/noisy")
    assert len(out["reasons"]) <= MAX_ROWS_PER_POD
    # the headline verdict keeps updating even when rows are saturated
    assert out["blocking_plugin"] == "Plugin99"
    assert out["attempts"] == 100


def test_repeat_attempts_aggregate_not_duplicate():
    eng = DiagnosisEngine()
    for attempt in range(5):
        eng.on_attempt(
            "default/p", "default/g", "unschedulable", "CapacityScheduling",
            f"Pod default/p is rejected in PreFilter because ElasticQuota "
            f"research is more than Max (attempt {attempt})",
            [{"plugin": "CapacityScheduling",
              "reason": "quota used would exceed Max", "nodes": 48}])
    out = eng.explain_pod("default/p")
    # per-attempt variance (the attempt counter) collapsed to ONE row
    quota_rows = [r for r in out["reasons"]
                  if r["plugin"] == "CapacityScheduling"]
    assert len(quota_rows) == 2                # headline + diagnosis row
    assert all(r["cycles"] == 5 for r in quota_rows)
    assert any(r["nodes"] == 48 for r in quota_rows)
    assert "quota" in out["suggestion"]


def test_concurrent_feed_and_read():
    """Binding-pool threads feed failures while /debug/explain reads —
    no torn state, bounds hold."""
    eng = DiagnosisEngine(max_pods=64, max_bytes=64 * 1024)
    stop = threading.Event()
    errors = []

    def feeder(tid: int):
        try:
            for i in range(2000):
                eng.on_attempt(f"default/t{tid}-{i % 100}",
                               f"default/g{tid}", "unschedulable",
                               "TpuSlice", "insufficient resource", None)
                if i % 3 == 0:
                    eng.on_resolved(f"default/t{tid}-{(i + 1) % 100}")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                eng.top_blockers()
                eng.explain_gang("default/g0")
                eng.dump()
        except Exception as e:  # noqa: BLE001
            errors.append(e)
    threads = [threading.Thread(target=feeder, args=(t,)) for t in range(3)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert not errors
    s = eng.stats()
    assert s["pods"] <= 64 and s["approx_bytes"] <= 64 * 1024
