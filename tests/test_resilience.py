"""API-failure resilience units: the client retry layer, token-bucket
deadlines, best-effort events, binding-pool shutdown semantics, degraded
mode, and the gang-atomic bind rollback — each against the seeded fault
injector (apiserver/faults.py). The multi-thousand-cycle composition of all
of these is tests/test_chaos_soak.py.
"""
import threading
import time

import pytest

from tpusched import trace
from tpusched.api.core import Binding
from tpusched.api.resources import make_resources
from tpusched.apiserver import (APIServer, Clientset, Conflict, FaultInjector,
                                FaultRule, NotFound, RetryPolicy, Throttled,
                                Unavailable)
from tpusched.apiserver import server as srv
from tpusched.apiserver.client import _TokenBucket
from tpusched.apiserver.errors import is_retriable
from tpusched.config.types import CoschedulingArgs
from tpusched.fwk import PluginProfile
from tpusched.sched.scheduler import _BindingPool, _DegradedMode
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, wait_until)
from tpusched.util.metrics import (api_retries, api_retry_exhausted,
                                   events_dropped, gang_bind_rollbacks)

FAST_RETRY = RetryPolicy(max_attempts=3, initial_backoff_s=0.005,
                         max_backoff_s=0.02, deadline_s=2.0)


# -- taxonomy -----------------------------------------------------------------

def test_taxonomy_classification():
    assert is_retriable("get", Unavailable("x"))
    assert is_retriable("bind", Unavailable("x"))
    assert not is_retriable("get", Throttled("x"))
    assert not is_retriable("get", NotFound("x"))
    # Conflict: only the server-side-RMW patch retries; a bind Conflict is
    # terminal (the lost-response case is resolved by the heal hook BEFORE
    # classification — see _PodClient.bind)
    assert is_retriable("patch", Conflict("x"))
    assert not is_retriable("bind", Conflict("x"))
    assert not is_retriable("update", Conflict("x"))
    assert not is_retriable("create", Conflict("x"))


# -- retry layer --------------------------------------------------------------

def test_transient_fault_is_retried_to_success():
    api = APIServer()
    inj = FaultInjector(api, seed=3)
    cs = Clientset(inj, retry=FAST_RETRY)
    inj.add_rule(FaultRule(verbs=("create",), error="unavailable",
                           max_injections=2))
    before = api_retries.value()
    out = cs.pods.create(make_pod("r1"))
    assert out.meta.name == "r1"
    assert api_retries.value() - before == 2
    assert api.get(srv.PODS, "default/r1") is not None


def test_retry_exhaustion_is_terminal_and_counted():
    api = APIServer()
    inj = FaultInjector(api, seed=3)
    exhausted = []
    cs = Clientset(inj, retry=FAST_RETRY,
                   on_retry_exhausted=lambda v, k, e: exhausted.append((v, k)))
    inj.add_rule(FaultRule(verbs=("get",), error="unavailable"))
    before = api_retry_exhausted.value()
    with pytest.raises(Unavailable):
        cs.pods.get("default/nope")
    assert api_retry_exhausted.value() - before == 1
    assert exhausted == [("get", srv.PODS)]


def test_terminal_errors_do_not_burn_retries():
    api = APIServer()
    cs = Clientset(api, retry=FAST_RETRY)
    before = api_retries.value()
    with pytest.raises(NotFound):
        cs.pods.get("default/absent")
    with pytest.raises(Conflict):
        api.create(srv.PODS, make_pod("dup"))
        cs.pods.create(make_pod("dup"))
    assert api_retries.value() == before


def test_patch_conflict_is_retried_via_server_side_reread():
    api = APIServer()
    inj = FaultInjector(api, seed=3)
    cs = Clientset(inj, retry=FAST_RETRY)
    api.create(srv.PODS, make_pod("p1"))
    inj.add_rule(FaultRule(verbs=("patch",), error="conflict",
                           max_injections=2))
    cs.pods.patch("default/p1",
                  lambda p: p.meta.labels.__setitem__("touched", "yes"))
    assert api.get(srv.PODS, "default/p1").meta.labels["touched"] == "yes"


def test_bind_lost_response_heals_on_retry():
    """The bind applied but the response was lost: the retry Conflicts and
    the client heals by re-reading — bound to OUR node is success."""
    api = APIServer()
    inj = FaultInjector(api, seed=3)
    cs = Clientset(inj, retry=FAST_RETRY)
    api.create(srv.NODES, make_node("n1"))
    api.create(srv.PODS, make_pod("b1"))
    inj.add_rule(FaultRule(verbs=("bind",), error="unavailable", after=True,
                           max_injections=1))
    cs.pods.bind(Binding(pod_key="default/b1", node_name="n1", annotations={}))
    assert api.get(srv.PODS, "default/b1").spec.node_name == "n1"


def test_bind_genuine_conflict_stays_terminal():
    """A real double-bind fails FAST: no retry sleeps burned, and no
    spurious retry-exhaustion fed into the degraded-mode trip counter (a
    semantic conflict is not an apiserver outage)."""
    api = APIServer()
    cs = Clientset(api, retry=FAST_RETRY)
    api.create(srv.NODES, make_node("other"))
    api.create(srv.NODES, make_node("mine"))
    api.create(srv.PODS, make_pod("b2"))
    api.bind(Binding(pod_key="default/b2", node_name="other", annotations={}))
    retries_before = api_retries.value()
    exhausted_before = api_retry_exhausted.value()
    with pytest.raises(Conflict):
        cs.pods.bind(Binding(pod_key="default/b2", node_name="mine",
                             annotations={}))
    assert api.get(srv.PODS, "default/b2").spec.node_name == "other"
    assert api_retries.value() == retries_before
    assert api_retry_exhausted.value() == exhausted_before


def test_retries_annotate_active_trace():
    api = APIServer()
    inj = FaultInjector(api, seed=3)
    cs = Clientset(inj, retry=FAST_RETRY)
    inj.add_rule(FaultRule(verbs=("create",), error="unavailable",
                           max_injections=1))
    tr = trace.CycleTrace("t1", "default/tp", "u1", None, 1, "s", 0.0, 0.0,
                          0.0)
    token = trace.activate(tr)
    try:
        cs.pods.create(make_pod("tp"))
    finally:
        trace.deactivate(token)
    names = [e[0] for e in tr._events]
    assert "api-retry" in names


# -- token bucket deadlines (satellite: no unbounded sleep) -------------------

def test_token_bucket_deadline_raises_throttled():
    b = _TokenBucket(qps=0.5, burst=1)
    b.wait()                                 # burns the burst token
    t0 = time.monotonic()
    with pytest.raises(Throttled):
        b.wait(deadline=time.monotonic() + 0.05)
    assert time.monotonic() - t0 < 0.5       # no 2s sleep toward the token


def test_token_bucket_no_deadline_still_waits():
    b = _TokenBucket(qps=100.0, burst=1)
    b.wait()
    t0 = time.monotonic()
    b.wait()                                 # ~10ms mint time
    assert 0.003 <= time.monotonic() - t0 < 1.0


def test_clientset_surfaces_throttled_terminally():
    api = APIServer()
    cs = Clientset(api, qps=0.2, burst=1,
                   retry=RetryPolicy(max_attempts=3, initial_backoff_s=0.005,
                                     max_backoff_s=0.01, deadline_s=0.1))
    api.create(srv.PODS, make_pod("q1"))     # raw create: no throttle burn
    cs.pods.get("default/q1")                # burns the burst token
    before = api_retries.value()
    t0 = time.monotonic()
    with pytest.raises(Throttled):
        cs.pods.get("default/q1")
    assert time.monotonic() - t0 < 1.0
    assert api_retries.value() == before     # Throttled is never retried


# (token-bucket hypothesis property tests live in
# tests/test_token_bucket_properties.py — a module-level importorskip must
# not skip THIS module's deterministic coverage when hypothesis is absent)


# -- best-effort events (satellite) -------------------------------------------

def test_record_event_never_raises_and_counts_drops():
    api = APIServer()
    inj = FaultInjector(api, seed=3)
    cs = Clientset(inj, retry=FAST_RETRY)
    inj.add_rule(FaultRule(verbs=("record_event",), error="unavailable"))
    before = events_dropped.value()
    cs.record_event("default/x", "Pod", "Warning", "FailedScheduling", "m")
    assert events_dropped.value() - before == 1
    assert api.events() == []
    inj.clear()
    cs.record_event("default/x", "Pod", "Normal", "Scheduled", "ok")
    assert len(api.events()) == 1


# -- binding pool shutdown (satellite) ----------------------------------------

def test_binding_pool_shutdown_aborts_queued_tasks_with_wedged_worker():
    """One wedged task must not extend shutdown past its timeout, queued
    tasks must drain through their ABORT path (reservations released), and
    no queued task's full body may run after shutdown returns."""
    pool = _BindingPool(workers=1)
    wedge = threading.Event()
    started = threading.Event()
    ran, aborted = [], []

    pool.submit(lambda: (started.set(), wedge.wait(10)), lambda: None)
    assert started.wait(2.0)
    for i in range(3):
        pool.submit(lambda i=i: ran.append(i), lambda i=i: aborted.append(i))

    t0 = time.monotonic()
    pool.shutdown(timeout=0.3)
    assert time.monotonic() - t0 < 2.0       # bounded by drain timeout
    assert sorted(aborted) == [0, 1, 2]
    assert ran == []
    with pytest.raises(RuntimeError):
        pool.submit(lambda: None, None)
    wedge.set()                              # release the wedged daemon
    time.sleep(0.1)
    assert ran == []                         # drained queue: nothing to run


def test_binding_pool_abort_fallback_used_after_shutdown_on_permit_resolve():
    """Satellite: a permit resolving AFTER the bind pool shut down must run
    the cheap abort path (unreserve + forget) on the signaling thread —
    never a full bind cycle. Observable as: the pod's reservation is gone,
    no FailedScheduling event was recorded, and its trace finalized as
    bind-aborted."""
    prev = trace.default_recorder()
    rec = trace.install_recorder(trace.FlightRecorder())
    profile = PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"],
        filter=["NodeResourcesFit"],
        permit=["Coscheduling"],
        bind=["DefaultBinder"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=30)},
    )
    c = TestCluster(profile=profile)
    try:
        c.scheduler.run()
        c.api.create(srv.NODES, make_node("n1"))
        # m0 parks at the permit barrier: its sibling exists (PreFilter's
        # sibling count passes) but can never fit, so quorum never forms
        # (no PostFilter in this profile ⇒ no optimistic gang rejection)
        c.api.create(srv.POD_GROUPS, make_pod_group("half", min_member=2))
        pod = make_pod("half-m0", requests=make_resources(cpu=1),
                       pod_group="half")
        c.api.create(srv.PODS, pod)
        c.api.create(srv.PODS, make_pod(
            "half-m1", requests=make_resources(cpu=10_000),
            pod_group="half"))
        sched = c.scheduler
        assert wait_until(
            lambda: sched._fw.get_waiting_pod(pod.meta.uid) is not None,
            timeout=5.0)
        assert sched.cache.is_assumed(pod.key)
        # the pool dies first (the stop() race this satellite hardens)
        sched._bind_pool.shutdown(timeout=1.0)
        events_before = len(c.api.events())
        sched._fw.reject_waiting_pod(pod.meta.uid, "Test", "forced rejection")
        assert wait_until(lambda: not sched.cache.is_assumed(pod.key),
                          timeout=2.0)
        outcomes = {t.outcome for t in rec.traces() if t.pod_key == pod.key}
        assert "bind-aborted" in outcomes
        # no failure-path side effects ran inline (no requeue event)
        assert len(c.api.events()) == events_before
    finally:
        c.stop()
        trace.install_recorder(prev)


# -- degraded mode ------------------------------------------------------------

def test_degraded_mode_trips_recovers_and_publishes():
    published = []
    dm = _DegradedMode(threshold=2, initial_pause_s=0.1, max_pause_s=0.4,
                       publish=lambda comp, st: published.append((comp, st)))
    dm.on_retry_exhausted("bind", "pods", Unavailable("x"))
    assert not dm.active()
    dm.on_retry_exhausted("bind", "pods", Unavailable("x"))
    assert dm.active()
    assert published and published[-1][0] == "degraded_mode"
    assert published[-1][1]["active"] is True
    dm.on_success()
    assert not dm.active()
    assert published[-1][1]["active"] is False
    # a fresh episode starts from the initial pause again
    dm.on_retry_exhausted("get", "pods", Unavailable("x"))
    dm.on_retry_exhausted("get", "pods", Unavailable("x"))
    assert 0 < dm.pause_remaining() <= 0.1 + 1e-3


def test_degraded_mode_pause_grows_without_recovery():
    dm = _DegradedMode(threshold=1, initial_pause_s=0.02, max_pause_s=0.1)
    dm.on_retry_exhausted("get", "pods", Unavailable("x"))
    first = dm.pause_remaining()
    assert wait_until(lambda: not dm.active(), timeout=1.0)
    dm.on_retry_exhausted("get", "pods", Unavailable("x"))
    assert dm.pause_remaining() > first      # doubled window
    assert dm.snapshot()["entries_total"] == 2


def test_degraded_mode_recovery_publishes_after_window_lapse():
    """A success arriving AFTER the pause window lapsed must still publish
    the recovery — otherwise /debug/flightrecorder's health section claims
    degraded forever while the gauge reads 0."""
    published = []
    dm = _DegradedMode(threshold=1, initial_pause_s=0.02, max_pause_s=0.05,
                       publish=lambda comp, st: published.append(st))
    dm.on_retry_exhausted("bind", "pods", Unavailable("x"))
    assert published[-1]["active"] is True
    assert wait_until(lambda: not dm.active(), timeout=1.0)  # window lapses
    dm.on_success()
    assert published[-1]["active"] is False


def test_degraded_mode_half_open_probing_keeps_escalated_pause():
    """Window lapse with NO success moves to half-open: health stops
    claiming an active pause (probing published), but the pause ladder is
    NOT reset — a still-down apiserver re-trips into a longer window;
    only a real success resets it."""
    published = []
    dm = _DegradedMode(threshold=1, initial_pause_s=0.02, max_pause_s=0.2,
                       publish=lambda comp, st: published.append(st))
    dm.on_retry_exhausted("bind", "pods", Unavailable("x"))
    assert wait_until(lambda: not dm.active(), timeout=1.0)
    dm.maybe_expire()
    assert published[-1]["active"] is False
    assert published[-1]["probing"] is True
    # re-trip while half-open: the window is the ESCALATED one
    dm.on_retry_exhausted("bind", "pods", Unavailable("x"))
    assert dm.pause_remaining() > 0.02
    assert published[-1]["active"] is True
    # a success anywhere ends the episode and resets the ladder
    dm.on_success()
    assert published[-1]["active"] is False
    assert published[-1]["probing"] is False


def test_degraded_mode_disabled_with_zero_threshold():
    dm = _DegradedMode(threshold=0, initial_pause_s=0.1, max_pause_s=0.1)
    for _ in range(10):
        dm.on_retry_exhausted("get", "pods", Unavailable("x"))
    assert not dm.active()


# -- gang-atomic bind rollback (tentpole acceptance) --------------------------

def _gang_profile():
    return PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"],
        filter=["NodeResourcesFit"],
        post_filter=["Coscheduling"],
        reserve=["Coscheduling"],
        permit=["Coscheduling"],
        bind=["DefaultBinder"],
        post_bind=["Coscheduling"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=3,
            # deliberately HUGE: a rollback-driven Unreserve must NOT put
            # the gang in the denial window (it failed on an API outage,
            # not on fit) — if it did, recovery would stall far past this
            # test's wait and fail it
            denied_pg_expiration_time_seconds=120)},
        pod_initial_backoff_s=0.02, pod_max_backoff_s=0.2,
    )


def test_terminal_midgang_bind_failure_rolls_back_and_recovers():
    """Acceptance: a terminal mid-gang bind failure is fully explainable
    from /debug/flightrecorder ALONE (pinned rollback anomaly with
    per-member attribution), no partially-bound gang wedges, and the gang
    binds once the faults clear."""
    from tpusched.util.httpserve import MetricsServer
    import json
    import urllib.request

    prev = trace.default_recorder()
    trace.install_recorder(trace.FlightRecorder())
    api = APIServer()
    inj = FaultInjector(api, seed=11)
    c = TestCluster(profile=_gang_profile(), api=inj)
    server = MetricsServer(port=0).start()
    rollbacks_before = gang_bind_rollbacks.value()
    try:
        c.scheduler.run()
        for i in range(3):
            api.create(srv.NODES, make_node(f"n{i}"))
        # member m0's binds fail until the outage budget is spent: two full
        # retry-exhausted bind calls (2 × max 4 attempts), then success
        inj.add_rule(FaultRule(name="m0-outage", verbs=("bind",),
                               error="unavailable", key_substr="roll-m0",
                               max_injections=8))
        api.create(srv.POD_GROUPS, make_pod_group("roll", min_member=3))
        keys = []
        for m in range(3):
            p = make_pod(f"roll-m{m}", requests=make_resources(cpu=1),
                         pod_group="roll")
            api.create(srv.PODS, p)
            keys.append(p.key)
        # faults clear by exhaustion; the gang must fully bind
        assert c.wait_for_pods_scheduled(keys, timeout=30.0), \
            [k for k in keys if not c.pod_scheduled(k)]
        assert gang_bind_rollbacks.value() - rollbacks_before >= 1

        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/flightrecorder") as r:
            dump = json.loads(r.read())
        pinned = dump["pinned"]
        rollback_anomalies = [
            a for t in pinned for a in t.get("anomalies", [])
            if a["kind"] == "gang_bind_rollback"]
        assert rollback_anomalies, "rollback anomaly not pinned"
        trigger = [a for a in rollback_anomalies if a.get("role") == "trigger"]
        assert trigger and trigger[0]["gang"] == "default/roll"
        assert trigger[0]["trigger_pod"] == "default/roll-m0"
        # per-member attribution: the triggering trace names the pod, node
        # and the terminal bind error
        assert "injected unavailable" in trigger[0]["message"]
        # no partially-bound gang at quiescence (all three are bound)
        bound = [p for p in api.list(srv.PODS) if p.spec.node_name]
        assert len(bound) == 3
    finally:
        server.stop()
        c.stop()
        inj.clear()
        trace.install_recorder(prev)


def test_gang_rollback_skipped_for_singletons():
    """A singleton's terminal bind failure requeues only itself — no
    rollback bookkeeping, no metric bump."""
    api = APIServer()
    inj = FaultInjector(api, seed=5)
    c = TestCluster(api=inj)
    before = gang_bind_rollbacks.value()
    try:
        c.scheduler.run()
        api.create(srv.NODES, make_node("n0"))
        inj.add_rule(FaultRule(verbs=("bind",), error="unavailable",
                               max_injections=8))
        p = make_pod("solo", requests=make_resources(cpu=1))
        api.create(srv.PODS, p)
        assert c.wait_for_pods_scheduled([p.key], timeout=20.0)
        assert gang_bind_rollbacks.value() == before
    finally:
        c.stop()


# -- node-death windows (node dies before permit / pre-bind / post-bind) ------

def _set_barrier_profile():
    """Gang + multislice-set profile: the set barrier is the only permit
    state that parks indefinitely with every member pod present — the
    stable "before permit resolves" window a node death can race."""
    from tpusched.config.types import MultiSliceArgs
    return PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling", "MultiSlice"],
        filter=["NodeUnschedulable", "NodeResourcesFit", "MultiSlice"],
        post_filter=["Coscheduling", "MultiSlice"],
        reserve=["Coscheduling", "MultiSlice"],
        permit=["Coscheduling", "MultiSlice"],
        bind=["DefaultBinder"],
        post_bind=["Coscheduling"],
        plugin_args={
            "Coscheduling": CoschedulingArgs(
                permit_waiting_time_seconds=20,
                denied_pg_expiration_time_seconds=0.1),
            "MultiSlice": MultiSliceArgs(
                set_schedule_timeout_seconds=20,
                denied_set_expiration_time_seconds=0.2)},
        pod_initial_backoff_s=0.02, pod_max_backoff_s=0.2,
        stuck_gang_after_s=2.0, stuck_gang_sweep_interval_s=0.2,
    )


def test_node_dies_before_permit_resolves():
    """Window (a): the node dies while gang members sit at the permit
    barrier. Gang-atomic outcome: the parked members' reservations are
    released (none may proceed to bind on the vanished node) and the whole
    set later binds on healthy hardware only."""
    from tpusched.testing.chaos import BindTransitionMonitor

    api = APIServer()
    monitor = BindTransitionMonitor(api)
    c = TestCluster(profile=_set_barrier_profile(), api=api)
    try:
        c.scheduler.run()
        api.create(srv.NODES, make_node("doomed"))
        for idx in range(2):
            api.create(srv.POD_GROUPS, make_pod_group(
                f"w-{idx}", min_member=2, multislice_set="w",
                multislice_index=idx, multislice_set_size=2))
        for m in range(2):
            api.create(srv.PODS, make_pod(
                f"w-0-m{m}", pod_group="w-0",
                requests=make_resources(cpu=2)))
        # slice w-1 unfittable for now: w-0 parks at the set barrier
        for m in range(2):
            api.create(srv.PODS, make_pod(
                f"w-1-m{m}", pod_group="w-1",
                requests=make_resources(cpu=900)))
        assert wait_until(
            lambda: c.scheduler.cache.snapshot().assigned_count(
                "w-0", "default") == 2, timeout=10.0)

        api.delete(srv.NODES, "/doomed")        # the window slams shut
        assert wait_until(
            lambda: c.scheduler.cache.snapshot().assigned_count(
                "w-0", "default") == 0, timeout=10.0)
        # none of the parked members ever bound anywhere (gang-atomic)
        assert all(not (api.peek(srv.PODS, f"default/w-0-m{m}") or
                        make_pod("x")).spec.node_name for m in range(2))

        api.create(srv.NODES, make_node("healthy"))
        for m in range(2):
            api.delete(srv.PODS, f"default/w-1-m{m}")
            api.create(srv.PODS, make_pod(
                f"w-1r-m{m}", pod_group="w-1",
                requests=make_resources(cpu=2)))
        keys = [f"default/w-0-m{m}" for m in range(2)] + \
               [f"default/w-1r-m{m}" for m in range(2)]
        assert c.wait_for_pods_scheduled(keys, timeout=20.0), \
            [k for k in keys if not c.pod_scheduled(k)]
        assert all(c.pod(k).spec.node_name == "healthy" for k in keys)
        assert not monitor.violations, monitor.violations
    finally:
        monitor.close()
        c.stop()


def test_node_dies_between_permit_and_bind():
    """Window (b): permit resolved, binds in flight, node deleted. The
    bind's terminal NotFound (node gone) triggers PR 3's gang-atomic
    rollback registry; the whole gang re-admits and binds on the healthy
    node."""
    from tpusched.testing.chaos import BindTransitionMonitor

    api = APIServer()
    inj = FaultInjector(api, seed=13)
    monitor = BindTransitionMonitor(api)
    c = TestCluster(profile=_gang_profile(), api=inj)
    rollbacks0 = gang_bind_rollbacks.value()
    try:
        c.scheduler.run()
        # name order makes z-doomed the argmax host while it exists
        api.create(srv.NODES, make_node("a-fresh"))
        api.create(srv.NODES, make_node("z-doomed"))
        # every bind fails retriably: the gang parks IN the permit→bind
        # window (permit resolved, bind not committed)
        inj.add_rule(FaultRule(name="bind-wedge", verbs=("bind",),
                               error="unavailable"))
        api.create(srv.POD_GROUPS, make_pod_group("wb", min_member=3))
        keys = []
        for m in range(3):
            p = make_pod(f"wb-m{m}", requests=make_resources(cpu=2),
                         pod_group="wb")
            api.create(srv.PODS, p)
            keys.append(p.key)
        assert wait_until(
            lambda: inj.stats()["injections_total"] >= 2, timeout=10.0)
        api.delete(srv.NODES, "/z-doomed")      # inside the window
        inj.clear()
        # terminal NotFound (vanished node) → whole-gang rollback → the
        # gang re-admits and completes on the healthy node
        assert c.wait_for_pods_scheduled(keys, timeout=30.0), \
            [k for k in keys if not c.pod_scheduled(k)]
        assert all(c.pod(k).spec.node_name == "a-fresh" for k in keys)
        assert gang_bind_rollbacks.value() - rollbacks0 >= 1
        assert not monitor.violations, monitor.violations
    finally:
        monitor.close()
        c.stop()
        inj.clear()


def test_node_dies_after_partial_bind():
    """Window (c): part of the gang is already bound when the node dies.
    The lifecycle controller orphan-GCs the dead node's members, the gang
    repair controller evicts the survivor and recreates the gang
    (restart-gang), and the gang re-reaches fully-Bound on healthy nodes."""
    from tpusched.controllers import (GangRepairController,
                                      NodeLifecycleController)
    from tpusched.testing.chaos import BindTransitionMonitor
    from tpusched.util.metrics import gang_repairs

    api = APIServer()
    monitor = BindTransitionMonitor(api)
    c = TestCluster(profile=_gang_profile(), api=api)
    lifecycle = NodeLifecycleController(api, heartbeat_grace_s=5.0,
                                        pod_eviction_grace_s=5.0,
                                        sweep_interval_s=0.05)
    repair = GangRepairController(api, cooldown_s=0.05)
    repairs0 = gang_repairs.value()
    try:
        c.scheduler.run()
        lifecycle.run()
        repair.run()
        # z-big fits two members, a-small one: deterministic 2+1 split
        api.create(srv.NODES, make_node(
            "z-big", capacity=make_resources(cpu=17, pods=10)))
        api.create(srv.NODES, make_node(
            "a-small", capacity=make_resources(cpu=9, pods=10)))
        api.create(srv.POD_GROUPS, make_pod_group("wc", min_member=3))
        keys = []
        for m in range(3):
            p = make_pod(f"wc-m{m}", requests=make_resources(cpu=8),
                         pod_group="wc")
            api.create(srv.PODS, p)
            keys.append(p.key)
        assert c.wait_for_pods_scheduled(keys, timeout=20.0)
        split = {c.pod(k).spec.node_name for k in keys}
        assert split == {"z-big", "a-small"}

        # replacement capacity, then the kill: two bound members orphaned
        api.create(srv.NODES, make_node(
            "m-replacement", capacity=make_resources(cpu=17, pods=10)))
        api.delete(srv.NODES, "/z-big")

        # orphan GC + restart-gang repair: every member re-reaches Bound on
        # nodes that exist (gang-atomic — the survivor restarted too)
        def settled():
            for k in keys:
                p = api.peek(srv.PODS, k)
                if p is None or not p.spec.node_name:
                    return False
                if api.peek(srv.NODES, "/" + p.spec.node_name) is None:
                    return False
            return True
        assert wait_until(settled, timeout=30.0), \
            {k: getattr(api.peek(srv.PODS, k), "spec", None) and
             api.peek(srv.PODS, k).spec.node_name for k in keys}
        assert gang_repairs.value() - repairs0 >= 1
        assert all(c.pod(k).spec.node_name in ("a-small", "m-replacement")
                   for k in keys)
        assert not monitor.violations, monitor.violations
    finally:
        monitor.close()
        for ctrl in (lifecycle, repair):
            ctrl.stop()
        c.stop()
