"""Composed chaos soak (VERDICT r4 #8): ONE seeded stream interleaving
every disruptive subsystem — priority bursts that drive window slice
preemption and quota reclaim, consent-gated defrag actuation, and active
SIGKILL → standby HA takeover over a shared WAL — with the safety
invariants asserted continuously across ≥1000 scheduling cycles:

  S1  no host oversubscribed, chip-index annotations disjoint (always);
  S2  no double-bind: a pod (by uid) never changes hosts — across defrag
      (which must delete+resubmit, never rebind), preemption, and WAL
      replay on takeover;
  S3  no stranded sub-quorum gang at quiesce (all-or-nothing, healing
      window allowed — the upstream per-pod permit race);
  S4  bin-pack: every bound slice gang sits in exactly one pool with
      coordinates;
  S5  WAL replay converges: a cold replay of the final state dir
      reproduces the live assignments exactly.

Individually these are pinned by test_soak_random / test_chaos_restart /
test_defrag_controller; this soak is the cross-product — the regressions
that only appear when a takeover lands mid-preemption or defrag races a
burst. Failures reproduce from the printed seed."""
from __future__ import annotations

import random
import shutil
import tempfile
import time

import pytest

from tpusched.api.resources import TPU
from tpusched.api.scheduling import POD_GROUP_LABEL
from tpusched.apiserver import persistence
from tpusched.apiserver import server as srv
from tpusched.config.profiles import full_stack_profile
from tpusched.controllers.defrag import (ALLOW_MIGRATION_ANNOTATION,
                                         DefragController)
from tpusched.plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from tpusched.plugins.tpuslice import CHIP_INDEX_ANNOTATION
from tpusched.sched.ha import HAScheduler
from tpusched.testing import (make_elastic_quota, make_pod, make_pod_group,
                              make_tpu_pool, wait_until)
from tpusched.util.metrics import schedule_attempts

SEED = 20260731          # module default; the test parametrizes over two
ROUNDS = 10
MIN_CYCLES = 1000
CHIPS_PER_HOST = 4


def _active_of(replicas, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for r in replicas:
            if r.is_active.is_set():
                return r
        time.sleep(0.02)
    raise AssertionError(f"no replica became active (seed {SEED})")


def _bound_pods(api):
    return [p for p in api.list(srv.PODS) if p.spec.node_name]


def _check_hard(api, assignments):
    """S1 + S2 + S4 — must hold at every instant."""
    by_node = {}
    for p in _bound_pods(api):
        by_node.setdefault(p.spec.node_name, []).append(p)
        prev = assignments.get(p.meta.uid)
        assert prev is None or prev == p.spec.node_name, (
            f"S2: pod {p.meta.key} (uid {p.meta.uid}) moved "
            f"{prev} -> {p.spec.node_name} (seed {SEED})")
        assignments[p.meta.uid] = p.spec.node_name
    for node, pods in by_node.items():
        used = sum(int(pp.spec.containers[0].limits.get(TPU, 0))
                   for pp in pods)
        assert used <= CHIPS_PER_HOST, (
            f"S1: {node} oversubscribed: {used} chips (seed {SEED})")
        idx = []
        for pp in pods:
            ann = pp.meta.annotations.get(CHIP_INDEX_ANNOTATION, "")
            idx.extend(i for i in ann.split(",") if i)
        assert len(idx) == len(set(idx)), (
            f"S1: {node} chip indexes collide: {idx} (seed {SEED})")


def _gang_violation(api, gangs):
    """S3 + S4 (eventual: healing window applies)."""
    for full, (members, shape) in gangs.items():
        ns, name = full.split("/")
        bound = [p for p in api.list(srv.PODS, ns)
                 if p.meta.labels.get(POD_GROUP_LABEL) == name
                 and p.spec.node_name]
        if not (len(bound) == 0 or len(bound) >= members):
            return f"S3: {full}: {len(bound)}/{members} bound"
        if shape and bound:
            pools = {p.meta.annotations.get(POOL_ANNOTATION) for p in bound}
            if len(pools) > 1:
                return f"S4: {full}: split across pools {pools}"
            if not all(p.meta.annotations.get(COORD_ANNOTATION)
                       for p in bound):
                return f"S4: {full}: coordinates missing"
    return None


@pytest.mark.parametrize("seed", [20260731, 7])
def test_composed_chaos_soak(seed):
    global SEED
    SEED = seed
    rng = random.Random(SEED)
    state_dir = tempfile.mkdtemp(prefix="tpusched-soak-composed-")
    profile = full_stack_profile(permit_wait_s=4, denied_s=1)
    mk = lambda ident: HAScheduler(state_dir, profiles=[profile],
                                   identity=ident, lease_duration_s=1.0,
                                   renew_interval_s=0.25)
    replicas = [mk("soak-a"), mk("soak-b"), mk("soak-c")]
    crash_rounds = {ROUNDS // 3, (2 * ROUNDS) // 3}
    attempts_start = schedule_attempts.value()
    defrag = None
    gangs = {}                 # full → (min_member, shape)
    assignments = {}           # uid → node (S2 ledger)
    counter = 0
    try:
        replicas[0].run()
        active = _active_of(replicas)
        for r in replicas[1:]:
            r.run()
        for i in range(2):
            topo, nodes = make_tpu_pool(f"pool-{i}", dims=(4, 4, 4))
            active.api.create(srv.TPU_TOPOLOGIES, topo)
            for n in nodes:
                active.api.create(srv.NODES, n)
        for team in ("team-a", "team-b"):
            active.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 32}, max={TPU: 128}))

        def fresh_defrag(api):
            nonlocal defrag
            if defrag is not None:
                defrag.stop()
            defrag = DefragController(api, blocked_after_s=0.5,
                                      cooldown_s=0.0, shadow_timeout_s=10.0,
                                      dry_run=False)
            return defrag

        fresh_defrag(active.api)

        def submit_gang(kind):
            nonlocal counter
            team = rng.choice(("team-a", "team-b"))
            name = f"{kind}{counter}"
            counter += 1
            members, shape, prio = {
                "filler": (1, "2x2x1", 0),
                "mid": (2, "2x2x2", 0),
                "burst": (16, "4x4x4", 100),
            }[kind]
            pg = make_pod_group(name, namespace=team, min_member=members,
                                tpu_slice_shape=shape,
                                tpu_accelerator="tpu-v5p")
            if kind != "burst":    # small gangs consent to defrag moves
                pg.meta.annotations[ALLOW_MIGRATION_ANNOTATION] = "true"
            active.api.create(srv.POD_GROUPS, pg)
            for j in range(members):
                active.api.create(srv.PODS, make_pod(
                    f"{name}-{j}", namespace=team, pod_group=name,
                    limits={TPU: 4}, priority=prio))
            gangs[f"{team}/{name}"] = (members, shape)

        def delete_gang():
            full = rng.choice(sorted(gangs))
            ns, name = full.split("/")
            for p in list(active.api.list(srv.PODS, ns)):
                if p.meta.labels.get(POD_GROUP_LABEL) == name:
                    try:
                        active.api.delete(srv.PODS, p.meta.key)
                    except srv.NotFound:
                        pass
                    assignments.pop(p.meta.uid, None)
            try:
                active.api.delete(srv.POD_GROUPS, full)
            except srv.NotFound:
                pass
            del gangs[full]

        def quiesced():
            return (active.is_active.is_set() and active.schedulers
                    and active.schedulers[0].queue.pending_counts()
                    ["active"] == 0)

        for rnd in range(ROUNDS):
            for _ in range(rng.randint(2, 4)):
                op = rng.random()
                if op < 0.35 or not gangs:
                    submit_gang(rng.choice(("filler", "filler", "mid")))
                elif op < 0.55:
                    submit_gang("burst")
                elif op < 0.75 and gangs:
                    delete_gang()
                else:
                    # defrag scan+actuation against the LIVE control plane
                    defrag.reconcile_once()
            if rnd in crash_rounds:
                # SIGKILL semantics: lease unreleased, journal fenced by
                # the successor's WAL rotation. Preemptions/permits
                # in-flight die with the process; the WAL + API are the
                # only checkpoint.
                dead = active
                dead.crash()
                replicas.remove(dead)
                active = _active_of(replicas, timeout=45)
                fresh_defrag(active.api)
                # S2 across replay: every surviving bound pod kept its host
                _check_hard(active.api, assignments)
            assert wait_until(quiesced, timeout=40), (
                f"round {rnd} did not quiesce (seed {SEED})")

            def stable_clean():
                _check_hard(active.api, assignments)
                if not quiesced() or _gang_violation(active.api, gangs):
                    return False
                time.sleep(0.3)
                return (quiesced()
                        and _gang_violation(active.api, gangs) is None)

            if not wait_until(stable_clean, timeout=40, interval=0.2):
                raise AssertionError(
                    f"round {rnd}: invariants never stabilized (seed "
                    f"{SEED}): {_gang_violation(active.api, gangs)}")

        # keep the stream going until the cycle floor is met: churn small
        # gangs (every admission, retry, and denial is a cycle)
        deadline = time.monotonic() + 120
        while (schedule_attempts.value() - attempts_start < MIN_CYCLES
               and time.monotonic() < deadline):
            submit_gang("filler")
            if len(gangs) > 40:
                delete_gang()
            time.sleep(0.02)
        cycles = schedule_attempts.value() - attempts_start
        assert cycles >= MIN_CYCLES, (
            f"only {cycles:.0f} scheduling cycles exercised (seed {SEED})")
        assert len(replicas) == 1, "both scheduled takeovers must have run"
        assert wait_until(quiesced, timeout=40)
        _check_hard(active.api, assignments)

        # S5: WAL replay convergence. The permit barrier can still resolve
        # binds after a quiesced read (steady-state of a contended
        # scheduler), so first remove every writer: crash the remaining
        # standby (it must NOT take over and rotate the WAL), then stop
        # the active cleanly — deactivation drains and closes the journal.
        # Only then is live-vs-replay comparable.
        for r in replicas:
            if r is not active:
                r.crash()
        active.stop()
        live = {p.meta.uid: p.spec.node_name
                for p in _bound_pods(active.api)}
        cold = srv.APIServer()
        persistence.load_into(cold, state_dir)
        replayed = {p.meta.uid: p.spec.node_name
                    for p in _bound_pods(cold)}
        assert replayed == live, (
            f"S5: cold replay diverged (seed {SEED}): "
            f"{len(replayed)} vs {len(live)} bound")
    finally:
        if defrag is not None:
            defrag.stop()
        for r in replicas:
            r.crash()
        shutil.rmtree(state_dir, ignore_errors=True)
