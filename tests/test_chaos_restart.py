"""Failure recovery: scheduler death mid-gang and controller fail-over.

The reference's failure model (SURVEY §5): no local checkpoint — a restarted
scheduler reconstructs everything from the API server; gang members parked at
the Permit barrier are process state and die with it, but unassigned pods are
still Pending in the API, so the next scheduler re-admits the whole gang.
Leader election covers the controller side
(/root/reference/cmd/controller/app/server.go:84-123)."""
from __future__ import annotations

import time

from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.controllers.runner import (LEASE_NAME, ControllerRunner,
                                         ServerRunOptions)
from tpusched.testing import TestCluster, make_pod, make_pod_group


def test_scheduler_death_at_permit_barrier_gang_recovers():
    """A gang with capacity for only half its members parks the schedulable
    half at the Permit barrier (quorum unreachable); the scheduler dies; a
    fresh scheduler against the same API server — with capacity restored —
    admits the full gang. Proves the barrier is process state and the API
    server is the only checkpoint."""
    from tpusched.testing import make_tpu_node
    api = srv.APIServer()
    gang = 16  # 1 stuck member = 6.25% gap, inside the ≤10% quorum-gap
    #            grace — PostFilter does NOT mass-reject, so the other 15
    #            stay parked at the barrier (coscheduling.go:140-176)

    # set the cluster up BEFORE the scheduling loop starts so the queue pops
    # in creation order: the 15 schedulable members assume + park first, then
    # the stuck one fails inside the grace window
    c = TestCluster(profile=tpu_gang_profile(permit_wait_s=60), api=api)
    c.add_nodes([make_tpu_node(f"n{i}", chips=4) for i in range(gang)])
    c.api.create(srv.POD_GROUPS, make_pod_group("g", min_member=gang))
    pods = [make_pod(f"w{i:02d}", pod_group="g", limits={TPU: 4},
                     node_selector=({"flavor": "special"}
                                    if i == gang - 1 else None))
            for i in range(gang)]
    c.create_pods(pods)
    c.scheduler.run()
    try:
        # w15 can't land anywhere (no node carries the label); the other 15
        # park at the Permit barrier (waitingPods map — in-process state)
        deadline = time.time() + 10
        waiting = []
        while time.time() < deadline:
            waiting = []
            c.scheduler.framework.iterate_over_waiting_pods(
                lambda wp: waiting.append(wp))
            if len(waiting) == gang - 1:
                break
            time.sleep(0.02)
        assert len(waiting) == gang - 1
        assert all(not c.pod_scheduled(p.key) for p in pods)
    finally:
        c.stop()

    # process death rejected the waiting pods; nothing was bound
    assert all(not p.spec.node_name for p in api.list(srv.PODS))

    # fresh scheduler, same control plane (etcd-as-truth); the missing
    # capacity appears and the whole gang admits
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=60), api=api) as c2:
        c2.add_nodes([make_tpu_node("n-special", chips=4)])
        c2.api.patch(srv.NODES, "/n-special",
                     lambda n: n.meta.labels.update({"flavor": "special"}))
        keys = [f"default/w{i:02d}" for i in range(gang)]
        assert c2.wait_for_pods_scheduled(keys, timeout=30)
        hosts = {c2.pod(k).spec.node_name for k in keys}
        assert len(hosts) == gang  # one host each, nothing double-placed


def test_controller_failover_via_leader_election():
    """Two controller runners with leader election: killing the leader hands
    the lease to the standby, which resumes reconciling PodGroup phases."""
    api = srv.APIServer()
    opts = ServerRunOptions(enable_leader_election=True,
                            lease_duration_s=0.5, renew_interval_s=0.1)
    a = ControllerRunner(api, opts)
    b = ControllerRunner(api, opts)
    a.run()
    assert a.is_leader.wait(timeout=5)
    b.run()
    time.sleep(0.3)
    assert not b.is_leader.is_set()  # standby while the lease is held

    a.stop()  # leader dies; lease expires; standby must take over
    assert b.is_leader.wait(timeout=10)
    assert api.lease_holder(LEASE_NAME) == b.identity

    # the new leader's controllers actually reconcile: a PodGroup gets phased
    api.create(srv.POD_GROUPS, make_pod_group("g", min_member=1))
    deadline = time.time() + 10
    phase = ""
    while time.time() < deadline:
        phase = api.get(srv.POD_GROUPS, "default/g").status.phase
        if phase:
            break
        time.sleep(0.05)
    assert phase != ""
    b.stop()


def test_permit_barrier_resolves_on_framework_close():
    """Shutdown straggler: a pod that reaches the permit barrier during
    teardown must still get its resolution callback (the failure path that
    unreserves + forgets it). Framework.close() rejects remaining waiters
    before killing the deadline sweeper; after close, new permit waits are
    refused outright."""
    from tpusched.config.profiles import tpu_gang_profile
    from tpusched.fwk import CycleState
    from tpusched.testing import make_pod, make_pod_group, make_tpu_node
    from tpusched.testing.harness import new_test_framework

    pg = make_pod_group("gang", min_member=2)
    fw, handle, api = new_test_framework(
        tpu_gang_profile(permit_wait_s=3600), nodes=[make_tpu_node("h0")])
    api.create(srv.POD_GROUPS, pg)
    member = make_pod("m0", pod_group="gang")
    api.create(srv.PODS, member)

    s = fw.run_permit_plugins(CycleState(), member, "h0")
    assert s.is_wait()
    resolved = []
    fw.notify_on_permit(member, resolved.append)
    assert resolved == []          # barrier still open

    fw.close()
    assert len(resolved) == 1
    assert resolved[0].is_unschedulable()
    assert "closing" in resolved[0].message()

    # post-close registration is refused, not leaked
    late = make_pod("m1", pod_group="gang")
    api.create(srv.PODS, late)
    s2 = fw.run_permit_plugins(CycleState(), late, "h0")
    assert s2.is_unschedulable()
    assert "closing" in s2.message()


def test_permit_timeout_fires_via_sweeper_callback():
    """Event-driven deadline: with nobody blocked in wait(), the framework's
    sweeper must expire the barrier and fire the callback."""
    from tpusched.config.profiles import tpu_gang_profile
    from tpusched.fwk import CycleState
    from tpusched.testing import make_pod, make_pod_group, make_tpu_node
    from tpusched.testing.harness import new_test_framework

    pg = make_pod_group("gang", min_member=2, schedule_timeout_seconds=1)
    fw, handle, api = new_test_framework(
        tpu_gang_profile(permit_wait_s=1), nodes=[make_tpu_node("h0")])
    api.create(srv.POD_GROUPS, pg)
    member = make_pod("m0", pod_group="gang")
    api.create(srv.PODS, member)

    s = fw.run_permit_plugins(CycleState(), member, "h0")
    assert s.is_wait()
    resolved = []
    fw.notify_on_permit(member, resolved.append)
    deadline = time.time() + 5
    while not resolved and time.time() < deadline:
        time.sleep(0.05)
    assert resolved and resolved[0].is_unschedulable()
    assert "timeout" in resolved[0].message()
    assert fw.get_waiting_pod(member.meta.uid) is None  # entry removed
