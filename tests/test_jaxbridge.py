"""jaxbridge tests on the virtual 8-device CPU mesh: slice→Mesh mapping and
the sharded train step (dp/fsdp/sp/tp)."""
import jax
import jax.numpy as jnp
import pytest

from tpusched.jaxbridge import mesh as meshlib
from tpusched.jaxbridge import workload as wl


def test_factor_mesh_power_of_two_tp():
    assert meshlib.factor_mesh(8) == (2, 4)
    assert meshlib.factor_mesh(6) == (3, 2)   # tp stays a power of two
    assert meshlib.factor_mesh(1) == (1, 1)
    assert meshlib.factor_mesh(12) == (3, 4)


def test_slice_assignment_decodes_annotations():
    from tpusched.plugins.topologymatch import COORD_ANNOTATION
    from tpusched.testing import make_pod
    pods = [make_pod(f"p{i}", node_name=f"n{i}",
                     annotations={COORD_ANNOTATION: f"{i * 2}-0-0"})
            for i in (1, 0, 2)]
    got = meshlib.slice_assignment(pods)
    assert [c for c, _ in got] == [(0, 0, 0), (2, 0, 0), (4, 0, 0)]
    assert [n for _, n in got] == ["n0", "n1", "n2"]


def test_sharded_train_step_4axis():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = wl.ModelConfig.tiny()
    mesh = meshlib.build_named_mesh({"dp": 1, "fsdp": 2, "sp": 2, "tp": 2})
    step, pshard, tshard = wl.make_sharded_train_step(mesh, cfg)
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(0), cfg), pshard)
    tokens = jax.device_put(jnp.zeros((4, cfg.seq), jnp.int32), tshard)
    new_params, loss = step(params, tokens)
    jax.block_until_ready(loss)
    assert jnp.isfinite(loss)
    # fsdp actually shards the params: a weight's addressable shard is smaller
    w = new_params["layers"][0]["wq"]
    assert w.addressable_shards[0].data.shape[0] == cfg.d_model // 2  # fsdp
    assert w.addressable_shards[0].data.shape[1] == cfg.d_model // 2  # tp


def test_multislice_mesh_axes():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = wl.ModelConfig.tiny()
    mesh = meshlib.build_named_mesh({"slice": 2, "dp": 2, "tp": 2})
    step, pshard, tshard = wl.make_sharded_train_step(mesh, cfg)
    params = jax.device_put(wl.init_params(jax.random.PRNGKey(1), cfg), pshard)
    tokens = jax.device_put(jnp.zeros((4, cfg.seq), jnp.int32), tshard)
    _, loss = step(params, tokens)
    assert jnp.isfinite(loss)
