"""HBM budget calculator (jaxbridge/budget.py): the analytic memory model
that sizes flagship configs and validates capacity plans arithmetically
(VERDICT r4 #4). Pins: the analytic parameter count against real init
trees, the 8B-on-v5p-256 plan, the llama_like_xl sizing decision (fits at
bf16 state, the 22-layer sibling and the f32-master policy do not), and
the what-if CLI plumbing."""
import json

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpusched.jaxbridge import budget as B  # noqa: E402
from tpusched.jaxbridge.workload import ModelConfig, init_params  # noqa: E402

LLAMA3_8B = {"vocab": 128256, "d_model": 4096, "n_layers": 32,
             "n_heads": 32, "n_kv_heads": 8, "d_ff": 14336, "seq": 8192,
             "dtype": "bf16", "param_dtype": "f32", "attn": "flash",
             "remat": True, "vocab_parallel_loss": True}


@pytest.mark.parametrize("cfg", [
    ModelConfig.tiny(),
    ModelConfig.llama_like(seq=256),
    ModelConfig(vocab=512, d_model=128, n_layers=3, n_heads=4,
                n_kv_heads=2, d_ff=256, seq=64),
    ModelConfig(vocab=512, d_model=128, n_layers=2, n_heads=4, d_ff=256,
                seq=64, n_experts=4, moe_top_k=2),
])
def test_analytic_param_count_matches_real_init(cfg):
    import numpy as np
    real = sum(int(np.prod(p.shape)) for p in
               jax.tree_util.tree_leaves(init_params(jax.random.PRNGKey(0),
                                                     cfg)))
    assert B.count_params(cfg) == real


def test_llama3_8b_plan_fits_v5p_256_but_not_one_chip():
    """The north-star plan, arithmetically: 8B AdamW(f32 master) at seq
    8192 fits a v5p-256 as dp8 x fsdp8 x tp4 (<10 GiB/chip of 95), and is
    ~1.6x over a SINGLE v5p chip — the calculator must say both."""
    plan = {"model": LLAMA3_8B, "batch_per_replica": 1,
            "mesh": {"dp": 8, "fsdp": 8, "tp": 4},
            "accelerator": "tpu-v5p"}
    out = B.validate_plan(plan)
    assert out["chips"] == 256
    assert out["fits"] is True
    assert out["breakdown"]["total_gib"] < 16
    assert 7.5e9 < out["breakdown"]["n_params"] < 8.5e9
    solo = B.validate_plan({**plan, "mesh": {}})
    assert solo["fits"] is False
    assert solo["breakdown"]["total_gib"] > 95


def test_xl_flagship_sizing_decision():
    """llama_like_xl was SIZED by this calculator: ~1.55B fits a 16 GiB
    v5e with pure-bf16 AdamW state at <=90% utilization; the 22-layer
    sibling exceeds the margin the docstring claims, and the classic
    f32-master policy does not fit at all."""
    import dataclasses
    xl = ModelConfig.llama_like_xl()
    bd = B.train_hbm_breakdown(xl, 1, mu_dtype="bf16",
                               accelerator="tpu-v5e")
    assert bd.fits and 1.4e9 < bd.n_params < 1.7e9
    assert bd.utilization <= 0.90
    bigger = dataclasses.replace(xl, n_layers=22)
    bd22 = B.train_hbm_breakdown(bigger, 1, mu_dtype="bf16",
                                 accelerator="tpu-v5e")
    assert bd22.utilization > 0.90
    f32_master = dataclasses.replace(xl, param_dtype=jnp.float32)
    bdf32 = B.train_hbm_breakdown(f32_master, 1, mu_dtype="f32",
                                  accelerator="tpu-v5e")
    assert not bdf32.fits


def test_remat_and_flash_reduce_activation_budget():
    import dataclasses
    base = ModelConfig.llama_like(seq=2048)
    flash = dataclasses.replace(base, attn="flash")
    remat = dataclasses.replace(flash, remat=True)
    a_naive = B.train_hbm_breakdown(base, 2).activations_gib
    a_flash = B.train_hbm_breakdown(flash, 2).activations_gib
    a_remat = B.train_hbm_breakdown(remat, 2).activations_gib
    assert a_flash < a_naive          # no s^2 score tensor
    assert a_remat < a_flash / 3      # one block's workspace, not all


def test_serve_breakdown_int8_halves_kv():
    cfg = ModelConfig.llama_like(seq=2048)
    import dataclasses
    exact = B.serve_hbm_breakdown(cfg, slots=8, max_seq=2048,
                                  accelerator="tpu-v5e")
    int8 = B.serve_hbm_breakdown(
        dataclasses.replace(cfg, kv_cache_dtype="int8"), slots=8,
        max_seq=2048, accelerator="tpu-v5e")
    assert int8.kv_arena_gib < 0.6 * exact.kv_arena_gib
    assert exact.fits
    # tp sharding divides both terms
    tp2 = B.serve_hbm_breakdown(cfg, slots=8, max_seq=2048, tp=2)
    assert abs(tp2.total_gib - exact.total_gib / 2) < 0.05


def test_tpu_memory_request_is_chip_node_units():
    bd = B.train_hbm_breakdown(ModelConfig.llama_like_big(), 1,
                               mu_dtype="f32", accelerator="tpu-v5e")
    mb = B.tpu_memory_request_mb(bd)
    assert mb == int(bd.total_gib * 1024 + 0.5)
    assert 0 < mb < 16 * 1024


def test_whatif_cli_train_plan(tmp_path, capsys):
    from tpusched.cmd import whatif as cli
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps({
        "model": LLAMA3_8B, "batch_per_replica": 1,
        "mesh": {"dp": 8, "fsdp": 8, "tp": 4}, "accelerator": "tpu-v5p"}))
    assert cli.main(["--train-plan", str(plan)]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["fits"] is True and out["chips"] == 256
    plan.write_text(json.dumps({
        "model": LLAMA3_8B, "batch_per_replica": 1,
        "accelerator": "tpu-v5p"}))
    assert cli.main(["--train-plan", str(plan)]) == 1
