"""TestSetup analog — the reference boots the real options stack and asserts
the fully-defaulted per-plugin wiring for every shipped scheduler config
(/root/reference/cmd/scheduler/main_test.go:48 `TestSetup`: a wantPlugins
table per plugin configuration). Here: every manifests/*/scheduler-config.yaml
is (a) accepted end-to-end by the real CLI (`--validate-only`), and (b)
resolved to EXACTLY the expected extension-point wiring and defaulted args —
any drift in defaults, decode, or manifest content fails the table.
"""
import dataclasses
import glob
import json
import os

import pytest

from tpusched.cmd import scheduler as sched_cmd
from tpusched.config import versioned as v

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_FILTERS = ["NodeUnschedulable", "NodeName", "NodeSelector",
                   "TaintToleration", "NodeResourcesFit"]

# (manifest, scheduler_name) -> want wiring. Unlisted points default to
# expectations of the defaults profile: PrioritySort + default filters +
# DefaultBinder, everything else empty.
WANT = {
    ("coscheduling", "tpusched"): dict(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"], post_filter=["Coscheduling"],
        reserve=["Coscheduling"], permit=["Coscheduling"],
        post_bind=["Coscheduling"],
        args={"Coscheduling": {"permit_waiting_time_seconds": 60,
                               "denied_pg_expiration_time_seconds": 20,
                               "pg_status_flush_seconds": 0.05}}),
    ("capacityscheduling", "tpusched"): dict(
        pre_filter=["CapacityScheduling"], post_filter=["CapacityScheduling"],
        reserve=["CapacityScheduling"]),
    ("full", "tpusched"): dict(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling", "TopologyMatch", "MultiSlice",
                    "CapacityScheduling"],
        filter=["TopologyMatch", "MultiSlice"] + DEFAULT_FILTERS + ["TpuSlice"],
        post_filter=["TopologyMatch", "Coscheduling", "MultiSlice",
                     "CapacityScheduling"],
        pre_score=["MultiSlice"],
        score=[("TpuSlice", 1), ("TopologyMatch", 2), ("MultiSlice", 3)],
        reserve=["TpuSlice", "TopologyMatch", "Coscheduling", "MultiSlice",
                 "CapacityScheduling"],
        permit=["Coscheduling", "MultiSlice"], bind=["TpuSlice"],
        post_bind=["Coscheduling"],
        args={"Coscheduling": {"permit_waiting_time_seconds": 60,
                               "denied_pg_expiration_time_seconds": 20,
                               "pg_status_flush_seconds": 0.05},
              "TopologyMatch": {"scoring_strategy": "LeastAllocated",
                                "resource_weights": {"google.com/tpu": 1},
                                "packing_weight": 0.7,
                                "enable_slice_preemption": True,
                                "slice_preemption_drain_seconds": 60.0,
                                "index_differential_period": 0}}),
    ("multislice", "tpusched"): dict(
        pre_score=["MultiSlice"], score=[("MultiSlice", 3)],
        args={"MultiSlice": {"same_domain_score": 100,
                             "adjacent_domain_score": 50,
                             "set_schedule_timeout_seconds": 120,
                             "denied_set_expiration_time_seconds": 20,
                             "hard_domain_policy": ""}}),
    ("noderesources", "tpusched"): dict(
        score=[("NodeResourcesAllocatable", 1)],
        args={"NodeResourcesAllocatable": {
            "mode": "Least",
            "resources": [{"name": "cpu", "weight": 1 << 20},
                          {"name": "memory", "weight": 1}]}}),
    ("podstate", "tpusched"): dict(score=[("PodState", 1)]),
    ("preemptiontoleration", "tpusched"): dict(
        post_filter=["PreemptionToleration"],
        args={"PreemptionToleration": {"min_candidate_nodes_percentage": 10,
                                       "min_candidate_nodes_absolute": 100}}),
    ("qos", "tpusched"): dict(queue_sort="QOSSort"),
    ("topologymatch", "tpusched"): dict(
        pre_filter=["TopologyMatch"],
        filter=["TopologyMatch"] + DEFAULT_FILTERS,
        score=[("TopologyMatch", 2)], reserve=["TopologyMatch"],
        args={"TopologyMatch": {"scoring_strategy": "LeastAllocated",
                                "resource_weights": {"google.com/tpu": 1},
                                "packing_weight": 0.7,
                                "enable_slice_preemption": False,
                                "slice_preemption_drain_seconds": 60.0,
                                "index_differential_period": 0}}),
    ("trimaran", "tpusched"): dict(
        score=[("TargetLoadPacking", 1)],
        args={"TargetLoadPacking": {
            "target_utilization": 40,          # defaults.go:50
            "default_requests_cpu_millis": 1000,
            "default_requests_multiplier": 1.5,  # defaults preserved
            "watcher_address": "http://127.0.0.1:2020",
            "metrics_refresh_interval_seconds": 30}}),
    ("trimaran", "tpusched-risk"): dict(
        score=[("LoadVariationRiskBalancing", 1)],
        args={"LoadVariationRiskBalancing": {
            "safe_variance_margin": 1,         # defaults.go SafeVarianceMargin
            "safe_variance_sensitivity": 1,
            "watcher_address": "http://127.0.0.1:2020",
            "metrics_refresh_interval_seconds": 30}}),
}


def resolved_profiles():
    out = {}
    for path in sorted(glob.glob(os.path.join(
            REPO, "manifests", "*", "scheduler-config.yaml"))):
        manifest = os.path.basename(os.path.dirname(path))
        for p in v.load_file(path).profiles:
            out[(manifest, p.scheduler_name)] = (path, p)
    return out


PROFILES = resolved_profiles()


def test_table_covers_every_manifest_profile():
    """New manifests must be added to the WANT table — drift is an error in
    both directions."""
    assert sorted(PROFILES) == sorted(WANT)


@pytest.mark.parametrize("key", sorted(WANT), ids=["/".join(k) for k in WANT])
def test_manifest_resolves_to_expected_wiring(key):
    path, profile = PROFILES[key]
    want = WANT[key]
    assert profile.queue_sort == want.get("queue_sort", "PrioritySort")
    assert profile.pre_filter == want.get("pre_filter", [])
    assert profile.filter == want.get("filter", DEFAULT_FILTERS)
    assert profile.post_filter == want.get("post_filter", [])
    assert profile.pre_score == want.get("pre_score", [])
    assert [tuple(s) for s in profile.score] == want.get("score", [])
    assert profile.reserve == want.get("reserve", [])
    assert profile.permit == want.get("permit", [])
    assert profile.pre_bind == want.get("pre_bind", [])
    assert profile.bind == want.get("bind", ["DefaultBinder"])
    assert profile.post_bind == want.get("post_bind", [])
    got_args = {name: dataclasses.asdict(a)
                for name, a in profile.plugin_args.items()}
    assert got_args == want.get("args", {})


@pytest.mark.parametrize("key", sorted(WANT), ids=["/".join(k) for k in WANT])
def test_cli_accepts_manifest(key, capsys):
    """The real binary path: decode → instantiate every plugin → report.
    --scheduler-name selects the profile, as a deployment would."""
    path, _ = PROFILES[key]
    rc = sched_cmd.main(["--config", path, "--validate-only",
                         "--scheduler-name", key[1]])
    assert rc == 0
    [out] = json.loads(capsys.readouterr().out)
    assert out["schedulerName"] == key[1]
    # every plugin the profile names was actually constructed
    for point in ("queueSort", "preFilter", "filter", "postFilter",
                  "permit", "reserve", "bind", "postBind", "score"):
        val = out.get(point)
        names = ([val] if isinstance(val, str) else
                 [e["name"] if isinstance(e, dict) else e
                  for e in (val or [])])
        for n in names:
            assert n in out["plugins"], (path, point, n)
