"""Unit tier for the gang runtime goodput plane (tpusched/obs/goodput.py):
matrix algebra + persistence, straggler hysteresis, aggregator bounds
(entry/byte budgets, LRU eviction, metric-child removal), the 10k-report
shed soak under concurrent scrapes, shadow inertness, and the jaxbridge
emitter contract (GoodputReporter).
"""
from __future__ import annotations

import json
import threading

import pytest

from tpusched.api.core import GangMemberStatus
from tpusched.obs.goodput import (GoodputAggregator, GoodputMatrix,
                                  MATRIX_SCHEMA_VERSION, load_matrix)
from tpusched.util.metrics import REGISTRY


def report(pod, gang="", step=1, step_time=0.1, throughput=0.0,
           unit="tokens", ttft=0.0, stall=0.0, ts=1000.0):
    return GangMemberStatus(pod_key=pod, gang=gang, step=step,
                            step_time_s=step_time, throughput=throughput,
                            unit=unit, ttft_s=ttft, stall_s=stall,
                            timestamp=ts)


def feed(agg, pod, gang, n, step_time, throughput=0.0, start_step=1):
    for i in range(n):
        agg.ingest([report(pod, gang, step=start_step + i,
                           step_time=step_time, throughput=throughput)])


# -- the matrix artifact -------------------------------------------------------


def test_matrix_fold_ewma_and_ordering():
    m = GoodputMatrix()
    # two workloads × two generations, injected per-chip rates whose
    # ordering the matrix must preserve
    for _ in range(8):
        m.fold("llama/16chip", "tpu-v5p", 250.0, "tokens", 1.0)
        m.fold("llama/16chip", "tpu-v6e", 510.0, "tokens", 1.0)
        m.fold("moe/32chip", "tpu-v5p", 90.0, "tokens", 1.0)
        m.fold("moe/32chip", "tpu-v6e", 60.0, "tokens", 1.0)
    assert m.peek("llama/16chip", "tpu-v6e") > m.peek("llama/16chip",
                                                      "tpu-v5p")
    # heterogeneity is real: moe prefers the OTHER generation
    assert m.peek("moe/32chip", "tpu-v5p") > m.peek("moe/32chip", "tpu-v6e")
    assert m.best_generation("llama/16chip") == "tpu-v6e"
    assert m.best_generation("moe/32chip") == "tpu-v5p"
    assert m.best_generation("never-seen") is None
    assert m.peek("llama/16chip", "tpu-v9") is None  # None, never 0.0
    assert m.size() == 4


def test_matrix_ewma_converges_and_first_report_seeds():
    m = GoodputMatrix()
    m.fold("w", "g", 100.0, "tokens", 1.0)
    assert m.peek("w", "g") == 100.0          # first report seeds exactly
    for _ in range(40):
        m.fold("w", "g", 200.0, "tokens", 2.0)
    assert 195.0 < m.peek("w", "g") <= 200.0  # EWMA converges to the level


def test_matrix_snapshot_reload_round_trip(tmp_path):
    m = GoodputMatrix()
    m.fold("llama/16chip", "tpu-v5p", 250.0, "tokens", 1.5)
    m.fold("moe/32chip", "tpu-v6e", 60.0, "examples", 2.5)
    path = str(tmp_path / "matrix.json")
    m.save(path)
    back = load_matrix(path)
    assert back.schema_version == MATRIX_SCHEMA_VERSION
    assert back.to_dict() == m.to_dict()
    assert back.peek("moe/32chip", "tpu-v6e") == m.peek("moe/32chip",
                                                        "tpu-v6e")
    assert back.cell("llama/16chip", "tpu-v5p").unit == "tokens"


@pytest.mark.parametrize("mutate, err", [
    (lambda d: d.update(schema_version=99), "schema_version"),
    (lambda d: d.pop("cells"), "cells"),
    (lambda d: d.update(cells="nope"), "cells"),
    (lambda d: d.update(cells={"w": "nope"}), "row"),
    (lambda d: d.update(cells={"w": {"g": {"unit": "tokens"}}}),
     "malformed cell"),
    (lambda d: d.update(cells={"w": {"g": {"goodput_per_chip": "NaNope"}}}),
     "malformed cell"),
])
def test_matrix_from_dict_negatives(mutate, err):
    doc = GoodputMatrix().to_dict()
    mutate(doc)
    with pytest.raises(ValueError, match=err):
        GoodputMatrix.from_dict(doc)


# -- straggler hysteresis ------------------------------------------------------


def test_straggler_enter_clear_hysteresis():
    agg = GoodputAggregator(publish=False)
    gang = "default/hys"
    for m in range(3):
        agg.register_member(f"default/hys-{m}", gang, f"n{m}",
                            workload="w", generation="tpu-v5p", chips=4)
    # all healthy: no verdict
    for m in range(3):
        feed(agg, f"default/hys-{m}", gang, 6, 0.1)
    assert agg.gang_health(gang)["stragglers"] == []
    # member 0 turns slow: p99 climbs over enter_ratio × gang median
    feed(agg, "default/hys-0", gang, 6, 0.5, start_step=7)
    health = agg.gang_health(gang)
    assert [s["pod"] for s in health["stragglers"]] == ["default/hys-0"]
    assert health["stragglers"][0]["skew"] > 1.5
    edges_after_enter = agg.stats()["straggler_edges_total"]
    assert edges_after_enter == 1
    # partial recovery: ratio sits between clear (1.2) and enter (1.5)
    # thresholds — the verdict must HOLD (no flap) and no new edge fires
    feed(agg, "default/hys-0", gang, 4, 0.1, start_step=13)
    health = agg.gang_health(gang)
    assert [s["pod"] for s in health["stragglers"]] == ["default/hys-0"]
    assert agg.stats()["straggler_edges_total"] == edges_after_enter
    # full recovery: the slow samples age out of the rolling window and
    # the ratio falls under clear_ratio — the verdict clears
    feed(agg, "default/hys-0", gang, 32, 0.1, start_step=17)
    assert agg.gang_health(gang)["stragglers"] == []
    assert agg.stats()["straggler_edges_total"] == edges_after_enter


def test_straggler_cleared_by_teardown():
    agg = GoodputAggregator(publish=False)
    gang = "default/tear"
    for m in range(3):
        agg.register_member(f"default/tear-{m}", gang, f"n{m}")
        feed(agg, f"default/tear-{m}", gang, 6, 0.5 if m == 0 else 0.1)
    assert agg.gang_health(gang)["stragglers"]
    agg.on_pod_delete("default/tear-0")     # drained, not argued with
    health = agg.gang_health(gang)
    assert health["stragglers"] == []
    assert health["members_reporting"] == 2
    # deleting the rest drops the gang entirely
    agg.on_pod_delete("default/tear-1")
    agg.on_pod_delete("default/tear-2")
    assert agg.gang_health(gang) is None
    assert agg.stats()["members"] == 0


def test_straggler_clears_when_gang_shrinks_below_judgeable():
    # the INVERSE teardown: deleting the straggler's last healthy PEER
    # leaves a gang of one — which has no skew, so the standing verdict
    # must clear rather than freeze at its last value
    agg = GoodputAggregator(publish=False)
    gang = "default/shrink"
    for m in range(2):
        agg.register_member(f"default/shrink-{m}", gang, f"n{m}")
        feed(agg, f"default/shrink-{m}", gang, 6, 0.5 if m == 0 else 0.1)
    assert [s["pod"] for s in agg.gang_health(gang)["stragglers"]] \
        == ["default/shrink-0"]
    agg.on_pod_delete("default/shrink-1")   # the healthy member leaves
    health = agg.gang_health(gang)
    assert health["stragglers"] == []
    assert health["step_skew"] == 1.0


def test_delete_triggered_enter_edge_pins_anomaly(monkeypatch):
    # deleting a member can shift the gang median enough to push a
    # SURVIVOR over the enter threshold — that edge must pin a
    # flight-recorder anomaly exactly like an ingest-triggered one
    pins = []
    monkeypatch.setattr("tpusched.trace.pin_event",
                        lambda kind, **kw: pins.append((kind, kw)))
    agg = GoodputAggregator(publish=True)
    gang = "default/delpin"
    for m in range(3):
        agg.register_member(f"default/delpin-{m}", gang, f"n{m}")
    # member 0: fast median, heavy tail (p99 0.4); peers at 0.28 hold the
    # gang median high enough that 0.4/0.28 stays under the enter ratio
    # (peers report first so no transient low median fires an early edge)
    feed(agg, "default/delpin-1", gang, 6, 0.28)
    feed(agg, "default/delpin-2", gang, 6, 0.28)
    feed(agg, "default/delpin-0", gang, 8, 0.1)
    feed(agg, "default/delpin-0", gang, 2, 0.4, start_step=9)
    assert agg.gang_health(gang)["stragglers"] == []
    assert pins == []
    try:
        agg.on_pod_delete("default/delpin-1")   # median drops to 0.19
        assert [s["pod"] for s in agg.gang_health(gang)["stragglers"]] \
            == ["default/delpin-0"]
        assert [(k, kw["gang"], kw["member"]) for k, kw in pins] \
            == [("gang_straggler", gang, "default/delpin-0")]
    finally:
        agg.on_pod_delete("default/delpin-0")   # drop the gang so its
        agg.on_pod_delete("default/delpin-2")   # gauge children go too


def test_member_budget_shed_leaves_no_empty_gang_shell():
    # at the member budget, traffic for NEW gangs is shed without
    # creating empty gang shells (which nothing could ever drop) or
    # LRU-evicting a live gang to make room for one
    agg = GoodputAggregator(publish=False, max_members=2, max_gangs=4)
    agg.register_member("default/full-0", "default/full", "n0")
    agg.register_member("default/full-1", "default/full", "n1")
    agg.ingest([report("default/other-0", "default/other", step_time=0.1)])
    agg.register_member("default/other-1", "default/other", "n2")
    s = agg.stats()
    assert s["shed_total"] == 2
    assert s["gangs"] == 1                      # no shell appeared
    assert s["gang_evictions_total"] == 0       # live gang untouched
    assert agg.gang_health("default/full") is not None


def test_solo_flood_does_not_starve_gang_telemetry():
    # gangless reporters share the byte budget but are evicted FIRST when
    # they hold the bulk of it — a solo flood must not evict every gang
    agg = GoodputAggregator(publish=False, max_bytes=16 * 1024)
    gang = "default/keep"
    for m in range(3):
        agg.register_member(f"default/keep-{m}", gang, f"n{m}")
        feed(agg, f"default/keep-{m}", gang, 6, 0.1)
    for i in range(200):    # ~83 KiB of solo members against 16 KiB
        agg.ingest([report(f"default/solo-{i}", "", step_time=0.1)])
    s = agg.stats()
    assert s["approx_bytes"] <= 16 * 1024
    assert s["solo_members"] < 200              # solos were trimmed
    assert s["gang_evictions_total"] > 0
    assert agg.gang_health(gang) is not None    # the gang survived
    assert s["gangs"] == 1


def test_straggler_needs_min_reports_and_min_members():
    agg = GoodputAggregator(publish=False)
    gang = "default/min"
    # a gang of one has no skew, however slow it looks
    feed(agg, "default/min-0", gang, 8, 0.5)
    assert agg.gang_health(gang)["stragglers"] == []
    # a second member with too few reports is not judged yet
    feed(agg, "default/min-1", gang, 2, 0.1)
    assert agg.gang_health(gang)["stragglers"] == []
    # enough reports on both: now the slow one is judged
    feed(agg, "default/min-1", gang, 4, 0.1, start_step=3)
    assert [s["pod"] for s in agg.gang_health(gang)["stragglers"]] \
        == ["default/min-0"]


# -- ingest semantics ----------------------------------------------------------


def test_register_on_the_fly_then_registration_fills_in():
    agg = GoodputAggregator(publish=False)
    gang = "default/fly"
    # report arrives BEFORE the scheduler's bind registration (out-of-order
    # heartbeat): folded, not lost
    agg.ingest([report("default/fly-0", gang, throughput=400.0)])
    assert agg.gang_health(gang)["members_reporting"] == 1
    assert agg.peek("", "") is None
    assert agg.stats()["matrix_cells"] == 0    # unattributable yet
    # registration names node/generation/chips; later reports fold into
    # the matrix
    agg.register_member("default/fly-0", gang, "n0", workload="w",
                        generation="tpu-v5p", chips=4)
    agg.ingest([report("default/fly-0", gang, step=2, throughput=400.0)])
    assert agg.peek("w", "tpu-v5p") == pytest.approx(100.0)


def test_solo_members_aggregate_without_gang():
    agg = GoodputAggregator(publish=False)
    agg.register_member("default/solo-0", None, "n0", workload="w",
                        generation="tpu-v5p", chips=1)
    agg.ingest([report("default/solo-0", "", throughput=50.0)])
    s = agg.stats()
    assert s["solo_members"] == 1 and s["gangs"] == 0
    assert agg.peek("w", "tpu-v5p") == pytest.approx(50.0)
    fleet = agg.fleet_summary()
    assert fleet["reporting_members"] == 1
    assert fleet["units_per_s"]["tokens"] == pytest.approx(50.0)


def test_gang_eviction_removes_metric_children():
    agg = GoodputAggregator(max_gangs=2)
    try:
        for i in range(3):
            gang = f"default/evict-{i}"
            for m in range(2):
                agg.register_member(f"default/evict-{i}-{m}", gang, "n0")
                feed(agg, f"default/evict-{i}-{m}", gang, 5, 0.1,
                     throughput=10.0)
        s = agg.stats()
        assert s["gangs"] == 2 and s["members"] == 4
        # the LRU gang (evict-0) was dropped: its published children must
        # be GONE from the exposition, not frozen at their last values
        text = REGISTRY.expose()
        assert 'gang="default/evict-0"' not in text
        assert 'gang="default/evict-2"' in text
        assert agg.gang_health("default/evict-0") is None
    finally:
        for i in range(3):
            for m in range(2):
                agg.on_pod_delete(f"default/evict-{i}-{m}")
    assert 'tpusched_gang_goodput_units_per_second{gang="default/evict' \
        not in REGISTRY.expose()


def test_shadow_aggregator_is_inert():
    """publish=False (the shadow shell): observations accumulate for
    dump() but no process-global metric family is touched and no anomaly
    is pinned — a what-if trial's synthetic members must never read as
    fleet runtime telemetry."""
    from tpusched import trace
    prev = trace.default_recorder()
    trace.install_recorder(trace.FlightRecorder())
    try:
        agg = GoodputAggregator(publish=False)
        gang = "default/shadow-trial"
        for m in range(2):
            agg.register_member(f"default/shadow-trial-{m}", gang, "n0",
                                workload="w", generation="tpu-v5p", chips=1)
            feed(agg, f"default/shadow-trial-{m}", gang, 6,
                 0.5 if m == 0 else 0.1, throughput=10.0)
        # straggler detected internally...
        assert agg.gang_health(gang)["stragglers"]
        # ...but nothing global: no metric children, no pinned anomaly
        assert "shadow-trial" not in REGISTRY.expose()
        assert trace.default_recorder().pinned_traces() == []
    finally:
        trace.install_recorder(prev)


# -- bounds: the 10k-report shed soak under concurrent scrapes -----------------


def test_shed_soak_bounds_hold_under_concurrent_scrapes():
    agg = GoodputAggregator(max_gangs=16, max_members=64,
                            max_bytes=64 * 1024, max_matrix_cells=8)
    stop = threading.Event()
    errors = []

    def scrape():
        while not stop.is_set():
            try:
                agg.dump()
                agg.fleet_summary()
                agg.gang_health("default/soak-3")
                json.dumps(agg.matrix_snapshot().summary())
                REGISTRY.expose()
            except Exception as e:  # noqa: BLE001 — the assertion payload
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=scrape, name=f"goodput-scrape-{i}",
                                daemon=True) for i in range(3)]
    for t in threads:
        t.start()
    # 16 long-lived gangs × 8 reporting members = 128 distinct members
    # against a 64-member budget: the entry budget must bite (shed), the
    # byte budget must hold, and scrapes must stay consistent throughout
    total = 10_000
    try:
        # heartbeat-sized batches (the production ingest shape); 10k
        # reports total so the budgets bite many times over
        batch = []
        for i in range(total):
            gang = f"default/soak-{i % 16}"
            batch.append(report(f"{gang}-m{(i // 16) % 8}", gang,
                                step=i, step_time=0.1,
                                throughput=float(i % 7) * 10))
            if len(batch) == 25:
                agg.ingest(batch)
                batch = []
        if batch:
            agg.ingest(batch)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    s = agg.stats()
    assert s["accepted_total"] + s["shed_total"] == total
    assert s["shed_total"] > 0          # the budgets actually bit
    assert s["gangs"] <= 16
    assert s["members"] <= 64
    assert s["approx_bytes"] <= 64 * 1024
    assert s["matrix_cells"] <= 8
    # cleanup: drop everything this soak registered so its gauge children
    # do not leak into later tests' expositions
    for i in range(16):
        for m in range(8):
            agg.on_pod_delete(f"default/soak-{i}-m{m}")


# -- the jaxbridge emitter contract --------------------------------------------


class _FakeClientset:
    def __init__(self):
        self.batches = []

    def report_status(self, reports):
        self.batches.append(list(reports))


def test_goodput_reporter_contract():
    from tpusched.jaxbridge.measure import GoodputReporter
    clock = {"now": 100.0}
    client = _FakeClientset()
    rep = GoodputReporter(client, "default/train-0", gang="default/train",
                          unit="tokens", min_interval_s=5.0,
                          clock=lambda: clock["now"])
    # empty window: nothing to say
    assert rep.flush() is False
    rep.observe_step(10, 0.5, items=1000)
    rep.observe_step(11, 0.5, items=1000)
    rep.observe_stall(2.0)
    assert rep.maybe_flush() is True          # first flush is immediate
    [r] = client.batches[0]
    assert r.pod_key == "default/train-0" and r.gang == "default/train"
    assert r.step == 11
    assert r.step_time_s == pytest.approx(0.5)
    assert r.throughput == pytest.approx(2000.0)   # 2000 items / 1.0s
    assert r.stall_s == pytest.approx(2.0)
    assert r.timestamp == 0.0                  # server stamps on ingest
    # within the interval: gated; past it: flushed, window reset
    rep.observe_step(12, 0.4, items=800)
    assert rep.maybe_flush() is False
    clock["now"] += 6.0
    rep.observe_ttft(0.25)
    assert rep.maybe_flush() is True
    [r2] = client.batches[1]
    assert r2.step == 12
    assert r2.ttft_s == pytest.approx(0.25)
    assert r2.stall_s == 0.0                   # windows do not snowball
    assert rep.sent == 2


def test_goodput_reporter_ingests_end_to_end():
    """Reporter → APIServer.report_status → aggregator: the full emitter
    path without a scheduler."""
    from tpusched.apiserver import APIServer, Clientset
    from tpusched.jaxbridge.measure import GoodputReporter
    api = APIServer()
    agg = GoodputAggregator(publish=False)
    agg.attach(api)
    try:
        rep = GoodputReporter(Clientset(api), "default/e2e-0",
                              gang="default/e2e")
        rep.observe_step(1, 0.1, items=100)
        assert rep.flush() is True
        health = agg.gang_health("default/e2e")
        assert health["members_reporting"] == 1
        assert health["goodput"]["tokens"] == pytest.approx(1000.0)
        # the server stamped the report
        assert health["last_report_wall"] > 0
    finally:
        agg.detach()


def test_heartbeat_piggybacks_reports():
    """The zero-extra-round-trips path: reports ride the node heartbeat
    and fan out AFTER the liveness stamp lands; a fan-out blip is counted,
    never raised into the node agent."""
    from tpusched.apiserver import APIServer, Clientset
    from tpusched.testing.wrappers import make_node
    api = APIServer()
    from tpusched.apiserver import server as srv
    api.create(srv.NODES, make_node("hb-n0"))
    agg = GoodputAggregator(publish=False)
    agg.attach(api)
    try:
        cs = Clientset(api)
        cs.nodes.heartbeat("hb-n0", now=123.0, reports=[
            report("default/hb-0", "default/hb", throughput=10.0)])
        node = api.peek(srv.NODES, "/hb-n0")
        assert node.status.last_heartbeat_time == 123.0
        assert agg.gang_health("default/hb")["members_reporting"] == 1
        # a panicking sink must not break the heartbeat (or the report
        # batch delivery to OTHER sinks registered before it)
        def bad_sink(reports):
            raise RuntimeError("sink bug")
        api.add_status_sink(bad_sink)
        cs.nodes.heartbeat("hb-n0", now=124.0, reports=[
            report("default/hb-0", "default/hb", step=2, throughput=10.0)])
        assert api.peek(srv.NODES, "/hb-n0").status.last_heartbeat_time \
            == 124.0
        assert agg.gang_health("default/hb")["members"][0]["step"] == 2
    finally:
        agg.detach()
