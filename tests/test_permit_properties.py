"""Property-based permit-barrier laws (hypothesis stateful).

The _WaitingPod barrier is the synchronization point every gang (and
multislice set) admission rides: per-plugin pending entries, allow/reject
from arbitrary threads, a deadline sweeper, and exactly-once callbacks.
The laws pinned for ANY interleaving of allows/rejects/expiries:

  L1  a pod resolves at most once, and its callback fires exactly once
      with the SAME status wait() observers see;
  L2  allowing every pending plugin ⇒ Success; any reject ⇒ Unschedulable
      (first resolution wins; later verbs are no-ops);
  L3  an expiry resolves the pod only when some plugin's deadline truly
      passed (fake clock), and late allows/rejects cannot overwrite it;
  L4  get_pending_plugins never grows and only shrinks by allowed names.
"""
import time

import pytest

pytest.importorskip("hypothesis")

from hypothesis import settings
from hypothesis import strategies as st
from hypothesis.stateful import RuleBasedStateMachine, invariant, rule

from tpusched.fwk.runtime import _WaitingPod
from tpusched.testing import make_pod

PLUGINS = ("A", "B", "C")


class BarrierMachine(RuleBasedStateMachine):
    def __init__(self):
        super().__init__()
        # control monotonic time via a patched deadline table: timeouts
        # below are huge so only explicit expire calls can trip them
        self.wp = _WaitingPod(make_pod("p"), {p: 10_000.0 for p in PLUGINS})
        self.allowed = set()
        self.resolved_status = None     # model: first resolution
        self.callback_fires = []
        self.wp.add_done_callback(self.callback_fires.append)

    @rule(plugin=st.sampled_from(PLUGINS))
    def allow(self, plugin):
        self.wp.allow(plugin)
        if self.resolved_status is None:
            self.allowed.add(plugin)
            if self.allowed == set(PLUGINS):
                self.resolved_status = "success"

    @rule(plugin=st.sampled_from(PLUGINS))
    def reject(self, plugin):
        self.wp.reject(plugin, "nope")
        if self.resolved_status is None:
            self.resolved_status = "unschedulable"

    @rule()
    def expire_not_due(self):
        # now is far before every deadline: must be a no-op
        self.wp.expire_if_due(time.monotonic())

    @rule()
    def expire_due(self):
        # now is past every deadline: resolves (timeout) unless already done
        self.wp.expire_if_due(time.monotonic() + 20_000.0)
        if self.resolved_status is None:
            self.resolved_status = "unschedulable"

    @invariant()
    def exactly_once_and_consistent(self):
        # L1: never more than one callback fire
        assert len(self.callback_fires) <= 1
        if self.resolved_status is None:
            assert not self.callback_fires
            # L4: pending is exactly the never-allowed set
            assert set(self.wp.get_pending_plugins()) == \
                set(PLUGINS) - self.allowed
        else:
            # L1/L2/L3: resolution matches the model, callback fired once
            assert len(self.callback_fires) == 1
            status = self.callback_fires[0]
            assert self.wp.wait() is status     # wait() sees the same object
            if self.resolved_status == "success":
                assert status.is_success()
            else:
                assert status.is_unschedulable()


BarrierMachine.TestCase.settings = settings(max_examples=80,
                                            stateful_step_count=40,
                                            deadline=None)
TestPermitBarrier = BarrierMachine.TestCase
