"""Coscheduling gang tests — integration tier over the in-process cluster
(reference analog: test/integration/coscheduling_test.go) plus manager units
(pkg/coscheduling/core/core_test.go). BASELINE eval config #2: 8-pod gang on
an emulated v5e-8 pool."""
import time

from tpusched.api.resources import CPU, PODS, TPU
from tpusched.api.scheduling import PG_SCHEDULED
from tpusched.apiserver import server as srv
from tpusched.config.types import CoschedulingArgs
from tpusched.fwk import PluginProfile
from tpusched.plugins.coscheduling.core import check_cluster_resource
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, make_tpu_node)


def gang_profile(permit_wait_s=3, denied_s=1):
    """Coscheduling wiring per the reference's scheduler-config
    (manifests/coscheduling/scheduler-config.yaml:10-34) + TpuSlice."""
    return PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"],
        filter=["NodeUnschedulable", "NodeSelector", "NodeResourcesFit", "TpuSlice"],
        post_filter=["Coscheduling"],
        score=[("TpuSlice", 1)],
        reserve=["TpuSlice", "Coscheduling"],
        permit=["Coscheduling"],
        bind=["TpuSlice"],
        post_bind=["Coscheduling"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=permit_wait_s,
            denied_pg_expiration_time_seconds=denied_s)},
    )


def v5e8_nodes():
    # v5e-8 slice: 2 hosts × 4 chips
    return [make_tpu_node(f"v5e-host-{i}", accelerator="tpu-v5e", chips=4,
                          pool="v5e-8") for i in range(2)]


def test_8_pod_gang_schedules_atomically():
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes(v5e8_nodes())
        c.api.create(srv.POD_GROUPS, make_pod_group("jax-job", min_member=8))
        pods = [make_pod(f"w{i}", pod_group="jax-job", limits={TPU: 1})
                for i in range(8)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)
        pg = c.api.get(srv.POD_GROUPS, "default/jax-job")
        assert pg.status.phase == PG_SCHEDULED
        assert pg.status.scheduled == 8


def test_gang_all_or_nothing_when_capacity_short():
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes(v5e8_nodes())  # 8 chips
        c.api.create(srv.POD_GROUPS, make_pod_group("too-big", min_member=9))
        pods = [make_pod(f"w{i}", pod_group="too-big", limits={TPU: 1})
                for i in range(9)]
        c.create_pods(pods)
        # all-or-nothing: NOBODY binds even though 8 chips are free
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=2.0)


def test_gang_waits_for_enough_siblings():
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes(v5e8_nodes())
        c.api.create(srv.POD_GROUPS, make_pod_group("gang", min_member=3))
        first_two = [make_pod(f"w{i}", pod_group="gang", limits={TPU: 1})
                     for i in range(2)]
        c.create_pods(first_two)
        # sibling count < minMember → PreFilter rejects
        assert c.wait_for_pods_unscheduled([p.key for p in first_two], hold=0.6)
        third = make_pod("w2", pod_group="gang", limits={TPU: 1})
        c.create_pods([third])
        keys = [p.key for p in first_two] + [third.key]
        assert c.wait_for_pods_scheduled(keys, timeout=15)


def test_min_resources_gate_then_capacity_arrives():
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes([make_node("small", capacity={CPU: 2000, "pods": 10})])
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "needs-tpus", min_member=2, min_resources={TPU: 8}))
        pods = [make_pod(f"w{i}", pod_group="needs-tpus", limits={TPU: 4})
                for i in range(2)]
        c.create_pods(pods)
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=1.0)
        c.add_nodes(v5e8_nodes())
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)


def test_quorum_gap_grace_lets_stragglers_catch_up():
    """≤10% gap: 9/10 assigned must NOT be mass-rejected; when the blocker
    frees a chip the straggler completes the gang (coscheduling.go:156-162)."""
    with TestCluster(profile=gang_profile(permit_wait_s=20)) as c:
        nodes = [make_tpu_node(f"h{i}", chips=4) for i in range(3)]  # 12 chips
        c.add_nodes(nodes)
        blockers = [make_pod(f"blocker-{i}", limits={TPU: 1}) for i in range(3)]
        c.create_pods(blockers)
        assert c.wait_for_pods_scheduled([b.key for b in blockers])
        # 10-member gang needs 10 of the 9 remaining chips
        c.api.create(srv.POD_GROUPS, make_pod_group("gang", min_member=10))
        pods = [make_pod(f"w{i}", pod_group="gang", limits={TPU: 1})
                for i in range(10)]
        c.create_pods(pods)
        time.sleep(1.0)
        bound = [p for p in pods if c.pod_scheduled(p.key)]
        assert len(bound) == 0  # waiting in Permit, not bound
        # free one chip → straggler fits → quorum completes
        c.api.delete(srv.PODS, blockers[0].key)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)


def test_permit_timeout_rejects_gang():
    with TestCluster(profile=gang_profile(permit_wait_s=1, denied_s=1)) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        c.api.create(srv.POD_GROUPS, make_pod_group("gang", min_member=5))
        # 5 members but only 4 chips: 4 wait in Permit, the 5th can't fit
        pods = [make_pod(f"w{i}", pod_group="gang", limits={TPU: 1})
                for i in range(5)]
        c.create_pods(pods)
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=2.5)
        # chips must all be free again after the gang rejection (no leak)
        probe = make_pod("probe", limits={TPU: 4})
        c.create_pods([probe])
        assert c.wait_for_pods_scheduled([probe.key], timeout=15)


# -- manager unit tests -------------------------------------------------------

def test_check_cluster_resource_does_not_mutate_request():
    from tpusched.fwk.nodeinfo import NodeInfo
    n = make_tpu_node("n1", chips=4)
    infos = [NodeInfo(n)]
    request = {TPU: 2, PODS: 2}
    snapshot = dict(request)
    assert check_cluster_resource(infos, request, "default/pg") is None
    assert request == snapshot  # fixed quirk: reference mutates its input
    gap = check_cluster_resource(infos, {TPU: 99}, "default/pg")
    assert gap is not None and "google.com/tpu" in gap


def test_check_cluster_resource_ignores_own_gang_pods():
    """A retrying gang must not be blocked by its own resident pods
    (getNodeResource, core.go:349-382)."""
    from tpusched.fwk.nodeinfo import NodeInfo
    n = make_tpu_node("n1", chips=4)
    own = make_pod("own", pod_group="pg", limits={TPU: 4}, node_name="n1")
    infos = [NodeInfo(n, [own])]
    assert check_cluster_resource(infos, {TPU: 4}, "default/pg") is None
    assert check_cluster_resource(infos, {TPU: 4}, "default/other") is not None


def test_lightweight_label_only_gang():
    """KEP-2: CRD-less gang — quorum from the min-available label; no
    PodGroup CR exists at any point."""
    from tpusched.api.scheduling import MIN_AVAILABLE_LABEL
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes(v5e8_nodes())
        lbl = {MIN_AVAILABLE_LABEL: "3"}
        first_two = [make_pod(f"lw{i}", pod_group="lwgang", limits={TPU: 1},
                              labels=lbl) for i in range(2)]
        c.create_pods(first_two)
        assert c.wait_for_pods_unscheduled([p.key for p in first_two], hold=0.6)
        c.create_pods([make_pod("lw2", pod_group="lwgang", limits={TPU: 1},
                                labels=lbl)])
        keys = [p.key for p in first_two] + ["default/lw2"]
        assert c.wait_for_pods_scheduled(keys, timeout=15)
        assert c.api.try_get(srv.POD_GROUPS, "default/lwgang") is None


def test_label_without_min_available_stays_pending():
    """A group label naming a CR that doesn't exist (and no min-available
    label) is held at Permit — reference parity: PodGroupNotFound ⇒
    Unschedulable (coscheduling.go:191-192)."""
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes(v5e8_nodes())
        p = make_pod("solo", pod_group="ghost-group", limits={TPU: 1})
        c.create_pods([p])
        assert c.wait_for_pods_unscheduled([p.key], hold=0.8)


def test_lightweight_gang_shares_synthesized_group_and_records_status():
    """KEP-2 follow-ups: all members share ONE synthesized PodGroup (same
    QueueSort timestamp), and post_bind tracks status on it (the north-star
    metric fires for CRD-less gangs too)."""
    from tpusched.api.scheduling import MIN_AVAILABLE_LABEL
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes(v5e8_nodes())
        lbl = {MIN_AVAILABLE_LABEL: "3"}
        pods = [make_pod(f"m{i}", pod_group="memo-gang", limits={TPU: 1},
                         labels=lbl) for i in range(3)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)
        mgr = c.scheduler.framework.plugins["Coscheduling"].pg_mgr
        pg1 = mgr.get_pod_group(c.pod(pods[0].key))[1]
        pg2 = mgr.get_pod_group(c.pod(pods[1].key))[1]
        assert pg1 is pg2
        assert pg1.status.scheduled == 3
        assert pg1.status.phase == PG_SCHEDULED


def test_gang_admitted_after_min_member_lowered():
    """A pending 3-member gang with minMember=4 becomes schedulable when the
    PodGroup is resized down — the PG UPDATE cluster event must requeue the
    members (events_to_register: PodGroup add|update)."""
    with TestCluster(profile=gang_profile()) as c:
        c.add_nodes(v5e8_nodes())
        c.api.create(srv.POD_GROUPS, make_pod_group("resizable", min_member=4))
        pods = [make_pod(f"w{i}", pod_group="resizable", limits={TPU: 1})
                for i in range(3)]
        c.create_pods(pods)
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=1.2)
        c.api.patch(srv.POD_GROUPS, "default/resizable",
                    lambda pg: setattr(pg.spec, "min_member", 3))
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)
        got = c.api.get(srv.POD_GROUPS, "default/resizable")
        assert got.status.scheduled == 3


def test_cordon_mid_admission_releases_chips_after_drain():
    """Members park at Permit, the pool is cordoned mid-admission, the gang
    is rejected and then deleted (operator drain): every assumed chip must
    be back — no leaked cache reservations from the interrupted admission.
    (While an under-capacity gang LIVES it keeps retrying and transiently
    re-assuming chips — upstream-parity optimism — so the deterministic
    no-leak probe requires the drain.)"""
    with TestCluster(profile=gang_profile(permit_wait_s=2, denied_s=1)) as c:
        nodes = v5e8_nodes()
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS, make_pod_group("doomed", min_member=3))
        pods = [make_pod(f"w{i}", pod_group="doomed", limits={TPU: 4})
                for i in range(3)]   # 12 chips > 8 available: 3rd can't fit
        c.create_pods(pods)
        time.sleep(0.8)              # two members parked at Permit
        for n in nodes:
            c.api.patch(srv.NODES, n.meta.key,
                        lambda live: setattr(live.spec, "unschedulable", True))
        time.sleep(2.5)              # permit deadline passes under cordon
        for p in pods:               # operator drains the doomed gang
            c.api.delete(srv.PODS, p.key)
        for n in nodes:
            c.api.patch(srv.NODES, n.meta.key,
                        lambda live: setattr(live.spec, "unschedulable", False))
        probes = [make_pod(f"probe{i}", limits={TPU: 4}) for i in range(2)]
        c.create_pods(probes)        # needs ALL 8 chips: any leak blocks it
        assert c.wait_for_pods_scheduled([p.key for p in probes], timeout=15)
