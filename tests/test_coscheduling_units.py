"""Direct unit tables for the Coscheduling plugin and PodGroupManager —
queue-sort ordering, PreFilter gating, Permit verdicts, wait-time
precedence. The reference's table style in
/root/reference/pkg/coscheduling/coscheduling_test.go (TestLess,
TestPermit, TestPostFilter) and pkg/coscheduling/core/core_test.go
(TestPreFilter); e2e gang behavior lives in tests/test_coscheduling.py."""
import time

from tpusched.api.resources import CPU, TPU
from tpusched.api.scheduling import MIN_AVAILABLE_LABEL
from tpusched.apiserver import APIServer
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.fwk import CycleState, PODS_TO_ACTIVATE_KEY, PodsToActivate
from tpusched.plugins.coscheduling.core import (POD_GROUP_NOT_FOUND,
                                                POD_GROUP_NOT_SPECIFIED,
                                                SUCCESS, WAIT,
                                                get_wait_time_duration)
from tpusched.sched.queue import QueuedPodInfo
from tpusched.testing import make_pod, make_pod_group, make_tpu_node
from tpusched.testing.harness import new_test_framework


def gang_framework(pod_groups=(), pods=(), nodes=(), permit_wait_s=60,
                   denied_s=20):
    api = APIServer()
    for pg in pod_groups:
        api.create(srv.POD_GROUPS, pg)
    fw, handle, api = new_test_framework(
        tpu_gang_profile(permit_wait_s=permit_wait_s, denied_s=denied_s),
        nodes=nodes, pods=pods, api=api)
    return fw, fw.plugins["Coscheduling"], handle, api


def qpi(pod, ts):
    info = QueuedPodInfo(pod, clock=lambda: ts)
    return info


# -- QueueSort Less (coscheduling.go:112-124) --------------------------------

def test_less_priority_wins_over_everything():
    fw, cs, _, _ = gang_framework()
    hi = qpi(make_pod("hi", priority=10), ts=200.0)
    lo = qpi(make_pod("lo", priority=1), ts=100.0)  # older, still loses
    assert cs.less(hi, lo)
    assert not cs.less(lo, hi)


def test_less_group_creation_time_breaks_priority_tie():
    old_pg = make_pod_group("old-gang", min_member=2)
    old_pg.meta.creation_timestamp = 100.0
    new_pg = make_pod_group("new-gang", min_member=2)
    new_pg.meta.creation_timestamp = 200.0
    fw, cs, _, _ = gang_framework(pod_groups=[old_pg, new_pg])
    # pod of the OLDER group sorts first even if the pod itself enqueued later
    a = qpi(make_pod("a", pod_group="old-gang"), ts=500.0)
    b = qpi(make_pod("b", pod_group="new-gang"), ts=50.0)
    assert cs.less(a, b)
    assert not cs.less(b, a)


def test_less_groupless_pod_uses_initial_attempt_time():
    fw, cs, _, _ = gang_framework()
    early = qpi(make_pod("early"), ts=10.0)
    late = qpi(make_pod("late"), ts=20.0)
    assert cs.less(early, late)
    assert not cs.less(late, early)


def test_less_same_group_members_tie_break_by_key():
    pg = make_pod_group("gang", min_member=2)
    pg.meta.creation_timestamp = 100.0
    fw, cs, _, _ = gang_framework(pod_groups=[pg])
    # same group ⇒ same timestamp ⇒ name decides: gang drains contiguously
    a = qpi(make_pod("a", pod_group="gang"), ts=500.0)
    b = qpi(make_pod("b", pod_group="gang"), ts=50.0)
    assert cs.less(a, b)
    assert not cs.less(b, a)


def test_less_mixed_gang_vs_groupless_compares_timestamps():
    pg = make_pod_group("gang", min_member=2)
    pg.meta.creation_timestamp = 100.0
    fw, cs, _, _ = gang_framework(pod_groups=[pg])
    member = qpi(make_pod("m", pod_group="gang"), ts=999.0)  # PG ts 100 rules
    loner_older = qpi(make_pod("loner-old"), ts=50.0)
    loner_newer = qpi(make_pod("loner-new"), ts=150.0)
    assert cs.less(loner_older, member)
    assert cs.less(member, loner_newer)


# -- PreFilter gating (core.go:149-196) --------------------------------------

def test_pre_filter_groupless_pod_passes():
    fw, cs, _, _ = gang_framework()
    assert cs.pre_filter(CycleState(), make_pod("solo")).is_success()


def test_pre_filter_rejects_below_min_member():
    pg = make_pod_group("gang", min_member=3)
    fw, cs, _, api = gang_framework(pod_groups=[pg])
    members = [make_pod(f"m{i}", pod_group="gang") for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    st = cs.pre_filter(CycleState(), members[0])
    assert st.is_unschedulable()
    assert "cannot find enough sibling pods" in st.message()


def test_pre_filter_denied_group_fast_fails_until_ttl():
    pg = make_pod_group("gang", min_member=1)
    fw, cs, _, api = gang_framework(pod_groups=[pg], denied_s=1)
    pod = make_pod("m0", pod_group="gang")
    api.create(srv.PODS, pod)
    assert cs.pre_filter(CycleState(), pod).is_success()
    cs.pg_mgr.add_denied_pod_group("default/gang")
    st = cs.pre_filter(CycleState(), pod)
    assert st.is_unschedulable()
    assert "denied-PodGroup expiration window" in st.message()
    time.sleep(1.1)  # TTL expiry reopens the gate
    assert cs.pre_filter(CycleState(), pod).is_success()


def test_pre_filter_min_resources_cluster_dry_run():
    """MinResources gate subtracts other pods' usage but ignores the group's
    own members (getNodeResource, core.go:349-382)."""
    pg = make_pod_group("gang", min_member=2, min_resources={TPU: 8})
    nodes = [make_tpu_node("h0", chips=4), make_tpu_node("h1", chips=4)]
    fw, cs, _, api = gang_framework(pod_groups=[pg], nodes=nodes)
    members = [make_pod(f"m{i}", pod_group="gang", limits={TPU: 4})
               for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    assert cs.pre_filter(CycleState(), members[0]).is_success()


def test_pre_filter_min_resources_shortfall_denies_group():
    pg = make_pod_group("gang", min_member=2, min_resources={TPU: 16})
    nodes = [make_tpu_node("h0", chips=4), make_tpu_node("h1", chips=4)]
    fw, cs, _, api = gang_framework(pod_groups=[pg], nodes=nodes)
    members = [make_pod(f"m{i}", pod_group="gang", limits={TPU: 8})
               for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    st = cs.pre_filter(CycleState(), members[0])
    assert st.is_unschedulable()
    # shortfall also primes the denied cache: the sibling fast-fails
    st2 = cs.pre_filter(CycleState(), members[1])
    assert "denied-PodGroup expiration window" in st2.message()


def test_pre_filter_permitted_group_memoizes_dry_run():
    """Once the capacity dry-run passes, the group is 'permitted' for the
    schedule timeout and the dry-run is skipped — capacity consumed by the
    gang's own landing members must not flip the gate mid-admission
    (core.go:168-170)."""
    pg = make_pod_group("gang", min_member=2, min_resources={TPU: 8})
    nodes = [make_tpu_node("h0", chips=4), make_tpu_node("h1", chips=4)]
    fw, cs, handle, api = gang_framework(pod_groups=[pg], nodes=nodes)
    members = [make_pod(f"m{i}", pod_group="gang", limits={TPU: 4})
               for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    assert cs.pre_filter(CycleState(), members[0]).is_success()
    # an unrelated pod eats the whole cluster in the snapshot
    hog = make_pod("hog", namespace="other", limits={TPU: 8}, node_name="h0")
    from tpusched.fwk import Snapshot
    handle.set_snapshot(Snapshot(nodes=nodes, pods=[hog]))
    # memoized: sibling still passes without re-running the dry-run
    assert cs.pre_filter(CycleState(), members[1]).is_success()
    cs.pg_mgr.delete_permitted_pod_group("default/gang")
    assert cs.pre_filter(CycleState(), members[1]).is_unschedulable()


# -- Permit verdicts (core.go:199-216) ---------------------------------------

def test_permit_verdict_table():
    pg = make_pod_group("gang", min_member=2)
    node = make_tpu_node("h0", chips=8)
    fw, cs, handle, api = gang_framework(pod_groups=[pg], nodes=[node])
    mgr = cs.pg_mgr

    assert mgr.permit(make_pod("solo")) == POD_GROUP_NOT_SPECIFIED
    # label names a group with no CR and no min-available ⇒ not found
    orphan = make_pod("orphan", pod_group="ghost")
    assert mgr.permit(orphan) == POD_GROUP_NOT_FOUND

    member = make_pod("m0", pod_group="gang")
    assert mgr.permit(member) == WAIT  # 0 assigned + 1 < 2

    # one sibling assumed onto a node ⇒ assigned(1) + 1 ≥ 2
    from tpusched.fwk import Snapshot
    bound = make_pod("m1", pod_group="gang", node_name="h0")
    handle.set_snapshot(Snapshot(nodes=[node], pods=[bound]))
    assert mgr.permit(member) == SUCCESS


def test_permit_synthesized_group_reaches_quorum():
    """KEP-2 lightweight gang: min-available label alone drives the quorum."""
    node = make_tpu_node("h0", chips=8)
    fw, cs, handle, api = gang_framework(nodes=[node])
    labels = {MIN_AVAILABLE_LABEL: "2"}
    member = make_pod("m0", pod_group="lite", labels=labels)
    assert cs.pg_mgr.permit(member) == WAIT
    from tpusched.fwk import Snapshot
    bound = make_pod("m1", pod_group="lite", labels=labels, node_name="h0")
    handle.set_snapshot(Snapshot(nodes=[node], pods=[bound]))
    assert cs.pg_mgr.permit(member) == SUCCESS


def test_activate_siblings_stashes_other_members():
    pg = make_pod_group("gang", min_member=3)
    fw, cs, _, api = gang_framework(pod_groups=[pg])
    members = [make_pod(f"m{i}", pod_group="gang") for i in range(3)]
    for m in members:
        api.create(srv.PODS, m)
    state = CycleState()
    stash = PodsToActivate()
    state.write(PODS_TO_ACTIVATE_KEY, stash)
    cs.pg_mgr.activate_siblings(members[0], state)
    assert sorted(stash.map) == ["default/m1", "default/m2"]


# -- PostFilter mass rejection (coscheduling.go:140-176, TestPostFilter) ------

def park_in_permit(fw, pods, node="h0"):
    """Drive each pod through run_permit_plugins so it parks as a waitingPod
    (the state PostFilter's mass-reject iterates over)."""
    for p in pods:
        st = fw.run_permit_plugins(CycleState(), p, node)
        assert st.is_wait(), f"{p.key} did not park: {st.message()}"


def permit_rejected(fw, pod):
    """True iff the parked pod's permit barrier has resolved (rejection sets
    the status; the entry leaves the map only when a binding-cycle waiter
    collects it — deadline() is None exactly once resolved)."""
    wp = fw.get_waiting_pod(pod.meta.uid)
    assert wp is not None, f"{pod.key} never parked at Permit"
    return wp.deadline() is None


def test_post_filter_pod_without_group_is_noop():
    fw, cs, _, _ = gang_framework()
    _, st = cs.post_filter(CycleState(), make_pod("solo"), {})
    assert st.is_unschedulable()
    assert "can not find pod group" in st.message()


def test_post_filter_enough_assigned_does_not_reject():
    """assigned ≥ minMember ⇒ the quorum is already satisfied; waiting
    members must be left alone (coscheduling_test.go:385 'enough pods
    assigned, do not reject all')."""
    from tpusched.fwk import Snapshot
    pg = make_pod_group("gang", min_member=3)
    node = make_tpu_node("h0", chips=8)
    fw, cs, handle, api = gang_framework(pod_groups=[pg], nodes=[node])
    waiter = make_pod("w", pod_group="gang")
    park_in_permit(fw, [waiter])  # 0 assigned + 1 < 3 ⇒ parks
    # three siblings land between the park and the straggler's failure
    bound = [make_pod(f"b{i}", pod_group="gang", node_name="h0")
             for i in range(3)]
    handle.set_snapshot(Snapshot(nodes=[node], pods=bound))
    straggler = make_pod("s", pod_group="gang")
    _, st = cs.post_filter(CycleState(), straggler, {})
    assert st.is_unschedulable()
    assert not permit_rejected(fw, waiter)  # still parked, unresolved
    assert "default/gang" not in cs.pg_mgr.last_denied_pg


def test_post_filter_small_quorum_gap_spares_gang():
    """9/10 assigned (10% gap) ⇒ grace: no mass rejection."""
    from tpusched.fwk import Snapshot
    pg = make_pod_group("gang", min_member=10)
    node = make_tpu_node("h0", chips=16)
    fw, cs, handle, api = gang_framework(pod_groups=[pg], nodes=[node])
    waiter = make_pod("w", pod_group="gang")
    park_in_permit(fw, [waiter])  # 0 + 1 < 10 ⇒ parks
    bound = [make_pod(f"b{i}", pod_group="gang", node_name="h0")
             for i in range(9)]
    handle.set_snapshot(Snapshot(nodes=[node], pods=bound))
    _, st = cs.post_filter(CycleState(), make_pod("s", pod_group="gang"), {})
    assert st.is_unschedulable()
    assert not permit_rejected(fw, waiter)
    assert "default/gang" not in cs.pg_mgr.last_denied_pg


def test_post_filter_mass_rejects_waiting_siblings_and_denies_group():
    """Filter failure with a real quorum gap ⇒ every waiting sibling is
    rejected, the group enters the denied cache, and its permitted
    memoization is dropped (coscheduling_test.go:391 'reject all pods')."""
    pg = make_pod_group("gang", min_member=4)
    node = make_tpu_node("h0", chips=8)
    fw, cs, handle, api = gang_framework(pod_groups=[pg], nodes=[node])
    waiters = [make_pod(f"w{i}", pod_group="gang") for i in range(2)]
    park_in_permit(fw, waiters)
    outsider = make_pod("other", pod_group="other-gang")
    api.create(srv.POD_GROUPS, make_pod_group("other-gang", min_member=2))
    park_in_permit(fw, [outsider])

    cs.pg_mgr.permitted_pg.set("default/gang")
    _, st = cs.post_filter(CycleState(), make_pod("s", pod_group="gang"), {})
    assert st.is_unschedulable()
    assert "gets rejected due to Pod" in st.message()
    for w in waiters:
        assert permit_rejected(fw, w)
        assert fw.get_waiting_pod(w.meta.uid).wait().is_unschedulable()
    # other groups' waiting pods are untouched
    assert not permit_rejected(fw, outsider)
    assert "default/gang" in cs.pg_mgr.last_denied_pg
    assert "default/gang" not in cs.pg_mgr.permitted_pg  # memoization dropped


def test_post_filter_rejection_scoped_to_namespace():
    """Same group name in another namespace must not be collateral damage."""
    pg = make_pod_group("gang", min_member=4)
    pg_other = make_pod_group("gang", namespace="team-b", min_member=4)
    node = make_tpu_node("h0", chips=8)
    fw, cs, handle, api = gang_framework(pod_groups=[pg, pg_other],
                                         nodes=[node])
    ours = make_pod("w0", pod_group="gang")
    theirs = make_pod("w1", namespace="team-b", pod_group="gang")
    park_in_permit(fw, [ours, theirs])
    _, st = cs.post_filter(CycleState(), make_pod("s", pod_group="gang"), {})
    assert st.is_unschedulable()
    assert permit_rejected(fw, ours)
    assert fw.get_waiting_pod(ours.meta.uid).wait().is_unschedulable()
    assert not permit_rejected(fw, theirs)
    assert "default/gang" in cs.pg_mgr.last_denied_pg
    assert "team-b/gang" not in cs.pg_mgr.last_denied_pg


# -- PostBind phase machine (core.go:220-252, TestPostBind) -------------------

def test_post_bind_tracks_scheduling_then_scheduled():
    from tpusched.api.scheduling import PG_SCHEDULED, PG_SCHEDULING
    pg = make_pod_group("gang", min_member=2)
    fw, cs, _, api = gang_framework(pod_groups=[pg])
    members = [make_pod(f"m{i}", pod_group="gang") for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    cs.post_bind(CycleState(), members[0], "h0")
    # partial progress coalesces per flush window (ISSUE 14): the patch
    # shows after a drain (any later manager activity, or close())
    cs.pg_mgr.flush_status()
    got = api.get(srv.POD_GROUPS, "default/gang")
    assert got.status.scheduled == 1
    assert got.status.phase == PG_SCHEDULING
    assert got.status.schedule_start_time is not None
    # quorum completion flushes INLINE — no drain needed
    cs.post_bind(CycleState(), members[1], "h0")
    got = api.get(srv.POD_GROUPS, "default/gang")
    assert got.status.scheduled == 2
    assert got.status.phase == PG_SCHEDULED


def test_post_bind_groupless_pod_is_noop():
    fw, cs, _, api = gang_framework()
    cs.post_bind(CycleState(), make_pod("solo"), "h0")  # must not raise


# -- wait-time precedence (util/podgroup.go:53-76) ----------------------------

def test_wait_time_precedence():
    pg = make_pod_group("g", schedule_timeout_seconds=10)
    assert get_wait_time_duration(pg, 40.0) == 10.0       # PG.spec first
    pg_unset = make_pod_group("g2")
    assert get_wait_time_duration(pg_unset, 40.0) == 40.0  # then plugin arg
    assert get_wait_time_duration(None, 40.0) == 40.0
    assert get_wait_time_duration(pg_unset, 0.0) == 60.0   # then 60s default
    assert get_wait_time_duration(None, 0.0) == 60.0


def test_denied_window_not_extended_by_repeat_denials():
    """go-cache Add semantics (core.go:268-270): the denial window runs from
    the FIRST denial; re-denials during event-driven retries must not extend
    it, or a retry storm pins the gang denied forever."""
    from tpusched.util.ttlcache import TTLCache
    now = [0.0]
    cache = TTLCache(1.0, clock=lambda: now[0])
    assert cache.add("pg")
    now[0] = 0.9
    assert not cache.add("pg")      # still fresh: not refreshed
    assert "pg" in cache
    now[0] = 1.1                    # original expiry passed despite re-add
    assert "pg" not in cache
    assert cache.add("pg")          # expired ⇒ add succeeds again


# -- PG status patch batching (ISSUE 14 satellite) ----------------------------

def _patch_counter(api):
    """Count PodGroup patch round trips through the store."""
    calls = {"n": 0}
    orig = api.update

    def counting_update(kind, obj, **kw):
        if kind == srv.POD_GROUPS:
            calls["n"] += 1
        return orig(kind, obj, **kw)
    api.update = counting_update
    return calls


def test_post_bind_batches_partial_progress_into_one_patch():
    """Partial-progress increments inside the flush window coalesce into
    ONE PG patch; quorum completion flushes INLINE (PG_SCHEDULED lands at
    the real completion instant, north-star clock intact)."""
    from tpusched.api.scheduling import PG_SCHEDULED
    pg = make_pod_group("gang", min_member=4)
    fw, cs, handle, api = gang_framework(pod_groups=[pg])
    mgr = cs.pg_mgr
    mgr._status_flush_s = 60.0            # window never lapses in-test
    members = [make_pod(f"m{i}", pod_group="gang") for i in range(4)]
    for p in members:
        api.create(srv.PODS, p)
    # three partial binds: all pending, ZERO patches yet
    for p in members[:3]:
        mgr.post_bind(p, "h0")
    live = api.try_get(srv.POD_GROUPS, "default/gang")
    assert live.status.scheduled == 0
    # the quorum-completing bind flushes the whole batch inline: one
    # patch carrying all four increments
    mgr.post_bind(members[3], "h0")
    live = api.try_get(srv.POD_GROUPS, "default/gang")
    assert live.status.scheduled == 4
    assert live.status.phase == PG_SCHEDULED
    assert mgr._status_pending == {}


def test_post_bind_flush_zero_patches_per_bind():
    """pg_status_flush_seconds=0 keeps the pre-14 per-bind patch (the
    deterministic-replay arm)."""
    pg = make_pod_group("gang", min_member=4)
    fw, cs, handle, api = gang_framework(pod_groups=[pg])
    mgr = cs.pg_mgr
    mgr._status_flush_s = 0.0
    m = make_pod("m0", pod_group="gang")
    api.create(srv.PODS, m)
    mgr.post_bind(m, "h0")
    assert api.try_get(srv.POD_GROUPS, "default/gang").status.scheduled == 1


def test_post_bind_residue_flushes_on_window_and_close():
    """A gang whose binds stop short of quorum must still surface its
    partial progress: the window flush (piggybacked on any later manager
    activity) and plugin close() both drain the residue."""
    pg = make_pod_group("gang", min_member=4)
    fw, cs, handle, api = gang_framework(pod_groups=[pg])
    mgr = cs.pg_mgr
    mgr._status_flush_s = 0.001
    m = make_pod("m0", pod_group="gang")
    api.create(srv.PODS, m)
    mgr.post_bind(m, "h0")
    # under-quorum: batched, not yet patched (or already window-flushed —
    # both legal; drive the due-flush deterministically)
    time.sleep(0.002)
    mgr.flush_status_if_due()
    assert api.try_get(srv.POD_GROUPS, "default/gang").status.scheduled == 1
    # close() drains anything still pending
    mgr._status_flush_s = 60.0
    m2 = make_pod("m1", pod_group="gang")
    api.create(srv.PODS, m2)
    mgr.post_bind(m2, "h0")
    cs.close()
    assert api.try_get(srv.POD_GROUPS, "default/gang").status.scheduled == 2
