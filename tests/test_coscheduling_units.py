"""Direct unit tables for the Coscheduling plugin and PodGroupManager —
queue-sort ordering, PreFilter gating, Permit verdicts, wait-time
precedence. The reference's table style in
/root/reference/pkg/coscheduling/coscheduling_test.go (TestLess,
TestPermit, TestPostFilter) and pkg/coscheduling/core/core_test.go
(TestPreFilter); e2e gang behavior lives in tests/test_coscheduling.py."""
import time

from tpusched.api.resources import CPU, TPU
from tpusched.api.scheduling import MIN_AVAILABLE_LABEL
from tpusched.apiserver import APIServer
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.fwk import CycleState, PODS_TO_ACTIVATE_KEY, PodsToActivate
from tpusched.plugins.coscheduling.core import (POD_GROUP_NOT_FOUND,
                                                POD_GROUP_NOT_SPECIFIED,
                                                SUCCESS, WAIT,
                                                get_wait_time_duration)
from tpusched.sched.queue import QueuedPodInfo
from tpusched.testing import make_pod, make_pod_group, make_tpu_node
from tpusched.testing.harness import new_test_framework


def gang_framework(pod_groups=(), pods=(), nodes=(), permit_wait_s=60,
                   denied_s=20):
    api = APIServer()
    for pg in pod_groups:
        api.create(srv.POD_GROUPS, pg)
    fw, handle, api = new_test_framework(
        tpu_gang_profile(permit_wait_s=permit_wait_s, denied_s=denied_s),
        nodes=nodes, pods=pods, api=api)
    return fw, fw.plugins["Coscheduling"], handle, api


def qpi(pod, ts):
    info = QueuedPodInfo(pod, clock=lambda: ts)
    return info


# -- QueueSort Less (coscheduling.go:112-124) --------------------------------

def test_less_priority_wins_over_everything():
    fw, cs, _, _ = gang_framework()
    hi = qpi(make_pod("hi", priority=10), ts=200.0)
    lo = qpi(make_pod("lo", priority=1), ts=100.0)  # older, still loses
    assert cs.less(hi, lo)
    assert not cs.less(lo, hi)


def test_less_group_creation_time_breaks_priority_tie():
    old_pg = make_pod_group("old-gang", min_member=2)
    old_pg.meta.creation_timestamp = 100.0
    new_pg = make_pod_group("new-gang", min_member=2)
    new_pg.meta.creation_timestamp = 200.0
    fw, cs, _, _ = gang_framework(pod_groups=[old_pg, new_pg])
    # pod of the OLDER group sorts first even if the pod itself enqueued later
    a = qpi(make_pod("a", pod_group="old-gang"), ts=500.0)
    b = qpi(make_pod("b", pod_group="new-gang"), ts=50.0)
    assert cs.less(a, b)
    assert not cs.less(b, a)


def test_less_groupless_pod_uses_initial_attempt_time():
    fw, cs, _, _ = gang_framework()
    early = qpi(make_pod("early"), ts=10.0)
    late = qpi(make_pod("late"), ts=20.0)
    assert cs.less(early, late)
    assert not cs.less(late, early)


def test_less_same_group_members_tie_break_by_key():
    pg = make_pod_group("gang", min_member=2)
    pg.meta.creation_timestamp = 100.0
    fw, cs, _, _ = gang_framework(pod_groups=[pg])
    # same group ⇒ same timestamp ⇒ name decides: gang drains contiguously
    a = qpi(make_pod("a", pod_group="gang"), ts=500.0)
    b = qpi(make_pod("b", pod_group="gang"), ts=50.0)
    assert cs.less(a, b)
    assert not cs.less(b, a)


def test_less_mixed_gang_vs_groupless_compares_timestamps():
    pg = make_pod_group("gang", min_member=2)
    pg.meta.creation_timestamp = 100.0
    fw, cs, _, _ = gang_framework(pod_groups=[pg])
    member = qpi(make_pod("m", pod_group="gang"), ts=999.0)  # PG ts 100 rules
    loner_older = qpi(make_pod("loner-old"), ts=50.0)
    loner_newer = qpi(make_pod("loner-new"), ts=150.0)
    assert cs.less(loner_older, member)
    assert cs.less(member, loner_newer)


# -- PreFilter gating (core.go:149-196) --------------------------------------

def test_pre_filter_groupless_pod_passes():
    fw, cs, _, _ = gang_framework()
    assert cs.pre_filter(CycleState(), make_pod("solo")).is_success()


def test_pre_filter_rejects_below_min_member():
    pg = make_pod_group("gang", min_member=3)
    fw, cs, _, api = gang_framework(pod_groups=[pg])
    members = [make_pod(f"m{i}", pod_group="gang") for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    st = cs.pre_filter(CycleState(), members[0])
    assert st.is_unschedulable()
    assert "cannot find enough sibling pods" in st.message()


def test_pre_filter_denied_group_fast_fails_until_ttl():
    pg = make_pod_group("gang", min_member=1)
    fw, cs, _, api = gang_framework(pod_groups=[pg], denied_s=1)
    pod = make_pod("m0", pod_group="gang")
    api.create(srv.PODS, pod)
    assert cs.pre_filter(CycleState(), pod).is_success()
    cs.pg_mgr.add_denied_pod_group("default/gang")
    st = cs.pre_filter(CycleState(), pod)
    assert st.is_unschedulable()
    assert "denied-PodGroup expiration window" in st.message()
    time.sleep(1.1)  # TTL expiry reopens the gate
    assert cs.pre_filter(CycleState(), pod).is_success()


def test_pre_filter_min_resources_cluster_dry_run():
    """MinResources gate subtracts other pods' usage but ignores the group's
    own members (getNodeResource, core.go:349-382)."""
    pg = make_pod_group("gang", min_member=2, min_resources={TPU: 8})
    nodes = [make_tpu_node("h0", chips=4), make_tpu_node("h1", chips=4)]
    fw, cs, _, api = gang_framework(pod_groups=[pg], nodes=nodes)
    members = [make_pod(f"m{i}", pod_group="gang", limits={TPU: 4})
               for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    assert cs.pre_filter(CycleState(), members[0]).is_success()


def test_pre_filter_min_resources_shortfall_denies_group():
    pg = make_pod_group("gang", min_member=2, min_resources={TPU: 16})
    nodes = [make_tpu_node("h0", chips=4), make_tpu_node("h1", chips=4)]
    fw, cs, _, api = gang_framework(pod_groups=[pg], nodes=nodes)
    members = [make_pod(f"m{i}", pod_group="gang", limits={TPU: 8})
               for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    st = cs.pre_filter(CycleState(), members[0])
    assert st.is_unschedulable()
    # shortfall also primes the denied cache: the sibling fast-fails
    st2 = cs.pre_filter(CycleState(), members[1])
    assert "denied-PodGroup expiration window" in st2.message()


def test_pre_filter_permitted_group_memoizes_dry_run():
    """Once the capacity dry-run passes, the group is 'permitted' for the
    schedule timeout and the dry-run is skipped — capacity consumed by the
    gang's own landing members must not flip the gate mid-admission
    (core.go:168-170)."""
    pg = make_pod_group("gang", min_member=2, min_resources={TPU: 8})
    nodes = [make_tpu_node("h0", chips=4), make_tpu_node("h1", chips=4)]
    fw, cs, handle, api = gang_framework(pod_groups=[pg], nodes=nodes)
    members = [make_pod(f"m{i}", pod_group="gang", limits={TPU: 4})
               for i in range(2)]
    for m in members:
        api.create(srv.PODS, m)
    assert cs.pre_filter(CycleState(), members[0]).is_success()
    # an unrelated pod eats the whole cluster in the snapshot
    hog = make_pod("hog", namespace="other", limits={TPU: 8}, node_name="h0")
    from tpusched.fwk import Snapshot
    handle.set_snapshot(Snapshot(nodes=nodes, pods=[hog]))
    # memoized: sibling still passes without re-running the dry-run
    assert cs.pre_filter(CycleState(), members[1]).is_success()
    cs.pg_mgr.delete_permitted_pod_group("default/gang")
    assert cs.pre_filter(CycleState(), members[1]).is_unschedulable()


# -- Permit verdicts (core.go:199-216) ---------------------------------------

def test_permit_verdict_table():
    pg = make_pod_group("gang", min_member=2)
    node = make_tpu_node("h0", chips=8)
    fw, cs, handle, api = gang_framework(pod_groups=[pg], nodes=[node])
    mgr = cs.pg_mgr

    assert mgr.permit(make_pod("solo")) == POD_GROUP_NOT_SPECIFIED
    # label names a group with no CR and no min-available ⇒ not found
    orphan = make_pod("orphan", pod_group="ghost")
    assert mgr.permit(orphan) == POD_GROUP_NOT_FOUND

    member = make_pod("m0", pod_group="gang")
    assert mgr.permit(member) == WAIT  # 0 assigned + 1 < 2

    # one sibling assumed onto a node ⇒ assigned(1) + 1 ≥ 2
    from tpusched.fwk import Snapshot
    bound = make_pod("m1", pod_group="gang", node_name="h0")
    handle.set_snapshot(Snapshot(nodes=[node], pods=[bound]))
    assert mgr.permit(member) == SUCCESS


def test_permit_synthesized_group_reaches_quorum():
    """KEP-2 lightweight gang: min-available label alone drives the quorum."""
    node = make_tpu_node("h0", chips=8)
    fw, cs, handle, api = gang_framework(nodes=[node])
    labels = {MIN_AVAILABLE_LABEL: "2"}
    member = make_pod("m0", pod_group="lite", labels=labels)
    assert cs.pg_mgr.permit(member) == WAIT
    from tpusched.fwk import Snapshot
    bound = make_pod("m1", pod_group="lite", labels=labels, node_name="h0")
    handle.set_snapshot(Snapshot(nodes=[node], pods=[bound]))
    assert cs.pg_mgr.permit(member) == SUCCESS


def test_activate_siblings_stashes_other_members():
    pg = make_pod_group("gang", min_member=3)
    fw, cs, _, api = gang_framework(pod_groups=[pg])
    members = [make_pod(f"m{i}", pod_group="gang") for i in range(3)]
    for m in members:
        api.create(srv.PODS, m)
    state = CycleState()
    stash = PodsToActivate()
    state.write(PODS_TO_ACTIVATE_KEY, stash)
    cs.pg_mgr.activate_siblings(members[0], state)
    assert sorted(stash.map) == ["default/m1", "default/m2"]


# -- wait-time precedence (util/podgroup.go:53-76) ----------------------------

def test_wait_time_precedence():
    pg = make_pod_group("g", schedule_timeout_seconds=10)
    assert get_wait_time_duration(pg, 40.0) == 10.0       # PG.spec first
    pg_unset = make_pod_group("g2")
    assert get_wait_time_duration(pg_unset, 40.0) == 40.0  # then plugin arg
    assert get_wait_time_duration(None, 40.0) == 40.0
    assert get_wait_time_duration(pg_unset, 0.0) == 60.0   # then 60s default
    assert get_wait_time_duration(None, 0.0) == 60.0
