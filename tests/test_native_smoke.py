"""native-smoke: the native-toolchain gate `make tier1` runs (ISSUE 13).

Builds the C++ engine from source (hash-stamped — a fresh checkout or an
out-of-band .so rewrite must rebuild, where the old mtime check silently
served a stale library), loads it, runs a tiny-grid differential against
the pure-Python implementations, and asserts CLEAN fallback when the
toolchain is absent or TPUSCHED_NO_NATIVE=1 is set.
"""
import shutil

import pytest

from tpusched import native
from tpusched.testing import make_tpu_pool
from tpusched.topology.engine import (MaskGrid, enumerate_placement_masks,
                                      feasible_membership)
from tpusched.topology.torus import HostGrid, enumerate_placements


@pytest.fixture(autouse=True)
def _restore_native():
    """Every test here pokes the loader's cached verdict; leave the
    process with the real library (re)loaded."""
    yield
    native.reset_for_tests()
    native.load()


def _tiny():
    topo, _ = make_tpu_pool("smoke", dims=(4, 4, 4))
    grid = HostGrid.from_spec(topo.spec)
    return grid, MaskGrid(grid)


def test_native_builds_loads_and_matches_python_on_tiny_grid(monkeypatch):
    if shutil.which("g++") is None and not native.available():
        pytest.skip("no toolchain and no prebuilt library")
    assert native.available(), "native engine failed to build/load"
    grid, mgrid = _tiny()
    shape = (4, 4, 2)
    pset_native = enumerate_placement_masks(mgrid, shape)
    ref = {frozenset(p) for p in enumerate_placements(grid, shape)}
    assert {mgrid.coords_of(m) for m in pset_native.masks} == ref
    free = mgrid.mask_of(frozenset(grid.coord_of.values()))
    n_native, mem_native = feasible_membership(pset_native, 0, free, free)
    monkeypatch.setattr(native, "load", lambda: None)
    n_py, mem_py = feasible_membership(pset_native, 0, free, free)
    assert (n_native, mem_native) == (n_py, mem_py)


def test_window_index_kernels_differential(monkeypatch):
    """The incremental-index kernels (postings/build/apply) agree between
    the native and Python implementations on the same plane."""
    if not native.available():
        pytest.skip("native engine unavailable")
    from tpusched.topology.windowindex import _ShapeIndex
    _, mgrid = _tiny()
    shape = (2, 2, 4)
    pset = enumerate_placement_masks(mgrid, shape)
    all_free = (1 << mgrid.ncells) - 1

    def run():
        sidx = _ShapeIndex(shape, pset)
        sidx.rebuild(all_free)
        sidx.apply([(0, -1), (5, -1)])
        sidx.apply([(0, 1)])
        return (sidx.survivors, list(sidx.blocked[:sidx.n]),
                list(sidx.membership[:sidx.ncells]), sidx.covered_int())

    got_native = run()
    monkeypatch.setattr(native, "load", lambda: None)
    assert run() == got_native


def test_clean_fallback_when_toolchain_missing(monkeypatch):
    """A failing build (g++ absent/broken) must degrade to the Python
    path, not raise into the scheduler."""
    native.reset_for_tests()
    monkeypatch.setattr(native, "_build",
                        lambda *a, **k: (_ for _ in ()).throw(
                            FileNotFoundError("g++: not found")))
    monkeypatch.setattr(native, "_source_fingerprint",
                        lambda src: "force-stale")
    assert native.load() is None
    assert not native.available()
    grid, mgrid = _tiny()
    pset = enumerate_placement_masks(mgrid, (4, 4, 2))   # reference path
    assert len(pset.masks) > 0
    free = mgrid.mask_of(frozenset(grid.coord_of.values()))
    n, mem = feasible_membership(pset, 0, free, free)
    assert n == len(pset.masks)
    assert mem


def test_clean_fallback_under_no_native_env(monkeypatch):
    native.reset_for_tests()
    monkeypatch.setenv("TPUSCHED_NO_NATIVE", "1")
    assert native.load() is None
    # the window index still runs, on its Python kernels
    from tpusched.sched.cache import Cache
    from tpusched.topology.windowindex import TorusWindowIndex
    topo, nodes = make_tpu_pool("fallback", dims=(4, 4, 4))
    cache = Cache()
    idx = TorusWindowIndex(publish=False)
    idx.observe_topology(topo)
    cache.attach_window_index(idx)
    for n in nodes:
        cache.add_node(n)
    snap = cache.snapshot()
    q = idx.query(topo, (4, 4, 4), ("default", "g"), 4,
                  snap.pool_cursors.get("fallback"))
    assert q is not None and q.survivors == 1


# -- ISSUE 16: the batched dispatch inner loop --------------------------------

import ctypes
import threading
import time

from tpusched.sched import nativedispatch as nd
from tpusched.util import tracectx


def _drow(alloc=(64, 1 << 30, 110, 4), req=(0, 0, 0, 0), ucl=0, uml=0,
          hbm=1 << 20, free=4, flags=nd._FLAG_HEALTHY):
    """One packed candidate row (DISPATCH_FIELDS int64s)."""
    return list(alloc) + list(req) + [ucl, uml, hbm, free, flags]


def _call_dispatch(lib, rows, req, chips_set, chips_req, start, want,
                   membership=None, pool_util=None, max_membership=1,
                   strategy=0, packing_weight=0.7, spin_us=0):
    """Single-block ctypes harness around tpusched_dispatch_eval, shaped
    exactly like py_dispatch_eval's return."""
    i64 = ctypes.c_int64
    i64p = ctypes.POINTER(i64)
    n = len(rows) // nd.DISPATCH_FIELDS
    buf = (i64 * len(rows))(*rows)
    blocks = (i64p * 1)(ctypes.cast(buf, i64p))
    lens = (i64 * 1)(n)
    req_buf = (i64 * 4)(*req)
    memb = (i64 * n)(*membership) if membership is not None else None
    util = (ctypes.c_double * n)(*pool_util) if pool_util is not None \
        else None
    out_f, out_r, out_t = (i64 * n)(), (i64 * n)(), (i64 * n)()
    out_v = (i64 * 1)()
    nf = lib.tpusched_dispatch_eval(
        blocks, lens, 1, req_buf, 1 if chips_set else 0, chips_req,
        start, want, memb, util, max_membership, strategy,
        packing_weight, spin_us, out_f, out_r, out_t, out_v)
    return (list(out_f[:nf]), list(out_r[:nf]), list(out_t[:nf]),
            out_v[0])


def test_dispatch_kernel_builds_and_matches_python_mirror():
    """tpusched_dispatch_eval against py_dispatch_eval over a row set
    exercising every filter leg (health, hard taint, resource fit, chip
    capacity/limit) at several rotation starts and want cutoffs."""
    if shutil.which("g++") is None and not native.available():
        pytest.skip("no toolchain and no prebuilt library")
    assert native.available(), "native engine failed to build/load"
    lib = native.load()
    rows = (_drow() + _drow(flags=0)
            + _drow(flags=nd._FLAG_HEALTHY | nd._FLAG_HARD_TAINT)
            + _drow(req=(60, 0, 0, 0)) + _drow(free=1) + _drow(ucl=3)
            + _drow(uml=2 << 20) + _drow())
    req = (8, 1 << 20, 1, 2)
    for start in (0, 3, 7):
        for want in (1, 3, 8):
            got = _call_dispatch(lib, rows, req, True, 2, start, want)
            exp = nd.py_dispatch_eval(rows, req, True, 2, start, want)
            assert got == tuple(exp), (start, want, got, exp)


def test_dispatch_kernel_topology_scoring_matches_python_mirror():
    """The TopologyMatch constraint/strategy blend (gang cycles): all
    three strategies, with the float math in C expected bit-identical
    (-ffp-contract=off) to CPython's."""
    if not native.available():
        pytest.skip("native engine unavailable")
    lib = native.load()
    rows = _drow() + _drow(free=3) + _drow(free=2) + _drow(flags=0)
    membership = [4, 2, 1, 3]
    pool_util = [0.25, 0.5, 0.875, 0.0]
    for strategy in (0, 1, 2):
        for pw in (0.7, 0.3):
            got = _call_dispatch(lib, rows, (0, 0, 0, 0), True, 1, 1, 4,
                                 membership=membership,
                                 pool_util=pool_util, max_membership=4,
                                 strategy=strategy, packing_weight=pw)
            exp = nd.py_dispatch_eval(rows, (0, 0, 0, 0), True, 1, 1, 4,
                                      membership=membership,
                                      pool_util=pool_util,
                                      max_membership=4, strategy=strategy,
                                      packing_weight=pw)
            assert got == tuple(exp), (strategy, pw, got, exp)


def test_dispatch_kernel_releases_gil_lanes_overlap():
    """Non-vacuity for the headline claim: two lanes busy inside the
    kernel (spin_us hook) must OVERLAP in wall time — impossible if the
    call held the GIL — and the hot-path sampler, which can only run
    mid-kernel because the GIL is free, must attribute samples to the
    ``native:dispatch`` plugin row."""
    if not native.available():
        pytest.skip("native engine unavailable")
    from tpusched.obs.profiler import (HotPathProfiler,
                                       set_profiling_enabled)
    lib = native.load()
    rows = _drow()
    spin_s = 0.25
    prev_enabled = set_profiling_enabled(True)
    prof = HotPathProfiler(interval_s=0.002)
    assert prof.ensure_started()
    barrier = threading.Barrier(2)

    def lane():
        prev = tracectx.set_plugin("native:dispatch")
        try:
            barrier.wait()
            _call_dispatch(lib, rows, (0, 0, 0, 0), False, 0, 0, 1,
                           spin_us=int(spin_s * 1e6))
        finally:
            tracectx.set_plugin(prev)

    lanes = [threading.Thread(target=lane, name=f"tpusched-lane-{i}")
             for i in range(2)]
    t0 = time.monotonic()
    for t in lanes:
        t.start()
    for t in lanes:
        t.join()
    elapsed = time.monotonic() - t0
    prof.stop()
    set_profiling_enabled(prev_enabled)
    assert elapsed < 2 * spin_s * 0.8, (
        f"two {spin_s}s kernel calls took {elapsed:.3f}s — the lanes "
        f"serialized, the kernel is holding the GIL")
    native_rows = [r for r in prof.top_attribution(64)
                   if r["plugin"] == "native:dispatch"]
    assert native_rows, (
        "sampler never caught a lane inside the kernel — the "
        "native:dispatch attribution is dark")


def test_dispatch_fallback_when_toolchain_missing(monkeypatch):
    """With the native library unavailable, NativeDispatch.attempt must
    decline (reason no-native) and leave the cycle to the Python path."""
    native.reset_for_tests()
    monkeypatch.setattr(native, "_build",
                        lambda *a, **k: (_ for _ in ()).throw(
                            FileNotFoundError("g++: not found")))
    monkeypatch.setattr(native, "_source_fingerprint",
                        lambda src: "force-stale")
    from types import SimpleNamespace
    from tpusched.util.metrics import native_dispatch_fallbacks
    disp = nd.NativeDispatch(SimpleNamespace(profile=SimpleNamespace()))
    before = native_dispatch_fallbacks.with_labels("no-native").value()
    got = disp.attempt(state=None, pod=None, snapshot=None, infos=[],
                       want=1, ctx=SimpleNamespace(pools_scoped=True),
                       restricted=False)
    assert got is None
    after = native_dispatch_fallbacks.with_labels("no-native").value()
    assert after == before + 1


def test_dispatch_fallback_under_no_native_env(monkeypatch):
    """TPUSCHED_NO_NATIVE=1 keeps the whole dispatch path pure-Python: the
    loader declines and the Scheduler constructor never wires
    NativeDispatch in (the in-vivo gate for the env contract)."""
    native.reset_for_tests()
    monkeypatch.setenv("TPUSCHED_NO_NATIVE", "1")
    assert native.load() is None
    from types import SimpleNamespace
    disp = nd.NativeDispatch(SimpleNamespace(profile=SimpleNamespace()))
    assert disp.attempt(state=None, pod=None, snapshot=None, infos=[],
                        want=1, ctx=SimpleNamespace(pools_scoped=True),
                        restricted=False) is None


def test_stale_stamp_forces_rebuild():
    if shutil.which("g++") is None:
        pytest.skip("no toolchain")
    from pathlib import Path
    here = Path(native.__file__).resolve().parent
    stamp = here / "_torus_engine.so.stamp"
    old = stamp.read_text() if stamp.exists() else None
    try:
        stamp.write_text("deadbeef stale")
        native.reset_for_tests()
        lib = native.load()
        assert lib is not None
        assert stamp.read_text() != "deadbeef stale", (
            "loader served the library without refreshing the stale stamp")
    finally:
        if old is not None and not stamp.exists():
            stamp.write_text(old)
