"""native-smoke: the native-toolchain gate `make tier1` runs (ISSUE 13).

Builds the C++ engine from source (hash-stamped — a fresh checkout or an
out-of-band .so rewrite must rebuild, where the old mtime check silently
served a stale library), loads it, runs a tiny-grid differential against
the pure-Python implementations, and asserts CLEAN fallback when the
toolchain is absent or TPUSCHED_NO_NATIVE=1 is set.
"""
import shutil

import pytest

from tpusched import native
from tpusched.testing import make_tpu_pool
from tpusched.topology.engine import (MaskGrid, enumerate_placement_masks,
                                      feasible_membership)
from tpusched.topology.torus import HostGrid, enumerate_placements


@pytest.fixture(autouse=True)
def _restore_native():
    """Every test here pokes the loader's cached verdict; leave the
    process with the real library (re)loaded."""
    yield
    native.reset_for_tests()
    native.load()


def _tiny():
    topo, _ = make_tpu_pool("smoke", dims=(4, 4, 4))
    grid = HostGrid.from_spec(topo.spec)
    return grid, MaskGrid(grid)


def test_native_builds_loads_and_matches_python_on_tiny_grid(monkeypatch):
    if shutil.which("g++") is None and not native.available():
        pytest.skip("no toolchain and no prebuilt library")
    assert native.available(), "native engine failed to build/load"
    grid, mgrid = _tiny()
    shape = (4, 4, 2)
    pset_native = enumerate_placement_masks(mgrid, shape)
    ref = {frozenset(p) for p in enumerate_placements(grid, shape)}
    assert {mgrid.coords_of(m) for m in pset_native.masks} == ref
    free = mgrid.mask_of(frozenset(grid.coord_of.values()))
    n_native, mem_native = feasible_membership(pset_native, 0, free, free)
    monkeypatch.setattr(native, "load", lambda: None)
    n_py, mem_py = feasible_membership(pset_native, 0, free, free)
    assert (n_native, mem_native) == (n_py, mem_py)


def test_window_index_kernels_differential(monkeypatch):
    """The incremental-index kernels (postings/build/apply) agree between
    the native and Python implementations on the same plane."""
    if not native.available():
        pytest.skip("native engine unavailable")
    from tpusched.topology.windowindex import _ShapeIndex
    _, mgrid = _tiny()
    shape = (2, 2, 4)
    pset = enumerate_placement_masks(mgrid, shape)
    all_free = (1 << mgrid.ncells) - 1

    def run():
        sidx = _ShapeIndex(shape, pset)
        sidx.rebuild(all_free)
        sidx.apply([(0, -1), (5, -1)])
        sidx.apply([(0, 1)])
        return (sidx.survivors, list(sidx.blocked[:sidx.n]),
                list(sidx.membership[:sidx.ncells]), sidx.covered_int())

    got_native = run()
    monkeypatch.setattr(native, "load", lambda: None)
    assert run() == got_native


def test_clean_fallback_when_toolchain_missing(monkeypatch):
    """A failing build (g++ absent/broken) must degrade to the Python
    path, not raise into the scheduler."""
    native.reset_for_tests()
    monkeypatch.setattr(native, "_build",
                        lambda *a, **k: (_ for _ in ()).throw(
                            FileNotFoundError("g++: not found")))
    monkeypatch.setattr(native, "_source_fingerprint",
                        lambda src: "force-stale")
    assert native.load() is None
    assert not native.available()
    grid, mgrid = _tiny()
    pset = enumerate_placement_masks(mgrid, (4, 4, 2))   # reference path
    assert len(pset.masks) > 0
    free = mgrid.mask_of(frozenset(grid.coord_of.values()))
    n, mem = feasible_membership(pset, 0, free, free)
    assert n == len(pset.masks)
    assert mem


def test_clean_fallback_under_no_native_env(monkeypatch):
    native.reset_for_tests()
    monkeypatch.setenv("TPUSCHED_NO_NATIVE", "1")
    assert native.load() is None
    # the window index still runs, on its Python kernels
    from tpusched.sched.cache import Cache
    from tpusched.topology.windowindex import TorusWindowIndex
    topo, nodes = make_tpu_pool("fallback", dims=(4, 4, 4))
    cache = Cache()
    idx = TorusWindowIndex(publish=False)
    idx.observe_topology(topo)
    cache.attach_window_index(idx)
    for n in nodes:
        cache.add_node(n)
    snap = cache.snapshot()
    q = idx.query(topo, (4, 4, 4), ("default", "g"), 4,
                  snap.pool_cursors.get("fallback"))
    assert q is not None and q.survivors == 1


def test_stale_stamp_forces_rebuild():
    if shutil.which("g++") is None:
        pytest.skip("no toolchain")
    from pathlib import Path
    here = Path(native.__file__).resolve().parent
    stamp = here / "_torus_engine.so.stamp"
    old = stamp.read_text() if stamp.exists() else None
    try:
        stamp.write_text("deadbeef stale")
        native.reset_for_tests()
        lib = native.load()
        assert lib is not None
        assert stamp.read_text() != "deadbeef stale", (
            "loader served the library without refreshing the stale stamp")
    finally:
        if old is not None and not stamp.exists():
            stamp.write_text(old)
