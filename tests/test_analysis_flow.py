"""tpulint flow-sensitive rules (atomicity-violation, snapshot-discipline)
+ the interprocedural locked-callgraph rule over the lazy per-module call
graph + SARIF output round-trip."""
from __future__ import annotations

import json
import textwrap
from pathlib import Path

from tpusched.analysis import Runner
from tpusched.analysis.core import FileContext

REPO_ROOT = Path(__file__).resolve().parents[1]


def run_snippet(tmp_path, relpath, source, rules=None):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return Runner(tmp_path, rules).run([f])


def rules_found(report):
    return [f.rule for f in report.findings]


# -- atomicity-violation -------------------------------------------------------

ATOMICITY_BAD = """
    from tpusched.util.locking import GuardedLock, guarded_by

    @guarded_by("_lock", "_count")
    class C:
        def bump(self):
            with self._lock:
                v = self._count
            with self._lock:
                self._count = v + 1
"""

ATOMICITY_GOOD_ONE_REGION = """
    from tpusched.util.locking import GuardedLock, guarded_by

    @guarded_by("_lock", "_count")
    class C:
        def bump(self):
            with self._lock:
                v = self._count
                self._count = v + 1
"""

ATOMICITY_GOOD_REBOUND = """
    from tpusched.util.locking import GuardedLock, guarded_by

    @guarded_by("_lock", "_count")
    class C:
        def bump(self):
            with self._lock:
                v = self._count
            v = 0
            with self._lock:
                self._count = v + 1
"""


def test_atomicity_read_write_across_release_flagged(tmp_path):
    r = run_snippet(tmp_path, "tpusched/sched/x.py", ATOMICITY_BAD,
                    ["atomicity-violation"])
    assert rules_found(r) == ["atomicity-violation"]
    assert "check-then-act" in r.findings[0].message


def test_atomicity_single_region_clean(tmp_path):
    r = run_snippet(tmp_path, "tpusched/sched/x.py",
                    ATOMICITY_GOOD_ONE_REGION, ["atomicity-violation"])
    assert r.findings == []


def test_atomicity_rebound_local_clean(tmp_path):
    """A local overwritten from a non-guarded source between the regions
    no longer carries stale guarded state."""
    r = run_snippet(tmp_path, "tpusched/sched/x.py",
                    ATOMICITY_GOOD_REBOUND, ["atomicity-violation"])
    assert r.findings == []


def test_atomicity_mutator_call_with_stale_operand_flagged(tmp_path):
    src = """
        from tpusched.util.locking import guarded_by

        @guarded_by("_lock", "_pods", "_keys")
        class C:
            def move(self):
                with self._lock:
                    k, v = self._pods.popitem()
                with self._lock:
                    self._keys.append(k)
    """
    r = run_snippet(tmp_path, "tpusched/sched/x.py", src,
                    ["atomicity-violation"])
    assert rules_found(r) == ["atomicity-violation"]


def test_atomicity_annotated_assignments_seen(tmp_path):
    """Type-annotating the local (or the write) must not bypass the rule."""
    src = """
        from tpusched.util.locking import guarded_by

        @guarded_by("_lock", "_count")
        class C:
            def bump(self):
                with self._lock:
                    v: int = self._count
                with self._lock:
                    self._count = v + 1
    """
    r = run_snippet(tmp_path, "tpusched/sched/x.py", src,
                    ["atomicity-violation"])
    assert rules_found(r) == ["atomicity-violation"]


def test_atomicity_locked_methods_exempt(tmp_path):
    src = """
        from tpusched.util.locking import guarded_by

        @guarded_by("_lock", "_count")
        class C:
            def _bump_locked(self):
                v = self._count
                self._count = v + 1
    """
    r = run_snippet(tmp_path, "tpusched/sched/x.py", src,
                    ["atomicity-violation"])
    assert r.findings == []


# -- snapshot-discipline -------------------------------------------------------


def test_snapshot_call_outside_dispatch_flagged(tmp_path):
    src = """
        class Collector:
            def collect(self, sched):
                return sched.cache.snapshot()
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert rules_found(r) == ["snapshot-discipline"]
    assert "peek_snapshot" in r.findings[0].message
    # the same call in dispatch-owned code is the sanctioned path
    r = run_snippet(tmp_path, "tpusched/sched/x.py", src,
                    ["snapshot-discipline"])
    assert r.findings == []


def test_non_cache_snapshot_not_flagged(tmp_path):
    src = """
        class H:
            def health(self):
                return self._degraded.snapshot()
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert r.findings == []


def test_peek_snapshot_mutation_flagged(tmp_path):
    src = """
        class Collector:
            def collect(self, sched):
                snap = sched.cache.peek_snapshot()
                snap.clear()
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert rules_found(r) == ["snapshot-discipline"]
    assert "read-only" in r.findings[0].message


def test_peek_snapshot_escape_to_self_flagged(tmp_path):
    src = """
        class Collector:
            def collect(self, sched):
                snap = sched.cache.peek_snapshot()
                self._snap = snap
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert rules_found(r) == ["snapshot-discipline"]
    assert "epoch pin" in r.findings[0].message


def test_peek_snapshot_annotated_binding_tracked(tmp_path):
    src = """
        class Collector:
            def collect(self, sched):
                snap: object = sched.cache.peek_snapshot()
                snap.clear()
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert rules_found(r) == ["snapshot-discipline"]


def test_peek_snapshot_return_escape_flagged(tmp_path):
    src = """
        class Collector:
            def grab(self, sched):
                snap = sched.cache.peek_snapshot()
                return snap
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert rules_found(r) == ["snapshot-discipline"]
    assert "escapes the function" in r.findings[0].message


def test_peek_snapshot_tracking_is_order_and_rebind_sensitive(tmp_path):
    """A name mutated BEFORE it ever holds a snapshot, or AFTER being
    re-bound to something else, is not a snapshot — no bogus
    suppressions required."""
    src = """
        class Collector:
            def collect(self, sched):
                out = []
                out.append(1)                    # plain list: fine
                out = sched.cache.peek_snapshot()
                out = transform(out)             # re-bound: snapshot gone
                return out
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert r.findings == []


def test_peek_snapshot_container_escapes_flagged(tmp_path):
    """Escape through a container on self — subscript store or mutator
    call — is the same epoch-laundering as a direct attribute store."""
    sub = """
        class Collector:
            def collect(self, sched, k):
                snap = sched.cache.peek_snapshot()
                self._saved[k] = snap
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", sub,
                    ["snapshot-discipline"])
    assert rules_found(r) == ["snapshot-discipline"]
    app = """
        class Collector:
            def collect(self, sched):
                snap = sched.cache.peek_snapshot()
                self._history.append(snap)
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", app,
                    ["snapshot-discipline"])
    assert rules_found(r) == ["snapshot-discipline"]


def test_peek_snapshot_tuple_rebind_untracks(tmp_path):
    src = """
        class Collector:
            def collect(self, sched):
                snap = sched.cache.peek_snapshot()
                snap, extra = [], 0
                snap.append(1)
                return snap
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert r.findings == []


def test_peek_snapshot_read_only_use_clean(tmp_path):
    src = """
        class Collector:
            def collect(self, sched):
                snap = sched.cache.peek_snapshot()
                if snap is None:
                    return 0
                return sum(1 for info in snap.list() for p in info.pods)
    """
    r = run_snippet(tmp_path, "tpusched/obs/x.py", src,
                    ["snapshot-discipline"])
    assert r.findings == []


# -- locked-callgraph ----------------------------------------------------------

CALLGRAPH_SRC = """
    from tpusched.util.locking import guarded_by

    @guarded_by("_lock", "_pods")
    class C:
        def _drop_locked(self, k):
            self._pods.pop(k, None)

        def good_with(self, k):
            with self._lock:
                self._drop_locked(k)

        def _also_locked(self, k):
            self._drop_locked(k)

        def bad_unguarded(self, k):
            self._drop_locked(k)

        def good_cv(self, k):
            with self._cond:
                self._drop_locked(k)

        def good_acquiring_helper(self, k):
            with self._locked():
                self._read(k)
"""


def test_locked_callgraph(tmp_path):
    r = run_snippet(tmp_path, "tpusched/sched/x.py", CALLGRAPH_SRC,
                    ["locked-callgraph"])
    assert [(f.rule, "bad_unguarded" in f.message) for f in r.findings] \
        == [("locked-callgraph", True)]


def test_locked_callgraph_scoped_to_tpusched(tmp_path):
    r = run_snippet(tmp_path, "hack/x.py", CALLGRAPH_SRC,
                    ["locked-callgraph"])
    assert r.findings == []


def test_call_graph_is_lazy(tmp_path):
    """--changed-only latency contract: building a FileContext never pays
    for the call graph; only a rule that asks for it does."""
    f = tmp_path / "m.py"
    f.write_text("class C:\n    def a(self):\n        self.b()\n")
    ctx = FileContext(tmp_path, f)
    assert ctx._self_call_graph is None
    sites = ctx.self_call_graph
    assert [(s.caller, s.callee) for s in sites] == [("a", "b")]
    assert ctx._self_call_graph is not None      # cached after first use


# -- SARIF ---------------------------------------------------------------------


def _validate_sarif(doc):
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    assert len(doc["runs"]) == 1
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpulint"
    rule_ids = {r["id"] for r in driver["rules"]}
    for r in driver["rules"]:
        assert isinstance(r["shortDescription"]["text"], str)
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert res["level"] == "error"
        assert isinstance(res["message"]["text"], str) \
            and res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uriBaseId"] == "SRCROOT"
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
    assert isinstance(run["invocations"][0]["executionSuccessful"], bool)
    return run


def test_sarif_round_trip(tmp_path):
    src = """
        from tpusched.util.locking import guarded_by

        @guarded_by("_lock", "_count")
        class C:
            def bump(self):
                with self._lock:
                    v = self._count
                with self._lock:
                    self._count = v + 1

            def ok(self):
                # tpulint: disable=atomicity-violation — test fixture reason
                with self._lock:
                    w = self._count
                return w
    """
    r = run_snippet(tmp_path, "tpusched/sched/x.py", src,
                    ["atomicity-violation"])
    doc = json.loads(r.to_sarif())
    run = _validate_sarif(doc)
    unsuppressed = [x for x in run["results"] if "suppressions" not in x]
    assert len(unsuppressed) == 1
    assert unsuppressed[0]["ruleId"] == "atomicity-violation"


def test_sarif_suppressions_carry_justifications(tmp_path):
    src = """
        import time

        def f():
            return time.time()  # tpulint: disable=monotonic-clock — fixture
    """
    r = run_snippet(tmp_path, "tpusched/sched/x.py", src,
                    ["monotonic-clock"])
    assert r.findings == []
    doc = json.loads(r.to_sarif())
    run = _validate_sarif(doc)
    sup = [x for x in run["results"] if "suppressions" in x]
    assert len(sup) == 1
    assert sup[0]["suppressions"][0]["justification"] == "fixture"
    assert sup[0]["suppressions"][0]["kind"] == "inSource"


def test_sarif_cli(tmp_path):
    import subprocess
    import sys
    p = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.lint", "--format=sarif",
         "tpusched/analysis/"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert p.returncode in (0, 1), p.stderr
    _validate_sarif(json.loads(p.stdout))
    # --json and --format=sarif together is a usage error
    p = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.lint", "--json",
         "--format=sarif"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert p.returncode == 2
