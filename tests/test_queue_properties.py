"""Property-based SchedulingQueue conservation laws (hypothesis stateful).

The queue juggles four structures (activeQ heap, backoff heap with
tombstones, unschedulable map, live-key index) across adds, pops, failure
requeues, deletes, event moves, and activations. The conservation law a
scheduler cannot live without: **every added, undeleted, unpopped pod is
pending in exactly one place — never lost, never duplicated** — under ANY
interleaving. A lost pod is a silently stranded workload; a duplicated one
double-schedules.

A deterministic fake clock drives backoff expiry so the machine can
explore "time passed" transitions without sleeping.
"""
import pytest

pytest.importorskip("hypothesis")

from hypothesis import settings
from hypothesis import strategies as st
from hypothesis.stateful import (Bundle, RuleBasedStateMachine, invariant,
                                 rule)

from tpusched.fwk.interfaces import EVENT_DELETE, RESOURCE_POD
from tpusched.sched.queue import SchedulingQueue
from tpusched.testing import make_pod


class QueueMachine(RuleBasedStateMachine):
    def __init__(self):
        super().__init__()
        self.now = [1000.0]
        self.q = SchedulingQueue(
            less=lambda a, b: a.pod.key < b.pod.key,
            clock=lambda: self.now[0])
        self.counter = 0
        self.pending = {}              # key -> Pod (added, not popped/deleted)
        self.popped = {}               # key -> QueuedPodInfo (in a "cycle")

    pods = Bundle("pods")

    @rule(target=pods)
    def add_pod(self):
        self.counter += 1
        p = make_pod(f"p{self.counter}")
        self.q.add(p)
        self.pending[p.key] = p
        return p

    @rule()
    def pop_one(self):
        info = self.q.pop(timeout=0)
        if info is not None:
            key = info.pod.key
            assert key in self.pending, f"popped unknown/duplicate {key}"
            assert key not in self.popped, f"double-pop {key}"
            self.popped[key] = info
            del self.pending[key]

    @rule(to_backoff=st.booleans())
    def fail_popped(self, to_backoff):
        if not self.popped:
            return
        key = next(iter(self.popped))
        info = self.popped.pop(key)
        self.q.requeue_after_failure(info, to_backoff=to_backoff)
        self.pending[key] = info.pod

    @rule(delay=st.floats(0.1, 5.0))
    def fail_popped_with_delay(self, delay):
        if not self.popped:
            return
        key = next(iter(self.popped))
        info = self.popped.pop(key)
        self.q.requeue_after_failure(info, delay_s=delay)
        self.pending[key] = info.pod

    @rule(pod=pods)
    def delete_pod(self, pod):
        if pod.key in self.pending:
            self.q.delete(pod)
            del self.pending[pod.key]
        elif pod.key in self.popped:
            # a pod deleted mid-cycle: the scheduler's failure path checks
            # liveness before requeueing; model that by dropping it
            self.q.delete(pod)
            del self.popped[pod.key]

    @rule()
    def event_move(self):
        self.q.move_all_to_active_or_backoff(RESOURCE_POD, EVENT_DELETE)

    @rule()
    def activate_all_pending(self):
        self.q.activate(list(self.pending.values()))

    @rule(dt=st.floats(0.1, 40.0))
    def advance_time(self, dt):
        self.now[0] += dt

    @invariant()
    def conservation(self):
        counts = self.q.pending_counts()
        total = counts["active"] + counts["backoff"] + counts["unschedulable"]
        assert total == len(self.pending), \
            f"{counts} vs model {sorted(self.pending)}"

    @invariant()
    def no_phantom_pods(self):
        queued = [p.key for p in self.q.pending_pods()]
        assert sorted(queued) == sorted(self.pending), \
            f"queue={sorted(queued)} model={sorted(self.pending)}"


QueueMachine.TestCase.settings = settings(max_examples=60,
                                          stateful_step_count=60,
                                          deadline=None)
TestQueueConservation = QueueMachine.TestCase
