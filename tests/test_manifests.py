"""Deploy manifests decode through the real config machinery.

Analog of the reference's tier-3 verify (CRD-manifest drift) plus the
scheme_test profile-decoding checks: every per-plugin scheduler-config in
manifests/ must decode strictly, and every plugin it names must exist in the
default registry.
"""
import os
import glob

import yaml

from tpusched.apiserver import APIServer
from tpusched.config import versioned as v
from tpusched.plugins import default_registry
from tpusched.sched import Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = sorted(glob.glob(os.path.join(REPO, "manifests", "*", "scheduler-config.yaml")))


def test_manifests_exist():
    assert len(CONFIGS) >= 8, CONFIGS


def test_every_manifest_decodes_and_wires():
    registry = default_registry()
    for path in CONFIGS:
        cfg = v.load_file(path)
        assert cfg.profiles, path
        for profile in cfg.profiles:
            # every named plugin resolves and instantiates
            s = Scheduler(APIServer(), default_registry(), profile)
            try:
                for name in profile.all_plugin_names():
                    assert name in registry, (path, name)
                    assert name in s.framework.plugins, (path, name)
            finally:
                s.stop()   # leaked collector threads log after teardown


def test_all_in_one_embedded_config_decodes():
    path = os.path.join(REPO, "manifests", "install", "all-in-one.yaml")
    docs = list(yaml.safe_load_all(open(path)))
    kinds = [d["kind"] for d in docs]
    assert {"Namespace", "ServiceAccount", "ConfigMap", "Deployment"} <= set(kinds)
    cm = next(d for d in docs if d["kind"] == "ConfigMap")
    cfg = v.loads(cm["data"]["scheduler-config.yaml"])
    p = cfg.profile("tpusched")
    assert p.queue_sort == "Coscheduling"
    assert p.bind == ["TpuSlice"]
    assert ("MultiSlice", 3) in p.score
    # the embedded profile matches the canned flagship profile's wiring
    from tpusched.config.profiles import tpu_gang_profile
    canned = tpu_gang_profile()
    assert p.filter[-2:] == canned.filter[-2:] == ["TpuSlice", "TopologyMatch"]
    assert p.permit == canned.permit
    assert sorted(p.score) == sorted(canned.score)


def test_crds_parse_and_match_groups():
    crds = sorted(glob.glob(os.path.join(REPO, "manifests", "crds", "*.yaml")))
    assert len(crds) == 3
    by_kind = {}
    for path in crds:
        doc = yaml.safe_load(open(path))
        assert doc["kind"] == "CustomResourceDefinition", path
        spec = doc["spec"]
        by_kind[spec["names"]["kind"]] = spec
        # storage version has a schema
        v0 = spec["versions"][0]
        assert v0["storage"] and "openAPIV3Schema" in v0["schema"], path
    from tpusched.api.scheduling import GROUP_NAME
    from tpusched.api.topology import TOPOLOGY_GROUP
    assert by_kind["PodGroup"]["group"] == GROUP_NAME
    assert by_kind["ElasticQuota"]["group"] == GROUP_NAME
    assert by_kind["TpuTopology"]["group"] == TOPOLOGY_GROUP
    assert by_kind["TpuTopology"]["scope"] == "Cluster"
    assert by_kind["PodGroup"]["scope"] == "Namespaced"


def test_crd_spec_fields_cover_dataclasses():
    """CRD-drift check (verify-crdgen.sh analog): every spec field of the Go…
    er, Python CRD dataclasses appears in the published schema."""
    import dataclasses
    from tpusched.api.scheduling import PodGroupSpec, ElasticQuotaSpec
    from tpusched.api.topology import TpuTopologySpec
    from tpusched.config.versioned import _snake_to_camel

    def props(path, kind):
        doc = yaml.safe_load(open(os.path.join(REPO, "manifests", "crds", path)))
        return doc["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"]["spec"]["properties"]

    for cls, path in ((PodGroupSpec, "scheduling.tpu.dev_podgroups.yaml"),
                      (ElasticQuotaSpec, "scheduling.tpu.dev_elasticquotas.yaml"),
                      (TpuTopologySpec, "topology.tpu.dev_tputopologies.yaml")):
        published = props(path, cls)
        for f in dataclasses.fields(cls):
            assert _snake_to_camel(f.name) in published, (path, f.name)
