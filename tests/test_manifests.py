"""Deploy manifests decode through the real config machinery.

Analog of the reference's tier-3 verify (CRD-manifest drift) plus the
scheme_test profile-decoding checks: every per-plugin scheduler-config in
manifests/ must decode strictly, and every plugin it names must exist in the
default registry.
"""
import os
import glob

import yaml

from tpusched.apiserver import APIServer
from tpusched.config import versioned as v
from tpusched.plugins import default_registry
from tpusched.sched import Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = sorted(glob.glob(os.path.join(REPO, "manifests", "*", "scheduler-config.yaml")))


def test_manifests_exist():
    assert len(CONFIGS) >= 8, CONFIGS


def test_every_manifest_decodes_and_wires():
    registry = default_registry()
    for path in CONFIGS:
        cfg = v.load_file(path)
        assert cfg.profiles, path
        for profile in cfg.profiles:
            # every named plugin resolves and instantiates
            s = Scheduler(APIServer(), default_registry(), profile)
            try:
                for name in profile.all_plugin_names():
                    assert name in registry, (path, name)
                    assert name in s.framework.plugins, (path, name)
            finally:
                s.stop()   # leaked collector threads log after teardown


def test_all_in_one_embedded_config_decodes():
    path = os.path.join(REPO, "manifests", "install", "all-in-one.yaml")
    docs = list(yaml.safe_load_all(open(path)))
    kinds = [d["kind"] for d in docs]
    assert {"Namespace", "ServiceAccount", "ConfigMap", "Deployment"} <= set(kinds)
    cm = next(d for d in docs if d["kind"] == "ConfigMap")
    cfg = v.loads(cm["data"]["scheduler-config.yaml"])
    p = cfg.profile("tpusched")
    assert p.queue_sort == "Coscheduling"
    assert p.bind == ["TpuSlice"]
    assert ("MultiSlice", 3) in p.score
    # the embedded profile matches the canned flagship profile's wiring
    # (incl. the TopologyMatch-first filter order — the fleet-scale perf
    # contract the canned profile documents)
    from tpusched.config.profiles import tpu_gang_profile
    canned = tpu_gang_profile()
    assert p.filter == canned.filter
    assert p.filter[0] == "TopologyMatch" and p.filter[-1] == "TpuSlice"
    assert p.permit == canned.permit
    assert sorted(p.score) == sorted(canned.score)


def test_crds_parse_and_match_groups():
    crds = sorted(glob.glob(os.path.join(REPO, "manifests", "crds", "*.yaml")))
    assert len(crds) == 3
    by_kind = {}
    for path in crds:
        doc = yaml.safe_load(open(path))
        assert doc["kind"] == "CustomResourceDefinition", path
        spec = doc["spec"]
        by_kind[spec["names"]["kind"]] = spec
        # storage version has a schema
        v0 = spec["versions"][0]
        assert v0["storage"] and "openAPIV3Schema" in v0["schema"], path
    from tpusched.api.scheduling import GROUP_NAME
    from tpusched.api.topology import TOPOLOGY_GROUP
    assert by_kind["PodGroup"]["group"] == GROUP_NAME
    assert by_kind["ElasticQuota"]["group"] == GROUP_NAME
    assert by_kind["TpuTopology"]["group"] == TOPOLOGY_GROUP
    assert by_kind["TpuTopology"]["scope"] == "Cluster"
    assert by_kind["PodGroup"]["scope"] == "Namespaced"


def test_crd_spec_fields_cover_dataclasses():
    """CRD-drift check (verify-crdgen.sh analog): every spec field of the Go…
    er, Python CRD dataclasses appears in the published schema."""
    import dataclasses
    from tpusched.api.scheduling import PodGroupSpec, ElasticQuotaSpec
    from tpusched.api.topology import TpuTopologySpec
    from tpusched.config.versioned import _snake_to_camel

    def props(path, kind):
        doc = yaml.safe_load(open(os.path.join(REPO, "manifests", "crds", path)))
        return doc["spec"]["versions"][0]["schema"]["openAPIV3Schema"][
            "properties"]["spec"]["properties"]

    for cls, path in ((PodGroupSpec, "scheduling.tpu.dev_podgroups.yaml"),
                      (ElasticQuotaSpec, "scheduling.tpu.dev_elasticquotas.yaml"),
                      (TpuTopologySpec, "topology.tpu.dev_tputopologies.yaml")):
        published = props(path, cls)
        for f in dataclasses.fields(cls):
            assert _snake_to_camel(f.name) in published, (path, f.name)


# -- tpuslice Helm chart ------------------------------------------------------

CHART = os.path.join(REPO, "manifests", "tpuslice")


def _render_chart_template(path: str) -> str:
    """Minimal helm-render for the constructs THIS chart uses, with its
    default values.yaml (no helm binary in the image): include helpers
    resolve to their default-value expansions, {{ .Values.* }} substitutes,
    nindent emits an indented block. A construct outside this subset fails
    the test loudly rather than silently passing."""
    import re
    values = yaml.safe_load(open(os.path.join(CHART, "values.yaml")))
    # default-values expansion of the _helpers.tpl defines
    helpers = {
        "tpuslice.name": "tpuslice-scheduler",
        "tpuslice.fullname": values["fullnameOverride"],
        "tpuslice.chart": "tpuslice-scheduler-0.1.0",
        "tpuslice.serviceAccountName": values["serviceAccount"]["name"],
        "tpuslice.selectorLabels": (
            "app.kubernetes.io/name: tpuslice-scheduler\n"
            "app.kubernetes.io/instance: RELEASE"),
        "tpuslice.labels": (
            "helm.sh/chart: tpuslice-scheduler-0.1.0\n"
            "app.kubernetes.io/name: tpuslice-scheduler\n"
            "app.kubernetes.io/instance: RELEASE\n"
            'app.kubernetes.io/version: "0.1.0"\n'
            "app.kubernetes.io/managed-by: Helm"),
    }
    text = open(path).read()

    def sub(m: "re.Match") -> str:
        expr = m.group(1).strip().strip("-").strip()
        nindent = re.search(r"\|\s*nindent\s+(\d+)$", expr)
        if nindent:
            expr = expr[:nindent.start()].strip()
        inc = re.fullmatch(r'include "([^"]+)" \.', expr)
        if inc:
            out = helpers[inc.group(1)]
        elif expr.startswith(".Values."):
            cur = values
            for part in expr[len(".Values."):].split("."):
                cur = cur[part]
            out = str(cur)
        else:
            raise AssertionError(f"{path}: unsupported construct {expr!r}")
        if nindent:
            pad = " " * int(nindent.group(1))
            out = "\n" + "\n".join(pad + line for line in out.splitlines())
        return out

    return re.sub(r"\{\{(.*?)\}\}", sub, text)


def test_chart_has_full_template_set():
    """Chart parity with the reference's flexgpu chart
    (/root/reference/manifests/flexgpu/templates): helpers, rbac, configmap,
    deployment, values."""
    for f in ("_helpers.tpl", "rbac.yaml", "configmap.yaml",
              "deployment.yaml"):
        assert os.path.exists(os.path.join(CHART, "templates", f)), f
    helpers = open(os.path.join(CHART, "templates", "_helpers.tpl")).read()
    for name in ("tpuslice.name", "tpuslice.fullname", "tpuslice.labels",
                 "tpuslice.selectorLabels", "tpuslice.serviceAccountName"):
        assert f'define "{name}"' in helpers, name


def test_chart_rbac_renders_complete_install():
    docs = list(yaml.safe_load_all(_render_chart_template(
        os.path.join(CHART, "templates", "rbac.yaml"))))
    kinds = [d["kind"] for d in docs if d]
    assert kinds == ["ServiceAccount", "ClusterRole", "ClusterRoleBinding"]
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    granted = {(g, r) for rule in role["rules"]
               for g in rule["apiGroups"] for r in rule["resources"]}
    # the scheduler's working set: core pods/binding/nodes, the tpusched
    # CRD groups, and leases for leader election
    for need in (("", "pods"), ("", "pods/binding"), ("", "nodes"),
                 ("scheduling.tpu.dev", "podgroups"),
                 ("scheduling.tpu.dev", "elasticquotas"),
                 ("topology.tpu.dev", "tputopologies"),
                 ("coordination.k8s.io", "leases")):
        assert need in granted, need
    binding = next(d for d in docs if d["kind"] == "ClusterRoleBinding")
    sa = next(d for d in docs if d["kind"] == "ServiceAccount")
    assert binding["subjects"][0]["name"] == sa["metadata"]["name"]
    assert binding["roleRef"]["name"] == role["metadata"]["name"]


def test_chart_deployment_and_configmap_render():
    for f in ("deployment.yaml", "configmap.yaml"):
        docs = list(yaml.safe_load_all(_render_chart_template(
            os.path.join(CHART, "templates", f))))
        assert docs and all(d for d in docs), f
    cm = list(yaml.safe_load_all(_render_chart_template(
        os.path.join(CHART, "templates", "configmap.yaml"))))[0]
    cfg = v.loads(cm["data"]["scheduler-config.yaml"])
    assert cfg.profiles[0].bind == ["TpuSlice"]


def test_chart_deployment_identity_is_rbac_bound():
    """The pod's serviceAccountName must be the SA the chart creates AND
    the one its ClusterRoleBinding grants — a mismatch means the default
    --kubeconfig=in-cluster transport has no working identity (403s or an
    unmountable token)."""
    dep = yaml.safe_load(_render_chart_template(
        os.path.join(CHART, "templates", "deployment.yaml")))
    rbac_docs = [d for d in yaml.safe_load_all(_render_chart_template(
        os.path.join(CHART, "templates", "rbac.yaml"))) if d]
    created = {d["metadata"]["name"] for d in rbac_docs
               if d["kind"] == "ServiceAccount"}
    bound = {s["name"] for d in rbac_docs
             if d["kind"] == "ClusterRoleBinding"
             for s in d.get("subjects", [])}
    pod_sa = dep["spec"]["template"]["spec"]["serviceAccountName"]
    assert pod_sa in created, (pod_sa, created)
    assert pod_sa in bound, (pod_sa, bound)
