"""Controller tests (reference analog: pkg/controller/{podgroup,elasticquota}_test.go
with fake clients; here against the in-memory API server)."""
import time

from tpusched.api.core import POD_RUNNING, POD_SUCCEEDED, POD_FAILED
from tpusched.api.resources import CPU, TPU
from tpusched.api.scheduling import (PG_FAILED, PG_FINISHED, PG_PENDING,
                                     PG_PRE_SCHEDULING, PG_RUNNING,
                                     PG_SCHEDULED, PG_SCHEDULING)
from tpusched.apiserver import APIServer
from tpusched.apiserver import server as srv
from tpusched.controllers import (ControllerRunner, ElasticQuotaController,
                                  PodGroupController, ServerRunOptions,
                                  WorkQueue)
from tpusched.testing import wait_until, make_elastic_quota, make_pod, make_pod_group


def pg_phase(api, key):
    pg = api.try_get(srv.POD_GROUPS, key)
    return pg.status.phase if pg else None


def test_workqueue_dedup_and_done():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    assert len(q) == 1
    item = q.get(timeout=1)
    assert item == "a"
    q.add("a")  # re-added while processing → dirty
    assert q.get(timeout=0.05) is None
    q.done("a")
    assert q.get(timeout=1) == "a"


def test_podgroup_phase_progression():
    api = APIServer()
    ctrl = PodGroupController(api)
    ctrl.run()
    try:
        pg = make_pod_group("gang", min_member=2)
        api.create(srv.POD_GROUPS, pg)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_PENDING)

        # two member pods exist → PreScheduling
        pods = [make_pod(f"m{i}", pod_group="gang") for i in range(2)]
        for p in pods:
            api.create(srv.PODS, p)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_PRE_SCHEDULING)

        # scheduler-side PostBind would set Scheduling + scheduled count
        def to_scheduling(o):
            o.status.phase = PG_SCHEDULING
            o.status.scheduled = 2
        api.patch(srv.POD_GROUPS, pg.key, to_scheduling)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_SCHEDULED)

        # pods running → Running
        for p in pods:
            api.patch(srv.PODS, p.key,
                      lambda o: setattr(o.status, "phase", POD_RUNNING))
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_RUNNING)

        # pods succeed → Finished
        for p in pods:
            api.patch(srv.PODS, p.key,
                      lambda o: setattr(o.status, "phase", POD_SUCCEEDED))
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_FINISHED)
    finally:
        ctrl.stop()


def test_podgroup_failure_counted():
    api = APIServer()
    ctrl = PodGroupController(api)
    ctrl.run()
    try:
        pg = make_pod_group("gang", min_member=2)
        api.create(srv.POD_GROUPS, pg)
        pods = [make_pod(f"m{i}", pod_group="gang") for i in range(2)]
        for p in pods:
            api.create(srv.PODS, p)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_PRE_SCHEDULING)
        api.patch(srv.POD_GROUPS, pg.key,
                  lambda o: setattr(o.status, "phase", PG_SCHEDULING))
        api.patch(srv.PODS, pods[0].key,
                  lambda o: setattr(o.status, "phase", POD_FAILED))
        api.patch(srv.PODS, pods[1].key,
                  lambda o: setattr(o.status, "phase", POD_RUNNING))
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_FAILED)
        pg_obj = api.get(srv.POD_GROUPS, pg.key)
        assert pg_obj.status.failed == 1 and pg_obj.status.running == 1
    finally:
        ctrl.stop()


def test_elasticquota_used_recompute():
    api = APIServer()
    ctrl = ElasticQuotaController(api)
    ctrl.run()
    try:
        eq = make_elastic_quota("quota-a", "team-a",
                                min={CPU: 4000, TPU: 8}, max={CPU: 8000, TPU: 16})
        api.create(srv.ELASTIC_QUOTAS, eq)
        # running pod counts; pending pod does not
        running = make_pod("r", namespace="team-a", requests={CPU: 1000, TPU: 4})
        pending = make_pod("p", namespace="team-a", requests={CPU: 500})
        api.create(srv.PODS, running)
        api.create(srv.PODS, pending)
        api.patch(srv.PODS, running.key,
                  lambda o: setattr(o.status, "phase", POD_RUNNING))

        def used_ok():
            got = api.get(srv.ELASTIC_QUOTAS, eq.key).status.used
            return got.get(CPU) == 1000 and got.get(TPU) == 4
        assert wait_until(used_ok)

        # pod deletion zeroes usage (zero-valued entries kept for min/max keys)
        api.delete(srv.PODS, running.key)
        def used_zero():
            got = api.get(srv.ELASTIC_QUOTAS, eq.key).status.used
            return got.get(CPU) == 0 and got.get(TPU) == 0
        assert wait_until(used_zero)
        assert any(e.reason == "Synced" for e in api.events())
    finally:
        ctrl.stop()


def test_leader_election_single_leader():
    api = APIServer()
    opts = ServerRunOptions(enable_leader_election=True, lease_duration_s=2.0,
                            renew_interval_s=0.2)
    r1 = ControllerRunner(api, opts)
    r2 = ControllerRunner(api, opts)
    r1.run()
    assert wait_until(lambda: r1.is_leader.is_set())
    r2.run()
    time.sleep(0.5)
    assert not r2.is_leader.is_set()   # lease held by r1
    r1.stop()
    # r2 takes over after the lease expires
    assert wait_until(lambda: r2.is_leader.is_set(), timeout=5)
    r2.stop()


# -- direct phase-machine tables (podgroup.go:185-303 edge coverage) ----------

def run_sync(pg, pods=(), clock=time.time):
    api = APIServer()
    api.create(srv.POD_GROUPS, pg)
    for p in pods:
        api.create(srv.PODS, p)
    ctrl = PodGroupController(api, clock=clock)  # workers not started
    err = ctrl.sync_handler(pg.key)
    assert err is None
    return api.get(srv.POD_GROUPS, pg.key), ctrl, api


def test_sync_empty_phase_becomes_pending():
    pg, _, _ = run_sync(make_pod_group("g", min_member=2))
    assert pg.status.phase == PG_PENDING


def test_sync_pending_stays_below_min_member():
    base = make_pod_group("g", min_member=3)
    base.status.phase = PG_PENDING
    pg, _, _ = run_sync(base, [make_pod(f"m{i}", pod_group="g")
                               for i in range(2)])
    assert pg.status.phase == PG_PENDING


def test_sync_prescheduling_fills_occupied_by_sorted():
    from tpusched.api.meta import OwnerReference
    base = make_pod_group("g", min_member=1)
    base.status.phase = PG_PENDING
    owner_pod = make_pod("m0", pod_group="g")
    owner_pod.meta.owner_references = [OwnerReference(name="job-b"),
                                       OwnerReference(name="job-a")]
    pg, _, _ = run_sync(base, [owner_pod])
    assert pg.status.phase == PG_PRE_SCHEDULING
    assert pg.status.occupied_by == "default/job-a;default/job-b"


def test_sync_all_pods_deleted_regresses_to_pending():
    base = make_pod_group("g", min_member=2)
    base.status.phase = PG_SCHEDULING
    pg, _, _ = run_sync(base, pods=())
    assert pg.status.phase == PG_PENDING


def test_sync_partial_quorum_failure_is_terminal():
    """failed + running + succeeded ≥ minMember with any failure ⇒ Failed
    (podgroup.go:255-265)."""
    base = make_pod_group("g", min_member=3)
    base.status.phase = PG_SCHEDULING
    base.status.scheduled = 3
    pods = [make_pod(f"m{i}", pod_group="g") for i in range(3)]
    pods[0].status.phase = POD_FAILED
    pods[1].status.phase = POD_SUCCEEDED
    pods[2].status.phase = POD_RUNNING
    pg, _, _ = run_sync(base, pods)
    assert pg.status.phase == PG_FAILED
    assert (pg.status.failed, pg.status.succeeded, pg.status.running) == (1, 1, 1)


def test_sync_finished_requires_min_member_successes():
    base = make_pod_group("g", min_member=2)
    base.status.phase = PG_SCHEDULED
    base.status.scheduled = 2
    pods = [make_pod(f"m{i}", pod_group="g") for i in range(2)]
    for p in pods:
        p.status.phase = POD_SUCCEEDED
    pg, _, _ = run_sync(base, pods)
    assert pg.status.phase == PG_FINISHED


def test_sync_no_change_no_patch():
    """Idempotent sync must not write (patch→event→resync loops)."""
    base = make_pod_group("g", min_member=2)
    base.status.phase = PG_PENDING
    api = APIServer()
    api.create(srv.POD_GROUPS, base)
    ctrl = PodGroupController(api)
    before = api.get(srv.POD_GROUPS, base.key).meta.resource_version
    assert ctrl.sync_handler(base.key) is None
    after = api.get(srv.POD_GROUPS, base.key).meta.resource_version
    assert before == after


def test_sync_deleted_group_is_not_an_error():
    api = APIServer()
    ctrl = PodGroupController(api)
    assert ctrl.sync_handler("default/ghost") is None


def test_stuck_group_not_enqueued():
    """Groups whose scheduling start lags creation by >48h are skipped
    (podgroup.go:122-126)."""
    api = APIServer()
    ctrl = PodGroupController(api)
    pg = make_pod_group("stuck", min_member=2)
    pg.meta.creation_timestamp = 1000.0
    pg.status.phase = PG_SCHEDULING
    pg.status.scheduled = 2
    pg.status.running = 0
    pg.status.schedule_start_time = 1000.0 + 49 * 3600
    ctrl._pg_added(pg)
    assert len(ctrl.queue) == 0
    fresh = make_pod_group("fresh", min_member=2)
    ctrl._pg_added(fresh)
    assert len(ctrl.queue) == 1


def test_terminal_groups_not_enqueued():
    api = APIServer()
    ctrl = PodGroupController(api)
    for phase in (PG_FINISHED, PG_FAILED):
        pg = make_pod_group(f"done-{phase}", min_member=1)
        pg.status.phase = phase
        ctrl._pg_added(pg)
    assert len(ctrl.queue) == 0


def test_workqueue_rate_limited_backoff():
    now = [1000.0]
    q = WorkQueue(clock=lambda: now[0])
    q.add_rate_limited("x")
    # within the 5 ms base backoff window the item is still delayed (fake
    # clock — no wall-time race; get()'s deadline also reads the fake clock,
    # so unavailability is asserted via the ready-queue length)
    assert len(q) == 0
    now[0] += 1.0  # past the backoff
    assert q.get(timeout=1) == "x"
    q.done("x")
    q.forget("x")
    q.add("x")
    assert q.get(timeout=1) == "x"
