"""Controller tests (reference analog: pkg/controller/{podgroup,elasticquota}_test.go
with fake clients; here against the in-memory API server)."""
import time

from tpusched.api.core import POD_RUNNING, POD_SUCCEEDED, POD_FAILED
from tpusched.api.resources import CPU, TPU
from tpusched.api.scheduling import (PG_FAILED, PG_FINISHED, PG_PENDING,
                                     PG_PRE_SCHEDULING, PG_RUNNING,
                                     PG_SCHEDULED, PG_SCHEDULING)
from tpusched.apiserver import APIServer
from tpusched.apiserver import server as srv
from tpusched.controllers import (ControllerRunner, ElasticQuotaController,
                                  PodGroupController, ServerRunOptions,
                                  WorkQueue)
from tpusched.testing import make_elastic_quota, make_pod, make_pod_group


def wait_until(fn, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def pg_phase(api, key):
    pg = api.try_get(srv.POD_GROUPS, key)
    return pg.status.phase if pg else None


def test_workqueue_dedup_and_done():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    assert len(q) == 1
    item = q.get(timeout=1)
    assert item == "a"
    q.add("a")  # re-added while processing → dirty
    assert q.get(timeout=0.05) is None
    q.done("a")
    assert q.get(timeout=1) == "a"


def test_podgroup_phase_progression():
    api = APIServer()
    ctrl = PodGroupController(api)
    ctrl.run()
    try:
        pg = make_pod_group("gang", min_member=2)
        api.create(srv.POD_GROUPS, pg)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_PENDING)

        # two member pods exist → PreScheduling
        pods = [make_pod(f"m{i}", pod_group="gang") for i in range(2)]
        for p in pods:
            api.create(srv.PODS, p)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_PRE_SCHEDULING)

        # scheduler-side PostBind would set Scheduling + scheduled count
        def to_scheduling(o):
            o.status.phase = PG_SCHEDULING
            o.status.scheduled = 2
        api.patch(srv.POD_GROUPS, pg.key, to_scheduling)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_SCHEDULED)

        # pods running → Running
        for p in pods:
            api.patch(srv.PODS, p.key,
                      lambda o: setattr(o.status, "phase", POD_RUNNING))
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_RUNNING)

        # pods succeed → Finished
        for p in pods:
            api.patch(srv.PODS, p.key,
                      lambda o: setattr(o.status, "phase", POD_SUCCEEDED))
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_FINISHED)
    finally:
        ctrl.stop()


def test_podgroup_failure_counted():
    api = APIServer()
    ctrl = PodGroupController(api)
    ctrl.run()
    try:
        pg = make_pod_group("gang", min_member=2)
        api.create(srv.POD_GROUPS, pg)
        pods = [make_pod(f"m{i}", pod_group="gang") for i in range(2)]
        for p in pods:
            api.create(srv.PODS, p)
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_PRE_SCHEDULING)
        api.patch(srv.POD_GROUPS, pg.key,
                  lambda o: setattr(o.status, "phase", PG_SCHEDULING))
        api.patch(srv.PODS, pods[0].key,
                  lambda o: setattr(o.status, "phase", POD_FAILED))
        api.patch(srv.PODS, pods[1].key,
                  lambda o: setattr(o.status, "phase", POD_RUNNING))
        assert wait_until(lambda: pg_phase(api, pg.key) == PG_FAILED)
        pg_obj = api.get(srv.POD_GROUPS, pg.key)
        assert pg_obj.status.failed == 1 and pg_obj.status.running == 1
    finally:
        ctrl.stop()


def test_elasticquota_used_recompute():
    api = APIServer()
    ctrl = ElasticQuotaController(api)
    ctrl.run()
    try:
        eq = make_elastic_quota("quota-a", "team-a",
                                min={CPU: 4000, TPU: 8}, max={CPU: 8000, TPU: 16})
        api.create(srv.ELASTIC_QUOTAS, eq)
        # running pod counts; pending pod does not
        running = make_pod("r", namespace="team-a", requests={CPU: 1000, TPU: 4})
        pending = make_pod("p", namespace="team-a", requests={CPU: 500})
        api.create(srv.PODS, running)
        api.create(srv.PODS, pending)
        api.patch(srv.PODS, running.key,
                  lambda o: setattr(o.status, "phase", POD_RUNNING))

        def used_ok():
            got = api.get(srv.ELASTIC_QUOTAS, eq.key).status.used
            return got.get(CPU) == 1000 and got.get(TPU) == 4
        assert wait_until(used_ok)

        # pod deletion zeroes usage (zero-valued entries kept for min/max keys)
        api.delete(srv.PODS, running.key)
        def used_zero():
            got = api.get(srv.ELASTIC_QUOTAS, eq.key).status.used
            return got.get(CPU) == 0 and got.get(TPU) == 0
        assert wait_until(used_zero)
        assert any(e.reason == "Synced" for e in api.events())
    finally:
        ctrl.stop()


def test_leader_election_single_leader():
    api = APIServer()
    opts = ServerRunOptions(enable_leader_election=True, lease_duration_s=2.0,
                            renew_interval_s=0.2)
    r1 = ControllerRunner(api, opts)
    r2 = ControllerRunner(api, opts)
    r1.run()
    assert wait_until(lambda: r1.is_leader.is_set())
    r2.run()
    time.sleep(0.5)
    assert not r2.is_leader.is_set()   # lease held by r1
    r1.stop()
    # r2 takes over after the lease expires
    assert wait_until(lambda: r2.is_leader.is_set(), timeout=5)
    r2.stop()
