"""tpuverify kernel + explorer unit tests: cooperative determinism,
strategies, trace canonicalization (DPOR-style pruning), schedule
artifacts, deterministic replay, divergence detection, and the
Condition-wait hand-off accounting (the C7-stays-exact satellite)."""
from __future__ import annotations

import json
import random
import time
from types import SimpleNamespace

import pytest

from tpusched import verify
from tpusched.util import locking
from tpusched.verify.explorer import canonical_trace_key
from tpusched.verify.scenarios import Scenario


@pytest.fixture(autouse=True)
def _clean_lock_state():
    """The explorer saves/restores debug mode and the hook itself, but a
    crashed assertion must not leak state into unrelated tests."""
    yield
    locking.set_verify_hook(None)
    locking.set_debug(False)
    locking.recorder().reset()


EX = verify.Explorer()


# -- soundness + non-vacuity on the selfchecks --------------------------------


def test_atomic_selfcheck_never_fails():
    rep = EX.explore(verify.SCENARIOS["selfcheck-atomic-update"],
                     seed=11, schedules=40)
    assert rep.failures == 0, rep.first_failure


def test_lost_update_found_and_artifact_captured():
    rep = EX.explore(verify.SCENARIOS["selfcheck-lost-update"],
                     seed=11, schedules=40)
    assert rep.failures == 1
    art = rep.first_failure
    assert art is not None
    verify.validate_artifact(art)
    assert "lost update" in art["failure"]
    assert art["decisions"]                      # a real decision list


def test_broken_arming_guard_found():
    rep = EX.explore(verify.SCENARIOS["selfcheck-broken-arming"],
                     seed=5, schedules=80)
    assert rep.failures == 1, "explorer missed the seeded arming-guard bug"
    assert "arming guard" in rep.first_failure["failure"]


def test_explored_schedules_are_instrumented():
    """Non-vacuity: the recorder actually observed acquisitions — the
    yield points were live, not silently skipped."""
    res = EX.run_schedule(verify.SCENARIOS["selfcheck-atomic-update"](),
                          verify.RandomWalk(random.Random(1)))
    assert res.ok and res.acquires > 0


# -- determinism + replay ------------------------------------------------------


def test_same_seed_same_schedules():
    a = EX.explore(verify.SCENARIOS["selfcheck-lost-update"],
                   seed=23, schedules=12, stop_on_failure=False)
    b = EX.explore(verify.SCENARIOS["selfcheck-lost-update"],
                   seed=23, schedules=12, stop_on_failure=False)
    assert a.failures == b.failures
    assert a.distinct_traces == b.distinct_traces
    assert (a.first_failure is None) == (b.first_failure is None)
    if a.first_failure:
        assert a.first_failure["decisions"] == b.first_failure["decisions"]


def test_replay_reproduces_failure_twice():
    rep = EX.explore(verify.SCENARIOS["selfcheck-lost-update"],
                     seed=3, schedules=40)
    art = rep.first_failure
    assert art is not None
    for _ in range(2):                       # determinism, not luck
        res = verify.replay_artifact(art)
        assert not res.ok
        assert res.failure == art["failure"]


def test_replay_of_clean_schedule_stays_clean():
    res = EX.run_schedule(verify.SCENARIOS["selfcheck-atomic-update"](),
                          verify.RandomWalk(random.Random(9)))
    assert res.ok
    art = verify.make_artifact("selfcheck-atomic-update", seed="9",
                               strategy="random-walk",
                               decisions=res.decisions, failure=None,
                               steps=res.steps)
    rep = verify.replay_artifact(art)
    assert rep.ok and rep.failure is None


def test_replay_divergence_detected():
    rep = EX.explore(verify.SCENARIOS["selfcheck-lost-update"],
                     seed=3, schedules=40)
    art = dict(rep.first_failure)
    art["decisions"] = art["decisions"][:1]     # truncated: must diverge
    res = verify.replay_artifact(art)
    assert not res.ok
    assert "ReplayDivergence" in res.failure


def test_timeout_wake_decision_recorded_and_replayed():
    rep = EX.explore(verify.SCENARIOS["selfcheck-timeout-wake"],
                     seed=1, schedules=4, stop_on_failure=False)
    assert rep.failures == 0
    res = EX.run_schedule(verify.SCENARIOS["selfcheck-timeout-wake"](),
                          verify.RandomWalk(random.Random(1)))
    assert res.ok
    assert any(d.startswith("~") for d in res.decisions), (
        "a timed wait with no notifier must be woken by an explicit "
        "timeout-fire decision")
    art = verify.make_artifact("selfcheck-timeout-wake", seed="1",
                               strategy="random-walk",
                               decisions=res.decisions, failure=None,
                               steps=res.steps)
    assert verify.replay_artifact(art).ok


# -- artifact schema -----------------------------------------------------------


def test_artifact_round_trip(tmp_path):
    art = verify.make_artifact("s", seed="0:1", strategy="pct",
                               decisions=["T0", "~T1"], failure="boom",
                               steps=7)
    path = tmp_path / "a.json"
    verify.dump_artifact(art, str(path))
    loaded = verify.load_artifact(str(path))
    assert loaded == art
    json.loads(path.read_text())                 # plain JSON on disk


@pytest.mark.parametrize("mutate", [
    lambda a: a.update(version=99),
    lambda a: a.pop("scenario"),
    lambda a: a.update(decisions="T0"),
    lambda a: a.update(decisions=[1, 2]),
    lambda a: a.update(failure=17),
    lambda a: a.update(steps="many"),
])
def test_artifact_validation_rejects(mutate):
    art = verify.make_artifact("s", seed="0", strategy="pct",
                               decisions=["T0"], failure=None, steps=1)
    mutate(art)
    with pytest.raises(ValueError):
        verify.validate_artifact(art)


# -- trace canonicalization ----------------------------------------------------


def test_independent_ops_commute_dependent_do_not():
    a = [("T0", "acquire", "L1"), ("T1", "acquire", "L2")]
    swapped = list(reversed(a))
    assert canonical_trace_key(a) == canonical_trace_key(swapped)
    dep = [("T0", "acquire", "L1"), ("T1", "acquire", "L1")]
    dep_swapped = list(reversed(dep))
    assert canonical_trace_key(dep) != canonical_trace_key(dep_swapped)


def test_program_order_is_dependence():
    t = [("T0", "acquire", "L1"), ("T0", "release", "L1")]
    assert canonical_trace_key(t) != canonical_trace_key(list(reversed(t)))


def test_exploration_prunes_equivalent_schedules():
    rep = EX.explore(verify.SCENARIOS["selfcheck-atomic-update"],
                     seed=2, schedules=30, stop_on_failure=False)
    assert rep.schedules == 30
    assert rep.distinct_traces < rep.schedules   # dedupe really happened
    assert rep.pruned == rep.schedules - rep.distinct_traces


def test_pct_change_points_fire_within_horizon():
    """PCT's whole value over a random walk is the mid-schedule
    preemption; with change points sampled inside the schedule horizon
    the favored worker must actually lose the CPU at least once."""
    strat = verify.PCT(random.Random(4), depth=3, horizon=10)
    picks = [strat.choose(["T0", "T1"]) for _ in range(12)]
    assert len(set(picks)) == 2, (
        f"no preemption in 12 steps with horizon=10: {picks} — PCT "
        f"degenerated to fixed priorities")


# -- modeled deadlock detection ------------------------------------------------


class _ABBADeadlock(Scenario):
    name = "test-abba"

    def setup(self):
        ctx = SimpleNamespace()
        ctx.a = locking.GuardedLock("verify.test.A")
        ctx.b = locking.GuardedLock("verify.test.B")
        return ctx

    def threads(self, ctx):
        def ab():
            with ctx.a:
                with ctx.b:
                    pass

        def ba():
            with ctx.b:
                with ctx.a:
                    pass

        return [ab, ba]


class _NonReentrantSelfDeadlock(Scenario):
    name = "test-self-deadlock"

    def setup(self):
        ctx = SimpleNamespace()
        ctx.lock = locking.GuardedLock("verify.test.NR", reentrant=False)
        return ctx

    def threads(self, ctx):
        def nest():
            with ctx.lock:
                with ctx.lock:
                    pass

        return [nest]

    def check(self, ctx):
        raise AssertionError("schedule completed despite a self-deadlock")


def test_nonreentrant_self_reacquire_reported_not_hung():
    """Re-acquiring a non-reentrant lock the worker already holds must be
    reported as a modeled self-deadlock immediately — not granted by the
    model only to hang on the real lock for the whole hang timeout."""
    t0 = time.monotonic()
    res = EX.run_schedule(_NonReentrantSelfDeadlock(),
                          verify.RandomWalk(random.Random(0)))
    assert not res.ok
    assert "self-deadlock" in res.failure, res.failure
    assert time.monotonic() - t0 < 5.0, "burned the hang timeout"


def test_abba_reported_as_deadlock_or_cycle():
    """Every schedule either interleaves into the modeled deadlock or
    records both edges — the lock-order cycle.  Nothing hangs."""
    failures = []
    for i in range(20):
        res = EX.run_schedule(_ABBADeadlock(),
                              verify.RandomWalk(random.Random(i)))
        if not res.ok:
            failures.append(res.failure)
    assert failures, "AB/BA never detected across 20 schedules"
    assert all("deadlock" in f or "cycle" in f for f in failures), failures


# -- Condition hand-off accounting (the C7 satellite) -------------------------


def _flatten(trace_key):
    return [op for layer in trace_key for op in layer]


def test_cond_handoff_accounting_survives_forced_interleavings():
    """A notify delivered while the waiter sits between release and
    re-acquire must not corrupt the per-thread lock-stack accounting:
    every schedule stays violation-free AND at least one explored
    schedule actually witnesses the wait → notify hand-off (the C7
    witness is non-vacuous)."""
    witnessed = 0
    for i in range(24):
        res = EX.run_schedule(verify.SCENARIOS["cond-handoff"](),
                              verify.RandomWalk(random.Random(i)))
        assert res.ok, res.failure
        assert res.acquires > 0
        ops = _flatten(res.trace_key)
        if any(k == "wait" for _, k, _ in ops) \
                and any(k == "notify" for _, k, _ in ops):
            witnessed += 1
    assert witnessed > 0, (
        "no explored schedule exercised the wait/notify hand-off — "
        "the regression this test pins would go untested")


class _SingleNotify(Scenario):
    """Two timed waiters, ONE notify(1): the model must wake at most one
    waiter by notify (FIFO, like the stdlib waiter list) — modeling
    notify(1) as notify-all would wake both."""

    name = "test-single-notify"

    def setup(self):
        ctx = SimpleNamespace(wakes=[])
        ctx.lock = locking.GuardedLock("verify.test.n1")
        ctx.cond = locking.GuardedCondition(ctx.lock)
        return ctx

    def threads(self, ctx):
        def waiter():
            with ctx.cond:
                ctx.wakes.append(bool(ctx.cond.wait(0.01)))

        def notifier():
            with ctx.cond:
                ctx.cond.notify(1)

        return [waiter, waiter, notifier]

    def check(self, ctx):
        assert len(ctx.wakes) == 2
        assert ctx.wakes.count(True) <= 1, (
            f"notify(1) woke {ctx.wakes.count(True)} waiters — modeled "
            f"as notify_all")


def test_notify_one_wakes_at_most_one_waiter():
    single_wake_witnessed = 0
    for i in range(24):
        res = EX.run_schedule(_SingleNotify(),
                              verify.RandomWalk(random.Random(i)))
        assert res.ok, res.failure
        ops = _flatten(res.trace_key)
        if sum(1 for _, k, _ in ops if k == "wait") == 2 \
                and any(k == "notify" for _, k, _ in ops):
            single_wake_witnessed += 1
    assert single_wake_witnessed > 0, (
        "no schedule had both waiters parked when notify(1) fired — "
        "the single-wake path went untested")


def test_guarded_condition_plain_without_explorer():
    """Off the explorer, GuardedCondition is a stdlib Condition: wait
    times out for real, notify wakes a real waiter."""
    cond = locking.GuardedCondition(locking.GuardedLock("verify.test.cv"))
    with cond:
        assert cond.wait(0.01) is False
