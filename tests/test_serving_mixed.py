"""Mixed train + serve placement (round-2 verdict item 7's scheduler half).

The serving shape: fractional ``tpu-memory`` pods (KV-cache inference
servers, jaxbridge/decode.py) sharing hosts and chips by HBM-megabyte,
co-resident with a whole-chip training gang — the workload mix a real pool
runs. The reference's flexgpu plugin models the same mix as whole-GPU vs
GPU-memory pods (/root/reference/pkg/flexgpu/flex_gpu.go:41-119); here the
fractional unit is HBM on a chip and the gang side goes through full ICI
slice fitting.
"""
from tpusched.api.resources import TPU, TPU_MEMORY
from tpusched.api.topology import ACCELERATORS
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile, tpuslice_profile
from tpusched.plugins.tpuslice.chip_node import CHIP_INDEX_ANNOTATION as INDEX_ANNOTATION
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_node, make_tpu_pool)

HBM = ACCELERATORS["tpu-v5p"].hbm_mb_per_chip   # per chip, MB


def test_serving_pods_pack_chips_by_hbm():
    """Three half-chip servers on a 1-host pool: two share chip 0 (bin-pack
    by least remaining), the third lands on chip 1."""
    with TestCluster(profile=tpuslice_profile()) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        servers = [make_pod(f"s{i}", limits={TPU_MEMORY: HBM // 2})
                   for i in range(3)]
        c.create_pods(servers)
        assert c.wait_for_pods_scheduled([p.key for p in servers])
        by_chip = {}
        for p in servers:
            idx = c.pod(p.key).meta.annotations[INDEX_ANNOTATION]
            by_chip.setdefault(idx, []).append(p.name)
        assert sorted(len(v) for v in by_chip.values()) == [1, 2]


def test_train_gang_and_serving_share_pool():
    """A 4x4x2 training gang and HBM serving pods coexist in one v5p pool:
    the gang takes its contiguous half, servers fill the other hosts, and
    both see correct chip annotations."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                              denied_s=1)) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))  # 16 hosts
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "train", min_member=8, tpu_slice_shape="4x4x2",
            tpu_accelerator="tpu-v5p"))
        gang = [make_pod(f"train-{i}", pod_group="train", limits={TPU: 4})
                for i in range(8)]
        c.create_pods(gang)
        assert c.wait_for_pods_scheduled([p.key for p in gang], timeout=30)
        gang_hosts = {c.pod(p.key).spec.node_name for p in gang}
        assert len(gang_hosts) == 8

        # serving fleet: one full-chip-equivalent of HBM per host left free
        servers = [make_pod(f"serve-{i}", limits={TPU_MEMORY: HBM})
                   for i in range(8)]
        c.create_pods(servers)
        assert c.wait_for_pods_scheduled([p.key for p in servers],
                                         timeout=15)
        server_hosts = {c.pod(p.key).spec.node_name for p in servers}
        # servers must avoid the gang's fully-occupied hosts
        assert not (server_hosts & gang_hosts)
        for p in servers:
            assert INDEX_ANNOTATION in c.pod(p.key).meta.annotations


def test_serving_respects_gang_chip_occupancy():
    """On a host where the gang holds 3 of 4 chips, HBM servers can only use
    the remaining chip; oversubscription stays Pending."""
    with TestCluster(profile=tpuslice_profile()) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        train = [make_pod(f"t{i}", limits={TPU: 1}) for i in range(3)]
        c.create_pods(train)
        assert c.wait_for_pods_scheduled([p.key for p in train])
        fits = make_pod("serve-fits", limits={TPU_MEMORY: HBM})
        c.create_pods([fits])
        assert c.wait_for_pods_scheduled([fits.key])
        # the free chip is now limit-full: the next server cannot fit
        over = make_pod("serve-over", limits={TPU_MEMORY: HBM // 4})
        c.create_pods([over])
        assert c.wait_for_pods_unscheduled([over.key], hold=1.0)


def test_mixed_request_rejected():
    """A pod asking for whole chips AND fractional HBM is permanently
    unresolvable (flex_gpu.go:58-61 mutual exclusion)."""
    with TestCluster(profile=tpuslice_profile()) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        bad = make_pod("bad", limits={TPU: 1, TPU_MEMORY: 1024})
        c.create_pods([bad])
        assert c.wait_for_pods_unscheduled([bad.key], hold=1.0)
        events = [e for e in c.api.events()
                  if e.reason == "FailedScheduling" and "conflict" in e.message]
        assert events
