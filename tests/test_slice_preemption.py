"""Slice preemption (TopologyMatch PostFilter) — window-wise eviction for
slice-shaped gangs. No reference analog (the reference ships cross-node
preemption disabled and its NRT plugin never preempts); the contract pinned
here: a whole placement window's victims are evicted together, and every
victim must be eligible (priority rule OR quota-borrowing rule, minus
PreemptionToleration exemptions).
"""
from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import full_stack_profile
from tpusched.config.types import TopologyMatchArgs
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool, wait_until)


def cluster(enable=True, permit_wait_s=15):
    prof = full_stack_profile(permit_wait_s=permit_wait_s, denied_s=1)
    prof.plugin_args["TopologyMatch"] = TopologyMatchArgs(
        enable_slice_preemption=enable)
    return TestCluster(profile=prof)


def add_pool(c, dims=(4, 4, 4)):
    topo, nodes = make_tpu_pool("pool", dims=dims)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)


def slice_gang(c, name, members=16, shape="4x4x4", namespace="default",
               priority=0):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, namespace=namespace, min_member=members,
        tpu_slice_shape=shape, tpu_accelerator="tpu-v5p"))
    pods = [make_pod(f"{name}-{i}", namespace=namespace, pod_group=name,
                     limits={TPU: 4}, priority=priority)
            for i in range(members)]
    c.create_pods(pods)
    return pods


def test_high_priority_slice_gang_evicts_low_priority_slice():
    """Priority rule, no quotas involved: the resident low-priority slice is
    evicted window-wise and the high-priority gang takes the pool."""
    with cluster() as c:
        add_pool(c)
        low = slice_gang(c, "low", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=30)
        high = slice_gang(c, "high", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in high], timeout=30)
        assert all(c.pod(p.key) is None for p in low)
        evicted = [e for e in c.api.events() if e.reason == "Preempted"
                   and "Slice-preempted" in e.message]
        assert len(evicted) == 16


def test_equal_priority_no_quota_never_evicts():
    """Without a priority edge or a quota-borrowing edge there is no right
    to the window: the second gang stays pending."""
    with cluster(permit_wait_s=3) as c:
        add_pool(c)
        first = slice_gang(c, "first", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in first], timeout=30)
        second = slice_gang(c, "second", priority=10)
        assert c.wait_for_pods_unscheduled([p.key for p in second], hold=3.0)
        assert all(c.pod(p.key) is not None for p in first)


def test_toleration_exempt_victims_block_the_window():
    """A resident slice whose PriorityClass grants unlimited toleration must
    not be slice-preempted even by a higher-priority gang (composition with
    PreemptionToleration's policy annotations)."""
    from tests.test_misc_plugins import make_pc
    with cluster(permit_wait_s=3) as c:
        add_pool(c)
        c.api.create(srv.PRIORITY_CLASSES,
                     make_pc("tolerant", 10, minimum=100000, toleration=-1))
        low = slice_gang(c, "protected", priority=10)
        for p in low:
            c.api.patch(srv.PODS, p.key, lambda live: setattr(
                live.spec, "priority_class_name", "tolerant"))
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=30)
        high = slice_gang(c, "impatient", priority=1000)
        assert c.wait_for_pods_unscheduled([p.key for p in high], hold=3.0)
        assert all(c.pod(p.key) is not None for p in low)


def test_disabled_flag_never_evicts():
    with cluster(enable=False, permit_wait_s=3) as c:
        add_pool(c)
        low = slice_gang(c, "low", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=30)
        high = slice_gang(c, "high", priority=1000)
        assert c.wait_for_pods_unscheduled([p.key for p in high], hold=2.5)
        assert all(c.pod(p.key) is not None for p in low)


def test_cheapest_victim_window_chosen():
    """A full pool holds two resident slices at different priorities; the
    incoming top-priority gang must evict the LOWER-total-priority window
    and leave the other resident running (window ranking: PDB violations →
    victim count → total priority)."""
    with cluster() as c:
        add_pool(c, dims=(4, 4, 8))  # exactly two disjoint 4x4x4 windows
        cheap = slice_gang(c, "cheap", members=16, shape="4x4x4", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in cheap], timeout=30)
        dear = slice_gang(c, "dear", members=16, shape="4x4x4", priority=500)
        assert c.wait_for_pods_scheduled([p.key for p in dear], timeout=30)
        big = slice_gang(c, "big", members=16, shape="4x4x4", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in big], timeout=30)
        assert all(c.pod(p.key) is None for p in cheap)      # evicted window
        assert all(c.pod(p.key) is not None for p in dear)   # spared


def test_priority_never_breaks_foreign_team_min():
    """A quota-governed team running INSIDE its min is untouchable even by a
    much higher-priority foreign gang — priority does not bypass another
    team's guarantee (upstream CapacityScheduling only ever evicts over-min
    borrowers cross-namespace)."""
    from tpusched.testing import make_elastic_quota
    with cluster(permit_wait_s=3) as c:
        add_pool(c)
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "a-quota", "team-a", min={TPU: 64}, max={TPU: 64}))
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "b-quota", "team-b", min={TPU: 64}, max={TPU: 64}))
        resident = slice_gang(c, "guarded", namespace="team-a", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in resident],
                                         timeout=30)
        intruder = slice_gang(c, "vip", namespace="team-b", priority=10000)
        assert c.wait_for_pods_unscheduled([p.key for p in intruder],
                                           hold=3.0)
        assert all(c.pod(p.key) is not None for p in resident)


def test_borrow_eviction_capped_at_overage():
    """The window's foreign victims may only consume the victim team's
    overage (usage - min): a window whose eviction would push the team
    below min is ineligible."""
    from tpusched.testing import make_elastic_quota
    with cluster(permit_wait_s=3) as c:
        add_pool(c, dims=(4, 4, 8))   # 128 chips, two 4x4x4 windows
        # team-a min 96: two 64-chip slices = 128 used, overage only 32 —
        # NO 64-chip window is evictable without breaking a's min
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "a-quota", "team-a", min={TPU: 96}, max={TPU: 128}))
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "b-quota", "team-b", min={TPU: 64}, max={TPU: 128}))
        s1 = slice_gang(c, "a-one", namespace="team-a", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in s1], timeout=30)
        s2 = slice_gang(c, "a-two", namespace="team-a", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in s2], timeout=30)
        b = slice_gang(c, "b-wants", namespace="team-b", priority=10)
        assert c.wait_for_pods_unscheduled([p.key for p in b], hold=3.0)
        assert all(c.pod(p.key) is not None for p in s1 + s2)


def test_cordoned_window_host_vetoes_eviction():
    """If a window host would still fail other filters after eviction (here:
    cordoned), the window must not be evicted — destroying a resident
    workload cannot help the gang (post-eviction dry-run, the analog of
    upstream preemption's filter re-check)."""
    with cluster(permit_wait_s=3) as c:
        add_pool(c)
        low = slice_gang(c, "low", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=30)
        node_name = c.pod(low[0].key).spec.node_name
        node = next(n for n in c.api.list(srv.NODES)
                    if n.meta.name == node_name)
        c.api.patch(srv.NODES, node.meta.key,
                    lambda n: setattr(n.spec, "unschedulable", True))
        high = slice_gang(c, "high", priority=1000)
        assert c.wait_for_pods_unscheduled([p.key for p in high], hold=3.0)
        assert all(c.pod(p.key) is not None for p in low)  # untouched


def test_fractional_serving_victims_fall_under_priority_rule():
    """Mixed fleet: low-priority fractional (tpu-memory) serving pods inside
    the only window are evicted by a higher-priority training slice via the
    priority rule (chip borrowing never governs sub-chip pods); raise their
    priority and the window is vetoed."""
    from tpusched.api.resources import TPU_MEMORY
    with cluster(permit_wait_s=3) as c:
        add_pool(c)
        serving = [make_pod(f"serve-{i}", limits={TPU_MEMORY: 1024},
                            priority=10) for i in range(4)]
        c.create_pods(serving)
        assert c.wait_for_pods_scheduled([p.key for p in serving], timeout=20)
        train = slice_gang(c, "train", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in train], timeout=30)
        assert all(c.pod(p.key) is None for p in serving)  # evicted

    with cluster(permit_wait_s=3) as c2:
        add_pool(c2)
        vip = [make_pod(f"vip-{i}", limits={TPU_MEMORY: 1024},
                        priority=5000) for i in range(4)]
        c2.create_pods(vip)
        assert c2.wait_for_pods_scheduled([p.key for p in vip], timeout=20)
        train = slice_gang(c2, "train", priority=1000)
        assert c2.wait_for_pods_unscheduled([p.key for p in train], hold=3.0)
        assert all(c2.pod(p.key) is not None for p in vip)


def test_metrics_count_attempts_and_victims():
    from tpusched.util.metrics import (preemption_attempts,
                                       slice_preemption_victims)
    a0, v0 = preemption_attempts.value(), slice_preemption_victims.value()
    with cluster() as c:
        add_pool(c)
        low = slice_gang(c, "low", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=30)
        high = slice_gang(c, "high", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in high], timeout=30)
    assert preemption_attempts.value() == a0 + 1
    assert slice_preemption_victims.value() == v0 + 16


def test_pdb_protected_window_is_last_resort():
    """PDBs are soft, upstream-parity: a window whose victims violate a PDB
    ranks behind a violation-free window, but IS evicted (with the warning)
    when it is the only option."""
    from tpusched.api.core import PodDisruptionBudget
    from tpusched.api.meta import ObjectMeta
    # two windows: 'guarded' (PDB, no disruptions left) and 'plain'
    with cluster() as c:
        add_pool(c, dims=(4, 4, 8))
        c.api.create(srv.PDBS, PodDisruptionBudget(
            meta=ObjectMeta(name="guard", namespace="default"),
            selector={"app": "guarded"}, disruptions_allowed=0))
        guarded = slice_gang(c, "guarded", priority=10)
        for p in guarded:
            c.api.patch(srv.PODS, p.key,
                        lambda live: live.meta.labels.__setitem__(
                            "app", "guarded"))
        assert c.wait_for_pods_scheduled([p.key for p in guarded], timeout=30)
        plain = slice_gang(c, "plain", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in plain], timeout=30)
        big = slice_gang(c, "big", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in big], timeout=30)
        # the violation-free window was chosen
        assert all(c.pod(p.key) is None for p in plain)
        assert all(c.pod(p.key) is not None for p in guarded)

    # only option: the PDB-protected window is still evicted (soft PDBs)
    with cluster() as c2:
        add_pool(c2)
        c2.api.create(srv.PDBS, PodDisruptionBudget(
            meta=ObjectMeta(name="guard", namespace="default"),
            selector={"app": "guarded"}, disruptions_allowed=0))
        only = slice_gang(c2, "only", priority=10)
        for p in only:
            c2.api.patch(srv.PODS, p.key,
                         lambda live: live.meta.labels.__setitem__(
                             "app", "guarded"))
        assert c2.wait_for_pods_scheduled([p.key for p in only], timeout=30)
        big = slice_gang(c2, "big", priority=1000)
        assert c2.wait_for_pods_scheduled([p.key for p in big], timeout=30)
        assert all(c2.pod(p.key) is None for p in only)


def test_window_claims_guard_the_freed_window():
    """The freed window is CLAIMED by the evictor (the nominatedNodeName
    analog for gangs): another slice gang's PreFilter must not see the
    claimed hosts as free, and plain TPU pods — whole-chip AND fractional —
    are rejected there THROUGH THE FRAMEWORK DISPATCH (PreFilter Skip would
    suppress our Filter; live claims must disable the skip), while the
    claimant itself still places and non-TPU pods are untouched."""
    from tpusched.api.resources import TPU, TPU_MEMORY
    from tpusched.fwk import CycleState
    from tpusched.testing import (make_pod, make_pod_group, make_tpu_pool,
                                  new_test_framework)
    from tpusched.config.profiles import full_stack_profile

    topo, nodes = make_tpu_pool("pool", dims=(4, 4, 4))  # ONE 4x4x4 window
    fw, handle, api = new_test_framework(full_stack_profile(), nodes=nodes)
    api.create(srv.TPU_TOPOLOGIES, topo)
    for name in ("claimant", "rival"):
        api.create(srv.POD_GROUPS, make_pod_group(
            name, min_member=16, tpu_slice_shape="4x4x4",
            tpu_accelerator="tpu-v5p"))
    tm = fw.plugins["TopologyMatch"]

    # simulate the eviction's claim: every host of the pool for 'claimant'
    tm._window_claims.set("default/claimant",
                          (topo.key, frozenset(n.meta.name for n in nodes)))

    rival_pod = make_pod("r0", pod_group="rival", limits={TPU: 4})
    st = tm.pre_filter(CycleState(), rival_pod)
    assert st.is_unschedulable()          # claimed hosts are not free

    mine = make_pod("c0", pod_group="claimant", limits={TPU: 4})
    assert tm.pre_filter(CycleState(), mine).is_success()  # claimant exempt

    ni = handle.snapshot_shared_lister().get(nodes[0].name)

    def framework_filter_verdict(pod):
        """The REAL dispatch: PreFilter (with skip bookkeeping) then Filter."""
        state = CycleState()
        st = fw.run_pre_filter_plugins(state, pod)
        if not st.is_success():
            return st
        return fw.run_filter_plugins(state, pod, ni)

    # whole-chip and fractional TPU pods are both rejected on claimed hosts
    st = framework_filter_verdict(make_pod("plain", limits={TPU: 1}))
    assert st.is_unschedulable() and "claimed" in st.message()
    st = framework_filter_verdict(make_pod("frac",
                                           limits={TPU_MEMORY: 1024}))
    assert st.is_unschedulable() and "claimed" in st.message()
    # non-TPU pod unaffected
    assert framework_filter_verdict(make_pod("cpu-only")).is_success()

    # claim expiry frees everything
    tm._window_claims.delete("default/claimant")
    assert tm.pre_filter(CycleState(), rival_pod).is_success()
    assert framework_filter_verdict(make_pod("plain2",
                                             limits={TPU: 1})).is_success()


def test_scheduler_restart_mid_drain_recovers_without_second_eviction():
    """Claims are in-memory and die with the scheduler — by design (KEP-119):
    after a restart the victims are already gone, so the claimant finds the
    free window directly and no second eviction fires. Chaos shape: kill the
    scheduler immediately after the eviction, restart on the surviving API
    state."""
    from tpusched.testing import wait_until
    prof = full_stack_profile(permit_wait_s=15, denied_s=1)
    c = TestCluster(profile=prof)
    with c:
        add_pool(c)
        low = slice_gang(c, "low", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=30)
        high = slice_gang(c, "high", priority=1000)
        # wait for the eviction (victims deleted), then kill the scheduler
        assert wait_until(
            lambda: all(c.pod(p.key) is None for p in low), timeout=20)
        api = c.api
    # scheduler died mid-drain; control plane survived. Fresh scheduler:
    evictions_before = len([e for e in api.events()
                            if e.reason == "Preempted"])
    with TestCluster(profile=full_stack_profile(permit_wait_s=15,
                                                denied_s=1), api=api) as c2:
        high_keys = [f"default/high-{i}" for i in range(16)]
        assert c2.wait_for_pods_scheduled(high_keys, timeout=30)
        hosts = {c2.pod(k).spec.node_name for k in high_keys}
        assert len(hosts) == 16
    evictions_after = len([e for e in api.events()
                           if e.reason == "Preempted"])
    assert evictions_after == evictions_before  # no second eviction


def test_parked_permit_victims_rejected_in_place():
    """Victims that are PARKED at Permit (assumed, not bound) are evicted via
    the waiting-pod rejection path, not API deletion — their pods survive as
    pending objects and their chips free immediately."""
    with cluster(permit_wait_s=30) as c:
        add_pool(c)
        # under-capacity resident gang: 17 members wanted, 16 park forever
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "stuck", min_member=17, tpu_slice_shape="4x4x4",
            tpu_accelerator="tpu-v5p"))
        stuck = [make_pod(f"stuck-{i}", pod_group="stuck", limits={TPU: 4},
                          priority=10) for i in range(16)]
        c.create_pods(stuck)
        import time
        time.sleep(1.5)   # members parked at Permit, chips assumed
        assert all(not c.pod_scheduled(p.key) for p in stuck)
        high = slice_gang(c, "vip", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in high], timeout=30)
        # parked victims were rejected in place: pods still exist, unbound
        for p in stuck:
            live = c.pod(p.key)
            assert live is not None and not live.spec.node_name


def test_node_selector_mismatch_vetoes_eviction():
    """A gang whose nodeSelector matches none of the pool's hosts must not
    evict anything — the viability dry-run includes NodeSelector/NodeName,
    or preemption destroys a window the gang can never use (and repeats
    every drain TTL)."""
    with cluster(permit_wait_s=3) as c:
        add_pool(c)
        low = slice_gang(c, "low", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=30)
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "picky", min_member=16, tpu_slice_shape="4x4x4",
            tpu_accelerator="tpu-v5p"))
        picky = [make_pod(f"picky-{i}", pod_group="picky", limits={TPU: 4},
                          priority=1000,
                          node_selector={"zone": "nowhere"})
                 for i in range(16)]
        c.create_pods(picky)
        assert c.wait_for_pods_unscheduled([p.key for p in picky], hold=3.0)
        assert all(c.pod(p.key) is not None for p in low)  # untouched


def test_claim_released_when_pg_deleted():
    """Deleting the claimant PodGroup releases its freed-window claim at
    once — the evicted capacity must not idle out the drain TTL."""
    with cluster(permit_wait_s=15) as c:
        add_pool(c)
        tm = c.scheduler.framework.plugins["TopologyMatch"]
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "ghost", min_member=16, tpu_slice_shape="4x4x4",
            tpu_accelerator="tpu-v5p"))
        tm._window_claims.set("default/ghost", ("pool-key", frozenset({"h"})))
        c.api.delete(srv.POD_GROUPS, "default/ghost")
        from tpusched.testing import wait_until
        assert wait_until(
            lambda: "default/ghost" not in tm._window_claims, timeout=5)


def test_claim_released_when_gang_lands_elsewhere():
    """If another window frees first and the claimant binds there, its claim
    on the evicted window is dropped at Reserve time — rivals regain the
    hosts immediately."""
    with cluster(permit_wait_s=15) as c:
        add_pool(c, dims=(4, 4, 8))  # two disjoint 4x4x4 windows
        tm = c.scheduler.framework.plugins["TopologyMatch"]
        # resident occupies window A; claimant holds a (stale) claim on A's
        # hosts but window B is free — the gang binds in B and must release
        resident = slice_gang(c, "resident", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in resident],
                                         timeout=30)
        occupied = {c.pod(p.key).spec.node_name for p in resident}
        topo = c.api.list(srv.TPU_TOPOLOGIES)[0]
        tm._window_claims.set("default/mover", (topo.key,
                                                frozenset(occupied)))
        mover = slice_gang(c, "mover", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in mover], timeout=30)
        hosts = {c.pod(p.key).spec.node_name for p in mover}
        assert hosts.isdisjoint(occupied)
        assert "default/mover" not in tm._window_claims  # released


def test_window_eviction_vetoed_when_it_would_strand_a_gang():
    """Gang minMember disruption floor (the soak-caught bug): a 1-host
    window whose only victims are 1 of a running 16-member gang must be
    VETOED — evicting it would leave 15/16 running below quorum. The
    blocked gang stays pending; the big gang stays whole."""
    from tpusched.api.resources import TPU
    from tpusched.apiserver import server as srv
    from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                                  make_pod_group, make_tpu_pool)

    prof = full_stack_profile(permit_wait_s=5, denied_s=1)
    with TestCluster(profile=prof) as c:
        topo, nodes = make_tpu_pool("pool", dims=(4, 4, 4))   # 64 chips
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        for team in ("team-a", "team-b"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 32}, max={TPU: 128}))
        # team-b's 16-member gang fills the whole pool (borrowing 32 chips)
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "big", namespace="team-b", min_member=16,
            tpu_slice_shape="4x4x4", tpu_accelerator="tpu-v5p"))
        big = [make_pod(f"big-{i}", namespace="team-b", pod_group="big",
                        limits={TPU: 4}) for i in range(16)]
        c.create_pods(big)
        assert c.wait_for_pods_scheduled([p.key for p in big], timeout=30)
        # team-a's tiny gang (one host) is within ITS min and team-b is over
        # min by 32 chips — every borrow-rule gate passes; ONLY the gang
        # floor stands between the window and a stranded 15/16
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "tiny", namespace="team-a", min_member=1,
            tpu_slice_shape="2x2x1", tpu_accelerator="tpu-v5p"))
        tiny = make_pod("tiny-0", namespace="team-a", pod_group="tiny",
                        limits={TPU: 4})
        c.create_pods([tiny])
        assert c.wait_for_pods_unscheduled([tiny.key], hold=3.0)
        # the big gang is untouched: 16/16 still bound
        bound = [p for p in c.api.list(srv.PODS, "team-b")
                 if p.spec.node_name]
        assert len(bound) == 16


def test_window_veto_protects_label_only_gangs():
    """The floor's KEP-2 fallback: a gang admitted via labels alone (no
    PodGroup CR, minMember from the min-available label) is protected from
    partial window eviction exactly like a CR-backed gang."""
    from tpusched.api.resources import TPU
    from tpusched.api.scheduling import MIN_AVAILABLE_LABEL
    from tpusched.apiserver import server as srv
    from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                                  make_pod_group, make_tpu_pool)

    prof = full_stack_profile(permit_wait_s=5, denied_s=1)
    with TestCluster(profile=prof) as c:
        topo, nodes = make_tpu_pool("pool", dims=(4, 4, 4))   # 64 chips
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        for team in ("team-a", "team-b"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 32}, max={TPU: 128}))
        # label-only 16-member gang fills the pool (no CR anywhere)
        big = [make_pod(f"lbig-{i}", namespace="team-b", pod_group="lbig",
                        labels={MIN_AVAILABLE_LABEL: "16"},
                        limits={TPU: 4}) for i in range(16)]
        c.create_pods(big)
        assert c.wait_for_pods_scheduled([p.key for p in big], timeout=30)
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "tiny", namespace="team-a", min_member=1,
            tpu_slice_shape="2x2x1", tpu_accelerator="tpu-v5p"))
        tiny = make_pod("tiny-0", namespace="team-a", pod_group="tiny",
                        limits={TPU: 4})
        c.create_pods([tiny])
        assert c.wait_for_pods_unscheduled([tiny.key], hold=3.0)
        assert len([p for p in c.api.list(srv.PODS, "team-b")
                    if p.spec.node_name]) == 16


def test_atomic_set_member_not_evicted_while_siblings_bound():
    """SET disruption floor (soak seed 7): a bound member gang of an atomic
    2-slice set is not a valid victim window while its sibling slice is
    bound elsewhere — evicting it would strand the survivor forever (the
    set barrier never re-admits piecemeal). The high-priority rival must
    stay pending rather than half-kill the set."""
    from tpusched.testing import make_tpu_pool as _mk
    with cluster() as c:
        # two pools; the atomic set takes both
        for pool in ("pool-a", "pool-b"):
            topo, nodes = _mk(pool, dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        set_pods = []
        for idx in range(2):
            name = f"atom-s{idx}"
            c.api.create(srv.POD_GROUPS, make_pod_group(
                name, min_member=16, tpu_slice_shape="4x4x4",
                tpu_accelerator="tpu-v5p", multislice_set="atom",
                multislice_index=idx, multislice_set_size=2))
            set_pods += [make_pod(f"{name}-{i}", pod_group=name,
                                  limits={TPU: 4}, priority=10)
                         for i in range(16)]
        c.create_pods(set_pods)
        keys = [p.key for p in set_pods]
        assert c.wait_for_pods_scheduled(keys, timeout=30)

        rival = slice_gang(c, "rival", priority=1000)
        # the rival outranks the set but may not break it: nothing evicted
        assert c.wait_for_pods_unscheduled([p.key for p in rival], hold=3.0)
        assert all(c.pod(k) is not None and c.pod(k).spec.node_name
                   for k in keys), "set member was evicted"


def test_plain_gang_still_evictable_next_to_protected_set():
    """The set floor must not over-protect: with a plain low-priority gang
    on one pool and an atomic set pool-less, the rival evicts the plain
    gang's window, never the set's."""
    from tpusched.testing import make_tpu_pool as _mk
    with cluster() as c:
        for pool in ("pool-a", "pool-b", "pool-c"):
            topo, nodes = _mk(pool, dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        set_pods = []
        for idx in range(2):
            name = f"atom-s{idx}"
            c.api.create(srv.POD_GROUPS, make_pod_group(
                name, min_member=16, tpu_slice_shape="4x4x4",
                tpu_accelerator="tpu-v5p", multislice_set="atom",
                multislice_index=idx, multislice_set_size=2))
            set_pods += [make_pod(f"{name}-{i}", pod_group=name,
                                  limits={TPU: 4}, priority=10)
                         for i in range(16)]
        c.create_pods(set_pods)
        assert c.wait_for_pods_scheduled([p.key for p in set_pods],
                                         timeout=30)
        plain = slice_gang(c, "plain", priority=10)
        assert c.wait_for_pods_scheduled([p.key for p in plain], timeout=30)

        rival = slice_gang(c, "rival", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in rival], timeout=30)
        # the plain gang paid; the set is intact
        assert all(c.pod(p.key) is None for p in plain)
        assert all(c.pod(p.key).spec.node_name for p in set_pods)


def test_half_dead_set_stays_evictable():
    """The set floor must not pin a broken set's chips: once one member
    gang of an atomic set has degraded below its own quorum, the set
    provides nothing to protect — a high-priority rival may take the
    surviving slice's window (whole-gang-to-zero, per the gang floor)."""
    from tpusched.testing import make_tpu_pool as _mk
    with cluster() as c:
        for pool in ("pool-a", "pool-b"):
            topo, nodes = _mk(pool, dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        set_pods = {0: [], 1: []}
        for idx in range(2):
            name = f"atom-s{idx}"
            c.api.create(srv.POD_GROUPS, make_pod_group(
                name, min_member=16, tpu_slice_shape="4x4x4",
                tpu_accelerator="tpu-v5p", multislice_set="atom",
                multislice_index=idx, multislice_set_size=2))
            set_pods[idx] = [make_pod(f"{name}-{i}", pod_group=name,
                                      limits={TPU: 4}, priority=10)
                             for i in range(16)]
            c.create_pods(set_pods[idx])
        all_keys = [p.key for pods in set_pods.values() for p in pods]
        assert c.wait_for_pods_scheduled(all_keys, timeout=30)

        # degrade slice 0 below quorum: 4 members die and are not replaced
        for p in set_pods[0][:4]:
            c.api.delete(srv.PODS, p.key)

        rival = slice_gang(c, "rival", priority=1000)
        assert c.wait_for_pods_scheduled([p.key for p in rival], timeout=30)
        # one of the broken set's slices paid for it
        survivors = [k for k in all_keys if c.pod(k) is not None
                     and c.pod(k).spec.node_name]
        assert len(survivors) < 28
