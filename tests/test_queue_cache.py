"""Unit tests: scheduling queue ordering/backoff/moves and scheduler cache."""
import time

from tpusched.fwk.interfaces import (ClusterEvent, EVENT_ADD, RESOURCE_NODE,
                                     RESOURCE_POD_GROUP)
from tpusched.sched.cache import Cache
from tpusched.sched.queue import QueuedPodInfo, SchedulingQueue
from tpusched.api.resources import TPU
from tpusched.testing import make_node, make_pod, make_tpu_node


def prio_less(a, b):
    if a.pod.priority != b.pod.priority:
        return a.pod.priority > b.pod.priority
    return a.timestamp < b.timestamp


def test_queue_priority_order():
    q = SchedulingQueue(prio_less)
    q.add(make_pod("low", priority=1))
    q.add(make_pod("high", priority=10))
    q.add(make_pod("mid", priority=5))
    assert q.pop().pod.name == "high"
    assert q.pop().pod.name == "mid"
    assert q.pop().pod.name == "low"


def test_queue_fifo_within_priority():
    q = SchedulingQueue(prio_less)
    for i in range(5):
        q.add(make_pod(f"p{i}"))
    assert [q.pop().pod.name for _ in range(5)] == [f"p{i}" for i in range(5)]


def test_unschedulable_requeue_on_matching_event():
    event_map = {"PluginA": [ClusterEvent(RESOURCE_POD_GROUP, EVENT_ADD)]}
    q = SchedulingQueue(prio_less, event_map)
    info = QueuedPodInfo(make_pod("p"))
    info.attempts = 1
    info.unschedulable_plugins = {"PluginA"}
    q.add_unschedulable_if_not_present(info)
    assert q.pop(timeout=0.05) is None
    # non-matching event: stays parked
    q.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_ADD)
    assert q.pop(timeout=0.05) is None
    # matching event: requeued (after its backoff expires)
    q.move_all_to_active_or_backoff(RESOURCE_POD_GROUP, EVENT_ADD)
    got = q.pop(timeout=3.0)
    assert got is not None and got.pod.name == "p"


def test_activate_bypasses_unschedulable():
    q = SchedulingQueue(prio_less)
    pod = make_pod("gang-member")
    info = QueuedPodInfo(pod)
    info.unschedulable_plugins = {"Coscheduling"}
    info.attempts = 3
    q.add_unschedulable_if_not_present(info)
    q.activate([pod])
    got = q.pop(timeout=0.5)
    assert got is not None and got.pod.name == "gang-member"


def test_cache_assume_confirm_snapshot():
    c = Cache()
    c.add_node(make_node("n1"))
    pod = make_pod("p1", requests={"cpu": 1000})
    c.assume_pod(pod, "n1")
    snap = c.snapshot()
    assert len(snap.get("n1").pods) == 1
    # confirmation replaces assumed
    bound = pod.deepcopy()
    bound.spec.node_name = "n1"
    c.add_pod(bound)
    assert not c.is_assumed(pod.key)
    assert len(c.snapshot().get("n1").pods) == 1
    c.remove_pod(bound)
    assert len(c.snapshot().get("n1").pods) == 0


def test_cache_assumed_expires_without_confirmation():
    now = [100.0]
    c = Cache(clock=lambda: now[0])
    c.add_node(make_node("n1"))
    pod = make_pod("p1")
    c.assume_pod(pod, "n1")
    c.finish_binding(pod)
    now[0] += 31.0  # past ASSUME_EXPIRATION_S
    assert len(c.snapshot().get("n1").pods) == 0


def test_cache_incremental_snapshot_reuse_and_invalidation():
    """snapshot() reuses a node's clone while unchanged, re-clones on any
    change — and a node deleted and re-added with identical pod count must
    NOT serve the old clone (the per-instance-generation collision; upstream
    uses a global monotonic generation for exactly this)."""
    c = Cache()
    c.add_node(make_node("n1", capacity={"cpu": 8000, "pods": 10}))
    s1 = c.snapshot()
    s2 = c.snapshot()
    assert s2.get("n1") is s1.get("n1")  # unchanged → same clone object

    pod = make_pod("p1", requests={"cpu": 1000})
    c.assume_pod(pod, "n1")
    s3 = c.snapshot()
    assert s3.get("n1") is not s2.get("n1")
    assert len(s3.get("n1").pods) == 1

    # delete + re-add with smaller allocatable and the same pod re-attached:
    # the fresh NodeInfo's snapshot must reflect the NEW node object
    node_small = make_node("n1", capacity={"cpu": 1000, "pods": 10})
    c.remove_node(node_small)
    c.add_node(node_small)
    s4 = c.snapshot()
    assert s4.get("n1") is not s3.get("n1")
    assert s4.get("n1").allocatable["cpu"] == 1000
    assert len(s4.get("n1").pods) == 1  # known pod re-attached


# -- event-gating tables (the EnqueueExtensions contract end to end) ----------

def park(q, name, plugins, attempts=1, clock=None):
    info = QueuedPodInfo(make_pod(name), clock or time.time)
    info.attempts = attempts
    info.unschedulable_plugins = set(plugins)
    q.add_unschedulable_if_not_present(info)
    return info


def test_event_gating_table():
    from tpusched.fwk.interfaces import (EVENT_DELETE, EVENT_UPDATE,
                                         RESOURCE_POD, WILDCARD_EVENT)
    event_map = {
        "PodDel": [ClusterEvent(RESOURCE_POD, EVENT_DELETE)],
        "NodeAny": [ClusterEvent(RESOURCE_NODE, EVENT_ADD | EVENT_UPDATE)],
        "Wild": [WILDCARD_EVENT],
    }
    now = [1000.0]
    q = SchedulingQueue(prio_less, event_map, clock=lambda: now[0])

    cases = [
        # (rejector plugins, event, should_unstick)
        ({"PodDel"}, (RESOURCE_POD, EVENT_DELETE), True),
        ({"PodDel"}, (RESOURCE_POD, EVENT_ADD), False),
        ({"PodDel"}, (RESOURCE_NODE, EVENT_DELETE), False),
        ({"NodeAny"}, (RESOURCE_NODE, EVENT_UPDATE), True),
        ({"NodeAny"}, (RESOURCE_NODE, EVENT_ADD), True),
        ({"NodeAny"}, (RESOURCE_POD, EVENT_ADD), False),
        ({"Wild"}, ("anything", EVENT_UPDATE), True),
        # any-of semantics across multiple rejectors
        ({"PodDel", "NodeAny"}, (RESOURCE_NODE, EVENT_ADD), True),
        ({"PodDel", "NodeAny"}, (RESOURCE_POD, EVENT_UPDATE), False),
        # no recorded rejector ⇒ every event unsticks
        (set(), (RESOURCE_POD, EVENT_UPDATE), True),
        # unknown plugin (no map entry) ⇒ nothing unsticks it
        ({"Ghost"}, (RESOURCE_POD, EVENT_DELETE), False),
    ]
    for i, (plugins, (res, act), want) in enumerate(cases):
        park(q, f"c{i}", plugins, clock=lambda: now[0])
        q.move_all_to_active_or_backoff(res, act)
        # clear the ≤10s per-pod backoff but stay inside the 30s
        # unschedulable-leftover flush window (which would unstick anything)
        now[0] += 15
        got = q.pop(timeout=0.1)
        assert (got is not None) == want, (i, plugins, res, act)
        if got is not None:
            q.delete(got.pod)
        else:
            # clean up the parked pod for the next row
            q.activate([make_pod(f"c{i}")])
            left = q.pop(timeout=0.5)
            assert left is not None
            q.delete(left.pod)


def test_unstuck_pod_respects_remaining_backoff():
    """A matching event moves the pod to backoffQ, not straight to activeQ,
    while its per-pod backoff window is still open (fake clock)."""
    now = [1000.0]
    q = SchedulingQueue(prio_less, {"P": [ClusterEvent(RESOURCE_NODE,
                                                       EVENT_ADD)]},
                        clock=lambda: now[0])
    park(q, "p", {"P"}, attempts=3, clock=lambda: now[0])  # backoff 4s
    q.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_ADD)
    assert q.pop(timeout=0.05) is None       # still backing off
    now[0] += 4.1
    got = q.pop(timeout=0.5)
    assert got is not None and got.pod.name == "p"


def test_backoff_duration_exponential_with_cap():
    info = QueuedPodInfo(make_pod("p"))
    expect = {1: 1.0, 2: 2.0, 3: 4.0, 4: 8.0, 5: 10.0, 10: 10.0}
    for attempts, want in expect.items():
        info.attempts = attempts
        assert info.backoff_duration() == want, attempts


def test_delete_removes_from_every_queue():
    now = [1000.0]
    q = SchedulingQueue(prio_less, clock=lambda: now[0])
    # active
    q.add(make_pod("a"))
    # backoff (via requeue with nomination short-circuit)
    info_b = QueuedPodInfo(make_pod("b"), clock=lambda: now[0])
    info_b.attempts = 2
    q.requeue_after_failure(info_b, to_backoff=True)
    # unschedulable
    park(q, "c", {"X"}, clock=lambda: now[0])
    for name in ("a", "b", "c"):
        q.delete(make_pod(name))
    now[0] += 60
    assert q.pop(timeout=0.1) is None
    assert q.pending_counts() == {"active": 0, "backoff": 0,
                                  "unschedulable": 0}


def test_update_refreshes_pod_in_place():
    q = SchedulingQueue(prio_less)
    q.add(make_pod("p"))
    updated = make_pod("p", labels={"v": "2"})
    q.update(updated)
    got = q.pop(timeout=0.5)
    assert got.pod.meta.labels.get("v") == "2"


def make_queue():
    """(queue, mutable clock) with a controllable time source."""
    clock = [1000.0]
    q = SchedulingQueue(prio_less, clock=lambda: clock[0])
    return q, clock


def test_update_refreshes_pod_in_backoff_and_unschedulable():
    """update() must refresh the stored copy wherever the pod sits —
    backoffQ entries and unschedulableQ entries included."""
    q, clock = make_queue()
    p = make_pod("p")
    info = QueuedPodInfo(p, clock=lambda: clock[0])
    info.attempts = 1
    q.requeue_after_failure(info, to_backoff=True)   # parked in backoff
    p2 = make_pod("p", labels={"v": "2"})
    q.update(p2)
    clock[0] += 60                                   # backoff expired
    got = q.pop(timeout=0.2)
    assert got is not None and got.pod.meta.labels.get("v") == "2"

    info2 = QueuedPodInfo(make_pod("u"), clock=lambda: clock[0])
    q.requeue_after_failure(info2)                   # unschedulable
    u2 = make_pod("u", labels={"v": "3"})
    q.update(u2)
    q.activate([u2])
    got = q.pop(timeout=0.2)
    assert got is not None and got.pod.meta.labels.get("v") == "3"


def test_preemptor_requeues_straight_to_backoff():
    """to_backoff=True (a pod that just won preemption): no cluster event is
    coming — it must resurface from backoffQ by itself."""
    q, clock = make_queue()
    info = QueuedPodInfo(make_pod("winner"), clock=lambda: clock[0])
    info.attempts = 1
    q.requeue_after_failure(info, to_backoff=True)
    assert q.pending_counts()["backoff"] == 1
    assert q.pop(timeout=0.05) is None               # still backing off
    clock[0] += 60
    got = q.pop(timeout=0.2)
    assert got is not None and got.pod.name == "winner"


def test_close_unblocks_poppers():
    import threading
    q, clock = make_queue()
    results = []
    t = threading.Thread(target=lambda: results.append(q.pop(timeout=5)))
    t.start()
    q.close()
    t.join(timeout=2)
    assert not t.is_alive() and results == [None]


def test_add_unschedulable_if_not_present_is_idempotent():
    q, clock = make_queue()
    p = make_pod("p")
    q.add(p)  # active
    info = QueuedPodInfo(p, clock=lambda: clock[0])
    q.add_unschedulable_if_not_present(info)  # already active: no-op
    assert q.pending_counts() == {"active": 1, "backoff": 0,
                                  "unschedulable": 0}


def test_cache_bind_confirmation_replaces_assumed():
    """Watch-stream bound pod replaces the assumed copy (no double count)."""
    from tpusched.sched.cache import Cache
    cache = Cache()
    cache.add_node(make_tpu_node("n1", chips=4))
    p = make_pod("p", limits={TPU: 2})
    cache.assume_pod(p, "n1")
    assert cache.is_assumed("default/p")
    bound = make_pod("p", limits={TPU: 2}, node_name="n1")
    cache.add_pod(bound)                     # bind confirmation
    assert not cache.is_assumed("default/p")
    snap = cache.snapshot()
    assert len(snap.get("n1").pods) == 1     # replaced, not duplicated
    assert snap.get("n1").requested.get(TPU, 0) == 2


def test_cache_forget_releases_assumed_resources():
    from tpusched.sched.cache import Cache
    cache = Cache()
    cache.add_node(make_tpu_node("n1", chips=4))
    p = make_pod("p", limits={TPU: 4})
    cache.assume_pod(p, "n1")
    assert cache.snapshot().get("n1").requested.get(TPU, 0) == 4
    cache.forget_pod(p)
    assert not cache.is_assumed("default/p")
    assert cache.snapshot().get("n1").requested.get(TPU, 0) == 0


def test_cache_assumed_never_expires_before_binding_finishes():
    """The assume TTL arms only at finish_binding: a pod parked at a long
    Permit barrier must not be expired out of the cache mid-wait."""
    from tpusched.sched import cache as cache_mod
    clock = [1000.0]
    c = cache_mod.Cache(clock=lambda: clock[0])
    c.add_node(make_tpu_node("n1", chips=4))
    p = make_pod("p", limits={TPU: 1})
    c.assume_pod(p, "n1")
    clock[0] += 10 * cache_mod.ASSUME_EXPIRATION_S   # far past any TTL
    assert c.is_assumed("default/p")
    assert len(c.snapshot().get("n1").pods) == 1     # still held
    c.finish_binding(p)
    clock[0] += cache_mod.ASSUME_EXPIRATION_S + 1
    c.snapshot()                                     # triggers cleanup
    assert not c.is_assumed("default/p")


def test_cache_remove_node_keeps_pod_accounting_consistent():
    from tpusched.sched.cache import Cache
    cache = Cache()
    cache.add_node(make_tpu_node("n1", chips=4))
    bound = make_pod("p", limits={TPU: 2}, node_name="n1")
    cache.add_pod(bound)
    cache.remove_node(make_tpu_node("n1", chips=4))
    assert cache.snapshot().get("n1") is None
    # pod deletion after its node vanished must not raise
    cache.remove_pod(bound)


# -- configurable pod backoff (upstream podInitialBackoffSeconds) -------------

def test_queue_initial_backoff_configurable():
    """podInitialBackoffSeconds analog: a requeued-to-backoff pod serves the
    configured initial backoff, not the 1 s upstream default."""
    now = [100.0]
    q = SchedulingQueue(prio_less, clock=lambda: now[0],
                        initial_backoff_s=0.25)
    info = QueuedPodInfo(make_pod("p"), clock=lambda: now[0])
    info.attempts = 1
    q.requeue_after_failure(info, to_backoff=True)
    assert q.pop(timeout=0.01) is None          # still backing off
    now[0] += 0.3                               # past 0.25s, well before 1s
    got = q.pop(timeout=0.5)
    assert got is not None and got.pod.name == "p"


def test_queue_explicit_zero_backoff_is_immediate():
    """Explicit 0 means retry immediately (upstream allows 0); it must not
    be conflated with 'unset'."""
    q = SchedulingQueue(prio_less, initial_backoff_s=0.0, max_backoff_s=0.0)
    info = QueuedPodInfo(make_pod("p"))
    info.attempts = 3
    q.requeue_after_failure(info, to_backoff=True)
    got = q.pop(timeout=0.5)
    assert got is not None and got.pod.name == "p"


def test_queue_max_backoff_caps_growth():
    now = [100.0]
    q = SchedulingQueue(prio_less, clock=lambda: now[0],
                        initial_backoff_s=0.5, max_backoff_s=1.0)
    info = QueuedPodInfo(make_pod("p"), clock=lambda: now[0])
    info.attempts = 10                          # exponential would be huge
    q.requeue_after_failure(info, to_backoff=True)
    now[0] += 1.1                               # just past the 1 s cap
    got = q.pop(timeout=0.5)
    assert got is not None


def test_activate_noop_when_nothing_parked():
    """The O(1) early exit: activating pods that are all in-flight (neither
    unschedulable nor in backoff) moves nothing and breaks nothing."""
    q = SchedulingQueue(prio_less)
    q.add(make_pod("active-one"))
    q.activate([make_pod(f"sib-{i}") for i in range(50)])
    got = q.pop(timeout=0.2)
    assert got is not None and got.pod.name == "active-one"
    assert q.pop(timeout=0.05) is None          # siblings were not conjured


# -- incremental gang-assigned index (Permit quorum input) --------------------

def test_snapshot_assigned_count_incremental():
    """The cache maintains gang→assigned counts at attach/detach; the
    snapshot answers assigned_count without walking nodes."""
    c = Cache()
    for i in range(3):
        c.add_node(make_tpu_node(f"n{i}", chips=4))
    pods = [make_pod(f"g-{i}", pod_group="gang") for i in range(3)]
    for i, p in enumerate(pods):
        c.assume_pod(p, f"n{i}")
    assert c.snapshot().assigned_count("gang", "default") == 3
    # forget one assumed pod: count drops
    c.forget_pod(pods[0])
    assert c.snapshot().assigned_count("gang", "default") == 2
    # confirmation (add_pod) replaces assumed without double counting
    bound = pods[1].deepcopy()
    c.add_pod(bound)
    assert c.snapshot().assigned_count("gang", "default") == 2
    # node removal sheds its resident members
    c.remove_node(make_tpu_node("n2", chips=4))
    assert c.snapshot().assigned_count("gang", "default") == 1
    # node re-add re-attaches the still-known bound pod
    c.add_node(make_tpu_node("n2", chips=4))
    assert c.snapshot().assigned_count("gang", "default") == 2


# -- PreFilterResult.NodeNames analog (CycleState.restrict_nodes) -------------

def test_restrict_nodes_intersects_and_clones():
    from tpusched.fwk import CycleState
    s = CycleState()
    assert s.restricted_node_names is None
    s.restrict_nodes(["a", "b", "c"])
    s.restrict_nodes({"b", "c", "d"})
    assert s.restricted_node_names == {"b", "c"}
    c = s.clone()
    c.restrict_nodes({"b"})
    assert s.restricted_node_names == {"b", "c"}   # clone is isolated
    assert c.restricted_node_names == {"b"}


def test_pending_counts_exclude_backoff_tombstones():
    """The pending_pods{queue=backoff} gauge counts live entries only —
    activate() tombstones a backoff entry in place, and the tombstone must
    not show as a pending pod until the heap happens to drain."""
    q = SchedulingQueue(prio_less)
    pod = make_pod("p")
    info = QueuedPodInfo(pod)
    info.attempts = 5                    # long backoff so it stays parked
    q.requeue_after_failure(info, to_backoff=True)
    assert q.pending_counts()["backoff"] == 1
    q.activate([pod])                    # tombstones the heap entry
    assert q.pop(timeout=0.5) is not None
    assert q.pending_counts()["backoff"] == 0
