"""Unit tests: scheduling queue ordering/backoff/moves and scheduler cache."""
import time

from tpusched.fwk.interfaces import (ClusterEvent, EVENT_ADD, RESOURCE_NODE,
                                     RESOURCE_POD_GROUP)
from tpusched.sched.cache import Cache
from tpusched.sched.queue import QueuedPodInfo, SchedulingQueue
from tpusched.testing import make_node, make_pod


def prio_less(a, b):
    if a.pod.priority != b.pod.priority:
        return a.pod.priority > b.pod.priority
    return a.timestamp < b.timestamp


def test_queue_priority_order():
    q = SchedulingQueue(prio_less)
    q.add(make_pod("low", priority=1))
    q.add(make_pod("high", priority=10))
    q.add(make_pod("mid", priority=5))
    assert q.pop().pod.name == "high"
    assert q.pop().pod.name == "mid"
    assert q.pop().pod.name == "low"


def test_queue_fifo_within_priority():
    q = SchedulingQueue(prio_less)
    for i in range(5):
        q.add(make_pod(f"p{i}"))
    assert [q.pop().pod.name for _ in range(5)] == [f"p{i}" for i in range(5)]


def test_unschedulable_requeue_on_matching_event():
    event_map = {"PluginA": [ClusterEvent(RESOURCE_POD_GROUP, EVENT_ADD)]}
    q = SchedulingQueue(prio_less, event_map)
    info = QueuedPodInfo(make_pod("p"))
    info.attempts = 1
    info.unschedulable_plugins = {"PluginA"}
    q.add_unschedulable_if_not_present(info)
    assert q.pop(timeout=0.05) is None
    # non-matching event: stays parked
    q.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_ADD)
    assert q.pop(timeout=0.05) is None
    # matching event: requeued (after its backoff expires)
    q.move_all_to_active_or_backoff(RESOURCE_POD_GROUP, EVENT_ADD)
    got = q.pop(timeout=3.0)
    assert got is not None and got.pod.name == "p"


def test_activate_bypasses_unschedulable():
    q = SchedulingQueue(prio_less)
    pod = make_pod("gang-member")
    info = QueuedPodInfo(pod)
    info.unschedulable_plugins = {"Coscheduling"}
    info.attempts = 3
    q.add_unschedulable_if_not_present(info)
    q.activate([pod])
    got = q.pop(timeout=0.5)
    assert got is not None and got.pod.name == "gang-member"


def test_cache_assume_confirm_snapshot():
    c = Cache()
    c.add_node(make_node("n1"))
    pod = make_pod("p1", requests={"cpu": 1000})
    c.assume_pod(pod, "n1")
    snap = c.snapshot()
    assert len(snap.get("n1").pods) == 1
    # confirmation replaces assumed
    bound = pod.deepcopy()
    bound.spec.node_name = "n1"
    c.add_pod(bound)
    assert not c.is_assumed(pod.key)
    assert len(c.snapshot().get("n1").pods) == 1
    c.remove_pod(bound)
    assert len(c.snapshot().get("n1").pods) == 0


def test_cache_assumed_expires_without_confirmation():
    now = [100.0]
    c = Cache(clock=lambda: now[0])
    c.add_node(make_node("n1"))
    pod = make_pod("p1")
    c.assume_pod(pod, "n1")
    c.finish_binding(pod)
    now[0] += 31.0  # past ASSUME_EXPIRATION_S
    assert len(c.snapshot().get("n1").pods) == 0


def test_cache_incremental_snapshot_reuse_and_invalidation():
    """snapshot() reuses a node's clone while unchanged, re-clones on any
    change — and a node deleted and re-added with identical pod count must
    NOT serve the old clone (the per-instance-generation collision; upstream
    uses a global monotonic generation for exactly this)."""
    c = Cache()
    c.add_node(make_node("n1", capacity={"cpu": 8000, "pods": 10}))
    s1 = c.snapshot()
    s2 = c.snapshot()
    assert s2.get("n1") is s1.get("n1")  # unchanged → same clone object

    pod = make_pod("p1", requests={"cpu": 1000})
    c.assume_pod(pod, "n1")
    s3 = c.snapshot()
    assert s3.get("n1") is not s2.get("n1")
    assert len(s3.get("n1").pods) == 1

    # delete + re-add with smaller allocatable and the same pod re-attached:
    # the fresh NodeInfo's snapshot must reflect the NEW node object
    node_small = make_node("n1", capacity={"cpu": 1000, "pods": 10})
    c.remove_node(node_small)
    c.add_node(node_small)
    s4 = c.snapshot()
    assert s4.get("n1") is not s3.get("n1")
    assert s4.get("n1").allocatable["cpu"] == 1000
    assert len(s4.get("n1").pods) == 1  # known pod re-attached
