"""Unit tests: scheduling queue ordering/backoff/moves and scheduler cache."""
import time

from tpusched.fwk.interfaces import (ClusterEvent, EVENT_ADD, RESOURCE_NODE,
                                     RESOURCE_POD_GROUP)
from tpusched.sched.cache import Cache
from tpusched.sched.queue import QueuedPodInfo, SchedulingQueue
from tpusched.testing import make_node, make_pod


def prio_less(a, b):
    if a.pod.priority != b.pod.priority:
        return a.pod.priority > b.pod.priority
    return a.timestamp < b.timestamp


def test_queue_priority_order():
    q = SchedulingQueue(prio_less)
    q.add(make_pod("low", priority=1))
    q.add(make_pod("high", priority=10))
    q.add(make_pod("mid", priority=5))
    assert q.pop().pod.name == "high"
    assert q.pop().pod.name == "mid"
    assert q.pop().pod.name == "low"


def test_queue_fifo_within_priority():
    q = SchedulingQueue(prio_less)
    for i in range(5):
        q.add(make_pod(f"p{i}"))
    assert [q.pop().pod.name for _ in range(5)] == [f"p{i}" for i in range(5)]


def test_unschedulable_requeue_on_matching_event():
    event_map = {"PluginA": [ClusterEvent(RESOURCE_POD_GROUP, EVENT_ADD)]}
    q = SchedulingQueue(prio_less, event_map)
    info = QueuedPodInfo(make_pod("p"))
    info.attempts = 1
    info.unschedulable_plugins = {"PluginA"}
    q.add_unschedulable_if_not_present(info)
    assert q.pop(timeout=0.05) is None
    # non-matching event: stays parked
    q.move_all_to_active_or_backoff(RESOURCE_NODE, EVENT_ADD)
    assert q.pop(timeout=0.05) is None
    # matching event: requeued (after its backoff expires)
    q.move_all_to_active_or_backoff(RESOURCE_POD_GROUP, EVENT_ADD)
    got = q.pop(timeout=3.0)
    assert got is not None and got.pod.name == "p"


def test_activate_bypasses_unschedulable():
    q = SchedulingQueue(prio_less)
    pod = make_pod("gang-member")
    info = QueuedPodInfo(pod)
    info.unschedulable_plugins = {"Coscheduling"}
    info.attempts = 3
    q.add_unschedulable_if_not_present(info)
    q.activate([pod])
    got = q.pop(timeout=0.5)
    assert got is not None and got.pod.name == "gang-member"


def test_cache_assume_confirm_snapshot():
    c = Cache()
    c.add_node(make_node("n1"))
    pod = make_pod("p1", requests={"cpu": 1000})
    c.assume_pod(pod, "n1")
    snap = c.snapshot()
    assert len(snap.get("n1").pods) == 1
    # confirmation replaces assumed
    bound = pod.deepcopy()
    bound.spec.node_name = "n1"
    c.add_pod(bound)
    assert not c.is_assumed(pod.key)
    assert len(c.snapshot().get("n1").pods) == 1
    c.remove_pod(bound)
    assert len(c.snapshot().get("n1").pods) == 0


def test_cache_assumed_expires_without_confirmation():
    now = [100.0]
    c = Cache(clock=lambda: now[0])
    c.add_node(make_node("n1"))
    pod = make_pod("p1")
    c.assume_pod(pod, "n1")
    c.finish_binding(pod)
    now[0] += 31.0  # past ASSUME_EXPIRATION_S
    assert len(c.snapshot().get("n1").pods) == 0
