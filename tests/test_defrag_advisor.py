"""Defragmentation advisor: single-move migration plans on shadow state.

The scenario the advisor exists for: total free chips suffice but no
contiguous window does, and the advisor must find the one migration that
(a) admits the blocked job AND (b) re-places the migrated gang — never a
plan that orphans it."""
from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.sim import suggest_migrations
from tpusched.testing import TestCluster, make_pod, make_pod_group, make_tpu_pool

import pytest


def _pool(c, name="pool", dims=(4, 4, 4)):
    topo, nodes = make_tpu_pool(name, dims=dims)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)


def _gang(c, name, shape, members, namespace="default"):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, namespace=namespace, min_member=members,
        tpu_slice_shape=shape, tpu_accelerator="tpu-v5p"))
    ps = [make_pod(f"{name}-{i}", namespace=namespace, pod_group=name,
                   limits={TPU: 4}) for i in range(members)]
    c.create_pods(ps)
    assert c.wait_for_pods_scheduled([p.key for p in ps], timeout=30)
    return ps


def test_advisor_finds_the_unfragmenting_move():
    """Two pools. pool-a holds a small gang; pool-b is full. A pool-sized
    target (4x4x4 on the 64-chip pool-a) is blocked ONLY by the small
    gang — the advisor must name it, place the target on pool-a, and
    re-home the small gang into pool-b's remaining space."""
    with TestCluster() as c:
        # deterministic fragmentation: pool-a exists ALONE when the small
        # gang arrives, so it must land there and fragment it; the exactly-
        # gang-sized re-home pool appears only afterwards
        _pool(c, "pool-a", dims=(4, 4, 4))          # 64 chips
        _gang(c, "small", "2x2x4", 4)               # 16 chips, in pool-a
        _pool(c, "rehome", dims=(2, 2, 4))          # 16 chips, empty
        # a contiguous 4x4x4 (the whole of pool-a) fits nowhere now
        target = dict(members=16, slice_shape="4x4x4",
                      accelerator="tpu-v5p", chips_per_pod=4)
        from tpusched.sim import simulate_gang
        blocked = simulate_gang(source_api=c.api, timeout_s=4, **target)
        assert not blocked.feasible, "scenario must start blocked"
        plans = suggest_migrations(source_api=c.api, job=target,
                                   timeout_s=15)
        assert len(plans) == 1
        plan = plans[0]
        assert plan.migrate == "default/small"
        assert plan.migrate_chips == 16
        assert plan.target.feasible and len(plan.target.placements) == 16
        assert plan.target.pool == "pool-a"
        assert plan.resubmitted.feasible
        assert len(plan.resubmitted.placements) == 4
        assert plan.resubmitted.pool == "rehome"
        # the SOURCE cluster was never touched
        assert len([p for p in c.api.list(srv.PODS)
                    if p.spec.node_name]) == 4


def test_advisor_returns_empty_when_no_single_move_helps():
    """One full pool, target needs the whole pool: migrating any single
    resident gang cannot re-home it anywhere (no second pool), so the
    advisor must return no plan rather than an orphaning one."""
    with TestCluster() as c:
        _pool(c, "only", dims=(4, 4, 4))            # 64 chips
        _gang(c, "a", "4x4x2", 8)                   # 32
        _gang(c, "b", "4x4x2", 8)                   # 32 — pool full
        target = dict(members=16, slice_shape="4x4x4",
                      accelerator="tpu-v5p", chips_per_pod=4)
        plans = suggest_migrations(source_api=c.api, job=target,
                                   timeout_s=6)
        assert plans == []


def test_advisor_respects_candidate_restriction():
    """Restricting candidates to a gang whose migration cannot help (or to
    an unknown gang) yields no plan / a clear error — never a fallback to
    gangs the caller excluded."""
    with TestCluster() as c:
        _pool(c, "pool-a", dims=(4, 4, 4))
        _gang(c, "small", "2x2x4", 4)               # fragments pool-a
        _pool(c, "rehome", dims=(2, 2, 4))
        _gang(c, "other", "2x2x4", 4)               # fills the rehome pool
        target = dict(members=16, slice_shape="4x4x4",
                      accelerator="tpu-v5p", chips_per_pod=4)
        # migrating `other` frees the rehome pool but pool-a stays
        # fragmented by `small` — no plan from this candidate set
        plans = suggest_migrations(source_api=c.api, job=target,
                                   candidates=["default/other"],
                                   timeout_s=6)
        assert plans == []
        with pytest.raises(ValueError, match="unknown candidate"):
            suggest_migrations(source_api=c.api, job=target,
                               candidates=["default/nope"], timeout_s=4)


def test_advisor_cli(tmp_path):
    """End-to-end: persisted fragmented state; the CLI reports infeasible
    + a migration plan and exits 0."""
    import json
    import subprocess
    import sys
    from tpusched.apiserver import APIServer
    from tpusched.apiserver.persistence import attach

    api = APIServer()
    journal = attach(api, str(tmp_path))
    try:
        with TestCluster(api=api) as c:
            _pool(c, "pool-a", dims=(4, 4, 4))
            _gang(c, "small", "2x2x4", 4)
            _pool(c, "rehome", dims=(2, 2, 4))
        assert journal.flush(timeout=10)
    finally:
        journal.close()

    out = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.whatif",
         "--state-dir", str(tmp_path), "--members", "16",
         "--slice-shape", "4x4x4", "--accelerator", "tpu-v5p",
         "--chips", "4", "--timeout", "10", "--suggest-migrations", "1"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-300:]
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert lines[0]["feasible"] is False
    plan = lines[1]["migration_plan"]
    assert plan["migrate"] == "default/small"
    assert plan["target"]["feasible"] and plan["resubmitted"]["feasible"]


def test_pair_plan_when_no_single_move_helps():
    """VERDICT r3 #8: pool-a is fragmented by TWO small gangs; each has a
    dedicated-size re-home pool, but migrating either one alone leaves the
    other still fragmenting pool-a. max_moves=1 must find nothing;
    max_moves=2 must return the pair plan with both gangs re-homed."""
    with TestCluster() as c:
        _pool(c, "pool-a", dims=(4, 4, 4))          # 64 chips, alone first
        _gang(c, "frag-1", "2x2x4", 4)              # 16 chips, in pool-a
        _gang(c, "frag-2", "2x2x4", 4)              # 16 chips, in pool-a
        _pool(c, "rehome-1", dims=(2, 2, 4))        # 16 chips, empty
        _pool(c, "rehome-2", dims=(2, 2, 4))        # 16 chips, empty
        target = dict(members=16, slice_shape="4x4x4",
                      accelerator="tpu-v5p", chips_per_pod=4)
        from tpusched.sim import simulate_gang
        blocked = simulate_gang(source_api=c.api, timeout_s=4, **target)
        assert not blocked.feasible, "scenario must start blocked"

        assert suggest_migrations(source_api=c.api, job=target,
                                  timeout_s=8) == []
        plans = suggest_migrations(source_api=c.api, job=target,
                                   max_moves=2, timeout_s=15)
        assert len(plans) == 1
        plan = plans[0]
        assert {m.gang for m in plan.moves} == {"default/frag-1",
                                                "default/frag-2"}
        assert plan.migrate_chips == 32
        assert plan.target.feasible and plan.target.pool == "pool-a"
        rehomes = {m.resubmitted.pool for m in plan.moves}
        assert rehomes == {"rehome-1", "rehome-2"}
        d = plan.to_dict()
        assert len(d["moves"]) == 2 and "resubmitted" not in d
        # the SOURCE cluster was never touched
        assert len([p for p in c.api.list(srv.PODS)
                    if p.spec.node_name]) == 8


def test_pair_search_is_bounded():
    """max_pair_trials caps shadow runs: with a zero budget the pair phase
    must not run at all."""
    with TestCluster() as c:
        _pool(c, "pool-a", dims=(4, 4, 4))
        _gang(c, "frag-1", "2x2x4", 4)
        _gang(c, "frag-2", "2x2x4", 4)
        _pool(c, "rehome-1", dims=(2, 2, 4))
        _pool(c, "rehome-2", dims=(2, 2, 4))
        target = dict(members=16, slice_shape="4x4x4",
                      accelerator="tpu-v5p", chips_per_pod=4)
        assert suggest_migrations(source_api=c.api, job=target, max_moves=2,
                                  max_pair_trials=0, timeout_s=8) == []


def test_advisor_treats_atomic_set_as_one_unit():
    """Suggesting half an atomic multislice set is suggesting an outage:
    the advisor must return the WHOLE set as one plan (both member gangs
    in .moves), never a single-slice migration."""
    from tpusched.config.profiles import tpu_gang_profile
    from tpusched.config.types import MultiSliceArgs
    prof = tpu_gang_profile(permit_wait_s=10, denied_s=1)
    prof.plugin_args["MultiSlice"] = MultiSliceArgs(
        set_schedule_timeout_seconds=8, denied_set_expiration_time_seconds=1)
    with TestCluster(profile=prof) as c:
        _pool(c, "pool-a", dims=(4, 4, 4))
        set_keys = []
        for idx in range(2):
            name = f"ms-s{idx}"
            c.api.create(srv.POD_GROUPS, make_pod_group(
                name, min_member=4, tpu_slice_shape="2x2x4",
                tpu_accelerator="tpu-v5p", multislice_set="ms",
                multislice_index=idx, multislice_set_size=2))
            ps = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
                  for i in range(4)]
            c.create_pods(ps)
            set_keys += [p.key for p in ps]
        from tpusched.testing import wait_until
        assert c.wait_for_pods_scheduled(set_keys, timeout=30)
        _pool(c, "rehome", dims=(4, 4, 2))
        target = dict(members=16, slice_shape="4x4x4",
                      accelerator="tpu-v5p", chips_per_pod=4)
        plans = suggest_migrations(source_api=c.api, job=target,
                                   timeout_s=15)
        assert len(plans) == 1
        assert sorted(m.gang for m in plans[0].moves) == \
            ["default/ms-s0", "default/ms-s1"]
        assert plans[0].migrate_chips == 32
        # naming only one slice as a candidate must NOT move the set
        assert suggest_migrations(source_api=c.api, job=target,
                                  candidates=["default/ms-s0"],
                                  timeout_s=6) == []
