"""Node & slice failure resilience units: the node health model, the
heartbeat-driven lifecycle controller (NotReady/taint/eviction/orphan GC),
scheduler-cache reconciliation on node removal, the gang repair controller's
restart-gang/backfill policies, the stuck-gang watchdog, and readiness-aware
Filters. The multi-thousand-cycle composition is
tests/test_chaos_soak.py::test_node_churn_soak_no_wedged_gangs.
"""
import time

import pytest

from tpusched.api.core import (NODE_READY, NodeCondition, TAINT_NODE_NOT_READY,
                               Taint, node_health_error, node_ready)
from tpusched.api.resources import make_resources
from tpusched.api.scheduling import PG_PENDING, PG_SCHEDULING
from tpusched.apiserver import APIServer, Clientset
from tpusched.apiserver import server as srv
from tpusched.controllers import (GangRepairController,
                                  NodeLifecycleController,
                                  REPAIR_BACKFILL, REPAIR_POLICY_ANNOTATION)
from tpusched.sched.cache import ASSUME_EXPIRATION_S, Cache
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, wait_until)
from tpusched.util.metrics import (gang_repairs, gang_stuck_total,
                                   node_pod_evictions)


# -- node health model --------------------------------------------------------

def test_node_ready_defaults_and_conditions():
    n = make_node("n1")
    assert node_ready(n)                        # no condition = legacy-ready
    assert node_health_error(n) is None
    changed = n.set_condition(NODE_READY, "False", reason="HeartbeatMissed",
                              now=100.0)
    assert changed and not node_ready(n)
    assert "NotReady" in node_health_error(n)
    # same status again: no transition, timestamp pinned
    assert not n.set_condition(NODE_READY, "False", now=200.0)
    assert n.ready_condition().last_transition_time == 100.0
    assert n.set_condition(NODE_READY, "True", now=300.0)
    assert node_ready(n) and node_health_error(n) is None


def test_node_health_error_variants():
    assert node_health_error(make_node("u", unschedulable=True))
    tainted = make_node("t")
    tainted.spec.taints.append(Taint(key=TAINT_NODE_NOT_READY,
                                     effect="NoSchedule"))
    assert "not-ready taint" in node_health_error(tainted)


def test_node_deepcopy_carries_conditions_and_heartbeat():
    n = make_node("n1")
    n.status.last_heartbeat_time = 42.0
    n.status.conditions.append(NodeCondition(type=NODE_READY, status="False"))
    c = n.deepcopy()
    assert c.status.last_heartbeat_time == 42.0
    assert not node_ready(c)
    c.status.conditions[0].status = "True"
    assert not node_ready(n)                    # isolated copy


def test_heartbeat_client_verb_stamps_time():
    api = APIServer()
    api.create(srv.NODES, make_node("n1"))
    cs = Clientset(api)
    cs.nodes.heartbeat("n1", now=123.0)
    assert api.get(srv.NODES, "/n1").status.last_heartbeat_time == 123.0


# -- apiserver contracts the pipeline leans on --------------------------------

def test_bind_to_missing_node_is_not_found():
    """A bind racing a node deletion fails terminally — the gang-atomic
    rollback path's trigger for the permit→bind window."""
    from tpusched.api.core import Binding
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    with pytest.raises(srv.NotFound):
        api.bind(Binding(pod_key="default/p", node_name="ghost"))


def test_delete_uid_precondition():
    """DeleteOptions.Preconditions.UID analog: a stale sweep's delete must
    not kill a same-name replacement object."""
    api = APIServer()
    old = api.create(srv.PODS, make_pod("p"))
    api.delete(srv.PODS, "default/p", uid=old.meta.uid)   # exact match: ok
    fresh = api.create(srv.PODS, make_pod("p"))
    with pytest.raises(srv.Conflict):
        api.delete(srv.PODS, "default/p", uid=old.meta.uid)
    assert api.get(srv.PODS, "default/p").meta.uid == fresh.meta.uid
    api.delete(srv.PODS, "default/p")                     # unconditional: ok


# -- scheduler cache reconciliation on node removal ---------------------------

def test_remove_node_returns_affected_and_arms_assume_ttl():
    """Satellite regression: remove_node must not leak an eternal assume
    entry for in-flight binds on the vanished node — the TTL arms so the
    entry expires, while a node-object replacement still re-attaches
    (upstream RemoveNode semantics)."""
    t = [1000.0]
    c = Cache(clock=lambda: t[0])
    node = make_node("n1")
    c.add_node(node)
    pod = make_pod("g-0", pod_group="gang")
    c.assume_pod(pod, "n1")
    assert c.snapshot().assigned_count("gang", "default") == 1

    affected = c.remove_node(node)
    assert [p.key for p in affected] == ["default/g-0"]
    # quorum no longer counts the vanished node's member
    assert c.snapshot().assigned_count("gang", "default") == 0
    assert c.is_assumed("default/g-0")

    # node replaced before the TTL: the pod re-attaches (old contract)
    c.add_node(make_node("n1"))
    assert c.snapshot().assigned_count("gang", "default") == 1

    # node gone again, TTL lapses: the entry expires instead of leaking
    c.remove_node(node)
    t[0] += ASSUME_EXPIRATION_S + 1
    c.snapshot()                                  # expiry runs in snapshot
    assert not c.is_assumed("default/g-0")
    c.add_node(make_node("n1"))
    assert c.snapshot().assigned_count("gang", "default") == 0


# -- node lifecycle controller ------------------------------------------------

def _hb_node(api, name, hb=None):
    n = make_node(name)
    n.status.last_heartbeat_time = time.time() if hb is None else hb
    api.create(srv.NODES, n)
    return n


def test_lifecycle_marks_not_ready_taints_and_recovers():
    api = APIServer()
    _hb_node(api, "n1")
    ctrl = NodeLifecycleController(api, heartbeat_grace_s=0.2,
                                   pod_eviction_grace_s=5.0,
                                   sweep_interval_s=0.05)
    ctrl.run()
    try:
        assert wait_until(lambda: not node_ready(api.get(srv.NODES, "/n1")),
                          timeout=5.0)
        live = api.get(srv.NODES, "/n1")
        assert any(t.key == TAINT_NODE_NOT_READY for t in live.spec.taints)
        # heartbeat resumes → Ready again, taint removed
        Clientset(api).nodes.heartbeat("n1")
        assert wait_until(lambda: node_ready(api.get(srv.NODES, "/n1")),
                          timeout=5.0)
        assert not api.get(srv.NODES, "/n1").spec.taints
    finally:
        ctrl.stop()


def test_lifecycle_evicts_pods_after_grace_and_gcs_orphans():
    api = APIServer()
    _hb_node(api, "dead")
    api.create(srv.NODES, make_node("fixture"))   # no heartbeat: untouched
    api.create(srv.PODS, make_pod("victim", node_name="dead"))
    api.create(srv.PODS, make_pod("safe", node_name="fixture"))
    api.create(srv.PODS, make_pod("orphan", node_name="never-existed"))
    ev0 = node_pod_evictions.value()
    ctrl = NodeLifecycleController(api, heartbeat_grace_s=0.1,
                                   pod_eviction_grace_s=0.2,
                                   sweep_interval_s=0.05)
    ctrl.run()
    try:
        # orphan GC is immediate; NotReady eviction waits out the grace
        assert wait_until(
            lambda: api.try_get(srv.PODS, "default/orphan") is None,
            timeout=5.0)
        assert wait_until(
            lambda: api.try_get(srv.PODS, "default/victim") is None,
            timeout=5.0)
        assert api.try_get(srv.PODS, "default/safe") is not None
        assert node_pod_evictions.value() - ev0 >= 2
    finally:
        ctrl.stop()


# -- gang repair controller ---------------------------------------------------

def _gang_fixture(api, name, members, policy=None, bind_to=None):
    ann = {REPAIR_POLICY_ANNOTATION: policy} if policy else None
    pg = make_pod_group(name, min_member=members)
    if ann:
        pg.meta.annotations.update(ann)
    api.create(srv.POD_GROUPS, pg)
    pods = []
    for m in range(members):
        p = make_pod(f"{name}-m{m}", pod_group=name,
                     requests=make_resources(cpu=2))
        api.create(srv.PODS, p)
        if bind_to:
            from tpusched.api.core import Binding
            api.bind(Binding(pod_key=p.key, node_name=bind_to[m]))
        pods.append(p.key)
    return pods


def test_gang_repair_restart_gang_recreates_all_members():
    api = APIServer()
    api.create(srv.NODES, make_node("nx"))
    api.create(srv.NODES, make_node("ny"))
    repair = GangRepairController(api, cooldown_s=0.05)
    repair.run()
    rep0 = gang_repairs.value()
    try:
        keys = _gang_fixture(api, "g1", 3, bind_to=["nx", "nx", "ny"])
        api.patch(srv.POD_GROUPS, "default/g1",
                  lambda g: setattr(g.status, "phase", PG_SCHEDULING))
        survivors_uid = api.get(srv.PODS, keys[2]).meta.uid
        # the node dies; its two members are orphan-deleted (simulated here
        # directly — the lifecycle controller owns this in composition)
        api.delete(srv.NODES, "/nx")
        api.delete(srv.PODS, keys[0])
        api.delete(srv.PODS, keys[1])
        # restart-gang (default): survivor evicted too, ALL THREE recreated
        # fresh and unbound, PG rewound to Pending
        assert wait_until(
            lambda: all((api.try_get(srv.PODS, k) or make_pod("x")).meta.uid
                        not in ("", survivors_uid)
                        and api.try_get(srv.PODS, k) is not None
                        and not api.try_get(srv.PODS, k).spec.node_name
                        for k in keys), timeout=5.0)
        assert gang_repairs.value() - rep0 == 1
        pg = api.get(srv.POD_GROUPS, "default/g1")
        assert pg.status.phase == PG_PENDING
        assert pg.status.scheduled == 0
    finally:
        repair.stop()


def test_gang_repair_backfill_keeps_survivors():
    api = APIServer()
    api.create(srv.NODES, make_node("nx"))
    api.create(srv.NODES, make_node("ny"))
    repair = GangRepairController(api, cooldown_s=0.05)
    repair.run()
    try:
        keys = _gang_fixture(api, "g2", 3, policy=REPAIR_BACKFILL,
                             bind_to=["nx", "ny", "ny"])
        api.patch(srv.POD_GROUPS, "default/g2",
                  lambda g: setattr(g.status, "phase", PG_SCHEDULING))
        survivor_uids = {k: api.get(srv.PODS, k).meta.uid for k in keys[1:]}
        api.delete(srv.NODES, "/nx")
        api.delete(srv.PODS, keys[0])
        # only the lost member is recreated; survivors keep their identity
        assert wait_until(
            lambda: (api.try_get(srv.PODS, keys[0]) is not None
                     and not api.get(srv.PODS, keys[0]).spec.node_name),
            timeout=5.0)
        for k, uid in survivor_uids.items():
            live = api.get(srv.PODS, k)
            assert live.meta.uid == uid and live.spec.node_name == "ny"
        pg = api.get(srv.POD_GROUPS, "default/g2")
        assert pg.status.phase == PG_SCHEDULING
        assert pg.status.scheduled == 2
    finally:
        repair.stop()


def test_gang_repair_ignores_user_deletions_on_healthy_nodes():
    api = APIServer()
    api.create(srv.NODES, make_node("nz"))
    repair = GangRepairController(api, cooldown_s=0.05)
    repair.run()
    try:
        keys = _gang_fixture(api, "g3", 2, bind_to=["nz", "nz"])
        api.delete(srv.PODS, keys[0])      # node healthy: user intent
        time.sleep(0.4)
        assert api.try_get(srv.PODS, keys[0]) is None    # NOT resurrected
        assert api.try_get(srv.PODS, keys[1]) is not None  # survivor intact
    finally:
        repair.stop()


# -- stuck-gang watchdog ------------------------------------------------------

def test_watchdog_fires_on_no_progress_gang():
    """A gang that can never reach quorum (member count < minMember) makes
    no progress: the watchdog pins gang_stuck, bumps the metric, and
    publishes the health entry."""
    from tpusched import trace
    from tpusched.config.types import CoschedulingArgs
    from tpusched.fwk import PluginProfile

    profile = PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"],
        filter=["NodeUnschedulable", "NodeResourcesFit"],
        permit=["Coscheduling"],
        reserve=["Coscheduling"],
        bind=["DefaultBinder"],
        post_bind=["Coscheduling"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=60,
            denied_pg_expiration_time_seconds=0.1)},
        pod_initial_backoff_s=0.02, pod_max_backoff_s=0.1,
        stuck_gang_after_s=0.5, stuck_gang_sweep_interval_s=0.1)
    prev = trace.default_recorder()
    recorder = trace.install_recorder(trace.FlightRecorder())
    stuck0 = gang_stuck_total.value()
    with TestCluster(profile=profile) as cluster:
        try:
            cluster.add_nodes([make_node("n1")])
            api = cluster.api
            api.create(srv.POD_GROUPS, make_pod_group("wedge", min_member=3))
            # only one member ever exists: quorum can never form
            api.create(srv.PODS, make_pod("wedge-m0", pod_group="wedge",
                                          requests=make_resources(cpu=1)))
            assert wait_until(
                lambda: gang_stuck_total.value() - stuck0 >= 1, timeout=10.0)
            assert wait_until(lambda: any(
                a.get("kind") == "gang_stuck"
                for t in recorder.pinned_traces()
                for a in (t.anomalies or [])), timeout=5.0)
            # health entry may flicker for a sweep while the pod is popped
            # mid-cycle (absence grace covers it); poll rather than snapshot
            assert wait_until(
                lambda: recorder.dump().get("health", {}).get(
                    "stuck_gangs", {}).get("count", 0) >= 1, timeout=5.0)
        finally:
            trace.install_recorder(prev)


# -- readiness-aware filters (e2e) --------------------------------------------

def test_scheduler_avoids_not_ready_node_e2e():
    """A NotReady node absorbs no placements even with free capacity; the
    pod lands on the healthy node."""
    with TestCluster() as cluster:
        ready = make_node("ready-n")
        sick = make_node("sick-n")
        sick.set_condition(NODE_READY, "False", reason="HeartbeatMissed")
        cluster.add_nodes([ready, sick])
        pod = make_pod("p1", requests=make_resources(cpu=2))
        cluster.create_pods([pod])
        assert cluster.wait_for_pods_scheduled([pod.key], timeout=10.0)
        assert cluster.pod(pod.key).spec.node_name == "ready-n"


def test_node_delete_rejects_barrier_parked_members():
    """Members assumed on a node that is deleted while they wait at a
    permit barrier are rejected (reservations released) and the gang
    re-lands whole on replacement hardware. The MultiSlice SET barrier is
    the parked state here — a single gang's quorum barrier resolves the
    moment all members exist, but a set waiting for a sibling slice parks
    indefinitely, which is exactly the window a node death must not leak
    through (full window matrix in tests/test_resilience.py)."""
    from tpusched.config.types import CoschedulingArgs, MultiSliceArgs
    from tpusched.fwk import PluginProfile

    profile = PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling", "MultiSlice"],
        filter=["NodeUnschedulable", "NodeResourcesFit", "MultiSlice"],
        post_filter=["Coscheduling", "MultiSlice"],
        permit=["Coscheduling", "MultiSlice"],
        reserve=["Coscheduling", "MultiSlice"],
        bind=["DefaultBinder"],
        post_bind=["Coscheduling"],
        plugin_args={
            "Coscheduling": CoschedulingArgs(
                permit_waiting_time_seconds=30,
                denied_pg_expiration_time_seconds=0.1),
            "MultiSlice": MultiSliceArgs(
                set_schedule_timeout_seconds=30,
                denied_set_expiration_time_seconds=0.2)},
        pod_initial_backoff_s=0.02, pod_max_backoff_s=0.1,
        stuck_gang_after_s=5.0, stuck_gang_sweep_interval_s=0.2)

    def slice_pg(api, idx):
        api.create(srv.POD_GROUPS, make_pod_group(
            f"s-{idx}", min_member=2, multislice_set="s",
            multislice_index=idx, multislice_set_size=2))

    with TestCluster(profile=profile) as cluster:
        api = cluster.api
        cluster.add_nodes([make_node("doomed")])
        slice_pg(api, 0)
        slice_pg(api, 1)
        for m in range(2):
            api.create(srv.PODS, make_pod(f"s-0-m{m}", pod_group="s-0",
                                          requests=make_resources(cpu=2)))
        # slice-1's members can never fit: slice-0's members stay parked at
        # the set barrier, assumed on "doomed"
        for m in range(2):
            api.create(srv.PODS, make_pod(f"s-1-m{m}", pod_group="s-1",
                                          requests=make_resources(cpu=900)))
        assert wait_until(
            lambda: cluster.scheduler.cache.snapshot().assigned_count(
                "s-0", "default") == 2, timeout=10.0)

        api.delete(srv.NODES, "/doomed")
        # the barrier-parked members were rejected: reservations released
        assert wait_until(
            lambda: cluster.scheduler.cache.snapshot().assigned_count(
                "s-0", "default") == 0, timeout=10.0)

        # replacement capacity + a fittable slice-1: the SET completes on
        # the healthy node only
        api.create(srv.NODES, make_node("fresh"))
        for m in range(2):
            api.delete(srv.PODS, f"default/s-1-m{m}")
            api.create(srv.PODS, make_pod(f"s-1r-m{m}", pod_group="s-1",
                                          requests=make_resources(cpu=2)))
        keys = [f"default/s-0-m{m}" for m in range(2)] + \
               [f"default/s-1r-m{m}" for m in range(2)]
        assert cluster.wait_for_pods_scheduled(keys, timeout=20.0)
        for k in keys:
            assert cluster.pod(k).spec.node_name == "fresh"


def test_kubecodec_node_health_roundtrip():
    """The kube transport must carry the health model: conditions and the
    heartbeat stamp (riding the Ready condition's lastHeartbeatTime)
    survive encode→decode — without this the lifecycle controller is dead
    code against a real apiserver."""
    from tpusched.apiserver.kubecodec import decode_node, encode_node

    n = make_node("kn")
    n.status.last_heartbeat_time = 1_700_000_000.25
    n.set_condition(NODE_READY, "False", reason="HeartbeatMissed",
                    message="gone quiet", now=1_700_000_100.5)
    n.spec.taints.append(Taint(key=TAINT_NODE_NOT_READY, effect="NoSchedule"))
    back = decode_node(encode_node(n))
    assert not node_ready(back)
    c = back.ready_condition()
    assert c.reason == "HeartbeatMissed" and c.message == "gone quiet"
    assert abs(c.last_transition_time - 1_700_000_100.5) < 1e-3
    assert abs(back.status.last_heartbeat_time - 1_700_000_000.25) < 1e-3
    assert node_health_error(back)

    # heartbeat-managed node with no condition yet: the stamp still rides
    hb_only = make_node("kn2")
    hb_only.status.last_heartbeat_time = 1_700_000_000.0
    back2 = decode_node(encode_node(hb_only))
    assert abs(back2.status.last_heartbeat_time - 1_700_000_000.0) < 1e-3
    assert node_ready(back2)
