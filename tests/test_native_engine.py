"""Differential tests: native C++ torus engine vs the pure-Python fallback vs
torus.py's reference frozenset semantics.

The three implementations must agree exactly — the native path
(tpusched/native/torus_engine.cc) and the Python mask fallback
(topology/engine.py) are both checked against torus.enumerate_placements /
feasible_placements on randomized grids, wraps, shapes, and occupancies.
"""
from __future__ import annotations

import itertools
import random

import pytest

from tpusched import native
from tpusched.api.topology import V5E, V5P, TpuTopologySpec
from tpusched.topology import engine
from tpusched.topology.torus import (HOST_EXTENT, HostGrid,
                                     enumerate_placements,
                                     feasible_placements)

CASES = [
    # (accelerator, chip dims, wrap, chip shape)
    (V5P, (8, 8, 4), (False, False, False), (4, 4, 4)),
    (V5P, (8, 8, 4), (False, False, False), (8, 8, 4)),   # whole pool
    (V5P, (8, 8, 8), (True, True, True), (4, 4, 2)),      # full wraparound
    (V5P, (8, 8, 4), (False, True, False), (2, 2, 4)),    # mixed wrap
    (V5P, (4, 4, 4), (False, False, False), (2, 2, 1)),   # sub-host block
    (V5E, (8, 8), (False, False), (4, 4)),
    (V5E, (16, 16), (True, True), (4, 8)),                # rotations matter
]


def make_grid(acc, dims, wrap) -> HostGrid:
    ext = HOST_EXTENT[acc.name]
    hdims = tuple(d // e for d, e in zip(dims, ext))
    hosts = {
        "n" + "-".join(map(str, hc)): tuple(c * e for c, e in zip(hc, ext))
        for hc in itertools.product(*(range(d) for d in hdims))}
    return HostGrid.from_spec(TpuTopologySpec(
        pool="p", accelerator=acc.name, dims=dims, wrap=wrap, hosts=hosts))


def reference_membership(placements, grid, assigned, free, eligible):
    survivors = feasible_placements(placements, assigned, free)
    counts = {}
    for p in survivors:
        for c in p:
            if c in eligible:
                n = grid.node_of[c]
                counts[n] = counts.get(n, 0) + 1
    return len(survivors), counts


def check_case(acc, dims, wrap, shape):
    grid = make_grid(acc, dims, wrap)
    ref = enumerate_placements(grid, shape)
    mgrid = engine.MaskGrid(grid)
    pset = engine.enumerate_placement_masks(mgrid, shape)
    assert {mgrid.coords_of(m) for m in pset.masks} == set(ref)

    rng = random.Random(hash((acc.name, dims, wrap, shape)) & 0xFFFF)
    hosts = list(grid.node_of)
    for _ in range(25):
        assigned = frozenset(
            rng.sample(hosts, rng.randint(0, min(3, len(hosts)))))
        free = frozenset(h for h in hosts
                         if h not in assigned and rng.random() < 0.8)
        eligible = assigned | free
        want = reference_membership(ref, grid, assigned, free, eligible)
        got = engine.feasible_membership(
            pset, mgrid.mask_of(assigned), mgrid.mask_of(free),
            mgrid.mask_of(eligible))
        assert got == want


@pytest.mark.parametrize("acc,dims,wrap,shape", CASES,
                         ids=[f"{a.name}-{d}-{s}" for a, d, _, s in CASES])
def test_python_fallback_matches_reference(acc, dims, wrap, shape,
                                           monkeypatch):
    monkeypatch.setattr(native, "load", lambda: None)
    check_case(acc, dims, wrap, shape)


@pytest.mark.parametrize("acc,dims,wrap,shape", CASES,
                         ids=[f"{a.name}-{d}-{s}" for a, d, _, s in CASES])
def test_native_matches_reference(acc, dims, wrap, shape):
    if not native.available():
        pytest.skip("native engine unavailable (no toolchain)")
    check_case(acc, dims, wrap, shape)


def test_native_buffer_regrow():
    """More than the initial 256-placement buffer: the engine must detect
    overflow, regrow, and return the complete set."""
    if not native.available():
        pytest.skip("native engine unavailable")
    grid = make_grid(V5P, (8, 8, 8), (True, True, True), )
    mgrid = engine.MaskGrid(grid)
    pset = engine.enumerate_placement_masks(mgrid, (4, 4, 2))
    ref = enumerate_placements(grid, (4, 4, 2))
    assert len(pset) == len(ref) > 256


def test_malformed_host_coords_dropped():
    """Out-of-torus or wrong-rank host coords from a malformed TpuTopology CR
    must be dropped at grid build, not alias a real mask cell (the bit for
    host (1,5) on a (4,4) grid is cell 9 == host (2,1))."""
    ext = HOST_EXTENT[V5E.name]
    hosts = {
        "good": (0, 0),
        "out-of-range": (2, 10),     # host coord (1,5) on a (4,4) host grid
        "negative": (-2, 0),
        "wrong-rank": (0, 0, 0),
    }
    grid = HostGrid.from_spec(TpuTopologySpec(
        pool="p", accelerator=V5E.name, dims=(8, 8), wrap=(False, False),
        hosts=hosts))
    assert set(grid.coord_of) == {"good"}
    mgrid = engine.MaskGrid(grid)  # must not raise
    assert mgrid.node_of_cell[0] == "good"


def test_empty_and_infeasible():
    grid = make_grid(V5P, (4, 4, 4), (False, False, False))
    mgrid = engine.MaskGrid(grid)
    # shape larger than the pool: no placements
    pset = engine.enumerate_placement_masks(mgrid, (8, 8, 8))
    assert len(pset) == 0
    assert engine.feasible_membership(pset, 0, 0, 0) == (0, {})


def test_fuzz_native_vs_python_vs_reference():
    """Seeded fuzz over random generations, grid dims, wrap patterns, slice
    shapes, and occupancies: all three implementations must agree exactly."""
    if not native.available():
        pytest.skip("native engine unavailable (no toolchain)")
    from tpusched.api.topology import V4, V6E
    rng = random.Random(0xC0FFEE)
    accs = [V4, V5E, V5P, V6E]
    for trial in range(40):
        acc = rng.choice(accs)
        ext = HOST_EXTENT[acc.name]
        dims = tuple(e * rng.randint(1, 3) for e in ext)
        wrap = tuple(rng.random() < 0.5 for _ in ext)
        # shape: random per-axis chip extents (may be rotated/infeasible)
        shape = tuple(rng.choice([1, 2, 4, e, d])
                      for e, d in zip(ext, dims))
        grid = make_grid(acc, dims, wrap)
        ref = enumerate_placements(grid, shape)

        mgrid = engine.MaskGrid(grid)
        pset_native = engine.enumerate_placement_masks(mgrid, shape)
        assert {mgrid.coords_of(m) for m in pset_native.masks} == set(ref), \
            (acc.name, dims, wrap, shape)

        hosts = list(grid.node_of)
        for _ in range(5):
            assigned = frozenset(
                rng.sample(hosts, rng.randint(0, min(2, len(hosts)))))
            free = frozenset(h for h in hosts
                             if h not in assigned and rng.random() < 0.7)
            eligible = assigned | free
            want = reference_membership(ref, grid, assigned, free, eligible)
            got = engine.feasible_membership(
                pset_native, mgrid.mask_of(assigned), mgrid.mask_of(free),
                mgrid.mask_of(eligible))
            assert got == want, (acc.name, dims, wrap, shape)


def test_enumeration_fleet_scale_budget():
    """Placement enumeration at v5p-4096 scale (1024 hosts) stays far inside
    the per-cycle Filter budget (SURVEY §7 hard part (c)); it is also cached
    per CR resource_version, so this cost is paid once per topology change."""
    import time
    from tpusched.testing import make_tpu_pool
    from tpusched.topology.torus import HostGrid
    from tpusched.topology.engine import MaskGrid, enumerate_placement_masks

    topo, nodes = make_tpu_pool("big", dims=(16, 16, 16))
    assert len(nodes) == 1024
    mgrid = MaskGrid(HostGrid.from_spec(topo.spec))
    t0 = time.perf_counter()
    ps = enumerate_placement_masks(mgrid, (4, 4, 4))
    elapsed = time.perf_counter() - t0
    assert len(ps) == 637           # pinned: count is geometry, not timing
    assert elapsed < 0.25, f"enumeration took {elapsed:.3f}s at 1024 hosts"
