"""Active-standby scheduler HA (VERDICT r3 #3): two replicas over a shared
--state-dir, file-lease leader election, WAL replay on takeover, and the
chaos case — the active dies mid-256-pod-gang and the standby completes it
against the surviving binds."""
import json
import os
import time

import pytest

from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import APIServer
from tpusched.apiserver import server as srv
from tpusched.apiserver.persistence import attach, load_into
from tpusched.config.profiles import tpu_gang_profile
from tpusched.sched.ha import FileLease, HAScheduler
from tpusched.testing import make_pod, make_pod_group, make_tpu_pool
from tpusched.testing.cluster import wait_until


# -- FileLease unit behavior --------------------------------------------------

def test_file_lease_mutual_exclusion_and_expiry(tmp_path):
    now = [100.0]
    lease = FileLease(str(tmp_path), clock=lambda: now[0])
    assert lease.acquire_or_renew("a", 5.0)
    assert not lease.acquire_or_renew("b", 5.0)   # live, someone else's
    assert lease.acquire_or_renew("a", 5.0)       # renew own
    assert lease.holder() == "a"
    now[0] += 6.0                                 # expire
    assert lease.holder() == ""
    assert lease.acquire_or_renew("b", 5.0)       # steal after expiry
    assert not lease.acquire_or_renew("a", 5.0)
    lease.release("a")                            # not the holder: no-op
    assert lease.holder() == "b"
    lease.release("b")
    assert lease.holder() == ""
    assert lease.acquire_or_renew("a", 5.0)       # immediate after release


def test_file_lease_survives_torn_file(tmp_path):
    lease = FileLease(str(tmp_path))
    (tmp_path / "scheduler.lease").write_text("{not json")
    assert lease.holder() == ""
    assert lease.acquire_or_renew("a", 5.0)


# -- WAL fencing --------------------------------------------------------------

def test_takeover_fences_deposed_journal_writes(tmp_path):
    """attach() rotates the WAL inode: a deposed active still appending
    through its old journal writes into an orphaned file, not the new
    active's WAL."""
    d = str(tmp_path)
    api1 = APIServer()
    j1 = attach(api1, d)
    api1.create(srv.POD_GROUPS, make_pod_group("before", min_member=1))
    assert j1.flush(timeout=10)

    api2 = APIServer()          # the new active takes over the directory
    j2 = attach(api2, d)
    assert api2.try_get(srv.POD_GROUPS, "default/before") is not None

    # the deposed active keeps writing through its orphaned fd
    api1.create(srv.POD_GROUPS, make_pod_group("zombie", min_member=1))
    j1.flush(timeout=10)
    api2.create(srv.POD_GROUPS, make_pod_group("after", min_member=1))
    assert j2.flush(timeout=10)
    j1.close()
    j2.close()

    fresh = APIServer()
    load_into(fresh, d)
    assert fresh.try_get(srv.POD_GROUPS, "default/after") is not None
    assert fresh.try_get(srv.POD_GROUPS, "default/zombie") is None


# -- failover e2e -------------------------------------------------------------

def _fleet(api, pools=("pool-a", "pool-b")):
    for name in pools:
        topo, nodes = make_tpu_pool(name, dims=(8, 8, 4))   # 256 chips each
        api.create(srv.TPU_TOPOLOGIES, topo)
        for n in nodes:
            api.create(srv.NODES, n)


def _gang(api, name, members=256):
    api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape="8x8x4",
        tpu_accelerator="tpu-v5p"))
    pods = [make_pod(f"{name}-{i:03d}", pod_group=name, limits={TPU: 1},
                     requests=make_resources(cpu=1, memory="1Gi"))
            for i in range(members)]
    for p in pods:
        api.create(srv.PODS, p)
    return [p.key for p in pods]


def _bound_count(api, keys):
    n = 0
    for k in keys:
        p = api.try_get(srv.PODS, k)
        if p is not None and p.spec.node_name:
            n += 1
    return n


def _assert_binpack(api, keys):
    from tpusched.plugins.tpuslice import CHIP_INDEX_ANNOTATION
    used = {}
    for k in keys:
        p = api.try_get(srv.PODS, k)
        used[p.spec.node_name] = used.get(p.spec.node_name, 0) + 1
        # a bound pod without its chip assignment would mean the WAL
        # persisted the bind but lost the Reserve-time annotation patch —
        # the crash-consistency hole TpuSlice accounting cannot survive
        assert CHIP_INDEX_ANNOTATION in p.meta.annotations, k
    assert len(used) == 64 and all(v == 4 for v in used.values()), used


def test_standby_completes_gang_after_active_crash(tmp_path):
    """The headline chaos case. The active binds gang-1 fully, then dies
    (SIGKILL semantics: lease kept, cleanup writes fenced) in the middle of
    admitting 256-pod gang-2. The standby waits out the lease, replays the
    WAL, preserves every surviving bind, and completes gang-2."""
    state = str(tmp_path)
    a = HAScheduler(state, identity="rep-a", lease_duration_s=1.5,
                    renew_interval_s=0.3)
    b = HAScheduler(state, identity="rep-b", lease_duration_s=1.5,
                    renew_interval_s=0.3)
    a.run()
    assert a.is_active.wait(10)
    b.run()                       # campaigns, must stay standby
    try:
        _fleet(a.api)
        g1 = _gang(a.api, "g1")
        assert wait_until(lambda: _bound_count(a.api, g1) == 256, timeout=60)
        g1_before = {k: a.api.try_get(srv.PODS, k).spec.node_name for k in g1}
        assert not b.is_active.is_set()

        g2 = _gang(a.api, "g2")
        # die mid-admission: as soon as any slice reservation work started
        # (deterministically before the full gang is bound: the permit
        # barrier releases binds only at quorum, and crash() fences the
        # journal before stopping the binder threads)
        died_at = time.monotonic()
        a.crash()
        pre = _bound_count(a.api, g2)
        assert pre < 256, "crash landed after the whole gang bound"

        assert b.is_active.wait(30), "standby never took over"
        takeover_s = time.monotonic() - died_at
        # the lease was never released: takeover must have waited it out
        assert takeover_s >= 1.0, f"standby took over at {takeover_s:.2f}s " \
                                  "without waiting out the crashed lease"
        # gang-1's binds survived the replay byte-for-byte
        for k, node in g1_before.items():
            assert b.api.try_get(srv.PODS, k).spec.node_name == node
        # and the standby completes gang-2
        assert wait_until(lambda: _bound_count(b.api, g2) == 256, timeout=90)
        _assert_binpack(b.api, g1)
        _assert_binpack(b.api, g2)
    finally:
        a.crash()
        b.stop()


def test_clean_shutdown_hands_over_without_waiting_out_lease(tmp_path):
    """stop() releases the lease: the standby activates promptly instead of
    sleeping through the remaining duration."""
    state = str(tmp_path)
    a = HAScheduler(state, identity="rep-a", lease_duration_s=10.0,
                    renew_interval_s=0.5)
    b = HAScheduler(state, identity="rep-b", lease_duration_s=10.0,
                    renew_interval_s=0.5)
    a.run()
    assert a.is_active.wait(10)
    b.run()
    try:
        _fleet(a.api, pools=("pool-a",))
        g1 = _gang(a.api, "g1")
        assert wait_until(lambda: _bound_count(a.api, g1) == 256, timeout=60)
        t0 = time.monotonic()
        a.stop()                      # releases the lease
        assert b.is_active.wait(8), "standby did not take over after release"
        assert time.monotonic() - t0 < 8.0
        assert _bound_count(b.api, g1) == 256
    finally:
        a.stop()
        b.stop()


def test_deposed_active_demotes_on_lost_lease(tmp_path):
    """A replica that sleeps through its lease (wedged process) must demote
    when it wakes and finds the lease stolen — exit-on-lost-lease."""
    state = str(tmp_path)
    now = [0.0]
    a = HAScheduler(state, identity="rep-a", lease_duration_s=1.0,
                    renew_interval_s=0.2)
    a.run()
    assert a.is_active.wait(10)
    try:
        # steal the lease out from under it (simulates: a froze > duration,
        # b acquired); a's next renew must fail and demote it
        lease = FileLease(state)
        deadline = time.monotonic() + 10
        stolen = False
        while time.monotonic() < deadline and not stolen:
            with lease._locked():
                cur = lease._read()
                if cur and cur.get("holder") == "rep-a":
                    cur["holder"] = "rep-b"
                    cur["renewed_at"] = time.time() + 3600
                    tmp = lease.path + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(cur, f)
                    os.replace(tmp, lease.path)
                    stolen = True
        assert stolen
        assert a.demoted.wait(10), "replica kept leading on a stolen lease"
        assert wait_until(lambda: not a.is_active.is_set(), timeout=5)
    finally:
        a.stop()


def test_cmd_level_ha_failover(tmp_path):
    """Binary-level e2e: two `tpusched.cmd.scheduler` processes with
    leaderElection in the config YAML and a shared --state-dir. SIGKILL the
    active; the standby must start leading within the lease duration."""
    import signal
    import subprocess
    import sys
    import textwrap

    cfg = tmp_path / "config.yaml"
    cfg.write_text(textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        leaderElection:
          leaderElect: true
          leaseDurationSeconds: 1.5
          renewIntervalSeconds: 0.3
        profiles:
        - schedulerName: tpusched
    """))
    state = tmp_path / "state"

    def spawn(log_name):
        log = open(tmp_path / log_name, "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpusched.cmd.scheduler",
             "--config", str(cfg), "--state-dir", str(state), "-v", "2"],
            stdout=log, stderr=subprocess.STDOUT)
        return proc, tmp_path / log_name

    def leading(logpath):
        try:
            return "started leading" in logpath.read_text()
        except OSError:
            return False

    a, a_log = spawn("a.log")
    b = b_log = None
    try:
        assert wait_until(lambda: leading(a_log), timeout=20), \
            a_log.read_text()[-500:]
        b, b_log = spawn("b.log")
        assert wait_until(lambda: "campaigning" in b_log.read_text(),
                          timeout=20)
        time.sleep(0.5)
        assert not leading(b_log), "standby led while the active was alive"
        a.send_signal(signal.SIGKILL)
        a.wait(timeout=10)
        assert wait_until(lambda: leading(b_log), timeout=15), \
            b_log.read_text()[-500:]
        b.terminate()                  # clean SIGTERM: releases the lease
        assert b.wait(timeout=15) == 0
        b = None
    finally:
        for proc in (a, b):
            if proc is not None and proc.poll() is None:
                proc.kill()


def test_deposed_journal_cannot_clobber_by_path(tmp_path):
    """Inode fencing (not just fd fencing): a deposed journal that later
    runs compact() — or the torn-write truncation path — must leave the new
    active's snapshot and WAL untouched."""
    d = str(tmp_path)
    api1 = APIServer()
    j1 = attach(api1, d)
    api1.create(srv.POD_GROUPS, make_pod_group("old", min_member=1))
    assert j1.flush(timeout=10)

    api2 = APIServer()
    j2 = attach(api2, d)          # takeover: rotates the WAL inode
    api2.create(srv.POD_GROUPS, make_pod_group("new", min_member=1))
    assert j2.flush(timeout=10)

    # the zombie journal compacts: would overwrite snapshot + swap the WAL
    # by path if not fenced
    j1.compact()
    api1.create(srv.POD_GROUPS, make_pod_group("zombie2", min_member=1))
    j1.flush(timeout=10)
    j1.close()

    api2.create(srv.POD_GROUPS, make_pod_group("new2", min_member=1))
    assert j2.flush(timeout=10)
    j2.close()

    fresh = APIServer()
    load_into(fresh, d)
    assert fresh.try_get(srv.POD_GROUPS, "default/new") is not None
    assert fresh.try_get(srv.POD_GROUPS, "default/new2") is not None
    assert fresh.try_get(srv.POD_GROUPS, "default/zombie2") is None


def test_repeated_takeover_churn_preserves_state(tmp_path):
    """Five successive crash-and-take-over generations: every takeover must
    replay the WHOLE surviving state, and the binds accumulated across
    generations survive byte-for-byte. Compaction runs on every attach, so
    this churns the snapshot/WAL rotation path five times over one
    directory."""
    state = str(tmp_path)
    expected = {}                     # pod key -> node, across generations
    rep = None
    try:
        for gen in range(5):
            rep = HAScheduler(state, identity=f"rep-{gen}",
                              lease_duration_s=0.8, renew_interval_s=0.2)
            rep.run()
            assert rep.is_active.wait(20), f"generation {gen} never led"
            # previous generation's binds all survived the replay
            for k, node in expected.items():
                p = rep.api.try_get(srv.PODS, k)
                assert p is not None and p.spec.node_name == node, \
                    f"gen {gen}: lost bind {k}"
            if gen == 0:
                topo, nodes = make_tpu_pool("pool", dims=(8, 8, 4))
                rep.api.create(srv.TPU_TOPOLOGIES, topo)
                for n in nodes:
                    rep.api.create(srv.NODES, n)
            # one fresh 16-chip slice gang per generation (5 gens fill 80
            # of the pool's 256 chips)
            name = f"gen-{gen}"
            rep.api.create(srv.POD_GROUPS, make_pod_group(
                name, min_member=4, tpu_slice_shape="2x2x4",
                tpu_accelerator="tpu-v5p"))
            keys = []
            for i in range(4):
                p_ = make_pod(f"{name}-{i}", pod_group=name,
                              limits={TPU: 4})
                rep.api.create(srv.PODS, p_)
                keys.append(p_.key)
            assert wait_until(lambda: _bound_count(rep.api, keys) == 4,
                              timeout=30), f"gen {gen} gang did not bind"
            for k in keys:
                expected[k] = rep.api.try_get(srv.PODS, k).spec.node_name
            rep.crash()               # SIGKILL semantics, lease kept
    finally:
        if rep is not None:
            rep.crash()               # idempotent; frees a mid-loop leak
    # final generation: clean recovery of all five gangs
    final = HAScheduler(state, identity="rep-final",
                        lease_duration_s=0.8, renew_interval_s=0.2)
    final.run()
    try:
        assert final.is_active.wait(20)
        assert len(expected) == 20
        for k, node in expected.items():
            p = final.api.try_get(srv.PODS, k)
            assert p is not None and p.spec.node_name == node
    finally:
        final.stop()


def test_three_replicas_exactly_one_leads(tmp_path):
    """Three replicas campaign simultaneously: exactly one activates; the
    others stay standby; killing the winner promotes exactly one more."""
    state = str(tmp_path)
    reps = [HAScheduler(state, identity=f"r{i}", lease_duration_s=1.0,
                        renew_interval_s=0.25) for i in range(3)]
    for r in reps:
        r.run()
    try:
        assert wait_until(
            lambda: sum(r.is_active.is_set() for r in reps) == 1, timeout=15)
        time.sleep(1.0)     # several renew cycles: still exactly one
        actives = [r for r in reps if r.is_active.is_set()]
        assert len(actives) == 1
        actives[0].crash()
        rest = [r for r in reps if r is not actives[0]]
        assert wait_until(
            lambda: sum(r.is_active.is_set() for r in rest) == 1, timeout=15)
        time.sleep(1.0)
        assert sum(r.is_active.is_set() for r in rest) == 1
    finally:
        for r in reps:
            r.crash()
