"""Why-pending diagnosis end-to-end (the ISSUE 5 acceptance tier).

A wedged gang — quota-blocked, fragmentation-blocked, and unhealthy-node —
must be fully diagnosable from ``/debug/explain`` / the explain CLI ALONE:
blocking plugin, top rejection reasons with node counts, and the
suggested unblock signal.  Plus the capacity/fragmentation gauges, the
SLO layer, and the config-surface decode for the objectives.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from tpusched import obs
from tpusched.api.core import TAINT_NODE_NOT_READY, Taint
from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import server as srv
from tpusched.config.profiles import full_stack_profile, tpu_gang_profile
from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                              make_pod_group, make_tpu_node, make_tpu_pool,
                              wait_until)
from tpusched.util.httpserve import MetricsServer
from tpusched.util.metrics import REGISTRY


@pytest.fixture()
def fresh_obs():
    """Isolate each test's diagnosis/SLO state in fresh global instances
    (schedulers capture the globals at construction)."""
    old_engine, old_slo = obs.default_engine(), obs.default_slo()
    engine = obs.install_engine(obs.DiagnosisEngine())
    slo = obs.install_slo(obs.SLOTracker())
    yield engine, slo
    obs.install_engine(old_engine)
    obs.install_slo(old_slo)


def _get_json(port: int, path: str):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=5) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_quota_blocked_gang_diagnosable_from_explain_alone(fresh_obs):
    """10-member gang under an ElasticQuota that fits 9: nine park at the
    permit barrier, the tenth bounces off CapacityScheduling forever.  The
    /debug/explain JSON alone names the blocking plugin, the quota reason,
    and the quota unblock signal."""
    with TestCluster(profile=full_stack_profile(permit_wait_s=120)) as c:
        c.add_nodes([make_tpu_node("n1", chips=8),
                     make_tpu_node("n2", chips=8)])
        c.api.create(srv.ELASTIC_QUOTAS,
                     make_elastic_quota("research", "research",
                                        min={TPU: 9}, max={TPU: 9}))
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("train", namespace="research",
                                    min_member=10))
        pods = [make_pod(f"m-{i}", namespace="research", pod_group="train",
                         limits={TPU: 1}) for i in range(10)]
        c.create_pods(pods)

        def waiting_count():
            n = [0]
            c.scheduler.framework.iterate_over_waiting_pods(
                lambda wp: n.__setitem__(0, n[0] + 1))
            return n[0]
        assert wait_until(lambda: waiting_count() == 9, timeout=15)
        engine, _ = fresh_obs
        assert wait_until(
            lambda: (engine.explain_gang("research/train") or {})
            .get("outcomes", {}).get("unschedulable", 0) >= 1, timeout=10)

        server = MetricsServer(port=0).start()
        try:
            status, out = _get_json(server.port,
                                    "/debug/explain?gang=research/train")
            _, metrics_text = _fetch_text(server.port, "/metrics")
        finally:
            server.stop()

    # ---- everything below reads ONLY the endpoint payloads ----
    assert status == 200
    assert out["gang"] == "research/train"
    assert out["members_pending"] == 10
    assert out["outcomes"]["waiting-permit"] == 9
    assert out["outcomes"]["unschedulable"] == 1
    # the permit barrier (stitched from the tracer) names its holder
    assert out["permit_barrier"]["resolved"] is False
    assert out["permit_barrier"]["blocking_plugins"] == ["Coscheduling"]
    reasons = {(r["plugin"], r["reason"]): r for r in out["top_reasons"]}
    quota_rows = [r for (p, _), r in reasons.items()
                  if p == "CapacityScheduling"]
    assert quota_rows, out["top_reasons"]
    assert any("more than Max" in r["reason"] for r in quota_rows)
    # node counts ride along (the PreFilter rejection covers every node)
    assert any(r["nodes"] == 2 for r in quota_rows)
    # the suggested unblock signal is the QUOTA, not the barrier echo
    assert "quota" in out["suggestion"].lower()
    # and the quota gauges confirm the story: 9 chips used of min 9
    assert 'tpusched_quota_used_chips{namespace="research"} 9' \
        in metrics_text
    assert 'tpusched_quota_utilization{namespace="research"} 1.0' \
        in metrics_text


def _fetch_text(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as r:
        return r.status, r.read().decode()


def test_fragmentation_blocked_gang_and_pool_gauges(fresh_obs):
    """A 4x4x4 slice gang blocked because a resident 2x2x2 gang fragments
    the pool: TopologyMatch attribution + the defrag unblock signal from
    /debug/explain, and the pool gauges quantify it (free chips >>
    largest placeable window)."""
    engine, _ = fresh_obs
    with TestCluster(profile=tpu_gang_profile()) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("small", min_member=8,
                                    tpu_slice_shape="2x2x2",
                                    tpu_accelerator="tpu-v5p"))
        small = [make_pod(f"s-{i}", pod_group="small", limits={TPU: 1},
                          requests=make_resources(cpu=1, memory="1Gi"))
                 for i in range(8)]
        c.create_pods(small)
        assert c.wait_for_pods_scheduled([p.key for p in small], timeout=30)

        c.api.create(srv.POD_GROUPS,
                     make_pod_group("big", min_member=16,
                                    tpu_slice_shape="4x4x4",
                                    tpu_accelerator="tpu-v5p"))
        big = [make_pod(f"b-{i}", pod_group="big", limits={TPU: 4})
               for i in range(16)]
        c.create_pods(big)
        # Wait for the diagnosis to stabilize on the fragmentation verdict,
        # not just full membership: the engine re-derives per cycle and a
        # loaded box can briefly regress to "fewer member pods than
        # minMember" between the membership wait and the HTTP query.
        def _fragmentation_diagnosed():
            out = engine.explain_gang("default/big") or {}
            return (out.get("members_pending", 0) == 16
                    and "defrag" in out.get("suggestion", ""))
        assert wait_until(_fragmentation_diagnosed, timeout=15)

        server = MetricsServer(port=0).start()
        try:
            status, out = _get_json(server.port,
                                    "/debug/explain?gang=default/big")
            _, metrics_text = _fetch_text(server.port, "/metrics")
        finally:
            server.stop()

    assert status == 200
    topo_rows = [r for r in out["top_reasons"]
                 if r["plugin"] == "TopologyMatch"]
    assert topo_rows and "no feasible" in topo_rows[0]["reason"]
    # node counts: the rejection covered the whole 16-host pool
    assert topo_rows[0]["nodes"] == 16
    # the unblock signal points at fragmentation/defrag tooling
    assert "defrag" in out["suggestion"]
    # gauges: 56 chips free but only a 32-chip window placeable — the
    # free-vs-largest gap IS the fragmentation diagnosis
    assert 'tpusched_pool_capacity_chips{pool="pool-a"} 64' in metrics_text
    assert 'tpusched_pool_free_chips{pool="pool-a"} 56' in metrics_text
    assert ('tpusched_pool_largest_placeable_chips{pool="pool-a"} 32'
            in metrics_text)
    frag = [ln for ln in metrics_text.splitlines()
            if ln.startswith('tpusched_pool_fragmentation_ratio')]
    assert frag and 0.0 < float(frag[0].split()[-1]) < 1.0


def test_unhealthy_node_gang_diagnosable(fresh_obs):
    """Every candidate node carries the lifecycle controller's not-ready
    taint: the diagnosis names the health reason and the repair runbook
    suggestion."""
    engine, _ = fresh_obs
    with TestCluster() as c:
        nodes = [make_tpu_node(f"n{i}", chips=4) for i in range(3)]
        for n in nodes:
            n.spec.taints.append(Taint(key=TAINT_NODE_NOT_READY,
                                       effect="NoSchedule"))
        c.add_nodes(nodes)
        c.create_pods([make_pod("sick", limits={TPU: 1})])
        assert wait_until(
            lambda: engine.explain_pod("default/sick") is not None,
            timeout=10)
        server = MetricsServer(port=0).start()
        try:
            status, out = _get_json(server.port,
                                    "/debug/explain?pod=default/sick")
        finally:
            server.stop()
    assert status == 200
    assert out["last_outcome"] == "unschedulable"
    rows = {r["reason"]: r for r in out["reasons"]}
    taint_rows = [r for r in rows.values() if "not-ready" in r["reason"]]
    assert taint_rows, rows
    assert any(r["nodes"] == 3 for r in taint_rows)   # all 3 nodes counted
    assert "repair" in out["suggestion"] or "unhealthy" in out["suggestion"]


def test_explain_endpoint_rollup_and_404(fresh_obs):
    engine, slo = fresh_obs
    engine.on_attempt("default/p1", None, "unschedulable", "TpuSlice",
                      "insufficient resource google.com/tpu", None)
    slo.observe(obs.POD_E2E, 0.5)
    slo.observe(obs.POD_E2E, 9.0)              # breach
    server = MetricsServer(port=0).start()
    try:
        status, out = _get_json(server.port, "/debug/explain")
        assert status == 200
        assert out["stats"]["pods"] == 1
        assert out["top_blockers"][0]["plugin"] == "TpuSlice"
        assert "suggestion" in out["top_blockers"][0]
        s = out["slo"]["pod_e2e"]
        assert s["events"] == 2 and s["breaches"] == 1
        assert s["objective_s"] == obs.DEFAULT_POD_E2E_S
        status, err = _get_json(server.port, "/debug/explain?pod=nope")
        assert status == 404 and "error" in err
        status, err = _get_json(server.port, "/debug/explain?gang=nope")
        assert status == 404 and "error" in err
    finally:
        server.stop()


def test_explain_cli_renders_and_exit_codes(fresh_obs, capsys):
    from tpusched.cmd import explain
    engine, _ = fresh_obs
    engine.on_attempt("default/w-1", "default/g", "unschedulable",
                      "CapacityScheduling",
                      "Pod default/w-1 is rejected in PreFilter because "
                      "ElasticQuota research is more than Max",
                      [{"plugin": "CapacityScheduling",
                        "reason": "quota used would exceed Max",
                        "nodes": 48}])
    server = MetricsServer(port=0).start()
    url = f"http://127.0.0.1:{server.port}"
    try:
        assert explain.main(["--url", url, "--pod", "w-1"]) == 0
        out = capsys.readouterr().out
        assert "CapacityScheduling" in out
        assert "48 node(s)" in out
        assert "unblock:" in out and "quota" in out
        assert explain.main(["--url", url, "--gang", "default/g"]) == 0
        out = capsys.readouterr().out
        assert "1 member(s) still pending" in out
        assert explain.main(["--url", url]) == 0
        out = capsys.readouterr().out
        assert "top blockers" in out and "SLO" in out
        # --json is machine-parseable
        assert explain.main(["--url", url, "--pod", "w-1", "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["pod"] == "default/w-1"
        # not-found → exit 1
        assert explain.main(["--url", url, "--pod", "ghost"]) == 1
    finally:
        server.stop()
    # unreachable server → exit 2
    assert explain.main(["--url", "http://127.0.0.1:1", "--pod", "x",
                         "--timeout", "0.2"]) == 2


def test_bound_pods_leave_the_diagnosis_and_feed_pod_e2e_slo(fresh_obs):
    """The happy path: a pod that binds is evicted from the why-pending
    table and its first-enqueue→bound latency lands in the pod_e2e SLO."""
    engine, slo = fresh_obs
    with TestCluster() as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        c.create_pods([make_pod("ok", limits={TPU: 2})])
        assert c.wait_for_pods_scheduled(["default/ok"])
        assert wait_until(
            lambda: slo.summary()["pod_e2e"]["events"] >= 1, timeout=5)
    assert engine.explain_pod("default/ok") is None
    s = slo.summary()["pod_e2e"]
    assert s["events"] >= 1
    assert s["p99_s"] < obs.DEFAULT_POD_E2E_S   # a 1-pod bind is fast
    assert s["breaches"] == 0 and s["burn_rate"] == 0.0


def test_gang_bound_slo_fed_by_quorum_completion(fresh_obs):
    _, slo = fresh_obs
    with TestCluster(profile=tpu_gang_profile()) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(2, 2, 2))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("g", min_member=8,
                                    tpu_slice_shape="2x2x2",
                                    tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w-{i}", pod_group="g", limits={TPU: 1},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(8)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
        assert wait_until(
            lambda: slo.summary()["gang_bound"]["events"] >= 1, timeout=5)
    s = slo.summary()["gang_bound"]
    assert s["events"] >= 1 and s["p50_s"] > 0.0
    # the tpusched_slo_* families are on /metrics
    text = REGISTRY.expose()
    assert 'tpusched_slo_events_total{objective="gang_bound"}' in text
    assert 'tpusched_slo_burn_rate{objective="gang_bound"}' in text
    assert 'tpusched_slo_objective_seconds{objective="gang_bound"} 2.0' \
        in text


def test_slo_objectives_decode_from_config():
    from tpusched.config.scheme import ConfigError, decode_profile
    p = decode_profile({"schedulerName": "x",
                        "slo": {"podE2ESeconds": 1.5,
                                "gangBoundSeconds": 30}})
    assert p.slo_pod_e2e_s == 1.5 and p.slo_gang_bound_s == 30.0
    assert decode_profile({}).slo_pod_e2e_s == 2.0      # defaults hold
    with pytest.raises(ConfigError):
        decode_profile({"slo": {"podE2ESeconds": "fast"}})
    with pytest.raises(ConfigError):
        decode_profile({"slo": {"gangBoundSeconds": -1}})
    with pytest.raises(ConfigError):
        decode_profile({"slo": {"ttftSeconds": 1}})


def test_shadow_scheduler_does_not_touch_global_observability(fresh_obs):
    """What-if/defrag trials schedule forked state holding the SAME pod
    keys as the live fleet: a shadow (telemetry=False) bind must not evict
    the real pod's why-pending diagnosis, publish capacity gauges, or
    burn the SLO."""
    from tpusched.apiserver import APIServer
    from tpusched.plugins import default_registry
    from tpusched.sched import Scheduler
    from tpusched.testing.cluster import default_profile
    engine, slo = fresh_obs
    # the "real" fleet state: a pod pending with a diagnosis
    engine.on_attempt("default/p", None, "unschedulable", "TpuSlice",
                      "insufficient resource google.com/tpu", None)
    events_before = slo.summary()["pod_e2e"]["events"]
    api = APIServer()
    api.create(srv.NODES, make_tpu_node("n1", chips=4))
    sched = Scheduler(api, default_registry(), default_profile(),
                      telemetry=False)
    sched.run()
    try:
        api.create(srv.PODS, make_pod("p", limits={TPU: 2}))
        assert wait_until(
            lambda: (api.peek(srv.PODS, "default/p") or make_pod("x"))
            .spec.node_name, timeout=10)
    finally:
        sched.stop()
    # the trial bound default/p — the REAL diagnosis entry must survive
    assert engine.explain_pod("default/p") is not None
    # no SLO burn from the trial bind
    assert slo.summary()["pod_e2e"]["events"] == events_before
    # no capacity collector registered for the shadow
    assert sched._capacity is None
    # and the trial's cycle traces went to a PRIVATE ring, not the global
    # recorder the /debug/explain gang stitch reads
    from tpusched import trace
    assert sched.recorder is not trace.default_recorder()


def test_largest_window_floor_never_false_zero():
    """A pool whose free hosts are scattered single cells must report one
    host block (the extent shape always fits a free host), never 0."""
    from tpusched.obs import largest_window_chips
    from tpusched.topology.torus import HostGrid
    topo, _ = make_tpu_pool("p", dims=(8, 8, 4))
    grid = HostGrid.from_spec(topo.spec)
    # free = two isolated, non-adjacent host cells
    free = frozenset({(0, 0, 0), (2, 2, 2)})
    chips = largest_window_chips(grid, free)
    assert chips == 4                     # one v5p host block (2x2x1)
    assert largest_window_chips(grid, frozenset()) == 0
    # a fully free pool places the whole torus
    assert largest_window_chips(
        grid, frozenset(grid.coord_of.values())) == 256


def test_burn_window_rolls_over_consistently(fresh_obs):
    """The O(1) rolling burn counter must agree with a recount after the
    window wraps (breaches falling off the back are un-counted)."""
    from tpusched.obs.slo import _WINDOW
    t = obs.SLOTracker(pod_e2e_s=1.0, gang_bound_s=0)
    for _ in range(_WINDOW):
        t.observe(obs.POD_E2E, 2.0)            # all breaches
    assert t.summary()["pod_e2e"]["burn_rate"] == 1.0
    for _ in range(_WINDOW // 2):
        t.observe(obs.POD_E2E, 0.1)            # half the window heals
    s = t.summary()["pod_e2e"]
    assert s["burn_rate"] == 0.5
    for _ in range(_WINDOW):
        t.observe(obs.POD_E2E, 0.1)            # fully healed
    assert t.summary()["pod_e2e"]["burn_rate"] == 0.0
    assert t.summary()["pod_e2e"]["breaches"] == _WINDOW  # cumulative kept


def test_pool_occupancy_ignores_chipless_healthy_hosts():
    """A healthy empty host advertising 0 allocatable chips (device plugin
    not up yet) must not count as window-eligible — largest_placeable
    would float above free_chips."""
    from tpusched.obs import pool_occupancy
    from tpusched.topology.torus import HostGrid
    from tpusched.fwk.nodeinfo import NodeInfo, Snapshot
    topo, nodes = make_tpu_pool("p", dims=(4, 4, 4))
    for n in nodes:
        n.status.allocatable[TPU] = 0          # chips not advertised
        n.status.capacity[TPU] = 0
    grid = HostGrid.from_spec(topo.spec)
    snap = Snapshot(nodes=nodes)
    free, free_chips, capacity = pool_occupancy(grid, snap)
    assert free == frozenset() and free_chips == 0 and capacity == 0


def test_install_slo_prunes_retired_objective_gauges(fresh_obs):
    from tpusched.obs.slo import slo_objective_seconds
    # current tracker exposes both objectives; the new one disables gangs
    assert ("gang_bound",) in slo_objective_seconds.children()
    obs.install_slo(obs.SLOTracker(pod_e2e_s=1.0, gang_bound_s=0))
    assert ("gang_bound",) not in slo_objective_seconds.children()
    assert ("pod_e2e",) in slo_objective_seconds.children()


def test_scheduler_installs_profile_slo_targets(fresh_obs):
    """A profile with non-default objectives re-installs the global
    tracker; a same-target scheduler does not reset it."""
    prof = tpu_gang_profile()
    prof.slo_pod_e2e_s = 0.25
    prof.slo_gang_bound_s = 7.5
    with TestCluster(profile=prof):
        assert obs.default_slo().targets == (0.25, 7.5)
        t = obs.default_slo()
    with TestCluster(profile=prof):
        assert obs.default_slo() is t          # same targets: kept
