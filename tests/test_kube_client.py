"""External kube-apiserver client mode (VERDICT r4 missing #1).

The reference's boot contract is "plugins in the real kube-scheduler against
a real apiserver" proven by an in-process apiserver
(/root/reference/cmd/scheduler/main_test.go:48-80,
/root/reference/test/integration/main_test.go:31-46). Equivalent here:
``testing.kubefake.FakeKube`` is a real HTTP server implementing the kube
REST slice; ``apiserver.kube.KubeAPIServer`` is driven against it through
actual sockets, watch streams included. Codec round-trips pin the wire
shapes to the CRDs in manifests/crds/.
"""
import threading
import time

import pytest

from tpusched.api.core import Binding, Pod, PodDisruptionBudget, PriorityClass
from tpusched.api.resources import CPU, MEMORY, TPU
from tpusched.apiserver import kube, kubecodec as codec
from tpusched.apiserver import server as srv
from tpusched.testing import (make_pod, make_pod_group, make_tpu_node,
                              make_tpu_pool, wait_until)
from tpusched.testing.kubefake import FakeKube


@pytest.fixture()
def fake():
    with FakeKube() as f:
        yield f


@pytest.fixture()
def api(fake):
    a = kube.KubeAPIServer(kube.ConnectionInfo(fake.url)).start()
    yield a
    a.stop()


# -- codec --------------------------------------------------------------------

def _eq_modulo_clock(a, b) -> None:
    """Codec round-trip equality: timestamps survive at second granularity
    (metav1.Time), so compare with integral stamps set by the caller."""
    assert type(a) is type(b)
    assert codec.KINDS  # sanity
    assert a.meta.name == b.meta.name
    assert a.meta.namespace == b.meta.namespace
    assert a.meta.labels == b.meta.labels
    assert a.meta.annotations == b.meta.annotations


def test_codec_round_trips_every_kind():
    pod = make_pod("p", pod_group="g", limits={TPU: 4, CPU: 1500,
                                               MEMORY: 2 << 30})
    pod.meta.creation_timestamp = 1_700_000_000.0
    pod.spec.node_selector = {"zone": "a"}
    node = make_tpu_node("n1", chips=4, dcn_domain="zoneA/rack0")
    node.meta.creation_timestamp = 1_700_000_000.0
    pg = make_pod_group("g", min_member=8, tpu_slice_shape="2x2x2",
                        tpu_accelerator="tpu-v5p",
                        min_resources={TPU: 32},
                        multislice_set="set", multislice_set_size=2)
    pg.meta.creation_timestamp = 1_700_000_000.0
    topo, _nodes = make_tpu_pool("pool-0", dims=(2, 2, 2))
    topo.meta.creation_timestamp = 1_700_000_000.0
    pc = PriorityClass(value=1000, preemption_policy="Never")
    pc.meta.name = "high"
    pdb = PodDisruptionBudget(selector={"app": "x"}, disruptions_allowed=1)
    pdb.meta.name = "pdb1"
    from tpusched.api.scheduling import ElasticQuota, ElasticQuotaSpec
    eq = ElasticQuota(spec=ElasticQuotaSpec(min={TPU: 8}, max={TPU: 16}))
    eq.meta.name = "quota"
    for kind, obj in [(srv.PODS, pod), (srv.NODES, node),
                      (srv.POD_GROUPS, pg), (srv.TPU_TOPOLOGIES, topo),
                      (srv.PRIORITY_CLASSES, pc), (srv.PDBS, pdb),
                      (srv.ELASTIC_QUOTAS, eq)]:
        info = codec.KINDS[kind]
        rt = info.decode(info.encode(obj))
        _eq_modulo_clock(obj, rt)
        # a second round-trip is a fixed point: encode∘decode is stable
        assert info.encode(rt) == info.encode(info.decode(info.encode(rt)))
    # the fields the scheduler actually consumes survive exactly
    rt = codec.decode_pod(codec.encode_pod(pod))
    assert rt.spec.containers[0].limits == pod.spec.containers[0].limits
    assert rt.spec.scheduler_name == pod.spec.scheduler_name
    assert rt.spec.node_selector == {"zone": "a"}
    rt = codec.decode_podgroup(codec.encode_podgroup(pg))
    assert rt.spec.min_member == 8
    assert rt.spec.min_resources == {TPU: 32}
    assert rt.spec.multislice_set_size == 2
    rt = codec.decode_tputopology(codec.encode_tputopology(topo))
    assert rt.spec.dims == topo.spec.dims
    assert rt.spec.hosts == topo.spec.hosts
    rt = codec.decode_node(codec.encode_node(node))
    assert rt.status.allocatable == node.status.allocatable


def test_quantity_formats_are_kube_canonical():
    assert codec.format_quantity(CPU, 1500) == "1500m"
    assert codec.format_quantity(TPU, 4) == "4"
    assert codec.decode_resources({"cpu": "1.5", "memory": "2Gi",
                                   TPU: "4"}) == {
        CPU: 1500, MEMORY: 2 << 30, TPU: 4}


def test_merge_patch_diff_and_apply_are_inverse():
    cases = [
        ({"a": 1, "b": {"c": 2}}, {"a": 1, "b": {"c": 3}}),
        ({"a": 1}, {"b": 2}),
        ({"x": {"y": {"z": 1}}}, {"x": {"y": {}}}),
        ({"l": [1, 2]}, {"l": [2, 1]}),
        ({"keep": {"deep": True}, "drop": 1}, {"keep": {"deep": True}}),
        ({}, {"new": {"nested": [1]}}),
    ]
    for old, new in cases:
        patch = codec.merge_patch(old, new)
        assert codec.apply_merge_patch(old, patch) == new
    assert codec.merge_patch({"a": {"b": 1}}, {"a": {"b": 1}}) == {}


# -- CRUD + watch over real HTTP ---------------------------------------------

def test_create_get_list_delete_and_watch_stream(api, fake):
    seen = []
    api.add_watch(srv.PODS, lambda ev: seen.append((ev.type, ev.object.key)))
    pod = make_pod("w1")
    created = api.create(srv.PODS, pod)
    assert created.meta.resource_version > 0
    assert created.meta.uid.startswith("fake-")   # server-minted identity
    assert api.get(srv.PODS, "default/w1").meta.name == "w1"
    assert [p.meta.name for p in api.list(srv.PODS)] == ["w1"]
    assert wait_until(lambda: ("Added", "default/w1") in seen, timeout=5)
    api.delete(srv.PODS, "default/w1")
    assert wait_until(lambda: ("Deleted", "default/w1") in seen, timeout=5)
    assert api.try_get(srv.PODS, "default/w1") is None
    with pytest.raises(srv.NotFound):
        api.delete(srv.PODS, "default/w1")


def test_update_conflict_on_stale_rv(api):
    pg = make_pod_group("g1", min_member=2)
    created = api.create(srv.POD_GROUPS, pg)
    fresh = api.patch(srv.POD_GROUPS, "default/g1",
                      lambda g: setattr(g.spec, "min_member", 3))
    assert fresh.spec.min_member == 3
    created.spec.min_member = 9   # stale copy: rv from create time
    with pytest.raises(srv.Conflict):
        api.update(srv.POD_GROUPS, created)


def test_patch_retries_through_conflicts(api, fake):
    api.create(srv.POD_GROUPS, make_pod_group("g2", min_member=1))
    # 8 threads patch concurrently; every increment must land exactly once
    def bump():
        api.patch(srv.POD_GROUPS, "default/g2",
                  lambda g: setattr(g.spec, "min_member",
                                    g.spec.min_member + 1))
    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    raw = fake.object("podgroups", "default", "g2")
    assert raw["spec"]["minMember"] == 9


def test_patch_preserves_unmodeled_fields(api, fake):
    """The lossiness discipline: a real pod carries fields this framework
    does not model; patching through the client must not strip them."""
    fake.put_object("pods", {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "rich", "namespace": "default"},
        "spec": {"containers": [{"name": "main",
                                 "image": "img",
                                 "env": [{"name": "A", "value": "1"}],
                                 "volumeMounts": [{"name": "v",
                                                   "mountPath": "/v"}]}],
                 "volumes": [{"name": "v", "emptyDir": {}}],
                 "schedulerName": "tpusched"},
        "status": {"phase": "Pending"}})
    api.patch(srv.PODS, "default/rich",
              lambda p: p.meta.annotations.update({"tpu.dev/chips": "0,1"}))
    raw = fake.object("pods", "default", "rich")
    assert raw["metadata"]["annotations"]["tpu.dev/chips"] == "0,1"
    assert raw["spec"]["volumes"] == [{"name": "v", "emptyDir": {}}]
    assert raw["spec"]["containers"][0]["env"] == [{"name": "A",
                                                    "value": "1"}]
    assert raw["spec"]["containers"][0]["volumeMounts"][0]["name"] == "v"


def test_status_writes_ride_the_status_subresource(api, fake):
    """The CRDs declare `subresources: status`, so a real apiserver
    silently DROPS status fields patched to the main resource (the fake
    enforces that). A mutate touching spec AND status must land both —
    proving the client splits the patch across the two endpoints."""
    api.create(srv.POD_GROUPS, make_pod_group("st", min_member=2))

    def mutate(pg):
        pg.spec.min_member = 5
        pg.status.phase = "Scheduling"
        pg.status.scheduled = 2

    got = api.patch(srv.POD_GROUPS, "default/st", mutate)
    assert got.spec.min_member == 5
    assert got.status.phase == "Scheduling"
    raw = fake.object("podgroups", "default", "st")
    assert raw["spec"]["minMember"] == 5
    assert raw["status"]["phase"] == "Scheduling"
    assert raw["status"]["scheduled"] == 2
    # status-only mutate: exactly one write, to /status
    got = api.patch(srv.POD_GROUPS, "default/st",
                    lambda pg: setattr(pg.status, "phase", "Scheduled"))
    assert got.status.phase == "Scheduled"
    raw = fake.object("podgroups", "default", "st")
    assert raw["status"]["phase"] == "Scheduled"
    assert raw["spec"]["minMember"] == 5
    # control: the fake really does drop main-resource status writes
    from tpusched.apiserver.kubecodec import KINDS
    info = KINDS[srv.POD_GROUPS]
    api._tx.request("PATCH", info.object_path("default/st"),
                    {"status": {"phase": "Bogus"}},
                    content_type="application/merge-patch+json")
    raw = fake.object("podgroups", "default", "st")
    assert raw["status"]["phase"] == "Scheduled"   # unchanged


def test_bind_subresource_contract(api, fake):
    """Bind = POST pods/binding: nodeName set, Binding annotations merged
    into the pod (the device-index contract, flex_gpu.go:230-242),
    PodScheduled condition appended, second bind Conflicts."""
    api.create(srv.NODES, make_tpu_node("n1"))
    api.create(srv.PODS, make_pod("b1", limits={TPU: 4}))
    api.bind(Binding(pod_key="default/b1", node_name="n1",
                     annotations={"tpu.dev/chip-indices": "0,1,2,3"}))
    raw = fake.object("pods", "default", "b1")
    assert raw["spec"]["nodeName"] == "n1"
    assert raw["metadata"]["annotations"]["tpu.dev/chip-indices"] == "0,1,2,3"
    assert any(c["type"] == "PodScheduled" and c["status"] == "True"
               for c in raw["status"]["conditions"])
    with pytest.raises(srv.Conflict):
        api.bind(Binding(pod_key="default/b1", node_name="n2"))
    # the watch stream reflects the bind into the client cache
    assert wait_until(
        lambda: (api.peek(srv.PODS, "default/b1") or Pod()).spec.node_name
        == "n1", timeout=5)


def test_watch_survives_reconnect(api, fake):
    """Kill every open watch socket; the reflector must re-watch/relist and
    keep delivering (client-go reflector behavior)."""
    seen = []
    api.add_watch(srv.NODES, lambda ev: seen.append(ev.object.meta.name))
    api.create(srv.NODES, make_tpu_node("r1"))
    assert wait_until(lambda: "r1" in seen, timeout=5)
    with api._lock:
        streams = list(api._streams)
    for conn in streams:
        kube._Transport.kill_stream(conn)   # sever every watch socket
    api.create(srv.NODES, make_tpu_node("r2"))
    assert wait_until(lambda: "r2" in seen, timeout=10)


def test_idle_watch_rv_advances_via_bookmarks(api, fake):
    """An idle kind's watch must keep its resume point fresh through
    BOOKMARK events (the fake sends them on idle, like a real apiserver
    with allowWatchBookmarks): after heavy traffic on ANOTHER kind, the
    idle kind's reflector RV catches up, so its next reconnect resumes
    near head instead of replaying foreign history."""
    for i in range(10):
        api.create(srv.NODES, make_tpu_node(f"bk{i}"))
    head = api._rv[srv.NODES]
    assert wait_until(lambda: api._rv[srv.PODS] >= head, timeout=10), (
        f"pods watch rv stuck at {api._rv[srv.PODS]} < {head}")


def test_lease_election_over_http(api):
    assert api.acquire_or_renew_lease("ctl", "alice", lease_duration=1)
    assert not api.acquire_or_renew_lease("ctl", "bob", lease_duration=1)
    assert api.lease_holder("ctl") == "alice"
    assert api.acquire_or_renew_lease("ctl", "alice", lease_duration=1)
    time.sleep(1.1)
    # client-go expiry discipline: a challenger must OBSERVE the record
    # unchanged for a full duration of ITS OWN clock before stealing —
    # never by comparing its clock to the holder's renewTime stamp. The
    # first post-expiry attempt only records the observation.
    assert not api.acquire_or_renew_lease("ctl", "bob", lease_duration=30)
    time.sleep(1.1)
    assert api.acquire_or_renew_lease("ctl", "bob", lease_duration=30)
    assert api.lease_holder("ctl") == "bob"
    lease = kube.KubeLease(api, "ctl")
    lease.release("bob")
    assert api.lease_holder("ctl") == ""


def test_events_posted_to_cluster(api, fake):
    api.create(srv.PODS, make_pod("e1"))
    api.record_event("default/e1", "Pod", "Warning", "FailedScheduling",
                     "0/0 nodes available")
    assert len(api.events()) == 1
    with fake.store.lock:
        evs = [o for (p, _ns, _n), o in fake.store.objects.items()
               if p == "events"]
    assert len(evs) == 1
    assert evs[0]["reason"] == "FailedScheduling"
    assert evs[0]["involvedObject"]["name"] == "e1"


def test_kube_mode_refuses_local_persistence(api):
    with pytest.raises(RuntimeError):
        api.restore(srv.PODS, [])
    api.set_persistence_sink(None)   # explicit no-op, must not raise


def test_kubeconfig_parsing(tmp_path):
    cfgfile = tmp_path / "kubeconfig"
    cfgfile.write_text("""
apiVersion: v1
kind: Config
current-context: dev
contexts:
- name: dev
  context: {cluster: local, user: admin}
- name: other
  context: {cluster: remote, user: admin}
clusters:
- name: local
  cluster: {server: "http://127.0.0.1:9999"}
- name: remote
  cluster: {server: "https://10.0.0.1:6443", insecure-skip-tls-verify: true}
users:
- name: admin
  user: {token: sekrit}
""")
    info = kube.ConnectionInfo.from_kubeconfig(str(cfgfile))
    assert info.server == "http://127.0.0.1:9999"
    assert info.token == "sekrit"
    assert info.scheme == "http" and info.port == 9999
    info2 = kube.ConnectionInfo.from_kubeconfig(str(cfgfile),
                                                context="other")
    assert info2.scheme == "https" and info2.port == 6443
    assert info2.ssl_context is not None


def test_scheduler_cli_rejects_kubeconfig_plus_state_dir(tmp_path, capsys):
    from tpusched.cmd import scheduler as cmd_sched
    rc = cmd_sched.main(["--kubeconfig", str(tmp_path / "kc"),
                         "--state-dir", str(tmp_path / "state")])
    assert rc == 1
    rc = cmd_sched.main(["--kubeconfig", str(tmp_path / "kc"),
                         "--emulate-pool", "4x4x4"])
    assert rc == 1


# -- the integration proof: a gang through HTTP watch streams -----------------

def test_scheduler_binds_gang_through_real_http(fake):
    """The round's acceptance test: the SAME plugin suite, transport
    swapped. A real Scheduler + tpu-gang profile runs against the fake
    apiserver over sockets; an 8-pod gang goes Pending → all-bound with
    per-chip annotations, driven end-to-end by HTTP watch streams."""
    from tpusched.config.profiles import tpu_gang_profile
    from tpusched.plugins import default_registry
    from tpusched.plugins.topologymatch import POOL_ANNOTATION
    from tpusched.sched import Scheduler

    api = kube.KubeAPIServer(kube.ConnectionInfo(fake.url)).start()
    topo, nodes = make_tpu_pool("pool-0", dims=(4, 4, 2))
    api.create(srv.TPU_TOPOLOGIES, topo)
    for n in nodes:
        api.create(srv.NODES, n)
    sched = Scheduler(api, default_registry(), tpu_gang_profile())
    sched.run()
    try:
        api.create(srv.POD_GROUPS, make_pod_group(
            "gang", min_member=8, tpu_slice_shape="4x4x2",
            tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"gang-{i}", pod_group="gang", limits={TPU: 4})
                for i in range(8)]
        for p in pods:
            api.create(srv.PODS, p)

        def all_bound():
            for p in pods:
                raw = fake.object("pods", "default", p.meta.name)
                if not (raw.get("spec") or {}).get("nodeName"):
                    return False
            return True

        assert wait_until(all_bound, timeout=30), (
            "gang did not bind through the HTTP transport")
        names = set()
        for p in pods:
            raw = fake.object("pods", "default", p.meta.name)
            ann = raw["metadata"].get("annotations") or {}
            assert ann.get(POOL_ANNOTATION) == "pool-0"
            names.add(raw["spec"]["nodeName"])
        assert len(names) == 8   # whole-pool gang: one host each
    finally:
        sched.stop()
        api.stop()
