"""k8s API contract suite: pins the in-memory API server to the semantics a
real kube-apiserver+etcd exhibits.

The reference's integration tier proves its plugins against a REAL control
plane (/root/reference/test/integration/main_test.go:31-46 boots
kube-apiserver + etcd via hack/integration-test.sh:36), so production
behaviors — optimistic-concurrency conflicts, merge-patch atomicity, watch
restart/replay, list+watch consistency — are exercised for free. This repo's
control plane is hermetic (tpusched/apiserver/server.py), so every such
behavior the schedulers/controllers rely on is pinned HERE, each case
annotated with the upstream behavior it substitutes for. Known divergences
are documented in doc/develop.md §"API-server contract".
"""
import threading

import pytest

from tpusched.api.meta import ObjectMeta
from tpusched.api.core import Binding
from tpusched.apiserver import APIServer, Clientset
from tpusched.apiserver import server as srv
from tpusched.apiserver.informers import InformerFactory
from tpusched.testing import make_node, make_pod, wait_until


# -- optimistic concurrency (PUT) --------------------------------------------

def test_stale_resource_version_put_conflicts():
    """Upstream: PUT with a resourceVersion older than the stored object
    returns 409 Conflict (etcd compare-and-swap on mod_revision); the
    client must re-read and retry. The classic lost-update guard."""
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    stale = api.get(srv.PODS, "default/p")          # reader A's copy
    api.patch(srv.PODS, "default/p",
              lambda p: p.meta.labels.update({"winner": "B"}))  # writer B
    stale.meta.labels["winner"] = "A"
    with pytest.raises(srv.Conflict):
        api.update(srv.PODS, stale)                 # A's put is stale
    assert api.get(srv.PODS, "default/p").meta.labels["winner"] == "B"


def test_fresh_resource_version_put_succeeds_and_bumps():
    """Upstream: PUT with the current resourceVersion wins and the stored
    object's RV strictly increases (etcd revision monotonicity)."""
    api = APIServer()
    created = api.create(srv.PODS, make_pod("p"))
    fresh = api.get(srv.PODS, "default/p")
    fresh.meta.labels["x"] = "1"
    updated = api.update(srv.PODS, fresh)
    assert updated.meta.resource_version > created.meta.resource_version
    assert api.get(srv.PODS, "default/p").meta.labels == {"x": "1"}


def test_conflict_then_reread_retry_succeeds():
    """The controller retry loop upstream documents (get → mutate → put,
    on 409 re-get): after re-reading, the same mutation lands."""
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    stale = api.get(srv.PODS, "default/p")
    api.patch(srv.PODS, "default/p",
              lambda p: p.meta.labels.update({"other": "y"}))
    stale.meta.labels["mine"] = "x"
    with pytest.raises(srv.Conflict):
        api.update(srv.PODS, stale)
    retry = api.get(srv.PODS, "default/p")
    retry.meta.labels["mine"] = "x"
    api.update(srv.PODS, retry)
    got = api.get(srv.PODS, "default/p")
    assert got.meta.labels == {"other": "y", "mine": "x"}  # neither lost


def test_create_on_existing_key_conflicts():
    """Upstream: POST of an existing name returns 409 AlreadyExists."""
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    with pytest.raises(srv.Conflict):
        api.create(srv.PODS, make_pod("p"))


def test_update_preserves_server_owned_metadata():
    """Upstream: uid and creationTimestamp are server-owned; a PUT cannot
    rewrite them (ObjectMeta validation / PrepareForUpdate)."""
    api = APIServer()
    created = api.create(srv.PODS, make_pod("p"))
    fresh = api.get(srv.PODS, "default/p")
    fresh.meta.uid = "forged-uid"
    fresh.meta.creation_timestamp = 1.0
    updated = api.update(srv.PODS, fresh)
    assert updated.meta.uid == created.meta.uid
    assert updated.meta.creation_timestamp == created.meta.creation_timestamp


# -- merge-patch vs replace ---------------------------------------------------

def test_concurrent_patches_merge_without_lost_update():
    """Upstream: strategic-merge-patch applies read-modify-write server-side
    under etcd's txn, so two controllers patching DIFFERENT fields both
    land — unlike two stale PUTs, where the second 409s. This is why every
    reference controller mutates via patch (pkg/util/podgroup.go:33-50)."""
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    n_each = 50
    def patcher(field):
        for i in range(n_each):
            api.patch(srv.PODS, "default/p",
                      lambda p, f=field, i=i: p.meta.labels.update({f: str(i)}))
    ts = [threading.Thread(target=patcher, args=(f,)) for f in ("a", "b", "c")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    got = api.get(srv.PODS, "default/p")
    # every field's final write survived — no interleaving lost one
    assert {got.meta.labels[f] for f in ("a", "b", "c")} == {str(n_each - 1)}


def test_patch_mutator_sees_latest_state():
    """Upstream: a merge patch is applied against the CURRENT object, not
    the reader's snapshot — sequential patches compose."""
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    for _ in range(10):
        api.patch(srv.PODS, "default/p",
                  lambda p: p.meta.labels.update(
                      {"n": str(int(p.meta.labels.get("n", "0")) + 1)}))
    assert api.get(srv.PODS, "default/p").meta.labels["n"] == "10"


# -- watch semantics ----------------------------------------------------------

def test_watch_events_fire_in_mutation_order():
    """Upstream: a single key's watch events arrive in etcd revision order
    (Added → Modified* → Deleted), never reordered."""
    api = APIServer()
    seen = []
    api.add_watch(srv.PODS, lambda ev: seen.append(
        (ev.type, ev.object.meta.resource_version)))
    api.create(srv.PODS, make_pod("p"))
    api.patch(srv.PODS, "default/p", lambda p: None)
    api.patch(srv.PODS, "default/p", lambda p: None)
    api.delete(srv.PODS, "default/p")
    assert [t for t, _ in seen] == [srv.ADDED, srv.MODIFIED, srv.MODIFIED,
                                    srv.DELETED]
    rvs = [rv for _, rv in seen]
    assert rvs == sorted(rvs)


def test_watch_event_objects_are_immutable_snapshots():
    """Upstream/client-go: an event carries the object AT that revision;
    later writes must not mutate an already-delivered event (the shared
    informer cache's read-only contract)."""
    api = APIServer()
    captured = []
    api.add_watch(srv.PODS, lambda ev: captured.append(ev.object))
    api.create(srv.PODS, make_pod("p"))
    api.patch(srv.PODS, "default/p",
              lambda p: p.meta.labels.update({"late": "write"}))
    assert "late" not in captured[0].meta.labels      # ADDED-time state
    assert captured[1].meta.labels == {"late": "write"}


def test_watch_reconnect_replays_current_state():
    """Upstream: a watcher that reconnects relists — it receives synthetic
    Added events for every LIVE object and nothing for objects deleted
    while it was away (no ghost deletes, no missed state)."""
    api = APIServer()
    api.create(srv.PODS, make_pod("kept"))
    api.create(srv.PODS, make_pod("gone"))
    api.delete(srv.PODS, "default/gone")
    api.patch(srv.PODS, "default/kept",
              lambda p: p.meta.labels.update({"v": "2"}))
    seen = []
    api.add_watch(srv.PODS, lambda ev: seen.append(ev))   # the "reconnect"
    assert [(e.type, e.object.meta.name) for e in seen] == [
        (srv.ADDED, "kept")]
    assert seen[0].object.meta.labels == {"v": "2"}       # current revision


def test_informer_converges_under_concurrent_writers():
    """Upstream: list+watch gives a cache that converges to the server's
    state under arbitrary write concurrency (no lost events, no stale
    entries) — the resync-free guarantee controllers build on."""
    api = APIServer()
    factory = InformerFactory(api)
    informer = factory.pods()
    n_writers, n_objs = 4, 25

    def writer(w):
        for i in range(n_objs):
            name = f"w{w}-p{i}"
            api.create(srv.PODS, make_pod(name))
            api.patch(srv.PODS, f"default/{name}",
                      lambda p: p.meta.labels.update({"done": "1"}))
            if i % 3 == 0:
                api.delete(srv.PODS, f"default/{name}")
    ts = [threading.Thread(target=writer, args=(w,)) for w in range(n_writers)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    want = {p.meta.key for p in api.list(srv.PODS)}
    assert wait_until(lambda: {p.meta.key for p in informer.items()} == want,
                      timeout=5)
    for p in informer.items():
        assert p.meta.labels.get("done") == "1"           # no stale revision


# -- subresources + read isolation -------------------------------------------

def test_bind_subresource_rejects_double_bind():
    """Upstream: pods/binding on an already-bound pod fails (the scheduler
    cache's assume/confirm machinery relies on exactly this)."""
    api = APIServer()
    api.create(srv.NODES, make_node("n1"))
    api.create(srv.NODES, make_node("n2"))
    api.create(srv.PODS, make_pod("p"))
    api.bind(Binding(pod_key="default/p", node_name="n1"))
    with pytest.raises(srv.Conflict):
        api.bind(Binding(pod_key="default/p", node_name="n2"))
    assert api.get(srv.PODS, "default/p").spec.node_name == "n1"


def test_reads_are_isolated_deep_copies():
    """client-go contract: objects from GET/LIST are the caller's own;
    mutating them must not leak into the store or other readers."""
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    a = api.get(srv.PODS, "default/p")
    a.meta.labels["rogue"] = "1"
    a.spec.containers[0].limits["cpu"] = 999
    b = api.get(srv.PODS, "default/p")
    assert "rogue" not in b.meta.labels
    assert b.spec.containers[0].limits.get("cpu") != 999


def test_resource_version_is_store_global_and_monotonic():
    """Upstream: resourceVersion comes from one etcd revision counter
    shared by all kinds — writes to different kinds never reuse an RV."""
    api = APIServer()
    rvs = [
        api.create(srv.PODS, make_pod("p")).meta.resource_version,
        api.create(srv.NODES, make_node("n")).meta.resource_version,
        api.patch(srv.PODS, "default/p", lambda p: None).meta.resource_version,
        api.patch(srv.NODES, "/n", lambda n: None).meta.resource_version,
    ]
    assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs)


def test_delete_missing_and_get_missing_raise_not_found():
    """Upstream: 404 for both; controllers branch on it (IsNotFound)."""
    api = APIServer()
    with pytest.raises(srv.NotFound):
        api.get(srv.PODS, "default/nope")
    with pytest.raises(srv.NotFound):
        api.delete(srv.PODS, "default/nope")


def test_create_restamps_falsy_creation_timestamp():
    """Upstream: the apiserver sets metadata.creationTimestamp at admission
    when absent. Round-4 reliance: sanitize_for_resubmit zeroes the
    timestamp so a migrated pod's age restarts — if create() ever stopped
    re-stamping, the defrag controller would instantly classify freshly
    resubmitted migrants as long-blocked."""
    api = APIServer()
    p = make_pod("fresh")
    p.meta.creation_timestamp = 0
    stored = api.create(srv.PODS, p)
    assert stored.meta.creation_timestamp > 0
    # a non-zero timestamp is preserved (recovery/restore path relies on it)
    q = make_pod("old")
    q.meta.creation_timestamp = 123.0
    assert api.create(srv.PODS, q).meta.creation_timestamp == 123.0


def test_create_conflict_on_existing_key():
    """Upstream: 409 AlreadyExists. Round-4 reliance: simulate_plan's
    fail-fast validation exists precisely because mid-plan creates raise
    this — the contract must hold for derived set gang names too."""
    api = APIServer()
    api.create(srv.PODS, make_pod("dup"))
    with pytest.raises(srv.Conflict):
        api.create(srv.PODS, make_pod("dup"))


def test_current_resource_version_tracks_every_write():
    """Round-4 reliance: the defrag controller's negative trial cache keys
    on this cursor — it must move on EVERY mutation (any kind), and only
    then."""
    api = APIServer()
    rv0 = api.current_resource_version()
    assert api.current_resource_version() == rv0   # reads don't bump
    api.create(srv.PODS, make_pod("a"))
    rv1 = api.current_resource_version()
    assert rv1 > rv0
    api.patch(srv.PODS, "default/a", lambda p: None)
    rv2 = api.current_resource_version()
    assert rv2 > rv1
    api.delete(srv.PODS, "default/a")
    assert api.current_resource_version() > rv2


def test_peek_is_zero_copy_and_live():
    """peek() hands back the STORED object (hot-poll path): it must reflect
    later writes through the same reference... but callers must never
    mutate it. The contract pinned: peek sees the post-patch object
    identity change (stored objects are replaced wholesale, never mutated
    in place — the shared-informer-cache discipline)."""
    api = APIServer()
    api.create(srv.PODS, make_pod("p"))
    first = api.peek(srv.PODS, "default/p")
    api.patch(srv.PODS, "default/p",
              lambda p: p.meta.labels.update({"x": "1"}))
    second = api.peek(srv.PODS, "default/p")
    assert second is not first          # wholesale replacement, no in-place
    assert second.meta.labels.get("x") == "1"
    assert first.meta.labels.get("x") is None   # old snapshot untouched


def test_patch_missing_raises_not_found():
    """Upstream: PATCH on a missing object is 404 (no upsert). The defrag
    actuator and controllers retry-or-skip on this; a silent create here
    would resurrect deleted pods."""
    api = APIServer()
    with pytest.raises(srv.NotFound):
        api.patch(srv.PODS, "default/ghost", lambda p: None)


def test_delete_then_recreate_same_key():
    """Upstream: deleting a key and POSTing a new object under the same
    name yields a NEW object: its resourceVersion is strictly newer than
    anything the old incarnation had, and watchers see Deleted then Added
    (never Modified). Defrag actuation (delete gang → resubmit sanitized
    copies) and the fleet bench's create/delete steady-state depend on the
    two incarnations never being conflated."""
    api = APIServer()
    events = []
    api.add_watch(srv.PODS, lambda ev: events.append(ev))
    first = api.create(srv.PODS, make_pod("p"))
    api.patch(srv.PODS, "default/p",
              lambda p: p.meta.labels.update({"gen": "1"}))
    last_rv = api.get(srv.PODS, "default/p").meta.resource_version
    api.delete(srv.PODS, "default/p")
    second = api.create(srv.PODS, make_pod("p"))
    assert second.meta.resource_version > last_rv > first.meta.resource_version
    assert [e.type for e in events] == [srv.ADDED, srv.MODIFIED, srv.DELETED,
                                        srv.ADDED]
    assert "gen" not in events[-1].object.meta.labels   # new incarnation
    assert api.get(srv.PODS, "default/p").meta.labels == {}


def test_deleted_event_carries_final_state():
    """Upstream: a DELETED watch event carries the object's last-stored
    state. The scheduler cache detaches a deleted pod from the node named
    by the EVENT object's spec.nodeName — an empty or stale object here
    would leak phantom occupancy on the node."""
    api = APIServer()
    api.create(srv.NODES, make_node("n1"))
    api.create(srv.PODS, make_pod("p"))
    api.bind(Binding(pod_key="default/p", node_name="n1"))
    deleted = []
    api.add_watch(srv.PODS,
                  lambda ev: deleted.append(ev.object)
                  if ev.type == srv.DELETED else None)
    api.delete(srv.PODS, "default/p")
    assert len(deleted) == 1
    assert deleted[0].spec.node_name == "n1"     # final (bound) state
