"""Hand-rolled API-object deepcopies: equal to the generic copy, and fully
independent (mutating the copy never leaks into the original).

The API-server store copies every object on read/write (server.py), so these
fast copies are on the hot scheduling path; a missed nested container here
would silently alias informer-cache state — exactly the Quantity-aliasing
class of bug the reference has (SURVEY §2 quirks, gpu_node.go:134-144).
"""
from __future__ import annotations

import copy

from tpusched.api.core import (Container, Pod, PodCondition,
                               PodDisruptionBudget, PriorityClass, Taint,
                               Toleration)
from tpusched.api.meta import ObjectMeta, OwnerReference
from tpusched.api.scheduling import ElasticQuota, PodGroup
from tpusched.api.topology import TpuTopology


def make_pod() -> Pod:
    p = Pod()
    p.meta = ObjectMeta(name="p", namespace="ns",
                        labels={"a": "1"}, annotations={"b": "2"},
                        owner_references=[OwnerReference(kind="Job", name="j")])
    p.spec.containers = [Container(requests={"cpu": 1000},
                                   limits={"google.com/tpu": 4})]
    p.spec.init_containers = [Container(name="init", requests={"cpu": 500})]
    p.spec.node_selector = {"pool": "a"}
    p.spec.tolerations = [Toleration(key="tpu", operator="Exists")]
    p.spec.overhead = {"cpu": 10}
    p.status.conditions = [PodCondition(type="PodScheduled")]
    return p


def assert_equal_and_independent(obj, mutators):
    """copy == original (vs the generic deep copy), then each mutator applied
    to the copy must leave the original untouched."""
    reference = copy.deepcopy(obj)
    got = obj.deepcopy()
    assert got == reference
    for mutate in mutators:
        cp = obj.deepcopy()
        mutate(cp)
        assert obj == reference, f"mutation leaked into original via {mutate}"


def test_pod_deepcopy():
    assert_equal_and_independent(make_pod(), [
        lambda p: p.meta.labels.update(x="y"),
        lambda p: p.meta.annotations.clear(),
        lambda p: setattr(p.meta.owner_references[0], "name", "changed"),
        lambda p: p.spec.containers[0].requests.update(cpu=9),
        lambda p: p.spec.containers[0].limits.clear(),
        lambda p: p.spec.init_containers[0].requests.update(cpu=9),
        lambda p: p.spec.node_selector.update(pool="b"),
        lambda p: setattr(p.spec.tolerations[0], "key", "changed"),
        lambda p: p.spec.overhead.update(cpu=99),
        lambda p: setattr(p.status.conditions[0], "status", "False"),
        lambda p: p.status.conditions.append(PodCondition(type="Ready")),
    ])


def test_node_deepcopy():
    from tpusched.testing import make_node
    n = make_node("n1", capacity={"cpu": 8000, "google.com/tpu": 4})
    n.spec.taints = [Taint(key="tpu", effect="NoSchedule")]
    n.meta.labels["tpu.dev/pool"] = "pool-a"
    assert_equal_and_independent(n, [
        lambda m: m.status.allocatable.update(cpu=1),
        lambda m: m.status.capacity.clear(),
        lambda m: setattr(m.spec.taints[0], "key", "changed"),
        lambda m: m.meta.labels.clear(),
    ])


def test_pod_group_deepcopy():
    pg = PodGroup()
    pg.meta.name = "gang"
    pg.spec.min_member = 8
    pg.spec.min_resources = {"cpu": 1000}
    pg.status.scheduled = 3
    assert_equal_and_independent(pg, [
        lambda g: g.spec.min_resources.update(cpu=9),
        lambda g: setattr(g.status, "scheduled", 99),
        lambda g: setattr(g.spec, "min_member", 1),
    ])
    # None min_resources stays None
    pg2 = PodGroup()
    assert pg2.deepcopy().spec.min_resources is None


def test_elastic_quota_deepcopy():
    eq = ElasticQuota()
    eq.meta.name = "q"
    eq.spec.min = {"cpu": 1}
    eq.spec.max = {"cpu": 2}
    eq.status.used = {"cpu": 1}
    assert_equal_and_independent(eq, [
        lambda q: q.spec.min.update(cpu=9),
        lambda q: q.spec.max.clear(),
        lambda q: q.status.used.update(cpu=9),
    ])


def test_tpu_topology_deepcopy():
    t = TpuTopology()
    t.meta.name = "pool-a"
    t.spec.pool = "pool-a"
    t.spec.dims = (8, 8, 4)
    t.spec.hosts = {"n1": (0, 0, 0), "n2": (2, 0, 0)}
    assert_equal_and_independent(t, [
        lambda x: x.spec.hosts.update(n3=(4, 0, 0)),
        lambda x: setattr(x.spec, "dims", (1,)),
    ])


def _sentinel(tp, counter):
    """A non-default value of type `tp` (recursing into containers)."""
    import dataclasses
    import typing
    counter[0] += 1
    n = counter[0]
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[...]
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _sentinel(args[0], counter)
    if origin is list:
        (elem,) = typing.get_args(tp)
        return [_sentinel(elem, counter)]
    if origin is dict:
        k, v = typing.get_args(tp)
        return {_sentinel(k, counter): _sentinel(v, counter)}
    if origin is tuple:
        args = typing.get_args(tp)
        if len(args) == 2 and args[1] is Ellipsis:
            return (_sentinel(args[0], counter), _sentinel(args[0], counter))
        return tuple(_sentinel(a, counter) for a in args)
    if dataclasses.is_dataclass(tp):
        return _populated(tp, counter)
    if tp is bool:
        return True
    if tp is int:
        return n
    if tp is float:
        return n + 0.5
    if tp is str:
        return f"s{n}"
    raise TypeError(f"no sentinel for {tp}")


def _populated(cls, counter):
    """Instance of `cls` with EVERY field set to a non-default sentinel."""
    import dataclasses
    import typing
    obj = cls()
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        setattr(obj, f.name, _sentinel(hints[f.name], counter))
    # re-apply constructor invariants (cluster-scoped kinds force
    # meta.namespace="") — the hand-rolled copies go through __init__ and
    # legitimately re-establish them
    post = getattr(obj, "__post_init__", None)
    if post is not None:
        post()
    return obj


def _mutate_every_container(obj):
    """Recursively mutate every dict/list (and dataclass scalar) reachable
    from obj's fields, so any container aliased between a copy and its
    original shows up as a change to the original."""
    import dataclasses
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if dataclasses.is_dataclass(v):
            _mutate_every_container(v)
        elif isinstance(v, dict):
            v["__mut__"] = "__mut__"
        elif isinstance(v, list):
            for e in v:
                if dataclasses.is_dataclass(e):
                    _mutate_every_container(e)
            v.append("__mut__")
        elif isinstance(v, bool):
            setattr(obj, f.name, not v)
        elif isinstance(v, (int, float)):
            setattr(obj, f.name, v + 1)
        elif isinstance(v, str):
            setattr(obj, f.name, v + "__mut__")
        # tuples/None are immutable — aliasing them is safe


def test_deepcopy_covers_every_field():
    """Drift guard, two halves:

    1. Dropped fields: a field added to any API dataclass without updating
       its hand-rolled deepcopy silently resets to default on every
       API-server read/write. Populating every field with sentinels makes
       that a loud equality failure.
    2. Aliased containers: a future mutable field copied by a shallow
       replace() would pass the equality check while sharing state with the
       original (the reference's Quantity-aliasing bug class,
       gpu_node.go:134-144). Mutating every container of the copy must
       leave the original untouched."""
    from tpusched.api.core import Node
    for cls in (ObjectMeta, Pod, Node, PodGroup, ElasticQuota, TpuTopology,
                PriorityClass, PodDisruptionBudget):
        obj = _populated(cls, [0])
        reference = copy.deepcopy(obj)
        cp = obj.deepcopy()
        assert cp == reference, f"{cls.__name__}.deepcopy dropped a field"
        _mutate_every_container(cp)
        assert obj == reference, \
            f"{cls.__name__}.deepcopy aliased a container with the original"


def test_priority_class_and_pdb_deepcopy():
    pc = PriorityClass(value=100)
    pc.meta.name = "high"
    assert_equal_and_independent(pc, [lambda c: setattr(c, "value", 0)])
    pdb = PodDisruptionBudget(selector={"app": "x"}, disruptions_allowed=1)
    pdb.meta.name = "pdb"
    assert_equal_and_independent(pdb, [lambda b: b.selector.clear()])
