"""ISSUE 13: incremental torus window index (topology/windowindex.py).

The load-bearing property: under ANY sequence of bind/unbind/assume/
forget (gang rollback)/node-health/node-removal transitions, the
incrementally-maintained index answers EXACTLY what (a) a from-scratch
rebuild of the index answers, and (b) the Python full-recompute oracle
(TopologyMatch._occupancy + feasible_membership) answers over a snapshot
captured at the same pool cursor — for survivor sets, membership counts,
assigned sets, utilization, AND the capacity plane / largest-placeable
window.  Both kernel implementations (native C++ and pure Python) are
driven through the same property.
"""
import copy
from types import SimpleNamespace

import pytest

from tpusched import native
from tpusched.api.core import NodeCondition
from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.obs.capacity import largest_window_chips, pool_occupancy
from tpusched.plugins.topologymatch import COORD_ANNOTATION
from tpusched.plugins.topologymatch.plugin import TopologyMatch
from tpusched.sched.cache import Cache
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool)
from tpusched.topology.engine import (MaskGrid, enumerate_placement_masks,
                                      feasible_membership)
from tpusched.topology.torus import HostGrid
from tpusched.topology.windowindex import TorusWindowIndex
from tpusched.util.metrics import (torus_index_differential_mismatches,
                                   torus_index_queries)

POOL = "wix"
DIMS = (4, 4, 4)              # v5p: host grid 2x2x4 = 16 hosts
SHAPES = ((2, 2, 4), (4, 4, 4))
GANGS = ("g0", "g1", "gx")    # gx never places: the empty-gang query


@pytest.fixture(params=["native", "python"])
def kernels(request, monkeypatch):
    """Drive every test through both kernel implementations."""
    if request.param == "python":
        monkeypatch.setattr(native, "load", lambda: None)
    elif not native.available():
        pytest.skip("native engine unavailable (no toolchain)")
    return request.param


def build_world():
    topo, nodes = make_tpu_pool(POOL, dims=DIMS)
    cache = Cache()
    idx = TorusWindowIndex(publish=False)
    idx.observe_topology(topo)
    cache.attach_window_index(idx)
    for n in nodes:
        cache.add_node(n)
    grid = HostGrid.from_spec(topo.spec)
    return SimpleNamespace(topo=topo, nodes=nodes, cache=cache, idx=idx,
                           grid=grid, mgrid=MaskGrid(grid))


def oracle_query(world, snapshot, gang, need, shape):
    """The Python full-recompute path, verbatim semantics."""
    fake = SimpleNamespace(_node_pg_usage=TopologyMatch._node_pg_usage)
    assigned, free, eligible, util = TopologyMatch._occupancy(
        fake, world.grid, snapshot, gang, "default", need)
    pset = enumerate_placement_masks(world.mgrid, shape)
    n, mem = feasible_membership(
        pset, world.mgrid.mask_of(assigned), world.mgrid.mask_of(free),
        world.mgrid.mask_of(eligible))
    return n, mem, frozenset(assigned), util


def assert_index_matches_oracle(world):
    snap = world.cache.snapshot()
    cursor = snap.pool_cursors.get(POOL)
    for gang in GANGS:
        for shape in SHAPES:
            for need in (2, 4):
                q = world.idx.query(world.topo, shape, ("default", gang),
                                    need, cursor)
                assert q is not None, "index refused at a matching cursor"
                n, mem, asg, util = oracle_query(world, snap, gang, need,
                                                 shape)
                assert q.survivors == n, (gang, shape, need)
                assert q.membership == mem, (gang, shape, need)
                assert q.assigned == asg, (gang, shape, need)
                assert abs(q.pool_util - util) < 1e-12
    # capacity plane + largest placeable vs the reference implementation
    free_set, free_chips, capacity = pool_occupancy(world.grid, snap)
    view = world.idx.capacity_view(world.topo)
    assert view is not None
    assert view[0] == free_set
    assert view[1] == free_chips
    assert view[2] == capacity
    lp = world.idx.largest_placeable(world.topo)
    assert lp[0] == largest_window_chips(world.grid, free_set)


def assert_incremental_equals_scratch(world):
    """A fresh index seeded from the same cache must hold byte-identical
    planes/blocked/membership state."""
    scratch = TorusWindowIndex(publish=False)
    scratch.observe_topology(world.topo)
    world.cache.attach_window_index(scratch)
    try:
        for shape in SHAPES:
            world.idx.ensure_shape(POOL, shape)
            scratch.ensure_shape(POOL, shape)
        inc = world.idx.debug_plane(POOL)
        fresh = scratch.debug_plane(POOL)
        for key in ("free_mask", "cap_mask", "gang_cells", "total_alloc",
                    "total_used", "free_chips"):
            assert inc[key] == fresh[key], key
        for shape in SHAPES:
            a, b = inc["shapes"][shape], fresh["shapes"][shape]
            assert a["survivors"] == b["survivors"], shape
            assert a["membership"] == b["membership"], shape
            assert a["covered"] == b["covered"], shape
            # blocked counts may differ only in how OVER-blocked a dead
            # placement is... they cannot: both count the same cells
            assert a["blocked"] == b["blocked"], shape
    finally:
        world.cache.attach_window_index(world.idx)


# -- the property ------------------------------------------------------------

def _ops_machine(world, ops):
    """Interpret an op stream against the cache; pods are tracked so
    unbind/forget target live keys."""
    live = {}
    counter = [0]
    for kind, a, b in ops:
        node = world.nodes[a % len(world.nodes)]
        if kind == "bind":
            counter[0] += 1
            gang = GANGS[b % 2] if b % 3 else ""
            chips = (1, 2, 4)[b % 3]
            p = make_pod(f"p{counter[0]}", pod_group=gang,
                         limits={TPU: chips}, node_name=node.name)
            world.cache.add_pod(p)
            live[p.key] = p
        elif kind == "assume":
            counter[0] += 1
            gang = GANGS[b % 2]
            p = make_pod(f"a{counter[0]}", pod_group=gang,
                         limits={TPU: 4})
            world.cache.assume_pod(p, node.name)
            live[p.key] = p
        elif kind == "forget" and live:
            key = sorted(live)[b % len(live)]
            world.cache.forget_pod(live.pop(key))
        elif kind == "unbind" and live:
            key = sorted(live)[b % len(live)]
            world.cache.remove_pod(live.pop(key))
        elif kind == "health":
            info = world.cache._infos.get(node.name)
            if info is None:
                continue          # node currently removed
            flipped = copy.deepcopy(info.node)
            ready = any(c.type == "Ready" and c.status == "True"
                        for c in flipped.status.conditions)
            flipped.status.conditions = [NodeCondition(
                type="Ready", status="False" if ready else "True")]
            world.cache.update_node(flipped)
        elif kind == "remove_node":
            world.cache.remove_node(node)
        elif kind == "add_node":
            world.cache.add_node(copy.deepcopy(node))


try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st
    HAVE_HYPOTHESIS = True

    OPS = st.lists(
        st.tuples(
            st.sampled_from(["bind", "assume", "forget", "unbind",
                             "health", "remove_node", "add_node"]),
            st.integers(min_value=0, max_value=31),
            st.integers(min_value=0, max_value=31)),
        max_size=24)

    @given(ops=OPS)
    @settings(max_examples=25, deadline=None,
              suppress_health_check=[HealthCheck.function_scoped_fixture])
    def test_random_transitions_match_oracle_and_scratch(kernels, ops):
        world = build_world()
        _ops_machine(world, ops)
        assert_index_matches_oracle(world)
        assert_incremental_equals_scratch(world)
except ImportError:   # pragma: no cover - CI always has hypothesis
    HAVE_HYPOTHESIS = False


# -- unit coverage ------------------------------------------------------------

def test_seeded_fuzz_transitions_match_oracle_and_scratch(kernels):
    """Deterministic stand-in for the hypothesis property when hypothesis
    is absent: 12 seeded random op streams through the same machine."""
    import random
    kinds = ["bind", "assume", "forget", "unbind", "health",
             "remove_node", "add_node"]
    for seed in range(12):
        rng = random.Random(20260804 + seed)
        ops = [(rng.choice(kinds), rng.randrange(32), rng.randrange(32))
               for _ in range(rng.randrange(4, 28))]
        world = build_world()
        # assert mid-stream too: the second query after more deltas takes
        # the memo PATCH path (dirty-cell repair), not a fresh build
        half = len(ops) // 2
        _ops_machine(world, ops[:half])
        assert_index_matches_oracle(world)
        _ops_machine(world, ops[half:])
        assert_index_matches_oracle(world)
        assert_incremental_equals_scratch(world)


def test_basic_transitions_match_oracle(kernels):
    """Deterministic spine of the property (runs even without
    hypothesis): bind foreign + gang pods, flip health, roll back."""
    world = build_world()
    _ops_machine(world, [
        ("bind", 0, 1), ("bind", 3, 2), ("assume", 5, 1),
        ("health", 7, 0), ("bind", 9, 0),
    ])
    assert_index_matches_oracle(world)
    _ops_machine(world, [
        ("forget", 0, 0), ("remove_node", 11, 0), ("health", 7, 0),
        ("unbind", 0, 0),
    ])
    assert_index_matches_oracle(world)      # memo patch path
    assert_incremental_equals_scratch(world)


def test_cursor_mismatch_falls_back(kernels):
    world = build_world()
    snap = world.cache.snapshot()
    cursor = snap.pool_cursors[POOL]
    # a mutation AFTER the snapshot: the index runs ahead of the epoch
    world.cache.add_pod(make_pod("late", limits={TPU: 4},
                                 node_name=world.nodes[0].name))
    assert world.idx.query(world.topo, SHAPES[0], ("default", "g0"), 4,
                           cursor) is None
    assert world.idx.query(world.topo, SHAPES[0], ("default", "g0"), 4,
                           None) is None
    # the fresh epoch serves again
    snap = world.cache.snapshot()
    assert world.idx.query(world.topo, SHAPES[0], ("default", "g0"), 4,
                           snap.pool_cursors[POOL]) is not None


def test_topology_rv_change_refuses_until_resync(kernels):
    world = build_world()
    snap = world.cache.snapshot()
    cursor = snap.pool_cursors[POOL]
    newer = world.topo.deepcopy()
    newer.meta.resource_version = world.topo.meta.resource_version + 7
    assert world.idx.query(newer, SHAPES[0], ("default", "g0"), 4,
                           cursor) is None
    assert world.idx.observe_topology(newer)
    world.cache.sync_window_index()
    snap = world.cache.snapshot()
    q = world.idx.query(newer, SHAPES[0], ("default", "g0"), 4,
                        snap.pool_cursors[POOL])
    assert q is not None and q.survivors > 0


def test_mark_stale_quarantines_until_sync(kernels):
    world = build_world()
    snap = world.cache.snapshot()
    cursor = snap.pool_cursors[POOL]
    world.idx.mark_stale(POOL)
    assert world.idx.query(world.topo, SHAPES[0], ("default", "g0"), 4,
                           cursor) is None
    world.cache.sync_window_index()
    assert world.idx.query(world.topo, SHAPES[0], ("default", "g0"), 4,
                           cursor) is not None
    assert_index_matches_oracle(world)


def test_mixed_pool_label_refuses(kernels):
    world = build_world()
    stray = copy.deepcopy(world.nodes[2])
    stray.meta.labels["tpu.dev/pool"] = "elsewhere"
    world.cache.update_node(stray)
    snap = world.cache.snapshot()
    assert world.idx.query(world.topo, SHAPES[0], ("default", "g0"), 4,
                           snap.pool_cursors.get(POOL)) is None


def test_window_exists_with_vacated_nodes(kernels):
    world = build_world()
    # fill the whole pool with foreign singletons: no window anywhere
    for i, n in enumerate(world.nodes):
        world.cache.add_pod(make_pod(f"f{i}", limits={TPU: 4},
                                     node_name=n.name))
    assert world.idx.window_exists_with(world.topo, (2, 2, 4)) is False
    # vacating one full 1x1x4-host column's residents reopens it
    # (node order: host coords (0,0,0..3) come first)
    want = {n.name for n in world.nodes[:4]}
    verdict = world.idx.window_exists_with(world.topo, (2, 2, 4), want)
    assert verdict is True
    # vacating a non-window scatter does not
    scatter = {world.nodes[0].name, world.nodes[5].name}
    assert world.idx.window_exists_with(world.topo, (2, 2, 4),
                                        scatter) is False


def test_defrag_pre_gate_consumes_index(kernels):
    from tpusched.sim.defrag import _unit_could_open_window
    world = build_world()
    api = srv.APIServer()
    api.create(srv.TPU_TOPOLOGIES, world.topo)
    # the apiserver stamps a fresh resourceVersion: re-observe ITS copy so
    # the gate's geometry check matches what api.list serves
    world.idx.observe_topology(api.peek(srv.TPU_TOPOLOGIES, f"/{POOL}"))
    world.cache.sync_window_index()
    # residents split by z-slab: gang a on host z∈{0,1}, gang b on z∈{2,3}
    # (node order is x,y,z row-major so i % 4 is the host z coordinate)
    for i, n in enumerate(world.nodes):
        gang = "resident-a" if i % 4 < 2 else "resident-b"
        p = make_pod(f"r{i}", pod_group=gang, limits={TPU: 4},
                     node_name=n.name)
        api.create(srv.PODS, p)
        world.cache.add_pod(p)
    # a 4x4x2-chip slice needs a 2x2x2-host slab
    job = dict(slice_shape="4x4x2", accelerator="", slices=1, members=8)
    unit_a = (("default/resident-a", 8, 32),)
    # vacating resident-a opens the z∈{0,1} slab
    assert _unit_could_open_window(world.idx, api, unit_a, job)
    # a unit vacating nothing new can never open an 8-host window
    unit_none = (("default/solo", 1, 4),)
    assert not _unit_could_open_window(world.idx, api, unit_none, job)
    # no index = no pruning
    assert _unit_could_open_window(None, api, unit_none, job)


def test_placement_set_shared_across_rv(kernels):
    world = build_world()
    ps1 = world.idx.placement_set(world.topo, world.mgrid, SHAPES[0])
    ps2 = world.idx.placement_set(world.topo, world.mgrid, SHAPES[0])
    assert ps1 is ps2
    ref = enumerate_placement_masks(world.mgrid, SHAPES[0])
    assert set(ps1.masks) == set(ref.masks)


# -- scheduler e2e ------------------------------------------------------------

def _add_pool(c, pool, dims):
    topo, nodes = make_tpu_pool(pool, dims=dims)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)
    return topo, nodes


def _slice_gang(c, name, shape, members):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape=shape,
        tpu_accelerator="tpu-v5p"))
    pods = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
            for i in range(members)]
    c.create_pods(pods)
    return pods


def test_scheduler_serves_from_index_with_differential(monkeypatch):
    """End-to-end: a slice gang schedules with the index serving sweeps,
    the in-cycle differential oracle (period 1 = every served sweep)
    agreeing, and health/version surfaced."""
    monkeypatch.setenv("TPUSCHED_INDEX_DIFFERENTIAL", "1")
    served0 = torus_index_queries.with_labels("served").value()
    mism0 = torus_index_differential_mismatches.value()
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                              denied_s=1)) as c:
        _add_pool(c, "e2e", dims=(4, 4, 4))
        pods = _slice_gang(c, "gang", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)
        for p in pods:
            got = c.pod(p.key)
            assert got.meta.annotations.get(COORD_ANNOTATION)
        idx = c.scheduler.window_index
        assert idx is not None
        health = idx.health(c.scheduler.cache.pool_cursor)
        assert "e2e" in health["pools"]
        assert health["pools"]["e2e"]["cursor_lag"] == 0
        assert health["updates_total"] > 0
    assert torus_index_queries.with_labels("served").value() > served0
    assert torus_index_differential_mismatches.value() == mism0, (
        "index answer diverged from the Python oracle during e2e")


def test_differential_mismatch_quarantines_and_self_heals(monkeypatch):
    """Seeded drift: corrupt the live plane's survivor table; the next
    served sweep's differential check must count a mismatch, quarantine
    the pool, reseed it from the cache, and keep scheduling correctly."""
    monkeypatch.setenv("TPUSCHED_INDEX_DIFFERENTIAL", "1")
    mism0 = torus_index_differential_mismatches.value()
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                              denied_s=1)) as c:
        _add_pool(c, "heal", dims=(4, 4, 4))
        first = _slice_gang(c, "first", "2x2x4", 4)
        assert c.wait_for_pods_scheduled([p.key for p in first], timeout=20)
        idx = c.scheduler.window_index
        # seed drift: cook the survivor count + memo of the hot shape
        with idx._lock:
            plane = idx._planes["heal"]
            sidx = plane.shapes[(2, 2, 4)]
            sidx.survivors += 3
            sidx.memo.clear()
        second = _slice_gang(c, "second", "2x2x4", 4)
        assert c.wait_for_pods_scheduled([p.key for p in second],
                                         timeout=20)
        assert torus_index_differential_mismatches.value() > mism0
        # healed: the plane serves again and matches the oracle
        snap = c.scheduler.cache.snapshot()
        q = idx.query(c.api.peek(srv.TPU_TOPOLOGIES, "/heal"), (2, 2, 4),
                      ("default", "nobody"), 4,
                      snap.pool_cursors.get("heal"))
        assert q is not None


def test_scheduler_disabled_by_env(monkeypatch):
    monkeypatch.setenv("TPUSCHED_NO_WINDOW_INDEX", "1")
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                              denied_s=1)) as c:
        _add_pool(c, "noidx", dims=(4, 4, 4))
        pods = _slice_gang(c, "gang", "4x4x4", 16)
        assert c.scheduler.window_index is None
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)
