"""Keep the contention bench scenario alive in CI (VERDICT r3 #4): one
iteration of the 8-gang / 2-team burst must admit everyone and satisfy the
quiesce invariants. Timing is the bench's job; this pins correctness of the
concurrent-arrival regime (queue ordering x backoff x denied-PG TTL x
freed-window claims) against regressions between bench runs."""
import importlib

bench = importlib.import_module("bench")


def test_contention_burst_admits_everyone():
    makespan, per_gang = bench.run_contention_once()
    assert len(per_gang) == 8
    # makespan runs from burst START; per-gang clocks from each gang's own
    # submission — so the slowest gang bounds it from below
    assert makespan >= max(per_gang) > 0
    assert makespan < 120
