"""Serving SLO harness (jaxbridge.serve.measure_serving_slo): the bench's
regression gates rest on its tick metrics being deterministic and meaning
what they claim — pin both, plus the prefix-cache TTFT win the bench line
advertises."""
import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tpusched.jaxbridge.serve import Request, measure_serving_slo
from tpusched.jaxbridge.workload import ModelConfig, init_params


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny()
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


def _workload(cfg, seed=9, n=12):
    rng = np.random.default_rng(seed)
    suffixes = [rng.integers(0, cfg.vocab, int(rng.integers(6, 40)),
                             dtype=np.int32) for _ in range(n)]
    gens = [int(rng.integers(4, 24)) for _ in range(n)]
    arrivals = np.cumsum(rng.poisson(2.0, size=n)).tolist()
    return suffixes, gens, arrivals


def _mk(prompts, gens):
    return [Request(rid=i, prompt=p, max_new_tokens=gens[i])
            for i, p in enumerate(prompts)]


TICK_KEYS = ("ttft_ticks_p50", "ttft_ticks_p99", "tokens", "ticks",
             "tokens_per_tick", "slo_attainment",
             "goodput_tokens_per_tick")


def test_tick_metrics_are_deterministic(model):
    """The gate contract: tick-denominated metrics must be identical run
    to run (they depend only on geometry — no EOS, no weights, no
    clock)."""
    cfg, params = model
    sfx, gens, arr = _workload(cfg)
    a = measure_serving_slo(cfg, params, _mk(sfx, gens), arr, slots=4,
                            max_seq=128, prompt_bucket=64,
                            ttft_slo_ticks=16)
    b = measure_serving_slo(cfg, params, _mk(sfx, gens), arr, slots=4,
                            max_seq=128, prompt_bucket=64,
                            ttft_slo_ticks=16)
    assert {k: a[k] for k in TICK_KEYS} == {k: b[k] for k in TICK_KEYS}
    assert a["tokens"] == float(sum(gens))   # all requests completed
    assert a["ttft_ticks_p50"] <= a["ttft_ticks_p99"]


def test_arrivals_are_honored(model):
    """A request must not be admitted before its arrival tick: with one
    request arriving at tick 20 into an idle engine, TTFT counts from
    arrival, not from t=0, and drain takes arrival + generation ticks."""
    cfg, params = model
    req = [Request(rid=0, prompt=np.arange(5, dtype=np.int32),
                   max_new_tokens=6)]
    m = measure_serving_slo(cfg, params, req, [20], slots=2, max_seq=64,
                            prompt_bucket=16)
    assert m["ticks"] >= 20 + 5          # idle ticks + decode ticks
    assert m["ttft_ticks_p99"] <= 2      # admitted+prefilled promptly


def test_goodput_counts_only_slo_meeting_requests(model):
    """With an SLO of 0 ticks only instant-TTFT requests count; with a
    huge SLO everything counts — goodput and attainment must track."""
    cfg, params = model
    sfx, gens, arr = _workload(cfg)
    tight = measure_serving_slo(cfg, params, _mk(sfx, gens), arr, slots=2,
                                max_seq=128, prompt_bucket=64,
                                ttft_slo_ticks=0)
    loose = measure_serving_slo(cfg, params, _mk(sfx, gens), arr, slots=2,
                                max_seq=128, prompt_bucket=64,
                                ttft_slo_ticks=10_000)
    assert loose["slo_attainment"] == 1.0
    assert loose["goodput_tokens_per_tick"] == loose["tokens_per_tick"]
    assert tight["slo_attainment"] < 1.0   # 2 slots, 12 requests: queueing
    assert (tight["goodput_tokens_per_tick"]
            < tight["tokens_per_tick"])


def test_prefix_cache_beats_full_prefill(model):
    """The bench's prefix line: same total context, but the shared head
    registered once — TTFT p50 and drain ticks must both improve vs
    chunk-prefilling the full prompts."""
    cfg, params = model
    sfx, gens, arr = _workload(cfg, seed=3)
    shared = (np.arange(48, dtype=np.int32) * 5) % cfg.vocab
    full = [np.concatenate([shared, s]) for s in sfx]
    base = measure_serving_slo(cfg, params, _mk(full, gens), arr, slots=4,
                               max_seq=192, prompt_bucket=96,
                               chunk_prefill=16, ttft_slo_ticks=32)
    pfx = measure_serving_slo(cfg, params, _mk(sfx, gens), arr, slots=4,
                              max_seq=192, prompt_bucket=96,
                              chunk_prefill=16, prefix_tokens=shared,
                              ttft_slo_ticks=32)
    assert pfx["ttft_ticks_p50"] < base["ttft_ticks_p50"]
    assert pfx["ticks"] < base["ticks"]
    assert pfx["tokens"] == base["tokens"]
