"""Adaptive node sampling (upstream numFeasibleNodesToFind semantics) and a
mid-size gang stress run exercising it end to end."""
from __future__ import annotations

import time

from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, make_tpu_pool)


def test_num_feasible_nodes_formula():
    with TestCluster() as c:
        s = c.scheduler
        # below the 100-node floor: scan everything
        assert s._num_feasible_nodes_to_find(64) == 64
        assert s._num_feasible_nodes_to_find(99) == 99
        # adaptive: 50 - nodes//125 percent, but never below 100 nodes
        assert s._num_feasible_nodes_to_find(256) == max(100, 256 * 48 // 100)
        assert s._num_feasible_nodes_to_find(5000) == 5000 * 10 // 100
        # explicit 100% pins a full scan
        s.percentage_of_nodes_to_score = 100
        assert s._num_feasible_nodes_to_find(256) == 256
        s.percentage_of_nodes_to_score = 5
        assert s._num_feasible_nodes_to_find(4000) == 200


def test_round_robin_start_spreads_scans():
    """With sampling active, successive cycles start at different nodes, so
    placement spreads instead of hammering the scan prefix."""
    with TestCluster() as c:
        c.add_nodes([make_node(f"n{i:03d}",
                               capacity=make_resources(cpu=64, memory="64Gi"))
                     for i in range(120)])
        pods = [make_pod(f"p{i}", requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(8)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods])
        start = c.scheduler._next_start_node_index
        assert start != 0  # the scan window moved


def test_512_gang_on_128_hosts_schedules_fully():
    """Stress: sampling must never starve a gang — all 512 members bind, 4
    chips per host, and the slice stays exact."""
    GANG = 512
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=120)) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(8, 8, 8))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        assert len(nodes) == 128
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("big", min_member=GANG,
                                    tpu_slice_shape="8x8x8",
                                    tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w{i:03d}", pod_group="big", limits={TPU: 1},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(GANG)]
        t0 = time.perf_counter()
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=120)
        elapsed = time.perf_counter() - t0
        used = {}
        for p in pods:
            node = c.pod(p.key).spec.node_name
            used[node] = used.get(node, 0) + 1
        assert len(used) == 128 and set(used.values()) == {4}
        # soft budget: scale roughly linearly with the bench (0.5s @ 256)
        assert elapsed < 30, f"512-gang took {elapsed:.1f}s"


def test_1024_gang_permit_barrier_thread_economy():
    """The event-driven barrier must hold a 1024-member gang with ZERO
    parked binding threads while waiting and bind it fully once quorum
    lands. Pre-redesign this would spawn 1024 OS threads blocked at
    wait_on_permit."""
    GANG = 1024
    import threading as _th
    # other tests' pools may linger inside their 5s shutdown-join window on
    # a loaded machine; assert the DELTA this cluster adds, not the global
    baseline = sum(1 for t in _th.enumerate()
                   if t.name.startswith("tpusched-bind"))
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=240)) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(8, 16, 8))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        assert len(nodes) == 256
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("huge", min_member=GANG,
                                    tpu_slice_shape="8x16x8",
                                    tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w{i:04d}", pod_group="huge", limits={TPU: 1},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(GANG)]
        t0 = time.perf_counter()
        c.create_pods(pods)

        # while the quorum forms, binding threads stay bounded: only the
        # pool's fixed workers exist, no thread-per-waiting-pod
        deadline = time.time() + 240
        max_bind_threads = 0
        while time.time() < deadline:
            names = [t.name for t in _th.enumerate()]
            max_bind_threads = max(
                max_bind_threads,
                sum(1 for n in names if n.startswith("tpusched-bind")))
            assert not any(n.startswith("bind-") for n in names)
            if c.pod_scheduled(pods[-1].key) and all(
                    c.pod_scheduled(p.key) for p in pods[::101]):
                break
            time.sleep(0.25)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=60)
        elapsed = time.perf_counter() - t0
        assert max_bind_threads - baseline <= 16
        used = {}
        for p in pods:
            node = c.pod(p.key).spec.node_name
            used[node] = used.get(node, 0) + 1
        assert len(used) == 256 and set(used.values()) == {4}
        assert elapsed < 90, f"1024-gang took {elapsed:.1f}s"
