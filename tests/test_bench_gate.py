"""Perf-gate logic (VERDICT r3 #5): the gate must fail a synthetic +0.15s
hot-path regression while passing ambient-noise inflation of the tail.
Exercises bench.py's _check_gate directly with synthetic sample arrays —
the statistic design is what's under test, not the scheduler."""
import importlib
import json

import numpy as np
import pytest

bench = importlib.import_module("bench")

# a quiet-machine headline distribution: min 0.26, p50 ~0.29, p99 ~0.34
QUIET = [0.26, 0.27, 0.27, 0.28, 0.28, 0.29, 0.29, 0.30, 0.30, 0.31,
         0.31, 0.32, 0.26, 0.27, 0.28, 0.29, 0.30, 0.31, 0.32, 0.33,
         0.28, 0.29, 0.30, 0.34]


@pytest.fixture
def gate(monkeypatch, tmp_path):
    """Arm the gate against a budget dict; returns a runner that yields the
    failure list for a given sample set."""
    def run(budget: dict, times):
        monkeypatch.setattr(bench, "_GATE", True)
        monkeypatch.setattr(bench, "_budgets_cache", None)
        monkeypatch.setattr(bench, "_gate_failures", [])
        path = tmp_path / "budget.json"
        path.write_text(json.dumps(budget))
        monkeypatch.setattr(bench, "_BUDGETS_PATH", str(path))
        bench._check_gate("gang_p99", times)
        return list(bench._gate_failures)
    return run


GANG_BUDGET = {"gang_p99": {"min": 0.38, "p99": 0.9}}


def test_quiet_machine_passes(gate):
    assert gate(GANG_BUDGET, QUIET) == []


def test_ambient_noise_passes(gate):
    """Ambient load: tail inflated by up to +0.2s on half the samples (the
    observed same-code spread) — the min is untouched, so no failure.
    This is the regime that forced the round-3 budget to 0.65."""
    noisy = [t + 0.2 * (i % 2) for i, t in enumerate(QUIET)]
    assert float(np.percentile(noisy, 99)) > 0.5   # old-style gate territory
    assert gate(GANG_BUDGET, noisy) == []


def test_hot_path_regression_fails(gate):
    """+0.15s on EVERY sample (a real hot-path cost): min moves with it."""
    regressed = [t + 0.15 for t in QUIET]
    failures = gate(GANG_BUDGET, regressed)
    assert failures and "min" in failures[0]


def test_round2_regression_would_fail(gate):
    """Round 2's 0.577s p99 regression (quiet min ~0.5) must not pass —
    the precise failure mode the round-3 0.65 p99-only budget had."""
    r2_like = [0.50 + 0.005 * i for i in range(24)]
    assert gate(GANG_BUDGET, r2_like)


def test_catastrophic_tail_fails(gate):
    """p99 backstop: a livelock-ish tail fails even with a healthy min."""
    tail = QUIET[:-1] + [1.4]
    failures = gate(GANG_BUDGET, tail)
    assert failures and "p99" in failures[0]


def test_legacy_number_budget_still_gates_p99(gate):
    assert gate({"gang_p99": 0.65}, QUIET) == []
    assert gate({"gang_p99": 0.65}, [t + 0.4 for t in QUIET])


def test_malformed_budget_reports(gate):
    assert gate({"gang_p99": "fast"}, QUIET)


def test_missing_key_passes(gate):
    assert gate({"other": 1.0}, QUIET) == []
