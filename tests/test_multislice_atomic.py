"""MultiSlice set-level atomic admission (VERDICT r3 #2): a multi-slice job
is N gangs sharing ``multislice_set``; with ``multislice_set_size`` declared,
admission is all-or-nothing across the set — no slice binds until every
member gang has quorum, and an infeasible member releases every sibling
slice's reservations instead of stranding chips."""
import time

from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.config.types import MultiSliceArgs
from tpusched.plugins.topologymatch import POOL_ANNOTATION
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool, wait_until)


def atomic_profile(permit_wait_s=10, denied_s=1, set_wait_s=6,
                   denied_set_s=30, hard=""):
    prof = tpu_gang_profile(permit_wait_s=permit_wait_s, denied_s=denied_s)
    prof.plugin_args["MultiSlice"] = MultiSliceArgs(
        set_schedule_timeout_seconds=set_wait_s,
        denied_set_expiration_time_seconds=denied_set_s,
        hard_domain_policy=hard)
    return prof


def add_pool(c, name, dcn_domain, dims=(4, 4, 4)):
    topo, nodes = make_tpu_pool(name, dims=dims, dcn_domain=dcn_domain)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)


def slice_pg(c, set_name, index, set_size, members=16, shape="4x4x4",
             min_resources=None):
    name = f"{set_name}-slice-{index}"
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape=shape,
        tpu_accelerator="tpu-v5p", multislice_set=set_name,
        multislice_index=index, multislice_set_size=set_size,
        min_resources=min_resources))
    pods = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
            for i in range(members)]
    c.create_pods(pods)
    return pods


def pool_of(c, pods):
    pools = {c.pod(p.key).meta.annotations[POOL_ANNOTATION] for p in pods}
    assert len(pools) == 1
    return pools.pop()


def test_complete_set_admits_all_slices():
    """Happy path: both slices of a size-2 set land, on distinct pools."""
    with TestCluster(profile=atomic_profile()) as c:
        add_pool(c, "p0", "zoneA/rack0")
        add_pool(c, "p1", "zoneA/rack1")
        s0 = slice_pg(c, "job", 0, set_size=2)
        s1 = slice_pg(c, "job", 1, set_size=2)
        keys = [p.key for p in s0 + s1]
        assert c.wait_for_pods_scheduled(keys, timeout=30)
        assert pool_of(c, s0) != pool_of(c, s1)


def test_incomplete_set_binds_nothing():
    """With only 1 of 2 slices submitted, no pod may bind — the set barrier
    holds the first slice at Permit even though its own gang has quorum."""
    with TestCluster(profile=atomic_profile(set_wait_s=30)) as c:
        add_pool(c, "p0", "zoneA/rack0")
        add_pool(c, "p1", "zoneA/rack1")
        s0 = slice_pg(c, "solo", 0, set_size=2)
        assert c.wait_for_pods_unscheduled([p.key for p in s0], hold=2.0)


def test_infeasible_member_releases_sibling_reservations():
    """The flagship stranding case: a 4-slice set on a 3-pool fleet. Slice 3
    can never fit; slices 0-2 must release their reserved pools (PostFilter
    set teardown) so an unrelated gang can use the chips."""
    with TestCluster(profile=atomic_profile(set_wait_s=20,
                                            denied_set_s=60)) as c:
        for i in range(3):
            add_pool(c, f"pool-{i}", f"zoneA/rack{i}")
        all_pods = []
        for idx in range(4):
            all_pods += slice_pg(c, "big", idx, set_size=4)
        # teardown is event-driven (slice-3 failure), well before the 20s
        # set timeout: every reservation must be gone again
        assert wait_until(
            lambda: all(not c.pod(p.key).spec.node_name for p in all_pods),
            timeout=15), "set members still hold assignments"
        # the freed chips are genuinely usable: an unrelated whole-pool gang
        # binds while the torn-down set sits in its denied window
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "winner", min_member=16, tpu_slice_shape="4x4x4",
            tpu_accelerator="tpu-v5p"))
        w = [make_pod(f"winner-{i}", pod_group="winner", limits={TPU: 4})
             for i in range(16)]
        c.create_pods(w)
        assert c.wait_for_pods_scheduled([p.key for p in w], timeout=30)


def test_set_capacity_dryrun_denies_before_reserving():
    """When every member declares minResources, the summed-set dry-run
    rejects the whole set at PreFilter — no chips are ever reserved."""
    with TestCluster(profile=atomic_profile(denied_set_s=60)) as c:
        for i in range(2):
            add_pool(c, f"pool-{i}", f"zoneA/rack{i}")
        # 3 slices × 64 chips on a 128-chip fleet: impossible, knowable
        # from the specs alone
        all_pods = []
        for idx in range(3):
            all_pods += slice_pg(c, "toobig", idx, set_size=3,
                                 min_resources={TPU: 64})
        assert c.wait_for_pods_unscheduled([p.key for p in all_pods],
                                           hold=1.0)
        assert all(POOL_ANNOTATION not in c.pod(p.key).meta.annotations
                   for p in all_pods)


def test_hard_domain_oversized_set_denied_in_one_cycle():
    """The module-doc footgun, mitigated: under hard same-domain policy a
    set whose summed request exceeds every single DCN domain (though not
    the fleet: 4x64 chips requested, 4 domains of 64 each) is denied at
    PreFilter in one cycle — no reservations, and nowhere near the 60 s
    set timeout."""
    with TestCluster(profile=atomic_profile(hard="same-domain",
                                            set_wait_s=60,
                                            denied_set_s=60)) as c:
        for i in range(4):
            add_pool(c, f"pool-{i}", f"zoneA/rack{i}")
        all_pods = []
        for idx in range(4):
            all_pods += slice_pg(c, "wide", idx, set_size=4,
                                 min_resources={TPU: 64})
        ms = c.scheduler._fw.plugins["MultiSlice"]
        assert wait_until(lambda: "default/wide" in ms._denied_sets,
                          timeout=10), "set not denied by the dry-run"
        assert c.wait_for_pods_unscheduled([p.key for p in all_pods],
                                           hold=1.0)
        assert all(POOL_ANNOTATION not in c.pod(p.key).meta.annotations
                   for p in all_pods)


def test_torn_down_set_recovers_when_capacity_appears():
    """After a teardown, the denied-set window expires and the set admits
    once a 4th pool exists (Node add events requeue the members)."""
    with TestCluster(profile=atomic_profile(permit_wait_s=20, set_wait_s=20,
                                            denied_set_s=2)) as c:
        for i in range(3):
            add_pool(c, f"pool-{i}", f"zoneA/rack{i}")
        all_pods = []
        for idx in range(4):
            all_pods += slice_pg(c, "grow", idx, set_size=4)
        keys = [p.key for p in all_pods]
        # stranding released first
        assert wait_until(
            lambda: all(not c.pod(k).spec.node_name for k in keys),
            timeout=15)
        add_pool(c, "pool-3", "zoneA/rack3")
        assert c.wait_for_pods_scheduled(keys, timeout=60)
        pools = set()
        for idx in range(4):
            pools.add(pool_of(c, all_pods[idx * 16:(idx + 1) * 16]))
        assert len(pools) == 4


def test_hard_same_zone_gates_rather_than_prefers():
    """hard_domain_policy=same-zone: once slice 0 lands in zoneA, a zoneB
    pool is Unschedulable for slice 1 (soft mode would degrade to it)."""
    with TestCluster(profile=atomic_profile(hard="same-zone",
                                            set_wait_s=30)) as c:
        add_pool(c, "a0", "zoneA/rack0")
        s0 = slice_pg(c, "pinned", 0, set_size=1)  # size-1: no barrier
        assert c.wait_for_pods_scheduled([p.key for p in s0], timeout=20)
        assert pool_of(c, s0) == "a0"
        add_pool(c, "b0", "zoneB/rack0")
        s1 = slice_pg(c, "pinned", 1, set_size=1)
        assert c.wait_for_pods_unscheduled([p.key for p in s1], hold=2.0)
        # a same-zone pool appears: slice 1 lands there and only there
        add_pool(c, "a1", "zoneA/rack1")
        assert c.wait_for_pods_scheduled([p.key for p in s1], timeout=30)
        assert pool_of(c, s1) == "a1"


def test_hard_same_domain_allows_same_domain():
    """Positive control for same-domain mode: a second pool in the anchor
    domain admits the second slice."""
    with TestCluster(profile=atomic_profile(hard="same-domain")) as c:
        add_pool(c, "a0", "zoneA/rack0")
        s0 = slice_pg(c, "dom", 0, set_size=1)
        assert c.wait_for_pods_scheduled([p.key for p in s0], timeout=20)
        add_pool(c, "a1", "zoneA/rack0")   # same domain, different pool
        add_pool(c, "b0", "zoneA/rack9")   # same zone, wrong domain
        s1 = slice_pg(c, "dom", 1, set_size=1)
        assert c.wait_for_pods_scheduled([p.key for p in s1], timeout=20)
        assert pool_of(c, s1) == "a1"


def test_permit_fails_fast_when_set_denied_mid_cycle():
    """A pod whose cycle was already past PreFilter when its set was denied
    is invisible to the denial's reject sweep (it is not parked yet). Its
    Permit must fail the cycle — releasing the reservation now — rather
    than park at the barrier for the full set timeout with nothing left to
    reject it."""
    from tpusched.fwk import CycleState

    with TestCluster(profile=atomic_profile(set_wait_s=60)) as c:
        add_pool(c, "p0", "zoneA/rack0")
        add_pool(c, "p1", "zoneA/rack1")
        s0 = slice_pg(c, "job", 0, set_size=2)   # incomplete set: no barrier
        assert wait_until(lambda: c.pod(s0[0].key) is not None, timeout=10)
        ms = c.scheduler._fw.plugins["MultiSlice"]
        ms._deny_set("default/job", "default", "job",
                     "test: simulated denial while a cycle was in flight")
        status, timeout_s = ms.permit(CycleState(), c.pod(s0[0].key),
                                      "p0-000000")
        assert not status.is_wait(), (
            "permit parked a pod of a denied set — the reject sweep "
            "already ran and would never resolve it")
        assert status.is_unschedulable()
        assert status.retry_after_s is not None


def test_on_pod_waiting_rejects_when_denial_raced_the_park():
    """The other half of the park-after-sweep race: the denial lands AFTER
    permit()'s denied-check but before (or while) the framework registers
    the pod. The post-registration hook must resolve the parked pod
    immediately instead of leaving it at the barrier for the set
    timeout."""
    from tpusched.fwk.runtime import _WaitingPod

    with TestCluster(profile=atomic_profile(set_wait_s=60)) as c:
        add_pool(c, "p0", "zoneA/rack0")
        s0 = slice_pg(c, "job", 0, set_size=2)
        assert wait_until(lambda: c.pod(s0[0].key) is not None, timeout=10)
        ms = c.scheduler._fw.plugins["MultiSlice"]
        wp = _WaitingPod(c.pod(s0[0].key), {ms.NAME: 60.0})
        # denial arrives while the pod is being parked (post permit-check)
        ms._deny_set("default/job", "default", "job",
                     "test: denial racing the park")
        ms.on_pod_waiting(wp)
        st = wp.wait()        # resolved by the hook, not the 60s deadline
        assert st is not None and st.is_unschedulable()
        assert "parked at the barrier" in st.message()
