"""Replay-smoke (ISSUE 9, `make replay-smoke`, a tier1 prerequisite):
record a tiny storm through the fleet trace capture, replay it twice into
identical configs, and gate on the determinism contract:

- zero placement diff + identical bind counts between the two replays
  (byte-identical placement sequences — `cmd.trace diff` exits 0);
- a deliberately perturbed scoring policy produces a NONZERO, attributed
  diff (non-vacuity: the gate can actually fail);
- capture overhead ≤3%, min-of-N A/B with the direct-attribution
  fallback this box's noise floor requires (doc/performance.md — the
  trace/prof-smoke precedent).
"""
from __future__ import annotations

import dataclasses
import json
import time

import pytest

from tpusched import obs
from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.obs.fleetrace import load_trace
from tpusched.sim.replay import diff_placements, recorded_reality, run_replay
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool)

# the smoke workload: feasibly provisioned (demand comfortably under
# capacity at every instant) so every unit binds promptly — determinism
# is exact in this regime; saturated workloads additionally race the
# wall-clock policy windows (permit timeouts, denial cascades) that
# lockstep cannot virtualize (see doc/performance.md)
UNITS = 36
IN_FLIGHT_CAP = 40          # pods


def record_smoke_storm(out_dir: str, seed: int = 7,
                       capture: bool = True,
                       quota_teams: tuple = (),
                       profile=None,
                       goodput_reports: bool = False) -> dict:
    """Record (or, capture=False, just run — the overhead-gate A/B arm)
    a tiny mixed storm with capacity recycling and a full drain.  Returns
    run stats including the wall time of the submission+drain window.

    ``quota_teams``: namespaces to spread units across, each with a
    generous-min ElasticQuota (the intra-min regime — the ISSUE 14 quota
    shards=1-vs-N equivalence gate's workload; pass a full_stack profile
    so CapacityScheduling actually runs)."""
    import random
    rng = random.Random(seed)
    rec = obs.default_fleetrecorder()
    stats = {"submitted": 0}
    if profile is None:
        profile = tpu_gang_profile(permit_wait_s=30, denied_s=1)
    with TestCluster(profile=profile) as c:
        for i in range(2):
            topo, nodes = make_tpu_pool(f"pool-{i}", dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        from tpusched.testing import make_elastic_quota
        for team in quota_teams:
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 512}, max={TPU: 1024}))
        if capture:
            rec.attach(c.api, out_dir)
        try:
            t0 = time.perf_counter()
            live, seq, in_flight = [], 0, 0

            def reap() -> int:
                done, kept = 0, []
                for pg, keys in live:
                    pods = [c.pod(k) for k in keys]
                    if all(p is not None and p.spec.node_name
                           for p in pods):
                        if goodput_reports and pg is not None:
                            # one step-report batch per bound gang before
                            # teardown: the trace then carries the
                            # goodput-report events matrix_from_trace
                            # joins, so `cmd.trace evaluate` prices
                            # placements through a non-empty matrix
                            c.pump_gang_progress(
                                pg, {k: 0.1 for k in keys}, steps=2,
                                tokens_per_step=400.0)
                        for k in keys:
                            c.api.delete(srv.PODS, k)
                        if pg is not None:
                            c.api.delete(srv.POD_GROUPS, pg)
                        done += len(keys)
                    else:
                        kept.append((pg, keys))
                live[:] = kept
                return done

            while seq < UNITS:
                if in_flight >= IN_FLIGHT_CAP:
                    in_flight -= reap()
                    time.sleep(0.005)
                    continue
                gang = rng.random() < 0.4
                name = f"smoke-{seq:03d}"
                ns = quota_teams[seq % len(quota_teams)] if quota_teams \
                    else "default"
                seq += 1
                if gang:
                    c.api.create(srv.POD_GROUPS, make_pod_group(
                        name, namespace=ns, min_member=4,
                        tpu_slice_shape="2x2x4",
                        tpu_accelerator="tpu-v5p"))
                    pods = [make_pod(f"{name}-{j}", namespace=ns,
                                     pod_group=name,
                                     limits={TPU: 4},
                                     requests=make_resources(
                                         cpu=1, memory="1Gi"))
                            for j in range(4)]
                    live.append((f"{ns}/{name}", [p.key for p in pods]))
                else:
                    pods = [make_pod(f"{name}-0", namespace=ns,
                                     limits={TPU: 1},
                                     requests=make_resources(
                                         cpu=1, memory="1Gi"))]
                    live.append((None, [p.key for p in pods]))
                c.create_pods(pods)
                in_flight += len(pods)
                stats["submitted"] += len(pods)
                # pace arrivals: an unpaced submit loop makes the run a
                # pure enqueue microbenchmark over a ~0.1 s wall, and the
                # overhead gate's percent-of-wall attribution turns
                # degenerate (3% of nothing).  8 ms/unit keeps the window
                # arrival-shaped (~0.4 s) like the storms it stands in for.
                time.sleep(0.008)
            deadline = time.monotonic() + 60
            while live and time.monotonic() < deadline:
                reap()
                time.sleep(0.005)
            assert not live, f"smoke storm wedged: {live}"
            stats["wall_s"] = time.perf_counter() - t0
        finally:
            if capture:
                rec.flush()
                stats["capture"] = rec.status()   # before detach: the
                rec.detach()                      # writer stats live there
    return stats


@pytest.fixture(scope="module")
def smoke_trace(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("fleettrace"))
    record_smoke_storm(d)
    return d


@pytest.fixture(scope="module")
def two_replays(smoke_trace):
    r1 = run_replay(smoke_trace)
    r2 = run_replay(smoke_trace)
    return r1, r2


# -- the determinism gate -----------------------------------------------------


def test_replaying_twice_yields_byte_identical_placements(two_replays,
                                                          smoke_trace):
    r1, r2 = two_replays
    assert r1.binds > 0
    assert r1.unbound == [] and r2.unbound == []
    # byte-identical placement SEQUENCES, not just equal sets
    assert json.dumps(r1.placements) == json.dumps(r2.placements)
    assert r1.binds == r2.binds
    diff = diff_placements(r1.to_dict(), r2.to_dict())
    assert diff["identical"] is True
    assert diff["moved"] == 0 and not diff["only_in_a"] \
        and not diff["only_in_b"]
    # the replay covered the recorded workload: every recorded arrival
    # bound in the replay too (feasible regime)
    trace = load_trace(smoke_trace)
    assert r1.binds == len({p for p, _ in trace.recorded_binds()})
    assert r1.workload_fingerprint == \
        trace.summary()["workload_fingerprint"]


def test_perturbed_scoring_policy_produces_attributed_diff(two_replays,
                                                           smoke_trace):
    """Non-vacuity: the zero-diff gate must be able to fail.  Replaying
    under a profile with different Score weights must move placements,
    and the diff must attribute each move (pod → node A vs node B)."""
    r1, _ = two_replays
    prof = tpu_gang_profile(permit_wait_s=30, denied_s=1)
    prof = dataclasses.replace(prof, score=[("TpuSlice", 1)])
    r3 = run_replay(smoke_trace, profile=prof)
    diff = diff_placements(r1.to_dict(), r3.to_dict())
    assert not diff["identical"]
    assert diff["moved"] > 0
    for row in diff["placement_diff"]:
        assert row["pod"] and row["a"] != row["b"]


def test_sharded_lockstep_replay_matches_single_lane(two_replays,
                                                     smoke_trace):
    """ISSUE 11 (`make replay-smoke` sharding gate): replay the recorded
    storm through the SHARDED dispatch core in lockstep and diff against
    the shards=1 replay.  The contract: the same pod set binds, bind
    counts match, the sharded replay is itself deterministic, and every
    placement move is ATTRIBUTED to the sharding policy — the pod landed
    inside its routed shard's pool partition (partition argmax ≠ fleet
    argmax, by design) or its unit was escalated to the global lane.
    Zero unattributed differences: anything the partitioning rule cannot
    explain is a real divergence (lost update, stale epoch) and fails."""
    from tpusched.api.topology import LABEL_POOL
    from tpusched.sched.shards import attribute_placement_diff
    from tpusched.sim.replay import _decode

    r1, _ = two_replays
    rs = run_replay(smoke_trace, dispatch_shards=4)
    assert rs.dispatch_shards == 4
    assert rs.unbound == [], "sharded replay left pods unbound"
    assert rs.binds == r1.binds

    # sharded lockstep replay is deterministic in its own right
    rs2 = run_replay(smoke_trace, dispatch_shards=4)
    assert json.dumps(rs.placements) == json.dumps(rs2.placements)

    trace = load_trace(smoke_trace)
    pool_of = {n.meta.name: n.meta.labels.get(LABEL_POOL, "")
               for n in trace.objects.get(srv.NODES, ())}
    gang_of = {}
    pinned_of = {}
    from tpusched.api.scheduling import pod_group_full_name
    for ev in trace.events:
        if ev.get("kind") == "pod-arrival":
            obj = _decode(ev)
            if obj is not None:
                gang_of[obj.meta.key] = pod_group_full_name(obj) or None
                pinned_of[obj.meta.key] = \
                    (obj.spec.node_selector or {}).get(LABEL_POOL)
    assert rs.escalations_truncated is False
    diff = diff_placements(r1.to_dict(), rs.to_dict())
    attributed = attribute_placement_diff(
        diff, shards=4,
        pool_of_node=lambda n: pool_of.get(n, ""),
        gang_of=lambda p: gang_of.get(p),
        escalated_units=rs.escalated_units,
        pinned_pool_of=lambda p: pinned_of.get(p),
        escalated_truncated=rs.escalations_truncated)
    assert attributed["unattributed_count"] == 0, (
        f"unattributed placement differences: "
        f"{attributed['unattributed']} / only_in: "
        f"{attributed['only_in_a']} {attributed['only_in_b']}")
    # every move carries its attribution verdict for the diff report
    for row in attributed["placement_diff"]:
        assert row["attributed"] in ("shard-partition", "escalated-global")
        assert row["routed_shard"].startswith("s")


@pytest.fixture(scope="module")
def quota_trace(tmp_path_factory):
    from tpusched.config.profiles import full_stack_profile
    d = str(tmp_path_factory.mktemp("quotatrace"))
    record_smoke_storm(d, seed=11, quota_teams=("team-a", "team-b"),
                       profile=full_stack_profile(permit_wait_s=30,
                                                  denied_s=1))
    return d


def test_quota_sharded_lockstep_replay_matches_single_lane(quota_trace):
    """ISSUE 14 (`make replay-smoke` quota gate): the quota-aware
    optimistic commit protocol must be placement-equivalent to the
    serialized single lane.  Replay a storm whose units all live in
    ElasticQuota namespaces (intra-min regime — the traffic the pre-14
    router serialized WHOLESALE through the global lane) at shards=1 and
    shards=4 in lockstep: same pod set binds, bind counts match, the
    sharded replay is deterministic, and every placement move is
    attributed to the partition/escalation policy — zero UNATTRIBUTED
    differences.  An unattributed move here is exactly a quota-epoch
    protocol bug (a commit landed against a superseded admission
    verdict)."""
    from tpusched.api.scheduling import pod_group_full_name
    from tpusched.api.topology import LABEL_POOL
    from tpusched.config.profiles import full_stack_profile
    from tpusched.sched.shards import attribute_placement_diff
    from tpusched.sim.replay import _decode

    def prof():
        return full_stack_profile(permit_wait_s=30, denied_s=1)

    r1 = run_replay(quota_trace, profile=prof())
    assert r1.binds > 0 and r1.unbound == []
    rs = run_replay(quota_trace, profile=prof(), dispatch_shards=4)
    assert rs.dispatch_shards == 4
    assert rs.unbound == [], "quota sharded replay left pods unbound"
    assert rs.binds == r1.binds
    rs2 = run_replay(quota_trace, profile=prof(), dispatch_shards=4)
    assert json.dumps(rs.placements) == json.dumps(rs2.placements), (
        "quota sharded lockstep replay is nondeterministic")

    trace = load_trace(quota_trace)
    pool_of = {n.meta.name: n.meta.labels.get(LABEL_POOL, "")
               for n in trace.objects.get(srv.NODES, ())}
    gang_of = {}
    pinned_of = {}
    for ev in trace.events:
        if ev.get("kind") == "pod-arrival":
            obj = _decode(ev)
            if obj is not None:
                gang_of[obj.meta.key] = pod_group_full_name(obj) or None
                pinned_of[obj.meta.key] = \
                    (obj.spec.node_selector or {}).get(LABEL_POOL)
    assert rs.escalations_truncated is False
    diff = diff_placements(r1.to_dict(), rs.to_dict())
    attributed = attribute_placement_diff(
        diff, shards=4,
        pool_of_node=lambda n: pool_of.get(n, ""),
        gang_of=lambda p: gang_of.get(p),
        escalated_units=rs.escalated_units,
        pinned_pool_of=lambda p: pinned_of.get(p),
        escalated_truncated=rs.escalations_truncated)
    assert attributed["unattributed_count"] == 0, (
        f"unattributed placement differences under quota sharding: "
        f"{attributed['unattributed']} / only_in: "
        f"{attributed['only_in_a']} {attributed['only_in_b']}")


def test_window_index_lockstep_replay_matches_python_path(two_replays,
                                                          smoke_trace,
                                                          monkeypatch):
    """ISSUE 13 (`make replay-smoke` native-index gate): replay the
    recorded storm (a) with the window index serving AND the in-cycle
    differential oracle re-checking EVERY served sweep, and (b) with the
    index disabled (the pure Python full-recompute path).  The contract:
    zero placement diffs between the arms, zero differential mismatches,
    and non-vacuity (the index actually served sweeps in arm a)."""
    from tpusched.topology.windowindex import TorusWindowIndex
    from tpusched.util.metrics import torus_index_differential_mismatches
    r1, _ = two_replays                       # index on, no differential
    served = {"n": 0}
    orig_query = TorusWindowIndex.query

    def spy(self, *a, **k):
        q = orig_query(self, *a, **k)
        if q is not None:
            served["n"] += 1
        return q

    monkeypatch.setattr(TorusWindowIndex, "query", spy)
    monkeypatch.setenv("TPUSCHED_INDEX_DIFFERENTIAL", "1")
    mism0 = torus_index_differential_mismatches.value()
    r_diff = run_replay(smoke_trace)
    assert served["n"] > 0, (
        "the index never served a sweep — the lockstep gate is vacuous")
    assert torus_index_differential_mismatches.value() == mism0, (
        "the in-cycle oracle caught an index/full-path feasible-set "
        "divergence during replay")
    monkeypatch.delenv("TPUSCHED_INDEX_DIFFERENTIAL")
    monkeypatch.setenv("TPUSCHED_NO_WINDOW_INDEX", "1")
    r_py = run_replay(smoke_trace)
    for arm, rep in (("differential", r_diff), ("no-index", r_py)):
        diff = diff_placements(r1.to_dict(), rep.to_dict())
        assert diff["identical"] is True, (arm, diff)
        assert rep.binds == r1.binds, arm


def test_diff_vs_recorded_reality_is_structured(two_replays, smoke_trace):
    r1, _ = two_replays
    real = recorded_reality(load_trace(smoke_trace))
    assert real["binds"] == r1.binds
    diff = diff_placements(r1.to_dict(), real)
    # same pods placed on both sides (nodes may differ: the replay runs
    # serial determinism overrides, reality ran parallel sweeps)
    assert not diff["only_in_a"] and not diff["only_in_b"]
    assert diff["binds_a"] == diff["binds_b"]


def test_replay_report_carries_differential_surfaces(two_replays):
    r1, _ = two_replays
    # per-pool utilization curve sampled over the stream (ISSUE 15: each
    # sample also stamps the replay-clock instant and — with topologies
    # present — the fragmentation trajectory row)
    assert r1.pool_utilization
    assert all({"event", "pools", "t"} <= set(s)
               and set(s) <= {"event", "pools", "t", "frag"}
               for s in r1.pool_utilization)
    final = r1.pool_utilization[-1]["pools"]
    assert all(isinstance(v, int) for v in final.values())
    # SLO attainment vs the profile objective
    assert r1.pod_e2e["events"] == r1.binds
    assert 0.0 <= r1.pod_e2e["attainment"] <= 1.0
    assert r1.pod_e2e["objective_s"] > 0


def test_compacted_trace_counts_snapshot_seeded_pods_on_both_sides(tmp_path):
    """Compaction discards a pod's arrival event but keeps it (pending) in
    the seeding snapshot, while its bind-commit stays in the stream.  The
    replay schedules those pods too, and BOTH report shapes must count
    them — otherwise every compacted trace diffs as only-in-recorded."""
    from tpusched.apiserver import APIServer
    from tpusched.obs.fleetrace import FleetTraceRecorder
    from tpusched.testing import make_tpu_node

    api = APIServer()
    for i in range(3):
        api.create(srv.NODES, make_tpu_node(f"n{i}", chips=4))
    # two pods arrive BEFORE capture: the attach snapshot is the only
    # record of them — exactly what WAL compaction leaves behind
    pre = [make_pod(f"pre-{i}", limits={TPU: 4}) for i in range(2)]
    for p in pre:
        api.create(srv.PODS, p)
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path))
    rec.flush()        # snapshot lands on the writer thread: barrier it
    # BEFORE the binds below, so it carries the pods pending — the
    # compaction shape under test
    post = make_pod("post-0", limits={TPU: 4})
    api.create(srv.PODS, post)
    # the recorded scheduler binds all three (post first: the stream need
    # not match the replay's own arrival ordering)
    for key, node in ((post.key, "n2"), (pre[0].key, "n0"),
                      (pre[1].key, "n1")):
        pod = api.get(srv.PODS, key)
        pod.spec.node_name = node
        api.update(srv.PODS, pod)
    rec.flush()
    rec.detach()

    trace = load_trace(str(tmp_path))
    assert len(trace.arrivals()) == 1           # pre-* arrivals compacted
    assert len(trace.recorded_binds()) == 3

    real = recorded_reality(trace)
    assert real["binds"] == 3 and real["unbound"] == []
    rep = run_replay(str(tmp_path))
    assert rep.binds == 3 and rep.unbound == []
    diff = diff_placements(rep.to_dict(), real)
    assert not diff["only_in_a"] and not diff["only_in_b"]
    assert diff["binds_a"] == diff["binds_b"] == 3


# -- the CLI contract ---------------------------------------------------------


def test_cmd_trace_inspect_replay_diff_round_trip(two_replays, smoke_trace,
                                                  tmp_path, capsys):
    from tpusched.cmd import trace as trace_cmd
    r1, r2 = two_replays
    f1, f2 = str(tmp_path / "r1.json"), str(tmp_path / "r2.json")
    for path, rep in ((f1, r1), (f2, r2)):
        with open(path, "w", encoding="utf-8") as f:
            json.dump(rep.to_dict(), f)

    assert trace_cmd.main(["inspect", smoke_trace, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["binds"] > 0 and summary["arrivals"] > 0

    # identical replays: diff exits 0
    assert trace_cmd.main(["diff", f1, f2, "--json"]) == 0
    diff = json.loads(capsys.readouterr().out)
    assert diff["identical"] is True

    # CLI replay produces a report usable by diff, and --fail-on-diff
    # agrees with the recorded reality check
    f3 = str(tmp_path / "r3.json")
    rc = trace_cmd.main(["replay", smoke_trace, "--report", f3])
    assert rc == 0
    capsys.readouterr()
    assert trace_cmd.main(["diff", f1, f3]) == 0    # deterministic again
    capsys.readouterr()

    # a perturbed report: diff exits 1 (the gate can fail)
    perturbed = r1.to_dict()
    perturbed["placements"] = [[p, n + "-moved"]
                               for p, n in perturbed["placements"]]
    f4 = str(tmp_path / "r4.json")
    with open(f4, "w", encoding="utf-8") as f:
        json.dump(perturbed, f)
    assert trace_cmd.main(["diff", f1, f4]) == 1
    capsys.readouterr()

    assert trace_cmd.main(["inspect", str(tmp_path / "nope")]) == 2
    capsys.readouterr()


# -- the overhead gate --------------------------------------------------------


def test_capture_overhead_gated_at_3_percent(tmp_path):
    """Capture-on vs capture-off on the smoke storm, min-of-N; when the
    box cannot resolve 3% by A/B (the usual case here — see
    doc/performance.md), fall back to DIRECT ATTRIBUTION: calibrated
    per-event enqueue cost × events actually captured, over the captured
    run's wall time.  The enqueue is the only work capture adds to the
    watch fan-out — encoding and disk I/O ride the dedicated writer
    thread."""
    on_walls, off_walls, captures = [], [], []
    for i in range(2):
        off_walls.append(record_smoke_storm("", seed=11 + i,
                                            capture=False)["wall_s"])
        s = record_smoke_storm(str(tmp_path / f"t{i}"), seed=11 + i)
        on_walls.append(s["wall_s"])
        captures.append(s["capture"])
    ab = min(on_walls) / min(off_walls)
    if ab <= 1.03:
        return                      # A/B resolved it: within budget
    # direct attribution: calibrate the per-event hot-path cost on an
    # armed recorder, charge it to every event the noisier run captured
    from tpusched.apiserver import APIServer
    from tpusched.obs.fleetrace import FleetTraceRecorder
    api = APIServer()
    rec = FleetTraceRecorder()
    rec.attach(api, str(tmp_path / "calib"))
    pod = make_pod("calib-0", limits={TPU: 1})
    # min over batches: one ambient-load spike during a single long
    # calibration loop would inflate per_event and fail the gate for
    # reasons that have nothing to do with the capture (doc/performance.md
    # noise methodology)
    n = 7_000
    batch_costs = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            rec._enqueue("pod-arrival", obj=pod, objkind=srv.PODS,
                         payload={"pod": pod.key, "gang": ""})
        batch_costs.append((time.perf_counter() - t0) / n)
    per_event = min(batch_costs)
    rec.detach()
    events = max(c["events_written"] + c["dropped"] for c in captures)
    attributed = events * per_event / min(on_walls)
    assert attributed <= 0.03, (
        f"capture overhead: A/B ratio {ab:.3f} and direct attribution "
        f"{attributed:.4f} ({events} events × {per_event * 1e6:.1f}µs "
        f"over {min(on_walls):.2f}s) both above the 3% budget")
