"""Pipeline parallelism (GPipe schedule over the pp mesh axis).

Correctness bar: the pipelined loss must EQUAL the plain single-program
loss_fn on identical params — the schedule (microbatching, bubble masking,
ppermute hand-offs) must be pure plumbing with no numerical effect.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusched.jaxbridge import compat
from tpusched.jaxbridge.mesh import build_named_mesh
from tpusched.jaxbridge.pipeline import (init_pipeline_params,
                                         make_pipeline_train_step,
                                         pipeline_param_shardings,
                                         stack_layers)
from tpusched.jaxbridge.workload import (ModelConfig, init_params, loss_fn,
                                         sgd_train_step)

# The pipeline schedule runs shard_map manual ONLY over pp (dp/tp stay
# automatic) and transposes a replicated-scalar loss — both constructs
# the legacy experimental shard_map cannot express (partial-auto
# axis_index lowers to a PartitionId instruction XLA SPMD rejects, and
# its spec prover fails the replicated-grad transpose).  The compat shim
# (jaxbridge/compat.py) keeps everything importable; the schedule tests
# skip cleanly on legacy-only builds instead of erroring.
needs_modern_shard_map = pytest.mark.skipif(
    not compat.have_modern_shard_map(),
    reason="pipeline schedule needs jax.shard_map (partial-auto manual "
           "axes + replicated-grad transpose unsupported on the legacy "
           "experimental API)")


def tiny(**kw):
    base = dict(vocab=128, d_model=32, n_layers=4, n_heads=2, d_ff=64,
                seq=16)
    base.update(kw)
    return ModelConfig(**base)


@needs_modern_shard_map
@pytest.mark.parametrize("pp,n_micro", [(2, 2), (4, 4), (2, 4)])
def test_pipeline_loss_matches_plain_loss(pp, n_micro):
    cfg = tiny()
    mesh = build_named_mesh({"pp": pp, "dp": 8 // pp})
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, cfg.seq), 0,
                                cfg.vocab, dtype=jnp.int32)
    want = float(loss_fn(params, tokens, cfg))

    step, shardings, tshard = make_pipeline_train_step(mesh, cfg, n_micro)
    pipe_params = jax.device_put(
        (stack_layers(params), params["embed"], params["out"],
         params["ln_f"]), shardings)
    _, got = step(pipe_params, jax.device_put(tokens, tshard))
    np.testing.assert_allclose(float(got), want, rtol=1e-5)


@needs_modern_shard_map
def test_pipeline_training_decreases_loss():
    cfg = tiny()
    mesh = build_named_mesh({"pp": 2, "dp": 2})
    step, shardings, tshard = make_pipeline_train_step(mesh, cfg, n_micro=2,
                                                       lr=1e-1)
    params = jax.device_put(
        init_pipeline_params(jax.random.PRNGKey(2), cfg), shardings)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(3), (4, cfg.seq), 0,
                           cfg.vocab, dtype=jnp.int32), tshard)
    losses = []
    for _ in range(6):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_pipeline_grads_match_plain_grads():
    """End-to-end gradient parity: one pipelined SGD step must move the
    stacked weights exactly where the plain step moves the per-layer
    weights (reverse-mode AD through scan+ppermute IS the backward
    schedule)."""
    cfg = tiny(n_layers=2)
    mesh = build_named_mesh({"pp": 2})
    params = init_params(jax.random.PRNGKey(4), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (4, cfg.seq), 0,
                                cfg.vocab, dtype=jnp.int32)
    plain_new, _ = jax.jit(
        lambda p, t: sgd_train_step(p, t, cfg, lr=1e-2))(params, tokens)

    step, shardings, tshard = make_pipeline_train_step(mesh, cfg, n_micro=2,
                                                       lr=1e-2)
    pipe_params = jax.device_put(
        (stack_layers(params), params["embed"], params["out"],
         params["ln_f"]), shardings)
    (stacked_new, embed_new, out_new, lnf_new), _ = step(
        pipe_params, jax.device_put(tokens, tshard))

    want_stacked = stack_layers(plain_new)
    for k in want_stacked:
        np.testing.assert_allclose(np.asarray(stacked_new[k]),
                                   np.asarray(want_stacked[k]),
                                   atol=2e-5, rtol=2e-4, err_msg=k)
    np.testing.assert_allclose(np.asarray(embed_new),
                               np.asarray(plain_new["embed"]),
                               atol=2e-5, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(out_new),
                               np.asarray(plain_new["out"]),
                               atol=2e-5, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(lnf_new),
                               np.asarray(plain_new["ln_f"]),
                               atol=2e-5, rtol=2e-4)


@needs_modern_shard_map
def test_pipeline_moe_composes():
    """pp x ep: an MoE model pipelined over 2 stages with experts sharded
    over ep inside each stage."""
    cfg = tiny(n_experts=4, moe_top_k=2)
    mesh = build_named_mesh({"pp": 2, "ep": 2, "tp": 2})
    step, shardings, tshard = make_pipeline_train_step(mesh, cfg, n_micro=2)
    params = jax.device_put(
        init_pipeline_params(jax.random.PRNGKey(6), cfg), shardings)
    stacked = params[0]
    assert stacked["w_gate"].sharding.spec == jax.sharding.PartitionSpec(
        "pp", "ep", None, "tp")
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(7), (4, cfg.seq), 0,
                           cfg.vocab, dtype=jnp.int32), tshard)
    params, loss = step(params, tokens)
    assert np.isfinite(float(loss))


def test_pipeline_rejects_indivisible_layers():
    cfg = tiny(n_layers=3)
    mesh = build_named_mesh({"pp": 2})
    with pytest.raises(ValueError, match="stages"):
        make_pipeline_train_step(mesh, cfg, n_micro=2)
