"""Flight recorder unit tier: span trees, ring/byte budgets, anomaly
pinning, /debug endpoints (empty + under concurrent writes), Perfetto
export validation, and trace-id correlation (klog + Events +
/debug/threads)."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpusched import trace
from tpusched.trace.span import CycleTrace, build_span_tree
from tpusched.util import tracectx
from tpusched.util.httpserve import MetricsServer


class _Meta:
    def __init__(self, i, gang=None):
        from tpusched.api.scheduling import POD_GROUP_LABEL
        self.labels = {POD_GROUP_LABEL: gang} if gang else {}
        self.namespace = "default"
        self.uid = f"uid-{i}"


class _Pod:
    def __init__(self, i, gang=None):
        self.meta = _Meta(i, gang)
        self.key = f"default/p-{i}"


class _Info:
    attempts = 1
    timestamp = 0.0
    initial_attempt_timestamp = 0.0


def _mk_trace(rec, i, gang=None, n_events=6, outcome="bound",
              anomaly=None):
    tr = rec.begin_cycle(_Pod(i, gang), _Info(), time.time())
    for j in range(n_events):
        t0 = time.perf_counter()
        tr.add_event(f"Point{j}", t0, 0.0001)
    if anomaly:
        tr.add_anomaly(anomaly, detail="x")
    tr.finish(outcome, node="n1" if outcome == "bound" else "")
    return tr


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# -- span tree ----------------------------------------------------------------

def test_span_tree_reconstruction_nesting():
    """End-ordered complete events rebuild the parent/child structure:
    children started at-or-after the parent and ended before it."""
    events = [
        ("child-a", 0.001, 0.002, None),      # inside parent
        ("child-b", 0.004, 0.001, None),      # inside parent
        ("parent", 0.001, 0.005, None),
        ("root2", 0.010, 0.002, {"k": "v"}),
    ]
    roots = build_span_tree(events)
    assert [r.name for r in roots] == ["parent", "root2"]
    assert [c.name for c in roots[0].children] == ["child-a", "child-b"]
    assert roots[1].attrs == {"k": "v"}
    assert roots[1].children is None


def test_cycle_trace_to_dict_and_extension_points():
    rec = trace.FlightRecorder()
    tr = rec.begin_cycle(_Pod(1, gang="g"), _Info(), time.time())
    t0 = time.perf_counter()
    tr.add_event("TpuSlice", t0, 0.001)       # child of Filter
    tr.add_event("Filter", t0, 0.003)
    tr.add_event("Score", time.perf_counter(), 0.002)
    tr.finish("bound", node="n1")
    d = tr.to_dict()
    assert d["outcome"] == "bound" and d["node"] == "n1"
    assert d["gang"] == "default/g"
    assert [s["name"] for s in d["spans"]] == ["Filter", "Score"]
    assert d["spans"][0]["children"][0]["name"] == "TpuSlice"
    pts = tr.extension_point_s()
    assert pytest.approx(pts["Filter"], abs=1e-9) == 0.003
    assert pytest.approx(pts["Score"], abs=1e-9) == 0.002
    assert "TpuSlice" not in pts              # child, not a root


def test_trace_truncation_bound():
    rec = trace.FlightRecorder()
    tr = rec.begin_cycle(_Pod(1), _Info(), time.time())
    for i in range(trace.MAX_SPANS_PER_TRACE + 50):
        tr.add_event("e", time.perf_counter(), 0.0)
    assert len(tr._events) == trace.MAX_SPANS_PER_TRACE
    assert tr.truncated == 50
    assert tr.to_dict()["truncated_spans"] == 50


# -- ring / byte budgets ------------------------------------------------------

def test_ring_bounds_hold_under_10k_cycle_soak():
    """The flight-recorder acceptance soak: 10k committed cycles, the ring
    never exceeds its entry or byte budget (checked continuously), eviction
    is counted, and the gang book stays within its LRU cap."""
    rec = trace.FlightRecorder(max_entries=128, max_bytes=256 * 1024,
                               max_pinned=16)
    for i in range(10_000):
        tr = _mk_trace(rec, i, gang=f"gang-{i % 100}",
                       outcome="bound" if i % 3 else "unschedulable")
        rec.commit(tr, final=True)
        if i % 997 == 0 or i > 9_900:
            s = rec.stats()
            assert s["entries"] <= 128, s
            assert s["approx_bytes"] <= 256 * 1024, s
    s = rec.stats()
    assert s["committed_total"] == 10_000
    assert s["evicted_total"] >= 10_000 - 128
    assert s["gangs"] <= 64                   # GangBook LRU cap
    # the cycles view serves only retained traces
    assert len(rec.cycles()) == s["entries"]


def test_byte_budget_evicts_before_entry_budget():
    """A few fat traces must trip the byte budget even when far below the
    entry budget."""
    rec = trace.FlightRecorder(max_entries=10_000, max_bytes=64 * 1024)
    for i in range(200):
        tr = _mk_trace(rec, i, n_events=200)  # ~72B/event estimate
        rec.commit(tr, final=True)
    s = rec.stats()
    assert s["approx_bytes"] <= 64 * 1024
    assert s["entries"] < 200


def test_finalize_recharges_bytes_for_late_spans():
    rec = trace.FlightRecorder()
    tr = _mk_trace(rec, 1, n_events=2, outcome="waiting-permit")
    rec.commit(tr)
    before = rec.stats()["approx_bytes"]
    for _ in range(40):                       # binding-side growth
        tr.add_event("Bind", time.perf_counter(), 0.001)
    tr.finish("bound", node="n1")
    rec.finalize(tr)
    assert rec.stats()["approx_bytes"] > before


def test_anomaly_pinning_bounded_and_fifo():
    rec = trace.FlightRecorder(max_entries=8, max_pinned=4)
    pinned_ids = []
    for i in range(10):
        tr = _mk_trace(rec, i, outcome="unschedulable",
                       anomaly="gang_denied")
        rec.commit(tr, final=True)            # fused path pins anomalies
        pinned_ids.append(tr.trace_id)
    pins = rec.pinned_dump()
    assert len(pins) == 4                     # bounded
    assert [p["trace_id"] for p in pins] == pinned_ids[-4:]  # FIFO evict
    assert all(p["anomalies"][0]["kind"] == "gang_denied" for p in pins)
    # pinning the same trace twice must not duplicate it
    tr = _mk_trace(rec, 99, anomaly="bind_failed")
    rec.pin(tr)
    rec.pin(tr)
    assert sum(1 for p in rec.pinned_dump()
               if p["trace_id"] == tr.trace_id) == 1


# -- /debug endpoints ---------------------------------------------------------

def test_debug_endpoints_valid_json_on_empty_recorder():
    rec = trace.FlightRecorder()
    server = MetricsServer(port=0, recorder=rec).start()
    try:
        for path in ("/debug/trace", "/debug/gangs", "/debug/flightrecorder",
                     "/debug/trace?format=perfetto"):
            status, body = _get(server.port, path)
            assert status == 200, path
            doc = json.loads(body)            # valid JSON even when empty
            assert isinstance(doc, dict)
        status, body = _get(server.port, "/debug/flightrecorder")
        doc = json.loads(body)
        assert doc["stats"]["entries"] == 0
        assert doc["cycles"] == [] and doc["pinned"] == []
        assert doc["gangs"] == []
    finally:
        server.stop()


def test_debug_threads_route_dumps_all_threads():
    """Satellite: util.httpserve._thread_dump is reachable at
    /debug/threads so a hung Permit barrier is diagnosable in place."""
    hang = threading.Event()
    t = threading.Thread(target=hang.wait, name="fake-permit-barrier",
                         daemon=True)
    t.start()
    server = MetricsServer(port=0).start()
    try:
        status, body = _get(server.port, "/debug/threads")
        assert status == 200
        assert "MainThread" in body
        assert "fake-permit-barrier" in body  # the wedged thread is visible
        assert "daemon=" in body
    finally:
        hang.set()
        server.stop()


def test_debug_endpoints_under_concurrent_writes():
    """Readers must see valid JSON while cycles are being committed,
    finalized and pinned from multiple writer threads."""
    rec = trace.FlightRecorder(max_entries=64, max_bytes=128 * 1024)
    server = MetricsServer(port=0, recorder=rec).start()
    stop = threading.Event()
    errors = []

    def writer(widx):
        i = 0
        while not stop.is_set():
            tr = _mk_trace(rec, f"{widx}-{i}", gang=f"g{widx}",
                           outcome="bound" if i % 2 else "unschedulable",
                           anomaly="bind_failed" if i % 7 == 0 else None)
            rec.commit(tr, final=True)
            i += 1

    threads = [threading.Thread(target=writer, args=(w,), daemon=True)
               for w in range(3)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 2.0
        reads = 0
        while time.monotonic() < deadline:
            for path in ("/debug/trace?n=10", "/debug/gangs",
                         "/debug/flightrecorder",
                         "/debug/trace?format=perfetto"):
                status, body = _get(server.port, path)
                if status != 200:
                    errors.append((path, status))
                    continue
                try:
                    json.loads(body)
                except ValueError as e:
                    errors.append((path, str(e)))
                reads += 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        server.stop()
    assert not errors
    assert reads > 8
    s = rec.stats()
    assert s["entries"] <= 64 and s["approx_bytes"] <= 128 * 1024


def test_debug_trace_filters():
    rec = trace.FlightRecorder()
    for i in range(20):
        rec.commit(_mk_trace(rec, i), final=True)
    server = MetricsServer(port=0, recorder=rec).start()
    try:
        _, body = _get(server.port, "/debug/trace?n=5")
        assert len(json.loads(body)["cycles"]) == 5
        _, body = _get(server.port, "/debug/trace?pod=p-7")
        cycles = json.loads(body)["cycles"]
        assert len(cycles) == 1 and cycles[0]["pod"] == "default/p-7"
        _, body = _get(server.port, "/debug/trace?n=0")
        assert json.loads(body)["cycles"] == []
        # the perfetto form honors the same filters
        _, body = _get(server.port, "/debug/trace?pod=p-7&format=perfetto")
        doc = json.loads(body)
        lanes = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M"}
        assert lanes == {"default/p-7"}
    finally:
        server.stop()


# -- Perfetto export ----------------------------------------------------------

def test_perfetto_export_validates_and_carries_lanes():
    rec = trace.FlightRecorder()
    for i in range(3):
        rec.commit(_mk_trace(rec, i, gang="g"), final=True)
    doc = trace.export.to_perfetto(rec.traces(), rec.pinned_traces())
    assert trace.export.validate_trace_events(doc) == []
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert names == {f"default/p-{i}" for i in range(3)}
    cycles = [e for e in doc["traceEvents"]
              if e["ph"] == "X" and e["name"].startswith("cycle:")]
    assert len(cycles) == 3
    assert json.loads(json.dumps(doc))        # serializable


def test_perfetto_validator_rejects_malformed():
    bad = {"traceEvents": [
        {"name": "x", "ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": -5},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 1},
        {"name": "y", "ph": "??", "pid": 1, "tid": 1},
    ]}
    problems = trace.export.validate_trace_events(bad)
    assert len(problems) == 3
    assert trace.export.validate_trace_events([]) \
        == ["document is not a JSON object"]


def test_span_tree_validator_flags_disorder():
    rec = trace.FlightRecorder()
    tr = _mk_trace(rec, 1)
    assert trace.export.validate_span_tree(tr) == []
    # hand-corrupt the event log: an event ending before its predecessor
    tr._events.append(("late", -1.0, 0.0, None))
    assert any("not end-ordered" in p
               for p in trace.export.validate_span_tree(tr))
    # a trace with no outcome is malformed
    tr2 = rec.begin_cycle(_Pod(2), _Info(), time.time())
    assert any("no outcome" in p
               for p in trace.export.validate_span_tree(tr2))


# -- correlation (klog + Events) ----------------------------------------------

def test_klog_lines_carry_active_trace_id(caplog):
    import logging

    from tpusched.util import klog
    rec = trace.FlightRecorder()
    tr = rec.begin_cycle(_Pod(1), _Info(), time.time())
    with caplog.at_level(logging.INFO, logger="tpusched"):
        token = trace.activate(tr)
        try:
            klog.info_s("inside cycle", pod="default/p-1")
        finally:
            trace.deactivate(token)
        klog.info_s("outside cycle")
    lines = [r.getMessage() for r in caplog.records]
    inside = [l for l in lines if '"inside cycle"' in l]
    assert inside and f'trace="{tr.trace_id}"' in inside[0]
    outside = [l for l in lines if '"outside cycle"' in l]
    assert outside and "trace=" not in outside[0]


def test_record_event_carries_active_trace_id():
    from tpusched.apiserver import APIServer, Clientset
    api = APIServer()
    cs = Clientset(api)
    rec = trace.FlightRecorder()
    tr = rec.begin_cycle(_Pod(1), _Info(), time.time())
    token = trace.activate(tr)
    try:
        cs.record_event("default/p-1", "Pod", "Warning",
                        "FailedScheduling", "0/3 nodes are available")
    finally:
        trace.deactivate(token)
    cs.record_event("default/p-1", "Pod", "Normal", "Scheduled", "plain")
    evs = api.events()
    assert f"[trace={tr.trace_id}]" in evs[0].message
    assert "0/3 nodes are available" in evs[0].message
    assert "trace=" not in evs[1].message


def test_activate_nests_and_restores():
    rec = trace.FlightRecorder()
    t1 = rec.begin_cycle(_Pod(1), _Info(), time.time())
    t2 = rec.begin_cycle(_Pod(2), _Info(), time.time())
    assert trace.current() is None
    tok1 = trace.activate(t1)
    assert trace.current() is t1 and tracectx.get() == t1.trace_id
    tok2 = trace.activate(t2)
    assert trace.current() is t2 and tracectx.get() == t2.trace_id
    trace.deactivate(tok2)
    assert trace.current() is t1 and tracectx.get() == t1.trace_id
    trace.deactivate(tok1)
    assert trace.current() is None and tracectx.get() == ""


def test_helpers_are_noops_without_active_trace():
    # must not raise, must not create state
    trace.annotate("k", "v")
    trace.record_rejection("P", "why", detail=1)
    trace.record_anomaly("kind")
    with trace.span("nothing"):
        pass
