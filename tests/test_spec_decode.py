"""Speculative decoding (jaxbridge/spec_decode.py). The load-bearing
contract: greedy speculation is EXACT — whatever the draft proposes, the
emitted tokens equal the target model's own greedy decode. A bad draft can
only cost speed, never correctness; a good draft shrinks the number of
target weight streams toward steps/(k+1)."""
import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpusched.jaxbridge.decode import generate  # noqa: E402
from tpusched.jaxbridge.spec_decode import (score_span,  # noqa: E402
                                            speculative_generate)
from tpusched.jaxbridge.workload import ModelConfig, init_params  # noqa: E402

TARGET = ModelConfig.tiny()
DRAFT = dataclasses.replace(TARGET, n_layers=1, d_model=32, n_heads=2,
                            d_ff=64)


def _models(seed_t=0, seed_d=100):
    tp = init_params(jax.random.PRNGKey(seed_t), TARGET)
    dp = init_params(jax.random.PRNGKey(seed_d), DRAFT)
    return tp, dp


@pytest.mark.parametrize("k", [1, 3, 4])
@pytest.mark.parametrize("steps", [1, 7, 12])
def test_speculative_matches_target_greedy(k, steps):
    """Exactness across k and generation lengths, with an UNRELATED random
    draft (worst case: most proposals rejected — every acceptance path,
    including n_ok=0 corrections, gets exercised)."""
    tp, dp = _models()
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 9), 0,
                                TARGET.vocab, dtype=jnp.int32)
    ref = np.asarray(generate(tp, prompt, TARGET, steps))
    got, stats = speculative_generate(tp, TARGET, dp, DRAFT, prompt,
                                      steps, k=k)
    np.testing.assert_array_equal(got, ref)
    assert stats["plain_calls"] == steps + 1
    # every round emits at least one token, plus the prefill
    assert stats["target_calls"] <= steps + 2


def test_perfect_draft_maximizes_acceptance():
    """Draft == target: every proposal matches, so each round accepts k
    and emits k+1 (bonus included) — target weight streams collapse to
    ceil(total/(k+1)) + prefill, the speculation bound."""
    tp, _ = _models()
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0,
                                TARGET.vocab, dtype=jnp.int32)
    steps, k = 11, 3
    ref = np.asarray(generate(tp, prompt, TARGET, steps))
    got, stats = speculative_generate(tp, TARGET, tp, TARGET, prompt,
                                      steps, k=k)
    np.testing.assert_array_equal(got, ref)
    assert stats["accept_rate"] == 1.0
    total = steps + 1
    rounds = -(-(total - 1) // (k + 1))     # prefill emits the first token
    assert stats["target_calls"] == 1 + rounds
    assert stats["target_calls"] < stats["plain_calls"]


def test_score_span_k1_equals_decode_step():
    """score_span with a length-1 span IS the decode step — one definition
    of the decode math (the file's own claim)."""
    from tpusched.jaxbridge.decode import decode_step, init_kv_cache, prefill
    tp, _ = _models()
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0,
                                TARGET.vocab, dtype=jnp.int32)
    cache = init_kv_cache(TARGET, 1, 32)
    _, cache = prefill(tp, cache, prompt, TARGET)
    tok = jnp.asarray([7], dtype=jnp.int32)
    span_logits, _ = score_span(tp, cache, tok[None, :], jnp.int32(5), TARGET)
    step_logits, _ = decode_step(tp, cache, tok, jnp.int32(5), TARGET)
    np.testing.assert_allclose(np.asarray(span_logits[0, 0]),
                               np.asarray(step_logits[0]), atol=1e-6)


def test_validation():
    tp, dp = _models()
    prompt = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError, match="single-sequence"):
        speculative_generate(tp, TARGET, dp, DRAFT, prompt, 2)
    bad_draft = dataclasses.replace(DRAFT, vocab=TARGET.vocab * 2)
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(tp, TARGET, init_params(jax.random.PRNGKey(4),
                                                     bad_draft),
                             bad_draft, jnp.zeros((1, 4), jnp.int32), 2)
    with pytest.raises(ValueError, match="k must"):
        speculative_generate(tp, TARGET, dp, DRAFT,
                             jnp.zeros((1, 4), jnp.int32), 2, k=0)


def test_speculative_with_moe_target():
    """MoE target: speculation rides the dropless decode path, keeping
    exactness (a capacity-routed target would break the span==step
    equivalence the acceptance rule relies on)."""
    moe_cfg = dataclasses.replace(TARGET, n_experts=4, moe_top_k=2)
    tp = init_params(jax.random.PRNGKey(5), moe_cfg)
    dp = init_params(jax.random.PRNGKey(6), DRAFT)
    prompt = jax.random.randint(jax.random.PRNGKey(7), (1, 8), 0,
                                moe_cfg.vocab, dtype=jnp.int32)
    steps = 6
    ref = np.asarray(generate(tp, prompt, moe_cfg, steps))
    got, _ = speculative_generate(tp, moe_cfg, dp, DRAFT, prompt, steps, k=3)
    np.testing.assert_array_equal(got, ref)
