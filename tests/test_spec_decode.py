"""Speculative decoding (jaxbridge/spec_decode.py). The load-bearing
contract: greedy speculation is EXACT — whatever the draft proposes, the
emitted tokens equal the target model's own greedy decode. A bad draft can
only cost speed, never correctness; a good draft shrinks the number of
target weight streams toward steps/(k+1)."""
import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpusched.jaxbridge.decode import generate  # noqa: E402
from tpusched.jaxbridge.spec_decode import (score_span,  # noqa: E402
                                            speculative_generate)
from tpusched.jaxbridge.workload import ModelConfig, init_params  # noqa: E402

TARGET = ModelConfig.tiny()
DRAFT = dataclasses.replace(TARGET, n_layers=1, d_model=32, n_heads=2,
                            d_ff=64)


def _models(seed_t=0, seed_d=100):
    tp = init_params(jax.random.PRNGKey(seed_t), TARGET)
    dp = init_params(jax.random.PRNGKey(seed_d), DRAFT)
    return tp, dp


@pytest.mark.parametrize("k", [1, 3, 4])
@pytest.mark.parametrize("steps", [1, 7, 12])
def test_speculative_matches_target_greedy(k, steps):
    """Exactness across k and generation lengths, with an UNRELATED random
    draft (worst case: most proposals rejected — every acceptance path,
    including n_ok=0 corrections, gets exercised)."""
    tp, dp = _models()
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 9), 0,
                                TARGET.vocab, dtype=jnp.int32)
    ref = np.asarray(generate(tp, prompt, TARGET, steps))
    got, stats = speculative_generate(tp, TARGET, dp, DRAFT, prompt,
                                      steps, k=k)
    np.testing.assert_array_equal(got, ref)
    assert stats["plain_calls"] == steps + 1
    # every round emits at least one token, plus the prefill
    assert stats["target_calls"] <= steps + 2


def test_perfect_draft_maximizes_acceptance():
    """Draft == target: every proposal matches, so each round accepts k
    and emits k+1 (bonus included) — target weight streams collapse to
    ceil(total/(k+1)) + prefill, the speculation bound."""
    tp, _ = _models()
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0,
                                TARGET.vocab, dtype=jnp.int32)
    steps, k = 11, 3
    ref = np.asarray(generate(tp, prompt, TARGET, steps))
    got, stats = speculative_generate(tp, TARGET, tp, TARGET, prompt,
                                      steps, k=k)
    np.testing.assert_array_equal(got, ref)
    assert stats["accept_rate"] == 1.0
    total = steps + 1
    rounds = -(-(total - 1) // (k + 1))     # prefill emits the first token
    assert stats["target_calls"] == 1 + rounds
    assert stats["target_calls"] < stats["plain_calls"]


def test_score_span_k1_equals_decode_step():
    """score_span with a length-1 span IS the decode step — one definition
    of the decode math (the file's own claim)."""
    from tpusched.jaxbridge.decode import decode_step, init_kv_cache, prefill
    tp, _ = _models()
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0,
                                TARGET.vocab, dtype=jnp.int32)
    cache = init_kv_cache(TARGET, 1, 32)
    _, cache = prefill(tp, cache, prompt, TARGET)
    tok = jnp.asarray([7], dtype=jnp.int32)
    span_logits, _ = score_span(tp, cache, tok[None, :], jnp.int32(5), TARGET)
    step_logits, _ = decode_step(tp, cache, tok, jnp.int32(5), TARGET)
    np.testing.assert_allclose(np.asarray(span_logits[0, 0]),
                               np.asarray(step_logits[0]), atol=1e-6)


def test_validation():
    tp, dp = _models()
    prompt = jnp.zeros((2, 4), jnp.int32)
    with pytest.raises(ValueError, match="single-sequence"):
        speculative_generate(tp, TARGET, dp, DRAFT, prompt, 2)
    bad_draft = dataclasses.replace(DRAFT, vocab=TARGET.vocab * 2)
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(tp, TARGET, init_params(jax.random.PRNGKey(4),
                                                     bad_draft),
                             bad_draft, jnp.zeros((1, 4), jnp.int32), 2)
    with pytest.raises(ValueError, match="k must"):
        speculative_generate(tp, TARGET, dp, DRAFT,
                             jnp.zeros((1, 4), jnp.int32), 2, k=0)


def test_speculative_with_moe_target():
    """MoE target: speculation rides the dropless decode path, keeping
    exactness (a capacity-routed target would break the span==step
    equivalence the acceptance rule relies on)."""
    moe_cfg = dataclasses.replace(TARGET, n_experts=4, moe_top_k=2)
    tp = init_params(jax.random.PRNGKey(5), moe_cfg)
    dp = init_params(jax.random.PRNGKey(6), DRAFT)
    prompt = jax.random.randint(jax.random.PRNGKey(7), (1, 8), 0,
                                moe_cfg.vocab, dtype=jnp.int32)
    steps = 6
    ref = np.asarray(generate(tp, prompt, moe_cfg, steps))
    got, _ = speculative_generate(tp, moe_cfg, dp, DRAFT, prompt, steps, k=3)
    np.testing.assert_array_equal(got, ref)


# -- distribution-preserving speculative SAMPLING -----------------------------

def test_residual_identity_makes_sampling_exact():
    """The algorithm's correctness is an algebraic identity, verified
    numerically against the shipped residual_distribution: for ANY draft
    p and target q, P(emit y) = p(y)·min(1, q(y)/p(y)) +
    P(reject)·residual(y) must equal q(y) exactly."""
    from tpusched.jaxbridge.spec_decode import residual_distribution
    rng = np.random.default_rng(3)
    for trial in range(20):
        v = int(rng.integers(4, 64))
        p = rng.dirichlet(np.full(v, 0.3))
        q = rng.dirichlet(np.full(v, 0.5))
        accept = np.minimum(1.0, q / np.maximum(p, 1e-300))
        reject_mass = 1.0 - float(np.sum(p * accept))
        emit = p * accept + reject_mass * residual_distribution(p, q)
        np.testing.assert_allclose(emit, q, atol=1e-12)
    # degenerate: q == p ⇒ rejection impossible; the guard returns q
    q = rng.dirichlet(np.full(16, 1.0))
    np.testing.assert_allclose(residual_distribution(q, q), q, atol=1e-12)


@pytest.mark.parametrize("k", [1, 3, 5])
def test_speculative_sample_self_draft_is_position_keyed_sampling(k):
    """The deterministic stand-in for a statistical test: with a PERFECT
    draft (draft == target) every proposal is accepted and the emitted
    stream equals decode.sample_position_keyed token-for-token — the
    canonical position-keyed sampler the key discipline is defined by."""
    from tpusched.jaxbridge.decode import sample_position_keyed
    from tpusched.jaxbridge.spec_decode import speculative_sample
    cfg = ModelConfig.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 7), 0,
                                cfg.vocab, dtype=jnp.int32)
    key = jax.random.PRNGKey(42)
    steps = 18
    ref = np.asarray(sample_position_keyed(params, prompt, cfg, steps,
                                           key, temperature=0.8,
                                           top_k=32))
    got, stats = speculative_sample(params, cfg, params, cfg, prompt,
                                    steps, key, k=k, temperature=0.8,
                                    top_k=32)
    np.testing.assert_array_equal(got, ref)
    assert stats["accept_rate"] == 1.0
    assert stats["target_calls"] < stats["plain_calls"]


def test_speculative_sample_with_weak_draft():
    """A real (different, smaller) draft: deterministic for a fixed key,
    token-range bounded, sensitive to the key, and the telemetry is
    coherent (acceptance strictly between the trivial bounds for a
    random-weights draft)."""
    from tpusched.jaxbridge.spec_decode import speculative_sample
    cfg = ModelConfig.tiny()
    dcfg = dataclasses.replace(cfg, n_layers=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(9), dcfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 5), 0,
                                cfg.vocab, dtype=jnp.int32)
    a, sa = speculative_sample(params, cfg, dparams, dcfg, prompt, 15,
                               jax.random.PRNGKey(7), k=3,
                               temperature=0.9)
    b, _ = speculative_sample(params, cfg, dparams, dcfg, prompt, 15,
                              jax.random.PRNGKey(7), k=3,
                              temperature=0.9)
    np.testing.assert_array_equal(a, b)          # same key ⇒ same stream
    c, _ = speculative_sample(params, cfg, dparams, dcfg, prompt, 15,
                              jax.random.PRNGKey(8), k=3,
                              temperature=0.9)
    assert not np.array_equal(a, c)              # the key matters
    assert a.shape == (1, 16)
    assert ((a >= 0) & (a < cfg.vocab)).all()
    assert 0 <= sa["accept_rate"] <= 1.0
    assert sa["drafted"] >= sa["accepted"]


def test_speculative_sample_validation():
    from tpusched.jaxbridge.spec_decode import speculative_sample
    cfg = ModelConfig.tiny()
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.zeros((1, 4), dtype=jnp.int32)
    with pytest.raises(ValueError, match="temperature"):
        speculative_sample(params, cfg, params, cfg, prompt, 4,
                           jax.random.PRNGKey(0), temperature=0.0)
    with pytest.raises(ValueError, match="single-sequence"):
        speculative_sample(params, cfg, params, cfg,
                           jnp.zeros((2, 4), dtype=jnp.int32), 4,
                           jax.random.PRNGKey(0))
