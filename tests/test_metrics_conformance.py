"""Prometheus text-exposition conformance + the labeled metric families.

A parser-based round trip: everything ``Registry.expose()`` emits must
parse back under the exposition-format grammar — sample names, escaped
label values (backslash/newline/quote), escaped HELP text, exactly one
``# TYPE`` per family emitted before its first sample, cumulative
histogram buckets.  Plus units for the PR 5 satellite work:
CounterVec/GaugeVec semantics and scrape-time collectors.
"""
from __future__ import annotations

import re

import pytest

from tpusched.util.metrics import (REGISTRY, CounterVec, GaugeVec, Registry,
                                   escape_label_value, format_labels)

_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[0-9eE+.naif-]+)$")


def parse_label_pairs(raw: str):
    """Parse `k="v",...` honoring \\\\ \\" \\n escapes; raises on garbage."""
    out = {}
    i = 0
    while i < len(raw):
        eq = raw.index("=", i)
        key = raw[i:eq]
        assert _NAME.match(key), f"bad label name {key!r}"
        assert raw[eq + 1] == '"', raw
        j = eq + 2
        val = []
        while raw[j] != '"':
            if raw[j] == "\\":
                nxt = raw[j + 1]
                assert nxt in ('\\', '"', 'n'), f"bad escape \\{nxt}"
                val.append({"\\": "\\", '"': '"', "n": "\n"}[nxt])
                j += 2
            else:
                val.append(raw[j])
                j += 1
        out[key] = "".join(val)
        i = j + 1
        if i < len(raw):
            assert raw[i] == ",", raw
            i += 1
    return out


def parse_exposition(text: str):
    """Validating parser: returns (types, helps, samples).  Asserts the
    grammar invariants a real Prometheus scraper enforces."""
    types, helps = {}, {}
    samples = []
    current_family = None
    sampled_families = set()
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert _NAME.match(name), name
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            assert _NAME.match(name), name
            assert mtype in ("counter", "gauge", "histogram", "summary",
                             "untyped"), mtype
            assert name not in types, f"duplicate TYPE for {name}"
            assert name not in sampled_families, \
                f"TYPE for {name} after its samples"
            types[name] = mtype
            current_family = name
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = _SAMPLE.match(line)
        assert m, f"unparseable sample line {line!r}"
        name = m.group("name")
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        family = base if base in types else name
        assert family == current_family, \
            f"sample {name} outside its family block ({current_family})"
        sampled_families.add(family)
        labels = parse_label_pairs(m.group("labels")) \
            if m.group("labels") else {}
        samples.append((name, labels, float(m.group("value"))))
    return types, helps, samples


def test_registry_exposition_round_trips():
    """The full global registry (every metric the scheduler ever
    registered in this process) parses clean."""
    types, helps, samples = parse_exposition(REGISTRY.expose())
    assert "tpusched_podgroup_to_bound_duration_seconds" in types
    assert types["tpusched_podgroup_to_bound_duration_seconds"] == "histogram"
    assert types["tpusched_bind_total"] == "counter"
    assert samples


def test_label_value_escaping_round_trips():
    reg = Registry()
    hostile = 'a"b\\c\nd'
    vec = reg.gauge_vec("tpusched_esc_test_info", ("who",), "esc \\ test\n2")
    vec.with_labels(hostile).set(7)
    types, helps, samples = parse_exposition(reg.expose())
    assert helps["tpusched_esc_test_info"] == "esc \\\\ test\\n2"
    (name, labels, value), = samples
    assert name == "tpusched_esc_test_info"
    assert labels == {"who": hostile}          # the round trip
    assert value == 7.0


def test_histogram_vec_label_escaping_and_bucket_monotonicity():
    reg = Registry()
    vec = reg.histogram_vec("tpusched_h_test_seconds", ("op",), "h")
    vec.with_labels('x"y').observe(0.003)
    vec.with_labels('x"y').observe(2.0)
    types, _, samples = parse_exposition(reg.expose())
    assert types["tpusched_h_test_seconds"] == "histogram"
    buckets = [(labels, v) for name, labels, v in samples
               if name.endswith("_bucket")]
    assert all(labels["op"] == 'x"y' for labels, _ in buckets)
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)            # cumulative
    count = [v for name, labels, v in samples if name.endswith("_count")]
    assert count == [2.0]
    # +Inf bucket equals _count
    inf = [v for labels, v in buckets if labels["le"] == "+Inf"]
    assert inf == [2.0]


def test_counter_vec_children_and_total():
    reg = Registry()
    vec = reg.counter_vec("tpusched_cv_test_total", ("verb",), "cv")
    assert isinstance(vec, CounterVec)
    vec.with_labels("bind").inc()
    vec.with_labels("bind").inc()
    vec.with_labels("patch").inc(3)
    assert vec.value() == 5.0                  # family total
    assert vec.children()[("bind",)].value() == 2.0
    with pytest.raises(ValueError):
        vec.with_labels("a", "b")
    _, _, samples = parse_exposition(reg.expose())
    assert (("tpusched_cv_test_total", {"verb": "patch"}, 3.0)) in samples
    # stable child ordering: bind before patch
    verbs = [labels["verb"] for _, labels, _ in samples]
    assert verbs == sorted(verbs)


def test_gauge_vec_remove_and_clear():
    reg = Registry()
    vec = reg.gauge_vec("tpusched_gv_test_chips", ("pool",), "gv")
    assert isinstance(vec, GaugeVec)
    vec.with_labels("a").set(1)
    vec.with_labels("b").set(2)
    vec.remove("a")
    assert set(vec.children()) == {("b",)}
    vec.clear()
    assert vec.children() == {}
    # an empty family emits no orphan HELP/TYPE header
    assert "tpusched_gv_test_chips" not in reg.expose()


def test_collectors_run_at_scrape_and_never_break_expose():
    reg = Registry()
    vec = reg.gauge_vec("tpusched_coll_test_chips", ("pool",), "c")
    calls = [0]

    def collect():
        calls[0] += 1
        vec.with_labels("p0").set(calls[0])

    def broken():
        raise RuntimeError("collector bug")
    reg.register_collector(collect)
    reg.register_collector(broken)
    _, _, samples = parse_exposition(reg.expose())
    assert (("tpusched_coll_test_chips", {"pool": "p0"}, 1.0)) in samples
    reg.expose()
    assert calls[0] == 2
    reg.unregister_collector(collect)
    reg.expose()
    assert calls[0] == 2


def test_gauge_func_series_share_one_family_header():
    reg = Registry()
    reg.gauge_func("tpusched_gf_test_depth", lambda: 1, "gf",
                   labels='queue="active"')
    reg.gauge_func("tpusched_gf_test_depth", lambda: 2, "gf",
                   labels='queue="backoff"')
    types, _, samples = parse_exposition(reg.expose())
    assert types["tpusched_gf_test_depth"] == "gauge"
    assert len([s for s in samples
                if s[0] == "tpusched_gf_test_depth"]) == 2


def test_migrated_counters_carry_labels():
    """The PR 5 migration: api retries by verb, flight-recorder anomalies
    by kind — labeled children, with the family total still readable via
    .value() (the pre-migration call-site contract)."""
    from tpusched import trace
    from tpusched.util.metrics import (api_retries,
                                       flight_recorder_anomalies)
    assert isinstance(api_retries, CounterVec)
    before_total = flight_recorder_anomalies.value()
    before_kind = flight_recorder_anomalies.with_labels(
        "conformance_test_kind").value()
    rec = trace.FlightRecorder()
    tr = trace.CycleTrace("t1", "default/p", "u1", None, 0, "s",
                          0.0, 0.0, 0.0)
    tr.add_anomaly("conformance_test_kind", detail="x")
    rec.pin(tr)
    assert flight_recorder_anomalies.with_labels(
        "conformance_test_kind").value() == before_kind + 1
    assert flight_recorder_anomalies.value() == before_total + 1
    text = REGISTRY.expose()
    assert ('tpusched_flight_recorder_anomalies_total'
            '{kind="conformance_test_kind"}') in text


def test_escape_helpers():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    assert format_labels(("k",), ('v"',)) == 'k="v\\""'


def test_issue9_fleetrace_families_round_trip_exposition():
    """The ISSUE 9 families (fleet-trace capture counters) parse clean
    through the validating round trip: events by kind, the drop counter
    and the byte counter, under the naming conventions the metrics-names
    rule pins."""
    from tpusched.util.metrics import (fleetrace_bytes_total,
                                       fleetrace_dropped_total,
                                       fleetrace_events_total)
    fleetrace_events_total.with_labels("pod-arrival").inc()
    fleetrace_events_total.with_labels("bind-commit").inc(2)
    fleetrace_dropped_total.inc(0)
    fleetrace_bytes_total.inc(128)
    types, helps, samples = parse_exposition(REGISTRY.expose())
    assert types["tpusched_fleetrace_events_total"] == "counter"
    assert types["tpusched_fleetrace_dropped_total"] == "counter"
    assert types["tpusched_fleetrace_bytes_written_total"] == "counter"
    kinds = {labels.get("kind"): v for name, labels, v in samples
             if name == "tpusched_fleetrace_events_total"}
    assert kinds["pod-arrival"] >= 1
    assert kinds["bind-commit"] >= 2
    assert any(name == "tpusched_fleetrace_bytes_written_total" and v >= 128
               for name, labels, v in samples)


def test_issue7_families_round_trip_exposition():
    """The ISSUE 7 families (lock contention histograms, throughput
    counters, profiler sample counter, arrival/backlog gauges) parse clean
    through the same validating round trip, with the naming conventions
    the metrics-names lint rule pins."""
    from tpusched.util.metrics import (binds_total, lock_hold_seconds,
                                       lock_wait_seconds,
                                       profiler_samples_total,
                                       scheduling_cycles_total)
    lock_wait_seconds.with_labels("conformance.Lock").observe(0.0004)
    lock_hold_seconds.with_labels("conformance.Lock").observe(0.002)
    binds_total.with_labels("conformance-sched", "").inc()
    scheduling_cycles_total.with_labels("conformance-sched", "").inc(2)
    profiler_samples_total.inc(0)
    types, helps, samples = parse_exposition(REGISTRY.expose())
    assert types["tpusched_lock_wait_seconds"] == "histogram"
    assert types["tpusched_lock_hold_seconds"] == "histogram"
    assert types["tpusched_binds_total"] == "counter"
    assert types["tpusched_scheduling_cycles_total"] == "counter"
    assert types["tpusched_profiler_samples_total"] == "counter"
    # the µs-scale buckets actually resolve a 0.4 ms wait: some bucket
    # below the default 1 ms floor already counts it
    sub_ms = [v for name, labels, v in samples
              if name == "tpusched_lock_wait_seconds_bucket"
              and labels.get("lock") == "conformance.Lock"
              and labels["le"] not in ("+Inf",)
              and float(labels["le"]) < 0.001]
    assert sub_ms and max(sub_ms) >= 1.0
    assert (("tpusched_binds_total",
             {"scheduler": "conformance-sched", "shard": ""},
             1.0)) in samples


def test_issue11_shard_families_round_trip_exposition():
    """The ISSUE 11 sharded-dispatch families: throughput counters carry
    the new ``shard`` label ('' on the single loop, s<N>/global per
    lane), queue-wait is a shard-labeled histogram family, and the
    conflict/escalation counters expose per-lane children — all through
    the validating exposition round trip."""
    from tpusched.util.metrics import (binds_total, queue_wait_seconds,
                                       scheduling_cycles_total,
                                       shard_conflicts_total,
                                       shard_escalations_total)
    binds_total.with_labels("conformance-shard", "s0").inc(3)
    binds_total.with_labels("conformance-shard", "global").inc()
    scheduling_cycles_total.with_labels("conformance-shard", "s0").inc(4)
    queue_wait_seconds.with_labels("s0").observe(0.01)
    queue_wait_seconds.with_labels("global").observe(0.02)
    shard_conflicts_total.with_labels("s0").inc()
    shard_escalations_total.with_labels("s0").inc(2)
    types, helps, samples = parse_exposition(REGISTRY.expose())
    assert types["tpusched_shard_conflicts_total"] == "counter"
    assert types["tpusched_shard_escalations_total"] == "counter"
    assert types["tpusched_scheduling_queue_wait_duration_seconds"] \
        == "histogram"
    assert (("tpusched_binds_total",
             {"scheduler": "conformance-shard", "shard": "s0"}, 3.0)
            in samples)
    assert (("tpusched_binds_total",
             {"scheduler": "conformance-shard", "shard": "global"}, 1.0)
            in samples)
    assert (("tpusched_shard_escalations_total", {"shard": "s0"}, 2.0)
            in samples)
    # per-shard queue-wait children expose their own bucket series
    shard_buckets = {labels.get("shard")
                     for name, labels, v in samples
                     if name == "tpusched_scheduling_queue_wait_"
                                "duration_seconds_bucket"}
    assert {"s0", "global"} <= shard_buckets
    # family totals still stand in for the pre-sharding unlabeled counters
    assert binds_total.value() >= 4.0


def test_issue10_goodput_families_round_trip_exposition():
    """The ISSUE 10 families (gang runtime goodput gauges, straggler
    counter/gauges, the workload×generation matrix gauge and the report
    accounting counters) parse clean through the validating round trip,
    and children removed on gang teardown vanish from the exposition —
    cardinality must track LIVE gangs only."""
    from tpusched.util.metrics import (gang_goodput_per_chip,
                                       gang_goodput_units, gang_step_skew,
                                       gang_straggler_events,
                                       gang_stragglers,
                                       goodput_reports_dropped,
                                       goodput_reports_shed,
                                       goodput_reports_total,
                                       workload_goodput_per_chip)
    gang = 'conformance/gang-"q"'       # exercises label escaping too
    gang_goodput_units.with_labels(gang, "tokens").set(4000.0)
    gang_goodput_per_chip.with_labels(gang, "tokens").set(250.0)
    gang_step_skew.with_labels(gang).set(1.5)
    gang_stragglers.with_labels(gang).set(1)
    gang_straggler_events.with_labels(gang).inc()
    workload_goodput_per_chip.with_labels("2x2x4/4chip", "tpu-v5p").set(250.0)
    workload_goodput_per_chip.with_labels("2x2x4/4chip", "tpu-v6e").set(510.0)
    goodput_reports_total.inc(3)
    goodput_reports_shed.inc(0)
    goodput_reports_dropped.inc(0)
    try:
        types, helps, samples = parse_exposition(REGISTRY.expose())
        assert types["tpusched_gang_goodput_units_per_second"] == "gauge"
        assert types["tpusched_gang_goodput_per_chip"] == "gauge"
        assert types["tpusched_gang_goodput_step_skew"] == "gauge"
        assert types["tpusched_gang_stragglers"] == "gauge"
        assert types["tpusched_gang_straggler_events_total"] == "counter"
        assert types["tpusched_workload_goodput_per_chip"] == "gauge"
        assert types["tpusched_goodput_reports_total"] == "counter"
        assert types["tpusched_goodput_reports_shed_total"] == "counter"
        assert types["tpusched_goodput_reports_dropped_total"] == "counter"
        assert ("tpusched_gang_goodput_units_per_second",
                {"gang": gang, "unit": "tokens"}, 4000.0) in samples
        cells = {labels["generation"]: v for name, labels, v in samples
                 if name == "tpusched_workload_goodput_per_chip"
                 and labels.get("workload") == "2x2x4/4chip"}
        assert cells == {"tpu-v5p": 250.0, "tpu-v6e": 510.0}
        # teardown removes the gang's children from the exposition
        gang_goodput_units.remove(gang, "tokens")
        gang_goodput_per_chip.remove(gang, "tokens")
        gang_step_skew.remove(gang)
        gang_stragglers.remove(gang)
        gang_straggler_events.remove(gang)
        _, _, samples2 = parse_exposition(REGISTRY.expose())
        assert not any(labels.get("gang") == gang
                       for _, labels, _ in samples2)
    finally:
        workload_goodput_per_chip.remove("2x2x4/4chip", "tpu-v5p")
        workload_goodput_per_chip.remove("2x2x4/4chip", "tpu-v6e")


def test_issue16_native_dispatch_and_fanout_families_round_trip():
    """The ISSUE 16 families: native batched-dispatch cycle/pod counters,
    the per-reason fallback vec, the differential-mismatch counter, and
    the bind fan-out batch/event counters + flush-latency histogram — all
    through the validating exposition round trip."""
    from tpusched.util.metrics import (
        fanout_batches_total, fanout_events_total, fanout_flush_seconds,
        native_dispatch_cycles_total,
        native_dispatch_differential_mismatches,
        native_dispatch_fallbacks, native_dispatch_pods_total)
    native_dispatch_cycles_total.inc(3)
    native_dispatch_pods_total.inc(2)
    native_dispatch_fallbacks.with_labels("no-native").inc()
    native_dispatch_fallbacks.with_labels("pod-shape").inc(2)
    native_dispatch_differential_mismatches.inc(0)
    fanout_batches_total.inc()
    fanout_events_total.inc(5)
    fanout_flush_seconds.observe(0.0009)
    types, helps, samples = parse_exposition(REGISTRY.expose())
    assert types["tpusched_native_dispatch_cycles_total"] == "counter"
    assert types["tpusched_native_dispatch_pods_total"] == "counter"
    assert types["tpusched_native_dispatch_fallbacks_total"] == "counter"
    assert types["tpusched_native_dispatch_differential_mismatches_total"] \
        == "counter"
    assert types["tpusched_fanout_batches_total"] == "counter"
    assert types["tpusched_fanout_events_total"] == "counter"
    assert types["tpusched_fanout_flush_seconds"] == "histogram"
    reasons = {labels.get("reason"): v for name, labels, v in samples
               if name == "tpusched_native_dispatch_fallbacks_total"}
    assert reasons.get("no-native", 0) >= 1
    assert reasons.get("pod-shape", 0) >= 2
    # the sub-ms flush actually lands in a sub-ms bucket
    sub_ms = [v for name, labels, v in samples
              if name == "tpusched_fanout_flush_seconds_bucket"
              and labels["le"] not in ("+Inf",)
              and float(labels["le"]) < 0.002]
    assert sub_ms and max(sub_ms) >= 1.0


def test_issue20_incident_plane_families_round_trip():
    """The ISSUE 20 families: timeline sample/overflow counters, the
    per-detector sentinel firing vec, and the incident bundle
    written/dropped counters — through the validating exposition round
    trip, alongside the native-dispatch families the health.native
    surface re-exposes."""
    from tpusched.util.metrics import (
        incident_bundles_dropped_total, incident_bundles_written_total,
        native_dispatch_cycles_total, sentinel_firings_total,
        timeline_overflow_total, timeline_samples_total)
    timeline_samples_total.inc(4)
    timeline_overflow_total.inc(2)
    sentinel_firings_total.with_labels("bind_rate_collapse").inc()
    sentinel_firings_total.with_labels("slo_burn_spike").inc(2)
    incident_bundles_written_total.inc()
    incident_bundles_dropped_total.inc(0)
    native_dispatch_cycles_total.inc(0)
    types, helps, samples = parse_exposition(REGISTRY.expose())
    assert types["tpusched_timeline_samples_total"] == "counter"
    assert types["tpusched_timeline_overflow_total"] == "counter"
    assert types["tpusched_sentinel_firings_total"] == "counter"
    assert types["tpusched_incident_bundles_written_total"] == "counter"
    assert types["tpusched_incident_bundles_dropped_total"] == "counter"
    assert types["tpusched_native_dispatch_cycles_total"] == "counter"
    for name in ("tpusched_timeline_samples_total",
                 "tpusched_sentinel_firings_total",
                 "tpusched_incident_bundles_written_total"):
        assert helps.get(name, "").strip(), f"{name}: empty HELP"
    by_detector = {labels.get("detector"): v for name, labels, v
                   in samples if name == "tpusched_sentinel_firings_total"}
    assert by_detector.get("bind_rate_collapse", 0) >= 1
    assert by_detector.get("slo_burn_spike", 0) >= 2
    totals = {name: v for name, labels, v in samples if not labels}
    assert totals.get("tpusched_timeline_samples_total", 0) >= 4
    assert totals.get("tpusched_timeline_overflow_total", 0) >= 2
    assert totals.get("tpusched_incident_bundles_written_total", 0) >= 1
