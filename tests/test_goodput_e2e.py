"""E2E tier for the gang runtime goodput plane (ISSUE 10):

- a synthetic gang with one injected slow member is detected, pinned as a
  ``gang_straggler`` flight-recorder anomaly, and fully attributable
  (gang, member, skew magnitude) from ``/debug/goodput`` +
  ``/debug/explain`` output ALONE; tearing the straggler down clears the
  detection (the hysteresis exit);
- the workload×generation throughput matrix built from injected
  step-times orders generations per the injection, survives a
  snapshot/reload round trip, and is consumable by ``sim/whatif.py``;
- fleetrace captures goodput reports as ``goodput-report`` events and
  ``matrix_from_trace`` rebuilds the matrix offline from the trace alone;
- the ``/debug/`` index enumerates every mounted debug endpoint;
- ``cmd.explain`` renders the RUNNING-phase gang view.
"""
from __future__ import annotations

import json
import urllib.request

import pytest

from tpusched import obs, trace
from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import server as srv
from tpusched.testing.cluster import TestCluster, wait_until
from tpusched.testing.wrappers import make_pod, make_pod_group, make_tpu_pool
from tpusched.util.httpserve import DEBUG_ENDPOINTS, MetricsServer


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _get_json(port: int, path: str):
    status, body = _get(port, path)
    return status, json.loads(body)


@pytest.fixture
def fresh_obs():
    """Fresh process-global goodput aggregator + flight recorder, restored
    afterwards so neighboring tests see their own surfaces."""
    prev_rec = trace.default_recorder()
    trace.install_recorder(trace.FlightRecorder())
    agg = obs.install_goodput(obs.GoodputAggregator())
    yield agg
    obs.install_goodput(obs.GoodputAggregator())
    trace.install_recorder(prev_rec)


def _bind_gang(c: TestCluster, name: str, members: int = 4,
               shape: str = "2x2x4", chips: int = 4):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape=shape,
        tpu_accelerator="tpu-v5p"))
    pods = [make_pod(f"{name}-{i:03d}", pod_group=name, limits={TPU: chips},
                     requests=make_resources(cpu=1, memory="1Gi"))
            for i in range(members)]
    c.create_pods(pods)
    keys = [p.key for p in pods]
    assert c.wait_for_pods_scheduled(keys, timeout=20), "gang did not bind"
    return keys


def test_straggler_fully_attributable_from_debug_alone(fresh_obs):
    """The acceptance e2e: slow member → detected + pinned + attributable
    from /debug/goodput + /debug/explain alone; teardown clears it."""
    agg = fresh_obs
    with TestCluster() as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        keys = _bind_gang(c, "slowgang")
        gang = "default/slowgang"
        slow = keys[0]
        # one member runs 5x slow — six synthetic step reports per member
        # (what the jaxbridge reporters would emit from real hardware)
        c.pump_gang_progress(gang,
                             {k: (0.5 if k == slow else 0.1) for k in keys},
                             steps=6, tokens_per_step=100.0)
        server = MetricsServer(port=0).start()
        try:
            # -- /debug/goodput: the full dump names gang, member, skew
            status, dump = _get_json(server.port, "/debug/goodput")
            assert status == 200
            assert dump["stats"]["attached"] is True
            [g] = [g for g in dump["gangs"] if g["gang"] == gang]
            [s] = g["stragglers"]
            assert s["pod"] == slow
            assert s["skew"] >= 4.0            # injected 5x, rolling p99
            assert s["node"]                   # placed node named
            assert g["step_skew"] >= 4.0
            member_rows = {m["pod"]: m for m in g["members"]}
            assert member_rows[slow]["straggler"] is True
            assert member_rows[keys[1]]["straggler"] is False
            # -- ?gang= narrows to one document
            status, one = _get_json(server.port,
                                    f"/debug/goodput?gang={gang}")
            assert status == 200 and one["gang"] == gang
            # -- /debug/explain: the RUNNING-phase answer (no pending
            # diagnosis exists — the gang is bound)
            status, ex = _get_json(server.port,
                                   f"/debug/explain?gang={gang}")
            assert status == 200
            assert ex["phase"] == "Running"
            assert [x["pod"] for x in ex["stragglers"]] == [slow]
            # -- pinned as a flight-recorder anomaly, fully attributed
            pinned = [a for t in trace.default_recorder().pinned_traces()
                      for a in (t.anomalies or [])
                      if a["kind"] == "gang_straggler"]
            assert pinned, "gang_straggler anomaly not pinned"
            assert pinned[0]["gang"] == gang
            assert pinned[0]["member"] == slow
            assert float(pinned[0]["skew"]) >= 1.5
            # -- hysteresis exit: tearing the straggler down clears the
            # detection (the informer delete evicts the member)
            c.api.delete(srv.PODS, slow)
            assert wait_until(
                lambda: (agg.gang_health(gang) or {}).get("stragglers")
                == [], timeout=5), "teardown did not clear the verdict"
            status, ex2 = _get_json(server.port,
                                    f"/debug/explain?gang={gang}")
            assert status == 200 and ex2["stragglers"] == []
        finally:
            server.stop()


def test_unknown_gang_404_names_goodput_surface(fresh_obs):
    server = MetricsServer(port=0).start()
    try:
        status, body = _get_json(server.port,
                                 "/debug/explain?gang=default/nope")
        assert status == 404
        assert "goodput" in body["error"]
        status, body = _get_json(server.port,
                                 "/debug/goodput?gang=default/nope")
        assert status == 404
    finally:
        server.stop()


def test_debug_index_enumerates_every_mounted_endpoint(fresh_obs):
    """/debug/ lists every debug route with a description, and the listing
    cannot go stale: every listed path answers (non-404), and every
    ``/debug/...`` literal dispatched in httpserve's handler is listed."""
    import re

    import tpusched.util.httpserve as hs
    server = MetricsServer(port=0).start()
    try:
        status, idx = _get_json(server.port, "/debug/")
        assert status == 200
        assert idx["endpoints"] == DEBUG_ENDPOINTS
        # the incident plane's surfaces are part of the pinned contract
        # (ISSUE 20): losing either would orphan the cmd.incident runbook
        assert "/debug/timeline" in idx["endpoints"]
        assert "/debug/incidents" in idx["endpoints"]
        # trailing-slash-less spelling serves the same index
        status2, idx2 = _get_json(server.port, "/debug")
        assert status2 == 200 and idx2 == idx
        for path, desc in idx["endpoints"].items():
            assert desc.strip(), f"{path}: empty description"
            status, _body = _get(server.port, path)
            assert status != 404, f"listed endpoint {path} is unmounted"
        # source pin: every mounted /debug route appears in the index
        with open(hs.__file__, encoding="utf-8") as f:
            src = f.read()
        mounted = set(re.findall(r'path == "(/debug/[^"]+)"', src))
        assert mounted <= set(DEBUG_ENDPOINTS), \
            f"unlisted debug endpoints: {mounted - set(DEBUG_ENDPOINTS)}"
    finally:
        server.stop()


def test_cmd_explain_renders_running_gang(fresh_obs, capsys):
    """cmd.explain covers the RUNNING phase: a bound-but-degraded gang
    renders goodput/straggler attribution instead of a dead end."""
    from tpusched.api.core import GangMemberStatus
    from tpusched.cmd import explain
    agg = fresh_obs
    gang = "default/rgang"
    for m in range(3):
        agg.register_member(f"default/rgang-{m}", gang, f"node-{m}",
                            workload="llama", generation="tpu-v5p", chips=4)
    for step in range(1, 7):
        for m in range(3):
            st = 0.4 if m == 0 else 0.1
            agg.ingest([GangMemberStatus(
                pod_key=f"default/rgang-{m}", gang=gang, step=step,
                step_time_s=st, throughput=100.0 / st, timestamp=1.0)])
    server = MetricsServer(port=0).start()
    try:
        rc = explain.main(["--url", f"http://127.0.0.1:{server.port}",
                           "--gang", gang])
        out = capsys.readouterr().out
        assert rc == 0
        assert "RUNNING" in out
        assert "STRAGGLERS (1)" in out
        assert "default/rgang-0 on node-0" in out
        assert "Why is my gang slow?" in out
        # --json yields the raw payload for scripting
        rc = explain.main(["--url", f"http://127.0.0.1:{server.port}",
                           "--gang", gang, "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0 and payload["phase"] == "Running"
    finally:
        server.stop()
        for m in range(3):
            agg.on_pod_delete(f"default/rgang-{m}")


def test_matrix_ordering_round_trip_and_whatif_consumption(fresh_obs,
                                                           tmp_path):
    """The throughput-matrix acceptance: two workloads × two generations
    with different injected step-times order per the injection, survive
    snapshot/reload, and feed the what-if planner's goodput annotation."""
    from tpusched.api.core import GangMemberStatus
    from tpusched.sim.whatif import simulate_gang
    agg = fresh_obs
    # injected device rates (tokens/s/chip over 4 chips):
    #   llama: v6e 2x faster than v5p;  moe: v5p faster than v6e
    inject = {("llama", "tpu-v5p"): 0.4, ("llama", "tpu-v6e"): 0.2,
              ("moe", "tpu-v5p"): 0.5, ("moe", "tpu-v6e"): 1.0}
    i = 0
    for (workload, gen), step_time in inject.items():
        gang = f"default/{workload}-{gen}"
        pod = f"{gang}-0"
        agg.register_member(pod, gang, f"n{i}", workload=workload,
                            generation=gen, chips=4)
        i += 1
        for step in range(1, 5):
            agg.ingest([GangMemberStatus(
                pod_key=pod, gang=gang, step=step, step_time_s=step_time,
                throughput=400.0 / step_time, timestamp=1.0)])
    matrix = agg.matrix_snapshot()
    # ordering matches the injected step times, per workload — and the
    # two workloads prefer OPPOSITE generations (the Gavel point)
    assert matrix.peek("llama", "tpu-v6e") > matrix.peek("llama", "tpu-v5p")
    assert matrix.peek("moe", "tpu-v5p") > matrix.peek("moe", "tpu-v6e")
    assert matrix.best_generation("llama") == "tpu-v6e"
    assert matrix.best_generation("moe") == "tpu-v5p"
    # snapshot → disk → reload round trip
    path = str(tmp_path / "matrix.json")
    agg.save_matrix(path)
    back = obs.load_matrix(path)
    assert back.to_dict() == matrix.to_dict()
    # consumable by the what-if planner: a hypothetical llama gang landing
    # on a v5e-free fleet of v6e reports the measured cell AND that the
    # matrix would prefer v5p for this workload
    api = srv.APIServer()
    topo, nodes = make_tpu_pool("pool-v6e", accelerator="tpu-v6e",
                                dims=(8, 8))      # v6e torus is 2-D
    api.create(srv.TPU_TOPOLOGIES, topo)
    for n in nodes:
        api.create(srv.NODES, n)
    report = simulate_gang(api, name="trial", members=4,
                           slice_shape="4x4", accelerator="tpu-v6e",
                           chips_per_pod=4, timeout_s=20.0,
                           goodput_matrix=back)
    assert report.feasible
    assert report.generation == "tpu-v6e"
    assert report.workload  # shape-derived fingerprint ("4x4/4chip")
    # the trial workload has no measured cell (fingerprints differ from
    # the labeled "llama"): None, never fabricated zero
    assert report.goodput_per_chip is None
    # a matrix measured under the SAME fingerprint annotates fully
    fp = report.workload
    for gen, per_chip in (("tpu-v5p", 900.0), ("tpu-v6e", 450.0)):
        back.fold(fp, gen, per_chip, "tokens", 2.0)
    report2 = simulate_gang(api, name="trial2", members=4,
                            slice_shape="4x4", accelerator="tpu-v6e",
                            chips_per_pod=4, timeout_s=20.0,
                            goodput_matrix=back)
    assert report2.feasible
    assert report2.goodput_per_chip == pytest.approx(450.0)
    assert report2.best_generation == "tpu-v5p"   # fits, but on the slow gen


def test_fleetrace_captures_reports_and_matrix_from_trace(fresh_obs,
                                                          tmp_path):
    """Recorded traces carry the matrix: goodput reports are captured as
    ``goodput-report`` events, and ``matrix_from_trace`` rebuilds the
    workload×generation matrix from the trace alone — no live aggregator
    state."""
    from tpusched.obs.fleetrace import FleetTraceRecorder, load_trace
    from tpusched.obs.goodput import matrix_from_trace
    rec = FleetTraceRecorder()
    with TestCluster() as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        rec.attach(c.api, str(tmp_path / "trace"))
        try:
            keys = _bind_gang(c, "tracegang")
            c.pump_gang_progress("default/tracegang",
                                 {k: 0.1 for k in keys}, steps=4,
                                 tokens_per_step=400.0)
        finally:
            rec.detach()
    tr = load_trace(str(tmp_path / "trace"))
    by_kind = tr.events_by_kind()
    assert by_kind.get("goodput-report", 0) == 16      # 4 members × 4 steps
    [ev] = [e for e in tr.events if e.get("kind") == "goodput-report"
            and e.get("pod") == keys[0] and e.get("step") == 4]
    assert ev["throughput"] == pytest.approx(4000.0)
    assert ev["unit"] == "tokens"
    # offline reconstruction: 4 chips/member ⇒ 1000 tokens/s/chip on the
    # pool's generation, keyed by the shape-derived fingerprint
    m = matrix_from_trace(tr)
    assert m.peek("2x2x4/4chip", "tpu-v5p") == pytest.approx(1000.0)
    # and the replay driver ignores the new kind (recorded telemetry is
    # not workload): apply_event refuses to re-feed it
    from tpusched.sim.replay import apply_event
    assert apply_event(srv.APIServer(), ev) is False
