"""TopologyMatch live-cluster scenarios — CR lifecycle, scoring strategies,
racing gangs, and every accelerator generation end-to-end. The reference's
NRT integration tier (/root/reference/test/integration/
noderesourcetopology_test.go, its biggest integration file) creates NRT CRs
through the real API server and asserts placement; the torus analog here
drives TpuTopology CRs against the live scheduler.
"""
from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.config.types import TopologyMatchArgs
from tpusched.plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool)


def add_pool(c, pool, accelerator="tpu-v5p", dims=(4, 4, 4), dcn_domain=""):
    topo, nodes = make_tpu_pool(pool, accelerator=accelerator, dims=dims,
                                dcn_domain=dcn_domain)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)
    return topo, nodes


def slice_gang(c, name, shape, members, accelerator="tpu-v5p", chips=4):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape=shape,
        tpu_accelerator=accelerator))
    pods = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: chips})
            for i in range(members)]
    c.create_pods(pods)
    return pods


def strategy_profile(strategy, packing_weight=0.0):
    """packing_weight=0: pure NRT-style strategy scoring over pool zones,
    so the strategy alone decides pool choice (the default 0.7 blend keeps
    anti-fragmentation packing dominant — covered by the corner-packing
    tests in test_topology.py)."""
    prof = tpu_gang_profile(permit_wait_s=5, denied_s=1)
    prof.plugin_args["TopologyMatch"] = TopologyMatchArgs(
        scoring_strategy=strategy, packing_weight=packing_weight)
    return prof


# -- CR lifecycle -------------------------------------------------------------

def test_gang_pending_until_topology_cr_arrives():
    """Slice-shaped gang with nodes but NO TpuTopology CR: PreFilter cannot
    resolve a pool; creating the CR later must requeue and admit the gang
    (cluster-event registration on the CR kind)."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        topo, nodes = make_tpu_pool("late-pool", dims=(4, 4, 4))
        c.add_nodes(nodes)  # nodes first, CR withheld
        pods = slice_gang(c, "early", "4x4x4", 16)
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=1.5)
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)


def test_topology_cr_deleted_blocks_new_slices_only():
    """Deleting the CR strands new slice gangs but must not disturb pods
    already bound (annotations-as-truth survives the CR)."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=2, denied_s=1)) as c:
        topo, _ = add_pool(c, "doomed", dims=(4, 4, 4))
        first = slice_gang(c, "resident", "2x2x4", 4)
        assert c.wait_for_pods_scheduled([p.key for p in first], timeout=20)
        c.api.delete(srv.TPU_TOPOLOGIES, topo.key)
        second = slice_gang(c, "stranded", "2x2x4", 4)
        assert c.wait_for_pods_unscheduled([p.key for p in second], hold=1.5)
        # residents untouched
        for p in first:
            assert c.pod(p.key).spec.node_name


# -- scoring strategies over pool zones ---------------------------------------

def test_most_allocated_packs_the_busy_pool():
    """MostAllocated: a new slice consolidates onto the fuller pool, keeping
    the empty pool free for large jobs (most_allocated.go:25-54 semantics
    over torus zones)."""
    with TestCluster(profile=strategy_profile("MostAllocated")) as c:
        add_pool(c, "busy", dims=(4, 4, 4))
        add_pool(c, "empty", dims=(4, 4, 4))
        seed = slice_gang(c, "seed", "2x2x4", 4)
        assert c.wait_for_pods_scheduled([p.key for p in seed], timeout=20)
        seed_pool = {c.pod(p.key).meta.annotations[POOL_ANNOTATION]
                     for p in seed}
        nxt = slice_gang(c, "next", "2x2x4", 4)
        assert c.wait_for_pods_scheduled([p.key for p in nxt], timeout=20)
        nxt_pool = {c.pod(p.key).meta.annotations[POOL_ANNOTATION]
                    for p in nxt}
        assert nxt_pool == seed_pool  # consolidated


def test_least_allocated_spreads_to_the_idle_pool():
    with TestCluster(profile=strategy_profile("LeastAllocated")) as c:
        add_pool(c, "busy", dims=(4, 4, 4))
        add_pool(c, "empty", dims=(4, 4, 4))
        seed = slice_gang(c, "seed", "2x2x4", 4)
        assert c.wait_for_pods_scheduled([p.key for p in seed], timeout=20)
        seed_pool = {c.pod(p.key).meta.annotations[POOL_ANNOTATION]
                     for p in seed}
        nxt = slice_gang(c, "next", "2x2x4", 4)
        assert c.wait_for_pods_scheduled([p.key for p in nxt], timeout=20)
        nxt_pool = {c.pod(p.key).meta.annotations[POOL_ANNOTATION]
                    for p in nxt}
        assert nxt_pool != seed_pool  # spread


# -- racing gangs -------------------------------------------------------------

def test_two_gangs_race_for_last_window_exactly_one_wins():
    """One 4x4x4 window left; two identical gangs submitted together. The
    Permit barrier + placement reservation must admit exactly one whole gang
    (no interleaved half-gangs deadlocking the window)."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=3, denied_s=1)) as c:
        add_pool(c, "arena", dims=(4, 4, 4))
        a = slice_gang(c, "gang-a", "4x4x4", 16)
        b = slice_gang(c, "gang-b", "4x4x4", 16)
        import time
        deadline = time.monotonic() + 25
        def done(pods):
            return all(c.pod_scheduled(p.key) for p in pods)
        while time.monotonic() < deadline and not (done(a) or done(b)):
            time.sleep(0.05)
        assert done(a) or done(b)
        winner, loser = (a, b) if done(a) else (b, a)
        # the loser must remain fully unbound (all-or-nothing held)
        assert c.wait_for_pods_unscheduled([p.key for p in loser], hold=1.5)
        hosts = {c.pod(p.key).spec.node_name for p in winner}
        assert len(hosts) == 16


# -- accelerator generations --------------------------------------------------

def test_every_generation_places_a_slice_e2e():
    """v4 / v5e / v5p / v6e each schedule a full-pool slice with the right
    host-block geometry (accelerator catalog, api/topology.py)."""
    cases = [
        ("tpu-v4", (4, 4, 4), "4x4x4", 16),    # 2x2x1 hosts → 16 hosts
        ("tpu-v5e", (4, 4), "4x4", 4),         # 2x2 hosts → 4 hosts
        ("tpu-v5p", (2, 2, 4), "2x2x4", 4),    # 4 hosts
        ("tpu-v6e", (8, 4), "8x4", 4),         # 4x2 hosts → 4 hosts
    ]
    import math
    from tpusched.topology.torus import HOST_EXTENT
    for acc, dims, shape, members in cases:
        with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                                  denied_s=1)) as c:
            chips_per_host = math.prod(HOST_EXTENT[acc])
            add_pool(c, f"pool-{acc}", accelerator=acc, dims=dims)
            pods = slice_gang(c, f"job-{acc}", shape, members,
                              accelerator=acc, chips=chips_per_host)
            assert c.wait_for_pods_scheduled([p.key for p in pods],
                                             timeout=20), acc
            coords = {c.pod(p.key).meta.annotations[COORD_ANNOTATION]
                      for p in pods}
            assert len(coords) == members, (acc, coords)


# -- gang→pool pin (Reserve-time sweep shortcut) ------------------------------

def test_gang_pool_pin_set_and_released():
    """The fleet-scale shortcut: once a sibling reserves, the gang is pinned
    to its pool (later siblings sweep 1 pool, not N); deleting the PodGroup
    releases the pin."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        for i in range(4):
            add_pool(c, f"pin-pool-{i}", dims=(4, 4, 4))
        pods = slice_gang(c, "pinned", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
        tm = c.scheduler._fw.plugins["TopologyMatch"]
        landed = {c.pod(p.key).meta.annotations[POOL_ANNOTATION]
                  for p in pods}
        assert len(landed) == 1
        assert tm._gang_pool.get("default/pinned") == landed.pop()
        c.api.delete(srv.POD_GROUPS, "default/pinned")
        from tpusched.testing import wait_until
        assert wait_until(lambda: "default/pinned" not in tm._gang_pool,
                          timeout=5)


def test_stale_gang_pool_pin_falls_back_to_full_sweep():
    """A pin pointing at a vanished/full pool must not wedge the gang: the
    sweep falls back to all matching pools and re-derives the pin."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        add_pool(c, "real-pool", dims=(4, 4, 4))
        tm = c.scheduler._fw.plugins["TopologyMatch"]
        # poison the pin before the gang arrives
        tm._gang_pool["default/resilient"] = "no-such-pool"
        pods = slice_gang(c, "resilient", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
        assert tm._gang_pool.get("default/resilient") == "real-pool"
