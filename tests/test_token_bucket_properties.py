"""Property-based token-bucket laws (hypothesis): the client-side QPS
throttle must (1) bound the admission rate, (2) never deadlock concurrent
waiters, and (3) resolve every deadline-carrying waiter — a token or a
terminal ``Throttled``, never an unbounded sleep (the satellite fix in
apiserver/client.py:_TokenBucket.wait).

Deterministic companions: tests/test_resilience.py's token-bucket section.
"""
import threading
import time

import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from tpusched.apiserver.client import _TokenBucket  # noqa: E402
from tpusched.apiserver.errors import Throttled  # noqa: E402


@settings(max_examples=10, deadline=None)
@given(qps=st.integers(min_value=100, max_value=400),
       burst=st.integers(min_value=1, max_value=4),
       extra=st.integers(min_value=1, max_value=8),
       workers=st.integers(min_value=1, max_value=4))
def test_rate_bound_and_liveness(qps, burst, extra, workers):
    """Concurrent waiters never exceed the configured rate (elapsed ≥
    tokens-minted/qps, with scheduling slack) and never deadlock (every
    waiter returns)."""
    b = _TokenBucket(qps=float(qps), burst=burst)
    n = burst + extra
    taken = []
    lock = threading.Lock()

    def puller():
        while True:
            with lock:
                if len(taken) >= n:
                    return
                taken.append(1)
            b.wait(deadline=time.monotonic() + 10.0)

    t0 = time.monotonic()
    threads = [threading.Thread(target=puller) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    elapsed = time.monotonic() - t0
    assert not any(t.is_alive() for t in threads), "token bucket deadlocked"
    assert elapsed >= (n - burst) / qps * 0.5


@settings(max_examples=10, deadline=None)
@given(deadline_ms=st.integers(min_value=5, max_value=50),
       waiters=st.integers(min_value=2, max_value=6))
def test_deadline_liveness_under_contention(deadline_ms, waiters):
    """Starved waiters with deadlines all resolve (token or Throttled) —
    nobody sleeps unboundedly toward a token that cannot arrive in time."""
    b = _TokenBucket(qps=0.2, burst=1)
    b.wait()                                 # starve the bucket
    outcomes = []
    lock = threading.Lock()

    def waiter():
        try:
            b.wait(deadline=time.monotonic() + deadline_ms / 1000.0)
            out = "token"
        except Throttled:
            out = "throttled"
        with lock:
            outcomes.append(out)

    threads = [threading.Thread(target=waiter) for _ in range(waiters)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in threads)
    assert len(outcomes) == waiters
    assert outcomes.count("throttled") >= waiters - 1
