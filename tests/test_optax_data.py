"""Optax sharded train step, optimizer-state checkpointing, data pipeline."""
from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpusched.jaxbridge import checkpoint, workload
from tpusched.jaxbridge.data import TokenBatcher
from tpusched.jaxbridge.mesh import build_named_mesh


def test_adamw_step_shards_optimizer_state_like_params():
    cfg = workload.ModelConfig.tiny()
    mesh = build_named_mesh({"fsdp": 2, "tp": 2})
    step, init_opt, pshard, tshard = workload.make_optax_train_step(
        mesh, cfg, optax.adamw(1e-3))
    params = jax.device_put(workload.init_params(jax.random.PRNGKey(0), cfg),
                            pshard)
    opt_state = init_opt(params)
    # adam moments inherit the params' fsdp×tp shardings (ZeRO-style)
    mu_wq = opt_state[0].mu["layers"][0]["wq"]
    assert mu_wq.sharding == params["layers"][0]["wq"].sharding
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, cfg.seq), 0, cfg.vocab),
        tshard)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # adamw actually optimizes


def test_checkpoint_roundtrips_optimizer_state(tmp_path):
    cfg = workload.ModelConfig.tiny()
    tokens_np = jax.random.randint(jax.random.PRNGKey(1), (4, cfg.seq),
                                   0, cfg.vocab)
    mesh_a = build_named_mesh({"dp": 4, "tp": 2})
    step_a, init_a, pshard_a, tshard_a = workload.make_optax_train_step(
        mesh_a, cfg, optax.adamw(1e-3))
    params = jax.device_put(workload.init_params(jax.random.PRNGKey(0), cfg),
                            pshard_a)
    opt = init_a(params)
    toks = jax.device_put(tokens_np, tshard_a)
    for _ in range(2):
        params, opt, _ = step_a(params, opt, toks)
    checkpoint.save(str(tmp_path), params, step=2, extra=opt)
    baseline_params = params
    for _ in range(2):
        baseline_params, opt, baseline_loss = step_a(baseline_params, opt, toks)

    # resume on a different mesh, momenta intact
    mesh_b = build_named_mesh({"fsdp": 4, "tp": 2})
    step_b, init_b, pshard_b, tshard_b = workload.make_optax_train_step(
        mesh_b, cfg, optax.adamw(1e-3))
    abstract_p = checkpoint.abstract_state(
        jax.eval_shape(lambda: workload.init_params(jax.random.PRNGKey(0), cfg)),
        pshard_b)
    # optimizer skeleton: init on the new mesh (inherits new shardings),
    # then fill it from the checkpoint
    skeleton = init_b(jax.device_put(
        workload.init_params(jax.random.PRNGKey(0), cfg), pshard_b))
    restored_p, step_n, restored_opt = checkpoint.restore(
        str(tmp_path), abstract_p, abstract_extra=checkpoint.abstract_like(skeleton))
    assert step_n == 2
    resumed_params, resumed_opt = restored_p, restored_opt
    for _ in range(2):
        resumed_params, resumed_opt, resumed_loss = step_b(
            resumed_params, resumed_opt, jax.device_put(tokens_np, tshard_b))
    np.testing.assert_allclose(float(resumed_loss), float(baseline_loss),
                               atol=1e-5, rtol=1e-5)


def test_token_batcher_deterministic_and_sharded():
    cfg = workload.ModelConfig.tiny()
    mesh = build_named_mesh({"dp": 8})
    _, _, _, tshard = workload.make_optax_train_step(
        mesh, cfg, optax.sgd(1e-3))
    a = list(itertools.islice(TokenBatcher(cfg, 8, tshard, seed=7), 3))
    b = list(itertools.islice(TokenBatcher(cfg, 8, tshard, seed=7), 3))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
        assert x.sharding == tshard
        assert x.shape == (8, cfg.seq) and x.dtype == jnp.int32
    # resume mid-stream: start_step skips exactly the consumed prefix
    c = next(iter(TokenBatcher(cfg, 8, tshard, seed=7, start_step=2)))
    np.testing.assert_array_equal(c, a[2])
    # different seed, different stream
    d = next(iter(TokenBatcher(cfg, 8, tshard, seed=8)))
    assert not np.array_equal(d, a[0])


def test_pack_documents():
    from tpusched.jaxbridge.data import pack_documents
    docs = [[1, 2, 3], [4, 5], [6, 7, 8, 9, 10, 11, 12]]
    rows = pack_documents(docs, seq=8, eos=99, pad=0)
    flat = [t for r in rows for t in r]
    # every token survives in order; exactly one eos per document
    content = [t for t in flat if t not in (0,)]
    assert content == [1, 2, 3, 99, 4, 5, 99, 6, 7, 8, 9, 10, 11, 12, 99]
    assert flat.count(99) == len(docs)
    assert rows.shape[1] == 8
    # a document longer than a whole row splits without a phantom eos
    long = pack_documents([list(range(1, 20))], seq=8, eos=99)
    lflat = [t for r in long for t in r]
    assert lflat.count(99) == 1
    assert lflat[19] == 99
    # full utilization: only the final row may carry padding
    for r in rows[:-1]:
        assert 0 not in r
    assert pack_documents([], seq=8, eos=99).shape == (0, 8)
