"""What-if capacity simulator: dry-run gang admission on shadow state.

No reference analog (nothing in the reference tree simulates admission);
the contract pinned here is the one that makes the feature trustworthy:
REAL scheduler decisions on the shadow, ZERO mutation of the source."""
import json
import subprocess
import sys

from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import full_stack_profile
from tpusched.sim import simulate_gang
from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                              make_pod_group, make_tpu_pool)


def _cluster_with_pool(c, dims=(4, 4, 4)):
    topo, nodes = make_tpu_pool("pool", dims=dims)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)


def test_whatif_feasible_gang_reports_placement():
    with TestCluster() as c:
        _cluster_with_pool(c)                      # 64 chips / 16 hosts free
        r = simulate_gang(source_api=c.api, members=16,
                          slice_shape="4x4x4", accelerator="tpu-v5p",
                          chips_per_pod=4, timeout_s=20)
        assert r.feasible
        assert len(r.placements) == 16 and r.pool == "pool"
        assert all(r.coords.values())              # chip coords annotated
        assert r.victims == []
        # the source cluster was not touched
        assert c.api.list(srv.PODS) == []
        assert len(c.api.list(srv.POD_GROUPS)) == 0


def test_whatif_infeasible_reports_scheduler_diagnosis():
    with TestCluster() as c:
        _cluster_with_pool(c)                      # 64 chips total
        r = simulate_gang(source_api=c.api, members=32,
                          slice_shape="4x4x8", accelerator="tpu-v5p",
                          chips_per_pod=4, timeout_s=3)
        assert not r.feasible
        assert r.placements == {} and r.victims == []
        assert r.reason                             # FailedScheduling detail


def test_whatif_preemption_reports_exact_victims():
    """Full-stack shadow: a team-b gang under quota evicts team-a's
    borrowed window — the report names the evicted pods, and the SOURCE
    cluster still runs them untouched."""
    with TestCluster(profile=full_stack_profile(permit_wait_s=20,
                                                denied_s=1)) as c:
        _cluster_with_pool(c, dims=(4, 4, 8))      # 128 chips
        for team in ("team-a", "team-b"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 64}, max={TPU: 128}))
        for g in ("a-first", "a-borrow"):
            c.api.create(srv.POD_GROUPS, make_pod_group(
                g, namespace="team-a", min_member=16,
                tpu_slice_shape="4x4x4", tpu_accelerator="tpu-v5p"))
            ps = [make_pod(f"{g}-{i}", namespace="team-a", pod_group=g,
                           limits={TPU: 4}) for i in range(16)]
            c.create_pods(ps)
            assert c.wait_for_pods_scheduled([p.key for p in ps], timeout=30)

        r = simulate_gang(source_api=c.api, members=16, namespace="team-b",
                          slice_shape="4x4x4", accelerator="tpu-v5p",
                          chips_per_pod=4, allow_preemption=True,
                          timeout_s=25)
        assert r.feasible
        assert len(r.victims) == 16                 # one whole window
        assert all(v.startswith("team-a/") for v in r.victims)
        # exactly one of team-a's gangs was chosen, not a mix
        gangs = {v.split("/")[1].rsplit("-", 1)[0] for v in r.victims}
        assert len(gangs) == 1
        # the source cluster still runs all 32 team-a pods
        assert len([p for p in c.api.list(srv.PODS)
                    if p.spec.node_name]) == 32


def test_whatif_cli_runs_from_state_dir(tmp_path):
    """End-to-end through the CLI: persist a cluster via the WAL, then ask
    the binary whether a gang fits. Exercises the durability+sim
    composition the binary exists for."""
    from tpusched.apiserver import APIServer
    from tpusched.apiserver.persistence import attach

    api = APIServer()
    journal = attach(api, str(tmp_path))
    try:
        with TestCluster(api=api) as c:
            _cluster_with_pool(c)
        assert journal.flush(timeout=10)
    finally:
        journal.close()

    out = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.whatif",
         "--state-dir", str(tmp_path), "--members", "16",
         "--slice-shape", "4x4x4", "--accelerator", "tpu-v5p",
         "--chips", "4", "--timeout", "20"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["feasible"] and len(report["placements"]) == 16


def test_whatif_plan_sequential_capacity():
    """simulate_plan: jobs share one shadow. On a 128-chip pool: gang 1
    (64 chips) fits; gang 2 wants the WHOLE pool (4x4x8) and must report
    infeasible; gang 3 (64 chips) still fits in the remainder — proving
    the failed job was withdrawn and did not poison the plan; gang 4
    finds the pool full."""
    from tpusched.sim import simulate_plan
    with TestCluster() as c:
        _cluster_with_pool(c, dims=(4, 4, 8))      # 128 chips
        gang = dict(members=16, slice_shape="4x4x4",
                    accelerator="tpu-v5p", chips_per_pod=4)
        whole_pool = dict(members=32, slice_shape="4x4x8",
                          accelerator="tpu-v5p", chips_per_pod=4)
        reports = simulate_plan(
            source_api=c.api,
            jobs=[dict(gang), dict(whole_pool), dict(gang), dict(gang)],
            timeout_s=6)
        assert [r.feasible for r in reports] == [True, False, True, False]
        # the two admitted slice gangs landed on disjoint host sets
        h0 = set(reports[0].placements.values())
        h2 = set(reports[2].placements.values())
        assert h0 and h2 and not (h0 & h2)
        assert reports[1].reason and reports[3].reason  # diagnoses surfaced
        # source untouched throughout
        assert c.api.list(srv.PODS) == []


def test_whatif_plan_cli(tmp_path):
    from tpusched.apiserver import APIServer
    from tpusched.apiserver.persistence import attach

    api = APIServer()
    journal = attach(api, str(tmp_path / "state"))
    try:
        with TestCluster(api=api) as c:
            _cluster_with_pool(c, dims=(4, 4, 8))
        assert journal.flush(timeout=10)
    finally:
        journal.close()
    plan = tmp_path / "plan.json"
    gang = {"members": 16, "slice_shape": "4x4x4",
            "accelerator": "tpu-v5p", "chips_per_pod": 4}
    plan.write_text(json.dumps([gang, gang, gang]))
    out = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.whatif",
         "--state-dir", str(tmp_path / "state"), "--plan", str(plan),
         "--timeout", "6"],
        capture_output=True, text=True, timeout=180)
    assert out.returncode == 1                      # third job does not fit
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert [r["feasible"] for r in lines] == [True, True, False]


def test_whatif_plan_validates_up_front():
    from tpusched.sim import simulate_plan
    import pytest as _pytest
    with TestCluster() as c:
        _cluster_with_pool(c)
        with _pytest.raises(ValueError, match="unknown keys"):
            simulate_plan(source_api=c.api,
                          jobs=[{"members": 4, "chips": 4}])   # CLI-flag typo
        with _pytest.raises(ValueError, match="members"):
            simulate_plan(source_api=c.api, jobs=[{"slice_shape": "2x2x1"}])
        with _pytest.raises(ValueError, match="duplicate"):
            simulate_plan(source_api=c.api,
                          jobs=[{"members": 4, "name": "j"},
                                {"members": 4, "name": "j"}])


def test_whatif_plan_failed_preemption_attempt_is_unwound():
    """An infeasible preempting job must not leave phantom free capacity:
    any pods its attempt evicted are restored, and the next job's report
    shows the true (preemption-requiring) cost."""
    from tpusched.sim import simulate_plan
    with TestCluster(profile=full_stack_profile(permit_wait_s=20,
                                                denied_s=1)) as c:
        _cluster_with_pool(c, dims=(4, 4, 8))      # 128 chips
        for team in ("team-a", "team-b"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 64}, max={TPU: 128}))
        for g in ("a-first", "a-borrow"):
            c.api.create(srv.POD_GROUPS, make_pod_group(
                g, namespace="team-a", min_member=16,
                tpu_slice_shape="4x4x4", tpu_accelerator="tpu-v5p"))
            ps = [make_pod(f"{g}-{i}", namespace="team-a", pod_group=g,
                           limits={TPU: 4}) for i in range(16)]
            c.create_pods(ps)
            assert c.wait_for_pods_scheduled([p.key for p in ps], timeout=30)

        # job 0: team-b wants the WHOLE pool — preemption can evict team-a's
        # borrowed window but can never break team-a's min, so it fails
        # (after evicting a window it must restore); job 1: a one-window
        # team-b gang — feasible, and its report must name 16 victims
        # (proof the failed attempt's evictions were restored: without the
        # restore, job 1 would find a free window and report victims=[])
        reports = simulate_plan(
            source_api=c.api, allow_preemption=True, timeout_s=8,
            jobs=[dict(members=32, slice_shape="4x4x8",
                       accelerator="tpu-v5p", chips_per_pod=4,
                       namespace="team-b"),
                  dict(members=16, slice_shape="4x4x4",
                       accelerator="tpu-v5p", chips_per_pod=4,
                       namespace="team-b")])
        assert [r.feasible for r in reports] == [False, True]
        assert reports[0].victims == []             # unwound
        assert len(reports[1].victims) == 16        # true admission cost
        assert reports[1].displaced_plan_pods == []
        # source untouched
        assert len([p for p in c.api.list(srv.PODS)
                    if p.spec.node_name]) == 32


def test_whatif_cli_rejects_plan_flag_mix(tmp_path):
    plan = tmp_path / "p.json"
    plan.write_text(json.dumps([{"members": 4}]))
    out = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.whatif",
         "--state-dir", str(tmp_path), "--plan", str(plan),
         "--members", "16"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 2
    assert "--members" in out.stderr and "--plan" in out.stderr
    # non-array plan file fails fast too
    plan.write_text(json.dumps({"members": 4}))
    out = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.whatif",
         "--state-dir", str(tmp_path), "--plan", str(plan)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 2 and "JSON array" in out.stderr


def test_whatif_with_production_config_profile():
    """--config: the shadow runs the EXACT decoded production wiring (the
    shipped full-stack manifest), not a canned profile."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = os.path.join(repo, "manifests", "full", "scheduler-config.yaml")
    with TestCluster() as c:
        _cluster_with_pool(c)
        r = simulate_gang(source_api=c.api, members=16,
                          slice_shape="4x4x4", accelerator="tpu-v5p",
                          chips_per_pod=4, timeout_s=25,
                          config_path=cfg)
        assert r.feasible and len(r.placements) == 16


def test_whatif_config_with_custom_scheduler_name():
    """A --config profile with a non-default schedulerName must still
    simulate: hypothetical pods are stamped with the profile's name (a
    mismatch would make every simulation falsely infeasible)."""
    import textwrap
    cfg_yaml = textwrap.dedent("""
        apiVersion: tpusched.config.tpu.dev/v1beta1
        kind: TpuSchedulerConfiguration
        profiles:
        - schedulerName: prod-sched
          plugins:
            queueSort:
              enabled: [{name: Coscheduling}]
              disabled: [{name: "*"}]
            preFilter:
              enabled: [{name: Coscheduling}, {name: TopologyMatch}]
            filter:
              enabled: [{name: TopologyMatch}, {name: TpuSlice}]
            postFilter:
              enabled: [{name: Coscheduling}]
            score:
              enabled: [{name: TpuSlice, weight: 1}]
            reserve:
              enabled: [{name: TpuSlice}, {name: TopologyMatch},
                        {name: Coscheduling}]
            permit:
              enabled: [{name: Coscheduling}]
            bind:
              enabled: [{name: TpuSlice}]
              disabled: [{name: DefaultBinder}]
    """)
    import tempfile, os
    with tempfile.NamedTemporaryFile("w", suffix=".yaml",
                                     delete=False) as f:
        f.write(cfg_yaml)
        path = f.name
    try:
        with TestCluster() as c:
            _cluster_with_pool(c)
            r = simulate_gang(source_api=c.api, members=16,
                              slice_shape="4x4x4", accelerator="tpu-v5p",
                              chips_per_pod=4, timeout_s=25,
                              config_path=path,
                              scheduler_name="prod-sched")
            assert r.feasible and len(r.placements) == 16
    finally:
        os.unlink(path)


def test_whatif_cli_scheduler_name_requires_config(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.whatif",
         "--state-dir", str(tmp_path), "--members", "4",
         "--scheduler-name", "prod"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 2 and "--config" in out.stderr


def test_whatif_cli_bad_config_exits_2_not_1(tmp_path):
    """Operational errors (bad --config) must exit 2, never the exit 1 an
    admission-control script reads as 'infeasible'."""
    out = subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.whatif",
         "--state-dir", str(tmp_path), "--members", "4",
         "--config", str(tmp_path / "missing.yaml")],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 2, (out.returncode, out.stderr[-200:])


def test_whatif_atomic_set_feasible_and_infeasible():
    """slices=N simulates the whole atomic set through the real barrier:
    feasible iff every member slice lands. Two pools hold a 2-slice set;
    the same ask with 3 slices must come back infeasible — and leave the
    source untouched either way."""
    with TestCluster() as c:
        for name in ("pool-a", "pool-b"):
            topo, nodes = make_tpu_pool(name, dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        r = simulate_gang(source_api=c.api, members=16, slices=2,
                          slice_shape="4x4x4", accelerator="tpu-v5p",
                          chips_per_pod=4, timeout_s=25)
        assert r.feasible
        assert len(r.placements) == 32             # both slices placed
        pools = {r.placements[k].split("-")[0] for k in r.placements}
        r3 = simulate_gang(source_api=c.api, members=16, slices=3,
                           slice_shape="4x4x4", accelerator="tpu-v5p",
                           chips_per_pod=4, timeout_s=6)
        assert not r3.feasible
        assert c.api.list(srv.PODS) == []
        assert len(c.api.list(srv.POD_GROUPS)) == 0


def test_whatif_plan_supports_set_jobs():
    """A plan job may declare slices: the set admits (or is withdrawn)
    as one unit, and later jobs see the capacity it consumed."""
    from tpusched.sim import simulate_plan
    with TestCluster() as c:
        for name in ("pool-a", "pool-b"):
            topo, nodes = make_tpu_pool(name, dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        reports = simulate_plan(source_api=c.api, jobs=[
            {"members": 16, "slices": 2, "slice_shape": "4x4x4",
             "accelerator": "tpu-v5p", "chips_per_pod": 4},
            {"members": 16, "slice_shape": "4x4x4",
             "accelerator": "tpu-v5p", "chips_per_pod": 4},
        ], timeout_s=25)
        assert reports[0].feasible and len(reports[0].placements) == 32
        assert not reports[1].feasible     # the set took both pools
