"""race-smoke: the tpuverify gate `make tier1` runs.

Three halves, all on deterministic seeds and a bounded schedule budget
(<60 s total by contract — the budget meta-test enforces it):

1. the LIVE-TREE scenarios (the critical-section pairs ROADMAP item 1's
   sharded dispatch will stress) must survive their full schedule budget
   with zero invariant violations and zero lock-discipline (C7)
   violations;
2. NON-VACUITY: the explorer must FIND the deliberately seeded bugs
   (lost-update, broken arming guard) within budget — a gate that cannot
   fail cannot gate;
3. REPLAY: a failure artifact must reproduce deterministically through
   ``python -m tpusched.cmd.replay`` from the artifact alone.
"""
from __future__ import annotations

import time

import pytest

from tpusched import verify
from tpusched.cmd import replay as replay_cmd
from tpusched.util import locking

SEED = 20260803            # deterministic: today's gate is tomorrow's too
BUDGET = 48                # schedules per scenario


@pytest.fixture(autouse=True)
def _clean_lock_state():
    yield
    locking.set_verify_hook(None)
    locking.set_debug(False)
    locking.recorder().reset()


EX = verify.Explorer()


@pytest.mark.parametrize("name", sorted(verify.LIVE_SCENARIOS))
def test_live_scenario_survives_schedule_budget(name):
    rep = EX.explore(verify.SCENARIOS[name], seed=SEED, schedules=BUDGET,
                     stop_on_failure=True)
    assert rep.failures == 0, (
        f"{name}: {rep.first_failure['failure']}\n"
        f"replay with: python -m tpusched.cmd.replay <artifact> after "
        f"saving {rep.first_failure}")
    assert rep.schedules == BUDGET
    assert rep.distinct_traces >= 2, (
        f"{name}: only {rep.distinct_traces} distinct interleaving(s) "
        f"explored — the scenario's yield points have gone dark")


@pytest.mark.parametrize("name", sorted(verify.SELFCHECK_BUGGY))
def test_seeded_bug_is_found(name):
    rep = EX.explore(verify.SCENARIOS[name], seed=SEED, schedules=120)
    assert rep.failures == 1, (
        f"{name}: the explorer missed a DELIBERATE bug in {rep.schedules} "
        f"schedules — the race-smoke gate is vacuous")
    verify.validate_artifact(rep.first_failure)


def test_seeded_bug_replays_from_artifact_alone(tmp_path):
    """The acceptance criterion verbatim: an injected failure reproduces
    deterministically via cmd.replay from its schedule artifact alone."""
    rep = EX.explore(verify.SCENARIOS["selfcheck-lost-update"],
                     seed=SEED, schedules=120)
    assert rep.first_failure is not None
    path = tmp_path / "failure.json"
    verify.dump_artifact(rep.first_failure, str(path))
    # fresh process-level entry point, artifact file only
    assert replay_cmd.main([str(path)]) == 0
    assert replay_cmd.main([str(path), "--json"]) == 0


def test_replay_cli_divergence_is_a_mismatch(tmp_path):
    """A stale artifact (the code moved, the recorded schedule no longer
    exists) must exit 1, not claim REPRODUCED: the replayed failure is a
    ReplayDivergence, not the recorded one."""
    rep = EX.explore(verify.SCENARIOS["selfcheck-lost-update"],
                     seed=SEED, schedules=120)
    art = dict(rep.first_failure)
    art["decisions"] = art["decisions"][:1]
    path = tmp_path / "stale.json"
    verify.dump_artifact(art, str(path))
    assert replay_cmd.main([str(path)]) == 1


def test_replay_cli_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{\"version\": 1}")
    assert replay_cmd.main([str(bad)]) == 2
    missing = tmp_path / "nope.json"
    assert replay_cmd.main([str(missing)]) == 2


def test_race_smoke_fits_its_budget():
    """One representative scenario timed: the whole gate (7 live + 2
    seeded + replay) must stay under 60 s; a single scenario budget has
    to clear its share with a wide margin."""
    t0 = time.monotonic()
    EX.explore(verify.SCENARIOS["informer-delete-resync"], seed=SEED,
               schedules=BUDGET, stop_on_failure=True)
    dt = time.monotonic() - t0
    assert dt < 8.0, (
        f"one scenario budget took {dt:.1f}s — race-smoke would blow "
        f"its 60 s tier1 budget")
