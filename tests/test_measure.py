"""CPU smoke of the measurement harness (jaxbridge/measure.py): the bench's
on-chip lines run exactly once, unattended, when the TPU tier fires — a
Python-level bug there wastes the capture. Every harness entry point the
bench calls is exercised here at tiny scale (numbers are meaningless on
CPU; shapes, dtypes, accounting and return contracts are not)."""
import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpusched.jaxbridge import measure as M  # noqa: E402
from tpusched.jaxbridge.workload import ModelConfig  # noqa: E402

TINY = ModelConfig.tiny()


def test_measure_train_step_contract():
    per, tf, mfu = M.measure_train_step(TINY, batch=2, k1=1, k2=2,
                                        repeats=1)
    assert per > 0 and tf > 0
    assert mfu is None or 0 <= mfu   # no peak table for CPU devices


@pytest.mark.parametrize("mu_dtype", [None, jnp.bfloat16])
def test_measure_adamw_train_step_contract(mu_dtype):
    """Both optimizer-state policies the bench uses: classic f32 mu
    (default) and the pure-bf16 policy the 1.55B line passes."""
    per, tf, mfu, note = M.measure_adamw_train_step(
        TINY, batch=1, k1=1, k2=2, repeats=1, mu_dtype=mu_dtype)
    assert per > 0 and tf > 0
    assert "params" in note and "remat" in note


def test_measure_decode_contract():
    # a wide k-spread: the slope (time(k2)-time(k1))/(k2-k1) needs the
    # chain-length delta to dominate scheduler noise on a loaded CPU —
    # a 2-step window can measure negative there
    cfg = dataclasses.replace(TINY, seq=64)
    tok_s, mean_ctx = M.measure_decode(cfg, batch=2, prompt_len=8,
                                       k1=4, k2=36, repeats=3)
    assert tok_s > 0
    assert 8 <= mean_ctx <= 64


def test_decode_bytes_accounting():
    """The corrected accounting (VERDICT r4 weak #2): the embedding table
    is a gather, not a stream — int8 KV halves only the KV term, and the
    MoE path charges every expert stack."""
    cfg = ModelConfig.llama_like(seq=256)
    base = M.decode_bytes_per_token(cfg, batch=8, mean_ctx=192)
    # table-as-streamed would add ~v*d*itemsize on top
    wrong = base + cfg.vocab * cfg.d_model * jnp.dtype(cfg.dtype).itemsize
    assert base < wrong
    i8 = M.decode_bytes_per_token(
        dataclasses.replace(cfg, kv_cache_dtype="int8"), batch=8,
        mean_ctx=192)
    assert i8 < base   # quantized cache streams fewer bytes
    moe = dataclasses.replace(cfg, n_experts=4, moe_top_k=2)
    assert M.decode_bytes_per_token(moe, batch=8, mean_ctx=192) > base


def test_train_step_flops_scales_with_tokens():
    f1 = M.train_step_flops(TINY, batch=1)
    f2 = M.train_step_flops(TINY, batch=2)
    assert f2 == 2 * f1
    note = M.moe_flops_note(ModelConfig.mixtral_like(seq=64), batch=1)
    assert "dispatch" in note or "%" in note
