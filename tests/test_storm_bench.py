"""Arrival-storm bench smoke (ISSUE 7): the sustained-throughput scenario
runs to completion at CI scale, reports binds/sec + p99 pod-e2e, and its
machine-readable results artifact round-trips the schema validator.  The
validator itself gets negative tables — a schema check that accepts
garbage is a disabled gate wearing a green checkmark.
"""
from __future__ import annotations

import importlib
import json

import pytest

bench = importlib.import_module("bench")


@pytest.fixture(autouse=True)
def _fresh_results(monkeypatch):
    monkeypatch.setattr(bench, "_results_scenarios", {})
    monkeypatch.setattr(bench, "_gate_failures", [])
    monkeypatch.setattr(bench, "_results_workload", {})


def test_storm_smoke_runs_and_reports(tmp_path):
    """Scaled-down storm (2 pools / 128 hosts, ~2s of continuous mixed
    arrivals): it must sustain throughput, drain without wedging a gang,
    and produce a schema-valid artifact."""
    r = bench.run_storm_once(pools=2, duration_s=2.0, max_pending_pods=300,
                             seed=11, drain_timeout_s=90)
    assert r["binds"] > 0
    assert r["binds_per_sec"] > 0
    assert r["total_binds"] == r["submitted_pods"]   # drained, no wedge
    assert r["pod_e2e_events"] == r["submitted_pods"]
    assert r["pod_e2e_p99_s"] >= r["pod_e2e_p50_s"] > 0
    assert r["hosts"] == 128
    assert r["cycles"] >= r["total_binds"]

    bench._record_scenario(
        "arrival_storm", "throughput",
        binds_per_sec=r["binds_per_sec"], pod_e2e_p50_s=r["pod_e2e_p50_s"],
        pod_e2e_p99_s=r["pod_e2e_p99_s"], runs=1)
    out = tmp_path / "results.json"
    bench.write_results_artifact(str(out))
    assert bench._gate_failures == []
    doc = json.loads(out.read_text())
    assert bench.validate_results_artifact(doc) == []
    assert doc["scenarios"]["arrival_storm"]["binds_per_sec"] > 0
    for k in ("python", "platform", "cpu_count", "timestamp"):
        assert k in doc["environment"]


def test_sharded_storm_smoke_runs_and_reports(tmp_path):
    """ISSUE 11 CI smoke: the sharded dispatch core (shards=4) sustains
    the scaled-down storm, drains without wedging a gang, and its record
    lands as the ``arrival_storm_sharded`` scenario — schema-valid, with
    the lane count stamped (the validator rejects a sharded record that
    does not name its shards)."""
    r = bench.run_storm_once(pools=2, duration_s=2.0, max_pending_pods=300,
                             seed=11, drain_timeout_s=90, shards=4)
    assert r["binds"] > 0
    assert r["binds_per_sec"] > 0
    assert r["total_binds"] == r["submitted_pods"]   # drained, no wedge
    assert r["pod_e2e_events"] == r["submitted_pods"]

    bench._record_scenario(
        "arrival_storm_sharded", "throughput", shards=4,
        binds_per_sec=r["binds_per_sec"], pod_e2e_p50_s=r["pod_e2e_p50_s"],
        pod_e2e_p99_s=r["pod_e2e_p99_s"], runs=1)
    out = tmp_path / "results.json"
    bench.write_results_artifact(str(out))
    assert bench._gate_failures == []
    doc = json.loads(out.read_text())
    assert bench.validate_results_artifact(doc) == []
    assert doc["scenarios"]["arrival_storm_sharded"]["shards"] == 4
    # negative: a sharded record without its lane count is rejected
    doc["scenarios"]["arrival_storm_sharded"].pop("shards")
    probs = bench.validate_results_artifact(doc)
    assert any("arrival_storm_sharded.shards" in p for p in probs)


def test_quota_storm_smoke_runs_and_reports(tmp_path):
    """ISSUE 14 CI smoke: the QUOTA-ENABLED storm (2 ElasticQuota teams,
    shards=4) sustains the scaled-down storm with the quota-aware
    optimistic commit protocol — quota'd binds land on SHARD lanes (the
    pre-14 router serialized them wholesale), the run drains without
    wedging, and the record lands as ``arrival_storm_quota`` —
    schema-valid, with the serialized-arm baseline and conflict
    attribution stamped (the validator rejects a record missing either)."""
    r = bench.run_storm_once(pools=2, duration_s=2.0, max_pending_pods=300,
                             seed=11, drain_timeout_s=90, shards=4,
                             quota_teams=2)
    assert r["binds"] > 0
    assert r["total_binds"] == r["submitted_pods"]   # drained, no wedge
    assert r["quota_teams"] == 2 and not r["quota_serialized"]
    assert r["dispatch"] is not None
    assert r["dispatch"]["shard_binds"] > 0, (
        f"no quota'd bind used a shard lane: {r['dispatch']}")
    # the serialized baseline arm still works (the A/B control)
    rs = bench.run_storm_once(pools=2, duration_s=1.0,
                              max_pending_pods=300, seed=11,
                              drain_timeout_s=90, shards=4,
                              quota_teams=2, quota_serialize=True)
    assert rs["total_binds"] == rs["submitted_pods"]
    assert rs["dispatch"]["shard_binds"] == 0, (
        f"legacy serialize arm bound on shard lanes: {rs['dispatch']}")

    bench._record_scenario(
        "arrival_storm_quota", "throughput", shards=4, quota_teams=2,
        binds_per_sec=r["binds_per_sec"], pod_e2e_p50_s=r["pod_e2e_p50_s"],
        pod_e2e_p99_s=r["pod_e2e_p99_s"], runs=1,
        serialized_binds_per_sec=rs["binds_per_sec"],
        quota_conflicts=r["dispatch"]["quota_conflicts"],
        escalations=r["dispatch"]["escalations"])
    out = tmp_path / "results.json"
    bench.write_results_artifact(str(out))
    assert bench._gate_failures == []
    doc = json.loads(out.read_text())
    assert bench.validate_results_artifact(doc) == []
    # negative tables: a quota record must name its anatomy
    for field in ("serialized_binds_per_sec", "quota_teams",
                  "quota_conflicts", "escalations", "shards"):
        broken = json.loads(out.read_text())
        broken["scenarios"]["arrival_storm_quota"].pop(field)
        probs = bench.validate_results_artifact(broken)
        assert any(f"arrival_storm_quota.{field}" in p for p in probs), (
            field, probs)


def test_latency_lines_record_into_artifact():
    bench.emit_latency("synthetic scenario", [0.1, 0.2, 0.3], "synth_p99")
    doc = bench.build_results_artifact()
    assert bench.validate_results_artifact(doc) == []
    rec = doc["scenarios"]["synth_p99"]
    assert rec["kind"] == "latency"
    assert rec["min_s"] == 0.1 and rec["n"] == 3


@pytest.mark.parametrize("mutate,expect", [
    (lambda d: d.pop("environment"), "environment missing"),
    (lambda d: d.update(schema_version=99), "schema_version"),
    (lambda d: d["scenarios"].update(bad={"kind": "nonsense"}),
     "unknown kind"),
    (lambda d: d["scenarios"]["x"].pop("p99_s"), "x.p99_s"),
    (lambda d: d["scenarios"]["x"].update(p99_s="fast"), "x.p99_s"),
    (lambda d: d.update(scenarios={}), "scenarios missing/empty"),
])
def test_validator_rejects_malformed_artifacts(mutate, expect):
    bench.emit_latency("x scenario", [0.1, 0.2], "x")
    doc = bench.build_results_artifact()
    assert bench.validate_results_artifact(doc) == []
    mutate(doc)
    probs = bench.validate_results_artifact(doc)
    assert probs and any(expect in p for p in probs), probs


def test_workload_stamp_rides_in_environment():
    """ISSUE 9: the environment block carries the workload identity —
    storm seeds + arrival-stream hash (or the trace path under --replay)
    — so a BENCH_RESULTS.json names the exact problem it measured."""
    bench.emit_latency("x scenario", [0.1, 0.2], "x")
    bench._record_workload(storm_seeds=[0, 1, 2],
                           workload_hash="ab12cd34ef56ab78")
    doc = bench.build_results_artifact()
    assert bench.validate_results_artifact(doc) == []
    wl = doc["environment"]["workload"]
    assert wl["storm_seeds"] == [0, 1, 2]
    assert wl["workload_hash"] == "ab12cd34ef56ab78"

    bench._record_workload(replay_trace="/some/trace")
    doc = bench.build_results_artifact()
    assert bench.validate_results_artifact(doc) == []
    assert doc["environment"]["workload"]["replay_trace"] == "/some/trace"


def test_storm_run_reports_workload_hash():
    """run_storm_once stamps its own seed + stream hash, and the same seed
    reproduces the same stream prefix (hash equality holds when the run
    submitted the same units)."""
    r = bench.run_storm_once(pools=1, duration_s=0.5, max_pending_pods=60,
                             seed=41, drain_timeout_s=60)
    assert r["seed"] == 41
    assert isinstance(r["workload_hash"], str) and len(r["workload_hash"]) == 16


@pytest.mark.parametrize("mutate,expect", [
    (lambda d: d["environment"].update(workload="not-a-dict"),
     "workload: not an object"),
    (lambda d: d["environment"].update(workload={"storm_seeds": [1]}),
     "workload_hash"),
    (lambda d: d["environment"].update(
        workload={"workload_hash": "abc", "storm_seeds": ["x"]}),
     "storm_seeds"),
    # an empty seed list satisfies a vacuous all() but names no
    # reproducible workload — the half-stamped artifact the validator
    # exists to reject
    (lambda d: d["environment"].update(
        workload={"workload_hash": "abc", "storm_seeds": []}),
     "storm_seeds"),
    (lambda d: d["environment"].update(
        workload={"workload_hash": "abc", "replay_trace": ""}),
     "replay_trace"),
    (lambda d: d["environment"].update(workload={"workload_hash": "abc"}),
     "neither storm_seeds nor replay_trace"),
])
def test_validator_rejects_malformed_workload_stamps(mutate, expect):
    bench.emit_latency("x scenario", [0.1, 0.2], "x")
    doc = bench.build_results_artifact()
    assert bench.validate_results_artifact(doc) == []
    mutate(doc)
    probs = bench.validate_results_artifact(doc)
    assert probs and any(expect in p for p in probs), probs


def test_throughput_scenario_schema_requirements():
    bench._record_scenario("arrival_storm", "throughput",
                           binds_per_sec=100.0, pod_e2e_p50_s=0.5,
                           pod_e2e_p99_s=1.5, runs=3)
    assert bench.validate_results_artifact(
        bench.build_results_artifact()) == []
    bench._record_scenario("arrival_storm", "throughput",
                           binds_per_sec=True, pod_e2e_p50_s=0.5,
                           pod_e2e_p99_s=1.5, runs=3)
    probs = bench.validate_results_artifact(bench.build_results_artifact())
    assert any("binds_per_sec" in p for p in probs)


def test_storm_run_carries_fleet_goodput_stamp():
    """ISSUE 10: every storm run ingests in-band member goodput reports
    and stamps the aggregate — reports accepted, nothing silently shed,
    measured matrix cells (ROADMAP item 3's baseline column) — and the
    stamped scenario round-trips the v2 validator."""
    r = bench.run_storm_once(pools=1, duration_s=0.5, max_pending_pods=60,
                             seed=7, drain_timeout_s=60)
    fg = r["fleet_goodput"]
    assert fg["reports"] == r["submitted_pods"]    # one flush per member
    assert fg["reporting_members"] == r["submitted_pods"]  # cumulative,
    # not a racy window-edge census of not-yet-reaped members
    assert fg["shed"] == 0
    assert fg["matrix_cells"] >= 1                 # v5p cells measured
    assert fg["goodput_per_chip_mean"] > 0
    bench._record_scenario(
        "arrival_storm", "throughput",
        binds_per_sec=r["binds_per_sec"], pod_e2e_p50_s=r["pod_e2e_p50_s"],
        pod_e2e_p99_s=r["pod_e2e_p99_s"], runs=1, fleet_goodput=fg)
    assert bench.validate_results_artifact(
        bench.build_results_artifact()) == []
    # the control arm (reports off) stamps explicit zeros, still valid
    r0 = bench.run_storm_once(pools=1, duration_s=0.5,
                              max_pending_pods=60, seed=8,
                              drain_timeout_s=60, goodput_reports=False)
    assert r0["fleet_goodput"]["reports"] == 0


@pytest.mark.parametrize("mutate,expect", [
    (lambda d: d["scenarios"]["arrival_storm"].update(
        fleet_goodput="not-a-dict"), "fleet_goodput: not an object"),
    (lambda d: d["scenarios"]["arrival_storm"]["fleet_goodput"].pop(
        "reports"), "fleet_goodput.reports"),
    (lambda d: d["scenarios"]["arrival_storm"]["fleet_goodput"].update(
        goodput_per_chip_mean="fast"), "goodput_per_chip_mean"),
    (lambda d: d["scenarios"]["arrival_storm"]["fleet_goodput"].update(
        reporting_members=True), "fleet_goodput.reporting_members"),
    # the stamp belongs to throughput scenarios only
    (lambda d: d["scenarios"].update(lat={"kind": "latency", "p50_s": 1.0,
                                          "p99_s": 2.0, "min_s": 0.5,
                                          "n": 3, "fleet_goodput": {}}),
     "only throughput scenarios"),
])
def test_validator_rejects_malformed_fleet_goodput(mutate, expect):
    bench._record_scenario(
        "arrival_storm", "throughput",
        binds_per_sec=100.0, pod_e2e_p50_s=0.5, pod_e2e_p99_s=1.5, runs=3,
        fleet_goodput={"reports": 100, "shed": 0, "straggler_edges": 0,
                       "matrix_cells": 2, "goodput_per_chip_mean": 250.0,
                       "reporting_members": 12})
    doc = bench.build_results_artifact()
    assert bench.validate_results_artifact(doc) == []
    mutate(doc)
    probs = bench.validate_results_artifact(doc)
    assert probs and any(expect in p for p in probs), probs


def test_native_storm_smoke_runs_and_reports(tmp_path):
    """ISSUE 16 CI smoke: the scaled-down sharded storm through the
    native batched dispatch inner loop — kernel engaged (non-vacuity),
    in-cycle differential oracle on EVERY native cycle with zero
    mismatches, the pure-Python control arm stays native-free, and the
    record lands as ``arrival_storm_native`` — schema-v3-valid, with the
    baseline arm + oracle stamp enforced by negative tables."""
    import importlib
    native = importlib.import_module("tpusched.native")
    if not native.available():
        pytest.skip("native engine unavailable")
    r = bench.run_storm_once(pools=2, duration_s=2.0, max_pending_pods=300,
                             seed=11, drain_timeout_s=90, shards=4,
                             native=True, native_differential_period=1)
    assert r["binds"] > 0
    assert r["total_binds"] == r["submitted_pods"]   # drained, no wedge
    assert r["native"]["enabled"]
    assert r["native"]["cycles"] > 0, (
        f"native kernel never engaged: {r['native']}")
    assert r["native"]["pods"] > 0
    assert r["native"]["differential_mismatches"] == 0, (
        f"oracle caught the kernel: {r['native']}")
    # the pure-Python control arm must not touch the kernel
    rp = bench.run_storm_once(pools=2, duration_s=1.0,
                              max_pending_pods=300, seed=11,
                              drain_timeout_s=90, shards=4, native=False)
    assert rp["total_binds"] == rp["submitted_pods"]
    assert rp["native"]["cycles"] == 0, (
        f"python arm ran native cycles: {rp['native']}")

    bench._record_scenario(
        "arrival_storm_native", "throughput", shards=4,
        binds_per_sec=r["binds_per_sec"], pod_e2e_p50_s=r["pod_e2e_p50_s"],
        pod_e2e_p99_s=r["pod_e2e_p99_s"], runs=1,
        python_binds_per_sec=rp["binds_per_sec"],
        native_cycles=r["native"]["cycles"],
        native_pods=r["native"]["pods"],
        differential_cycles=r["native"]["cycles"],
        differential_mismatches=0)
    out = tmp_path / "results.json"
    bench.write_results_artifact(str(out))
    assert bench._gate_failures == []
    doc = json.loads(out.read_text())
    assert bench.validate_results_artifact(doc) == []
    assert doc["schema_version"] == 3
    # negative tables: the native record must carry its anatomy
    for field in ("python_binds_per_sec", "native_cycles",
                  "differential_cycles", "differential_mismatches"):
        broken = json.loads(out.read_text())
        broken["scenarios"]["arrival_storm_native"].pop(field)
        probs = bench.validate_results_artifact(broken)
        assert any(f"arrival_storm_native.{field}" in p for p in probs), (
            field, probs)
    # a nonzero mismatch count is rejected outright — the artifact must
    # never ship a native headline the oracle disagreed with
    broken = json.loads(out.read_text())
    broken["scenarios"]["arrival_storm_native"]["differential_mismatches"] = 2
    probs = bench.validate_results_artifact(broken)
    assert any("differential_mismatches" in p for p in probs)
    # a kernel that never ran is a fallback measurement, not a native one
    broken = json.loads(out.read_text())
    broken["scenarios"]["arrival_storm_native"]["native_cycles"] = 0
    probs = bench.validate_results_artifact(broken)
    assert any("native_cycles" in p for p in probs)


def test_fanout_storm_smoke_runs_and_reports(tmp_path):
    """ISSUE 16 CI smoke: the scaled-down storm with watch fan-out
    coalesced through the commit-order batcher — flush batches actually
    delivered, the run drains without a wedge, and the record lands as
    ``arrival_storm_fanout`` — schema-v3-valid, with the synchronous
    baseline + window + delivery proof enforced by negative tables."""
    r = bench.run_storm_once(pools=2, duration_s=2.0, max_pending_pods=300,
                             seed=11, drain_timeout_s=90, shards=4,
                             fanout_flush_ms=1.0)
    assert r["binds"] > 0
    assert r["total_binds"] == r["submitted_pods"]   # drained, no wedge
    assert r["fanout"] is not None
    assert r["fanout"]["mode"] == "batched"
    assert r["fanout"]["batches_delta"] >= 1, r["fanout"]
    assert r["fanout"]["events_delta"] >= r["total_binds"], (
        "fewer fan-out events than binds — deliveries leaked around "
        "the batcher")
    rs = bench.run_storm_once(pools=2, duration_s=1.0,
                              max_pending_pods=300, seed=11,
                              drain_timeout_s=90, shards=4)
    assert rs["fanout"] is None                      # synchronous control

    bench._record_scenario(
        "arrival_storm_fanout", "throughput", shards=4,
        binds_per_sec=r["binds_per_sec"], pod_e2e_p50_s=r["pod_e2e_p50_s"],
        pod_e2e_p99_s=r["pod_e2e_p99_s"], runs=1,
        flush_window_ms=1.0,
        sync_binds_per_sec=rs["binds_per_sec"],
        fanout_batches=r["fanout"]["batches_delta"],
        fanout_events=r["fanout"]["events_delta"])
    out = tmp_path / "results.json"
    bench.write_results_artifact(str(out))
    assert bench._gate_failures == []
    doc = json.loads(out.read_text())
    assert bench.validate_results_artifact(doc) == []
    # negative tables: the fan-out record must carry its anatomy
    for field in ("sync_binds_per_sec", "flush_window_ms",
                  "fanout_batches"):
        broken = json.loads(out.read_text())
        broken["scenarios"]["arrival_storm_fanout"].pop(field)
        probs = bench.validate_results_artifact(broken)
        assert any(f"arrival_storm_fanout.{field}" in p for p in probs), (
            field, probs)
    # a zero-batch record measured synchronous dispatch in costume
    broken = json.loads(out.read_text())
    broken["scenarios"]["arrival_storm_fanout"]["fanout_batches"] = 0
    probs = bench.validate_results_artifact(broken)
    assert any("fanout_batches" in p for p in probs)
