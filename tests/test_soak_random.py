"""Randomized soak: seeded chaos over the full-stack profile with invariant
checks at every quiesce point.

The reference's race story rests on Go's race detector running over its
integration tier; the analog here is adversarial interleaving — a seeded
stream of gang arrivals, deletions, and node cordons against the live
scheduler, with the safety invariants that must survive ANY interleaving
asserted after each quiesce:

  I1  no host is ever oversubscribed (sum of resident pods' chips ≤ chips);
  I2  chip-index annotations on a host are pairwise disjoint;
  I3  at quiesce every gang is all-or-nothing: either ≥ min_member bound or
      zero bound (the Permit barrier's whole contract);
  I4  every bound slice-gang member landed in exactly one pool, with a
      coordinate annotation.

Failures reproduce from the printed seed."""
import random

from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import full_stack_profile
from tpusched.api.scheduling import POD_GROUP_LABEL
from tpusched.plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from tpusched.plugins.tpuslice import CHIP_INDEX_ANNOTATION
from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                              make_pod_group, make_tpu_pool, wait_until)

SEED = 20260730          # default; the test is parametrized over several
ROUNDS = 6
SHAPES = ["2x2x1", "2x2x2", "4x4x4"]          # 4 / 8 / 64 chips
MEMBERS = {"2x2x1": 1, "2x2x2": 2, "4x4x4": 16}


def _quiesced(c) -> bool:
    """No pod is mid-flight: everything is either bound or parked."""
    counts = c.scheduler.queue.pending_counts()
    return counts["active"] == 0


def _check_invariants(c, gangs):
    chips_per_host = 4
    by_node = {}
    for p in c.api.list(srv.PODS):
        if p.spec.node_name:
            by_node.setdefault(p.spec.node_name, []).append(p)
    for node, pods in by_node.items():
        used = sum(int(pp.spec.containers[0].limits.get(TPU, 0))
                   for pp in pods)
        assert used <= chips_per_host, \
            f"I1 violated on {node}: {used} chips (seed {SEED})"
        indexes = []
        for pp in pods:
            ann = pp.meta.annotations.get(CHIP_INDEX_ANNOTATION, "")
            indexes.extend(i for i in ann.split(",") if i)
        assert len(indexes) == len(set(indexes)), \
            f"I2 violated on {node}: {indexes} (seed {SEED})"
    for full, (members, slice_shape) in gangs.items():
        ns, name = full.split("/")
        bound = [p for p in c.api.list(srv.PODS, ns)
                 if p.meta.labels.get(POD_GROUP_LABEL) == name
                 and p.spec.node_name]
        assert len(bound) == 0 or len(bound) >= members, \
            f"I3 violated for {full}: {len(bound)}/{members} (seed {SEED})"
        if slice_shape:
            pools = {p.meta.annotations.get(POOL_ANNOTATION) for p in bound}
            assert len(pools) <= 1, \
                f"I4 violated for {full}: pools {pools} (seed {SEED})"
            assert all(p.meta.annotations.get(COORD_ANNOTATION)
                       for p in bound), f"I4 coords missing (seed {SEED})"


import pytest


@pytest.mark.parametrize("seed", [20260730, 42, 999])
def test_randomized_soak_invariants(seed):
    """seed 42 is the one that caught the stranded-gang bug (a slice-
    preemption window evicting 1 of 16 — now vetoed by the minMember
    disruption floor); it stays pinned here as a regression."""
    global SEED
    SEED = seed
    rng = random.Random(seed)
    with TestCluster(profile=full_stack_profile(permit_wait_s=6,
                                                denied_s=1)) as c:
        for i in range(2):
            topo, nodes = make_tpu_pool(f"pool-{i}", dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        for team in ("team-a", "team-b"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 32}, max={TPU: 128}))

        gangs = {}                     # full name → (members, slice_shape)
        counter = 0
        for rnd in range(ROUNDS):
            for _ in range(rng.randint(2, 4)):
                op = rng.random()
                if op < 0.6 or not gangs:          # submit a gang
                    shape = rng.choice(SHAPES)
                    members = MEMBERS[shape]
                    team = rng.choice(("team-a", "team-b"))
                    name = f"g{counter}"
                    counter += 1
                    c.api.create(srv.POD_GROUPS, make_pod_group(
                        name, namespace=team, min_member=members,
                        tpu_slice_shape=shape, tpu_accelerator="tpu-v5p"))
                    c.create_pods([
                        make_pod(f"{name}-{j}", namespace=team,
                                 pod_group=name, limits={TPU: 4})
                        for j in range(members)])
                    gangs[f"{team}/{name}"] = (members, shape)
                else:                               # delete a random gang
                    full = rng.choice(sorted(gangs))
                    ns, name = full.split("/")
                    for p in list(c.api.list(srv.PODS, ns)):
                        if p.meta.labels.get(POD_GROUP_LABEL) == name:
                            try:
                                c.api.delete(srv.PODS, p.meta.key)
                            except srv.NotFound:
                                pass
                    try:
                        c.api.delete(srv.POD_GROUPS, full)
                    except srv.NotFound:
                        pass
                    del gangs[full]
            assert wait_until(lambda: _quiesced(c), timeout=20), \
                f"round {rnd} did not quiesce (seed {SEED})"
            # small settle for in-flight binds to confirm
            import time
            time.sleep(0.3)
            _check_invariants(c, gangs)
