"""Randomized soak: seeded chaos over the full-stack profile with invariant
checks at every quiesce point.

The reference's race story rests on Go's race detector running over its
integration tier; the analog here is adversarial interleaving — a seeded
stream of gang arrivals, deletions, and node cordons against the live
scheduler, with the safety invariants that must survive ANY interleaving
asserted after each quiesce:

  I1  no host is ever oversubscribed (sum of resident pods' chips ≤ chips);
  I2  chip-index annotations on a host are pairwise disjoint;
  I3  at quiesce every gang is all-or-nothing: either ≥ min_member bound or
      zero bound (the Permit barrier's whole contract);
  I4  every bound slice-gang member landed in exactly one pool, with a
      coordinate annotation;
  I5  an ATOMIC multislice set (multislice_set_size declared) is
      all-or-nothing across the whole set at quiesce: its surviving member
      gangs are either all fully bound or none bound — even when a sibling
      slice was deleted out from under the barrier mid-flight.

Failures reproduce from the printed seed."""
import random

from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import full_stack_profile
from tpusched.config.types import MultiSliceArgs
from tpusched.api.scheduling import POD_GROUP_LABEL
from tpusched.plugins.topologymatch import COORD_ANNOTATION, POOL_ANNOTATION
from tpusched.plugins.tpuslice import CHIP_INDEX_ANNOTATION
from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                              make_pod_group, make_tpu_pool, wait_until)

SEED = 20260730          # default; the test is parametrized over several
ROUNDS = 6
SHAPES = ["2x2x1", "2x2x2", "4x4x4"]          # 4 / 8 / 64 chips
MEMBERS = {"2x2x1": 1, "2x2x2": 2, "4x4x4": 16}


def _quiesced(c) -> bool:
    """No pod is mid-cycle. Neither the queues nor the permit barrier are
    required to be empty: infeasible work retrying on its denial windows —
    and infeasible SETS cycling reserve → barrier-timeout → teardown, out
    of phase with each other — is the steady state of a contended
    scheduler; total silence never happens under pressure. Mid-bind-burst
    reads (a barrier resolving as we look) are filtered by the
    consecutive-clean-reads stability requirement below instead."""
    return c.scheduler.queue.pending_counts()["active"] == 0


def _eventual_violation(c, gangs, sets=None):
    """I3/I4/I5 checker — returns the first violation as a string, None if
    clean. These are EVENTUAL invariants: a barrier resolution racing a
    per-pod permit timeout can transiently leave a gang or set partially
    bound (upstream coscheduling has the same per-pod window); the
    contract is that the system HEALS — the freed reservations re-admit
    the short members. The soak therefore requires these to hold stably
    within a bounded healing window, not at every instant."""
    for full, (members, slice_shape) in gangs.items():
        ns, name = full.split("/")
        bound = [p for p in c.api.list(srv.PODS, ns)
                 if p.meta.labels.get(POD_GROUP_LABEL) == name
                 and p.spec.node_name]
        if not (len(bound) == 0 or len(bound) >= members):
            return f"I3: {full}: {len(bound)}/{members} bound"
        if slice_shape:
            pools = {p.meta.annotations.get(POOL_ANNOTATION) for p in bound}
            if len(pools) > 1:
                return f"I4: {full}: pools {pools}"
            if not all(p.meta.annotations.get(COORD_ANNOTATION)
                       for p in bound):
                return f"I4: {full}: coords missing"
    for set_name, members_of_set in (sets or {}).items():
        fully = 0
        alive = 0
        for full in members_of_set:
            if full not in gangs:
                continue               # deleted mid-flight
            alive += 1
            members, _ = gangs[full]
            ns, name = full.split("/")
            bound = [p for p in c.api.list(srv.PODS, ns)
                     if p.meta.labels.get(POD_GROUP_LABEL) == name
                     and p.spec.node_name]
            if len(bound) >= members:
                fully += 1
        if fully not in (0, alive):
            return f"I5: set {set_name}: {fully}/{alive} member gangs bound"
    return None


def _check_hard_invariants(c):
    """I1/I2 hold at EVERY instant — no transient may oversubscribe a host
    or collide chip indexes (annotations land at Reserve, before binds are
    visible, so a mid-burst read can never show a bound pod without its
    chips)."""
    chips_per_host = 4
    by_node = {}
    for p in c.api.list(srv.PODS):
        if p.spec.node_name:
            by_node.setdefault(p.spec.node_name, []).append(p)
    for node, pods in by_node.items():
        used = sum(int(pp.spec.containers[0].limits.get(TPU, 0))
                   for pp in pods)
        assert used <= chips_per_host, \
            f"I1 violated on {node}: {used} chips (seed {SEED})"
        indexes = []
        for pp in pods:
            ann = pp.meta.annotations.get(CHIP_INDEX_ANNOTATION, "")
            indexes.extend(i for i in ann.split(",") if i)
        assert len(indexes) == len(set(indexes)), \
            f"I2 violated on {node}: {indexes} (seed {SEED})"


import pytest


@pytest.mark.parametrize("seed,with_sets", [
    # original op stream, byte-for-byte: seed 42 is the one that caught
    # the stranded-gang bug (a slice-preemption window evicting 1 of 16 —
    # now vetoed by the minMember disruption floor). Adding new op KINDS
    # would reinterpret these seeds' RNG draws and un-pin the regression,
    # so the pinned seeds run with the set branch disabled.
    (20260730, False), (42, False), (999, False),
    # set-enabled stream: seed 7 caught the SET disruption hole (window
    # preemption half-killing a bound atomic set — atomic_set_eviction_
    # vetoed); pinned with sets on.
    (7, True), (20260731, True), (104, True),
])
def test_randomized_soak_invariants(seed, with_sets):
    global SEED
    SEED = seed
    rng = random.Random(seed)
    profile = full_stack_profile(permit_wait_s=6, denied_s=1)
    profile.plugin_args["MultiSlice"] = MultiSliceArgs(
        set_schedule_timeout_seconds=4,
        denied_set_expiration_time_seconds=1)
    with TestCluster(profile=profile) as c:
        for i in range(2):
            topo, nodes = make_tpu_pool(f"pool-{i}", dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        for team in ("team-a", "team-b"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"{team}-quota", team, min={TPU: 32}, max={TPU: 128}))

        gangs = {}                     # full name → (members, slice_shape)
        sets = {}                      # set name → [gang full names]
        counter = 0
        for rnd in range(ROUNDS):
            for _ in range(rng.randint(2, 4)):
                op = rng.random()
                if with_sets and ((op < 0.2 and gangs) or op >= 0.8):
                    # submit an ATOMIC 2-slice set (small slices so the
                    # fleet can usually hold both)
                    set_name = f"set{counter}"
                    counter += 1
                    team = rng.choice(("team-a", "team-b"))
                    members_of_set = []
                    for idx in range(2):
                        name = f"{set_name}-s{idx}"
                        c.api.create(srv.POD_GROUPS, make_pod_group(
                            name, namespace=team, min_member=2,
                            tpu_slice_shape="2x2x2",
                            tpu_accelerator="tpu-v5p",
                            multislice_set=set_name, multislice_index=idx,
                            multislice_set_size=2))
                        c.create_pods([
                            make_pod(f"{name}-{j}", namespace=team,
                                     pod_group=name, limits={TPU: 4})
                            for j in range(2)])
                        full = f"{team}/{name}"
                        gangs[full] = (2, "2x2x2")
                        members_of_set.append(full)
                    sets[set_name] = members_of_set
                elif op < 0.6 or not gangs:        # submit a gang
                    shape = rng.choice(SHAPES)
                    members = MEMBERS[shape]
                    team = rng.choice(("team-a", "team-b"))
                    name = f"g{counter}"
                    counter += 1
                    c.api.create(srv.POD_GROUPS, make_pod_group(
                        name, namespace=team, min_member=members,
                        tpu_slice_shape=shape, tpu_accelerator="tpu-v5p"))
                    c.create_pods([
                        make_pod(f"{name}-{j}", namespace=team,
                                 pod_group=name, limits={TPU: 4})
                        for j in range(members)])
                    gangs[f"{team}/{name}"] = (members, shape)
                else:                               # delete a random gang
                    full = rng.choice(sorted(gangs))
                    ns, name = full.split("/")
                    for p in list(c.api.list(srv.PODS, ns)):
                        if p.meta.labels.get(POD_GROUP_LABEL) == name:
                            try:
                                c.api.delete(srv.PODS, p.meta.key)
                            except srv.NotFound:
                                pass
                    try:
                        c.api.delete(srv.POD_GROUPS, full)
                    except srv.NotFound:
                        pass
                    del gangs[full]
            assert wait_until(lambda: _quiesced(c), timeout=25), \
                f"round {rnd} did not quiesce (seed {SEED})"
            # hard invariants hold at every instant; eventual ones must
            # hold STABLY within the healing window (two consecutive clean
            # reads 0.3s apart, re-quiesced in between)
            import time

            def _stable_clean():
                _check_hard_invariants(c)
                if not _quiesced(c) or _eventual_violation(c, gangs, sets):
                    return False
                time.sleep(0.3)
                return (_quiesced(c)
                        and _eventual_violation(c, gangs, sets) is None)
            if not wait_until(_stable_clean, timeout=25, interval=0.2):
                _check_hard_invariants(c)
                violation = _eventual_violation(c, gangs, sets)
                raise AssertionError(
                    f"round {rnd}: invariants never stabilized "
                    f"(seed {SEED}): {violation}")
