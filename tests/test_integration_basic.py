"""Integration tier (envtest analog): real scheduler against the in-memory API
server with fabricated nodes. Covers BASELINE eval config #1: a 1-pod
google.com/tpu Filter pass on a CPU-only-emulated TPU node."""
import time

from tpusched.api.resources import CPU, TPU, TPU_MEMORY, make_resources
from tpusched.apiserver import server as srv
from tpusched.plugins.tpuslice import CHIP_INDEX_ANNOTATION
from tpusched.testing import TestCluster, make_node, make_pod, make_tpu_node


def test_single_tpu_pod_schedules():
    with TestCluster() as c:
        c.add_nodes([make_node("cpu-node"), make_tpu_node("tpu-node")])
        pod = make_pod("jax-worker", limits={TPU: 4},
                       requests=make_resources(cpu=8, memory="16Gi"))
        c.create_pods([pod])
        assert c.wait_for_pods_scheduled([pod.key])
        bound = c.pod(pod.key)
        assert bound.spec.node_name == "tpu-node"
        assert bound.meta.annotations[CHIP_INDEX_ANNOTATION] == "0,1,2,3"


def test_fractional_pods_pack_one_chip():
    with TestCluster() as c:
        c.add_nodes([make_tpu_node("tpu-node")])
        pods = [make_pod(f"frac-{i}", limits={TPU_MEMORY: 10 * 1024})
                for i in range(3)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods])
        indexes = {c.pod(p.key).meta.annotations[CHIP_INDEX_ANNOTATION]
                   for p in pods}
        assert indexes == {"0"}  # bin-pack keeps them on one chip


def test_unschedulable_pod_stays_pending_then_fits_after_node_add():
    with TestCluster() as c:
        c.add_nodes([make_node("cpu-node")])
        pod = make_pod("needs-tpu", limits={TPU: 1})
        c.create_pods([pod])
        assert c.wait_for_pods_unscheduled([pod.key], hold=0.4)
        c.add_nodes([make_tpu_node("late-tpu")])
        assert c.wait_for_pods_scheduled([pod.key], timeout=15)


def test_chip_exhaustion_blocks_fifth_pod():
    with TestCluster() as c:
        c.add_nodes([make_tpu_node("tpu-node", chips=4)])
        pods = [make_pod(f"w{i}", limits={TPU: 1}) for i in range(4)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods])
        # all four chips distinct
        assert sorted(c.pod(p.key).meta.annotations[CHIP_INDEX_ANNOTATION]
                      for p in pods) == ["0", "1", "2", "3"]
        extra = make_pod("w4", limits={TPU: 1})
        c.create_pods([extra])
        assert c.wait_for_pods_unscheduled([extra.key], hold=0.4)
        # deleting a bound pod frees its chip and unsticks the waiter
        c.api.delete(srv.PODS, pods[0].key)
        assert c.wait_for_pods_scheduled([extra.key], timeout=15)


def test_priority_order_respected():
    with TestCluster() as c:
        # no nodes yet: both pods queue; high priority must bind first
        lo = make_pod("lo", limits={TPU: 4}, priority=1)
        hi = make_pod("hi", limits={TPU: 4}, priority=100)
        c.create_pods([lo, hi])
        time.sleep(0.3)
        c.add_nodes([make_tpu_node("tpu-node", chips=4)])
        assert c.wait_for_pods_scheduled([hi.key])
        assert c.pod(hi.key).spec.node_name == "tpu-node"
        assert not c.pod_scheduled(lo.key)
