"""Defrag controller (controllers/defrag.py): opt-in, consent-gated
actuation of shadow-verified migration plans. The contract under test:
nothing moves without the consent annotation, plans are verified on a
shadow with the blocked gang's OWN pods, and after actuation everyone —
target and migrant — ends up bound by the real scheduler."""
import time

from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.controllers.defrag import (ALLOW_MIGRATION_ANNOTATION,
                                         DefragController)
from tpusched.plugins.topologymatch import POOL_ANNOTATION
from tpusched.config.profiles import tpu_gang_profile
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool, wait_until)


def _cluster():
    return TestCluster(profile=tpu_gang_profile(permit_wait_s=10, denied_s=1))


def _pool(c, name, dims=(4, 4, 4)):
    topo, nodes = make_tpu_pool(name, dims=dims)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)


def _gang(c, name, shape, members, consent=False, wait=True):
    pg = make_pod_group(name, min_member=members, tpu_slice_shape=shape,
                        tpu_accelerator="tpu-v5p")
    if consent:
        pg.meta.annotations[ALLOW_MIGRATION_ANNOTATION] = "true"
    c.api.create(srv.POD_GROUPS, pg)
    ps = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
          for i in range(members)]
    c.create_pods(ps)
    if wait:
        assert c.wait_for_pods_scheduled([p.key for p in ps], timeout=30)
    return ps


def _fragmented_cluster(c, consent=True):
    """pool-a fragmented by a small consenting gang; rehome pool sized for
    it; a whole-pool target gang blocked."""
    _pool(c, "pool-a")                              # 64 chips
    small = _gang(c, "small", "2x2x4", 4, consent=consent)
    _pool(c, "rehome", dims=(2, 2, 4))              # fits `small` exactly
    target = _gang(c, "target", "4x4x4", 16, wait=False)   # needs all of pool-a
    assert c.wait_for_pods_unscheduled([p.key for p in target], hold=0.5)
    return small, target


def _controller(c, **kw):
    kw.setdefault("blocked_after_s", 0.5)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("shadow_timeout_s", 15.0)
    return DefragController(c.api, **kw)


def test_controller_migrates_consenting_gang_and_admits_blocked():
    with _cluster() as c:
        small, target = _fragmented_cluster(c)
        ctl = _controller(c)
        time.sleep(0.6)                     # cross blocked_after
        plan = ctl.reconcile_once()
        assert plan is not None
        assert plan["migrate"] == ["default/small"]
        assert plan["blocked"] == "default/target"
        assert ctl.migrations == 1
        # everyone lands: target takes pool-a, small re-homes
        assert c.wait_for_pods_scheduled([p.key for p in target], timeout=30)
        small_keys = [p.key for p in small]
        assert c.wait_for_pods_scheduled(small_keys, timeout=30)
        pools = {c.pod(k).meta.annotations[POOL_ANNOTATION]
                 for k in small_keys}
        assert pools == {"rehome"}
        evs = [e for e in c.api.events() if e.reason == "DefragMigrated"]
        assert len(evs) == 4


def test_no_consent_no_migration():
    with _cluster() as c:
        small, target = _fragmented_cluster(c, consent=False)
        ctl = _controller(c)
        time.sleep(0.6)
        assert ctl.reconcile_once() is None
        assert ctl.migrations == 0
        # nothing was evicted
        assert all(c.pod(p.key).spec.node_name for p in small)


def test_dry_run_plans_without_evicting():
    with _cluster() as c:
        small, target = _fragmented_cluster(c)
        ctl = _controller(c, dry_run=True)
        time.sleep(0.6)
        plan = ctl.reconcile_once()
        assert plan is not None and plan["migrate"] == ["default/small"]
        assert ctl.migrations == 0
        assert all(c.pod(p.key).spec.node_name for p in small)
        assert all(not c.pod(p.key).spec.node_name for p in target)


def test_no_plan_when_migration_would_orphan():
    """No rehome pool: migrating `small` would leave it homeless — the
    shadow trial must reject the plan and nothing is evicted."""
    with _cluster() as c:
        _pool(c, "pool-a")
        small = _gang(c, "small", "2x2x4", 4, consent=True)
        target = _gang(c, "target", "4x4x4", 16, wait=False)
        assert c.wait_for_pods_unscheduled([p.key for p in target], hold=0.5)
        ctl = _controller(c, shadow_timeout_s=4.0)
        time.sleep(0.6)
        assert ctl.reconcile_once() is None
        assert all(c.pod(p.key).spec.node_name for p in small)


def test_cooldown_limits_actuations():
    with _cluster() as c:
        small, target = _fragmented_cluster(c)
        ctl = _controller(c, cooldown_s=3600.0)
        ctl._last_actuation = ctl.clock()   # as if one just happened
        time.sleep(0.6)
        assert ctl.reconcile_once() is None
        assert ctl.migrations == 0


def test_runner_wires_defrag_controller():
    from tpusched.controllers.runner import ControllerRunner, ServerRunOptions
    api = srv.APIServer()
    r = ControllerRunner(api, ServerRunOptions(enable_defrag=True,
                                               defrag_dry_run=True))
    r.run()
    try:
        assert wait_until(lambda: any(
            type(ctl).__name__ == "DefragController"
            for ctl in r._controllers), timeout=5)
    finally:
        r.stop()


def test_atomic_set_migrates_as_one_unit():
    """An atomic multislice set is one migration unit: the controller must
    move BOTH member gangs together (half-migrating a bound set would
    strand the survivor) and the set must re-admit whole through its own
    barrier on the re-home pool."""
    from tpusched.config.types import MultiSliceArgs
    prof = tpu_gang_profile(permit_wait_s=10, denied_s=1)
    prof.plugin_args["MultiSlice"] = MultiSliceArgs(
        set_schedule_timeout_seconds=8, denied_set_expiration_time_seconds=1)
    with TestCluster(profile=prof) as c:
        _pool(c, "pool-a")                          # 64 chips
        # the atomic set fragments pool-a (2 x 16 chips)
        set_keys = []
        for idx in range(2):
            name = f"ms-s{idx}"
            pg = make_pod_group(name, min_member=4, tpu_slice_shape="2x2x4",
                                tpu_accelerator="tpu-v5p",
                                multislice_set="ms", multislice_index=idx,
                                multislice_set_size=2)
            pg.meta.annotations[ALLOW_MIGRATION_ANNOTATION] = "true"
            c.api.create(srv.POD_GROUPS, pg)
            ps = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
                  for i in range(4)]
            c.create_pods(ps)
            set_keys += [p.key for p in ps]
        assert c.wait_for_pods_scheduled(set_keys, timeout=30)
        _pool(c, "rehome", dims=(4, 4, 2))          # fits the whole set
        target = _gang(c, "target", "4x4x4", 16, wait=False)
        assert c.wait_for_pods_unscheduled([p.key for p in target], hold=0.5)

        ctl = _controller(c)
        time.sleep(0.6)
        plan = ctl.reconcile_once()
        assert plan is not None
        assert sorted(plan["migrate"]) == ["default/ms-s0", "default/ms-s1"]
        assert c.wait_for_pods_scheduled([p.key for p in target], timeout=30)
        assert c.wait_for_pods_scheduled(set_keys, timeout=30)
        pools = {c.pod(k).meta.annotations[POOL_ANNOTATION]
                 for k in set_keys}
        assert pools == {"rehome"}


def test_half_consented_set_is_not_a_candidate():
    """Consent on ONE slice of an atomic set does not make the set movable."""
    from tpusched.config.types import MultiSliceArgs
    prof = tpu_gang_profile(permit_wait_s=10, denied_s=1)
    prof.plugin_args["MultiSlice"] = MultiSliceArgs(
        set_schedule_timeout_seconds=8, denied_set_expiration_time_seconds=1)
    with TestCluster(profile=prof) as c:
        _pool(c, "pool-a")
        set_keys = []
        for idx in range(2):
            name = f"ms-s{idx}"
            pg = make_pod_group(name, min_member=4, tpu_slice_shape="2x2x4",
                                tpu_accelerator="tpu-v5p",
                                multislice_set="ms", multislice_index=idx,
                                multislice_set_size=2)
            if idx == 0:
                pg.meta.annotations[ALLOW_MIGRATION_ANNOTATION] = "true"
            c.api.create(srv.POD_GROUPS, pg)
            ps = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: 4})
                  for i in range(4)]
            c.create_pods(ps)
            set_keys += [p.key for p in ps]
        assert c.wait_for_pods_scheduled(set_keys, timeout=30)
        _pool(c, "rehome", dims=(4, 4, 2))
        target = _gang(c, "target", "4x4x4", 16, wait=False)
        assert c.wait_for_pods_unscheduled([p.key for p in target], hold=0.5)
        ctl = _controller(c)
        time.sleep(0.6)
        assert ctl.reconcile_once() is None
        assert all(c.pod(k).spec.node_name for k in set_keys)


def test_cross_namespace_blocked_and_migrant():
    """The blocked gang and the consenting migrant live in different
    namespaces: planning, eviction, and re-homing must all be
    namespace-correct."""
    with _cluster() as c:
        _pool(c, "pool-a")
        pg = make_pod_group("small", namespace="team-a", min_member=4,
                            tpu_slice_shape="2x2x4",
                            tpu_accelerator="tpu-v5p")
        pg.meta.annotations[ALLOW_MIGRATION_ANNOTATION] = "true"
        c.api.create(srv.POD_GROUPS, pg)
        small = [make_pod(f"small-{i}", namespace="team-a",
                          pod_group="small", limits={TPU: 4})
                 for i in range(4)]
        c.create_pods(small)
        assert c.wait_for_pods_scheduled([p.key for p in small], timeout=30)
        _pool(c, "rehome", dims=(2, 2, 4))
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "target", namespace="team-b", min_member=16,
            tpu_slice_shape="4x4x4", tpu_accelerator="tpu-v5p"))
        target = [make_pod(f"target-{i}", namespace="team-b",
                           pod_group="target", limits={TPU: 4})
                  for i in range(16)]
        c.create_pods(target)
        assert c.wait_for_pods_unscheduled([p.key for p in target], hold=0.5)

        ctl = _controller(c)
        time.sleep(0.6)
        plan = ctl.reconcile_once()
        assert plan is not None
        assert plan["migrate"] == ["team-a/small"]
        assert plan["blocked"] == "team-b/target"
        assert c.wait_for_pods_scheduled([p.key for p in target], timeout=30)
        assert c.wait_for_pods_scheduled([p.key for p in small], timeout=30)
        pools = {c.pod(p.key).meta.annotations[POOL_ANNOTATION]
                 for p in small}
        assert pools == {"rehome"}
