"""Property-based torus-engine invariants (hypothesis). The example-based
suites pin known shapes; these pin the LAWS that must hold for every
shape/pool combination the fuzzer can draw — the combinatorial core where
a subtle rotation or wraparound bug would otherwise only surface on an
operator's exotic pool.

Invariants:
  P1  every candidate host block divides out the accelerator's host extent
      exactly and fits the pool (and the memoized result is stable);
  P2  every enumerated placement has exactly prod(block) distinct in-bounds
      hosts, and without wrap no placement crosses an axis boundary;
  P3  placements are pairwise distinct as sets;
  P4  validate_slice_shape is consistent with enumeration: a shape that
      validates on a fully-populated pool enumerates >= 1 placement, and a
      shape that fails validation enumerates none;
  P5  feasible_placements never returns a placement missing an assigned
      host or touching a non-free host.
"""
import math

import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from tpusched.api.topology import ACCELERATORS
from tpusched.topology.torus import (HostGrid, HOST_EXTENT,
                                     candidate_host_blocks,
                                     enumerate_placements,
                                     feasible_placements,
                                     validate_slice_shape)

ACC_3D = ACCELERATORS["tpu-v5p"]          # host extent (2, 2, 1)
ACC_2D = ACCELERATORS["tpu-v5e"]          # host extent (2, 2)


def _grid(acc, chip_dims, wrap):
    extent = HOST_EXTENT[acc.name]
    host_dims = tuple(d // e for d, e in zip(chip_dims, extent))
    node_of = {}
    coords = [()]
    for d in host_dims:
        coords = [c + (i,) for c in coords for i in range(d)]
    for hc in coords:
        node_of[hc] = "n" + "-".join(map(str, hc))
    return HostGrid(pool="p", acc=acc, dims=host_dims, wrap=wrap,
                    node_of=node_of,
                    coord_of={v: k for k, v in node_of.items()})


dims3 = st.tuples(st.sampled_from([2, 4, 6, 8]), st.sampled_from([2, 4, 6]),
                  st.sampled_from([1, 2, 4]))
shape3 = st.tuples(st.integers(1, 8), st.integers(1, 8), st.integers(1, 4))
wrap3 = st.tuples(st.booleans(), st.booleans(), st.booleans())

dims2 = st.tuples(st.sampled_from([2, 4, 8, 16]), st.sampled_from([2, 4, 8]))
shape2 = st.tuples(st.integers(1, 16), st.integers(1, 8))
wrap2 = st.tuples(st.booleans(), st.booleans())


@settings(max_examples=200, deadline=None)
@given(shape=shape3, dims=dims3)
def test_p1_candidate_blocks_divide_extent_and_fit(shape, dims):
    extent = HOST_EXTENT[ACC_3D.name]
    host_dims = tuple(d // e for d, e in zip(dims, extent))
    blocks = candidate_host_blocks(shape, ACC_3D, host_dims)
    again = candidate_host_blocks(shape, ACC_3D, host_dims)
    assert tuple(blocks) == tuple(again)          # memo stability
    for hb in blocks:
        assert all(0 < hb[i] <= host_dims[i] for i in range(3))
        # some permutation of the chip shape reproduces hb * extent
        assert any(tuple(p[i] // extent[i] for i in range(3)) == hb
                   and all(p[i] % extent[i] == 0 for i in range(3))
                   for p in set(__import__("itertools").permutations(shape)))


@settings(max_examples=120, deadline=None)
@given(shape=shape3, dims=dims3, wrap=wrap3)
def test_p2_p3_placements_sized_in_bounds_distinct(shape, dims, wrap):
    grid = _grid(ACC_3D, dims, wrap)
    placements = enumerate_placements(grid, shape)
    extent = HOST_EXTENT[ACC_3D.name]
    sizes = {tuple(p[i] // extent[i] for i in range(3))
             for p in __import__("itertools").permutations(shape)
             if all(p[i] % extent[i] == 0 for i in range(3))}
    valid_sizes = {math.prod(hb) for hb in sizes
                   if all(hb[i] <= grid.dims[i] for i in range(3))}
    seen = set()
    for pl in placements:
        assert pl not in seen                    # P3
        seen.add(pl)
        assert len(pl) in valid_sizes            # P2: cardinality
        for hc in pl:
            assert all(0 <= hc[i] < grid.dims[i] for i in range(3))
        if not any(wrap):
            # without wrap the placement is a contiguous axis-aligned box
            for i in range(3):
                axis = sorted({hc[i] for hc in pl})
                assert axis == list(range(axis[0], axis[-1] + 1))


@settings(max_examples=120, deadline=None)
@given(shape=shape2, dims=dims2, wrap=wrap2)
def test_p4_validate_consistent_with_enumeration_2d(shape, dims, wrap):
    err = validate_slice_shape(shape, ACC_2D, dims)
    grid = _grid(ACC_2D, dims, wrap)
    placements = enumerate_placements(grid, shape)
    if err is None:
        assert placements, (shape, dims, wrap)
    else:
        assert not placements, (shape, dims, wrap, err)


@settings(max_examples=80, deadline=None)
@given(shape=shape3, dims=dims3, wrap=wrap3, data=st.data())
def test_p5_feasible_respects_assigned_and_free(shape, dims, wrap, data):
    grid = _grid(ACC_3D, dims, wrap)
    placements = enumerate_placements(grid, shape)
    hosts = sorted(grid.node_of)
    free = frozenset(data.draw(st.sets(st.sampled_from(hosts))) if hosts
                     else set())
    assigned_pool = sorted(free) or hosts
    assigned = frozenset(data.draw(
        st.sets(st.sampled_from(assigned_pool), max_size=3))) if hosts \
        else frozenset()
    for pl in feasible_placements(placements, assigned, free):
        assert assigned <= pl
        assert all(hc in free or hc in assigned for hc in pl)
