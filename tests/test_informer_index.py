"""Informer indexers (client-go cache.Indexers analog): bucket membership
tracks adds/updates/deletes, including label changes that move an object
between buckets — plus the delete-race tolerance and relist/resync
contracts the node-failure pipeline leans on."""
from __future__ import annotations

from tpusched.api.scheduling import (POD_GROUP_INDEX, POD_GROUP_LABEL,
                                     pod_group_index_key)
from tpusched.apiserver import APIServer, InformerFactory
from tpusched.apiserver import server as srv
from tpusched.testing import make_pod


def keys(informer, value):
    return sorted(p.meta.key for p in informer.by_index(POD_GROUP_INDEX, value))


def test_index_add_update_delete():
    api = srv.APIServer()
    # one pod exists BEFORE the index is registered: must be back-filled
    api.create(srv.PODS, make_pod("pre", labels={POD_GROUP_LABEL: "g1"}))
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)  # idempotent

    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g1"}))
    api.create(srv.PODS, make_pod("b", labels={POD_GROUP_LABEL: "g2"}))
    api.create(srv.PODS, make_pod("plain"))  # unindexed (no gang label)
    assert keys(informer, "default/g1") == ["default/a", "default/pre"]
    assert keys(informer, "default/g2") == ["default/b"]

    # relabel moves the pod between buckets
    api.patch(srv.PODS, "default/a",
              lambda p: p.meta.labels.update({POD_GROUP_LABEL: "g2"}))
    assert keys(informer, "default/g1") == ["default/pre"]
    assert keys(informer, "default/g2") == ["default/a", "default/b"]

    # delete drops from the bucket; empty buckets vanish
    api.delete(srv.PODS, "default/pre")
    assert keys(informer, "default/g1") == []
    assert keys(informer, "default/unknown") == []


def test_index_scoped_by_namespace():
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g"}))
    api.create(srv.PODS, make_pod("a", namespace="other",
                                  labels={POD_GROUP_LABEL: "g"}))
    assert keys(informer, "default/g") == ["default/a"]
    assert keys(informer, "other/g") == ["other/a"]


def test_handler_exceptions_are_isolated():
    """One raising handler must not starve later handlers of the event nor
    propagate into the mutating API call (delivery is synchronous under the
    write here — client-go's per-listener processors give the analogous
    isolation)."""
    api = APIServer()
    informers = InformerFactory(api)
    pods = informers.pods()
    seen = []

    def bad(obj):
        raise RuntimeError("buggy plugin handler")

    pods.add_event_handler(on_add=bad, on_delete=bad)
    pods.add_event_handler(on_add=lambda o: seen.append(("add", o.meta.name)),
                           on_delete=lambda o: seen.append(("del", o.meta.name)))

    p = make_pod("p1")
    api.create(srv.PODS, p)        # must not raise despite `bad`
    api.delete(srv.PODS, p.key)
    assert seen == [("add", "p1"), ("del", "p1")]
    # cache stayed consistent through the bad handler
    assert pods.get("default/p1") is None


def test_handler_exceptions_isolated_during_replay():
    """Registration with a pre-populated cache: a raising on_add must not
    abort the replay of remaining cached objects nor escape the registering
    constructor."""
    api = APIServer()
    for i in range(3):
        api.create(srv.PODS, make_pod(f"p{i}"))
    informers = InformerFactory(api)
    pods = informers.pods()
    seen = []

    def bad_then_record(obj):
        if obj.meta.name == "p0":
            raise RuntimeError("boom on first replayed object")
        seen.append(obj.meta.name)

    pods.add_event_handler(on_add=bad_then_record)   # must not raise
    assert sorted(seen) == ["p1", "p2"]


def test_informer_close_detaches_from_watch_fanout():
    """client-go watch-Stop analog: after close(), the informer's cache is
    frozen and its handlers receive nothing; a fresh informer on the same
    server still sees the full state (replay)."""
    from tpusched.apiserver import APIServer
    from tpusched.apiserver import server as srv
    from tpusched.apiserver.informers import InformerFactory
    from tpusched.testing import make_pod

    api = APIServer()
    api.create(srv.PODS, make_pod("before"))
    f1 = InformerFactory(api)
    inf1 = f1.pods()
    seen = []
    inf1.add_event_handler(on_add=lambda p: seen.append(p.meta.name))
    assert seen == ["before"]
    f1.close()
    api.create(srv.PODS, make_pod("after"))
    assert seen == ["before"]                  # no post-close delivery
    assert inf1.get("default/after") is None   # cache frozen
    # a new factory on the same server replays everything
    f2 = InformerFactory(api)
    assert {p.meta.name for p in f2.pods().items()} == {"before", "after"}
    f2.close()


def test_stopped_scheduler_stops_consuming_events():
    """A stopped scheduler's informers detach: subsequent writes reach only
    the live scheduler (the HA fail-over / what-if restart hygiene)."""
    from tpusched.apiserver import APIServer
    from tpusched.apiserver import server as srv
    from tpusched.api.resources import TPU
    from tpusched.plugins import default_registry
    from tpusched.sched import Scheduler
    from tpusched.testing import make_pod, make_tpu_node, wait_until
    from tpusched.testing.cluster import default_profile

    api = APIServer()
    s1 = Scheduler(api, default_registry(), default_profile())
    s1.run()
    live = len(api._handlers[srv.PODS])
    assert live >= 1
    s1.stop()
    assert len(api._handlers[srv.PODS]) == 0   # fully detached
    s2 = Scheduler(api, default_registry(), default_profile())
    s2.run()
    try:
        api.create(srv.NODES, make_tpu_node("n1", chips=4))
        api.create(srv.PODS, make_pod("p", limits={TPU: 1}))
        assert wait_until(
            lambda: (api.peek(srv.PODS, "default/p") or make_pod("x")
                     ).spec.node_name, timeout=10)
        # s1's informers are detached; only s2's (same count) remain
        assert len(api._handlers[srv.PODS]) == live
    finally:
        s2.stop()


# -- delete-race tolerance + relist/resync ------------------------------------

def test_deleted_event_for_unknown_key_is_tolerated():
    """A DELETED for a key the informer never cached (replay race: the
    object was created+deleted around add_watch's snapshot) must not
    throw, must not corrupt indexes, and must still fan out to delete
    handlers (client-go DeletedFinalStateUnknown analog)."""
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    deletes = []
    informer.add_event_handler(on_delete=deletes.append)

    ghost = make_pod("ghost", labels={POD_GROUP_LABEL: "g1"})
    informer._handle(srv.WatchEvent(srv.DELETED, srv.PODS, ghost))
    assert [p.meta.key for p in deletes] == ["default/ghost"]
    assert informer.get("default/ghost") is None
    assert keys(informer, "default/g1") == []

    # the informer keeps working normally afterwards, indexes consistent
    api.create(srv.PODS, make_pod("real", labels={POD_GROUP_LABEL: "g1"}))
    assert keys(informer, "default/g1") == ["default/real"]
    api.delete(srv.PODS, "default/real")
    assert keys(informer, "default/g1") == []


def test_resync_reconciles_missed_events():
    """Relist/resync (reconnect-after-missed-events): an informer whose
    cache drifted from the store — missed add, missed update, missed
    delete — converges on resync(), with handler deliveries and index
    maintenance exactly as a live watch would have produced."""
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    api.create(srv.PODS, make_pod("keep", labels={POD_GROUP_LABEL: "g1"}))
    api.create(srv.PODS, make_pod("stale", labels={POD_GROUP_LABEL: "g1"}))
    api.create(srv.PODS, make_pod("doomed", labels={POD_GROUP_LABEL: "g2"}))

    # simulate a disconnected window: mutate the store behind the
    # informer's back by detaching its watch first
    api.remove_watch(srv.PODS, informer._handle)
    api.delete(srv.PODS, "default/doomed")
    api.patch(srv.PODS, "default/stale",
              lambda p: p.meta.labels.update({POD_GROUP_LABEL: "g2"}))
    api.create(srv.PODS, make_pod("born", labels={POD_GROUP_LABEL: "g2"}))

    # drifted: the informer still sees the old world
    assert informer.get("default/doomed") is not None
    assert informer.get("default/born") is None

    adds, updates, deletes = [], [], []
    informer.add_event_handler(on_add=adds.append, replay=False,
                               on_update=lambda o, n: updates.append((o, n)),
                               on_delete=deletes.append)
    informer.resync()

    assert [p.meta.key for p in adds] == ["default/born"]
    assert [(o.meta.key, n.meta.labels[POD_GROUP_LABEL])
            for o, n in updates] == [("default/stale", "g2")]
    assert [p.meta.key for p in deletes] == ["default/doomed"]
    assert informer.get("default/doomed") is None
    assert keys(informer, "default/g1") == ["default/keep"]
    assert keys(informer, "default/g2") == ["default/born", "default/stale"]


# -- unordered watch fan-out protection (ISSUE 13 root-cause fix) -------------
#
# The APIServer dispatches watch events OUTSIDE its store lock, on each
# mutating caller's thread — so two racing writers can deliver their events
# in the opposite of store order.  The informer imposes per-key order via
# the globally monotonic resourceVersion: late events are dropped, never
# resurrecting dead state in downstream caches (the scheduler cache counted
# such phantoms as permanent occupancy — wedged gangs under storm churn).

def _ev(type_, obj, old=None):
    return srv.WatchEvent(type_, srv.PODS, obj, old)


def _bound(name, rv, node="n1"):
    p = make_pod(name, node_name=node)
    p.meta.resource_version = rv
    return p


def test_late_modified_after_delete_is_dropped():
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    seen = []
    informer.add_event_handler(on_add=lambda o: seen.append(("add", o)),
                               on_update=lambda o, n: seen.append(("upd", n)),
                               on_delete=lambda o: seen.append(("del", o)),
                               replay=False)
    informer._handle(_ev(srv.ADDED, _bound("p", 5)))
    informer._handle(_ev(srv.DELETED, _bound("p", 7)))
    # the bind-confirm MODIFIED (rv 7) overtaken by the DELETE: must NOT
    # resurrect the pod in the informer cache or reach handlers
    informer._handle(_ev(srv.MODIFIED, _bound("p", 7), _bound("p", 5)))
    assert informer.get("default/p") is None
    assert [k for k, _ in seen] == ["add", "del"]


def test_late_delete_after_recreate_is_dropped():
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    seen = []
    informer.add_event_handler(on_delete=lambda o: seen.append(o.meta.key),
                               replay=False)
    informer._handle(_ev(srv.ADDED, _bound("p", 5)))
    # recreate (global rv counter: strictly newer) overtakes the old
    # instance's DELETED in the fan-out
    informer._handle(_ev(srv.ADDED, _bound("p", 9)))
    informer._handle(_ev(srv.DELETED, _bound("p", 5)))   # dead predecessor
    live = informer.get("default/p")
    assert live is not None and live.meta.resource_version == 9
    assert seen == []


def test_genuine_recreate_after_delete_is_delivered():
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    adds = []
    informer.add_event_handler(on_add=lambda o: adds.append(
        o.meta.resource_version), replay=False)
    informer._handle(_ev(srv.ADDED, _bound("p", 5)))
    informer._handle(_ev(srv.DELETED, _bound("p", 5)))
    informer._handle(_ev(srv.ADDED, _bound("p", 8)))     # fresh instance
    assert adds == [5, 8]
    assert informer.get("default/p").meta.resource_version == 8
