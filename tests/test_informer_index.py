"""Informer indexers (client-go cache.Indexers analog): bucket membership
tracks adds/updates/deletes, including label changes that move an object
between buckets."""
from __future__ import annotations

from tpusched.api.scheduling import (POD_GROUP_INDEX, POD_GROUP_LABEL,
                                     pod_group_index_key)
from tpusched.apiserver import APIServer, InformerFactory
from tpusched.apiserver import server as srv
from tpusched.testing import make_pod


def keys(informer, value):
    return sorted(p.meta.key for p in informer.by_index(POD_GROUP_INDEX, value))


def test_index_add_update_delete():
    api = srv.APIServer()
    # one pod exists BEFORE the index is registered: must be back-filled
    api.create(srv.PODS, make_pod("pre", labels={POD_GROUP_LABEL: "g1"}))
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)  # idempotent

    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g1"}))
    api.create(srv.PODS, make_pod("b", labels={POD_GROUP_LABEL: "g2"}))
    api.create(srv.PODS, make_pod("plain"))  # unindexed (no gang label)
    assert keys(informer, "default/g1") == ["default/a", "default/pre"]
    assert keys(informer, "default/g2") == ["default/b"]

    # relabel moves the pod between buckets
    api.patch(srv.PODS, "default/a",
              lambda p: p.meta.labels.update({POD_GROUP_LABEL: "g2"}))
    assert keys(informer, "default/g1") == ["default/pre"]
    assert keys(informer, "default/g2") == ["default/a", "default/b"]

    # delete drops from the bucket; empty buckets vanish
    api.delete(srv.PODS, "default/pre")
    assert keys(informer, "default/g1") == []
    assert keys(informer, "default/unknown") == []


def test_index_scoped_by_namespace():
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g"}))
    api.create(srv.PODS, make_pod("a", namespace="other",
                                  labels={POD_GROUP_LABEL: "g"}))
    assert keys(informer, "default/g") == ["default/a"]
    assert keys(informer, "other/g") == ["other/a"]


def test_handler_exceptions_are_isolated():
    """One raising handler must not starve later handlers of the event nor
    propagate into the mutating API call (delivery is synchronous under the
    write here — client-go's per-listener processors give the analogous
    isolation)."""
    api = APIServer()
    informers = InformerFactory(api)
    pods = informers.pods()
    seen = []

    def bad(obj):
        raise RuntimeError("buggy plugin handler")

    pods.add_event_handler(on_add=bad, on_delete=bad)
    pods.add_event_handler(on_add=lambda o: seen.append(("add", o.meta.name)),
                           on_delete=lambda o: seen.append(("del", o.meta.name)))

    p = make_pod("p1")
    api.create(srv.PODS, p)        # must not raise despite `bad`
    api.delete(srv.PODS, p.key)
    assert seen == [("add", "p1"), ("del", "p1")]
    # cache stayed consistent through the bad handler
    assert pods.get("default/p1") is None


def test_handler_exceptions_isolated_during_replay():
    """Registration with a pre-populated cache: a raising on_add must not
    abort the replay of remaining cached objects nor escape the registering
    constructor."""
    api = APIServer()
    for i in range(3):
        api.create(srv.PODS, make_pod(f"p{i}"))
    informers = InformerFactory(api)
    pods = informers.pods()
    seen = []

    def bad_then_record(obj):
        if obj.meta.name == "p0":
            raise RuntimeError("boom on first replayed object")
        seen.append(obj.meta.name)

    pods.add_event_handler(on_add=bad_then_record)   # must not raise
    assert sorted(seen) == ["p1", "p2"]
