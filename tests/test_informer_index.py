"""Informer indexers (client-go cache.Indexers analog): bucket membership
tracks adds/updates/deletes, including label changes that move an object
between buckets."""
from __future__ import annotations

from tpusched.api.scheduling import (POD_GROUP_INDEX, POD_GROUP_LABEL,
                                     pod_group_index_key)
from tpusched.apiserver import InformerFactory
from tpusched.apiserver import server as srv
from tpusched.testing import make_pod


def keys(informer, value):
    return sorted(p.meta.key for p in informer.by_index(POD_GROUP_INDEX, value))


def test_index_add_update_delete():
    api = srv.APIServer()
    # one pod exists BEFORE the index is registered: must be back-filled
    api.create(srv.PODS, make_pod("pre", labels={POD_GROUP_LABEL: "g1"}))
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)  # idempotent

    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g1"}))
    api.create(srv.PODS, make_pod("b", labels={POD_GROUP_LABEL: "g2"}))
    api.create(srv.PODS, make_pod("plain"))  # unindexed (no gang label)
    assert keys(informer, "default/g1") == ["default/a", "default/pre"]
    assert keys(informer, "default/g2") == ["default/b"]

    # relabel moves the pod between buckets
    api.patch(srv.PODS, "default/a",
              lambda p: p.meta.labels.update({POD_GROUP_LABEL: "g2"}))
    assert keys(informer, "default/g1") == ["default/pre"]
    assert keys(informer, "default/g2") == ["default/a", "default/b"]

    # delete drops from the bucket; empty buckets vanish
    api.delete(srv.PODS, "default/pre")
    assert keys(informer, "default/g1") == []
    assert keys(informer, "default/unknown") == []


def test_index_scoped_by_namespace():
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g"}))
    api.create(srv.PODS, make_pod("a", namespace="other",
                                  labels={POD_GROUP_LABEL: "g"}))
    assert keys(informer, "default/g") == ["default/a"]
    assert keys(informer, "other/g") == ["other/a"]
