"""Informer indexers (client-go cache.Indexers analog): bucket membership
tracks adds/updates/deletes, including label changes that move an object
between buckets."""
from __future__ import annotations

from tpusched.api.scheduling import (POD_GROUP_INDEX, POD_GROUP_LABEL,
                                     pod_group_index_key)
from tpusched.apiserver import APIServer, InformerFactory
from tpusched.apiserver import server as srv
from tpusched.testing import make_pod


def keys(informer, value):
    return sorted(p.meta.key for p in informer.by_index(POD_GROUP_INDEX, value))


def test_index_add_update_delete():
    api = srv.APIServer()
    # one pod exists BEFORE the index is registered: must be back-filled
    api.create(srv.PODS, make_pod("pre", labels={POD_GROUP_LABEL: "g1"}))
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)  # idempotent

    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g1"}))
    api.create(srv.PODS, make_pod("b", labels={POD_GROUP_LABEL: "g2"}))
    api.create(srv.PODS, make_pod("plain"))  # unindexed (no gang label)
    assert keys(informer, "default/g1") == ["default/a", "default/pre"]
    assert keys(informer, "default/g2") == ["default/b"]

    # relabel moves the pod between buckets
    api.patch(srv.PODS, "default/a",
              lambda p: p.meta.labels.update({POD_GROUP_LABEL: "g2"}))
    assert keys(informer, "default/g1") == ["default/pre"]
    assert keys(informer, "default/g2") == ["default/a", "default/b"]

    # delete drops from the bucket; empty buckets vanish
    api.delete(srv.PODS, "default/pre")
    assert keys(informer, "default/g1") == []
    assert keys(informer, "default/unknown") == []


def test_index_scoped_by_namespace():
    api = srv.APIServer()
    informer = InformerFactory(api).pods()
    informer.add_index(POD_GROUP_INDEX, pod_group_index_key)
    api.create(srv.PODS, make_pod("a", labels={POD_GROUP_LABEL: "g"}))
    api.create(srv.PODS, make_pod("a", namespace="other",
                                  labels={POD_GROUP_LABEL: "g"}))
    assert keys(informer, "default/g") == ["default/a"]
    assert keys(informer, "other/g") == ["other/a"]


def test_handler_exceptions_are_isolated():
    """One raising handler must not starve later handlers of the event nor
    propagate into the mutating API call (delivery is synchronous under the
    write here — client-go's per-listener processors give the analogous
    isolation)."""
    api = APIServer()
    informers = InformerFactory(api)
    pods = informers.pods()
    seen = []

    def bad(obj):
        raise RuntimeError("buggy plugin handler")

    pods.add_event_handler(on_add=bad, on_delete=bad)
    pods.add_event_handler(on_add=lambda o: seen.append(("add", o.meta.name)),
                           on_delete=lambda o: seen.append(("del", o.meta.name)))

    p = make_pod("p1")
    api.create(srv.PODS, p)        # must not raise despite `bad`
    api.delete(srv.PODS, p.key)
    assert seen == [("add", "p1"), ("del", "p1")]
    # cache stayed consistent through the bad handler
    assert pods.get("default/p1") is None


def test_handler_exceptions_isolated_during_replay():
    """Registration with a pre-populated cache: a raising on_add must not
    abort the replay of remaining cached objects nor escape the registering
    constructor."""
    api = APIServer()
    for i in range(3):
        api.create(srv.PODS, make_pod(f"p{i}"))
    informers = InformerFactory(api)
    pods = informers.pods()
    seen = []

    def bad_then_record(obj):
        if obj.meta.name == "p0":
            raise RuntimeError("boom on first replayed object")
        seen.append(obj.meta.name)

    pods.add_event_handler(on_add=bad_then_record)   # must not raise
    assert sorted(seen) == ["p1", "p2"]


def test_informer_close_detaches_from_watch_fanout():
    """client-go watch-Stop analog: after close(), the informer's cache is
    frozen and its handlers receive nothing; a fresh informer on the same
    server still sees the full state (replay)."""
    from tpusched.apiserver import APIServer
    from tpusched.apiserver import server as srv
    from tpusched.apiserver.informers import InformerFactory
    from tpusched.testing import make_pod

    api = APIServer()
    api.create(srv.PODS, make_pod("before"))
    f1 = InformerFactory(api)
    inf1 = f1.pods()
    seen = []
    inf1.add_event_handler(on_add=lambda p: seen.append(p.meta.name))
    assert seen == ["before"]
    f1.close()
    api.create(srv.PODS, make_pod("after"))
    assert seen == ["before"]                  # no post-close delivery
    assert inf1.get("default/after") is None   # cache frozen
    # a new factory on the same server replays everything
    f2 = InformerFactory(api)
    assert {p.meta.name for p in f2.pods().items()} == {"before", "after"}
    f2.close()


def test_stopped_scheduler_stops_consuming_events():
    """A stopped scheduler's informers detach: subsequent writes reach only
    the live scheduler (the HA fail-over / what-if restart hygiene)."""
    from tpusched.apiserver import APIServer
    from tpusched.apiserver import server as srv
    from tpusched.api.resources import TPU
    from tpusched.plugins import default_registry
    from tpusched.sched import Scheduler
    from tpusched.testing import make_pod, make_tpu_node, wait_until
    from tpusched.testing.cluster import default_profile

    api = APIServer()
    s1 = Scheduler(api, default_registry(), default_profile())
    s1.run()
    live = len(api._handlers[srv.PODS])
    assert live >= 1
    s1.stop()
    assert len(api._handlers[srv.PODS]) == 0   # fully detached
    s2 = Scheduler(api, default_registry(), default_profile())
    s2.run()
    try:
        api.create(srv.NODES, make_tpu_node("n1", chips=4))
        api.create(srv.PODS, make_pod("p", limits={TPU: 1}))
        assert wait_until(
            lambda: (api.peek(srv.PODS, "default/p") or make_pod("x")
                     ).spec.node_name, timeout=10)
        # s1's informers are detached; only s2's (same count) remain
        assert len(api._handlers[srv.PODS]) == live
    finally:
        s2.stop()
