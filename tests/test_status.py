"""Status semantics tables — the framework's per-node verdict type is shared
and cached (success singleton, plugins may memoize failures), so its
copy-on-write and merge rules are load-bearing (fwk/status.py; upstream
framework.Status / PluginToStatus.Merge analogs).
"""
from tpusched.fwk.status import (ERROR, SUCCESS, UNSCHEDULABLE,
                                 UNSCHEDULABLE_AND_UNRESOLVABLE, Status,
                                 merge_statuses)


def test_with_plugin_is_uniformly_copy_on_write():
    """A shared/cached Status instance must never be mutated by attribution:
    run_filter_plugins calls with_plugin per node (advisor round-1 finding:
    only the success singleton was copy-on-write)."""
    shared = Status.unschedulable("cached failure")
    a = shared.with_plugin("PluginA")
    b = shared.with_plugin("PluginB")
    assert shared.plugin == ""          # untouched
    assert (a.plugin, b.plugin) == ("PluginA", "PluginB")
    assert a is not shared and b is not shared
    # same-name attribution short-circuits without a copy
    assert a.with_plugin("PluginA") is a


def test_success_singleton_shared_and_safe():
    s1, s2 = Status.success(), Status.success()
    assert s1 is s2                      # the singleton
    named = s1.with_plugin("X")
    assert named is not s1 and Status.success().plugin == ""


def test_merge_severity_order():
    """error > unresolvable > unschedulable > success, reasons concatenated
    (PluginToStatus.Merge)."""
    merged = merge_statuses([
        Status.unschedulable("u1").with_plugin("A"),
        Status.unresolvable("hard").with_plugin("B"),
        Status.unschedulable("u2").with_plugin("C"),
    ])
    assert merged.code == UNSCHEDULABLE_AND_UNRESOLVABLE
    assert merged.plugin == "B"
    assert "u1" in merged.message() and "hard" in merged.message()

    err = merge_statuses([Status.unresolvable("x"),
                          Status.error("boom").with_plugin("E")])
    assert err.code == ERROR and err.plugin == "E"

    assert merge_statuses([Status.success(), Status.success()]).is_success()
    assert merge_statuses([]).is_success()


def test_merge_does_not_mutate_inputs():
    u = Status.unschedulable("why")
    before = list(u.reasons)
    merge_statuses([u, Status.unschedulable("other")])
    assert u.reasons == before
