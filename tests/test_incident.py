"""Black-box incident bundles + the closed incident loop (ISSUE 20, the
pytest half of ``make incident-smoke``).

The acceptance claims:

- bundles are schema-validated, written atomically (tmp+fsync+replace),
  and a torn write / corrupt file on reopen is recovered — ``.tmp``
  removed, unparseable ``.json`` quarantined to ``.corrupt`` — with
  every valid bundle still served;
- budgets and per-detector cooldown bound disk usage and bundle volume;
- NON-VACUITY, closed loop, end to end: a seeded bind-rate collapse in
  a real scheduler storm fires the ``bind_rate_collapse`` detector,
  which freezes a bundle whose ``cmd.incident inspect`` rendering ALONE
  names the detector, the cause, and the blocking reason — the 3am
  triage without a single debug-endpoint curl;
- DETERMINISM: two virtual-time replays of one recorded storm render
  byte-identical timeline sample counts and incident censuses (the
  ``cmd.trace evaluate`` per-arm incident verdicts stand on these).
"""
from __future__ import annotations

import json
import os
import time

import pytest

from tpusched import obs
from tpusched.api.resources import TPU, make_resources
from tpusched.cmd import incident as cli
from tpusched.obs.incident import (ENV_DIR, SCHEMA_VERSION, IncidentManager,
                                   config_fingerprint, validate_bundle)
from tpusched.testing import (TestCluster, make_pod, make_tpu_node,
                              wait_until)
from tpusched.util.clock import VirtualClock

from test_replay_smoke import record_smoke_storm


def _trigger(detector="bind_rate_collapse", **detail):
    detail.setdefault("reason", "test trigger")
    return {"detector": detector, "t": 1.0, "wall": 1e9, "detail": detail,
            "values": {"bind_rate": 0.1}}


def _sources(**extra):
    base = {"timeline": lambda: [{"t": 1.0, "v": {"bind_rate": 0.1}}],
            "queues": lambda: {"active": 3}}
    base.update(extra)
    return base


# -- schema -------------------------------------------------------------------

def test_validate_bundle_accepts_captured_doc(tmp_path):
    mgr = IncidentManager(directory=str(tmp_path), publish=False)
    bid = mgr.capture(_trigger(), _sources())
    assert bid is not None
    doc = mgr.get(bid)
    assert validate_bundle(doc) == []
    assert doc["schema_version"] == SCHEMA_VERSION


def test_validate_bundle_names_each_problem():
    assert validate_bundle(None) == ["bundle is not an object"]
    problems = validate_bundle({"schema_version": 99, "id": "",
                                "captured_wall": "late",
                                "trigger": {},
                                "sections": {"x": {"ok": True}}})
    text = "\n".join(problems)
    assert "schema_version" in text
    assert "id must be" in text
    assert "captured_wall" in text
    assert "trigger.detector missing" in text
    assert "ok without data" in text


def test_raising_source_becomes_error_section(tmp_path):
    def boom():
        raise RuntimeError("surface unavailable")
    mgr = IncidentManager(directory=str(tmp_path), publish=False)
    bid = mgr.capture(_trigger(), _sources(explain=boom))
    doc = mgr.get(bid)
    assert validate_bundle(doc) == []        # partial evidence is valid
    sec = doc["sections"]["explain"]
    assert sec["ok"] is False and "surface unavailable" in sec["error"]
    assert doc["sections"]["queues"]["ok"] is True


# -- atomicity / recovery -----------------------------------------------------

def test_capture_leaves_no_tmp_and_survives_reopen(tmp_path):
    mgr = IncidentManager(directory=str(tmp_path), publish=False)
    bid = mgr.capture(_trigger(), _sources())
    names = sorted(os.listdir(tmp_path))
    assert names == [bid + ".json"]          # no .tmp left behind
    reopened = IncidentManager(directory=str(tmp_path), publish=False)
    assert [e["id"] for e in reopened.list()] == [bid]
    assert reopened.stats()["quarantined"] == 0


def test_torn_write_recovery_on_reopen(tmp_path):
    """The crash matrix: an interrupted write (``.tmp``), a torn/garbage
    ``.json``, a schema-invalid ``.json``, and a healthy bundle.  Reopen
    removes the tmp, quarantines both bad docs to ``.corrupt`` (counted,
    never served, never deleted by the budget sweep), and serves the
    healthy bundle."""
    mgr = IncidentManager(directory=str(tmp_path), publish=False)
    good = mgr.capture(_trigger(), _sources())
    (tmp_path / "inc-0000000000001-0001-x.json.tmp").write_text(
        '{"schema_version": 1, "id": "inc-half', encoding="utf-8")
    (tmp_path / "inc-0000000000002-0002-torn.json").write_text(
        '{"schema_version": 1, "id": "inc-torn"', encoding="utf-8")
    (tmp_path / "inc-0000000000003-0003-bad.json").write_text(
        json.dumps({"schema_version": 99}), encoding="utf-8")

    reopened = IncidentManager(directory=str(tmp_path), publish=False)
    st = reopened.stats()
    assert st["recovered_tmp"] == 1
    assert st["quarantined"] == 2
    names = sorted(os.listdir(tmp_path))
    assert not any(n.endswith(".tmp") for n in names)
    assert "inc-0000000000002-0002-torn.json.corrupt" in names
    assert "inc-0000000000003-0003-bad.json.corrupt" in names
    assert [e["id"] for e in reopened.list()] == [good]
    # a quarantined id is not servable
    assert reopened.get("inc-0000000000002-0002-torn") is None


def test_get_refuses_path_traversal(tmp_path):
    secret = tmp_path.parent / "secret.json"
    secret.write_text("{}", encoding="utf-8")
    mgr = IncidentManager(directory=str(tmp_path), publish=False)
    assert mgr.get("../secret") is None
    assert mgr.get(".hidden") is None


# -- budgets / cooldown -------------------------------------------------------

def test_bundle_budget_deletes_oldest_first(tmp_path):
    vc = VirtualClock(start=0.0, wall0=1_000_000.0)
    mgr = IncidentManager(directory=str(tmp_path), max_bundles=3,
                          cooldown_s=0.0, publish=False, clock=vc)
    ids = []
    for _ in range(5):
        ids.append(mgr.capture(_trigger(), _sources()))
        vc.advance(1.0)
    kept = [e["id"] for e in mgr.list()]
    assert kept == list(reversed(ids[2:]))   # newest-first, oldest gone
    assert mgr.stats()["dropped_total"] == 2


def test_per_detector_cooldown_suppresses_then_releases(tmp_path):
    vc = VirtualClock(start=0.0, wall0=1_000_000.0)
    mgr = IncidentManager(directory=str(tmp_path), cooldown_s=60.0,
                          publish=False, clock=vc)
    assert mgr.capture(_trigger("a"), _sources()) is not None
    assert mgr.capture(_trigger("a"), _sources()) is None     # suppressed
    assert mgr.capture(_trigger("b"), _sources()) is not None  # per-detector
    vc.advance(61.0)
    assert mgr.capture(_trigger("a"), _sources()) is not None  # released
    assert len(mgr.list()) == 3


def test_memory_ring_mode_bounds_and_census(tmp_path):
    mgr = IncidentManager(max_bundles=2, cooldown_s=0.0, publish=False)
    for d in ("a", "a", "b"):
        mgr.capture(_trigger(d), _sources())
    census = mgr.census()
    assert census["written_total"] == 3 and census["dropped_total"] == 1
    assert census["by_detector"] == {"a": 1, "b": 1}  # ring kept newest 2
    assert not os.listdir(tmp_path)          # memory mode: disk untouched


def test_diff_names_changed_sections(tmp_path):
    vc = VirtualClock(start=0.0, wall0=1_000_000.0)
    mgr = IncidentManager(directory=str(tmp_path), cooldown_s=0.0,
                          publish=False, clock=vc)
    a = mgr.capture(_trigger("a"), _sources(queues=lambda: {"active": 3}))
    vc.advance(1.0)
    b = mgr.capture(_trigger("b"), _sources(
        queues=lambda: {"active": 9}, health=lambda: {"x": 1}))
    out = mgr.diff(a, b)
    assert out["trigger_a"] == "a" and out["trigger_b"] == "b"
    assert out["only_in_b"] == ["health"]
    assert out["changed"]["queues"] == ["active"]


def test_config_fingerprint_stable_and_sensitive():
    from tpusched.testing.cluster import default_profile
    p1, p2 = default_profile(), default_profile()
    f1, f2 = config_fingerprint(p1), config_fingerprint(p2)
    assert f1["sha256"] == f2["sha256"]
    p2.dispatch_shards = 7
    assert config_fingerprint(p2)["sha256"] != f1["sha256"]
    # non-scalar fields never leak into the fingerprint payload
    assert all(isinstance(v, (str, int, float, bool, type(None)))
               for v in f1["profile"].values())


# -- the CLI ------------------------------------------------------------------

def test_cli_usage_and_missing_bundle_exit_codes(tmp_path, capsys):
    assert cli.main(["list"]) == 2                    # no --dir, no env
    assert cli.main(["--dir", str(tmp_path / "nope"), "list"]) == 2
    mgr = IncidentManager(directory=str(tmp_path), publish=False)
    mgr.capture(_trigger(), _sources())
    assert cli.main(["--dir", str(tmp_path), "list"]) == 0
    assert cli.main(["--dir", str(tmp_path), "inspect", "absent"]) == 1
    capsys.readouterr()


def test_cli_env_dir_and_json_output(tmp_path, monkeypatch, capsys):
    mgr = IncidentManager(directory=str(tmp_path), publish=False)
    bid = mgr.capture(_trigger(), _sources())
    monkeypatch.setenv(ENV_DIR, str(tmp_path))
    assert cli.main(["--json", "list"]) == 0
    index = json.loads(capsys.readouterr().out)
    assert [e["id"] for e in index] == [bid]
    # unique-substring resolution
    assert cli.main(["--json", "inspect", "bind_rate_collapse"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["id"] == bid


# -- the closed loop, end to end ----------------------------------------------

@pytest.fixture()
def incident_plane(tmp_path):
    """Fresh process-global incident plane writing into ``tmp_path``,
    restored afterwards.  The timeline's interval is set beyond any test
    horizon so the housekeeping lane cannot race the test's MANUAL ticks
    (``tick()`` itself is not interval-gated)."""
    bundles = str(tmp_path / "bundles")
    prev_tl, prev_sn = obs.default_timeline(), obs.default_sentinel()
    prev_inc = obs.default_incidents()
    tl = obs.install_timeline(obs.HealthTimeline(interval_s=1e9))
    obs.install_sentinel(obs.AnomalySentinel())
    obs.install_incidents(IncidentManager(directory=bundles,
                                          cooldown_s=0.0))
    yield tl, bundles
    obs.install_timeline(prev_tl)
    obs.install_sentinel(prev_sn)
    obs.install_incidents(prev_inc)


def test_seeded_collapse_fires_bundle_triagable_from_cli_alone(
        incident_plane, capsys):
    """The non-vacuity e2e: a real scheduler binds a healthy stream
    (trailing baseline accrues), then capacity is pinned and a burst of
    unplaceable pods arrives — the bind rate collapses while pods stay
    pending.  The detector must fire, freeze a bundle, and the
    ``cmd.incident inspect`` rendering ALONE must name the detector, the
    cause, and the blocking diagnosis."""
    tl, bundles = incident_plane
    with TestCluster() as c:
        c.add_nodes([make_tpu_node(f"n{i}", chips=8) for i in range(4)])

        # healthy phase: waves of singletons bind and recycle; one manual
        # timeline tick per wave accrues the trailing bind-rate baseline
        from tpusched.apiserver import server as srv
        for wave in range(8):
            pods = [make_pod(f"ok-{wave}-{i}", limits={TPU: 1},
                             requests=make_resources(cpu=1, memory="1Gi"))
                    for i in range(4)]
            c.create_pods(pods)
            assert c.wait_for_pods_scheduled([p.key for p in pods])
            tl.tick(now=time.monotonic())
            for p in pods:
                c.api.delete(srv.PODS, p.key)

        # pin the fleet: 32 chips, 32 one-chip pods that stay bound
        pins = [make_pod(f"pin-{i}", limits={TPU: 1},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(32)]
        c.create_pods(pins)
        assert c.wait_for_pods_scheduled([p.key for p in pins],
                                         timeout=30)

        # the storm that cannot bind: pending stays high, binds stop
        stuck = [make_pod(f"stuck-{i}", limits={TPU: 1},
                          requests=make_resources(cpu=1, memory="1Gi"))
                 for i in range(12)]
        c.create_pods(stuck)
        assert wait_until(
            lambda: sum(c.scheduler.queue.pending_counts().values()) >= 8,
            timeout=15)

        fired = []
        for _ in range(6):                   # enter_ticks=3 + slack
            time.sleep(0.05)
            fired += obs.default_sentinel().on_sample(
                tl.tick(now=time.monotonic())) or []
            if any(f["detector"] == "bind_rate_collapse" for f in fired):
                break
        # on_sample above re-evaluates the listener-side firing list;
        # the authoritative record is the sentinel's own census
        census = obs.default_sentinel().census()
        assert census.get("bind_rate_collapse", 0) >= 1, census

    index = obs.default_incidents().list()
    assert index, "firing produced no bundle"
    bundle_id = next(e["id"] for e in index
                     if e["detector"] == "bind_rate_collapse")

    # triage from the CLI rendering ALONE
    assert cli.main(["--dir", bundles, "inspect", bundle_id]) == 0
    out = capsys.readouterr().out
    assert "bind_rate_collapse" in out
    assert "bind rate collapsed vs trailing baseline" in out
    assert "timeline:" in out and "bind_rate" in out
    assert "diagnosis:" in out
    assert "config fingerprint:" in out
    # the numeric evidence names the collapse inputs
    assert "baseline=" in out and "pending_pods=" in out

    # and the bundle itself is schema-valid with the load-bearing
    # sections captured ok
    doc = obs.default_incidents().get(bundle_id)
    assert validate_bundle(doc) == []
    for section in ("timeline", "explain", "health", "queues", "config"):
        assert doc["sections"][section]["ok"], section


# -- replay determinism -------------------------------------------------------

@pytest.fixture(scope="module")
def incident_trace(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("incident-fleettrace"))
    record_smoke_storm(d)
    return d


def test_two_virtual_replays_render_identical_censuses(incident_trace):
    """The determinism half of the incident-smoke gate: the shadow
    incident plane accrues in VIRTUAL time, so two replays of one trace
    must agree byte-for-byte on timeline sample counts and detector /
    bundle censuses — and must actually have sampled (non-vacuity)."""
    from tpusched.sim.replay import run_replay
    r1 = run_replay(incident_trace)
    r2 = run_replay(incident_trace)
    c1 = json.dumps({"timeline": r1.timeline, "incidents": r1.incidents},
                    sort_keys=True, separators=(",", ":"))
    c2 = json.dumps({"timeline": r2.timeline, "incidents": r2.incidents},
                    sort_keys=True, separators=(",", ":"))
    assert c1 == c2
    assert r1.timeline["samples_total"] > 0, \
        "virtual replay accrued zero timeline samples — the " \
        "deadline-registry tick path never fired"
    assert r1.timeline["overflow_total"] == 0
    # the evaluation plane reads these same censuses per arm
    from tpusched.obs.fleetrace import load_trace
    from tpusched.sim.evaluate import summarize_arm
    summary = summarize_arm(load_trace(incident_trace), r1.to_dict())
    assert summary["timeline"]["samples_total"] == \
        r1.timeline["samples_total"]
    assert summary["incidents_fired"] == \
        sum(r1.incidents["sentinel"].values())
