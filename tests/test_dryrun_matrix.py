"""Pin the parallelism matrix beyond 8 devices (VERDICT r3 #6): CI asserts
dryrun_multichip at 16 and 32 virtual CPU devices every run, so dp>1 ×
fsdp × sp × tp compositions and the wider ep/pp splits can't regress
silently between manual runs.

Each run needs its own XLA device count, which is fixed at backend init —
so every size gets a fresh subprocess (the in-process jax here is pinned to
8 devices by tests/conftest.py)."""
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# 6 pins the odd-count fallback (dp=3, tp=2 — tp must stay a
# power of two or sharded dims stop dividing); 16/32 pin the
# wider dp>1 x fsdp x sp x tp and 4-way ep/pp splits
@pytest.mark.parametrize("n", [6, 16, 32])
def test_dryrun_multichip(n):
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n})"],
        cwd=REPO_ROOT, env=env, timeout=1200, capture_output=True, text=True)
    assert r.returncode == 0, (
        f"dryrun_multichip({n}) failed\n--- stdout ---\n{r.stdout}"
        f"\n--- stderr ---\n{r.stderr}")
    # the asserted-parity markers must have printed (moe/pipeline run on
    # multiples of 8 only — the ep/pp splits need those factors; tp-serve
    # needs just 2 devices, so every CI size must show it)
    families = (("dense", "moe", "pipeline", "tp-serve") if n % 8 == 0
                else ("dense", "tp-serve"))
    for family in families:
        assert f"{family} mesh=" in r.stdout, (
            f"{family} family missing from dryrun_multichip({n}) output:\n"
            f"{r.stdout}")
