"""ISSUE 16 tentpole b: coalesced bind-side fan-out — ordering contract.

The batcher enqueues watch events UNDER the store lock (commit order IS
queue order) and one flusher delivers batches; the informers' per-key RV
staleness defenses stay on for mixed-mode/replay traffic.  These tests
pin: per-key RV monotonicity under batched delivery, DELETED-after-
MODIFIED rejection when a split batch reorders, handler re-attach
mid-flush, the deferred Event ride-along (trace-id preserved), the env
knob, and the health/metrics surfaces.
"""
from __future__ import annotations

import threading
import time

from tpusched.api.core import Pod, ObjectMeta
from tpusched.apiserver import server as srv
from tpusched.apiserver.client import Clientset
from tpusched.apiserver.informers import Informer
from tpusched.util import tracectx
from tpusched.util.metrics import fanout_batches_total, fanout_events_total


def _pod(name, ns="d"):
    return Pod(meta=ObjectMeta(name=name, namespace=ns))


def _batched(window_s=3600.0):
    """A batched APIServer with the daemon flusher parked (stopped before
    any event): tests drive delivery deterministically via
    fanout_flush()."""
    api = srv.APIServer(fanout_flush_window_s=window_s)
    api._fanout.stop()
    return api


def test_sync_default_is_unchanged():
    api = srv.APIServer()
    assert api._fanout is None
    seen = []
    api.add_watch(srv.PODS, lambda ev: seen.append(ev.type))
    api.create(srv.PODS, _pod("a"))
    assert seen == [srv.ADDED]        # delivered on the mutator's thread
    assert api.fanout_health() == {"mode": "synchronous",
                                   "flush_window_ms": 0.0}


def test_env_knob_arms_the_batcher(monkeypatch):
    monkeypatch.setenv("TPUSCHED_FANOUT_FLUSH_MS", "2.5")
    api = srv.APIServer()
    assert api._fanout is not None
    assert api.fanout_health()["flush_window_ms"] == 2.5
    api._fanout.stop()
    monkeypatch.setenv("TPUSCHED_FANOUT_FLUSH_MS", "garbage")
    assert srv.APIServer()._fanout is None      # unparsable → synchronous


def test_batched_delivery_is_commit_ordered_per_key():
    """Racing writer threads: every informer-observed RV sequence per key
    must be strictly increasing — the commit-order enqueue makes the
    global delivery order the store order."""
    api = _batched()
    inf = Informer(api, srv.PODS)
    seen = {}
    inf.add_event_handler(
        on_add=lambda o: seen.setdefault(o.meta.key, []).append(
            o.meta.resource_version),
        on_update=lambda _old, o: seen.setdefault(o.meta.key, []).append(
            o.meta.resource_version))

    def writer(i):
        p = _pod(f"p{i}")
        api.create(srv.PODS, p)
        for _ in range(10):
            api.patch(srv.PODS, p.meta.key, lambda q: None)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    stop = threading.Event()
    flusher = threading.Thread(
        target=lambda: [api.fanout_flush() or time.sleep(0.001)
                        for _ in iter(lambda: not stop.is_set(), False)])
    flusher.start()
    for t in threads:
        t.join()
    stop.set()
    flusher.join()
    api.fanout_flush()
    assert len(seen) == 4
    for key, rvs in seen.items():
        assert rvs == sorted(rvs), f"{key}: non-monotone delivery {rvs}"
        assert len(rvs) == 11, f"{key}: lost events ({len(rvs)}/11)"


def test_stale_modified_after_deleted_is_rejected():
    """A batch split across racing flush calls can deliver DELETED before
    an older MODIFIED — the informer's per-key staleness rejection must
    drop the stale MODIFIED, never resurrecting the key."""
    api = _batched()
    p = _pod("doomed")
    api.create(srv.PODS, p)
    api.fanout_flush()
    inf = Informer(api, srv.PODS)
    updates, deletes = [], []
    inf.add_event_handler(on_update=lambda _o, o: updates.append(
        o.meta.resource_version),
        on_delete=lambda o: deletes.append(o.meta.resource_version))
    api.patch(srv.PODS, p.meta.key, lambda q: None)      # MODIFIED rv2
    api.delete(srv.PODS, p.meta.key)                     # DELETED  rv2-obj
    # simulate the reorder: deliver the queue back-to-front
    batch = list(api._fanout._queue)
    api._fanout._queue.clear()
    for ev in reversed(batch):
        api._dispatch(ev)
    assert deletes and inf.get(p.meta.key) is None
    assert not updates, (
        "stale MODIFIED delivered after DELETED resurrected the pod in "
        "the informer cache")


def test_handler_reattach_mid_flush_sees_consistent_replay():
    """add_event_handler while the queue holds undelivered events: the
    replay (cache snapshot) plus live deliveries must converge on the
    store's final state, without duplicate-resurrect."""
    api = _batched()
    for i in range(3):
        api.create(srv.PODS, _pod(f"p{i}"))
    api.fanout_flush()
    inf = Informer(api, srv.PODS)
    api.patch(srv.PODS, "d/p0", lambda q: None)
    api.delete(srv.PODS, "d/p1")                    # still queued
    adds, deletes = [], []
    inf.add_event_handler(on_add=lambda o: adds.append(o.meta.key),
                          on_delete=lambda o: deletes.append(o.meta.key))
    api.fanout_flush()                              # drain the backlog
    assert sorted(adds) == ["d/p0", "d/p1", "d/p2"]  # replay snapshot
    assert deletes == ["d/p1"]                       # live delete lands
    assert inf.get("d/p1") is None
    assert inf.get("d/p0") is not None


def test_deferred_event_rides_the_flush_and_keeps_trace_id():
    """record_event_deferred: formatting happens on the flusher, but the
    thread-local trace id is captured at call time — the flight-recorder
    correlation survives the hop."""
    api = _batched()
    cs = Clientset(api)
    prev = tracectx.set("t-fanout")
    try:
        cs.record_event_deferred("d/p", "Pod", "Normal", "Scheduled",
                                 lambda: "Successfully assigned d/p to n1")
    finally:
        tracectx.set(prev)
    assert not api.events()                  # nothing before the flush
    api.fanout_flush()
    evs = api.events()
    assert len(evs) == 1
    assert evs[0].message == "Successfully assigned d/p to n1 [trace=t-fanout]"
    # synchronous fallback: no batcher → recorded immediately
    api2 = srv.APIServer()
    Clientset(api2).record_event_deferred("d/q", "Pod", "Normal", "S",
                                          lambda: "m")
    assert api2.events()[0].message == "m"


def test_flush_metrics_and_health_surface():
    api = _batched()
    b0 = fanout_batches_total.value()
    e0 = fanout_events_total.value()
    api.create(srv.PODS, _pod("m0"))
    api.create(srv.PODS, _pod("m1"))
    api.fanout_flush()
    assert fanout_batches_total.value() == b0 + 1
    assert fanout_events_total.value() == e0 + 2
    h = api.fanout_health()
    assert h["mode"] == "batched"
    assert h["batches"] >= 1 and h["events_delivered"] >= 2
    assert h["queue_depth"] == 0
    published = []
    api.set_fanout_health_sink(published.append)
    api.create(srv.PODS, _pod("m2"))
    api.fanout_flush()
    assert published and published[-1]["events_delivered"] >= 3


def test_daemon_flusher_delivers_without_explicit_flush():
    """The real shape: a live flusher thread with a short window delivers
    on its own; the mutator never runs a handler."""
    api = srv.APIServer(fanout_flush_window_s=0.002)
    seen = []
    mutator_tid = threading.get_ident()
    tids = []
    api.add_watch(srv.PODS, lambda ev: (seen.append(ev.type),
                                        tids.append(threading.get_ident())))
    api.create(srv.PODS, _pod("bg"))
    deadline = time.monotonic() + 2.0
    while not seen and time.monotonic() < deadline:
        time.sleep(0.005)
    assert seen == [srv.ADDED]
    assert tids[0] != mutator_tid, (
        "batched mode delivered on the mutator's thread — the bind "
        "critical path still pays the fan-out")
    api._fanout.stop()


def test_health_fanout_in_flightrecorder(monkeypatch):
    """health.fanout in the /debug/flightrecorder payload: a static
    synchronous snapshot by default, live flush counters in batched
    mode."""
    from tpusched.testing import TestCluster, make_node
    with TestCluster() as c:
        h = c.scheduler.recorder.dump()["health"]
        assert h.get("fanout", {}).get("mode") == "synchronous"
    monkeypatch.setenv("TPUSCHED_FANOUT_FLUSH_MS", "1")
    with TestCluster() as c:
        c.api.create(srv.NODES, make_node("h-fanout"))
        api = c.api
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            h = c.scheduler.recorder.dump()["health"].get("fanout", {})
            if h.get("batches", 0) >= 1:
                break
            time.sleep(0.01)
        assert h.get("mode") == "batched", h
        assert h.get("batches", 0) >= 1, h
        assert h.get("flush_window_ms") == 1.0, h
