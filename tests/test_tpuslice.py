"""TpuSlice plugin unit tests (reference has no flexgpu unit tests — SURVEY §2
row 1 notes 0 test LoC; this suite covers the fit/score/reserve semantics the
reference only exercises manually, including its documented quirks we fixed)."""
from tpusched.api.resources import TPU, TPU_MEMORY
from tpusched.fwk import CycleState, PluginProfile
from tpusched.fwk.nodeinfo import NodeInfo
from tpusched.plugins.tpuslice import CHIP_INDEX_ANNOTATION, ChipNode, TpuSlice
from tpusched.testing import make_pod, make_tpu_node, new_test_framework

V5P_HBM = 95 * 1024


def tpuslice_profile():
    return PluginProfile(filter=["TpuSlice"], score=[("TpuSlice", 1)],
                        reserve=["TpuSlice"], bind=["TpuSlice"])


def node_info_with(pods=(), chips=4):
    node = make_tpu_node("n1", chips=chips)
    return NodeInfo(node, pods)


def test_chipnode_from_empty_node():
    cn = ChipNode.from_node_info(node_info_with())
    assert len(cn.chips) == 4
    assert all(c.hbm_mb == V5P_HBM for c in cn.chips)
    assert cn.free_chip_indexes() == [0, 1, 2, 3]


def test_chipnode_rebuilds_from_annotations():
    mono = make_pod("mono", limits={TPU: 1},
                    annotations={CHIP_INDEX_ANNOTATION: "2"}, node_name="n1")
    frac = make_pod("frac", limits={TPU_MEMORY: 1000},
                    annotations={CHIP_INDEX_ANNOTATION: "0"}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([mono, frac]))
    assert cn.chips[2].monopoly
    assert cn.chips[0].used_mb == 1000
    assert cn.free_chip_indexes() == [1, 3]


def test_chipnode_annotationless_pod_skipped():
    # fixed quirk: annotation checked before parsing (gpu_node.go:91-96)
    p = make_pod("no-ann", limits={TPU: 1}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([p]))
    assert cn.free_chip_indexes() == [0, 1, 2, 3]


def test_mem_fit_binpack_order():
    # chip 1 has least remaining after fit → listed first (bin-pack)
    a = make_pod("a", limits={TPU_MEMORY: 50 * 1024},
                 annotations={CHIP_INDEX_ANNOTATION: "1"}, node_name="n1")
    b = make_pod("b", limits={TPU_MEMORY: 10 * 1024},
                 annotations={CHIP_INDEX_ANNOTATION: "3"}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([a, b]))
    fits = cn.mem_fit_indexes(20 * 1024)
    assert fits[0] == 1 and fits[1] == 3
    assert set(fits) == {0, 1, 2, 3}


def test_mem_fit_no_aliasing_corruption():
    # fixed quirk: the reference's fit computation mutated chip state
    # (gpu_node.go:134-144); repeated fits must be idempotent here.
    cn = ChipNode.from_node_info(node_info_with())
    before = [(c.used_mb, c.hbm_mb) for c in cn.chips]
    for _ in range(5):
        cn.mem_fit_indexes(1024)
    assert [(c.used_mb, c.hbm_mb) for c in cn.chips] == before


def test_filter_conflict_and_capacity():
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[make_tpu_node("n1")])
    ni = handle.snapshot_shared_lister().get("n1")
    plugin = fw.plugins["TpuSlice"]
    # mixing whole-chip and fractional is UnschedulableAndUnresolvable
    s = plugin.filter(CycleState(), make_pod("x", limits={TPU: 1, TPU_MEMORY: 5}), ni)
    assert s.code.name == "UNSCHEDULABLE_AND_UNRESOLVABLE"
    # 5 chips on a 4-chip node
    s = plugin.filter(CycleState(), make_pod("y", limits={TPU: 5}), ni)
    assert s.is_unschedulable()
    # fits
    s = plugin.filter(CycleState(), make_pod("z", limits={TPU: 4}), ni)
    assert s.is_success()
    # non-TPU pod passes trivially
    s = plugin.filter(CycleState(), make_pod("w"), ni)
    assert s.is_success()


def test_filter_non_tpu_node_unresolvable():
    from tpusched.testing import make_node
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[make_node("cpu-only")])
    ni = handle.snapshot_shared_lister().get("cpu-only")
    s = fw.plugins["TpuSlice"].filter(CycleState(), make_pod("p", limits={TPU: 1}), ni)
    assert s.code.name == "UNSCHEDULABLE_AND_UNRESOLVABLE"


def test_reserve_whole_chips_multi():
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[make_tpu_node("n1")])
    pod = make_pod("p", limits={TPU: 4})
    s = fw.run_reserve_plugins_reserve(CycleState(), pod, "n1")
    assert s.is_success()
    assert pod.meta.annotations[CHIP_INDEX_ANNOTATION] == "0,1,2,3"
    fw.run_reserve_plugins_unreserve(CycleState(), pod, "n1")
    assert CHIP_INDEX_ANNOTATION not in pod.meta.annotations


def test_reserve_fractional_binpack():
    occupied = make_pod("occ", limits={TPU_MEMORY: 90 * 1024},
                        annotations={CHIP_INDEX_ANNOTATION: "2"}, node_name="n1")
    node = make_tpu_node("n1")
    fw, handle, _ = new_test_framework(tpuslice_profile(), nodes=[node],
                                       pods=[occupied])
    pod = make_pod("p", limits={TPU_MEMORY: 4 * 1024})
    s = fw.run_reserve_plugins_reserve(CycleState(), pod, "n1")
    assert s.is_success()
    # chip 2 has least remaining (95-90-4=1GB) → bin-pack picks it
    assert pod.meta.annotations[CHIP_INDEX_ANNOTATION] == "2"


def test_score_binpack_normalize():
    # fuller node must win under the reference's reverse normalize
    n_empty = make_tpu_node("empty")
    n_half = make_tpu_node("half")
    used = make_pod("u", limits={TPU: 2},
                    annotations={CHIP_INDEX_ANNOTATION: "0,1"}, node_name="half")
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[n_empty, n_half], pods=[used])
    state = CycleState()
    totals, s = fw.run_score_plugins(state, make_pod("p", limits={TPU: 1}),
                                     [n_empty, n_half])
    assert s.is_success()
    assert totals["half"] > totals["empty"]
