"""TpuSlice plugin unit tests (reference has no flexgpu unit tests — SURVEY §2
row 1 notes 0 test LoC; this suite covers the fit/score/reserve semantics the
reference only exercises manually, including its documented quirks we fixed)."""
from tpusched.api.resources import TPU, TPU_MEMORY
from tpusched.fwk import CycleState, PluginProfile
from tpusched.fwk.nodeinfo import NodeInfo
from tpusched.plugins.tpuslice import CHIP_INDEX_ANNOTATION, ChipNode, TpuSlice
from tpusched.testing import make_pod, make_tpu_node, new_test_framework

V5P_HBM = 95 * 1024


def tpuslice_profile():
    return PluginProfile(filter=["TpuSlice"], score=[("TpuSlice", 1)],
                        reserve=["TpuSlice"], bind=["TpuSlice"])


def node_info_with(pods=(), chips=4):
    node = make_tpu_node("n1", chips=chips)
    return NodeInfo(node, pods)


def test_chipnode_from_empty_node():
    cn = ChipNode.from_node_info(node_info_with())
    assert len(cn.chips) == 4
    assert all(c.hbm_mb == V5P_HBM for c in cn.chips)
    assert cn.free_chip_indexes() == [0, 1, 2, 3]


def test_chipnode_rebuilds_from_annotations():
    mono = make_pod("mono", limits={TPU: 1},
                    annotations={CHIP_INDEX_ANNOTATION: "2"}, node_name="n1")
    frac = make_pod("frac", limits={TPU_MEMORY: 1000},
                    annotations={CHIP_INDEX_ANNOTATION: "0"}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([mono, frac]))
    assert cn.chips[2].monopoly
    assert cn.chips[0].used_mb == 1000
    assert cn.free_chip_indexes() == [1, 3]


def test_chipnode_annotationless_pod_skipped():
    # fixed quirk: annotation checked before parsing (gpu_node.go:91-96)
    p = make_pod("no-ann", limits={TPU: 1}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([p]))
    assert cn.free_chip_indexes() == [0, 1, 2, 3]


def test_mem_fit_binpack_order():
    # chip 1 has least remaining after fit → listed first (bin-pack)
    a = make_pod("a", limits={TPU_MEMORY: 50 * 1024},
                 annotations={CHIP_INDEX_ANNOTATION: "1"}, node_name="n1")
    b = make_pod("b", limits={TPU_MEMORY: 10 * 1024},
                 annotations={CHIP_INDEX_ANNOTATION: "3"}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([a, b]))
    fits = cn.mem_fit_indexes(20 * 1024)
    assert fits[0] == 1 and fits[1] == 3
    assert set(fits) == {0, 1, 2, 3}


def test_mem_fit_no_aliasing_corruption():
    # fixed quirk: the reference's fit computation mutated chip state
    # (gpu_node.go:134-144); repeated fits must be idempotent here.
    cn = ChipNode.from_node_info(node_info_with())
    before = [(c.used_mb, c.hbm_mb) for c in cn.chips]
    for _ in range(5):
        cn.mem_fit_indexes(1024)
    assert [(c.used_mb, c.hbm_mb) for c in cn.chips] == before


def test_filter_conflict_and_capacity():
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[make_tpu_node("n1")])
    ni = handle.snapshot_shared_lister().get("n1")
    plugin = fw.plugins["TpuSlice"]
    # mixing whole-chip and fractional is UnschedulableAndUnresolvable
    s = plugin.filter(CycleState(), make_pod("x", limits={TPU: 1, TPU_MEMORY: 5}), ni)
    assert s.code.name == "UNSCHEDULABLE_AND_UNRESOLVABLE"
    # 5 chips on a 4-chip node
    s = plugin.filter(CycleState(), make_pod("y", limits={TPU: 5}), ni)
    assert s.is_unschedulable()
    # fits
    s = plugin.filter(CycleState(), make_pod("z", limits={TPU: 4}), ni)
    assert s.is_success()
    # non-TPU pod passes trivially
    s = plugin.filter(CycleState(), make_pod("w"), ni)
    assert s.is_success()


def test_filter_non_tpu_node_unresolvable():
    from tpusched.testing import make_node
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[make_node("cpu-only")])
    ni = handle.snapshot_shared_lister().get("cpu-only")
    s = fw.plugins["TpuSlice"].filter(CycleState(), make_pod("p", limits={TPU: 1}), ni)
    assert s.code.name == "UNSCHEDULABLE_AND_UNRESOLVABLE"


def test_reserve_whole_chips_multi():
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[make_tpu_node("n1")])
    pod = make_pod("p", limits={TPU: 4})
    s = fw.run_reserve_plugins_reserve(CycleState(), pod, "n1")
    assert s.is_success()
    assert pod.meta.annotations[CHIP_INDEX_ANNOTATION] == "0,1,2,3"
    fw.run_reserve_plugins_unreserve(CycleState(), pod, "n1")
    assert CHIP_INDEX_ANNOTATION not in pod.meta.annotations


def test_reserve_fractional_binpack():
    occupied = make_pod("occ", limits={TPU_MEMORY: 90 * 1024},
                        annotations={CHIP_INDEX_ANNOTATION: "2"}, node_name="n1")
    node = make_tpu_node("n1")
    fw, handle, _ = new_test_framework(tpuslice_profile(), nodes=[node],
                                       pods=[occupied])
    pod = make_pod("p", limits={TPU_MEMORY: 4 * 1024})
    s = fw.run_reserve_plugins_reserve(CycleState(), pod, "n1")
    assert s.is_success()
    # chip 2 has least remaining (95-90-4=1GB) → bin-pack picks it
    assert pod.meta.annotations[CHIP_INDEX_ANNOTATION] == "2"


def test_score_binpack_normalize():
    # fuller node must win under the reference's reverse normalize
    n_empty = make_tpu_node("empty")
    n_half = make_tpu_node("half")
    used = make_pod("u", limits={TPU: 2},
                    annotations={CHIP_INDEX_ANNOTATION: "0,1"}, node_name="half")
    fw, handle, _ = new_test_framework(tpuslice_profile(),
                                       nodes=[n_empty, n_half], pods=[used])
    state = CycleState()
    totals, s = fw.run_score_plugins(state, make_pod("p", limits={TPU: 1}),
                                     [n_empty, n_half])
    assert s.is_success()
    assert totals["half"] > totals["empty"]


def test_chipnode_invalid_annotations_counted_but_unplaced():
    """Garbage / out-of-range chip indexes: the pod still counts against the
    node-level limit sums (capacity check input) but places nothing."""
    bad1 = make_pod("bad1", limits={TPU: 1},
                    annotations={CHIP_INDEX_ANNOTATION: "nope"}, node_name="n1")
    bad2 = make_pod("bad2", limits={TPU: 1},
                    annotations={CHIP_INDEX_ANNOTATION: "7"}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([bad1, bad2]))
    assert cn.free_chip_indexes() == [0, 1, 2, 3]  # nothing placed
    assert cn.used_chips_limit == 2                # but capacity-counted


def test_chipnode_hbm_from_accelerator_catalog():
    """A node advertising chips but no google.com/tpu-memory falls back to
    the accelerator catalog's per-chip HBM (api/topology.py)."""
    from tpusched.api.resources import make_resources
    from tpusched.api.topology import ACCELERATORS, LABEL_ACCELERATOR
    from tpusched.testing import make_node
    cap = make_resources(cpu=8, memory="16Gi", pods=110)
    cap[TPU] = 4
    node = make_node("bare", capacity=cap,
                     labels={LABEL_ACCELERATOR: "tpu-v5e"})
    cn = ChipNode.from_node_info(NodeInfo(node, []))
    assert cn.chips[0].hbm_mb == ACCELERATORS["tpu-v5e"].hbm_mb_per_chip


def test_pod_tpu_limits_multi_container_and_requests_fallback():
    from tpusched.api.core import Container
    from tpusched.plugins.tpuslice.chip_node import pod_tpu_limits
    p = make_pod("multi")
    p.spec.containers = [Container(limits={TPU: 2}),
                         Container(limits={TPU: 1})]
    assert pod_tpu_limits(p) == (3, True, 0, False)
    # requests-only containers fall back (extended resources force
    # requests==limits in k8s, so this is behavior-preserving)
    p.spec.containers = [Container(requests={TPU_MEMORY: 512})]
    assert pod_tpu_limits(p) == (0, False, 512, True)


def test_fractional_pod_occupies_first_index_only():
    frac = make_pod("f", limits={TPU_MEMORY: 1000},
                    annotations={CHIP_INDEX_ANNOTATION: "1,2"}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([frac]))
    assert cn.chips[1].used_mb == 1000
    assert cn.chips[2].used_mb == 0


def test_mem_fit_skips_monopoly_chips():
    mono = make_pod("m", limits={TPU: 2},
                    annotations={CHIP_INDEX_ANNOTATION: "0,1"}, node_name="n1")
    cn = ChipNode.from_node_info(node_info_with([mono]))
    assert cn.mem_fit_indexes(1024) == [2, 3]


def test_fractional_tenants_pack_then_overflow_e2e():
    """Live cluster: three 40GB fractional pods — the first two pack one
    chip (bin-pack by least remaining), the third overflows to a new chip;
    a whole-chip pod then takes a free chip, never the fractional ones."""
    from tpusched.testing import TestCluster
    profile = PluginProfile(filter=["NodeResourcesFit", "TpuSlice"],
                            score=[("TpuSlice", 1)],
                            reserve=["TpuSlice"], bind=["TpuSlice"])
    with TestCluster(profile=profile) as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        gb40 = 40 * 1024
        fr = [make_pod(f"fr{i}", limits={TPU_MEMORY: gb40}) for i in range(3)]
        c.create_pods(fr)
        assert c.wait_for_pods_scheduled([p.key for p in fr])
        idx = [c.pod(p.key).meta.annotations[CHIP_INDEX_ANNOTATION]
               for p in fr]
        assert idx[0] == idx[1] != idx[2]  # two pack, third overflows
        whole = make_pod("whole", limits={TPU: 2})
        c.create_pods([whole])
        assert c.wait_for_pods_scheduled([whole.key])
        whole_idx = set(c.pod(whole.key).meta.annotations[
            CHIP_INDEX_ANNOTATION].split(","))
        assert not (whole_idx & set(idx))  # disjoint from fractional chips


def test_annotations_as_truth_restart_e2e():
    """A second scheduler attached to the same API state rebuilds chip
    occupancy purely from bound pods' annotations (SURVEY §5: the API server
    is the checkpoint) — it must refuse a 4th whole chip but admit a 1-chip
    pod on the remaining free chip."""
    from tpusched.apiserver import server as srv
    from tpusched.testing import TestCluster
    profile = PluginProfile(filter=["NodeResourcesFit", "TpuSlice"],
                            score=[("TpuSlice", 1)],
                            reserve=["TpuSlice"], bind=["TpuSlice"])
    with TestCluster(profile=profile) as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        first = [make_pod(f"a{i}", limits={TPU: 1}) for i in range(3)]
        c.create_pods(first)
        assert c.wait_for_pods_scheduled([p.key for p in first])
        api = c.api
    # control plane survives; a fresh scheduler process attaches
    with TestCluster(profile=profile, api=api) as c2:
        late_big = make_pod("late-big", limits={TPU: 2})
        late_fit = make_pod("late-fit", limits={TPU: 1})
        c2.create_pods([late_big, late_fit])
        assert c2.wait_for_pods_scheduled([late_fit.key])
        assert c2.wait_for_pods_unscheduled([late_big.key], hold=1.0)
        used = set()
        for p in first:
            used |= set(c2.pod(p.key).meta.annotations[
                CHIP_INDEX_ANNOTATION].split(","))
        fit_idx = c2.pod(late_fit.key).meta.annotations[CHIP_INDEX_ANNOTATION]
        assert fit_idx not in used and len(used) == 3
