"""Table-driven topology matrix (round-2 depth pass).

The reference's NUMA plugin carries a 989-LoC table suite tracked against
its TESTS.md (/root/reference/pkg/noderesourcetopology/filter_test.go); this
file is the equivalent sweep for the ICI-torus engine, closing the gaps the
round-1 TESTS.md tracked:

- placement enumeration differentially tested against an independent
  brute-force enumerator across every accelerator x wrap pattern x rotation
  (including rotation-on-wrapped-axis interactions);
- malformed/duplicate/degenerate TpuTopology CRs;
- placement-cache invalidation when a CR's resource_version changes;
- fragmentation, then defrag after gang deletion, at the scheduler level.
"""
import itertools

import pytest

from tpusched.api.resources import TPU
from tpusched.api.topology import ACCELERATORS, TpuTopology, TpuTopologySpec
from tpusched.api.meta import ObjectMeta
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool)
from tpusched.topology.torus import (HOST_EXTENT, HostGrid,
                                     candidate_host_blocks,
                                     enumerate_placements,
                                     validate_slice_shape)


# -- independent brute-force reference ---------------------------------------

def brute_force_placements(chip_shape, acc_name, dims, wrap):
    """Every distinct host-coordinate set reachable by (rotation, anchor):
    written independently of torus.py (no shared helpers) so the two can
    only agree by both being right."""
    extent = HOST_EXTENT[acc_name]
    host_dims = tuple(d // e for d, e in zip(dims, extent))
    rank = len(host_dims)
    results = set()
    for perm in set(itertools.permutations(chip_shape)):
        if any(perm[i] % extent[i] for i in range(rank)):
            continue
        hb = tuple(perm[i] // extent[i] for i in range(rank))
        if any(hb[i] > host_dims[i] for i in range(rank)):
            continue
        axis_anchors = []
        for i in range(rank):
            if hb[i] == host_dims[i]:
                axis_anchors.append([0])          # full axis: one anchor
            elif wrap[i]:
                axis_anchors.append(list(range(host_dims[i])))
            else:
                axis_anchors.append(list(range(host_dims[i] - hb[i] + 1)))
        for anchor in itertools.product(*axis_anchors):
            hosts = frozenset(
                tuple((anchor[i] + o[i]) % host_dims[i] for i in range(rank))
                for o in itertools.product(*(range(b) for b in hb)))
            results.add(hosts)
    return results


def grid_for(acc_name, dims, wrap):
    extent = HOST_EXTENT[acc_name]
    hosts = {}
    ranges = [range(0, d, e) for d, e in zip(dims, extent)]
    for c in itertools.product(*ranges):
        hosts["h" + "-".join(map(str, c))] = c
    spec = TpuTopologySpec(pool="p", accelerator=acc_name, dims=tuple(dims),
                           wrap=tuple(wrap), hosts=hosts,
                           chips_per_host=ACCELERATORS[acc_name].chips_per_host)
    g = HostGrid.from_spec(spec)
    assert g is not None
    return g


# the full sweep: accelerator x pool dims x wrap pattern x chip shape.
# Shapes are chosen to exercise: exact tile, rotation-required, wraparound-
# required, full-axis, too-big, and non-tiling (expected 0 placements).
_SWEEP = []
for _acc, _dims, _shapes in [
    ("tpu-v5p", (8, 4, 4), [(4, 4, 4), (8, 4, 2), (2, 2, 4), (4, 2, 2),
                            (8, 4, 4), (2, 2, 3), (16, 4, 4), (4, 4, 2)]),
    ("tpu-v4", (4, 4, 4), [(2, 2, 4), (4, 4, 4), (2, 2, 1), (3, 2, 2)]),
    ("tpu-v5e", (8, 8), [(4, 4), (8, 2), (2, 8), (8, 8), (6, 4), (2, 2)]),
    ("tpu-v6e", (8, 4), [(4, 4), (8, 2), (4, 2), (8, 4), (2, 4)]),
]:
    _rank = len(_dims)
    for _wrap in itertools.product([False, True], repeat=_rank):
        for _shape in _shapes:
            _SWEEP.append((_acc, _dims, _wrap, _shape))


@pytest.mark.parametrize("acc,dims,wrap,shape", _SWEEP)
def test_enumeration_matches_brute_force(acc, dims, wrap, shape):
    g = grid_for(acc, dims, wrap)
    got = set(enumerate_placements(g, shape))
    want = brute_force_placements(shape, acc, dims, wrap)
    assert got == want
    err = validate_slice_shape(shape, ACCELERATORS[acc], dims)
    # validation must agree with enumeration about impossibility — except
    # for wraparound-only feasibility, which validation (host-count check)
    # cannot rule out; it may only be MORE permissive, never less
    if err is not None:
        assert got == set()


def test_rotation_onto_wrapped_axis_only():
    """Rows 2x3 interaction from TESTS.md known gaps: a v5p pool wrapped on
    axis 0 only; a 2x2x6-chip slice on an 8x4x4... use dims where the shape's
    long axis exceeds every unwrapped axis span but rides the wrapped one
    split across the seam."""
    # host dims (4,2,4) from chip dims (8,4,4); block (1,1,3) in hosts fits
    # axis 2 (span 4) without wrap; rotate so the 3 lands on axis 0 -> needs
    # anchors 2,3 to wrap. Wrapping axis 0 must strictly add placements.
    unwrapped = grid_for("tpu-v5p", (8, 4, 4), (False, False, False))
    wrapped = grid_for("tpu-v5p", (8, 4, 4), (True, False, False))
    shape = (6, 2, 2)   # hosts (3,1,2) identity; rotations put 3 on any axis
    n_unwrapped = len(enumerate_placements(unwrapped, shape))
    n_wrapped = len(enumerate_placements(wrapped, shape))
    assert n_wrapped > n_unwrapped
    # and both agree with brute force (also covered by the sweep)
    assert n_wrapped == len(
        brute_force_placements(shape, "tpu-v5p", (8, 4, 4),
                               (True, False, False)))


# -- malformed CRs ------------------------------------------------------------

def _spec(**kw):
    base = dict(pool="p", accelerator="tpu-v5p", dims=(4, 4, 4),
                wrap=(False, False, False),
                hosts={"h0": (0, 0, 0), "h1": (2, 0, 0)}, chips_per_host=4)
    base.update(kw)
    return TpuTopologySpec(**base)


def test_malformed_cr_unknown_accelerator():
    assert HostGrid.from_spec(_spec(accelerator="tpu-v9")) is None


def test_malformed_cr_rank_mismatch_dims():
    assert HostGrid.from_spec(_spec(dims=(4, 4))) is None


def test_malformed_cr_host_coord_rank_mismatch_drops_host():
    g = HostGrid.from_spec(_spec(hosts={"bad": (0, 0), "ok": (0, 0, 0)}))
    assert g is not None
    assert "bad" not in g.coord_of and "ok" in g.coord_of


def test_malformed_cr_out_of_torus_coord_drops_host():
    g = HostGrid.from_spec(_spec(hosts={"out": (8, 0, 0), "ok": (2, 0, 0)}))
    assert g is not None
    assert "out" not in g.coord_of and "ok" in g.coord_of


def test_malformed_cr_duplicate_host_coords_last_wins_consistently():
    """Two nodes claiming one torus cell: the grid must stay internally
    consistent (node_of and coord_of agree on a single winner), never map
    one cell to two nodes."""
    g = HostGrid.from_spec(_spec(hosts={"a": (0, 0, 0), "b": (0, 0, 0)}))
    assert g is not None
    winner = g.node_of[(0, 0, 0)]
    assert winner in ("a", "b")
    assert g.coord_of[winner] == (0, 0, 0)
    assert len([n for n, c in g.coord_of.items() if c == (0, 0, 0)]) >= 1
    assert list(g.node_of.values()).count(winner) == 1


@pytest.mark.parametrize("shape,msg", [
    ((4, 4), "axes"),                 # rank mismatch
    ((0, 4, 4), "positive"),          # degenerate axis
    ((-2, 4, 4), "positive"),
    ((3, 3, 3), "rotation"),          # never tiles the 2x2x1 extent
    ((16, 4, 4), "rotation"),         # exceeds the pool on every rotation
])
def test_validate_slice_shape_rejections(shape, msg):
    err = validate_slice_shape(shape, ACCELERATORS["tpu-v5p"], (4, 4, 4))
    assert err is not None and msg in err


# -- scheduler-level: cache invalidation + defrag -----------------------------

def _gang(c, name, members, shape="4x4x2", chips=4):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape=shape,
        tpu_accelerator="tpu-v5p"))
    pods = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: chips})
            for i in range(members)]
    c.create_pods(pods)
    return pods


def test_topology_cache_invalidated_on_cr_update():
    """A gang needing wraparound stays Pending on an unwrapped pool; patching
    the SAME CR to wrap (bumping resource_version) must flow through the
    placement cache and admit it."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                              denied_s=1)) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(8, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        # occupy the middle of axis 0 so a 4x4x4 block fits only wrapped
        # around the seam: blockers on host-axis-0 rows 1 and 2
        blockers = []
        for node, hc in topo.spec.hosts.items():
            if hc[0] in (2, 4):   # chip rows 2,4 -> host rows 1,2
                blockers.append(make_pod(f"blk-{node}", limits={TPU: 4},
                                         node_name=node))
        for b in blockers:
            c.api.create(srv.PODS, b)
        gang = _gang(c, "ring", members=16, shape="4x4x4")
        assert c.wait_for_pods_unscheduled([p.key for p in gang], hold=1.5)
        c.api.patch(srv.TPU_TOPOLOGIES, topo.key,
                    lambda t: setattr(t.spec, "wrap", (True, False, False)))
        assert c.wait_for_pods_scheduled([p.key for p in gang], timeout=30)
        rows = {topo.spec.hosts[c.pod(p.key).spec.node_name][0]
                for p in gang}
        assert rows == {0, 6}   # the wrapped block across the seam


def test_defrag_after_gang_deletion():
    """Fill the torus with two gangs, delete one, and a third gang must land
    exactly in the freed contiguous block (fragmentation bookkeeping)."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                              denied_s=1)) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        g1 = _gang(c, "left", members=8)    # 4x4x2 = half the pool
        assert c.wait_for_pods_scheduled([p.key for p in g1], timeout=30)
        g2 = _gang(c, "right", members=8)
        assert c.wait_for_pods_scheduled([p.key for p in g2], timeout=30)
        g3 = _gang(c, "wait", members=8)
        assert c.wait_for_pods_unscheduled([p.key for p in g3], hold=1.5)
        g1_hosts = {c.pod(p.key).spec.node_name for p in g1}
        for p in g1:
            c.api.delete(srv.PODS, p.key)
        assert c.wait_for_pods_scheduled([p.key for p in g3], timeout=30)
        assert {c.pod(p.key).spec.node_name for p in g3} == g1_hosts


def test_full_pool_fragmented_gang_blocked_until_contiguous():
    """Foreign single-host pods scattered so no contiguous half-pool block
    survives: the gang must stay Pending even though enough TOTAL chips are
    free (contiguity, not capacity, is the constraint)."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5,
                                              denied_s=1)) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        # host grid is (2,2,4); a 4x4x2-chip gang needs a (2,2,2) host block
        # (or a (2,1,4)/(1,2,4) rotation). Blockers at host coords (0,0,1)
        # and (1,1,2) — chip coords (0,0,1), (2,2,2) — intersect every
        # placement of every rotation while freeing 14 of 16 hosts.
        blocked_chip_coords = {(0, 0, 1), (2, 2, 2)}
        blockers = [node for node, hc in topo.spec.hosts.items()
                    if tuple(hc) in blocked_chip_coords]
        assert len(blockers) == 2
        for i, node in enumerate(blockers):
            c.api.create(srv.PODS, make_pod(f"blk-{i}", limits={TPU: 4},
                                            node_name=node))
        gang = _gang(c, "frag", members=8)
        assert c.wait_for_pods_unscheduled([p.key for p in gang], hold=1.5)
        # free every blocker: the gang must now bind
        for i in range(len(blockers)):
            c.api.delete(srv.PODS, f"default/blk-{i}")
        assert c.wait_for_pods_scheduled([p.key for p in gang], timeout=30)
