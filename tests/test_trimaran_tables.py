"""Table-driven Trimaran scoring math — the reference's analysis_test.go
(322 LoC) + targetloadpacking_test.go score tables at full depth. Basic
curve/e2e coverage lives in tests/test_trimaran.py."""
import pytest

from tpusched.api.core import Container
from tpusched.api.resources import CPU, MEMORY, TPU, make_resources
from tpusched.config.types import (LoadVariationRiskBalancingArgs,
                                   TargetLoadPackingArgs)
from tpusched.fwk import CycleState, PluginProfile
from tpusched.plugins.trimaran import (AVERAGE, CPU_TYPE, LATEST, MEMORY_TYPE,
                                       Metric, STD, LoadVariationRiskBalancing,
                                       TargetLoadPacking)
from tpusched.plugins.trimaran.loadvariationriskbalancing import (
    ResourceStats, create_resource_stats)
from tpusched.plugins.trimaran.watcher import TPU_TYPE, get_resource_data
from tpusched.testing import make_node, make_pod, make_tpu_node, new_test_framework
from tests.test_trimaran import make_handle, metrics_for


# -- ResourceStats.compute_score (analysis.go:48-78) --------------------------

@pytest.mark.parametrize(
    "used_avg,used_stdev,req,capacity,margin,sensitivity,expected",
    [
        # id: nominal — risk = (0.5 + 0.1)/2 = 0.3
        (50.0, 10.0, 0.0, 100.0, 1.0, 1.0, 70),
        # idle node, no variance → perfect score
        (0.0, 0.0, 0.0, 100.0, 1.0, 1.0, 100),
        # fully loaded, fully variable → worst score
        (100.0, 100.0, 0.0, 100.0, 1.0, 1.0, 0),
        # invalid capacity → score 0 (guard, analysis.go:49-52)
        (50.0, 10.0, 0.0, 0.0, 1.0, 1.0, 0),
        (50.0, 10.0, 0.0, -5.0, 1.0, 1.0, 0),
        # request pushes mu past 1 → clamped: (1 + 0)/2 = 0.5
        (80.0, 0.0, 50.0, 100.0, 1.0, 1.0, 50),
        # negative request treated as 0
        (50.0, 0.0, -10.0, 100.0, 1.0, 1.0, 75),
        # measured average above capacity clamps to capacity
        (150.0, 0.0, 0.0, 100.0, 1.0, 1.0, 50),
        # stdev above capacity clamps to capacity → sigma 1
        (0.0, 150.0, 0.0, 100.0, 1.0, 1.0, 50),
        # margin scales sigma: risk = (0.4 + 2*0.2)/2 = 0.4
        (40.0, 20.0, 0.0, 100.0, 2.0, 1.0, 60),
        # margin product clamps at 1: (0 + min(2*0.8,1))/2 = 0.5
        (0.0, 80.0, 0.0, 100.0, 2.0, 1.0, 50),
        # sensitivity 2 → sigma^(1/2): (0 + sqrt(0.25))/2 = 0.25
        (0.0, 25.0, 0.0, 100.0, 1.0, 2.0, 75),
        # sensitivity 0.5 → sigma^2 amplifies: (0 + 0.25)/2
        (0.0, 50.0, 0.0, 100.0, 1.0, 0.5, round((1 - 0.125) * 100)),
        # Go pow(+Inf) edge at sensitivity 0: sigma<1 → 0 (analysis.go quirk)
        (40.0, 20.0, 0.0, 100.0, 1.0, 0.0, 80),
        # ...but sigma == 1 stays 1
        (0.0, 100.0, 0.0, 100.0, 1.0, 0.0, 50),
    ])
def test_lvrb_compute_score_table(used_avg, used_stdev, req, capacity,
                                  margin, sensitivity, expected):
    rs = ResourceStats(used_avg=used_avg, used_stdev=used_stdev, req=req,
                       capacity=capacity)
    assert round(rs.compute_score(margin, sensitivity)) == expected


def test_create_resource_stats_memory_converts_to_mb():
    """Memory stats operate in MB (analysis.go:81-131): a 1Gi node at 50%
    average yields used_avg 512 MB against a 1024 MB capacity."""
    node = make_node("n1", capacity=make_resources(cpu=10, memory="1Gi"))
    metrics = [Metric(type=MEMORY_TYPE, operator=AVERAGE, value=50.0),
               Metric(type=MEMORY_TYPE, operator=STD, value=10.0)]
    rs, ok = create_resource_stats(metrics, node, {MEMORY: 256 * 1024 * 1024},
                                   MEMORY, MEMORY_TYPE)
    assert ok
    assert rs.capacity == 1024.0
    assert rs.used_avg == 512.0
    assert rs.used_stdev == pytest.approx(102.4)
    assert rs.req == 256.0


def test_create_resource_stats_absent_type_not_found():
    node = make_node("n1")
    metrics = [Metric(type=CPU_TYPE, operator=AVERAGE, value=50.0)]
    rs, ok = create_resource_stats(metrics, node, {}, MEMORY, MEMORY_TYPE)
    assert not ok and rs is None


@pytest.mark.parametrize("metrics,want_avg,want_std,want_found", [
    # Average + Std, plus noise of another type
    ([Metric(type=CPU_TYPE, operator=AVERAGE, value=40.0),
      Metric(type=CPU_TYPE, operator=STD, value=10.0),
      Metric(type=MEMORY_TYPE, operator=AVERAGE, value=99.0)], 40.0, 10.0, True),
    # Latest stands in for Average when no Average present
    ([Metric(type=CPU_TYPE, operator=LATEST, value=30.0)], 30.0, 0.0, True),
    # ...but a real Average wins over Latest regardless of order
    ([Metric(type=CPU_TYPE, operator=LATEST, value=30.0),
      Metric(type=CPU_TYPE, operator=AVERAGE, value=40.0)], 40.0, 0.0, True),
    ([Metric(type=CPU_TYPE, operator=AVERAGE, value=40.0),
      Metric(type=CPU_TYPE, operator=LATEST, value=30.0)], 40.0, 0.0, True),
    # empty-string operator behaves like Latest (backward compat)
    ([Metric(type=CPU_TYPE, operator="", value=25.0)], 25.0, 0.0, True),
    # nothing of the requested type
    ([Metric(type=MEMORY_TYPE, operator=AVERAGE, value=40.0)], 0.0, 0.0, False),
    ([], 0.0, 0.0, False),
])
def test_get_resource_data_table(metrics, want_avg, want_std, want_found):
    avg, std, found = get_resource_data(metrics, CPU_TYPE)
    assert (avg, std, found) == (want_avg, want_std, want_found)


def test_lvrb_tpu_duty_cycle_joins_min():
    """TPU-native extension: a host hot on tensorcore duty cycle loses the
    min() even when CPU looks idle."""
    node = make_tpu_node("n1", chips=4)
    handle = make_handle([node])
    plugin = LoadVariationRiskBalancing(
        LoadVariationRiskBalancingArgs(), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=CPU_TYPE, operator=AVERAGE, value=0.0),
            Metric(type=CPU_TYPE, operator=STD, value=0.0),
            Metric(type=TPU_TYPE, operator=AVERAGE, value=90.0),
            Metric(type=TPU_TYPE, operator=STD, value=10.0),
        ]}))
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), make_pod("p"), "n1")
    # cpu score 100; tpu risk = (0.9 + 0.1)/2 = 0.5 → 50; min wins
    assert s == 50


def test_lvrb_single_dimension_stands_alone():
    node = make_node("n1", capacity=make_resources(cpu=10, memory="1Gi"))
    handle = make_handle([node])
    plugin = LoadVariationRiskBalancing(
        LoadVariationRiskBalancingArgs(), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=MEMORY_TYPE, operator=AVERAGE, value=40.0)]}))
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == 80  # memory-only: risk 0.2, no min() partner


def test_lvrb_no_valid_dimensions_min_score():
    node = make_node("n1")
    handle = make_handle([node])
    plugin = LoadVariationRiskBalancing(
        LoadVariationRiskBalancingArgs(), handle,
        provider=lambda: metrics_for({"n1": []}))
    plugin.collector.update_metrics()
    s, status = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == 0 and status.is_success()


# -- TargetLoadPacking score curve (targetloadpacking.go:253-269) -------------

@pytest.mark.parametrize("measured_pct,expected", [
    # cap 10 cores; pod defaults to 1000m = +10%. target 40.
    # rising edge: (100-40)*predicted/40 + 40
    (0.0, 55),    # predicted 10
    (10.0, 70),   # predicted 20
    (20.0, 85),   # predicted 30
    (30.0, 100),  # predicted exactly at target
    # falling edge: 40*(100-predicted)/60
    (35.0, 37),   # predicted 45 → 36.67
    (50.0, 27),   # predicted 60 → 26.67
    (60.0, 20),   # predicted 70
    (80.0, 7),    # predicted 90 → 6.67
    (90.0, 0),    # predicted exactly 100 → 0 (not the >100 branch)
    (95.0, 0),    # predicted 105 → MinScore branch
])
def test_tlp_score_curve_table(measured_pct, expected):
    node = make_node("n1", capacity=make_resources(cpu=10, memory="64Gi"))
    handle = make_handle([node])
    plugin = TargetLoadPacking(
        TargetLoadPackingArgs(), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=CPU_TYPE, operator=AVERAGE, value=measured_pct)]}))
    plugin.collector.update_metrics()
    s, status = plugin.score(CycleState(), make_pod("p"), "n1")
    assert status.is_success()
    assert s == expected


@pytest.mark.parametrize("measured_pct,expected", [
    # custom target 60: peak moves right
    (0.0, 67),    # (100-60)*10/60 + 60 = 66.67
    (50.0, 100),  # predicted 60 = target
    (70.0, 30),   # 60*(100-80)/40
])
def test_tlp_custom_target_table(measured_pct, expected):
    node = make_node("n1", capacity=make_resources(cpu=10, memory="64Gi"))
    handle = make_handle([node])
    plugin = TargetLoadPacking(
        TargetLoadPackingArgs(target_utilization=60), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=CPU_TYPE, operator=AVERAGE, value=measured_pct)]}))
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == expected


def test_tlp_prediction_sums_containers_and_overhead():
    """predictUtilisation per container + pod overhead
    (targetloadpacking.go:286-294, :229-232)."""
    handle = make_handle([make_node("n1")])
    plugin = TargetLoadPacking(TargetLoadPackingArgs(), handle,
                               provider=lambda: None)
    pod = make_pod("p")
    pod.spec.containers = [Container(limits={CPU: 2000}),
                           Container(requests={CPU: 1000}),
                           Container()]
    pod.spec.overhead = {CPU: 250}
    # 2000 (limit) + 1500 (request×1.5) + 1000 (default) + 250 overhead
    assert plugin._pod_predicted_millis(pod) == 4750


def test_tlp_latest_operator_accepted():
    node = make_node("n1", capacity=make_resources(cpu=10, memory="64Gi"))
    handle = make_handle([node])
    plugin = TargetLoadPacking(
        TargetLoadPackingArgs(), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=CPU_TYPE, operator=LATEST, value=30.0)]}))
    plugin.collector.update_metrics()
    s, _ = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == 100


def test_tlp_node_without_metrics_entry_min_score():
    node = make_node("n1", capacity=make_resources(cpu=10, memory="64Gi"))
    handle = make_handle([node])
    plugin = TargetLoadPacking(
        TargetLoadPackingArgs(), handle,
        provider=lambda: metrics_for({"other-node": [
            Metric(type=CPU_TYPE, operator=AVERAGE, value=10.0)]}))
    plugin.collector.update_metrics()
    s, status = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == 0 and status.is_success()


def test_tlp_zero_cpu_capacity_min_score():
    node = make_node("n1", capacity={CPU: 0, MEMORY: 1024, "pods": 10})
    handle = make_handle([node])
    plugin = TargetLoadPacking(
        TargetLoadPackingArgs(), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=CPU_TYPE, operator=AVERAGE, value=10.0)]}))
    plugin.collector.update_metrics()
    s, status = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == 0 and status.is_success()


def test_tlp_cpu_metric_missing_from_node_entry():
    node = make_node("n1", capacity=make_resources(cpu=10, memory="64Gi"))
    handle = make_handle([node])
    plugin = TargetLoadPacking(
        TargetLoadPackingArgs(), handle,
        provider=lambda: metrics_for({"n1": [
            Metric(type=MEMORY_TYPE, operator=AVERAGE, value=10.0)]}))
    plugin.collector.update_metrics()
    s, status = plugin.score(CycleState(), make_pod("p"), "n1")
    assert s == 0 and status.is_success()
