"""Accelerator-catalog coverage: gang + slice placement end-to-end on every
supported TPU generation (v4/v5e/v5p/v6e), exercising both 2-D mesh and 3-D
torus host extents and the 8-chips-per-host v6e layout."""
from __future__ import annotations

import pytest

from tpusched.api.resources import TPU
from tpusched.api.topology import ACCELERATORS
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.plugins.topologymatch import COORD_ANNOTATION
from tpusched.testing import TestCluster, make_pod, make_pod_group, make_tpu_pool
from tpusched.topology.torus import HOST_EXTENT


def test_catalog_is_consistent():
    for name, acc in ACCELERATORS.items():
        extent = HOST_EXTENT[name]
        assert len(extent) == acc.ici_dims == len(acc.max_dims)
        chips_in_extent = 1
        for e in extent:
            chips_in_extent *= e
        assert chips_in_extent == acc.chips_per_host
        # every max dim is tileable by the host extent
        assert all(d % e == 0 for d, e in zip(acc.max_dims, extent))


@pytest.mark.parametrize("accelerator,pool_dims,slice_shape,gang", [
    ("tpu-v4", (4, 4, 4), "2x2x4", 4),     # 3-D torus, 16 chips = 4 hosts
    ("tpu-v5e", (8, 8), "4x4", 4),         # 2-D mesh, 16 chips = 4 hosts
    ("tpu-v5p", (4, 4, 4), "4x4x1", 4),    # 3-D torus
    ("tpu-v6e", (8, 8), "4x4", 2),         # 2-D, 8 chips/host ⇒ 2 hosts
])
def test_gang_slice_placement_per_generation(accelerator, pool_dims,
                                             slice_shape, gang):
    acc = ACCELERATORS[accelerator]
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=10)) as c:
        topo, nodes = make_tpu_pool("pool", accelerator=accelerator,
                                    dims=pool_dims)
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("g", min_member=gang,
                                    tpu_slice_shape=slice_shape,
                                    tpu_accelerator=accelerator))
        pods = [make_pod(f"w{i}", pod_group="g",
                         limits={TPU: acc.chips_per_host})
                for i in range(gang)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods])
        # every member landed on a distinct host with a torus coordinate
        placed = {c.pod(p.key).spec.node_name for p in pods}
        assert len(placed) == gang
        coords = {c.pod(p.key).meta.annotations[COORD_ANNOTATION]
                  for p in pods}
        assert len(coords) == gang


def test_v6e_eight_chip_host_packs_two_four_chip_pods():
    """Sub-host pods pack a single 8-chip v6e host before spilling."""
    with TestCluster() as c:
        topo, nodes = make_tpu_pool("pool", accelerator="tpu-v6e", dims=(4, 2))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)  # one host, 8 chips
        assert len(nodes) == 1
        pods = [make_pod(f"w{i}", limits={TPU: 4}) for i in range(2)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods])
        chips = set()
        for p in pods:
            ann = c.pod(p.key).meta.annotations["tpuslice.scheduling.tpu.dev/chip-index"]
            chips.update(ann.split(","))
        assert len(chips) == 8  # disjoint halves of the same host
        c.create_pods([make_pod("overflow", limits={TPU: 1})])
        assert c.wait_for_pods_unscheduled(["default/overflow"])
