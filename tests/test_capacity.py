"""CapacityScheduling tests: ElasticQuota borrowing, max caps, quota-aware
preemption, PDB reprieve. Reference analogs: pkg/capacityscheduling tests +
test/integration/capacity_scheduling_test.go. BASELINE eval config #4:
2 teams contending on a v5p pool."""
import time

from tpusched.api.core import PodDisruptionBudget
from tpusched.api.meta import ObjectMeta
from tpusched.api.resources import CPU, TPU
from tpusched.apiserver import server as srv
from tpusched.config.profiles import capacity_profile
from tpusched.plugins.capacity import ElasticQuotaInfo, ElasticQuotaInfos
from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                              make_tpu_node)


# -- unit: quota accounting ---------------------------------------------------

def test_eq_info_bounds():
    info = ElasticQuotaInfo("team-a", min={TPU: 8}, max={TPU: 16})
    info.reserve_resource({TPU: 8})
    assert not info.used_over_min()
    assert info.used_over_min_with({TPU: 1})
    assert not info.used_over_max_with({TPU: 8})
    assert info.used_over_max_with({TPU: 9})
    # resources absent from the bound are unlimited
    assert not info.used_over_max_with({CPU: 10**9})


def test_eq_info_idempotent_pod_accounting():
    info = ElasticQuotaInfo("team-a", min={TPU: 8})
    pod = make_pod("p", namespace="team-a", limits={TPU: 4})
    info.add_pod_if_not_present(pod)
    info.add_pod_if_not_present(pod)
    assert info.used[TPU] == 4
    info.delete_pod_if_present(pod)
    info.delete_pod_if_present(pod)
    assert info.used[TPU] == 0


def test_aggregated_borrow_gate():
    infos = ElasticQuotaInfos()
    infos["a"] = ElasticQuotaInfo("a", min={TPU: 8})
    infos["b"] = ElasticQuotaInfo("b", min={TPU: 8})
    infos["a"].reserve_resource({TPU: 12})  # a borrows 4 from b's min
    assert not infos.aggregated_used_over_min_with({TPU: 4})
    assert infos.aggregated_used_over_min_with({TPU: 5})
    # clone isolation
    c = infos.clone()
    c["a"].reserve_resource({TPU: 100})
    assert infos["a"].used[TPU] == 12


# -- integration --------------------------------------------------------------

def two_team_cluster():
    c = TestCluster(profile=capacity_profile())
    # 4 hosts x 4 chips = 16 chips total
    c.add_nodes([make_tpu_node(f"h{i}", chips=4) for i in range(4)])
    c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
        "quota-a", "team-a", min={TPU: 8}, max={TPU: 16}))
    c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
        "quota-b", "team-b", min={TPU: 8}, max={TPU: 16}))
    return c


def team_pods(c, team, count, chips=4, prefix=None, priority=0):
    pods = [make_pod(f"{prefix or team}-{i}", namespace=team,
                     limits={TPU: chips}, priority=priority)
            for i in range(count)]
    c.create_pods(pods)
    return pods


def test_borrowing_up_to_aggregate_min():
    with two_team_cluster() as c:
        # team-a takes all 16 chips: 8 guaranteed + 8 borrowed from b's idle min
        pods = team_pods(c, "team-a", 4)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=10)


def test_max_cap_enforced():
    with two_team_cluster() as c:
        # raise capacity so only the quota, not the chips, is the limit
        c.add_nodes([make_tpu_node(f"extra{i}", chips=4) for i in range(2)])
        pods = team_pods(c, "team-a", 4)          # 16 chips = max
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=10)
        over = team_pods(c, "team-a", 1, prefix="over")
        assert c.wait_for_pods_unscheduled([over[0].key], hold=1.0)


def test_reclaim_preempts_borrowers():
    """BASELINE config #4: team-b reclaims its min by evicting team-a's
    borrowed pods (cross-quota victim selection, :539-553)."""
    with two_team_cluster() as c:
        a_pods = team_pods(c, "team-a", 4)   # 16 chips: 8 borrowed
        assert c.wait_for_pods_scheduled([p.key for p in a_pods], timeout=10)
        b_pods = team_pods(c, "team-b", 2)   # 8 chips, within b's min
        assert c.wait_for_pods_scheduled([p.key for p in b_pods], timeout=20)
        # exactly two of team-a's pods were preempted
        surviving = [p for p in a_pods if c.pod(p.key) is not None]
        assert len(surviving) == 2
        events = [e for e in c.api.events() if e.reason == "Preempted"]
        assert len(events) >= 2


def test_no_preemption_when_borrower_within_min():
    """team-b over-min pods cannot evict team-a pods that are within a's min."""
    with two_team_cluster() as c:
        a_pods = team_pods(c, "team-a", 2)   # 8 chips = a's min, no borrowing
        assert c.wait_for_pods_scheduled([p.key for p in a_pods], timeout=10)
        b_pods = team_pods(c, "team-b", 3)   # 12 chips: 8 fit free, 4th over
        # two of b's pods fit on the free chips; the third would need to
        # preempt a — but a is within min, so nothing is evicted
        time.sleep(2.0)
        assert all(c.pod(p.key) is not None for p in a_pods)
        bound_b = [p for p in b_pods if c.pod_scheduled(p.key)]
        assert len(bound_b) == 2


def test_same_quota_priority_preemption():
    """Over-min preemptor evicts lower-priority pods of its own quota
    (:526-538)."""
    with two_team_cluster() as c:
        # fill team-a to max with low-priority pods
        low = team_pods(c, "team-a", 4, priority=1, prefix="low")
        assert c.wait_for_pods_scheduled([p.key for p in low], timeout=10)
        # a high-priority team-a pod must evict a low one (a is over min)
        high = team_pods(c, "team-a", 1, priority=100, prefix="high")
        assert c.wait_for_pods_scheduled([high[0].key], timeout=20)
        assert sum(1 for p in low if c.pod(p.key) is None) == 1


def test_pdb_protected_victims_reprieved_last():
    with two_team_cluster() as c:
        a_pods = team_pods(c, "team-a", 4)
        assert c.wait_for_pods_scheduled([p.key for p in a_pods], timeout=10)
        # protect ALL team-a pods with a zero-disruption PDB; preemption
        # should still go through (PDB is best-effort) but count violations
        for p in a_pods:
            c.api.patch(srv.PODS, p.key,
                        lambda o: o.meta.labels.update({"app": "a"}))
        pdb = PodDisruptionBudget(
            meta=ObjectMeta(name="protect-a", namespace="team-a"),
            selector={"app": "a"}, disruptions_allowed=0)
        c.api.create(srv.PDBS, pdb)
        b_pods = team_pods(c, "team-b", 2)
        assert c.wait_for_pods_scheduled([p.key for p in b_pods], timeout=20)


def test_nominated_preemptor_counts_against_quota():
    """PreFilter's nominated-pod accounting (capacity_scheduling.go:232-268):
    a nominated-but-unbound preemptor already consumes quota headroom, so a
    second pod whose admission would exceed max with the nominated pod
    counted is rejected at PreFilter — deterministically, with the nominated
    state fabricated (the e2e transient is racy by construction)."""
    from tpusched.fwk import CycleState
    from tpusched.testing.harness import new_test_framework

    profile = capacity_profile()
    nodes = [make_tpu_node(f"h{i}", chips=4) for i in range(4)]
    fw, handle, api = new_test_framework(profile, nodes=nodes)
    api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
        "quota-a", "team-a", min={TPU: 8}, max={TPU: 8}))

    # a preemptor nominated onto h0 but not yet bound: 4 of team-a's 8 max
    pree = make_pod("pree", namespace="team-a", limits={TPU: 4}, priority=100)
    pree.status.nominated_node_name = "h0"
    handle.pod_nominator.add_nominated_pod(pree, "h0")

    # 4 more chips still fit under max=8...
    ok = fw.run_pre_filter_plugins(
        CycleState(), make_pod("fits", namespace="team-a", limits={TPU: 4}))
    assert ok.is_success()
    # ...but 8 more would exceed max once the nominated pod is counted
    rejected = fw.run_pre_filter_plugins(
        CycleState(), make_pod("late", namespace="team-a", limits={TPU: 8}))
    assert rejected.is_unschedulable()
    assert rejected.plugin == "CapacityScheduling"

    # drop the nomination: the same pod now fits under max
    handle.pod_nominator.delete_nominated_pod_if_exists(pree)
    ok2 = fw.run_pre_filter_plugins(
        CycleState(), make_pod("late2", namespace="team-a", limits={TPU: 8}))
    assert ok2.is_success()


def test_three_team_aggregate_min_gate():
    """Σmin borrowing across >2 quotas (capacity_scheduling.go:242-255).
    Physical capacity (32 chips) exceeds Σmin (24), so the aggregate gate —
    not free chips — is what decides admission:
    - within-own-min pods reclaim from borrowers (preemption);
    - an over-own-min pod whose admission would push aggregate past Σmin
      stays pending even with free chips on the floor."""
    c = TestCluster(profile=capacity_profile())
    with c:
        c.add_nodes([make_tpu_node(f"h{i}", chips=4) for i in range(8)])  # 32
        for team in ("t-a", "t-b", "t-c"):
            c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
                f"q-{team}", team, min={TPU: 8}, max={TPU: 24}))
        # t-a borrows far beyond its min while b and c are idle: 20 of Σ24
        team_pods(c, "t-a", 5, chips=4)
        assert c.wait_for_pods_scheduled([f"t-a/t-a-{i}" for i in range(5)])
        team_pods(c, "t-b", 3, chips=4)
        # b-0 admits outright (aggregate 24 ≤ Σmin); b-1 is within t-b's own
        # min, so it may reclaim from the borrower t-a via preemption
        assert c.wait_for_pods_scheduled(["t-b/t-b-0", "t-b/t-b-1"],
                                         timeout=20)
        # b-2 would take t-b over its own min AND aggregate past Σmin:
        # pending forever despite free physical chips (32 - 28 = 4 free)
        assert c.wait_for_pods_unscheduled(["t-b/t-b-2"], hold=1.0)
        surviving_a = 0
        for i in range(5):
            p = c.pod(f"t-a/t-a-{i}")  # evicted victims are deleted
            if p is not None and p.spec.node_name and not p.is_terminating():
                surviving_a += 1
        assert surviving_a == 4  # exactly one borrower reclaimed


def test_eq_shrink_blocks_new_pods_keeps_running():
    """Shrinking max below current used must not evict running pods, but
    new pods in the namespace are rejected until usage drains."""
    with two_team_cluster() as c:
        team_pods(c, "team-a", 3, chips=4)  # 12 used, max 16
        assert c.wait_for_pods_scheduled([f"team-a/team-a-{i}" for i in range(3)])
        c.api.patch(srv.ELASTIC_QUOTAS, "team-a/quota-a",
                    lambda eq: eq.spec.max.update({TPU: 8}))
        team_pods(c, "team-a", 1, chips=4, prefix="extra")
        assert c.wait_for_pods_unscheduled(["team-a/extra-0"])
        # running pods untouched
        assert all(c.pod(f"team-a/team-a-{i}").spec.node_name
                   for i in range(3))
