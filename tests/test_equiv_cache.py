"""Equivalence-class scheduling cache: invalidation edges.

The fast path (sched/equivcache.py) may only serve a gang sibling while the
validity triple holds — mutation cursor, nominator generation, per-plugin
fingerprints. These tests pin the edges where serving a STALE entry would be
a correctness bug: a node update between siblings, a foreign assume/forget,
and a nominated preemptor in play (mandatory full-path bypass). Each edge is
driven synchronously — Scheduler constructed but never run(); the test pops
and calls schedule_one itself — so the interleaving is exact, not a race.
"""
from __future__ import annotations

from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.fwk import PluginProfile
from tpusched.plugins import default_registry
from tpusched.sched import Scheduler
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, make_tpu_pool)
from tpusched.util.equivalence import equivalence_key
from tpusched.util.metrics import (equiv_cache_bypasses,
                                   equiv_cache_differential_mismatches,
                                   equiv_cache_hits,
                                   equiv_cache_invalidations)


def gang_profile(min_member_permit: bool = True) -> PluginProfile:
    """Minimal gang wiring: Coscheduling quorum + the default node filters.
    Permit keeps members WAITING until quorum, so no bind lands (and no
    informer event fires) mid-burst — the interleaving stays synchronous."""
    return PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"],
        filter=["NodeUnschedulable", "NodeName", "NodeSelector",
                "TaintToleration", "NodeResourcesFit"],
        permit=["Coscheduling"] if min_member_permit else [],
        bind=["DefaultBinder"],
        parallelism=1,
    )


class Counters:
    """Before/after deltas of the global equiv-cache counters."""

    def __init__(self):
        self._at = {}
        for name, c in (("hits", equiv_cache_hits),
                        ("invalidations", equiv_cache_invalidations),
                        ("bypasses", equiv_cache_bypasses),
                        ("mismatches", equiv_cache_differential_mismatches)):
            self._at[name] = c.value()

    def delta(self, name: str) -> float:
        cur = {"hits": equiv_cache_hits,
               "invalidations": equiv_cache_invalidations,
               "bypasses": equiv_cache_bypasses,
               "mismatches": equiv_cache_differential_mismatches}[name].value()
        return cur - self._at[name]


def build(n_nodes: int = 4, gang: int = 8, min_member: int = 8):
    """A never-run scheduler + one gang parked in its queue. min_member
    defaults to the full gang so permit holds every member waiting (no
    async binds mutate the cache under the test's feet)."""
    api = srv.APIServer()
    s = Scheduler(api, default_registry(), gang_profile())
    for i in range(n_nodes):
        api.create(srv.NODES, make_node(
            f"n{i}", capacity=make_resources(cpu=8, memory="16Gi")))
    api.create(srv.POD_GROUPS, make_pod_group("g", min_member=min_member))
    pods = [make_pod(f"w{i}", pod_group="g",
                     requests=make_resources(cpu=1, memory="1Gi"))
            for i in range(gang)]
    for p in pods:
        api.create(srv.PODS, p)
    return api, s, pods


def step(s: Scheduler) -> None:
    info = s.queue.pop(timeout=1.0)
    assert info is not None, "queue unexpectedly empty"
    s.schedule_one(info)


def assumed_node(s: Scheduler, key: str) -> str:
    info = s.cache.snapshot()
    for ni in info.list():
        for p in ni.pods:
            if p.key == key:
                return ni.node.name
    return ""


def test_gang_siblings_hit_back_to_back():
    api, s, pods = build()
    try:
        c = Counters()
        for _ in range(len(pods)):
            step(s)
        # first member is the miss that builds the entry; every sibling
        # after it rides the fast path
        assert c.delta("hits") == len(pods) - 1
        assert c.delta("invalidations") == 0
        # every member actually got a host
        for p in pods:
            assert assumed_node(s, p.key)
    finally:
        s.stop()


def test_equiv_key_separates_gangs_and_shapes():
    a = make_pod("a", pod_group="g1", requests=make_resources(cpu=1))
    b = make_pod("b", pod_group="g1", requests=make_resources(cpu=1))
    other_gang = make_pod("c", pod_group="g2", requests=make_resources(cpu=1))
    other_shape = make_pod("d", pod_group="g1", requests=make_resources(cpu=2))
    assert equivalence_key(a) == equivalence_key(b)
    assert equivalence_key(a) != equivalence_key(other_gang)
    assert equivalence_key(a) != equivalence_key(other_shape)


def test_node_update_invalidates_entry():
    """A node update between two siblings moves the mutation cursor: the
    second sibling must NOT be served from the stale entry (the update may
    have been a cordon, a relabel, a capacity change)."""
    api, s, pods = build()
    try:
        step(s)                      # member 0: full path, entry armed
        c = Counters()
        api.patch(srv.NODES, "/n0",
                  lambda n: n.meta.labels.update({"churned": "yes"}))
        step(s)                      # member 1: entry stale -> full path
        assert c.delta("hits") == 0
        assert c.delta("invalidations") == 1
        assert assumed_node(s, pods[1].key)
        # the full path re-armed a fresh entry: member 2 hits again
        step(s)
        assert c.delta("hits") == 1
    finally:
        s.stop()


def test_foreign_assume_and_forget_invalidate():
    """assume_pod/forget_pod from anywhere but this class's own chain break
    the cursor chain — a sibling must re-derive feasibility (the foreign
    pod consumed capacity the entry never saw)."""
    api, s, pods = build()
    try:
        step(s)
        c = Counters()
        foreign = make_pod("foreign", requests=make_resources(cpu=1))
        s.cache.assume_pod(foreign.deepcopy(), "n1")
        step(s)
        assert c.delta("hits") == 0
        assert c.delta("invalidations") == 1

        # the full path above re-armed; a forget breaks the chain again
        c2 = Counters()
        s.cache.forget_pod(foreign)
        step(s)
        assert c2.delta("hits") == 0
        assert c2.delta("invalidations") == 1
    finally:
        s.stop()


def test_nominated_preemptor_bypasses_cache():
    """Nominated pods change per-node filter semantics (preemption dry-run):
    the cache must not even be consulted, in either direction — no lookup,
    no entry creation. And once the nomination clears, the GENERATION (not
    emptiness) is what gates reuse: a nominate->un-nominate round trip ran
    preemption machinery the armed entry never saw."""
    api, s, pods = build()
    try:
        step(s)                      # arm an entry
        c = Counters()
        preemptor = make_pod("preemptor", priority=100,
                             requests=make_resources(cpu=1))
        s.handle.pod_nominator.add_nominated_pod(preemptor, "n0")
        step(s)                      # sibling: mandatory full path
        assert c.delta("hits") == 0
        assert c.delta("bypasses") == 1

        s.handle.pod_nominator.delete_nominated_pod_if_exists(preemptor)
        c2 = Counters()
        step(s)
        # nominator empty again, but its generation moved past every entry
        # armed before/during the nomination: no stale hit
        assert c2.delta("hits") == 0
        assert c2.delta("bypasses") == 0
    finally:
        s.stop()


def test_podgroup_spec_change_invalidates_fingerprint():
    """minMember lives OUTSIDE the scheduler cache (no node/pod mutation):
    only the Coscheduling fingerprint can catch it changing between
    siblings."""
    api, s, pods = build()
    try:
        step(s)
        c = Counters()
        api.patch(srv.POD_GROUPS, "default/g",
                  lambda pg: setattr(pg.spec, "min_member", 4))
        step(s)
        assert c.delta("hits") == 0
        assert c.delta("invalidations") == 1
    finally:
        s.stop()


def test_differential_mode_end_to_end_slice_gang():
    """The oracle run: a real v5p slice gang scheduled end to end with
    equiv_cache_differential=True — every cache hit re-runs the FULL path
    and asserts the identical placement. Zero mismatches tolerated."""
    GANG = 64
    profile = tpu_gang_profile(permit_wait_s=120)
    profile.equiv_cache_differential = True
    c = Counters()
    with TestCluster(profile=profile) as tc:
        topo, nodes = make_tpu_pool("pool-a", dims=(4, 4, 4))
        tc.api.create(srv.TPU_TOPOLOGIES, topo)
        tc.add_nodes(nodes)
        tc.api.create(srv.POD_GROUPS,
                      make_pod_group("gang", min_member=GANG,
                                     tpu_slice_shape="4x4x4",
                                     tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w{i:02d}", pod_group="gang", limits={TPU: 1},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(GANG)]
        tc.create_pods(pods)
        assert tc.wait_for_pods_scheduled([p.key for p in pods], timeout=60)
        used = {}
        for p in pods:
            used.setdefault(tc.pod(p.key).spec.node_name, 0)
            used[tc.pod(p.key).spec.node_name] += 1
        assert len(used) == 16 and all(v == 4 for v in used.values())
    assert c.delta("mismatches") == 0
    assert c.delta("hits") > 0


def test_cache_disabled_profile_still_schedules():
    """equiv_cache=False wiring: the fast path never engages but the gang
    still schedules identically (the knob is a pure perf toggle)."""
    api = srv.APIServer()
    prof = gang_profile()
    prof.equiv_cache = False
    s = Scheduler(api, default_registry(), prof)
    try:
        for i in range(2):
            api.create(srv.NODES, make_node(
                f"n{i}", capacity=make_resources(cpu=8, memory="16Gi")))
        api.create(srv.POD_GROUPS, make_pod_group("g", min_member=4))
        pods = [make_pod(f"w{i}", pod_group="g",
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(4)]
        for p in pods:
            api.create(srv.PODS, p)
        c = Counters()
        for _ in range(4):
            step(s)
        assert c.delta("hits") == 0
        for p in pods:
            assert assumed_node(s, p.key)
    finally:
        s.stop()


def test_queue_prefers_gang_siblings():
    """SchedulingQueue.pop drains same-priority siblings of the last-popped
    gang back-to-back even when QueueSort interleaves another gang at equal
    priority — the cursor chain (and so the cache) depends on it."""
    from tpusched.api.scheduling import POD_GROUP_LABEL
    from tpusched.sched.queue import SchedulingQueue

    q = SchedulingQueue(
        lambda a, b: (a.pod.priority, -a.timestamp) > (b.pod.priority, -b.timestamp))
    # interleave two gangs' arrivals at equal priority
    for i in range(3):
        q.add(make_pod(f"a{i}", pod_group="ga"))
        q.add(make_pod(f"b{i}", pod_group="gb"))
    order = [q.pop(timeout=0.1).pod.meta.labels[POD_GROUP_LABEL]
             for _ in range(6)]
    # whatever gang pops first is fully drained before the other starts
    assert order == sorted(order) or order == sorted(order, reverse=True)
    assert order.count(order[0]) == 3 and order[0] == order[1] == order[2]

    # a HIGHER-priority arrival must still preempt the preference
    for i in range(2):
        q.add(make_pod(f"c{i}", pod_group="gc"))
    q.add(make_pod("urgent", priority=10))
    first = q.pop(timeout=0.1)
    assert first.pod.meta.name == "urgent"
