"""Workload checkpoint/resume: sharded save on one mesh, restore onto a
DIFFERENT mesh (the re-placed gang), training continuation bit-exact."""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpusched.jaxbridge import checkpoint, workload
from tpusched.jaxbridge.mesh import build_named_mesh


def _train(params, step_fn, tokens, n):
    loss = None
    for _ in range(n):
        params, loss = step_fn(params, tokens)
    return params, loss


def test_save_restore_across_mesh_change(tmp_path):
    cfg = workload.ModelConfig.tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, cfg.seq),
                                0, cfg.vocab)

    # train 2 steps on a dp×tp mesh, checkpoint
    mesh_a = build_named_mesh({"dp": 4, "tp": 2})
    step_a, pshard_a, tshard_a = workload.make_sharded_train_step(mesh_a, cfg)
    params = jax.device_put(workload.init_params(jax.random.PRNGKey(0), cfg),
                            pshard_a)
    toks_a = jax.device_put(tokens, tshard_a)
    params, _ = _train(params, step_a, toks_a, 2)
    checkpoint.save(str(tmp_path), params, step=2)
    assert checkpoint.latest_step(str(tmp_path)) == 2

    # uninterrupted baseline: 2 more steps on mesh A
    baseline_params, baseline_loss = _train(params, step_a, toks_a, 2)

    # "reschedule": restore onto a different mesh topology (fsdp×sp×tp)
    mesh_b = build_named_mesh({"fsdp": 2, "sp": 2, "tp": 2})
    step_b, pshard_b, tshard_b = workload.make_sharded_train_step(mesh_b, cfg)
    abstract = checkpoint.abstract_state(
        jax.eval_shape(lambda: workload.init_params(jax.random.PRNGKey(0), cfg)),
        pshard_b)
    restored, step = checkpoint.restore(str(tmp_path), abstract)
    assert step == 2
    # every leaf landed with the NEW mesh's sharding
    leaf = restored["layers"][0]["wq"]
    assert leaf.sharding.mesh.shape == dict(mesh_b.shape)

    resumed_params, resumed_loss = _train(
        restored, step_b, jax.device_put(tokens, tshard_b), 2)
    np.testing.assert_allclose(float(resumed_loss), float(baseline_loss),
                               atol=1e-5, rtol=1e-5)
    # parameters agree too (same math, different partitioning)
    np.testing.assert_allclose(
        np.asarray(resumed_params["out"].astype(jnp.float32)),
        np.asarray(baseline_params["out"].astype(jnp.float32)),
        atol=1e-5, rtol=1e-5)


def test_latest_step_empty_dir(tmp_path):
    assert checkpoint.latest_step(str(tmp_path)) is None
    assert checkpoint.latest_step(str(tmp_path / "missing")) is None


def test_moe_checkpoint_across_ep_change(tmp_path):
    """Expert-parallel resume: ep-sharded expert stacks saved on an ep=2
    mesh restore onto ep=4 (the re-placed gang got a different slice
    shape), training continuation equivalent."""
    import dataclasses
    cfg = dataclasses.replace(workload.ModelConfig.tiny(), n_experts=4)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, cfg.seq),
                                0, cfg.vocab)

    mesh_a = build_named_mesh({"dp": 2, "ep": 2, "tp": 2})
    step_a, pshard_a, tshard_a = workload.make_sharded_train_step(mesh_a, cfg)
    params = jax.device_put(workload.init_params(jax.random.PRNGKey(0), cfg),
                            pshard_a)
    toks_a = jax.device_put(tokens, tshard_a)
    params, _ = _train(params, step_a, toks_a, 2)
    checkpoint.save(str(tmp_path), params, step=2)
    baseline_params, baseline_loss = _train(params, step_a, toks_a, 2)

    mesh_b = build_named_mesh({"dp": 1, "ep": 4, "tp": 2})
    step_b, pshard_b, tshard_b = workload.make_sharded_train_step(mesh_b, cfg)
    abstract = checkpoint.abstract_state(
        jax.eval_shape(lambda: workload.init_params(jax.random.PRNGKey(0),
                                                    cfg)), pshard_b)
    restored, step = checkpoint.restore(str(tmp_path), abstract)
    assert step == 2
    # expert stacks landed ep-sharded on the new mesh: 1 expert per device
    w = restored["layers"][0]["w_gate"]
    assert w.addressable_shards[0].data.shape[0] == cfg.n_experts // 4

    _, resumed_loss = _train(restored, step_b,
                             jax.device_put(tokens, tshard_b), 2)
    np.testing.assert_allclose(float(resumed_loss), float(baseline_loss),
                               atol=1e-5, rtol=1e-5)


def test_optimizer_state_checkpoints_with_params(tmp_path):
    """Adam moments resume exactly: save {params, opt} as one tree, restore
    onto the same shardings, continuation matches the uninterrupted run."""
    import optax
    cfg = workload.ModelConfig.tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, cfg.seq),
                                0, cfg.vocab)
    mesh = build_named_mesh({"dp": 4, "tp": 2})
    tx = optax.adamw(1e-3)
    step, init_opt, pshard, tshard = workload.make_optax_train_step(
        mesh, cfg, tx)
    params = jax.device_put(workload.init_params(jax.random.PRNGKey(0), cfg),
                            pshard)
    opt = init_opt(params)
    toks = jax.device_put(tokens, tshard)
    for _ in range(2):
        params, opt, _ = step(params, opt, toks)
    checkpoint.save(str(tmp_path), {"params": params, "opt": opt}, step=2)

    base_p, base_o = params, opt
    for _ in range(2):
        base_p, base_o, base_loss = step(base_p, base_o, toks)

    shardings = {"params": pshard,
                 "opt": jax.tree_util.tree_map(lambda x: x.sharding, opt)}
    abstract = checkpoint.abstract_state(
        jax.eval_shape(lambda: {"params": params, "opt": opt}), shardings)
    restored, _ = checkpoint.restore(str(tmp_path), abstract)
    r_p, r_o = restored["params"], restored["opt"]
    for _ in range(2):
        r_p, r_o, r_loss = step(r_p, r_o, toks)
    np.testing.assert_allclose(float(r_loss), float(base_loss),
                               atol=1e-6, rtol=1e-6)


def test_export_and_load_for_serving(tmp_path):
    """Train→serve handoff: the serving snapshot carries compute-dtype
    params only (no optimizer state, no f32 masters); loading replicated
    equals the cast train params exactly, and loading onto a tp mesh
    restores every leaf directly to its ServeEngine sharding with greedy
    outputs identical to serving the original params."""
    import numpy as np
    from jax.sharding import Mesh
    from tpusched.jaxbridge import checkpoint as ckpt
    from tpusched.jaxbridge.decode import generate
    from tpusched.jaxbridge.workload import (ModelConfig,
                                             cast_params_for_compute,
                                             init_params)

    cfg = dataclasses.replace(ModelConfig.tiny(), dtype=jnp.bfloat16,
                              param_dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    path = ckpt.export_for_serving(str(tmp_path), params, cfg, step=7)
    assert "serving_00000007" in path
    # replicated load == cast-at-export params, compute dtype, no masters
    loaded = ckpt.load_for_serving(str(tmp_path), cfg)
    want = cast_params_for_compute(params, cfg)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a, np.float32),
                                                   np.asarray(b, np.float32)),
        loaded, want)
    assert loaded["layers"][0]["wq"].dtype == jnp.bfloat16
    # tp-mesh load: leaves land sharded; greedy generation matches the
    # original params served unsharded
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    sharded = ckpt.load_for_serving(str(tmp_path), cfg, mesh=mesh)
    ws = sharded["layers"][0]["wq"]
    assert "tp" in (ws.sharding.spec[1],)   # column-parallel in-proj
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab,
                                dtype=jnp.int32)
    got = np.asarray(generate(sharded, prompt, cfg, steps=5))
    ref = np.asarray(generate(params, prompt, cfg, steps=5))
    np.testing.assert_array_equal(got, ref)


def test_latest_step_skips_orbax_tmp_dirs(tmp_path):
    """A crashed save leaves an atomic-tmp dir next to good snapshots; the
    last GOOD one must load, not a ValueError on the tmp suffix."""
    import os
    os.makedirs(tmp_path / "step_00000003")
    os.makedirs(tmp_path / "step_00000007.orbax-checkpoint-tmp-12345")
    os.makedirs(tmp_path / "serving_00000002")
    os.makedirs(tmp_path / "serving_00000009.orbax-checkpoint-tmp-6")
    assert checkpoint.latest_step(str(tmp_path)) == 3
    assert checkpoint.latest_serving_step(str(tmp_path)) == 2
