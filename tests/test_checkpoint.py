"""Workload checkpoint/resume: sharded save on one mesh, restore onto a
DIFFERENT mesh (the re-placed gang), training continuation bit-exact."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpusched.jaxbridge import checkpoint, workload
from tpusched.jaxbridge.mesh import build_named_mesh


def _train(params, step_fn, tokens, n):
    loss = None
    for _ in range(n):
        params, loss = step_fn(params, tokens)
    return params, loss


def test_save_restore_across_mesh_change(tmp_path):
    cfg = workload.ModelConfig.tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, cfg.seq),
                                0, cfg.vocab)

    # train 2 steps on a dp×tp mesh, checkpoint
    mesh_a = build_named_mesh({"dp": 4, "tp": 2})
    step_a, pshard_a, tshard_a = workload.make_sharded_train_step(mesh_a, cfg)
    params = jax.device_put(workload.init_params(jax.random.PRNGKey(0), cfg),
                            pshard_a)
    toks_a = jax.device_put(tokens, tshard_a)
    params, _ = _train(params, step_a, toks_a, 2)
    checkpoint.save(str(tmp_path), params, step=2)
    assert checkpoint.latest_step(str(tmp_path)) == 2

    # uninterrupted baseline: 2 more steps on mesh A
    baseline_params, baseline_loss = _train(params, step_a, toks_a, 2)

    # "reschedule": restore onto a different mesh topology (fsdp×sp×tp)
    mesh_b = build_named_mesh({"fsdp": 2, "sp": 2, "tp": 2})
    step_b, pshard_b, tshard_b = workload.make_sharded_train_step(mesh_b, cfg)
    abstract = checkpoint.abstract_state(
        jax.eval_shape(lambda: workload.init_params(jax.random.PRNGKey(0), cfg)),
        pshard_b)
    restored, step = checkpoint.restore(str(tmp_path), abstract)
    assert step == 2
    # every leaf landed with the NEW mesh's sharding
    leaf = restored["layers"][0]["wq"]
    assert leaf.sharding.mesh.shape == dict(mesh_b.shape)

    resumed_params, resumed_loss = _train(
        restored, step_b, jax.device_put(tokens, tshard_b), 2)
    np.testing.assert_allclose(float(resumed_loss), float(baseline_loss),
                               atol=1e-5, rtol=1e-5)
    # parameters agree too (same math, different partitioning)
    np.testing.assert_allclose(
        np.asarray(resumed_params["out"].astype(jnp.float32)),
        np.asarray(baseline_params["out"].astype(jnp.float32)),
        atol=1e-5, rtol=1e-5)


def test_latest_step_empty_dir(tmp_path):
    assert checkpoint.latest_step(str(tmp_path)) is None
    assert checkpoint.latest_step(str(tmp_path / "missing")) is None
