"""Torus fitting engine + TopologyMatch plugin tests.

Reference analog: the NRT filter table tests (pkg/noderesourcetopology/
filter_test.go, the reference's biggest suite) — here covering the TPU
generalization. BASELINE eval config #3: ICI-zone fit on a 4x4x4 v5p-64
torus."""
import time

from tpusched.api.resources import TPU
from tpusched.api.topology import V5P, parse_shape
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.plugins.topologymatch import (COORD_ANNOTATION, POOL_ANNOTATION,
                                            TopologyMatch)
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_pool)
from tpusched.topology.torus import (HostGrid, enumerate_placements,
                                     feasible_placements, host_block_shape,
                                     validate_slice_shape)


# -- engine unit tests --------------------------------------------------------

def grid_4x4x8():
    topo, _ = make_tpu_pool("p", dims=(4, 4, 8))
    return HostGrid.from_spec(topo.spec)


def test_host_block_shape():
    assert host_block_shape((4, 4, 4), V5P) == (2, 2, 4)
    assert host_block_shape((2, 2, 8), V5P) == (1, 1, 8)


def test_validate_slice_shape():
    assert validate_slice_shape((4, 4, 4), V5P, (4, 4, 8)) is None
    # wrong rank
    assert validate_slice_shape((4, 4), V5P, (4, 4, 8)) is not None
    # no rotation can divide the (2,2,1) host extent: two odd chip axes
    assert validate_slice_shape((3, 3, 4), V5P, (4, 4, 8)) is not None
    # too big for the pool under any rotation
    assert validate_slice_shape((4, 4, 16), V5P, (4, 4, 8)) is not None
    # fits ONLY under rotation (8 must land on the z axis)
    assert validate_slice_shape((8, 4, 4), V5P, (4, 4, 8)) is None


def test_enumerate_placements_counts():
    grid = grid_4x4x8()          # host grid 2x2x8, no wrap
    # 4x4x4 chips → 2x2x4 hosts sliding along z: 5 anchors
    ps = enumerate_placements(grid, (4, 4, 4))
    assert len(ps) == 5
    assert all(len(p) == 16 for p in ps)
    # 2x2x8 chips → 1x1x8 hosts spanning z; 2x2 anchor positions in x,y = 4;
    # rotations putting the long axis on x/y don't divide/fit → exactly 4
    ps = enumerate_placements(grid, (2, 2, 8))
    assert len(ps) == 4


def test_enumerate_placements_rotation_onto_anisotropic_extent():
    """8x4x4 chips fits a 4x4x8 pool ONLY as the rotation 4x4x8 (the 8 must
    land on the z axis whose host extent is 1) — regression for permuting
    host blocks instead of chip shapes."""
    grid = grid_4x4x8()
    ps = enumerate_placements(grid, (8, 4, 4))
    assert len(ps) == 1
    assert len(ps[0]) == 32  # whole pool: 2x2x8 hosts


def test_enumerate_placements_wraparound():
    topo, _ = make_tpu_pool("p", dims=(4, 4, 8), wrap=(False, False, True))
    grid = HostGrid.from_spec(topo.spec)
    # with z wraparound a 4x4x4-chip (2x2x4-host) block anchors at any z
    ps = enumerate_placements(grid, (4, 4, 4))
    assert len(ps) == 8


def test_feasible_placements_respects_assigned_and_free():
    grid = grid_4x4x8()
    ps = enumerate_placements(grid, (4, 4, 4))
    all_hosts = frozenset(grid.node_of)
    # a blocker at z=3 kills every window containing it
    blocked = frozenset({(0, 0, 3)})
    free = all_hosts - blocked
    survivors = feasible_placements(ps, frozenset(), free)
    assert len(survivors) == 1  # only window z∈[4,8)
    # an assigned sibling at z=0 pins the window to z∈[0,4) — conflicts
    survivors = feasible_placements(ps, frozenset({(0, 0, 0)}), free)
    assert survivors == []


# -- integration: BASELINE config #3 -----------------------------------------

def add_pool(c, *args, **kw):
    topo, nodes = make_tpu_pool(*args, **kw)
    c.api.create(srv.TPU_TOPOLOGIES, topo)
    c.add_nodes(nodes)
    return topo, nodes


def slice_gang(c, name, shape, members, accelerator="tpu-v5p", chips=4):
    c.api.create(srv.POD_GROUPS, make_pod_group(
        name, min_member=members, tpu_slice_shape=shape,
        tpu_accelerator=accelerator))
    pods = [make_pod(f"{name}-{i}", pod_group=name, limits={TPU: chips})
            for i in range(members)]
    c.create_pods(pods)
    return pods


def test_v5p64_full_slice_gang():
    """4x4x4 slice on a v5p-64 pool: 16 hosts, the whole pool, with coord
    annotations on every member."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        add_pool(c, "v5p-64", dims=(4, 4, 4))
        pods = slice_gang(c, "llama", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)
        coords = set()
        for p in pods:
            bound = c.pod(p.key)
            assert bound.meta.annotations[POOL_ANNOTATION] == "v5p-64"
            coords.add(bound.meta.annotations[COORD_ANNOTATION])
        assert len(coords) == 16  # every host exactly once


def test_contiguity_respected_with_blocker():
    """A blocker host in the middle of the torus forces the slice into the
    contiguous free window; a second identical slice cannot fit."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=3, denied_s=1)) as c:
        topo, nodes = add_pool(c, "v5p-128", dims=(4, 4, 8))
        # occupy host (0,0,3) with a pre-bound pod: its chips are gone, so
        # every z-window containing z=3 is blocked
        target = next(n for n in nodes if topo.spec.hosts[n.name] == (0, 0, 3))
        c.create_pods([make_pod("pinned-blocker", limits={TPU: 4},
                                node_name=target.name)])
        gang = slice_gang(c, "sliceA", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in gang], timeout=20)
        zs = set()
        for p in gang:
            coord = c.pod(p.key).meta.annotations[COORD_ANNOTATION]
            zs.add(int(coord.split("-")[2]))
        assert zs == {4, 5, 6, 7}  # pushed past the blocker at z=3
        # no second 4x4x4 window remains
        gang2 = slice_gang(c, "sliceB", "4x4x4", 16)
        assert c.wait_for_pods_unscheduled([p.key for p in gang2], hold=2.0)


def test_two_slices_pack_one_pool():
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        add_pool(c, "v5p-128", dims=(4, 4, 8))
        a = slice_gang(c, "a", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in a], timeout=20)
        b = slice_gang(c, "b", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in b], timeout=20)
        # disjoint host sets
        nodes_a = {c.pod(p.key).spec.node_name for p in a}
        nodes_b = {c.pod(p.key).spec.node_name for p in b}
        assert not (nodes_a & nodes_b)


def test_wrong_accelerator_unresolvable():
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=2, denied_s=1)) as c:
        add_pool(c, "v5e-16", accelerator="tpu-v5e", dims=(4, 4))
        pods = slice_gang(c, "wants-v5p", "4x4x4", 16, accelerator="tpu-v5p")
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=1.0)


def test_v5e_2d_slice():
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        add_pool(c, "v5e-16", accelerator="tpu-v5e", dims=(4, 4))
        pods = slice_gang(c, "flash", "4x4", 4, accelerator="tpu-v5e")
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)


def test_gang_never_splits_across_pools():
    """Two identical pools: the gang must land entirely in one torus
    (regression: cross-pool slice splitting)."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        add_pool(c, "pool-a", dims=(4, 4, 4))
        add_pool(c, "pool-b", dims=(4, 4, 4))
        pods = slice_gang(c, "whole", "4x4x4", 16)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)
        pools = {c.pod(p.key).meta.annotations[POOL_ANNOTATION] for p in pods}
        assert len(pools) == 1, f"gang split across pools: {pools}"


def test_foreign_chip_excludes_host_from_placement():
    """One foreign 1-chip pod inside the only candidate window must make the
    slice infeasible — a placement owns whole hosts (regression:
    false-free partially-occupied hosts deadlocking the Permit barrier)."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=2, denied_s=1)) as c:
        topo, nodes = add_pool(c, "v5p-64", dims=(4, 4, 4))
        # 1 foreign chip on one host: 255 of 256... here 63 of 64 chips free
        c.create_pods([make_pod("foreign", limits={TPU: 1},
                                node_name=nodes[0].name)])
        pods = slice_gang(c, "full", "4x4x4", 16)
        # PreFilter must reject outright (no feasible placement) — nobody
        # assumes, nobody parks at Permit
        assert c.wait_for_pods_unscheduled([p.key for p in pods], hold=2.0)


def test_subhost_pods_pack_hosts_within_slice():
    """4 one-chip pods per host: sibling-partial hosts stay eligible."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1)) as c:
        add_pool(c, "v5p-16", dims=(2, 2, 4))  # 4 hosts x 4 chips
        pods = slice_gang(c, "packed", "2x2x4", 16, chips=1)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=20)
        per_host = {}
        for p in pods:
            n = c.pod(p.key).spec.node_name
            per_host[n] = per_host.get(n, 0) + 1
        assert sorted(per_host.values()) == [4, 4, 4, 4]
