"""Flight recorder end-to-end tier.

The acceptance scenario: a deliberately wedged gang (Permit barrier never
satisfied) must be fully explainable from the /debug/flightrecorder output
alone — the dump names the blocking plugin, the unschedulable reason per
member, and queue-wait vs extension-point time. Plus: gang critical-path
stitching against the measured PodGroup-to-Bound wall time, structured
plugin rejections, and anomaly pinning through the real scheduler."""
from __future__ import annotations

import json
import time
import urllib.request

import pytest

from tpusched import trace
from tpusched.api.resources import TPU, make_resources
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.config.types import CoschedulingArgs
from tpusched.fwk import PluginProfile
from tpusched.testing import (TestCluster, make_pod, make_pod_group,
                              make_tpu_node, make_tpu_pool, wait_until)
from tpusched.util.httpserve import MetricsServer


@pytest.fixture()
def fresh_recorder():
    """Isolate each test's traces in a private global recorder (schedulers
    capture the global at construction)."""
    old = trace.default_recorder()
    rec = trace.install_recorder(trace.FlightRecorder())
    yield rec
    trace.install_recorder(old)


def _gang_profile(permit_wait_s=120):
    return PluginProfile(
        queue_sort="Coscheduling",
        pre_filter=["Coscheduling"],
        filter=["NodeUnschedulable", "NodeResourcesFit", "TpuSlice"],
        post_filter=["Coscheduling"],
        score=[("TpuSlice", 1)],
        reserve=["TpuSlice", "Coscheduling"],
        permit=["Coscheduling"],
        bind=["TpuSlice"],
        post_bind=["Coscheduling"],
        plugin_args={"Coscheduling": CoschedulingArgs(
            permit_waiting_time_seconds=permit_wait_s,
            denied_pg_expiration_time_seconds=20)},
    )


def test_wedged_gang_explainable_from_flightrecorder_alone(fresh_recorder):
    """10-member gang, capacity for 9: nine members park at the Permit
    barrier (quorum 10 never forms — Coscheduling's ≤10% grace keeps the
    gang from being mass-rejected), the tenth retries unschedulable. The
    /debug/flightrecorder JSON alone must explain the wedge."""
    rec = fresh_recorder
    with TestCluster(profile=_gang_profile()) as c:
        c.add_nodes([make_tpu_node("n1", chips=4),
                     make_tpu_node("n2", chips=4),
                     make_tpu_node("n3", chips=1)])   # 9 chips total
        c.api.create(srv.POD_GROUPS, make_pod_group("wedge", min_member=10))
        pods = [make_pod(f"m-{i}", pod_group="wedge", limits={TPU: 1})
                for i in range(10)]
        c.create_pods(pods)

        def waiting_count():
            n = [0]
            c.scheduler.framework.iterate_over_waiting_pods(
                lambda wp: n.__setitem__(0, n[0] + 1))
            return n[0]
        assert wait_until(lambda: waiting_count() == 9, timeout=15)
        # let the straggler's retry cycles land in the recorder
        assert wait_until(
            lambda: any(cy["outcome"] == "unschedulable"
                        for cy in rec.cycles()), timeout=10)

        server = MetricsServer(port=0, recorder=rec).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/debug/flightrecorder",
                    timeout=5) as r:
                dump = json.loads(r.read().decode())
        finally:
            server.stop()

    # ---- everything below reads ONLY the dump ----
    gangs = [g for g in dump["gangs"] if g["pod_group"] == "default/wedge"]
    assert len(gangs) == 1
    g = gangs[0]
    assert g["waiting_at_permit"] == 9
    assert g["bound"] == 0

    # the dump names the blocking plugin
    barrier = g["permit_barrier"]
    assert barrier["resolved"] is False
    assert barrier["blocking_plugins"] == ["Coscheduling"]
    assert len(barrier["waiting_members"]) == 9

    members = g["members"]
    assert len(members) == 10
    waiting = {k: m for k, m in members.items()
               if m["outcome"] == "waiting-permit"}
    stuck = {k: m for k, m in members.items()
             if m["outcome"] == "unschedulable"}
    assert len(waiting) == 9 and len(stuck) == 1
    # per-member blocking-plugin + unschedulable-reason attribution
    assert all(m["plugin"] == "Coscheduling" for m in waiting.values())
    (stuck_key, stuck_m), = stuck.items()
    assert stuck_m["plugin"] in ("NodeResourcesFit", "TpuSlice")
    assert "Insufficient" in stuck_m["reason"] \
        or "insufficient" in stuck_m["reason"]
    # queue-wait vs extension-point decomposition, per member
    for m in members.values():
        assert m["queue_wait_s"] >= 0.0
        assert m["sched_s"] > 0.0

    # the stuck member's full cycle trace is in the ring with the per-node
    # diagnosis summary and the quorum annotation on the waiting members
    stuck_cycles = [cy for cy in dump["cycles"]
                    if cy["pod"] == stuck_key
                    and cy["outcome"] == "unschedulable"]
    assert stuck_cycles
    cy = stuck_cycles[-1]
    assert cy["plugin"] in ("NodeResourcesFit", "TpuSlice")
    assert cy["diagnosis"]
    assert sum(row["nodes"] for row in cy["diagnosis"]) == 3
    assert any(s["name"] == "Filter" for s in cy["spans"])
    waiting_cycles = [cy for cy in dump["cycles"]
                      if cy["outcome"] == "waiting-permit"]
    assert waiting_cycles
    assert waiting_cycles[-1]["blocked_on"] == ["Coscheduling"]
    assert waiting_cycles[-1]["annotations"]["coscheduling_quorum"] \
        .endswith("/10")


def test_gang_critical_path_matches_measured_wall(fresh_recorder):
    """Gang stitching: the PodGroup-to-Bound critical path reconstructed
    from member cycle traces matches the externally measured wall time."""
    rec = fresh_recorder
    with TestCluster(profile=tpu_gang_profile()) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(2, 2, 2))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("g", min_member=8,
                                    tpu_slice_shape="2x2x2",
                                    tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w-{i}", pod_group="g", limits={TPU: 1},
                         requests=make_resources(cpu=1, memory="1Gi"))
                for i in range(8)]
        start = time.perf_counter()
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
        wall = time.perf_counter() - start
        assert wait_until(
            lambda: (rec.gangs.get("default/g") is not None
                     and rec.gangs.get("default/g").to_dict()["bound"] == 8),
            timeout=5)

    g = rec.gangs.get("default/g").to_dict()
    cp = g["critical_path"]
    # the measured wall brackets the critical path (creation before first
    # enqueue, poll tick after last bind)
    assert 0 < cp["total_s"] <= wall + 0.05
    assert wall - cp["total_s"] <= max(0.3, 0.5 * wall)
    assert cp["queue_wait_s"] >= 0
    assert g["permit_barrier"]["resolved"] is True
    assert g["permit_barrier"]["max_wait_s"] > 0
    assert len(g["stragglers"]) == 5
    # every member bound, with spans decomposing the cycle
    assert all(m["outcome"] == "bound" and m["node"]
               for m in g["members"].values())
    pts = g["extension_point_s"]
    for point in ("Reserve", "Permit", "Bind"):
        assert pts.get(point, 0) > 0, (point, pts)
    assert "PermitWait" not in pts            # idle time is not work

    # the exported Perfetto document validates and reconstructs the gang
    doc = trace.export.to_perfetto(rec.traces(), rec.pinned_traces())
    assert trace.export.validate_trace_events(doc) == []
    for t in rec.traces():
        assert trace.export.validate_span_tree(t) == []


def test_gang_denial_pins_anomaly_with_structured_reason(fresh_recorder):
    """A gang too large for the fleet (quorum gap > 10%) is mass-rejected
    by Coscheduling's PostFilter: the denial is pinned as an anomaly and
    later retries carry the structured denied-window rejection."""
    rec = fresh_recorder
    with TestCluster(profile=_gang_profile(permit_wait_s=30)) as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])   # room for 4 of 8
        c.api.create(srv.POD_GROUPS, make_pod_group("big", min_member=8))
        pods = [make_pod(f"b-{i}", pod_group="big", limits={TPU: 1})
                for i in range(8)]
        c.create_pods(pods)
        assert wait_until(
            lambda: any(p["anomalies"][0]["kind"] == "gang_denied"
                        for p in rec.pinned_dump() if p.get("anomalies")),
            timeout=15)
        assert wait_until(
            lambda: any(
                any(rj["reason"] == "gang inside denied-PodGroup window"
                    for rj in cy.get("rejections", []))
                for cy in rec.cycles()), timeout=15)

    pinned = [p for p in rec.pinned_dump()
              if p.get("anomalies")
              and p["anomalies"][0]["kind"] == "gang_denied"]
    anom = pinned[0]["anomalies"][0]
    assert anom["pod_group"] == "default/big"
    assert anom["min_member"] == 8
    denied = [rj for cy in rec.cycles()
              for rj in cy.get("rejections", [])
              if rj["plugin"] == "Coscheduling"
              and rj["reason"] == "gang inside denied-PodGroup window"]
    assert denied and denied[0]["pod_group"] == "default/big"
    assert "denied_remaining_s" in denied[0]


def test_scheduler_events_correlate_to_ring_traces(fresh_recorder):
    """FailedScheduling / Scheduled events carry [trace=<id>] suffixes
    that resolve to entries in the flight recorder."""
    rec = fresh_recorder
    with TestCluster() as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        c.create_pods([make_pod("ok", limits={TPU: 4}),
                       make_pod("nofit", limits={TPU: 8})])
        assert c.wait_for_pods_scheduled(["default/ok"])
        assert c.wait_for_pods_unscheduled(["default/nofit"])
        events = c.api.events()
    ids = {cy["trace_id"] for cy in rec.cycles()}
    tagged = [e for e in events if "[trace=" in e.message]
    assert tagged
    for e in tagged:
        tid = e.message.rsplit("[trace=", 1)[1].rstrip("]")
        assert tid in ids, (e.reason, e.message)
    # both outcomes are represented in the ring
    outcomes = {cy["outcome"] for cy in rec.cycles()}
    assert "bound" in outcomes and "unschedulable" in outcomes


def test_equivcache_annotations_in_traces(fresh_recorder):
    """Gang sibling cycles annotate their equivalence-cache disposition."""
    rec = fresh_recorder
    with TestCluster(profile=tpu_gang_profile()) as c:
        topo, nodes = make_tpu_pool("pool-a", dims=(2, 2, 2))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        c.api.create(srv.POD_GROUPS,
                     make_pod_group("g", min_member=8,
                                    tpu_slice_shape="2x2x2",
                                    tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w-{i}", pod_group="g", limits={TPU: 1})
                for i in range(8)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=30)
    dispositions = [cy.get("annotations", {}).get("equiv_cache")
                    for cy in rec.cycles()]
    assert "hit" in dispositions              # siblings hit the cache
    assert any(d in ("miss", "invalidated") for d in dispositions)
