"""Sharded dispatch core (ISSUE 11, sched/shards.py): router determinism
and fallback rules, the cache's per-pool cursor / epoch-view / guarded-
assume protocol, the per-lane queue facade, the bind-pool sizing knob, and
the end-to-end sharded scheduler — binds land, per-shard telemetry and
health surfaces populate, escalation rescues pods whose routed shard
cannot host them.
"""
from __future__ import annotations

import time

import pytest

from tpusched.api.resources import TPU, make_resources
from tpusched.api.topology import LABEL_POOL
from tpusched.apiserver import server as srv
from tpusched.config.profiles import tpu_gang_profile
from tpusched.fwk import PluginProfile
from tpusched.sched.cache import Cache, pool_of_node
from tpusched.sched.queue import SchedulingQueue, ShardedQueues
from tpusched.sched.shards import (GLOBAL_LANE, ShardRouter,
                                   attribute_placement_diff, pool_shard,
                                   shard_lane, unit_key_of)
from tpusched.testing import (TestCluster, make_node, make_pod,
                              make_pod_group, make_tpu_pool)


def pool_node(name: str, pool: str):
    n = make_node(name, capacity=make_resources(cpu=8, memory="16Gi"))
    n.meta.labels[LABEL_POOL] = pool
    return n


# -- router ───────────────────────────────────────────────────────────────────


def test_pool_shard_is_stable_and_total():
    for shards in (2, 4, 8):
        for pool in ("pool-00", "pool-31", "", "zoneA/p1"):
            a = pool_shard(pool, shards)
            assert a == pool_shard(pool, shards)      # deterministic
            assert 0 <= a < shards


def test_router_keeps_gang_units_in_one_lane():
    r = ShardRouter(4)
    members = [make_pod(f"m-{i}", pod_group="g1") for i in range(6)]
    lanes = {r.lane_for(p) for p in members}
    assert len(lanes) == 1
    assert lanes.pop() == shard_lane(pool_shard("default/g1", 4))
    # singletons route by their own key
    solo = make_pod("solo-1")
    assert r.lane_for(solo) == shard_lane(pool_shard(solo.key, 4))
    assert unit_key_of(solo) == solo.key
    assert unit_key_of(members[0]) == "default/g1"


def test_router_global_fallbacks():
    pgs = {}
    r = ShardRouter(4, pg_lookup=pgs.get)
    # nominated preemptors serialize on the global lane
    pod = make_pod("nom")
    pod.status.nominated_node_name = "n1"
    assert r.lane_for(pod) == GLOBAL_LANE
    # multislice member gangs span pools: global
    ms = make_pod_group("ms1", min_member=2)
    ms.spec.multislice_set = "setA"
    pgs["default/ms1"] = ms
    assert r.lane_for(make_pod("m", pod_group="ms1")) == GLOBAL_LANE
    # quota presence no longer serializes dispatch (ISSUE 14: the commit
    # is quota-epoch guarded instead) ...
    r.set_quota_mode(True)
    assert r.lane_for(make_pod("plain")) != GLOBAL_LANE
    assert not r.quota_serialized()
    r.set_quota_mode(False)
    # ... unless the LEGACY quota_serialize_dispatch arm is on (the bench
    # baseline / operational escape hatch)
    r_legacy = ShardRouter(4, pg_lookup=pgs.get, quota_serialize=True)
    r_legacy.set_quota_mode(True)
    assert r_legacy.lane_for(make_pod("plain")) == GLOBAL_LANE
    assert r_legacy.quota_serialized()
    r_legacy.set_quota_mode(False)
    assert r_legacy.lane_for(make_pod("plain")) != GLOBAL_LANE
    # an explicit pool selector pins a SINGLETON to that pool's shard
    pinned = make_pod("pin")
    pinned.spec.node_selector = {LABEL_POOL: "pool-07"}
    assert r.lane_for(pinned) == shard_lane(pool_shard("pool-07", 4))
    # ...but never splits a gang: a pinned MEMBER still routes by unit
    # (one unit = one lane; an out-of-partition pin escalates the unit)
    member = make_pod("m-pin", pod_group="gp")
    member.spec.node_selector = {LABEL_POOL: "pool-07"}
    assert r.lane_for(member) == shard_lane(pool_shard("default/gp", 4))
    # shards=1 is always the (single) global lane
    assert ShardRouter(1).lane_for(make_pod("x")) == GLOBAL_LANE


def test_router_escalation_ttl_and_registry():
    now = [0.0]
    r = ShardRouter(4, clock=lambda: now[0], escalation_ttl_s=10.0)
    member = make_pod("m-0", pod_group="g2")
    home = r.lane_for(member)
    assert home != GLOBAL_LANE
    unit = r.escalate(member)
    assert unit == "default/g2"
    # the WHOLE unit routes global, not just the escalated pod
    assert r.lane_for(make_pod("m-1", pod_group="g2")) == GLOBAL_LANE
    assert r.is_escalated(unit)
    assert unit in r.escalated_units()
    assert r.escalations() == 1
    # TTL lapse returns the unit to its home shard
    now[0] = 11.0
    assert not r.is_escalated(unit)
    assert r.lane_for(member) == home
    # the cumulative set survives expiry (replay-diff attribution input)
    assert unit in r.escalated_units()


def test_router_partition_covers_every_pool_exactly_once():
    r = ShardRouter(4)
    pools = [f"pool-{i:02d}" for i in range(16)]
    parts = [r.partition(pools, shard_lane(i)) for i in range(4)]
    flat = [p for part in parts for p in part]
    assert sorted(flat) == sorted(pools)          # a partition, exactly
    assert r.partition(pools, GLOBAL_LANE) == pools


# -- cache: pool cursors, epoch views, guarded assume ─────────────────────────


def test_pool_cursors_attribute_mutations_to_the_touched_pool():
    c = Cache()
    c.add_node(pool_node("a1", "pool-a"))
    c.add_node(pool_node("b1", "pool-b"))
    a0, b0 = c.pool_cursor("pool-a"), c.pool_cursor("pool-b")
    c.add_pod(make_pod("p", node_name="a1"))
    assert c.pool_cursor("pool-a") == a0 + 1
    assert c.pool_cursor("pool-b") == b0          # untouched pool untouched
    g0 = c.mutation_cursor()
    c.remove_pod(make_pod("p", node_name="a1"))
    assert c.mutation_cursor() == g0 + 1
    assert c.pool_cursor("pool-b") == b0


def test_snapshot_view_partition_is_restricted_and_cached():
    c = Cache()
    c.add_node(pool_node("a1", "pool-a"))
    c.add_node(pool_node("b1", "pool-b"))
    v1 = c.snapshot_view(["pool-a"])
    assert v1.snapshot.node_names() == ["a1"]     # partition-restricted
    assert set(v1.pool_cursors) == {"pool-a"}
    # a foreign-pool mutation must NOT rebuild this partition's snapshot
    c.add_pod(make_pod("pb", node_name="b1"))
    v2 = c.snapshot_view(["pool-a"])
    assert v2.snapshot is v1.snapshot
    # a mutation in MY pool does
    c.add_pod(make_pod("pa", node_name="a1"))
    v3 = c.snapshot_view(["pool-a"])
    assert v3.snapshot is not v1.snapshot
    assert [p.key for i in v3.snapshot.list() for p in i.pods] \
        == ["default/pa"]


def test_assume_pod_guarded_commits_and_refuses():
    c = Cache()
    c.add_node(pool_node("a1", "pool-a"))
    c.add_node(pool_node("a2", "pool-a"))
    view = c.snapshot_view(["pool-a"])
    cur = view.pool_cursors["pool-a"]
    # clean commit: returns the post-assume cursor tuple for the pools
    out = c.assume_pod_guarded(make_pod("p1"), "a1", cur, pools=["pool-a"])
    assert out == (("pool-a", cur + 1),)
    assert c.is_assumed("default/p1")
    # stale epoch: refused, nothing assumed
    assert c.assume_pod_guarded(make_pod("p2"), "a2", cur) is None
    assert not c.is_assumed("default/p2")
    # vanished node: refused
    assert c.assume_pod_guarded(make_pod("p3"), "gone", 0) is None


def test_guarded_assume_ignores_foreign_pool_traffic():
    c = Cache()
    c.add_node(pool_node("a1", "pool-a"))
    c.add_node(pool_node("b1", "pool-b"))
    view = c.snapshot_view(["pool-a"])
    # heavy foreign-pool churn between capture and commit
    for i in range(5):
        c.add_pod(make_pod(f"fb{i}", node_name="b1"))
    out = c.assume_pod_guarded(make_pod("p"), "a1",
                               view.pool_cursors["pool-a"])
    assert out is not None, \
        "cross-pool traffic must never refuse a shard's commit"


def test_assume_replaces_instead_of_stacking_quorum():
    """The cross-shard-gang-quorum race scenario's fix: an assume over an
    already-cached copy (a raced watch confirm) replaces it — the permit
    quorum index must count the member once."""
    c = Cache()
    c.add_node(pool_node("a1", "pool-a"))
    c.add_pod(make_pod("m", pod_group="g", node_name="a1"))   # confirm first
    c.assume_pod(make_pod("m", pod_group="g"), "a1")          # raced assume
    assert c.snapshot().assigned_count("g", "default") == 1


def test_pool_of_node_and_pools_accounting():
    c = Cache()
    n = pool_node("x1", "pool-x")
    assert pool_of_node(n) == "pool-x"
    v0 = c.pools_version
    c.add_node(n)
    assert c.pools() == ["pool-x"]
    assert c.pools_version == v0 + 1
    c.add_node(pool_node("x2", "pool-x"))
    assert c.pools_version == v0 + 1      # pool SET unchanged
    c.remove_node(n)
    assert c.pools() == ["pool-x"]
    c.remove_node(pool_node("x2", "pool-x"))
    assert c.pools() == []
    assert c.pools_version == v0 + 2


# -- sharded queue facade ─────────────────────────────────────────────────────


def _less(a, b):
    if a.pod.priority != b.pod.priority:
        return a.pod.priority > b.pod.priority
    return a.timestamp < b.timestamp


def make_lane_queues(route):
    lanes = [shard_lane(i) for i in range(2)] + [GLOBAL_LANE]
    return ShardedQueues(
        lanes, lambda: SchedulingQueue(_less, initial_backoff_s=0,
                                       max_backoff_s=0), route)


def test_sharded_queues_route_pop_and_single_lane_delete():
    routed = {}

    def route(pod):
        return routed.get(pod.key, "s0")

    q = make_lane_queues(route)
    a, b = make_pod("a"), make_pod("b")
    routed[b.key] = "s1"
    q.add(a)
    q.add(b)
    by_lane = q.pending_counts_by_lane()
    assert by_lane["s0"]["active"] == 1 and by_lane["s1"]["active"] == 1
    assert q.pending_counts()["active"] == 2
    # lane-scoped pop serves only its own lane
    assert q.pop(timeout=0, lane="s1").pod.key == b.key
    assert q.pop(timeout=0, lane="s1") is None
    # delete goes through the location map (single-lane)
    q.delete(a)
    assert q.pending_counts()["active"] == 0
    assert not q.pending_pods()


def test_sharded_queues_pop_none_blocks_like_the_single_queue():
    """Facade contract parity: pop(timeout=None) blocks until a pod
    arrives, and returns None once the queues close — exactly the
    wrapped SchedulingQueue's behavior for by-hand drivers."""
    import threading
    q = make_lane_queues(lambda pod: "s0")
    got = []
    t = threading.Thread(target=lambda: got.append(q.pop()),
                         daemon=True, name="popper-1")
    t.start()
    time.sleep(0.05)
    assert t.is_alive(), "pop(None) returned instead of blocking"
    q.add(make_pod("blocker"))
    t.join(2)
    assert not t.is_alive() and got[0].pod.key == "default/blocker"
    t2 = threading.Thread(target=lambda: got.append(q.pop()),
                          daemon=True, name="popper-2")
    t2.start()
    time.sleep(0.05)
    q.close()
    t2.join(2)
    assert not t2.is_alive() and got[1] is None


def test_sharded_queues_reroute_and_requeue_follow_the_router():
    lane = {"v": "s0"}

    def route(pod):
        return lane["v"]

    q = make_lane_queues(route)
    q.add(make_pod("p"))
    info = q.pop(timeout=0, lane="s0")
    assert info is not None
    # escalation hop: push straight into the global lane's activeQ
    q.push_active(info, GLOBAL_LANE)
    info = q.pop(timeout=0, lane=GLOBAL_LANE)
    assert info is not None
    # requeue re-routes by the router's CURRENT verdict
    lane["v"] = "s1"
    q.requeue_after_failure(info, to_backoff=True)
    assert q.pending_counts_by_lane()["s1"]["backoff"] == 1


# -- attribution of sharded placement diffs ───────────────────────────────────


def test_attribute_placement_diff_classifies_moves():
    shards = 4
    unit = "default/gX"
    lane_idx = pool_shard(unit, shards)
    in_part = next(f"pool-{i:02d}" for i in range(64)
                   if pool_shard(f"pool-{i:02d}", shards) == lane_idx)
    out_part = next(f"pool-{i:02d}" for i in range(64)
                    if pool_shard(f"pool-{i:02d}", shards) != lane_idx)
    diff = {"binds_a": 3, "binds_b": 3, "only_in_a": [], "only_in_b": [],
            "placement_diff": [
                {"pod": "default/gX-0", "a": "na", "b": f"{in_part}-n"},
                {"pod": "default/gX-1", "a": "na", "b": f"{out_part}-n"},
                {"pod": "default/gX-2", "a": "na", "b": f"{out_part}-m"}]}
    pool_of = lambda node: node.rsplit("-", 1)[0]   # noqa: E731
    out = attribute_placement_diff(
        diff, shards=shards, pool_of_node=pool_of,
        gang_of=lambda p: unit,
        escalated_units=[])
    kinds = [r["attributed"] for r in out["placement_diff"]]
    assert kinds[0] == "shard-partition"
    assert kinds[1] == "" and kinds[2] == ""
    assert out["unattributed_count"] == 2
    # the same moves become attributed when the unit escalated
    out2 = attribute_placement_diff(
        diff, shards=shards, pool_of_node=pool_of,
        gang_of=lambda p: unit, escalated_units=[unit])
    assert out2["unattributed_count"] == 0
    assert all(r["attributed"] for r in out2["placement_diff"])
    # a bind-count delta is always unattributed
    out3 = attribute_placement_diff(
        dict(diff, binds_b=2), shards=shards, pool_of_node=pool_of,
        gang_of=lambda p: unit, escalated_units=[unit])
    assert out3["unattributed_count"] == 1
    # a pinned SINGLETON attributes against its pinned pool's shard,
    # mirroring the router's selector rule
    solo_diff = {"binds_a": 1, "binds_b": 1, "only_in_a": [],
                 "only_in_b": [],
                 "placement_diff": [
                     {"pod": "default/solo", "a": "na",
                      "b": f"{out_part}-n"}]}
    out4 = attribute_placement_diff(
        solo_diff, shards=shards, pool_of_node=pool_of,
        gang_of=lambda p: None, escalated_units=[],
        pinned_pool_of=lambda p: out_part)
    assert out4["unattributed_count"] == 0
    assert out4["placement_diff"][0]["attributed"] == "shard-partition"
    # a truncated escalated set is itself an unattributed condition
    out5 = attribute_placement_diff(
        diff, shards=shards, pool_of_node=pool_of,
        gang_of=lambda p: unit, escalated_units=[unit],
        escalated_truncated=True)
    assert out5["escalated_set_truncated"] is True
    assert out5["unattributed_count"] == 1


def test_profiler_thread_labels_keep_the_shard_id():
    """/debug/profile attribution rows are keyed by thread label; the
    sampler folds only PLAIN numeric worker suffixes ("tpusched-bind-3" →
    "tpusched-bind") — a dispatch lane's "-s<N>"/"-global" suffix must
    survive so per-shard samples stay attributable."""
    from tpusched.obs.profiler import _NUM_SUFFIX
    fold = lambda n: _NUM_SUFFIX.sub("", n)   # noqa: E731
    assert fold("tpusched-bind-3") == "tpusched-bind"
    assert fold("tpusched-dispatch-s0") == "tpusched-dispatch-s0"
    assert fold("tpusched-dispatch-s12") == "tpusched-dispatch-s12"
    assert fold("tpusched-dispatch-global") == "tpusched-dispatch-global"


# -- profile knobs ────────────────────────────────────────────────────────────


def test_bind_pool_sizing_follows_profile_and_shards():
    api = srv.APIServer()
    from tpusched.plugins import default_registry
    prof = tpu_gang_profile()
    prof.bind_pool_workers = 3
    from tpusched.sched import Scheduler
    s = Scheduler(api, default_registry(), prof)
    try:
        assert len(s._bind_pool._threads) == 3
    finally:
        s.stop()
    # auto sizing scales with the lane count (2 per lane, floor 4, cap 32)
    prof2 = tpu_gang_profile(scheduler_name="auto-sized")
    prof2.dispatch_shards = 12
    s2 = Scheduler(srv.APIServer(), default_registry(), prof2)
    try:
        assert len(s2._bind_pool._threads) == 24
        assert s2.dispatch_shards == 12
    finally:
        s2.stop()


def test_profile_yaml_decodes_dispatch_shards():
    from tpusched.config import versioned
    cfg = versioned.loads("""
apiVersion: tpusched.config.tpu.dev/v1beta1
kind: TpuSchedulerConfiguration
profiles:
  - schedulerName: sharded
    dispatchShards: 4
    bindPoolWorkers: 8
""")
    prof = cfg.profile("sharded")
    assert prof.dispatch_shards == 4
    assert prof.bind_pool_workers == 8
    with pytest.raises(versioned.ConfigError):
        versioned.loads("""
apiVersion: tpusched.config.tpu.dev/v1beta1
kind: TpuSchedulerConfiguration
profiles:
  - schedulerName: bad
    dispatchShards: -1
""")


# -- end to end ───────────────────────────────────────────────────────────────


def _drain(c, pods, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        live = [c.pod(p.key) for p in pods]
        if all(p is not None and p.spec.node_name for p in live):
            return []
        time.sleep(0.1)
    return [p.key for q, p in
            zip(pods, (c.pod(p.key) for p in pods))
            if p is None or not p.spec.node_name]


def test_sharded_scheduler_binds_mixed_workload_with_shard_telemetry():
    from tpusched.util.metrics import binds_total, scheduling_cycles_total
    prof = tpu_gang_profile(permit_wait_s=30, denied_s=1,
                            scheduler_name="shard-e2e")
    prof.dispatch_shards = 4
    with TestCluster(profile=prof) as c:
        for i in range(8):
            topo, nodes = make_tpu_pool(f"pool-{i:02d}", dims=(4, 4, 4))
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        pods = [make_pod(f"solo-{j}", limits={TPU: 1},
                         scheduler_name="shard-e2e",
                         requests=make_resources(cpu=1, memory="1Gi"))
                for j in range(12)]
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "g1", min_member=4, tpu_slice_shape="2x2x4",
            tpu_accelerator="tpu-v5p"))
        pods += [make_pod(f"g1-{j}", pod_group="g1", limits={TPU: 4},
                          scheduler_name="shard-e2e",
                          requests=make_resources(cpu=1, memory="1Gi"))
                 for j in range(4)]
        c.create_pods(pods)
        assert _drain(c, pods) == []

        s = c.scheduler
        assert s.dispatch_shards == 4
        assert sorted(s.queue.lanes()) == sorted(
            [f"s{i}" for i in range(4)] + [GLOBAL_LANE])
        # per-shard throughput children exist and account for every bind
        kids = binds_total.children()
        lane_binds = {k[1]: v.value() for k, v in kids.items()
                      if k[0] == "shard-e2e"}
        assert sum(lane_binds.values()) == len(pods)
        assert any(l.startswith("s") for l, v in lane_binds.items() if v)
        cyc = {k[1]: v.value() for k, v in
               scheduling_cycles_total.children().items()
               if k[0] == "shard-e2e"}
        assert sum(cyc.values()) >= len(pods)

        # health.shards published into the flight recorder
        # (/debug/flightrecorder renders recorder.dump()["health"])
        s._publish_shard_health()
        health = s.recorder.dump()["health"]["shards"]
        assert health["shard_count"] == 5
        assert set(health["lanes"]) == set(s.queue.lanes())
        for lane, row in health["lanes"].items():
            assert {"cycles", "binds", "conflicts",
                    "escalations"} <= set(row)
        # cycle traces carry the lane id (the ring is process-global:
        # filter to THIS scheduler's cycles)
        shards_seen = {t.shard for t in s.recorder.traces()
                       if t.scheduler == "shard-e2e"}
        assert shards_seen and all(sh in set(s.queue.lanes())
                                   for sh in shards_seen)


def test_partition_capacity_shortfall_does_not_poison_denied_window():
    """A gang whose min_resources exceed its home shard's partition but
    fit the fleet must bind promptly: the shard-lane Coscheduling
    capacity dry-run failure must NOT write the process-global
    denied-PodGroup window (the escalated global-lane retry would bounce
    off it for the whole denial TTL)."""
    prof = tpu_gang_profile(permit_wait_s=30, denied_s=30,
                            scheduler_name="shard-deny")
    prof.dispatch_shards = 2
    # two pools on DIFFERENT shards (so each partition holds one pool)
    pools = []
    i = 0
    while len({pool_shard(p, 2) for p in pools}) < 2:
        name = f"pool-{i:02d}"
        i += 1
        if name not in pools:
            pools = ([p for p in pools
                      if pool_shard(p, 2) != pool_shard(name, 2)]
                     + [name]) if pools else [name]
    with TestCluster(profile=prof) as c:
        for p in pools:
            topo, nodes = make_tpu_pool(p, dims=(4, 4, 4))  # 64 chips each
            c.api.create(srv.TPU_TOPOLOGIES, topo)
            c.add_nodes(nodes)
        pg = make_pod_group("bigmin", min_member=4,
                            tpu_slice_shape="2x2x4",
                            tpu_accelerator="tpu-v5p")
        # dry-run demand: > one pool (64), <= fleet (128)
        pg.spec.min_resources = {TPU: 100}
        c.api.create(srv.POD_GROUPS, pg)
        pods = [make_pod(f"bigmin-{j}", pod_group="bigmin",
                         limits={TPU: 4}, scheduler_name="shard-deny",
                         requests=make_resources(cpu=1, memory="1Gi"))
                for j in range(4)]
        c.create_pods(pods)
        # well under the 30s denial TTL: a poisoned window would wedge it
        assert _drain(c, pods, timeout=15.0) == [], (
            "gang stalled: the shard-lane capacity shortfall poisoned "
            "the global denied-PodGroup window")


def test_escalation_rescues_units_hashed_to_poolless_shards():
    """A unit hashed to a shard that owns no pools must still bind: the
    empty-partition cycle escalates it to the global lane."""
    prof = tpu_gang_profile(permit_wait_s=30, denied_s=1,
                            scheduler_name="shard-esc")
    prof.dispatch_shards = 4
    with TestCluster(profile=prof) as c:
        # ONE pool: three of the four shards own nothing
        topo, nodes = make_tpu_pool("pool-00", dims=(4, 4, 4))
        c.api.create(srv.TPU_TOPOLOGIES, topo)
        c.add_nodes(nodes)
        pool_lane = pool_shard("pool-00", 4)
        # find pod names hashed to a POOLLESS shard
        pods = []
        i = 0
        while len(pods) < 3:
            name = f"esc-{i}"
            i += 1
            key = f"default/{name}"
            if pool_shard(key, 4) != pool_lane:
                pods.append(make_pod(name, limits={TPU: 1},
                                     scheduler_name="shard-esc",
                                     requests=make_resources(
                                         cpu=1, memory="1Gi")))
        c.create_pods(pods)
        assert _drain(c, pods) == []
        assert c.scheduler.shard_router().escalations() >= len(pods)
        assert c.scheduler.shard_router().escalated_units()
