"""tpulint (tpusched/analysis): positive + negative fixtures for every
rule, suppression handling, JSON output schema, the CLI, and the meta-test
that the LIVE tree is lint-clean inside the latency budget that lets the
lint gate tier1 (< 15 s full-tree).
"""
from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tpusched.analysis import Runner, rule_names
from tpusched.analysis.core import SUPPRESSION_HYGIENE

REPO_ROOT = Path(__file__).resolve().parents[1]


def run_snippet(tmp_path, relpath, source, rules=None, extra=()):
    """Write dedented ``source`` at ``relpath`` under a scratch repo root
    and lint it (plus ``extra`` (relpath, source) files) with ``rules``."""
    paths = []
    for rp, src in [(relpath, source)] + list(extra):
        f = tmp_path / rp
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent(src))
        paths.append(f)
    return Runner(tmp_path, rules).run(paths)


def names(report):
    return [(f.rule, f.line) for f in report.findings]


# -- naked-api-calls -----------------------------------------------------------


def test_naked_api_calls(tmp_path):
    bad = """
        class S:
            def work(self):
                return self._api.try_get("pods", "k")
    """
    r = run_snippet(tmp_path, "tpusched/sched/foo.py", bad,
                    ["naked-api-calls"])
    assert [f.rule for f in r.findings] == ["naked-api-calls"]
    # same file under apiserver/ is the implementation package — exempt
    r = run_snippet(tmp_path, "tpusched/apiserver/foo.py", bad,
                    ["naked-api-calls"])
    assert r.findings == []
    # direct store verbs on self.api in the scheduling core
    core_bad = """
        class P:
            def bind_it(self, b):
                return self.api.bind(b)
    """
    r = run_snippet(tmp_path, "tpusched/plugins/p.py", core_bad,
                    ["naked-api-calls"])
    assert len(r.findings) == 1 and "retry layer" in r.findings[0].message
    # non-verb attribute access on self.api is informer wiring — fine
    ok = """
        class P:
            def wire(self):
                self.api.add_watch("pods", self.cb)
    """
    r = run_snippet(tmp_path, "tpusched/sched/q.py", ok,
                    ["naked-api-calls"])
    assert r.findings == []


# -- node-health-filters -------------------------------------------------------


def test_node_health_filter_missing_reference(tmp_path):
    bad = """
        class F:
            def filter(self, state, pod, node_info):
                return None
    """
    r = run_snippet(tmp_path, "tpusched/plugins/myplug.py", bad,
                    ["node-health-filters"])
    assert [f.rule for f in r.findings] == ["node-health-filters"]
    ok = """
        from ..api.core import node_health_error

        class F:
            def filter(self, state, pod, node_info):
                if node_health_error(node_info.node()):
                    return "unhealthy"
    """
    r = run_snippet(tmp_path, "tpusched/plugins/myplug2.py", ok,
                    ["node-health-filters"])
    assert r.findings == []


def test_node_health_helper_fact_check(tmp_path):
    weakened = """
        def node_health_error(node):
            if node.spec.unschedulable:
                return "cordoned"
            return None
    """
    r = run_snippet(tmp_path, "tpusched/api/core.py", weakened,
                    ["node-health-filters"])
    msgs = [f.message for f in r.findings]
    assert any("node_ready" in m for m in msgs)
    assert any("TAINT_NODE_NOT_READY" in m for m in msgs)


# -- metrics-names -------------------------------------------------------------


def test_metrics_naming_contract(tmp_path):
    bad = """
        from ..util.metrics import REGISTRY
        a = REGISTRY.counter("foo_total", "no prefix")
        b = REGISTRY.counter("tpusched_things", "no _total")
        c = REGISTRY.histogram("tpusched_lat_ms", "wrong unit")
        d = REGISTRY.gauge("tpusched_depth_total", "gauge as counter")
    """
    r = run_snippet(tmp_path, "tpusched/obs/m.py", bad, ["metrics-names"])
    msgs = " ".join(f.message for f in r.findings)
    assert "missing tpusched_ prefix" in msgs
    assert "counters must end _total" in msgs
    assert "histograms must end _seconds" in msgs
    assert "gauges must not end _total" in msgs


def test_metrics_duplicate_across_files(tmp_path):
    one = 'x = REGISTRY.counter("tpusched_x_total", "a")\n'
    two = 'y = REGISTRY.counter("tpusched_x_total", "b")\n'
    r = run_snippet(tmp_path, "tpusched/a.py", one, ["metrics-names"],
                    extra=[("tpusched/b.py", two)])
    assert any("duplicate registration" in f.message for f in r.findings)
    # gauge_func re-registration is its designed lifecycle
    gf = 'g = REGISTRY.gauge_func("tpusched_g", lambda: 1)\n'
    r = run_snippet(tmp_path, "tpusched/c.py", gf, ["metrics-names"],
                    extra=[("tpusched/d.py", gf)])
    assert r.findings == []
    # ...but ONLY gauge_func-vs-gauge_func: a counter colliding with a
    # gauge_func name ships two registrations of one series, either order
    ctr = 'c = REGISTRY.gauge("tpusched_g", "collides")\n'
    r = run_snippet(tmp_path, "tpusched/e.py", gf, ["metrics-names"],
                    extra=[("tpusched/f.py", ctr)])
    assert any("duplicate registration" in f.message for f in r.findings)


# -- structured-logging --------------------------------------------------------


def test_print_flagged_outside_cmd(tmp_path):
    src = 'print("hello")\n'
    r = run_snippet(tmp_path, "tpusched/sched/x.py", src,
                    ["structured-logging"])
    assert [f.rule for f in r.findings] == ["structured-logging"]
    for exempt in ("tpusched/cmd/x.py", "tpusched/testing/x.py"):
        r = run_snippet(tmp_path, exempt, src, ["structured-logging"])
        assert r.findings == []


# -- exception-taxonomy --------------------------------------------------------


def test_exception_taxonomy(tmp_path):
    bare = """
        try:
            x = 1
        except:
            pass
    """
    r = run_snippet(tmp_path, "tpusched/a.py", bare,
                    ["exception-taxonomy"])
    assert "bare except" in r.findings[0].message
    swallow = """
        try:
            x = 1
        except Exception:
            x = 2
    """
    r = run_snippet(tmp_path, "tpusched/b.py", swallow,
                    ["exception-taxonomy"])
    assert len(r.findings) == 1
    # binding + referencing the exception preserves the taxonomy
    logged = """
        try:
            x = 1
        except Exception as e:
            log(e)
    """
    r = run_snippet(tmp_path, "tpusched/c.py", logged,
                    ["exception-taxonomy"])
    assert r.findings == []
    reraised = """
        try:
            x = 1
        except BaseException:
            raise
    """
    r = run_snippet(tmp_path, "tpusched/d.py", reraised,
                    ["exception-taxonomy"])
    assert r.findings == []
    narrow = """
        try:
            x = 1
        except ValueError:
            pass
    """
    r = run_snippet(tmp_path, "tpusched/e.py", narrow,
                    ["exception-taxonomy"])
    assert r.findings == []


# -- shadow-isolation ----------------------------------------------------------


def test_shadow_module_must_not_touch_globals(tmp_path):
    bad = """
        from ..util.metrics import REGISTRY

        def plan(api, registry, profile):
            s = Scheduler(api, registry, profile)
            return s
    """
    r = run_snippet(tmp_path, "tpusched/sim/planner.py", bad,
                    ["shadow-isolation"])
    msgs = " ".join(f.message for f in r.findings)
    assert "REGISTRY" in msgs
    assert "telemetry=False" in msgs
    ok = """
        def plan(api, registry, profile):
            return Scheduler(api, registry, profile, telemetry=False)
    """
    r = run_snippet(tmp_path, "tpusched/sim/planner2.py", ok,
                    ["shadow-isolation"])
    assert r.findings == []


def test_accessor_needs_guard_outside_sim(tmp_path):
    bad = """
        from .. import trace

        def wire(self):
            self.rec = trace.default_recorder()
    """
    r = run_snippet(tmp_path, "tpusched/sched/s.py", bad,
                    ["shadow-isolation"])
    assert len(r.findings) == 1
    guarded = """
        from .. import trace

        def wire(self, telemetry):
            if telemetry:
                self.rec = trace.default_recorder()
            else:
                self.rec = trace.FlightRecorder()
    """
    r = run_snippet(tmp_path, "tpusched/sched/s2.py", guarded,
                    ["shadow-isolation"])
    assert r.findings == []
    module_level = """
        from .. import trace
        REC = trace.default_recorder()
    """
    r = run_snippet(tmp_path, "tpusched/sched/s3.py", module_level,
                    ["shadow-isolation"])
    assert "module level" in r.findings[0].message


def test_profiler_accessors_are_shadow_guarded(tmp_path):
    """ISSUE 7: the profiler joined the global-surface accessor set — an
    unguarded ensure_profiler()/default_profiler() outside sim/ is a
    finding, and a shadow module may not reference them at all (a trial
    run must never publish live hot-path samples)."""
    bad = """
        from .. import obs

        def wire(self):
            obs.ensure_profiler()
            self.prof = obs.default_profiler()
    """
    r = run_snippet(tmp_path, "tpusched/sched/s.py", bad,
                    ["shadow-isolation"])
    assert len(r.findings) == 2
    guarded = """
        from .. import obs

        def wire(self, telemetry):
            if telemetry:
                obs.ensure_profiler()
    """
    r = run_snippet(tmp_path, "tpusched/sched/s2.py", guarded,
                    ["shadow-isolation"])
    assert r.findings == []
    shadow = """
        from .. import obs

        def trial(self):
            obs.install_profiler(obs.HotPathProfiler())
    """
    r = run_snippet(tmp_path, "tpusched/sim/trial.py", shadow,
                    ["shadow-isolation"])
    assert any("install_profiler" in f.message for f in r.findings)


def test_fleetrace_accessors_are_shadow_guarded(tmp_path):
    """ISSUE 9: the fleet trace recorder joined the global-surface
    accessor set — a replay driver (sim/) reaching default_fleetrecorder
    or ensure_fleetrace would journal simulated binds as fleet reality,
    and a replay-driven shadow Scheduler constructed without
    telemetry=False wires every live surface."""
    replay_driver = """
        from .. import obs

        def run_replay(api, registry, profile):
            rec = obs.default_fleetrecorder()
            sched = Scheduler(api, registry, profile)
            return rec, sched
    """
    r = run_snippet(tmp_path, "tpusched/sim/replaybad.py", replay_driver,
                    ["shadow-isolation"])
    msgs = " ".join(f.message for f in r.findings)
    assert "default_fleetrecorder" in msgs
    assert "telemetry=False" in msgs

    registry_reach = """
        from ..util.metrics import REGISTRY

        def publish(report):
            REGISTRY.gauge_func("x", lambda: 1.0, "")
    """
    r = run_snippet(tmp_path, "tpusched/sim/replaybad2.py", registry_reach,
                    ["shadow-isolation"])
    assert any("REGISTRY" in f.message for f in r.findings)

    guarded = """
        from .. import obs

        def wire(self, api, telemetry):
            if telemetry:
                self._fleet = obs.ensure_fleetrace(api)
            else:
                self._fleet = obs.FleetTraceRecorder()
    """
    r = run_snippet(tmp_path, "tpusched/sched/wiring.py", guarded,
                    ["shadow-isolation"])
    assert r.findings == []

    unguarded = """
        from .. import obs

        def wire(self, api):
            self._fleet = obs.ensure_fleetrace(api)
    """
    r = run_snippet(tmp_path, "tpusched/sched/wiring2.py", unguarded,
                    ["shadow-isolation"])
    assert len(r.findings) == 1
    assert "ensure_fleetrace" in r.findings[0].message


def test_goodput_accessors_are_shadow_guarded(tmp_path):
    """ISSUE 10: the goodput aggregator joined the global-surface
    accessor set — a shadow scheduler publishing synthetic member
    reports would fabricate fleet goodput, straggler anomalies and
    throughput-matrix cells.  A sim/ module may not reference the
    accessors at all; elsewhere they need the telemetry guard; the pure
    matrix types stay importable by sim/ (matrices are consumed by
    value)."""
    shadow = """
        from .. import obs

        def trial(api):
            return obs.ensure_goodput(api)
    """
    r = run_snippet(tmp_path, "tpusched/sim/goodbad.py", shadow,
                    ["shadow-isolation"])
    assert any("ensure_goodput" in f.message for f in r.findings)

    shadow_install = """
        from ..obs import install_goodput
    """
    r = run_snippet(tmp_path, "tpusched/sim/goodbad2.py", shadow_install,
                    ["shadow-isolation"])
    assert any("install_goodput" in f.message for f in r.findings)

    unguarded = """
        from .. import obs

        def wire(self, api):
            self._goodput = obs.ensure_goodput(api)
    """
    r = run_snippet(tmp_path, "tpusched/sched/gwire.py", unguarded,
                    ["shadow-isolation"])
    assert len(r.findings) == 1
    assert "ensure_goodput" in r.findings[0].message

    guarded = """
        from .. import obs

        def wire(self, api, telemetry):
            if telemetry:
                self._goodput = obs.ensure_goodput(api)
            else:
                self._goodput = obs.GoodputAggregator(publish=False)
    """
    r = run_snippet(tmp_path, "tpusched/sched/gwire2.py", guarded,
                    ["shadow-isolation"])
    assert r.findings == []

    # the pure data types are NOT accessors: the what-if planner consumes
    # a measured matrix by value, and that must stay lint-clean
    consumer = """
        from ..obs.goodput import GoodputMatrix, workload_fingerprint_of

        def annotate(report, matrix):
            return matrix.peek(report.workload, report.generation)
    """
    r = run_snippet(tmp_path, "tpusched/sim/matrixok.py", consumer,
                    ["shadow-isolation"])
    assert r.findings == []


# -- monotonic-clock -----------------------------------------------------------


def test_monotonic_clock_flags_calls_not_references(tmp_path):
    src = """
        import time

        def f():
            return time.time()

        def g(clock=time.time):
            return clock()
    """
    r = run_snippet(tmp_path, "tpusched/a.py", src, ["monotonic-clock"])
    assert len(r.findings) == 1       # the call, not the default parameter


def test_monotonic_clock_sees_through_aliases(tmp_path):
    src = """
        import time as _t
        from time import time as wall

        def f():
            return _t.time() + wall()
    """
    r = run_snippet(tmp_path, "tpusched/b.py", src, ["monotonic-clock"])
    assert len(r.findings) == 2


def test_monotonic_clock_gate_modules_flag_raw_monotonic_calls(tmp_path):
    """ISSUE 15: inside the scheduler gate modules a raw
    time.monotonic() CALL is a finding (a deadline computed from it is
    invisible to VirtualClock); outside them it stays free."""
    src = """
        import time

        def deadline():
            return time.monotonic() + 5.0
    """
    gate = run_snippet(tmp_path, "tpusched/sched/queue.py", src,
                       ["monotonic-clock"])
    assert len(gate.findings) == 1
    assert "handle clock" in gate.findings[0].message
    free = run_snippet(tmp_path, "tpusched/obs/whatever.py", src,
                       ["monotonic-clock"])
    assert free.findings == []


def test_monotonic_clock_gate_modules_flag_clock_default_param(tmp_path):
    """...and a ``clock=time.monotonic`` DEFAULT parameter (gate
    components must default to clock=None and resolve in the body, so
    skipping the handle clock is a visible wiring choice).  Aliases are
    resolved; non-clock parameters and body fallbacks stay free."""
    src = """
        import time
        from time import monotonic as mono

        class Gate:
            def __init__(self, ttl, clock=time.monotonic):
                self._clock = clock

        def ok(ttl, clock=None, other=mono):
            return (clock or time.monotonic)
    """
    r = run_snippet(tmp_path, "tpusched/util/ttlcache.py", src,
                    ["monotonic-clock"])
    assert len(r.findings) == 1
    assert "visible choice" in r.findings[0].message

    aliased = """
        from time import monotonic as mono

        def gate(clock=mono):
            return clock
    """
    r2 = run_snippet(tmp_path, "tpusched/sched/shards.py", aliased,
                     ["monotonic-clock"])
    assert len(r2.findings) == 1


def test_monotonic_clock_substrate_module_is_exempt(tmp_path):
    src = """
        import time

        def now():
            return time.monotonic()
        wall = time.time
    """
    r = run_snippet(tmp_path, "tpusched/util/clock.py", src,
                    ["monotonic-clock"])
    assert r.findings == []


# -- thread-hygiene ------------------------------------------------------------


def test_thread_hygiene(tmp_path):
    src = """
        import threading

        def a():
            threading.Thread(target=a).start()

        def b():
            threading.Thread(target=b, daemon=True).start()

        def c():
            threading.Thread(target=c, name="tpusched-c",
                             daemon=True).start()
    """
    r = run_snippet(tmp_path, "tpusched/t.py", src, ["thread-hygiene"])
    assert len(r.findings) == 2
    assert "name/daemon" in r.findings[0].message
    assert "name" in r.findings[1].message


# -- lock-discipline -----------------------------------------------------------

_GUARDED_CLASS = """
    from tpusched.util.locking import GuardedLock, guarded_by

    @guarded_by("_lock", "_d", "_n")
    class Box:
        def __init__(self):
            self._lock = GuardedLock("Box")
            self._d = {}
            self._n = 0

        def good(self):
            with self._lock:
                self._d["a"] = 1
                self._n += 1

        def helper_locked(self):
            self._d.pop("a", None)

        def bad(self):
            self._d["b"] = 2

        def bad_mutator(self):
            self._d.update(x=1)

        def bad_rebind(self):
            self._n = 7
"""


def test_lock_discipline_rule(tmp_path):
    r = run_snippet(tmp_path, "tpusched/sched/box.py", _GUARDED_CLASS,
                    ["lock-discipline"])
    got = sorted((f.message.split(":")[0], f.line) for f in r.findings)
    # exactly the three bad methods; good/__init__/_locked are clean
    assert len(got) == 3
    msgs = " ".join(f.message for f in r.findings)
    assert "Box.bad:" in msgs
    assert "Box.bad_mutator:" in msgs
    assert "Box.bad_rebind:" in msgs


def test_lock_discipline_ignores_undeclared_classes(tmp_path):
    src = """
        class Plain:
            def poke(self):
                self._d["a"] = 1
    """
    r = run_snippet(tmp_path, "tpusched/sched/plain.py", src,
                    ["lock-discipline"])
    assert r.findings == []


# -- suppressions --------------------------------------------------------------


def test_sameline_suppression(tmp_path):
    src = ('import time\n'
           'x = time.time()  '
           '# tpulint: disable=monotonic-clock — fixture wall time\n')
    r = run_snippet(tmp_path, "tpusched/a.py", src,
                    ["monotonic-clock", SUPPRESSION_HYGIENE])
    assert r.findings == []
    assert len(r.suppressed) == 1
    assert r.suppressed[0][1].reason == "fixture wall time"


def test_standalone_suppression_spans_wrapped_comment(tmp_path):
    src = ('import time\n'
           '# tpulint: disable=monotonic-clock — a justification that\n'
           '# wraps over two comment lines\n'
           'x = time.time()\n')
    r = run_snippet(tmp_path, "tpusched/b.py", src,
                    ["monotonic-clock", SUPPRESSION_HYGIENE])
    assert r.findings == []
    assert len(r.suppressed) == 1


def test_suppression_without_reason_is_a_finding(tmp_path):
    src = ('import time\n'
           'x = time.time()  # tpulint: disable=monotonic-clock —\n')
    r = run_snippet(tmp_path, "tpusched/c.py", src,
                    ["monotonic-clock", SUPPRESSION_HYGIENE])
    rules = {f.rule for f in r.findings}
    assert SUPPRESSION_HYGIENE in rules
    assert any("no justification" in f.message for f in r.findings)


def test_suppression_without_separator_still_parsed_and_flagged(tmp_path):
    """The most natural malformed directive — no separator, no reason —
    must not be silently ignored: it suppresses nothing AND hygiene tells
    the author why."""
    src = ('import time\n'
           'x = time.time()  # tpulint: disable=monotonic-clock\n')
    r = run_snippet(tmp_path, "tpusched/c2.py", src,
                    ["monotonic-clock", SUPPRESSION_HYGIENE])
    assert any("no justification" in f.message for f in r.findings)


def test_unknown_rule_in_suppression_is_a_finding(tmp_path):
    src = 'x = 1  # tpulint: disable=no-such-rule — because\n'
    r = run_snippet(tmp_path, "tpusched/d.py", src,
                    [SUPPRESSION_HYGIENE])
    assert any("unknown rule" in f.message for f in r.findings)


def test_unused_suppression_is_a_finding(tmp_path):
    src = 'x = 1  # tpulint: disable=monotonic-clock — nothing here\n'
    r = run_snippet(tmp_path, "tpusched/e.py", src,
                    ["monotonic-clock", SUPPRESSION_HYGIENE])
    assert any("matched no finding" in f.message for f in r.findings)


def test_unused_check_skipped_for_inactive_rules(tmp_path):
    """A single-rule wrapper run must not flag other rules' suppressions
    as stale — only `make verify`'s full pass judges usedness."""
    src = 'x = 1  # tpulint: disable=monotonic-clock — wall by design\n'
    r = run_snippet(tmp_path, "tpusched/f.py", src,
                    ["thread-hygiene", SUPPRESSION_HYGIENE])
    assert r.findings == []


# -- output + CLI --------------------------------------------------------------


def test_json_schema(tmp_path):
    src = ('import time\n'
           'x = time.time()\n'
           'y = time.time()  # tpulint: disable=monotonic-clock — fixture\n')
    r = run_snippet(tmp_path, "tpusched/j.py", src,
                    ["monotonic-clock", SUPPRESSION_HYGIENE])
    doc = json.loads(r.to_json())
    assert doc["version"] == 1
    assert doc["files"] == 1
    assert set(doc) == {"version", "files", "rules", "findings",
                        "suppressed", "errors", "duration_s"}
    (f,) = doc["findings"]
    assert set(f) == {"rule", "path", "line", "col", "message"}
    assert f["rule"] == "monotonic-clock" and f["line"] == 2
    (s,) = doc["suppressed"]
    assert s["reason"] == "fixture" and s["suppressed_at"] == 3


def test_syntax_error_is_an_error_not_a_crash(tmp_path):
    r = run_snippet(tmp_path, "tpusched/broken.py", "def f(:\n")
    assert r.findings == []
    assert len(r.errors) == 1 and "syntax error" in r.errors[0]
    assert not r.clean


def _cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tpusched.cmd.lint", *args],
        capture_output=True, text=True, cwd=cwd)


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "tpusched" / "x.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import time\nx = time.time()\n")
    p = _cli("--root", str(tmp_path), "--json")
    assert p.returncode == 1
    doc = json.loads(p.stdout)
    assert doc["findings"][0]["rule"] == "monotonic-clock"
    bad.write_text("x = 1\n")
    p = _cli("--root", str(tmp_path))
    assert p.returncode == 0, p.stdout + p.stderr
    p = _cli("--rules", "no-such-rule")
    assert p.returncode == 2
    assert "unknown rule" in p.stderr


def test_cli_list_rules():
    p = _cli("--list-rules")
    assert p.returncode == 0
    for name in rule_names():
        assert name in p.stdout


def test_cli_changed_only_smoke():
    p = _cli("--changed-only", "--json")
    # clean or findings, but never a usage/internal error — and the
    # output must parse
    assert p.returncode in (0, 1), p.stderr
    json.loads(p.stdout)


# -- the meta-tests: the live tree, and the latency budget ---------------------


def test_live_tree_is_lint_clean_and_fast():
    """The acceptance criteria in one test: tpulint reports zero
    unsuppressed findings on the REAL tree (including its own package —
    the self-check), every suppression carries a reason (hygiene is part
    of the run), and the full pass fits the < 15 s budget that lets it
    gate tier1."""
    runner = Runner(REPO_ROOT)
    report = runner.run([REPO_ROOT / "tpusched"])
    assert report.errors == [], report.errors
    assert report.findings == [], "\n" + report.render_text()
    assert report.files > 100            # the whole tree, not a subset
    assert all(s.reason for _, s in report.suppressed)
    assert report.duration_s < 15.0, (
        f"tpulint full-tree pass took {report.duration_s:.1f}s — "
        f"too slow to stay a tier1 prerequisite")


def test_all_advertised_rules_are_registered():
    expected = {"naked-api-calls", "node-health-filters", "metrics-names",
                "structured-logging", "exception-taxonomy",
                "shadow-isolation", "monotonic-clock", "thread-hygiene",
                "lock-discipline", "atomicity-violation",
                "snapshot-discipline", "locked-callgraph",
                SUPPRESSION_HYGIENE}
    assert expected == set(rule_names())
