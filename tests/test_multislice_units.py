"""MultiSlice PreScore/Score unit tables — the plugin is a TPU-native
addition with no reference analog (SURVEY §7.7), so its contract is pinned
here at the same table depth the ported plugins get: domain collection from
placed siblings, the same/adjacent/remote score ladder, skip paths, and
isolation between sets and namespaces.
"""
from tpusched.api.resources import TPU
from tpusched.apiserver import server as srv
from tpusched.config.types import MultiSliceArgs
from tpusched.fwk import CycleState, PluginProfile
from tpusched.testing import (make_pod, make_pod_group, make_tpu_node,
                              new_test_framework)

SET = "llama70b"


def ms_framework(args=None, pod_groups=(), pods=(), nodes=()):
    profile = PluginProfile(
        pre_score=["MultiSlice"], score=[("MultiSlice", 1)],
        bind=["DefaultBinder"],
        plugin_args={"MultiSlice": args} if args else {})
    fw, handle, api = new_test_framework(profile, nodes=nodes, pods=pods)
    for pg in pod_groups:
        api.create(srv.POD_GROUPS, pg)
    return fw, fw.plugins["MultiSlice"], handle, api


def domain_node(name, domain):
    return make_tpu_node(name, chips=4, dcn_domain=domain)


def slice_pg(index, namespace="default"):
    return make_pod_group(f"{SET}-slice-{index}", namespace=namespace,
                          min_member=1, multislice_set=SET,
                          multislice_index=index)


def placed_sibling(name, pg, node, namespace="default"):
    return make_pod(name, namespace=namespace, pod_group=pg,
                    limits={TPU: 4}, node_name=node)


def run_pre_score(ms, pod):
    state = CycleState()
    st = ms.pre_score(state, pod, [])
    return state, st


def test_pre_score_skips_non_multislice_pods():
    pg = make_pod_group("plain-gang", min_member=1)
    fw, ms, _, api = ms_framework(pod_groups=[pg])
    _, st = run_pre_score(ms, make_pod("solo"))
    assert st.is_skip()
    _, st = run_pre_score(ms, make_pod("m", pod_group="plain-gang"))
    assert st.is_skip()


def test_pre_score_skips_first_slice_of_set():
    """No placed sibling ⇒ nothing to pull toward: Score must not run."""
    fw, ms, _, api = ms_framework(pod_groups=[slice_pg(0)])
    _, st = run_pre_score(ms, make_pod("p", pod_group=f"{SET}-slice-0"))
    assert st.is_skip()


def scored_framework(extra_pgs=(), sibling_domains=("zoneA/rack1",)):
    """slice-1 scoring while slice-0 members sit in sibling_domains."""
    nodes = [domain_node(f"placed-{i}", d)
             for i, d in enumerate(sibling_domains)]
    nodes += [domain_node("same", "zoneA/rack1"),
              domain_node("adjacent", "zoneA/rack2"),
              domain_node("remote", "zoneB/rack1"),
              make_tpu_node("unlabeled", chips=4)]
    placed = [placed_sibling(f"s0-{i}", f"{SET}-slice-0", f"placed-{i}")
              for i in range(len(sibling_domains))]
    fw, ms, handle, api = ms_framework(
        pod_groups=[slice_pg(0), slice_pg(1), *extra_pgs],
        pods=placed, nodes=nodes)
    return fw, ms, handle, api


def test_score_ladder_same_adjacent_remote_unlabeled():
    fw, ms, handle, api = scored_framework()
    pod = make_pod("p", pod_group=f"{SET}-slice-1", limits={TPU: 4})
    state, st = run_pre_score(ms, pod)
    assert st.is_success()
    assert ms.score(state, pod, "same")[0] == 100
    assert ms.score(state, pod, "adjacent")[0] == 50
    assert ms.score(state, pod, "remote")[0] == 0
    assert ms.score(state, pod, "unlabeled")[0] == 0


def test_score_custom_args_and_cap():
    args = MultiSliceArgs(same_domain_score=500, adjacent_domain_score=80)
    nodes = [domain_node("placed-0", "zoneA/rack1"),
             domain_node("same", "zoneA/rack1"),
             domain_node("adjacent", "zoneA/rack2")]
    fw, ms, handle, api = ms_framework(
        args=args, pod_groups=[slice_pg(0), slice_pg(1)],
        pods=[placed_sibling("s0-0", f"{SET}-slice-0", "placed-0")],
        nodes=nodes)
    pod = make_pod("p", pod_group=f"{SET}-slice-1", limits={TPU: 4})
    state, st = run_pre_score(ms, pod)
    assert st.is_success()
    assert ms.score(state, pod, "same")[0] == 100   # capped at MaxNodeScore
    assert ms.score(state, pod, "adjacent")[0] == 80


def test_siblings_spanning_domains_all_attract():
    """A set already spread over two domains: BOTH count as same-domain."""
    fw, ms, handle, api = scored_framework(
        sibling_domains=("zoneA/rack1", "zoneB/rack1"))
    pod = make_pod("p", pod_group=f"{SET}-slice-1", limits={TPU: 4})
    state, _ = run_pre_score(ms, pod)
    assert ms.score(state, pod, "same")[0] == 100      # zoneA/rack1
    assert ms.score(state, pod, "remote")[0] == 100    # zoneB/rack1 now sibling


def test_other_set_does_not_attract():
    """Placed pods of a DIFFERENT multislice set must not pull this one."""
    other_pg = make_pod_group("other-slice-0", min_member=1,
                              multislice_set="other", multislice_index=0)
    nodes = [domain_node("placed-0", "zoneA/rack1"),
             domain_node("same", "zoneA/rack1")]
    fw, ms, handle, api = ms_framework(
        pod_groups=[other_pg, slice_pg(0), slice_pg(1)],
        pods=[placed_sibling("o-0", "other-slice-0", "placed-0")],
        nodes=nodes)
    pod = make_pod("p", pod_group=f"{SET}-slice-1", limits={TPU: 4})
    _, st = run_pre_score(ms, pod)
    assert st.is_skip()   # no OWN siblings placed anywhere


def test_same_set_other_namespace_does_not_attract():
    """multislice_set matching is namespace-scoped."""
    fw, ms, handle, api = ms_framework(
        pod_groups=[slice_pg(0, namespace="team-b"), slice_pg(0),
                    slice_pg(1)],
        pods=[placed_sibling("b-0", f"{SET}-slice-0", "placed-0",
                             namespace="team-b")],
        nodes=[domain_node("placed-0", "zoneA/rack1"),
               domain_node("same", "zoneA/rack1")])
    pod = make_pod("p", pod_group=f"{SET}-slice-1", limits={TPU: 4})
    _, st = run_pre_score(ms, pod)
    assert st.is_skip()


def test_unassigned_siblings_do_not_attract():
    """Only pods with a node (assumed/bound) contribute domains — a pending
    sibling slice must not anchor the set to nowhere."""
    pending = make_pod("s0-pending", pod_group=f"{SET}-slice-0",
                       limits={TPU: 4})  # no node_name
    fw, ms, handle, api = ms_framework(
        pod_groups=[slice_pg(0), slice_pg(1)],
        pods=[pending],
        nodes=[domain_node("same", "zoneA/rack1")])
    pod = make_pod("p", pod_group=f"{SET}-slice-1", limits={TPU: 4})
    _, st = run_pre_score(ms, pod)
    assert st.is_skip()


def test_set_capacity_gap_is_domain_wise_under_hard_policy():
    """Hard same-domain turns the set dry-run domain-wise: a request that
    fits the FLEET but no single domain is a gap (the module-doc footgun —
    without this the set burns its full timeout); unlabeled nodes count
    with every candidate domain since the hard Filter never excludes
    them."""
    nodes = ([domain_node(f"a{i}", "zoneA/rack0") for i in range(2)]
             + [domain_node(f"b{i}", "zoneA/rack1") for i in range(2)])
    fw, ms, handle, api = ms_framework(
        args=MultiSliceArgs(hard_domain_policy="same-domain"), nodes=nodes)
    infos = handle.snapshot_shared_lister().list()
    assert ms._set_capacity_gap(infos, {TPU: 8}, frozenset()) is None
    gap = ms._set_capacity_gap(infos, {TPU: 12}, frozenset())
    assert gap and "no single DCN domain" in gap
    # soft mode keeps the fleet-wide semantics
    fw2, ms2, _, _ = ms_framework(nodes=nodes)
    assert ms2._set_capacity_gap(infos, {TPU: 12}, frozenset()) is None
    # same-zone groups merge the two racks: 16 chips in one zone
    fw3, ms3, _, _ = ms_framework(
        args=MultiSliceArgs(hard_domain_policy="same-zone"), nodes=nodes)
    assert ms3._set_capacity_gap(infos, {TPU: 12}, frozenset()) is None
    # unlabeled spill is usable alongside any single domain
    nodes4 = nodes + [make_tpu_node("u0", chips=4)]
    fw4, ms4, handle4, _ = ms_framework(
        args=MultiSliceArgs(hard_domain_policy="same-domain"), nodes=nodes4)
    infos4 = handle4.snapshot_shared_lister().list()
    assert ms4._set_capacity_gap(infos4, {TPU: 12}, frozenset()) is None
    assert ms4._set_capacity_gap(infos4, {TPU: 14}, frozenset()) is not None
