"""The rest of the stack over the kube transport (KEP-304): the
acceptance test in test_kube_client.py proved the SCHEDULER; this file
proves the async controllers (leader election via HTTP Leases, PodGroup
phase machine and ElasticQuota usage writing through the /status
subresource over sockets) and the what-if simulator snapshotting a live
cluster without mutating it."""
import pytest

from tpusched.api.core import Binding, POD_RUNNING, POD_SUCCEEDED
from tpusched.api.resources import TPU
from tpusched.api.scheduling import (PG_FINISHED, PG_RUNNING, PG_SCHEDULED)
from tpusched.apiserver import kube
from tpusched.apiserver import server as srv
from tpusched.controllers.runner import ControllerRunner, ServerRunOptions
from tpusched.testing import (make_elastic_quota, make_pod, make_pod_group,
                              make_tpu_node, make_tpu_pool, wait_until)
from tpusched.testing.kubefake import FakeKube


@pytest.fixture()
def fake():
    with FakeKube() as f:
        yield f


@pytest.fixture()
def api(fake):
    a = kube.KubeAPIServer(kube.ConnectionInfo(fake.url)).start()
    yield a
    a.stop()


def _set_phase(api, key, phase):
    api.patch(srv.PODS, key, lambda p: setattr(p.status, "phase", phase))


def test_podgroup_controller_reconciles_over_http(api, fake):
    """PodGroup lifecycle with scheduler AND controller both on the kube
    transport: the scheduler's PostBind writes status.scheduled, the
    controller walks the phase machine — all through podgroups/status
    over sockets. The fake DROPS main-resource status writes, so a green
    run proves every status patch rides the right endpoint."""
    from tpusched.config.profiles import tpu_gang_profile
    from tpusched.plugins import default_registry
    from tpusched.sched import Scheduler

    runner = ControllerRunner(api, ServerRunOptions(workers=1))
    runner.run()
    sched = Scheduler(api, default_registry(), tpu_gang_profile())
    sched.run()
    try:
        topo, nodes = make_tpu_pool("pool-0", dims=(2, 2, 2))
        api.create(srv.TPU_TOPOLOGIES, topo)
        for n in nodes:
            api.create(srv.NODES, n)
        api.create(srv.POD_GROUPS, make_pod_group(
            "job", min_member=2, tpu_slice_shape="2x2x2",
            tpu_accelerator="tpu-v5p"))
        pods = [make_pod(f"w{i}", pod_group="job", limits={TPU: 4})
                for i in range(2)]
        for p in pods:
            api.create(srv.PODS, p)

        def phase():
            raw = fake.object("podgroups", "default", "job")
            return (raw.get("status") or {}).get("phase", "")

        assert wait_until(lambda: phase() == PG_SCHEDULED, timeout=30)
        for p in pods:
            _set_phase(api, p.meta.key, POD_RUNNING)
        assert wait_until(lambda: phase() == PG_RUNNING, timeout=15)
        for p in pods:
            _set_phase(api, p.meta.key, POD_SUCCEEDED)
        assert wait_until(lambda: phase() == PG_FINISHED, timeout=15)
        raw = fake.object("podgroups", "default", "job")
        assert raw["status"]["succeeded"] == 2
        assert raw["status"]["scheduled"] == 2   # the scheduler's PostBind
    finally:
        sched.stop()
        runner.stop()


def test_elasticquota_controller_tracks_usage_over_http(api, fake):
    runner = ControllerRunner(api, ServerRunOptions(workers=1))
    runner.run()
    try:
        api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "team-quota", "default", min={TPU: 8}, max={TPU: 16}))
        api.create(srv.NODES, make_tpu_node("n0", chips=4))
        pod = make_pod("u0", limits={TPU: 4})
        api.create(srv.PODS, pod)
        api.bind(Binding(pod_key="default/u0", node_name="n0"))
        _set_phase(api, "default/u0", POD_RUNNING)

        def used():
            raw = fake.object("elasticquotas", "default", "team-quota")
            return ((raw.get("status") or {}).get("used") or {}).get(
                TPU, "0")

        assert wait_until(lambda: str(used()) == "4", timeout=15)
        api.delete(srv.PODS, "default/u0")
        assert wait_until(lambda: str(used()) in ("0", "None"), timeout=15)
    finally:
        runner.stop()


def test_leader_election_over_http_leases(api):
    """Two runners against the same cluster: exactly one leads (the HTTP
    Lease), and the standby takes over when the leader stops."""
    a = ControllerRunner(api, ServerRunOptions(
        workers=1, enable_leader_election=True, lease_duration_s=1.0,
        renew_interval_s=0.25))
    b = ControllerRunner(api, ServerRunOptions(
        workers=1, enable_leader_election=True, lease_duration_s=1.0,
        renew_interval_s=0.25))
    a.run()
    try:
        assert wait_until(lambda: a.is_leader.is_set(), timeout=15)
        b.run()
        assert not b.is_leader.wait(1.0)
        a.stop()
        # the released (or expired) lease hands over; kube-mode expiry
        # needs a full observed-unchanged duration on top
        assert wait_until(lambda: b.is_leader.is_set(), timeout=20)
    finally:
        a.stop()
        b.stop()


def test_whatif_snapshots_a_live_cluster_without_mutating_it(api, fake):
    from tpusched.sim import simulate_gang
    topo, nodes = make_tpu_pool("pool-0", dims=(4, 4, 2))
    api.create(srv.TPU_TOPOLOGIES, topo)
    for n in nodes:
        api.create(srv.NODES, n)
    before = {k for (p, _ns, k) in fake.store.objects if p == "pods"}
    report = simulate_gang(source_api=api, members=8,
                           slice_shape="4x4x2", accelerator="tpu-v5p",
                           chips_per_pod=4)
    assert report.feasible, report.to_dict()
    assert len(report.placements) == 8
    after = {k for (p, _ns, k) in fake.store.objects if p == "pods"}
    assert after == before     # the real cluster never saw the gang
