"""Direct unit tables for quota-aware victim selection and preemptor
eligibility — the reference's TestSelectVictimsOnNode /
TestPodEligibleToPreemptOthers style suites
(/root/reference/pkg/capacityscheduling/capacity_scheduling_test.go),
driving _Preemptor against fabricated snapshot + cycle state rather than
the full scheduler loop (tests/test_capacity.py covers the e2e paths)."""
import time

from tpusched.api.core import PodDisruptionBudget, PriorityClass
from tpusched.api.meta import ObjectMeta
from tpusched.api.resources import TPU
from tpusched.apiserver import APIServer
from tpusched.apiserver import server as srv
from tpusched.config.profiles import capacity_profile
from tpusched.fwk import CycleState
from tpusched.fwk.status import UNSCHEDULABLE_AND_UNRESOLVABLE
from tpusched.plugins.capacity.plugin import _Preemptor
from tpusched.testing import make_elastic_quota, make_pod, make_tpu_node
from tpusched.testing.harness import new_test_framework


def build(quotas, running, preemptor, chips=8, priority_classes=()):
    """Framework + populated cycle state for one 8-chip node. EQs are created
    before pods so informer replay accounts existing usage (the same create
    order the controllers guarantee in production)."""
    api = APIServer()
    for eq in quotas:
        api.create(srv.ELASTIC_QUOTAS, eq)
    for pc in priority_classes:
        api.create(srv.PRIORITY_CLASSES, pc)
    for p in running:
        p.spec.node_name = "h0"
    node = make_tpu_node("h0", chips=chips)
    fw, handle, _ = new_test_framework(capacity_profile(), nodes=[node],
                                       pods=running, api=api)
    state = CycleState()
    fw.run_pre_filter_plugins(state, preemptor)  # snapshot written either way
    return fw, handle, state


def select_victims(quotas, running, preemptor, chips=8, pdbs=()):
    fw, handle, state = build(quotas, running, preemptor, chips)
    ni = handle.snapshot_shared_lister().get("h0").clone()
    return _Preemptor(handle, state).select_victims_on_node(
        state, preemptor, ni, list(pdbs))


def names(pods):
    return sorted(p.name for p in pods)


# -- select_victims_on_node ---------------------------------------------------

def test_over_min_evicts_lowest_priority_same_quota_only():
    """Preemptor beyond its own min reclaims inside its quota, lowest
    priority first, and the reprieve loop keeps the minimal victim set
    (capacity_scheduling.go:526-538 + reprieve :597-642)."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8})]
    running = [make_pod("low", "team-a", limits={TPU: 4}, priority=1),
               make_pod("mid", "team-a", limits={TPU: 4}, priority=5)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=10)
    victims, n_pdb, status = select_victims(quotas, running, preemptor)
    assert status.is_success()
    # min=8: after evicting only `low`, used(4)+req(4) == Σmin → `mid` is
    # reprieved; exactly the lowest-priority pod pays
    assert names(victims) == ["low"]
    assert n_pdb == 0


def test_over_min_never_touches_other_quotas():
    """Same-quota reclaim must not consider another team's pods even when
    they are the only occupants."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 2}),
              make_elastic_quota("qb", "team-b", min={TPU: 2})]
    running = [make_pod("b0", "team-b", limits={TPU: 4}, priority=0),
               make_pod("b1", "team-b", limits={TPU: 4}, priority=0)]
    # a already over min via the preemptor's own request
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=10)
    victims, _, status = select_victims(quotas, running, preemptor)
    assert victims == []
    assert status.code == UNSCHEDULABLE_AND_UNRESOLVABLE
    assert "No victims" in status.message()


def test_within_min_evicts_borrowers_cross_quota():
    """Preemptor within its guarantee evicts borrowers — other quotas over
    min — regardless of victim priority (capacity_scheduling.go:539-553);
    the reprieve pass then restores the most important candidates first."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8}),
              make_elastic_quota("qb", "team-b", min={TPU: 0})]
    running = [make_pod("b-hi", "team-b", limits={TPU: 4}, priority=100),
               make_pod("b-lo", "team-b", limits={TPU: 4}, priority=1)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=0)
    victims, _, status = select_victims(quotas, running, preemptor)
    assert status.is_success()
    # min=0 keeps team-b over min throughout collection, so BOTH borrowers
    # are candidates; reprieve keeps the higher-priority one
    assert names(victims) == ["b-lo"]


def test_borrower_collection_stops_at_min():
    """Candidate collection mutates the quota snapshot as it removes pods
    (the Add/RemovePod extensions), so once evictions bring a quota down to
    its min, its remaining pods are spared — candidate choice follows pod
    order on the node, not priority. Faithful to the reference's sequential
    dry-run (capacity_scheduling.go:539-553 + :283-318)."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8}),
              make_elastic_quota("qb", "team-b", min={TPU: 4})]
    running = [make_pod("b-first", "team-b", limits={TPU: 4}, priority=100),
               make_pod("b-second", "team-b", limits={TPU: 4}, priority=1)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=0)
    victims, _, status = select_victims(quotas, running, preemptor)
    assert status.is_success()
    # removing b-first drops team-b to min=4 ⇒ b-second never becomes a
    # candidate, and b-first cannot be reprieved (b-second still holds chips)
    assert names(victims) == ["b-first"]


def test_within_min_spares_quotas_at_or_under_min():
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8}),
              make_elastic_quota("qb", "team-b", min={TPU: 16})]
    running = [make_pod("b0", "team-b", limits={TPU: 4}, priority=0),
               make_pod("b1", "team-b", limits={TPU: 4}, priority=0)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=100)
    victims, _, status = select_victims(quotas, running, preemptor)
    assert victims == []
    assert status.code == UNSCHEDULABLE_AND_UNRESOLVABLE


def test_no_quota_namespace_ignores_quota_pods():
    """A preemptor outside any ElasticQuota falls back to plain priority
    preemption, but only over pods that are also outside every quota
    (capacity_scheduling.go:555-575)."""
    quotas = [make_elastic_quota("qb", "team-b", min={TPU: 1})]
    running = [make_pod("free-lo", "wild", limits={TPU: 4}, priority=1),
               make_pod("b0", "team-b", limits={TPU: 4}, priority=0)]
    preemptor = make_pod("pree", "wild2", limits={TPU: 4}, priority=10)
    victims, _, status = select_victims(quotas, running, preemptor)
    assert status.is_success()
    assert names(victims) == ["free-lo"]  # team-b pod untouchable here


def test_no_quota_namespace_requires_lower_priority():
    quotas = []
    running = [make_pod("peer", "wild", limits={TPU: 4}, priority=10)]
    preemptor = make_pod("pree", "wild2", limits={TPU: 4}, priority=10)
    victims, _, status = select_victims(quotas, running, preemptor, chips=4)
    assert victims == []
    assert status.code == UNSCHEDULABLE_AND_UNRESOLVABLE


def test_preemptor_over_quota_max_rejected_despite_victims():
    """Even with a feasible victim set, admission that would break the
    preemptor's own Max is refused (capacity_scheduling.go:577-594)."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 2}, max={TPU: 6})]
    running = [make_pod("low", "team-a", limits={TPU: 4}, priority=1)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 8}, priority=10)
    victims, _, status = select_victims(quotas, running, preemptor)
    assert victims == []
    assert status.is_unschedulable()
    assert "max" in status.message().lower()


def test_pdb_violations_counted_and_minimized():
    """PDB-covered candidates are tried first so reprieve minimizes
    violations; survivors of a zero-budget PDB still count when evicted
    (filterPodsWithPDBViolation, capacity_scheduling.go:857-902)."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8}),
              make_elastic_quota("qb", "team-b", min={TPU: 0})]
    running = [make_pod("b-hi", "team-b", limits={TPU: 4}, priority=100,
                        labels={"app": "b"}),
               make_pod("b-lo", "team-b", limits={TPU: 4}, priority=1,
                        labels={"app": "b"})]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=0)
    pdb = PodDisruptionBudget(
        meta=ObjectMeta(name="protect-b", namespace="team-b"),
        selector={"app": "b"}, disruptions_allowed=0)
    victims, n_pdb, status = select_victims(quotas, running, preemptor,
                                            pdbs=[pdb])
    assert status.is_success()
    assert names(victims) == ["b-lo"]
    assert n_pdb == 1


def test_aggregate_min_gate_limits_reprieve():
    """quota_broken: a reprieve that would push aggregate used past Σmin is
    rolled back even when chips physically fit."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 4})]
    running = [make_pod("low", "team-a", limits={TPU: 4}, priority=1),
               make_pod("mid", "team-a", limits={TPU: 4}, priority=5)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=10)
    # Σmin = 4: with the preemptor admitted (4), NO running pod can stay
    # under the aggregate gate although the node has 8 chips
    victims, _, status = select_victims(quotas, running, preemptor, chips=16)
    assert status.is_success()
    assert names(victims) == ["low", "mid"]


def test_victims_must_leave_room_for_fit():
    """Candidate set feasible quota-wise but the node still can't fit the
    preemptor after all evictions → filter failure surfaces as the status."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 1})]
    running = [make_pod("low", "team-a", limits={TPU: 2}, priority=1)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 16}, priority=10)
    victims, _, status = select_victims(quotas, running, preemptor, chips=8)
    assert victims == []
    assert not status.is_success()


# -- pod_eligible_to_preempt_others ------------------------------------------

def eligible(quotas, running, preemptor, priority_classes=(),
             nominated_status=None):
    fw, handle, state = build(quotas, running, preemptor,
                              priority_classes=priority_classes)
    return _Preemptor(handle, state).pod_eligible_to_preempt_others(
        preemptor, nominated_status)


def terminating(pod):
    pod.meta.deletion_timestamp = time.time()
    return pod


def test_preempt_never_policy_blocks_preemption():
    pc = PriorityClass(meta=ObjectMeta(name="no-preempt"), value=100,
                       preemption_policy="Never")
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=100,
                         priority_class_name="no-preempt")
    assert not eligible([], [], preemptor, priority_classes=[pc])


def test_eligible_without_nomination():
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=100)
    assert eligible([], [], preemptor)


def test_eligible_when_nominated_node_became_unresolvable():
    from tpusched.fwk import Status
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=100)
    preemptor.status.nominated_node_name = "h0"
    assert eligible([], [], preemptor,
                    nominated_status=Status.unresolvable("gone"))


def test_waits_for_terminating_same_quota_victim():
    """A lower-priority same-quota pod already terminating on the nominated
    node is about to release quota — don't preempt again, wait
    (capacity_scheduling.go:427-460)."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8})]
    running = [terminating(make_pod("dying", "team-a", limits={TPU: 4},
                                    priority=1))]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=100)
    preemptor.status.nominated_node_name = "h0"
    assert not eligible(quotas, running, preemptor)


def test_waits_for_terminating_borrower():
    """Preemptor within min + terminating pod of an over-min quota on the
    nominated node: the borrower's exit will satisfy the guarantee."""
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8}),
              make_elastic_quota("qb", "team-b", min={TPU: 1})]
    running = [terminating(make_pod("borrower", "team-b", limits={TPU: 4},
                                    priority=200))]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=0)
    preemptor.status.nominated_node_name = "h0"
    assert not eligible(quotas, running, preemptor)


def test_eligible_when_terminating_pod_is_higher_priority_same_quota():
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8})]
    running = [terminating(make_pod("dying", "team-a", limits={TPU: 4},
                                    priority=200))]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=100)
    preemptor.status.nominated_node_name = "h0"
    assert eligible(quotas, running, preemptor)


def test_eligible_no_terminating_pods_on_nominated_node():
    quotas = [make_elastic_quota("qa", "team-a", min={TPU: 8})]
    running = [make_pod("healthy", "team-a", limits={TPU: 4}, priority=1)]
    preemptor = make_pod("pree", "team-a", limits={TPU: 4}, priority=100)
    preemptor.status.nominated_node_name = "h0"
    assert eligible(quotas, running, preemptor)


def test_single_node_reclaim_respects_gang_min_member_floor():
    """GangDisruptionFloor in the capacity evaluator: quota reclaim may not
    evict one member of a running gang (leaving it below minMember) even
    when every borrowing rule would otherwise allow it; a gang-free borrower
    on another node IS evicted instead."""
    from tpusched.api.resources import TPU
    from tpusched.apiserver import server as srv
    from tpusched.config.profiles import capacity_profile
    from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                                  make_pod_group, make_tpu_node, wait_until)

    with TestCluster(profile=capacity_profile()) as c:
        c.add_nodes([make_tpu_node(f"h{i}", chips=4) for i in range(3)])
        # aggregate min must cover all 12 chips or the borrow gate
        # (aggregated-used-over-min) blocks the third pod outright
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "qa", "team-a", min={TPU: 8}, max={TPU: 12}))
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "qb", "team-b", min={TPU: 4}, max={TPU: 12}))
        # team-b borrows: a 2-member gang (8 chips, over its 4 min) +
        # one plain borrower pod (4 chips)
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "duo", namespace="team-b", min_member=2))
        gang = [make_pod(f"duo-{i}", namespace="team-b", pod_group="duo",
                         limits={TPU: 4}) for i in range(2)]
        plain = make_pod("plain", namespace="team-b", limits={TPU: 4})
        c.create_pods(gang + [plain])
        assert c.wait_for_pods_scheduled(
            [p.key for p in gang] + [plain.key], timeout=30)
        # team-a reclaims its min: one 4-chip pod. Victim must be `plain`
        # (gang-free), never a duo member (2-member gang, floor == 2).
        a = make_pod("a-0", namespace="team-a", limits={TPU: 4})
        c.create_pods([a])
        assert c.wait_for_pods_scheduled([a.key], timeout=30)
        assert wait_until(
            lambda: c.api.try_get(srv.PODS, "team-b/plain") is None,
            timeout=10)
        duo_bound = [p for p in c.api.list(srv.PODS, "team-b")
                     if p.meta.labels.get(
                         "pod-group.scheduling.tpu.dev") == "duo"
                     and p.spec.node_name]
        assert len(duo_bound) == 2            # the gang never degraded
