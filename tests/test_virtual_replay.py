"""Virtual-time replay gates (ISSUE 15, `make replay-smoke`).

Three claims, each load-bearing:

1. **Compression** — a recorded trace spanning ≥1 simulated hour, with
   permit/backoff/denial windows left at production-nonzero values,
   replays to completion in bounded wall time (the discrete-event clock
   jumps quiet gaps instead of sleeping them).
2. **Determinism with live gates** — two virtual-time replays of the
   same trace are byte-identical even though every retry gate fires
   (the pre-ISSUE-15 mode had to ZERO the gates to get this).
3. **Non-vacuity vs the zeroed arm** — the virtual arm demonstrably
   exercises dynamics the legacy ``--legacy-zeroed-gates`` arm erases:
   gate deadlines fire, and at least one pod's retry ordinal differs
   between the arms, attributed to those fired gate labels.

Plus the ``cmd.trace evaluate`` exit-code contract (0 comparable / 1
regression vs budget / 2 usage).
"""
import json
import os

import pytest

from tpusched.obs.fleetrace import load_trace
from tpusched.sim.replay import diff_placements, run_replay

from test_replay_smoke import record_smoke_storm

# gate labels whose fires attribute a retry-ordinal divergence to the
# virtual clock (vs the zeroed arm, where these windows don't exist)
_GATE_LABELS = frozenset(("backoff", "denied-window", "permit",
                          "unsched-flush", "escalation", "watchdog"))


def stretch_trace(src: str, dst: str, factor: float) -> None:
    """Rewrite a trace with its event stamps stretched around the first
    instant: mono' = m0 + (mono - m0) · factor (wall likewise).  The
    workload is untouched — only the recorded timeline dilates, which is
    exactly what makes the compression claim honest: the hour is real
    recorded span, not synthetic idle padding appended at the end."""
    os.makedirs(dst, exist_ok=True)
    m0 = w0 = None
    names = sorted(n for n in os.listdir(src) if n.endswith(".jsonl"))
    for name in names:
        with open(os.path.join(src, name), encoding="utf-8") as f:
            out_lines = []
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if "mono" in rec:
                    if m0 is None:
                        m0, w0 = rec["mono"], rec.get("wall", rec["mono"])
                    rec["mono"] = m0 + (rec["mono"] - m0) * factor
                    if "wall" in rec:
                        rec["wall"] = w0 + (rec["wall"] - w0) * factor
                out_lines.append(json.dumps(rec, separators=(",", ":")))
        with open(os.path.join(dst, name), "w", encoding="utf-8") as f:
            f.write("\n".join(out_lines) + "\n")


@pytest.fixture(scope="module")
def hour_trace(tmp_path_factory):
    """A recorded storm stretched to span ≥1 simulated hour."""
    raw = str(tmp_path_factory.mktemp("raw-trace"))
    record_smoke_storm(raw)
    span = load_trace(raw).window_s()
    assert span > 0
    stretched = str(tmp_path_factory.mktemp("hour-trace"))
    stretch_trace(raw, stretched, factor=max(2.0, 3900.0 / span))
    assert load_trace(stretched).window_s() >= 3600.0
    return stretched


@pytest.fixture(scope="module")
def virtual_pair(hour_trace):
    r1 = run_replay(hour_trace)
    r2 = run_replay(hour_trace)
    return r1, r2


def test_hour_long_trace_compresses_to_bounded_wall(virtual_pair,
                                                    hour_trace):
    """The acceptance bar: ≥1 h of simulated fleet time, production
    windows intact, replayed to completion in ≤60 s wall."""
    r1, _ = virtual_pair
    assert r1.clock_mode == "virtual"
    vt = r1.virtual_time
    assert vt["recorded_span_s"] >= 3600.0
    assert r1.elapsed_s <= 60.0, (
        f"virtual replay took {r1.elapsed_s}s wall for "
        f"{vt['recorded_span_s']}s recorded")
    assert vt["compression_ratio"] >= 60.0
    # completion: every recorded arrival bound in the replay too
    trace = load_trace(hour_trace)
    assert r1.binds == len({p for p, _ in trace.recorded_binds()})
    assert r1.unbound == []
    # virtual span actually covered the recorded timeline
    assert vt["virtual_span_s"] >= 3600.0


def test_virtual_replay_is_deterministic_with_nonzero_gates(virtual_pair):
    r1, r2 = virtual_pair
    assert json.dumps(r1.placements) == json.dumps(r2.placements)
    assert r1.binds == r2.binds and r1.binds > 0
    assert diff_placements(r1.to_dict(), r2.to_dict())["identical"]
    # the retry-ordinal record is part of the determinism contract too
    assert r1.retries == r2.retries
    assert r1.virtual_time["deadlines_fired"] == \
        r2.virtual_time["deadlines_fired"]
    assert r1.virtual_time["fired_by_label"] == \
        r2.virtual_time["fired_by_label"]


def test_virtual_arm_diverges_from_zeroed_arm_on_retry_ordinals(
        virtual_pair, hour_trace):
    """Non-vacuity: the virtual clock must demonstrably CHANGE the
    retry dynamics vs the legacy zeroed-gate arm — gate deadlines fired,
    and at least one pod's attempt ordinal differs between the arms."""
    r_virtual, _ = virtual_pair
    r_zeroed = run_replay(hour_trace, legacy_zeroed_gates=True)
    assert r_zeroed.clock_mode == "zeroed"
    fired = r_virtual.virtual_time.get("fired_by_label", {})
    gate_fires = {k: v for k, v in fired.items() if k in _GATE_LABELS}
    assert gate_fires, (
        f"virtual arm fired no gate deadlines (fired: {fired}) — the "
        "virtual-time gate is vacuous on this trace")
    rv, rz = r_virtual.retries, r_zeroed.retries
    divergent = [k for k in set(rv) | set(rz)
                 if rv.get(k, 1) != rz.get(k, 1)]
    assert divergent, (
        "every pod resolved with identical attempt ordinals under "
        "virtual and zeroed gates — nothing the zeroed arm erases was "
        f"exercised (virtual retries: {len(rv)}, zeroed: {len(rz)})")


def test_report_stamps_the_virtual_wall_mapping(virtual_pair):
    """The ISSUE 15 small fix, replay side: an operator must tell a
    compressed evaluation from a timed one from the report alone."""
    r1, _ = virtual_pair
    vt = r1.virtual_time
    for key in ("mode", "recorded_span_s", "replay_wall_s",
                "compression_ratio", "deadlines_fired",
                "fired_by_label"):
        assert key in vt, key
    assert vt["mode"] == "virtual"
    # zeroed/wall reports carry the stamp too (mode distinguishes)
    doc = r1.to_dict()
    assert doc["clock_mode"] == "virtual"
    assert doc["queueing_delay"]["events"] > 0
    assert "slo" in doc and doc["slo"].get("pod_e2e", {}).get("events")


def test_samples_carry_fragmentation_trajectory(virtual_pair):
    r1, _ = virtual_pair
    frames = [s for s in r1.pool_utilization if s.get("frag")]
    assert frames, "no fragmentation samples despite topologies present"
    last = frames[-1]["frag"]
    for pool, row in last.items():
        assert set(row) >= {"free", "capacity", "largest",
                            "fragmentation"}
        assert 0.0 <= row["fragmentation"] <= 1.0


# -- cmd.trace evaluate exit-code contract ------------------------------------


@pytest.fixture(scope="module")
def tiny_trace(tmp_path_factory):
    """A small unstretched trace (with in-band goodput reports, so the
    evaluate matrix prices placements) — each arm replays fast."""
    d = str(tmp_path_factory.mktemp("tiny-trace"))
    record_smoke_storm(d, goodput_reports=True)
    return d


def test_evaluate_exit_codes(tiny_trace, tmp_path, capsys):
    from tpusched.cmd.trace import main
    # 2: usage — no arms
    assert main(["evaluate", tiny_trace]) == 2
    # 2: usage — missing trace directory
    assert main(["evaluate", str(tmp_path / "nope"),
                 "--arm", "default"]) == 2
    # 2: usage — arm config file does not exist
    assert main(["evaluate", tiny_trace,
                 "--arm", str(tmp_path / "no.yaml")]) == 2
    # 0: comparable two-arm run (same config twice — deltas ~0)
    report = str(tmp_path / "eval.json")
    assert main(["evaluate", tiny_trace, "--arm", "base=default",
                 "--arm", "cand=default", "--report", report]) == 0
    doc = json.load(open(report))
    assert len(doc["arms"]) == 2 and len(doc["comparisons"]) == 1
    deltas = doc["comparisons"][0]["deltas"]
    assert deltas["identical_placements"] is True
    assert deltas["binds_delta"] == 0
    # the goodput column is non-vacuous: the trace carries in-band
    # reports, so the matrix prices real placements
    assert doc["matrix_cells"] > 0
    gp = doc["arms"][0]["summary"]["goodput"]
    assert gp["priced_pods"] > 0 and gp["total_units_per_s"] > 0
    # 1: regression vs budget — an unreachable attainment floor (one
    # arm is enough: the attainment budget judges every candidate arm,
    # and with a single arm it judges that arm — one replay, not two)
    assert main(["evaluate", tiny_trace, "--arm", "default",
                 "--budget-min-attainment", "1.01"]) == 1
    capsys.readouterr()
