"""Trimaran end-to-end placement — the reference's integration tier
(/root/reference/test/integration/targetloadpacking_test.go:56-95 and
loadVariationRiskBalancing_test.go: real scheduler + watcher faked at the
HTTP layer) over the in-process cluster: a local HTTP server serves
load-watcher JSON, the scheduler profile wires the plugin by args, and the
assertion is WHERE pods land.
"""
import pytest

from tpusched.api.resources import CPU, make_resources
from tpusched.config.types import (LoadVariationRiskBalancingArgs,
                                   TargetLoadPackingArgs)
from tpusched.fwk import PluginProfile
from tpusched.testing import FakeWatcher, TestCluster, make_node, make_pod


@pytest.fixture
def watcher():
    w = FakeWatcher()
    yield w
    w.close()


def cpu_node(name, cores=10):
    return make_node(name, capacity=make_resources(
        cpu=cores, memory="64Gi", pods=110))


def tlp_profile(watcher, target=40):
    return PluginProfile(
        filter=["NodeUnschedulable", "NodeResourcesFit"],
        score=[("TargetLoadPacking", 1)],
        bind=["DefaultBinder"],
        plugin_args={"TargetLoadPacking": TargetLoadPackingArgs(
            target_utilization=target, watcher_address=watcher.address)},
    )


def lvrb_profile(watcher):
    return PluginProfile(
        filter=["NodeUnschedulable", "NodeResourcesFit"],
        score=[("LoadVariationRiskBalancing", 1)],
        bind=["DefaultBinder"],
        plugin_args={"LoadVariationRiskBalancing":
                     LoadVariationRiskBalancingArgs(
                         watcher_address=watcher.address)},
    )


def landed_on(c, key):
    return c.pod(key).spec.node_name


def test_tlp_packs_toward_target_not_emptiest(watcher):
    """Best-fit packing: the node already near (but under) target wins over
    the idle one — the defining difference from spread-style scorers."""
    watcher.set_cpu(busy=30.0, idle=0.0)
    with TestCluster(profile=tlp_profile(watcher)) as c:
        c.add_nodes([cpu_node("busy"), cpu_node("idle")])
        p = make_pod("p", requests={CPU: 666})  # predicted ~1000m = 10%
        c.create_pods([p])
        assert c.wait_for_pods_scheduled([p.key], timeout=10)
        # busy: predicted 40% → 100; idle: predicted 10% → 55
        assert landed_on(c, p.key) == "busy"


def test_tlp_penalizes_overshoot(watcher):
    """A node that the pod would push past the target scores below one it
    leaves under target."""
    watcher.set_cpu(hot=80.0, warm=20.0)
    with TestCluster(profile=tlp_profile(watcher)) as c:
        c.add_nodes([cpu_node("hot"), cpu_node("warm")])
        p = make_pod("p", requests={CPU: 666})
        c.create_pods([p])
        assert c.wait_for_pods_scheduled([p.key], timeout=10)
        # hot: predicted 90% → 40*(100-90)/60 ≈ 7; warm: 30% → 85
        assert landed_on(c, p.key) == "warm"


def test_tlp_recently_bound_pods_shift_subsequent_placements(watcher):
    """The PodAssignEventHandler bridge: pods bound inside the metrics
    window count at requests x 1.5 even though the watcher still reports
    the stale pre-bind load (targetloadpacking.go:234-251)."""
    watcher.set_cpu(a=30.0, b=28.0)
    with TestCluster(profile=tlp_profile(watcher)) as c:
        c.add_nodes([cpu_node("a"), cpu_node("b")])
        first = make_pod("first", requests={CPU: 666})
        c.create_pods([first])
        assert c.wait_for_pods_scheduled([first.key], timeout=10)
        assert landed_on(c, first.key) == "a"  # 30+10=40 exactly at target
        # watcher unchanged; 'a' must now be seen as 40% + first's 10%
        second = make_pod("second", requests={CPU: 666})
        c.create_pods([second])
        assert c.wait_for_pods_scheduled([second.key], timeout=10)
        # a: predicted 50% → penalized ≈ 33; b: 28+10=38% → 97
        assert landed_on(c, second.key) == "b"


def test_tlp_watcher_down_still_schedules(watcher):
    """Missing metrics ⇒ MinScore everywhere, but pods must still bind —
    load-awareness degrades, admission does not fail."""
    watcher.fail = True
    with TestCluster(profile=tlp_profile(watcher)) as c:
        c.add_nodes([cpu_node("n1")])
        p = make_pod("p", requests={CPU: 500})
        c.create_pods([p])
        assert c.wait_for_pods_scheduled([p.key], timeout=10)


def test_lvrb_prefers_low_risk_node(watcher):
    """Same mean, different variance: the steadier node wins
    (analysis.go:48-78 risk = (mu + margin*sigma)/2)."""
    watcher.node_metrics = {
        "steady": [{"type": "CPU", "operator": "Average", "value": 40.0},
                   {"type": "CPU", "operator": "Std", "value": 5.0}],
        "spiky": [{"type": "CPU", "operator": "Average", "value": 40.0},
                  {"type": "CPU", "operator": "Std", "value": 40.0}],
    }
    with TestCluster(profile=lvrb_profile(watcher)) as c:
        c.add_nodes([cpu_node("steady"), cpu_node("spiky")])
        p = make_pod("p", requests={CPU: 100})
        c.create_pods([p])
        assert c.wait_for_pods_scheduled([p.key], timeout=10)
        assert landed_on(c, p.key) == "steady"


def test_lvrb_memory_pressure_caps_cpu_score(watcher):
    """cpu and memory scores combine via min(): a memory-hot node loses even
    with an idle CPU (loadvariationriskbalancing.go:104-129)."""
    watcher.node_metrics = {
        "mem-hot": [{"type": "CPU", "operator": "Average", "value": 0.0},
                    {"type": "Memory", "operator": "Average", "value": 95.0}],
        "balanced": [{"type": "CPU", "operator": "Average", "value": 30.0},
                     {"type": "Memory", "operator": "Average", "value": 30.0}],
    }
    with TestCluster(profile=lvrb_profile(watcher)) as c:
        c.add_nodes([cpu_node("mem-hot"), cpu_node("balanced")])
        p = make_pod("p", requests={CPU: 100})
        c.create_pods([p])
        assert c.wait_for_pods_scheduled([p.key], timeout=10)
        assert landed_on(c, p.key) == "balanced"
