"""Observability endpoint + end-to-end load-aware scheduling over HTTP.

Mirrors the reference's ops surface: Prometheus /metrics via ServiceMonitor
(/root/reference/config/prometheus/monitor.yaml:4-22) and the integration
tier's httptest-faked load-watcher
(/root/reference/test/integration/targetloadpacking_test.go:56-95) — here
with a REAL scheduler making placement decisions off the live HTTP metrics."""
from __future__ import annotations

import threading
import urllib.error
import urllib.request

from tpusched.api.resources import make_resources
from tpusched.config.profiles import load_aware_profile
from tpusched.testing import TestCluster, make_node, make_pod
from tpusched.util.httpserve import MetricsServer
from tpusched.util.metrics import REGISTRY


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_metrics_endpoint_serves_registry_and_health():
    c = REGISTRY.counter("tpusched_observability_test_total")
    c.inc(3)
    server = MetricsServer(port=0).start()
    try:
        status, body = _get(server.port, "/metrics")
        assert status == 200
        assert "tpusched_observability_test_total 3" in body
        # the north-star histogram is registered and exposed
        assert "tpusched_podgroup_to_bound_duration_seconds_bucket" in body
        assert _get(server.port, "/healthz") == (200, "ok\n")
        status, body = _get(server.port, "/debug/threads")
        assert status == 200 and "MainThread" in body
        status, _ = _get(server.port, "/nope")
        assert status == 404
    finally:
        server.stop()


def test_readyz_probe():
    ready = {"v": False}
    server = MetricsServer(port=0, ready_probe=lambda: ready["v"]).start()
    try:
        assert _get(server.port, "/readyz")[0] == 503
        ready["v"] = True
        assert _get(server.port, "/readyz")[0] == 200
    finally:
        server.stop()


def test_load_aware_scheduling_over_live_watcher():
    """A real scheduler steers pods toward the under-target node reported by
    a live load-watcher HTTP endpoint."""
    from tpusched.testing import FakeWatcher
    watcher = FakeWatcher(window_end=100)
    watcher.set_cpu(cold=5.0, hot=95.0)
    try:
        profile = load_aware_profile(watcher_address=watcher.address)
        with TestCluster(profile=profile) as c:
            caps = make_resources(cpu=8, memory="16Gi")
            c.add_nodes([make_node("hot", capacity=caps),
                         make_node("cold", capacity=caps)])
            pods = [make_pod(f"w{i}", requests=make_resources(cpu=1, memory="1Gi"))
                    for i in range(3)]
            c.create_pods(pods)
            assert c.wait_for_pods_scheduled([p.key for p in pods])
            placed = {c.pod(p.key).spec.node_name for p in pods}
            assert placed == {"cold"}
    finally:
        watcher.close()


def test_scheduler_emits_scheduled_and_failed_events():
    """Upstream-parity Events: Scheduled on bind, FailedScheduling on an
    unschedulable cycle (the kube-scheduler event surface kubectl shows)."""
    from tpusched.api.resources import TPU
    from tpusched.testing import make_tpu_node

    with TestCluster() as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        c.create_pods([make_pod("ok", limits={TPU: 4}),
                       make_pod("nofit", limits={TPU: 8})])
        assert c.wait_for_pods_scheduled(["default/ok"])
        assert c.wait_for_pods_unscheduled(["default/nofit"])
        events = c.api.events()
        by = {(e.object_key, e.reason) for e in events}
        assert ("default/ok", "Scheduled") in by
        assert ("default/nofit", "FailedScheduling") in by
        failed = [e for e in events if e.reason == "FailedScheduling"]
        assert all(e.type == "Warning" for e in failed)
        assert any("Insufficient" in e.message for e in failed)


def test_extension_point_duration_metrics():
    """framework_extension_point_duration_seconds{extension_point=...}
    (upstream parity): one observation per cycle for every point the cycle
    traverses, exposed with labels in the Prometheus text format."""
    from tpusched.api.resources import TPU
    from tpusched.testing import TestCluster, make_pod, make_tpu_node
    from tpusched.util.metrics import extension_point_seconds

    before = {k: h.count()
              for k, h in extension_point_seconds.children().items()}
    with TestCluster() as c:
        # two nodes: a single feasible node short-circuits before Score
        c.add_nodes([make_tpu_node("n1", chips=4), make_tpu_node("n2", chips=4)])
        c.create_pods([make_pod("p", limits={TPU: 2})])
        assert c.wait_for_pods_scheduled(["default/p"])
    for point in ("PreFilter", "Filter", "Score", "Reserve", "Bind",
                  "PostBind"):
        h = extension_point_seconds.with_labels(point)
        assert h.count() > before.get((point,), 0), point

    text = REGISTRY.expose()
    assert ('tpusched_framework_extension_point_duration_seconds_bucket'
            '{extension_point="Filter",le="+Inf"}') in text
    assert ('tpusched_framework_extension_point_duration_seconds_count'
            '{extension_point="Bind"}') in text


def test_histogram_vec_label_arity_checked():
    import pytest
    from tpusched.util.metrics import HistogramVec
    vec = HistogramVec("x_seconds", ("a", "b"))
    with pytest.raises(ValueError):
        vec.with_labels("only-one")
    vec.with_labels("1", "2").observe(0.5)
    assert vec.children()[("1", "2")].count() == 1


def test_plugin_execution_duration_metrics():
    """plugin_execution_duration_seconds{plugin,extension_point} (upstream
    parity): recorded at the cold points, never for the per-node sweeps."""
    from tpusched.api.resources import TPU
    from tpusched.testing import TestCluster, make_pod, make_tpu_node
    from tpusched.util.metrics import plugin_execution_seconds

    before = {k: h.count()
              for k, h in plugin_execution_seconds.children().items()}

    def grew(plugin, point):
        h = plugin_execution_seconds.with_labels(plugin, point)
        return h.count() > before.get((plugin, point), 0)

    with TestCluster() as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        c.create_pods([make_pod("p", limits={TPU: 2})])
        assert c.wait_for_pods_scheduled(["default/p"])
    assert grew("TpuSlice", "Reserve")
    assert grew("TpuSlice", "Bind")
    # the hot per-node sweep is deliberately not per-plugin-instrumented
    assert not any(point == "Filter"
                   for (_, point) in plugin_execution_seconds.children())


def test_pending_pods_gauges():
    """pending_pods{queue=...} (upstream parity), computed at scrape time:
    an unschedulable pod shows up in the unschedulable gauge and the
    exposition carries the queue label."""
    from tpusched.api.resources import TPU
    from tpusched.testing import TestCluster, make_pod, make_tpu_node

    with TestCluster() as c:
        c.add_nodes([make_tpu_node("n1", chips=4)])
        c.create_pods([make_pod("nofit", limits={TPU: 64})])
        assert c.wait_for_pods_unscheduled(["default/nofit"])

        def unsched_count():
            return c.scheduler.queue.pending_counts()["unschedulable"]
        deadline = threading.Event()
        for _ in range(100):
            if unsched_count() == 1:
                break
            deadline.wait(0.05)
        assert unsched_count() == 1
        text = REGISTRY.expose()
        assert ('tpusched_pending_pods{scheduler="tpusched",'
                'queue="unschedulable"} 1') in text
        assert ('tpusched_pending_pods{scheduler="tpusched",'
                'queue="active"} 0') in text
