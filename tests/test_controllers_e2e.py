"""Live scheduler + controllers end-to-end — the whole control plane in one
cluster, the reference's controller integration tier
(/root/reference/test/integration/elasticquota_controller_test.go:49 runs
the real EQ controller against envtest). Here: TestCluster starts the real
scheduler AND both controllers; the kubelet simulator flips bound pods to
Running; assertions are on CR *status* written by the controllers while
scheduling happens around them.
"""
from tpusched.api.core import POD_FAILED, POD_SUCCEEDED
from tpusched.api.resources import TPU
from tpusched.api.scheduling import (PG_FAILED, PG_FINISHED, PG_RUNNING,
                                     PG_SCHEDULED)
from tpusched.apiserver import server as srv
from tpusched.config.profiles import capacity_profile, tpu_gang_profile
from tpusched.testing import (TestCluster, make_elastic_quota, make_pod,
                              make_pod_group, make_tpu_node, wait_until)


def set_pod_phase(c, key, phase):
    def mutate(pod):
        pod.status.phase = phase
    c.api.patch(srv.PODS, key, mutate)


def test_podgroup_walks_scheduled_running_finished_live():
    """Full lifecycle with every component live: gang binds (scheduler) →
    Scheduled; kubelet sim marks Running → controller moves Running;
    pods succeed → Finished."""
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1),
                     start_controllers=True) as c:
        c.add_nodes([make_tpu_node(f"h{i}", chips=4) for i in range(2)])
        c.api.create(srv.POD_GROUPS, make_pod_group("job", min_member=8))
        pods = [make_pod(f"w{i}", pod_group="job", limits={TPU: 1})
                for i in range(8)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)

        def phase():
            return c.api.get(srv.POD_GROUPS, "default/job").status.phase
        assert wait_until(lambda: phase() == PG_SCHEDULED)

        c.mark_running()
        assert wait_until(lambda: phase() == PG_RUNNING)
        pg = c.api.get(srv.POD_GROUPS, "default/job")
        assert pg.status.running == 8

        for p in pods:
            set_pod_phase(c, p.key, POD_SUCCEEDED)
        assert wait_until(lambda: phase() == PG_FINISHED)
        pg = c.api.get(srv.POD_GROUPS, "default/job")
        assert pg.status.succeeded == 8 and pg.status.running == 0


def test_podgroup_member_failure_is_terminal_live():
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1),
                     start_controllers=True) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        c.api.create(srv.POD_GROUPS, make_pod_group("job", min_member=4))
        pods = [make_pod(f"w{i}", pod_group="job", limits={TPU: 1})
                for i in range(4)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)
        c.mark_running()
        set_pod_phase(c, pods[0].key, POD_FAILED)

        def phase():
            return c.api.get(srv.POD_GROUPS, "default/job").status.phase
        assert wait_until(lambda: phase() == PG_FAILED)
        assert c.api.get(srv.POD_GROUPS, "default/job").status.failed == 1


def test_elasticquota_status_tracks_running_pods_live():
    """EQ controller recomputes status.used from Running pods while the
    scheduler binds them; deletion drops used; a Synced event is emitted."""
    with TestCluster(profile=capacity_profile(),
                     start_controllers=True) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        # min 4: all three pods sit within guaranteed quota (borrowing past
        # min would need another quota's unused min to borrow from)
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "quota", "default", min={TPU: 4}, max={TPU: 4}))
        pods = [make_pod(f"w{i}", limits={TPU: 1}) for i in range(3)]
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)

        def used():
            return c.api.get(srv.ELASTIC_QUOTAS,
                             "default/quota").status.used.get(TPU, 0)
        # bound but not Running: used stays 0 (reference counts Running only,
        # controller/elasticquota.go:212-224)
        assert not wait_until(lambda: used() > 0, timeout=0.7)
        c.mark_running()
        assert wait_until(lambda: used() == 3)

        c.api.delete(srv.PODS, pods[0].key)
        assert wait_until(lambda: used() == 2)
        events = [e for e in c.api.events()
                  if e.reason == "Synced" and "quota" in e.object_key]
        assert events, "EQ controller emitted no Synced event"


def test_occupied_by_filled_live():
    """PreScheduling fills OccupiedBy from member owner references
    (podgroup.go:291-303)."""
    from tpusched.api.meta import OwnerReference
    with TestCluster(profile=tpu_gang_profile(permit_wait_s=5, denied_s=1),
                     start_controllers=True) as c:
        c.add_nodes([make_tpu_node("h0", chips=4)])
        c.api.create(srv.POD_GROUPS, make_pod_group("job", min_member=2))
        pods = [make_pod(f"w{i}", pod_group="job", limits={TPU: 1})
                for i in range(2)]
        for p in pods:
            p.meta.owner_references.append(OwnerReference(
                api_version="batch/v1", kind="Job", name="train-job",
                uid="uid-123"))
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled([p.key for p in pods], timeout=15)

        def occupied():
            return c.api.get(srv.POD_GROUPS, "default/job").status.occupied_by
        assert wait_until(lambda: bool(occupied()))
        assert "train-job" in occupied()
