"""ISSUE 16 parity suite: the native batched dispatch kernel against its
oracles, over fuzzed occupancy / node-health / quota states.

Three layers, strongest last:

1. seeded-fuzz kernel parity: tpusched_dispatch_eval (C) against
   py_dispatch_eval (the pure-Python mirror of the SAME packed-row
   semantics) on randomized row matrices — both kernel implementations
   must agree field-for-field (feasible set, raw scores, topo scores,
   visited count);
2. the same property under hypothesis when available (the container may
   not ship it; the seeded fuzz always runs);
3. in-vivo differential: a live TestCluster with the per-cycle oracle
   sampling EVERY native cycle (TPUSCHED_NATIVE_DIFFERENTIAL=1) over
   fuzzed pod shapes, cordoned/unhealthy nodes and an ElasticQuota —
   zero mismatches allowed, and the native path must actually have run
   (non-vacuity).

The fuzz keeps membership <= max_membership: the stash's max IS the max
over its members (production invariant), and C truncation vs Python
floor division only diverge on the negative numerators that invariant
excludes.
"""
from __future__ import annotations

import ctypes
import random
from dataclasses import replace

import pytest

from tpusched import native
from tpusched.sched import nativedispatch as nd

SEED = 20260807
TRIALS = 300


def _rand_rows(rng: random.Random, n: int):
    rows = []
    for _ in range(n):
        alloc = [rng.randrange(0, 64), rng.randrange(0, 1 << 22),
                 rng.randrange(0, 110), rng.randrange(0, 8)]
        req = [rng.randrange(0, 64), rng.randrange(0, 1 << 22),
               rng.randrange(0, 110), rng.randrange(0, 8)]
        ucl = rng.randrange(0, 8)
        uml = rng.randrange(0, 1 << 16)
        hbm = rng.randrange(0, 1 << 16)
        free = rng.randrange(0, 8)
        flags = rng.randrange(0, 4)
        rows += alloc + req + [ucl, uml, hbm, free, flags]
    return rows


def _call_native(lib, rows, req, chips_set, chips_req, start, want,
                 membership, pool_util, max_membership, strategy,
                 packing_weight):
    i64 = ctypes.c_int64
    i64p = ctypes.POINTER(i64)
    n = len(rows) // nd.DISPATCH_FIELDS
    buf = (i64 * len(rows))(*rows)
    blocks = (i64p * 1)(ctypes.cast(buf, i64p))
    lens = (i64 * 1)(n)
    req_buf = (i64 * 4)(*req)
    memb = (i64 * n)(*membership) if membership is not None else None
    util = (ctypes.c_double * n)(*pool_util) if pool_util is not None \
        else None
    out_f, out_r, out_t = (i64 * n)(), (i64 * n)(), (i64 * n)()
    out_v = (i64 * 1)()
    nf = lib.tpusched_dispatch_eval(
        blocks, lens, 1, req_buf, 1 if chips_set else 0, chips_req,
        start, want, memb, util, max_membership, strategy,
        packing_weight, 0, out_f, out_r, out_t, out_v)
    return (list(out_f[:nf]), list(out_r[:nf]), list(out_t[:nf]),
            out_v[0])


def _one_trial(lib, rng: random.Random):
    n = rng.randrange(1, 25)
    rows = _rand_rows(rng, n)
    req = tuple(rng.randrange(0, 80) for _ in range(4))
    chips_set = rng.random() < 0.7
    chips_req = rng.randrange(0, 8)
    start = rng.randrange(0, n)
    want = rng.randrange(1, n + 2)
    if rng.random() < 0.5:
        max_membership = rng.randrange(1, 9)
        membership = [rng.randrange(-1, max_membership + 1)
                      for _ in range(n)]       # <= maxm by construction
        pool_util = [rng.random() for _ in range(n)]
    else:
        max_membership, membership, pool_util = 1, None, None
    strategy = rng.randrange(0, 3)
    packing_weight = rng.choice([0.0, 0.3, 0.5, 0.7, 1.0])
    got = _call_native(lib, rows, req, chips_set, chips_req, start, want,
                       membership, pool_util, max_membership, strategy,
                       packing_weight)
    exp = nd.py_dispatch_eval(rows, req, chips_set, chips_req, start,
                              want, membership, pool_util, max_membership,
                              strategy, packing_weight)
    assert got == tuple(exp), (
        f"kernel/mirror divergence: n={n} start={start} want={want} "
        f"chips=({chips_set},{chips_req}) strat={strategy} "
        f"pw={packing_weight}\n rows={rows}\n memb={membership}\n "
        f"util={pool_util}\n native={got}\n python={exp}")


def test_kernel_matches_python_mirror_seeded_fuzz():
    if not native.available():
        pytest.skip("native engine unavailable")
    lib = native.load()
    rng = random.Random(SEED)
    for _ in range(TRIALS):
        _one_trial(lib, rng)


def test_combine_scores_normalization_properties():
    """The shared normalize+blend helper (used by the native select and
    the parity oracle): bounded output, reverse flips, zero-max passthrough
    — pure Python, runs everywhere."""
    rng = random.Random(SEED + 1)
    for _ in range(200):
        k = rng.randrange(0, 12)
        raws = [rng.randrange(0, 100) for _ in range(k)]
        topos = [rng.randrange(0, 100) for _ in range(k)]
        w_tpu, w_topo = rng.randrange(0, 5), rng.randrange(0, 5)
        fwd = nd.combine_scores(raws, topos, w_tpu, w_topo, False)
        rev = nd.combine_scores(raws, topos, w_tpu, w_topo, True)
        assert len(fwd) == len(rev) == k
        for f, r, topo in zip(fwd, rev, topos):
            assert f + r == 100 * w_tpu + 2 * topo * w_topo
        if raws and max(raws) > 0:
            hi = raws.index(max(raws))
            assert fwd[hi] - topos[hi] * w_topo == 100 * w_tpu


try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @given(st.integers(min_value=0, max_value=2 ** 31))
    @settings(max_examples=60, deadline=None)
    def test_kernel_parity_hypothesis(trial_seed):
        if not native.available():
            pytest.skip("native engine unavailable")
        _one_trial(native.load(), random.Random(trial_seed))
except ImportError:      # container without hypothesis: seeded fuzz above
    pass                 # carries the property


# -- in-vivo: every native cycle differentially checked ------------------------


def test_native_dispatch_in_vivo_zero_mismatches(monkeypatch):
    """A live cluster with fuzzed occupancy (mixed pod sizes), node health
    (cordons), a gang, and an ElasticQuota — scheduled with the in-cycle
    oracle re-running EVERY native cycle.  Zero differential mismatches,
    and the native path must actually have evaluated cycles."""
    if not native.available():
        pytest.skip("native engine unavailable")
    monkeypatch.setenv("TPUSCHED_NATIVE_DIFFERENTIAL", "1")
    from tpusched.apiserver import server as srv
    from tpusched.testing import (TestCluster, make_elastic_quota,
                                  make_pod, make_pod_group, make_tpu_pool)
    from tpusched.util.metrics import (
        native_dispatch_cycles_total,
        native_dispatch_differential_mismatches)
    from tpusched.api.resources import TPU
    from tpusched.testing.cluster import default_profile

    profile = replace(default_profile(), dispatch_shards=2)
    mismatch0 = native_dispatch_differential_mismatches.value()
    cycles0 = native_dispatch_cycles_total.value()
    rng = random.Random(SEED + 2)
    with TestCluster(profile=profile) as c:
        topo_a, nodes_a = make_tpu_pool("pa", dims=(4, 4, 4))
        topo_b, nodes_b = make_tpu_pool("pb", dims=(4, 4, 4))
        # node-health fuzz with a deterministic footprint: cordon one
        # z=3-layer host per pool — that layer only backs the z=2 slice
        # window, so the 4x4x2 gang stays placeable at z in {0, 1}
        for nodes in (nodes_a, nodes_b):
            layer = [n for n in nodes if n.meta.name.endswith("-3")]
            rng.choice(layer).spec.unschedulable = True
        c.api.create(srv.TPU_TOPOLOGIES, topo_a)
        c.api.create(srv.TPU_TOPOLOGIES, topo_b)
        c.add_nodes(nodes_a + nodes_b)
        c.api.create(srv.ELASTIC_QUOTAS, make_elastic_quota(
            "q", "default", min={TPU: 64}, max={TPU: 128}))
        pods, keys = [], []
        for i in range(12):           # fuzzed occupancy: mixed chip sizes
            p = make_pod(f"solo-{i}", limits={TPU: rng.choice([1, 2, 4])})
            pods.append(p)
            keys.append(p.key)
        c.api.create(srv.POD_GROUPS, make_pod_group(
            "gang", min_member=8, tpu_slice_shape="4x4x2"))
        for i in range(8):            # 8 hosts x 4 chips = the 4x4x2 slice
            p = make_pod(f"gang-{i}", limits={TPU: 4}, pod_group="gang")
            pods.append(p)
            keys.append(p.key)
        c.create_pods(pods)
        assert c.wait_for_pods_scheduled(keys, timeout=30.0), (
            "fuzzed workload failed to schedule")
    assert native_dispatch_differential_mismatches.value() == mismatch0, (
        "the in-cycle oracle caught the kernel disagreeing with the "
        "plugin path")
    assert native_dispatch_cycles_total.value() > cycles0, (
        "native dispatch never engaged — the in-vivo parity test is "
        "vacuous")
