"""Attention implementations: flash (pallas) and ring (sp sequence
parallelism) against the naive reference. Runs on the 8-device virtual CPU
mesh from conftest; flash uses pallas interpret mode on CPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusched.jaxbridge import attention, compat, workload
from tpusched.jaxbridge.mesh import build_named_mesh

# The legacy experimental shard_map cannot express two constructs these
# tests rely on: manual axis_index inside a PARTIALLY-auto mesh (its
# lowering emits a PartitionId instruction XLA SPMD rejects), and the
# non-causal ring-flash arm's collective pattern.  The compat shim
# (jaxbridge/compat.py) recovers everything else; these skip cleanly
# instead of erroring when only the legacy API exists.
needs_modern_shard_map = pytest.mark.skipif(
    not compat.have_modern_shard_map(),
    reason="needs jax.shard_map (legacy experimental shard_map lowers "
           "manual axis_index under partial-auto to PartitionId, which "
           "XLA SPMD rejects)")


def _qkv(key, b=2, s=256, h=2, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_naive(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = attention.naive_attention(q, k, v, causal)
    out = attention.flash_attention(q, k, v, causal, 128, 128)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_multiblock_and_blocksize_independence():
    q, k, v = _qkv(jax.random.PRNGKey(1), s=256)
    ref = attention.naive_attention(q, k, v, True)
    for bq, bk in ((64, 64), (128, 64), (64, 128)):
        out = attention.flash_attention(q, k, v, True, bq, bk)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_odd_seq_falls_back():
    q, k, v = _qkv(jax.random.PRNGKey(2), s=100)
    ref = attention.naive_attention(q, k, v, True)
    out = attention.flash_attention(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_gradients_match_naive():
    q, k, v = _qkv(jax.random.PRNGKey(3), s=128)

    def loss_flash(q, k, v):
        return jnp.sum(attention.flash_attention(q, k, v, True, 64, 64) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(attention.naive_attention(q, k, v, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_naive(causal, sp):
    mesh = build_named_mesh({"sp": sp})
    q, k, v = _qkv(jax.random.PRNGKey(4), s=64)
    ring = jax.jit(attention.make_ring_attention(mesh, causal=causal))
    out = ring(q, k, v)
    ref = attention.naive_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_gradients_match_naive():
    mesh = build_named_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(5), s=64)
    ring = attention.make_ring_attention(mesh)

    gr = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                          argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(lambda q, k, v: jnp.sum(
        attention.naive_attention(q, k, v) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gn):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


@needs_modern_shard_map
def test_ring_composes_with_full_mesh_train_step():
    """cfg.attn='ring' on a dp×sp×tp mesh: the full sharded train step runs
    and matches the GSPMD (naive) step loss."""
    import dataclasses
    mesh = build_named_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg_naive = workload.ModelConfig.tiny()
    cfg_ring = dataclasses.replace(cfg_naive, attn="ring")

    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, cfg_ring.seq),
                                0, cfg_ring.vocab)

    losses = {}
    for name, cfg in (("ring", cfg_ring), ("naive", cfg_naive)):
        params = workload.init_params(jax.random.PRNGKey(0), cfg)
        step, pshard, tshard = workload.make_sharded_train_step(mesh, cfg)
        params = jax.device_put(params, pshard)
        toks = jax.device_put(tokens, tshard)
        _, loss = step(params, toks)
        losses[name] = float(loss)
    assert losses["ring"] == pytest.approx(losses["naive"], abs=1e-4)


def test_flash_backward_uses_kernel_residuals():
    """The differentiable path must carry the (out, lse) residuals — i.e. go
    through the blockwise backward kernels, not the naive-recompute fallback."""
    q, k, v = _qkv(jax.random.PRNGKey(7), s=128)
    out, res = attention._flash_fwd(q, k, v, True, 64, 64, None)
    assert res[4] is not None          # lse present ⇒ kernel backward
    assert res[4].shape == (q.shape[0] * q.shape[2], q.shape[1], 1)
    # unsupported (odd) shapes fall back to the recompute path
    qo, ko, vo = _qkv(jax.random.PRNGKey(8), s=100)
    _, res_odd = attention._flash_fwd(qo, ko, vo, True, 64, 64, None)
    assert res_odd[4] is None


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gradients_noncausal_and_rect_blocks(causal):
    q, k, v = _qkv(jax.random.PRNGKey(9), s=256)

    def loss_flash(q, k, v):
        return jnp.sum(attention.flash_attention(q, k, v, causal, 128, 64) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(attention.naive_attention(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(a, b, atol=3e-4, rtol=3e-4)


def test_gqa_naive_matches_repeat_kv():
    q, _, _ = _qkv(jax.random.PRNGKey(10), h=4)
    kq = jax.random.PRNGKey(11)
    k = jax.random.normal(kq, (2, 256, 2, 64))
    v = jax.random.normal(jax.random.PRNGKey(12), (2, 256, 2, 64))
    grouped = attention.naive_attention(q, k, v, True)
    expanded = attention.naive_attention(q, attention.repeat_kv(k, 2),
                                         attention.repeat_kv(v, 2), True)
    np.testing.assert_allclose(grouped, expanded, atol=1e-6, rtol=1e-6)


def test_gqa_ring_matches_naive_without_expansion():
    """Ring attention with kv_heads < n_heads: the ring carries the small
    tensors; result matches the grouped reference."""
    mesh = build_named_mesh({"sp": 4})
    q, _, _ = _qkv(jax.random.PRNGKey(13), s=64, h=4)
    k = jax.random.normal(jax.random.PRNGKey(14), (2, 64, 2, 64))
    v = jax.random.normal(jax.random.PRNGKey(15), (2, 64, 2, 64))
    ring = jax.jit(attention.make_ring_attention(mesh))
    np.testing.assert_allclose(ring(q, k, v),
                               attention.naive_attention(q, k, v, True),
                               atol=2e-5, rtol=2e-5)


def test_gqa_flash_gradients_reduce_over_group():
    q, _, _ = _qkv(jax.random.PRNGKey(16), s=128, h=4)
    k = jax.random.normal(jax.random.PRNGKey(17), (2, 128, 2, 64))
    v = jax.random.normal(jax.random.PRNGKey(18), (2, 128, 2, 64))

    gf = jax.grad(lambda q, k, v: jnp.sum(
        attention.flash_attention_gqa(q, k, v, True, 64, 64) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(lambda q, k, v: jnp.sum(
        attention.naive_attention(q, k, v, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gn):
        assert a.shape == b.shape  # dk/dv keep the kv_heads shape
        np.testing.assert_allclose(a, b, atol=3e-4, rtol=3e-4)


def test_flash_head_mismatch_fails_loudly():
    q, _, _ = _qkv(jax.random.PRNGKey(19), s=128, h=4)
    k = jax.random.normal(jax.random.PRNGKey(20), (2, 128, 3, 64))
    v = jax.random.normal(jax.random.PRNGKey(21), (2, 128, 3, 64))
    with pytest.raises(ValueError, match="divide"):
        attention.flash_attention_gqa(q, k, v)
    with pytest.raises(ValueError, match="divide"):
        attention.flash_attention(q, k, v)


def test_flash_attention_is_gqa_native():
    """flash_attention takes kv_heads-sized K/V directly — the grouped
    kernels resolve the group via index maps; output matches naive GQA."""
    q, _, _ = _qkv(jax.random.PRNGKey(19), s=128, h=4)
    k = jax.random.normal(jax.random.PRNGKey(22), (2, 128, 2, 64))
    v = jax.random.normal(jax.random.PRNGKey(23), (2, 128, 2, 64))
    out = attention.flash_attention(q, k, v, True, 64, 64)
    ref = attention.naive_attention(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# -- ring-flash: the pallas kernels inside the sp ring ------------------------

@pytest.mark.parametrize("causal", [
    True,
    pytest.param(False, marks=needs_modern_shard_map)])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_flash_matches_naive(causal, sp):
    mesh = build_named_mesh({"sp": sp})
    q, k, v = _qkv(jax.random.PRNGKey(7), s=256)
    ring = jax.jit(attention.make_ring_flash_attention(
        mesh, causal=causal, block_q=32, block_k=32))
    out = ring(q, k, v)
    ref = attention.naive_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_gradients_match_naive(causal):
    mesh = build_named_mesh({"sp": 4})
    q, k, v = _qkv(jax.random.PRNGKey(8), s=256)
    ring = attention.make_ring_flash_attention(mesh, causal=causal,
                                               block_q=32, block_k=32)
    gr = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                          argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(lambda q, k, v: jnp.sum(
        attention.naive_attention(q, k, v, causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gn):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


def test_ring_flash_gqa_matches_naive():
    """GQA chunks ride the ring kv_heads-sized and the kernels resolve the
    group — parity against the expanded naive reference."""
    mesh = build_named_mesh({"sp": 2})
    key = jax.random.PRNGKey(9)
    kq, kk, kv_ = jax.random.split(key, 3)
    b, s, h, g, d = 2, 128, 4, 2, 64
    q = jax.random.normal(kq, (b, s, h, d))
    k = jax.random.normal(kk, (b, s, g, d))
    v = jax.random.normal(kv_, (b, s, g, d))
    ring = jax.jit(attention.make_ring_flash_attention(mesh, block_q=32,
                                                       block_k=32))
    out = ring(q, k, v)
    ref = attention.naive_attention(q, attention.repeat_kv(k, h // g),
                                    attention.repeat_kv(v, h // g), True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@needs_modern_shard_map
def test_ring_flash_composes_with_full_mesh_train_step():
    import dataclasses
    mesh = build_named_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg_naive = workload.ModelConfig.tiny()
    cfg_rf = dataclasses.replace(cfg_naive, attn="ringflash")
    tokens = jax.random.randint(jax.random.PRNGKey(10), (4, cfg_rf.seq),
                                0, cfg_rf.vocab)
    losses = {}
    for name, cfg in (("ringflash", cfg_rf), ("naive", cfg_naive)):
        params = workload.init_params(jax.random.PRNGKey(0), cfg)
        step, pshard, tshard = workload.make_sharded_train_step(mesh, cfg)
        params = jax.device_put(params, pshard)
        toks = jax.device_put(tokens, tshard)
        _, loss = step(params, toks)
        losses[name] = float(loss)
    assert losses["ringflash"] == pytest.approx(losses["naive"], abs=1e-4)


def test_ring_flash_masked_outlier_gradients_finite():
    """Regression: a future (causally-masked) key whose logit exceeds the
    row's global lse must not poison gradients. Excluded chunk pairs are
    skipped with lax.cond — running the kernel and zeroing afterwards would
    compute 0·inf = NaN from the overflowing exp(s − lse)."""
    mesh = build_named_mesh({"sp": 2})
    q, k, v = _qkv(jax.random.PRNGKey(11), s=64)
    # second-half keys huge: for first-chunk queries these are masked, but
    # their raw logits dwarf the global lse
    k = k.at[:, 32:].multiply(100.0)
    ring = attention.make_ring_flash_attention(mesh, causal=True,
                                               block_q=32, block_k=32)
    gr = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                          argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(lambda q, k, v: jnp.sum(
        attention.naive_attention(q, k, v, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gn):
        assert jnp.isfinite(a).all()
        # 100×-scaled keys produce gradients in the hundreds; tolerance
        # scales with the adversarial input magnitude (f32 rounding only)
        np.testing.assert_allclose(a, b, atol=3e-3, rtol=2e-3)


def test_ring_flash_gqa_gradients_match_naive():
    """GQA backward through the ring: group-reduced dK/dV accumulators ride
    the ppermute kv_heads-sized and must match the expanded reference."""
    mesh = build_named_mesh({"sp": 2})
    key = jax.random.PRNGKey(12)
    kq, kk, kv_ = jax.random.split(key, 3)
    b, s, h, g, d = 2, 128, 4, 2, 64
    q = jax.random.normal(kq, (b, s, h, d))
    k = jax.random.normal(kk, (b, s, g, d))
    v = jax.random.normal(kv_, (b, s, g, d))
    ring = attention.make_ring_flash_attention(mesh, block_q=32, block_k=32)
    gr = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                          argnums=(0, 1, 2)))(q, k, v)

    def naive_gqa(q, k, v):
        out = attention.naive_attention(q, attention.repeat_kv(k, h // g),
                                        attention.repeat_kv(v, h // g), True)
        return jnp.sum(out ** 2)

    gn = jax.grad(naive_gqa, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gn):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)
