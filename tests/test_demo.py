"""The capability-tour demo must stay green — it is the first thing a new
user runs, and it exercises gang admission, the atomic set barrier,
what-if, set-unit defrag (advisor + controller), and HA takeover against
the real stack in one process."""
import subprocess
import sys


def test_demo_runs_green():
    r = subprocess.run([sys.executable, "-m", "tpusched.cmd.demo"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-1000:]
    assert "demo complete — all steps green" in r.stdout
